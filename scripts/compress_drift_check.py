"""Compression-plane drift + bytes guard (ISSUE 8 satellite; run by
scripts/run_tests.sh).

Three checks over the compression co-design (tier/quant.py,
core/store.py `_sync_replicas_compressed`, docs/MEMORY.md "Cold-row
numeric contract"):

1. BIT-IDENTITY PIN: with both features OFF (`--sys.tier.cold_dtype
   fp32`, `--sys.sync.compress off`) the randomized
   push/promote/demote/sync storm reads BIT-identically to an untiered
   fp32 shadow at every step and after quiesce — the pre-PR behavior,
   byte accounting recording full-width rows. A regression here means
   the compression plane leaked into the exact path.

2. DRIFT BOUND: the same storm at fp16 and int8 (quantized cold store
   + compressed sync, the worst case — every lossy surface at once)
   must keep every read within the documented contract bound: two grid
   steps of the row's max-abs (one for the at-rest rounding, one for a
   parked EF residual's worth of slack). The error-feedback loop is
   what makes this a BOUND rather than a random walk — without it,
   repeated promote/demote/sync cycles accumulate bias and the final
   read drifts past the bar.

3. BYTES/ROUND: across the storm's sync rounds the compressed server's
   shipped wire bytes must be <= 0.55x (fp16) / 0.30x (int8) of the
   fp32 shadow's for the SAME dirty population (ADAPM_COMPRESS_FP16_MAX
   / ADAPM_COMPRESS_INT8_MAX override). The expected ratios are the
   wire-format ratios themselves (0.5 / ~0.28); the failure mode — a
   path quietly shipping full-width rows — lands at 1.0.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("ADAPM_PLATFORM", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    from xla_compat import mesh_flags
    os.environ["XLA_FLAGS"] = " ".join([_flags, mesh_flags(2)]).strip()

import numpy as np  # noqa: E402

E = 384
# value length matches the mgmt-phase workload the acceptance ratios
# are defined on: int8's fixed 2-byte scale column costs (L+2)/4L, i.e.
# 0.281x at L=16 but 0.3125x at L=8 — shorter rows dilute the format
L = 16
STEPS = 25


def _build(mode: str):
    """(tiered server in `mode`, untiered fp32 shadow). REPLICATION_ONLY
    + a cache pool sized for the whole replica set: the bytes/round
    comparison needs both servers shipping the SAME dirty population
    (relocation decisions and slot-capacity evictions would let the two
    storms diverge structurally)."""
    import adapm_tpu
    from adapm_tpu.base import MgmtTechniques
    from adapm_tpu.config import SystemOptions

    common = dict(sync_max_per_sec=0, prefetch=False,
                  techniques=MgmtTechniques.REPLICATION_ONLY,
                  cache_slots_per_shard=128)
    srv = adapm_tpu.setup(E, L, opts=SystemOptions(
        tier=True, tier_hot_rows=16, tier_cold_dtype=mode,
        sync_compress="off" if mode == "fp32" else mode, **common))
    ref = adapm_tpu.setup(E, L, opts=SystemOptions(**common))
    return srv, ref


def _grid_tol(mode: str, rows: np.ndarray) -> np.ndarray:
    """The documented per-row bound (docs/MEMORY.md): two grid steps of
    the row's max-abs."""
    from adapm_tpu.tier.quant import grid_step
    return 2.0 * grid_step(mode, rows) + 1e-6


def run_storm(mode: str):
    """Randomized push/promote/demote/sync storm vs the fp32 shadow.
    Returns (max observed drift, worst drift/bound ratio, shipped
    bytes, shadow full-width bytes). mode == "fp32" asserts bitwise
    equality instead of the bound."""
    from adapm_tpu.base import CLOCK_MAX

    srv, ref = _build(mode)
    w, wr = srv.make_worker(0), ref.make_worker(0)
    rng = np.random.default_rng(11)
    vals = rng.normal(size=(E, L)).astype(np.float32)
    w.set(np.arange(E), vals)
    wr.set(np.arange(E), vals)
    keys = np.arange(E)
    # long-lived replicas of non-local keys: the sync rounds must ship
    # real deltas for the bytes/round comparison to mean anything
    repl = keys[srv.ab.owner[keys] != w.shard][:64]
    for ww, ss in ((w, srv), (wr, ref)):
        ww.intent(repl, 0, CLOCK_MAX)
        ss.sync.run_round(force_intents=True, all_channels=True)
    b0 = sum(st.sync_bytes_shipped for st in srv.stores)
    f0 = sum(st.sync_bytes_shipped for st in ref.stores)
    worst_drift, worst_ratio = 0.0, 0.0
    for step in range(STEPS):
        op = rng.integers(0, 4)
        if op == 0:
            ks = np.concatenate([rng.integers(0, E, 16),
                                 rng.choice(repl, 8, replace=False)])
            v = rng.normal(size=(24, L)).astype(np.float32)
            w.push(ks, v)
            wr.push(ks, v)
        elif op == 1:
            srv.tier.promote_keys(rng.choice(E, 32, replace=False))
        elif op == 2:
            srv.tier.demote_keys(rng.choice(E, 32, replace=False))
            srv.tier.maintain()
        else:
            srv.sync.run_round(force_intents=True, all_channels=True)
            ref.sync.run_round(force_intents=True, all_channels=True)
        a = np.asarray(srv.read_main(keys)).reshape(E, L)
        b = np.asarray(ref.read_main(keys)).reshape(E, L)
        if mode == "fp32":
            if not np.array_equal(a, b):
                print(f"[compress-check] FAILED: fp32/off storm step "
                      f"{step} (op {op}) diverged from the untiered "
                      f"shadow — the exact path is no longer "
                      f"bit-identical to pre-PR behavior",
                      file=sys.stderr)
                srv.shutdown()
                ref.shutdown()
                sys.exit(1)
        else:
            drift = np.abs(a - b).max(axis=1)
            tol = _grid_tol(mode, b)
            worst_drift = max(worst_drift, float(drift.max()))
            worst_ratio = max(worst_ratio, float((drift / tol).max()))
            if (drift > tol).any():
                print(f"[compress-check] FAILED: {mode} storm step "
                      f"{step} (op {op}) drifted {drift.max():.3g} > "
                      f"contract bound {tol[drift.argmax()]:.3g} — the "
                      f"EF residual loop is not bounding the error "
                      f"(tier/quant.py / store."
                      f"_sync_replicas_compressed)", file=sys.stderr)
                srv.shutdown()
                ref.shutdown()
                sys.exit(1)
    # bytes measured BEFORE quiesce: the quiesce flush is exact
    # (full-width) BY DESIGN and would dilute the wire ratio
    shipped = sum(st.sync_bytes_shipped for st in srv.stores) - b0
    full = sum(st.sync_bytes_shipped for st in ref.stores) - f0
    # final read after quiesce stays under the same bound (fp32: exact)
    srv.quiesce()
    ref.quiesce()
    a = np.asarray(srv.read_main(keys)).reshape(E, L)
    b = np.asarray(ref.read_main(keys)).reshape(E, L)
    if mode == "fp32":
        if not np.array_equal(a, b):
            print("[compress-check] FAILED: fp32/off post-quiesce read "
                  "diverged", file=sys.stderr)
            sys.exit(1)
    else:
        drift = np.abs(a - b).max(axis=1)
        tol = _grid_tol(mode, b)
        worst_drift = max(worst_drift, float(drift.max()))
        if (drift > tol).any():
            print(f"[compress-check] FAILED: {mode} final read drifted "
                  f"{drift.max():.3g} past the contract bound",
                  file=sys.stderr)
            sys.exit(1)
    srv.shutdown()
    ref.shutdown()
    return worst_drift, worst_ratio, shipped, full


def main() -> int:
    caps = {"fp16": float(os.environ.get("ADAPM_COMPRESS_FP16_MAX",
                                         "0.55")),
            "int8": float(os.environ.get("ADAPM_COMPRESS_INT8_MAX",
                                         "0.30"))}

    # -- 1. both features off: bit-identical to pre-PR ---------------------
    run_storm("fp32")
    print(f"[compress-check] fp32/off: {STEPS}-step storm + quiesce "
          f"bit-identical to the untiered shadow (pre-PR pin)")

    # -- 2+3. quantized storms: drift bound + bytes/round ------------------
    for mode in ("fp16", "int8"):
        drift, ratio, shipped, full = run_storm(mode)
        byte_ratio = shipped / full if full else None
        print(f"[compress-check] {mode}: worst drift {drift:.3g} "
              f"({ratio:.2f}x of the contract bound), sync bytes "
              f"{shipped}/{full} = {byte_ratio:.4f}x fp32 "
              f"(cap {caps[mode]})")
        if full == 0:
            print(f"[compress-check] FAILED: {mode} storm shipped no "
                  f"sync bytes — the rounds never exercised the "
                  f"compressed program", file=sys.stderr)
            return 1
        if byte_ratio > caps[mode]:
            print(f"[compress-check] FAILED: {mode} sync shipped "
                  f"{byte_ratio:.4f}x of the fp32 shadow's bytes "
                  f"(cap {caps[mode]}) — a path is shipping "
                  f"full-width rows under compression", file=sys.stderr)
            return 1
    print("[compress-check] OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
