"""Port-differential + fused-bag guard (ISSUE 16 satellite; run by
scripts/run_tests.sh).

Three assertions about the device plane that a regression would break
silently:

1. **The two ports agree bitwise.** The SAME seeded 5-plane storm —
   training pulls, pushes, sets, serve-plane flat lookups, and bag
   lookups (sum AND mean, fused and host-pool dispatch alternating),
   over a TIERED server, maintenance kicked throughout — runs once
   against the jax DevicePort and once against the pure-NumPy
   reference port (device/refport.py). Every read the storm observes,
   and the full post-quiesce table, must be bit-identical between the
   two runs. The storm's tier keeps the fp32 cold wire: WHICH rows
   sit cold at read time depends on async maintenance timing, so a
   lossy wire would make the comparison race on residency, not on
   program correctness — the quantized wires are instead compared
   store-level below, where residency is a deterministic function of
   the slot index. The reference port is the executable spec: a
   device program that drifts from it (a changed accumulation order,
   a quantization shortcut, a donation bug corrupting a buffer) fails
   HERE, with a named op index, instead of surfacing as a flaky
   training loss three layers up. The fp16 and int8 wire programs
   (set-rows ingest, gather, fused gather_pool sum/mean over mixed
   hot/cold slots) get their own differential pass on standalone
   tiered stores, one per port, same inputs — bitwise again.

2. **The reference port stays confined.** device/refport.py must
   contain no jax import and no `apm-lint: disable` suppression — the
   APM008 device-API confinement story (docs/LINT.md): the reference
   implementation is trustworthy BECAUSE it cannot touch the device
   API it specifies, and it earns that status without silencing the
   analyzer.

3. **The fused bag read pays (or at worst breaks even on CPU).** The
   satellite bag workload — 8192 member rows x 128 wide pooled into
   256 bags (32 members/bag, the DLRM shape) — is timed store-level,
   fused `gather_pool` vs gather-then-host-pool, MEDIAN-pairwise per
   the exec_overlap_check.py convention. On an accelerator backend the
   fused program must win outright: median < 0.9 — its saving is wire
   bytes (nbags*L pooled rows cross instead of n*L member rows), a
   32x transfer reduction at this shape. A host-CPU multiplex moves
   those bytes with a memcpy, so the saving is invisible there and the
   honest pass bar is "within noise of host pooling": median < 1.25
   (observed CPU medians 0.84-1.05 across runs on this shared box).
   Override: ADAPM_BAG_RATIO_MAX. The structural failure mode this
   catches — a fused program that re-gathers per bag, or pools on a
   serialized side stream — costs a MULTIPLE on every backend.
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("ADAPM_PLATFORM", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    from xla_compat import mesh_flags
    os.environ["XLA_FLAGS"] = " ".join([_flags, mesh_flags(2)]).strip()

import numpy as np  # noqa: E402

NK = 2048
VLEN = 16
STEPS = 96            # storm ops per port (6-op cycle)
B = 48                # keys per storm op
NBAGS = 8             # bags per storm bag lookup
# the bag-ratio workload (module docstring, item 3)
RATIO_E = 20_000
RATIO_L = 128
RATIO_N = 8192
RATIO_NBAGS = 256
RATIO_REPEATS = 9


def storm(port) -> list:
    """One seeded 5-plane storm against `port`; returns every array
    the storm READ (op order) plus the post-quiesce full table."""
    import adapm_tpu
    from adapm_tpu.config import SystemOptions
    from adapm_tpu.device.port import set_default_port
    from adapm_tpu.serve import ServePlane

    set_default_port(port)
    try:
        srv = adapm_tpu.setup(NK, VLEN, opts=SystemOptions(
            sync_max_per_sec=0, prefetch=False,
            tier=True, tier_hot_rows=max(8, NK // 4)))
        w = srv.make_worker(0)
        rng = np.random.default_rng(7)
        w.wait(w.set(np.arange(NK),
                     rng.normal(size=(NK, VLEN)).astype(np.float32)))
        srv.block()
        plane = ServePlane(srv)
        sess = plane.session()
        rec = []
        for step in range(STEPS):
            keys = rng.integers(0, NK, B)
            op = step % 6
            if op == 0:
                w.wait(w.push(np.unique(keys),
                              rng.normal(size=(len(np.unique(keys)),
                                               VLEN))
                              .astype(np.float32) * 0.1))
            elif op == 1:
                rec.append(w.pull_sync(keys))
            elif op == 2:
                w.wait(w.set(np.unique(keys),
                             rng.normal(size=(len(np.unique(keys)),
                                              VLEN))
                             .astype(np.float32)))
            elif op == 3:
                rec.append(sess.lookup(keys))
            else:
                # bag plane: sum and mean, alternating the dispatch
                # between the fused program and the host-pool fallback
                # — the four combinations must all agree across ports
                srv.opts.serve_bags = (step % 2 == 0)
                bg = np.arange(0, B + 1, B // NBAGS)
                (pooled,) = sess.lookup_bags(
                    [keys], [bg], pooling="sum" if op == 4 else "mean")
                rec.append(pooled)
            if step % 16 == 0 and srv.tier is not None:
                srv.tier.engine.kick()
        plane.close()
        srv.block()
        rec.append(w.pull_sync(np.arange(NK)))
        srv.shutdown()
        return rec
    finally:
        set_default_port(None)


def wire_records(port, mode: str) -> list:
    """Deterministic quantized-wire differential: one standalone
    tiered store on `port` (residency = slot index, no async
    maintenance), ingest rows across the hot/cold boundary, then read
    them back flat and pooled. Returns every array read."""
    from adapm_tpu.core.store import OOB, ShardedStore
    from adapm_tpu.parallel.mesh import make_mesh

    ctx = make_mesh()
    hot = 16
    rows_total = 64
    L = 8
    st = ShardedStore(rows_total * ctx.num_shards, L, ctx,
                      tier_hot_rows=hot, tier_cold_dtype=mode,
                      port=port)
    rng = np.random.default_rng(11)
    S = ctx.num_shards
    n = rows_total * S
    o_sh = np.tile(np.arange(S, dtype=np.int32), rows_total)
    o_sl = np.repeat(np.arange(rows_total, dtype=np.int32), S)
    c_sh = o_sh.copy()
    c_sl = np.full(n, OOB, np.int32)
    use_c = np.zeros(n, bool)
    st.set_rows(o_sh, o_sl,
                rng.normal(size=(n, L)).astype(np.float32) * 3.0,
                c_sh, c_sl)
    rec = [np.asarray(st.gather(o_sh, o_sl, c_sh, c_sl, use_c))[:n]]
    nbags = 8
    seg = (np.arange(n) % nbags).astype(np.int32)  # hot+cold per bag
    for pooling in ("sum", "mean"):
        rec.append(np.asarray(st.gather_pool(
            o_sh, o_sl, c_sh, c_sl, use_c, seg, nbags,
            pooling=pooling))[:nbags])
    return rec


def bag_ratio() -> float:
    """Median-pairwise fused/host-pool ratio at the satellite
    workload, measured store-level (no serve-plane noise)."""
    from adapm_tpu.core.store import OOB, ShardedStore
    from adapm_tpu.parallel.mesh import make_mesh
    from adapm_tpu.serve.bags import pool_bags_host

    ctx = make_mesh()
    st = ShardedStore(RATIO_E, RATIO_L, ctx)
    rng = np.random.default_rng(0)
    S = ctx.num_shards
    for lo in range(0, RATIO_E, 50_000):
        hi = min(lo + 50_000, RATIO_E)
        ks = np.arange(lo, hi)
        st.set_rows((ks % S).astype(np.int32),
                    (ks // S).astype(np.int32),
                    rng.normal(size=(hi - lo, RATIO_L))
                    .astype(np.float32),
                    (ks % S).astype(np.int32),
                    np.full(hi - lo, OOB, np.int32))
    n, nbags = RATIO_N, RATIO_NBAGS
    seg = np.repeat(np.arange(nbags), n // nbags).astype(np.int32)
    c_sh = np.zeros(n, np.int32)
    c_sl = np.full(n, OOB, np.int32)
    use_c = np.zeros(n, bool)

    def mk():
        ks = rng.integers(0, RATIO_E, n)
        return (ks % S).astype(np.int32), (ks // S).astype(np.int32)

    o_sh, o_sl = mk()   # warm both bucket compiles
    np.asarray(st.gather_pool(o_sh, o_sl, c_sh, c_sl, use_c, seg,
                              nbags))
    np.asarray(st.gather(o_sh, o_sl, c_sh, c_sl, use_c))
    pairs = []
    for _ in range(RATIO_REPEATS):
        o_sh, o_sl = mk()
        t0 = time.perf_counter()
        r1 = np.asarray(st.gather_pool(o_sh, o_sl, c_sh, c_sl, use_c,
                                       seg, nbags))[:nbags]
        t1 = time.perf_counter()
        rows = np.asarray(st.gather(o_sh, o_sl, c_sh, c_sl,
                                    use_c))[:n]
        r2 = pool_bags_host(rows, seg, nbags, "sum")
        t2 = time.perf_counter()
        assert np.array_equal(r1, r2), \
            "fused gather_pool != gather + host pool (bitwise)"
        pairs.append((t1 - t0) / (t2 - t1))
    pairs.sort()
    return pairs[len(pairs) // 2]


def main() -> int:
    rc = 0

    # -- confinement: the reference port must stay jax-free -------------
    ref_path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "adapm_tpu", "device",
        "refport.py")
    with open(ref_path) as f:
        src = f.read()
    jax_imports = [ln for ln in src.splitlines()
                   if ln.strip().startswith(("import jax",
                                             "from jax"))]
    suppressions = src.count("apm-lint: disable")
    if jax_imports or suppressions:
        print(f"[portdiff-check] FAILED: device/refport.py must not "
              f"import jax ({len(jax_imports)} found) or suppress the "
              f"linter ({suppressions} found) — the reference port is "
              f"the executable spec precisely because it cannot touch "
              f"the device API (APM008)", file=sys.stderr)
        rc = 1

    # -- the port-differential storm ------------------------------------
    import jax

    from adapm_tpu.device.jaxport import JaxDevicePort
    from adapm_tpu.device.refport import NumpyRefPort

    t0 = time.perf_counter()
    rec_jax = storm(JaxDevicePort())
    rec_ref = storm(NumpyRefPort())
    t_storm = time.perf_counter() - t0
    mismatches = []
    if len(rec_jax) != len(rec_ref):
        mismatches.append(f"record count {len(rec_jax)} vs "
                          f"{len(rec_ref)}")
    else:
        for i, (a, b) in enumerate(zip(rec_jax, rec_ref)):
            if not np.array_equal(np.asarray(a), np.asarray(b)):
                mismatches.append(f"op {i}")
    if mismatches:
        print(f"[portdiff-check] FAILED: jax port and NumPy reference "
              f"port diverged (bitwise) at: "
              f"{', '.join(mismatches[:8])} — a device program no "
              f"longer matches its executable spec "
              f"(device/refport.py)", file=sys.stderr)
        rc = 1

    # -- quantized-wire differential (deterministic, store-level) -------
    wire_bad = []
    for mode in ("fp16", "int8"):
        wj = wire_records(JaxDevicePort(), mode)
        wr = wire_records(NumpyRefPort(), mode)
        for i, (a, b) in enumerate(zip(wj, wr)):
            if not np.array_equal(a, b):
                wire_bad.append(f"{mode}/read{i}")
    if wire_bad:
        print(f"[portdiff-check] FAILED: quantized wire programs "
              f"diverged between ports at: {', '.join(wire_bad)} — "
              f"the fp16/int8 ingest+dequant (or the fused pool over "
              f"cold wire rows) no longer matches the NumPy spec",
              file=sys.stderr)
        rc = 1

    # -- the fused-bag ratio guard --------------------------------------
    backend = jax.default_backend()
    default_max = "0.9" if backend not in ("cpu",) else "1.25"
    ratio_max = float(os.environ.get("ADAPM_BAG_RATIO_MAX",
                                     default_max))
    median = bag_ratio()
    print(f"[portdiff-check] storm: 2 ports x {STEPS} ops "
          f"({len(rec_jax)} recorded reads + final table) in "
          f"{t_storm:.1f}s, {len(mismatches)} mismatches | bag ratio "
          f"({backend}): median fused/hostpool {median:.3f} over "
          f"{RATIO_REPEATS} pairs at {RATIO_N}x{RATIO_L}->"
          f"{RATIO_NBAGS} bags (guard: < {ratio_max:.2f})")
    if median >= ratio_max:
        print(f"[portdiff-check] FAILED: the fused gather_pool program "
              f"costs {median:.3f}x the gather-then-host-pool path — "
              f"structural regression (per-bag re-gather? pooling off "
              f"the dispatch stream?); on CPU relax via "
              f"ADAPM_BAG_RATIO_MAX if the box is just noisy",
              file=sys.stderr)
        rc = 1
    if rc == 0:
        print("[portdiff-check] OK")
    return rc


if __name__ == "__main__":
    sys.exit(main())
