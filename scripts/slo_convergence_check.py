"""SLO-autopilot convergence guard (ISSUE 7 satellite; run by
scripts/run_tests.sh).

Drives an open-loop serve load with `--sys.serve.slo_ms` set and an
ABSURDLY oversized static micro-batch window (the window itself is 4x
the SLO target, so the uncontrolled P99 sits far above target by
construction) and asserts the closed-loop controller (obs/slo.py):

1. **moves the knob in the correct direction** — at least one recorded
   `max_wait_us` adjustment, the FIRST adjustment is downward, and the
   effective window ends below the static knob it started from;
2. **lands the tail inside the tolerance band** — the observed serve
   P99, measured over trailing windows AFTER the controller has had
   time to act (cumulative `serve.latency_s` snapshots diffed per
   window, quantile via `hist_percentile` — the controller's own
   method), must come within `ADAPM_SLO_BAND` (default 3x) of the
   target. Guard on the MEDIAN of the trailing windows (the
   mgmt_plane_check.py / metrics_overhead_check.py pattern, sized for
   this shared 2-core box: single windows spike on scheduler noise,
   but the failure mode — a controller that never shrinks the window —
   leaves EVERY window's P99 pinned at the full static window, 4x
   target, well past any band).

The static-knob path needs no guard here: with `--sys.serve.slo_ms`
unset no controller object exists at all (tests/test_flight.py pins
that the registry, the executor streams, and the effective window are
untouched).
"""
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("ADAPM_PLATFORM", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    from xla_compat import mesh_flags
    os.environ["XLA_FLAGS"] = " ".join([_flags, mesh_flags(2)]).strip()

import numpy as np  # noqa: E402

NK = 4096
VLEN = 8
B = 64               # keys per lookup
CLIENTS = 8
TARGET_MS = 25.0
WAIT_US = 100_000    # static window = 4x the SLO target
SETTLE_S = 2.0       # controller reaction time before measuring
WINDOW_S = 0.75      # one P99 measurement window
WINDOWS = 4          # trailing windows; guard on their median


def main() -> int:
    band = float(os.environ.get("ADAPM_SLO_BAND", "3.0"))
    import jax

    import adapm_tpu
    from adapm_tpu.config import SystemOptions
    from adapm_tpu.obs.metrics import hist_percentile
    from adapm_tpu.serve import ServePlane

    jax.config.update("jax_platforms", "cpu")
    srv = adapm_tpu.setup(NK, VLEN, opts=SystemOptions(
        sync_max_per_sec=0, prefetch=False,
        serve_max_wait_us=WAIT_US, serve_slo_ms=TARGET_MS))
    w = srv.make_worker(0)
    rng = np.random.default_rng(0)
    w.wait(w.set(np.arange(NK),
                 rng.normal(size=(NK, VLEN)).astype(np.float32)))
    # pre-compile the gather bucket shapes the unions can hit (a
    # mid-run XLA compile would pollute a measurement window)
    n = B
    while True:
        w.pull_sync(np.arange(min(n, NK), dtype=np.int64))
        if n >= min(CLIENTS * B, NK):
            break
        n *= 2

    plane = ServePlane(srv)
    assert plane.slo is not None, "no controller with slo_ms set"
    h_lat = srv.obs.find("serve.latency_s")
    stop = threading.Event()
    errs: list = []

    def client(ci):
        try:
            sess = plane.session()
            crng = np.random.default_rng(ci)
            while not stop.is_set():
                batch = (NK * crng.random(B) ** 3).astype(np.int64) \
                    .clip(0, NK - 1)
                sess.lookup(batch)
        except BaseException as e:  # noqa: BLE001
            errs.append(e)

    threads = [threading.Thread(target=client, args=(ci,))
               for ci in range(CLIENTS)]
    for t in threads:
        t.start()
    time.sleep(SETTLE_S)        # the controller walks the window down
    p99s = []
    for _ in range(WINDOWS):    # trailing measurement windows
        snap0 = h_lat.snap()
        time.sleep(WINDOW_S)
        snap1 = h_lat.snap()
        count = snap1["count"] - snap0["count"]
        buckets = [a - b for a, b in zip(snap1["buckets"],
                                         snap0["buckets"])]
        if count:
            p99s.append(hist_percentile(
                {"count": count, "bounds": snap1["bounds"],
                 "buckets": buckets}, 0.99) * 1e3)
    stop.set()
    for t in threads:
        t.join(timeout=60)
        assert not t.is_alive(), "serve client hung"
    assert not errs, errs[:3]

    rep = plane.slo.report()
    adjustments = rep["adjustments"]
    first = rep["first_adjustment"]
    final_us = rep["wait_us"]
    srv.shutdown()

    p99s.sort()
    median_p99 = p99s[len(p99s) // 2] if p99s else float("inf")
    print(f"[slo-check] target {TARGET_MS:.0f} ms, window "
          f"{WAIT_US} us -> {final_us} us in {adjustments} "
          f"adjustments; trailing-window P99s "
          f"{[round(p, 1) for p in p99s]} ms, median "
          f"{median_p99:.1f} (guard: median < {TARGET_MS * band:.0f} "
          f"= {band:.1f}x target)")
    rc = 0
    if adjustments < 1 or final_us >= WAIT_US:
        print("[slo-check] FAILED: the controller never moved "
              "max_wait_us below the oversized static knob — check "
              "obs/slo.py tick scheduling and the shrink branch",
              file=sys.stderr)
        rc = 1
    if first is not None and first["new_us"] >= first["old_us"]:
        print("[slo-check] FAILED: first adjustment moved the window "
              "UP with P99 far above target — control law direction "
              "inverted", file=sys.stderr)
        rc = 1
    if median_p99 >= TARGET_MS * band:
        print(f"[slo-check] FAILED: median trailing-window P99 "
              f"{median_p99:.1f} ms not within {band:.1f}x of the "
              f"{TARGET_MS:.0f} ms target — the tail is not tracking "
              f"the SLO (ADAPM_SLO_BAND to override on a saturated "
              f"box)", file=sys.stderr)
        rc = 1
    if rc == 0:
        print("[slo-check] OK")
    return rc


if __name__ == "__main__":
    sys.exit(main())
