"""Tiered-storage residency guard (ISSUE 5 satellite; run by
scripts/run_tests.sh).

Three checks over the tiering plane (adapm_tpu/tier, docs/MEMORY.md):

1. ADAPTATION: under a zipf-skewed pull workload with device-hot
   capacity capped at 25% of the keys, the score-driven promotion
   worker must converge the hot set onto the head of the distribution —
   measured hot-hit rate over the post-adaptation window >= 0.9
   (ADAPM_TIER_HIT_MIN overrides). The workload's skew puts ~97% of
   accesses in the top quarter, so a broken replacement policy (random,
   FIFO, or thrashing) lands far below the bar while measurement noise
   moves it by fractions of a point.

2. CORRECTNESS FLOOR: the ALL-COLD configuration (tier on, minimal hot
   pool, promotion never driven) must return bit-identical reads to an
   untiered server initialized with the same values — the cold path
   serves slowly, never wrongly. Servers run SEQUENTIALLY (two live
   servers sharing one virtual device set can interleave sharded
   programs from different lock domains and deadlock XLA-CPU's
   collective rendezvous — same constraint as tests/test_tier.py).

3. TIMING GUARD: with the hot pool sized at 100% of the keys and
   everything promoted, the tiered pull path must stay within
   ADAPM_TIER_RATIO_MAX (default 2.5) of the untiered pull path —
   MEDIAN-pairwise-ratio over per-batch best-of-3 timings, per the
   check-script conventions (metrics_overhead_check.py). Guard sizing:
   the real failure mode — a hot-path residency resolve doing per-key
   Python, or a device sync per gather — costs 5-50x, while this
   shared 2-core box's scheduler noise moves the recorded medians
   between ~0.7 and ~1.6 across runs (the tiered pull is at parity
   with untiered; the smaller device pool even wins some runs).
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("ADAPM_PLATFORM", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    from xla_compat import mesh_flags
    os.environ["XLA_FLAGS"] = " ".join([_flags, mesh_flags(8)]).strip()

import numpy as np  # noqa: E402

E = 8192
L = 16
B = 512
SKEW = 48.0  # key = E * u^SKEW; P(top 25%) = 0.25^(1/48) ~= 0.971


def _build(tier: bool, hot_rows: int, init: np.ndarray):
    import adapm_tpu
    import jax
    from adapm_tpu.config import SystemOptions

    jax.config.update("jax_platforms", "cpu")
    srv = adapm_tpu.setup(E, L, opts=SystemOptions(
        sync_max_per_sec=0, prefetch=False,
        tier=tier, tier_hot_rows=hot_rows))
    if tier:
        # deterministic adaptation: maintenance is driven explicitly
        srv.tier.engine.kick = lambda: None
    w = srv.make_worker(0)
    w.set(np.arange(E), init)
    srv.block()
    return srv, w


def _schedule(n_batches: int):
    rng = np.random.default_rng(7)
    return [(E * rng.random(B) ** SKEW).astype(np.int64).clip(0, E - 1)
            for _ in range(n_batches)]


def main() -> int:
    hit_min = float(os.environ.get("ADAPM_TIER_HIT_MIN", "0.9"))
    ratio_max = float(os.environ.get("ADAPM_TIER_RATIO_MAX", "2.5"))
    init = np.random.default_rng(1).normal(size=(E, L)).astype(np.float32)
    import jax
    S = len(jax.devices())

    # -- 1. adaptation: 25% hot capacity, zipf pulls -----------------------
    adapt, measure = 30, 30
    sched = _schedule(adapt + measure)
    srv, w = _build(True, max(8, E // 4 // S), init)
    for b in sched[:adapt]:
        w.pull_sync(b)
        srv.tier.maintain()
    st = srv.stores[0]
    h0, c0 = st.tier_hot_hits, st.tier_cold_hits
    for b in sched[adapt:]:
        w.pull_sync(b)
        srv.tier.maintain()
    dh = st.tier_hot_hits - h0
    dc = st.tier_cold_hits - c0
    hit = dh / max(1, dh + dc)
    rep = srv.tier.report()
    srv.shutdown()
    print(f"[tier-check] adaptation: hot-hit {hit:.4f} over {measure} "
          f"post-adaptation batches at 25% capacity (floor {hit_min}); "
          f"promotions={rep['promotions']} demotions={rep['demotions']}")
    if hit < hit_min:
        print("[tier-check] FAILED: the promotion policy did not "
              "converge the hot set onto the zipf head — check the "
              "score/eviction policy in tier/promote.py",
              file=sys.stderr)
        return 1

    # -- 2+3. untiered reference reads + timings (sequential servers) -----
    t_sched = _schedule(16)
    ref, wr = _build(False, 0, init)
    ref_out = [np.asarray(wr.pull_sync(b)) for b in t_sched]  # warm + ref

    def _time_batches(worker):
        """Per-batch BEST-of-3 pull wall: this shared 2-core box's
        scheduler spikes individual pulls by >10x; the min is the
        undisturbed cost (same rationale as serve_latency_check's
        min-pairwise guard)."""
        best = np.full(len(t_sched), np.inf)
        for _ in range(3):
            for i, b in enumerate(t_sched):
                t0 = time.perf_counter()
                worker.pull_sync(b)
                best[i] = min(best[i], time.perf_counter() - t0)
        return best

    t_ref = _time_batches(wr)
    ref.shutdown()

    # all-cold: minimal hot pool, promotion never driven -> every owner
    # read goes through the cold path; bit-identity is the floor
    cold_srv, wc = _build(True, 8, init)
    for i, b in enumerate(t_sched):
        got = np.asarray(wc.pull_sync(b))
        if not np.array_equal(got, ref_out[i]):
            print(f"[tier-check] FAILED: all-cold read of batch {i} "
                  f"diverged from the untiered reference "
                  f"({int((got != ref_out[i]).sum())} floats)",
                  file=sys.stderr)
            cold_srv.shutdown()
            return 1
    st = cold_srv.stores[0]
    assert st.tier_cold_hits > 0, \
        "all-cold config never exercised the cold path"
    cold_srv.shutdown()
    print(f"[tier-check] all-cold: {len(t_sched)} batches bit-identical "
          f"to the untiered reference (cold-served entries: "
          f"{st.tier_cold_hits})")

    # all-hot: full-capacity pool, everything promoted up front
    hot_srv, wh = _build(True, -(-E // S), init)
    hot_srv.tier.promote_keys(np.arange(E))
    for b in t_sched:
        wh.pull_sync(b)  # warm the tiered gather buckets
    t_hot = _time_batches(wh)
    st = hot_srv.stores[0]
    hot_srv.shutdown()
    pairs = sorted(h / r for h, r in zip(t_hot, t_ref))
    median = pairs[len(pairs) // 2]
    print(f"[tier-check] timing: all-hot/untiered per-batch ratios min "
          f"{pairs[0]:.3f} / median {median:.3f} / max {pairs[-1]:.3f} "
          f"(guard: median < {ratio_max:.2f})")
    if median >= ratio_max:
        print("[tier-check] FAILED: the all-hot tiered pull path costs "
              "a multiple of the untiered path — check the residency "
              "resolve in tier/coldpath.py split_owner for per-key "
              "Python or device syncs", file=sys.stderr)
        return 1
    print("[tier-check] OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
