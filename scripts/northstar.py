"""North-star scale runs on one TPU chip (driver targets, BASELINE.json):

  kge  — Wikidata5M-sized ComplEx: 4.6M entities / 822 relations, d=128,
         B=4096, 32 negatives; reports ms/step and the derived epoch time
         over Wikidata5M's 20.6M train triples.
  w2v  — 1B-words-sized SGNS: 800k vocab (the benchmark corpus' min-count-5
         vocabulary), d=128, B=8192 pairs, 5 negatives with on-device
         unigram^0.75 alias sampling; reports pairs/s.
  mf   — MovieLens-25M-sized: 162,541 users x 59,047 movies, rank 128,
         B=16384 ratings; reports updates/s and derived epoch time over
         25M ratings.

Each run drives the same PM loop as bench.py (intent for the next batch +
a planner round per step, device-routed fused step) at full table scale —
the point is the table SIZE (the KGE table fills most of a v5e chip's
HBM; `--sys.main_over_alloc` close to 1 trades relocation headroom for
fitting), not new machinery. Timing is slope-based (docs/PERF.md
"Measurement methodology"). Prints one JSON line per workload.

Usage: python scripts/northstar.py [kge w2v mf]
"""
from __future__ import annotations

import json
import sys
import time

import numpy as np

import os

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))


def progress(msg: str) -> None:
    print(f"[northstar +{time.perf_counter() - T0:7.1f}s] {msg}",
          file=sys.stderr, flush=True)


T0 = time.perf_counter()


def bulk_device_init(store, emb_cols: int, scale: float, seed: int) -> None:
    """Fill a store's whole main table: normal(0, scale) embedding
    columns, 1e-6 optimizer-state columns. Slot assignment is irrelevant —
    every slot gets an i.i.d. row, so this equals a per-key host init in
    distribution.

    Untiered: one device fill program (skips the host->HBM transfer
    entirely — a 4.6M x 512 table inits in milliseconds instead of
    minutes), constructed through the DevicePort like every other
    program (ISSUE 14). Tiered (--tier): the authoritative table IS the
    host cold store, so the init is a host fill — rows promote lazily
    to the HBM hot pool as the workload touches them, which is the
    point: the table no longer needs to fit on the chip."""
    import jax
    import jax.numpy as jnp

    from adapm_tpu.device import default_port

    if store.res is not None:
        # tiered: fill the cold store host-side (slabbed generation; at
        # full KGE scale this is the one place the host pays the table)
        from adapm_tpu.tier.coldpath import install_main_full
        S, M, L = store.main_shape_full
        rng = np.random.default_rng(seed)
        full = rng.standard_normal((S, M, L), dtype=np.float32)
        full *= np.float32(scale)  # in place: a second full-size array
        # here would transiently DOUBLE host RSS at KGE scale
        full[:, :, emb_cols:] = 1e-6
        install_main_full(store, full)
        return

    S, M, L = store.main.shape
    slab = min(M, 262_144)

    def fill(main, key, lo):
        r = jax.random.normal(key, (S, slab, L), main.dtype) * scale
        r = r.at[:, :, emb_cols:].set(1e-6)
        return jax.lax.dynamic_update_slice(main, r, (0, lo, 0))

    fill = default_port().compile(fill, donate_argnums=0)
    key = jax.random.PRNGKey(seed)
    lo = 0
    while lo < M:
        key, sub = jax.random.split(key)
        # dynamic_update_slice clamps the final slab to [M-slab, M)
        store.main = fill(store.main, sub, jnp.int32(min(lo, M - slab)))
        lo += slab
    store.block()


# --tier (ISSUE 14 satellite): run the scale workloads on the TIERED
# store. The KGE table then no longer needs --sys.main_over_alloc≈1 to
# fit a chip: the authoritative table lives in the host cold store and
# only TIER_HOT_FRAC of the keys (per shard) occupy HBM, promoted by
# the intent windows the pm loop already declares — and every program
# rides the DevicePort like the rest of the tree.
TIER = False
TIER_HOT_FRAC = 0.25


def _sys_opts(num_keys: int, **kw):
    from adapm_tpu.config import SystemOptions
    if TIER:
        import jax
        S = len(jax.devices())
        # no HBM squeeze under tier: main_slots beyond the hot pool are
        # host rows, so the relocation-headroom default costs no HBM
        kw.pop("main_over_alloc", None)
        kw.update(tier=True,
                  tier_hot_rows=max(8, -(-int(num_keys * TIER_HOT_FRAC)
                                         // S)))
    return SystemOptions(cache_slots_per_shard=1, sync_max_per_sec=0,
                         **kw)


def skewed(rng, n, size):
    return (n * rng.random(size) ** 3).astype(np.int64).clip(0, n - 1)


def slope_time(step, steps: int):
    """(T_long - T_short) / (steps - steps//4); step(i) must end in a
    host-visible value only when asked (see bench.py)."""
    assert steps >= 4, "slope timing needs steps >= 4 (two loop lengths)"

    def timed(n):
        t0 = time.perf_counter()
        out = None
        for i in range(n):
            out = step(i)
        float(out)
        return time.perf_counter() - t0

    timed(1)
    t_s = timed(steps // 4)
    t_l = timed(steps)
    return (t_l - t_s) / (steps - steps // 4)


def pm_loop(srv, w, runner, batches, aux, lr, steps, warmup):
    """The bench.py PM step shape: intent for the NEXT batch, fused step,
    one planner round, clock tick."""
    nb = len(batches)
    intent_keys = [np.unique(np.concatenate([v.ravel() for v in b.values()]))
                   for b in batches]

    def step(i):
        nxt = (i + 1) % nb
        w.intent(intent_keys[nxt], w.current_clock + 1, w.current_clock + 2)
        loss = runner(batches[i % nb], None if aux is None else aux[i % nb],
                      lr)
        srv.sync.run_round()
        w.advance_clock()
        return loss

    for _ in range(warmup):
        step(0)
    return slope_time(step, steps)


def run_kge(E=4_600_000, R=822, d=128, B=4096, N=32, steps=16,
            train_triples=20_614_279, full_epoch=False, do_eval=False):
    import adapm_tpu
    from adapm_tpu.models import make_kge_loss
    from adapm_tpu.ops import DeviceRoutedRunner

    progress(f"kge: building server ({E + R} keys x {4 * d} f32 = "
             f"{(E + R) * 4 * d * 4 / 2**30:.1f} GiB main table"
             + (", tiered)" if TIER else " on device)"))
    srv = adapm_tpu.setup(E + R, 4 * d,
                          opts=_sys_opts(E + R, main_over_alloc=1.02))
    bulk_device_init(srv.stores[0], 2 * d, 0.1, seed=0)
    progress("kge: init done (device bulk init)")
    w = srv.make_worker(0)
    runner = DeviceRoutedRunner(
        srv, make_kge_loss("complex"),
        role_class={"s": 0, "r": 0, "o": 0, "neg": 0},
        role_dim={k: 2 * d for k in ("s", "r", "o", "neg")},
        neg_role="neg", neg_shape=(B, N), neg_population=np.arange(E))
    rng = np.random.default_rng(0)
    batches = [{"s": skewed(rng, E, B),
                "r": rng.integers(E, E + R, B).astype(np.int64),
                "o": skewed(rng, E, B)} for _ in range(4)]
    progress("kge: compiling + warmup")
    dt = pm_loop(srv, w, runner, batches, None, 0.1, steps, warmup=3)
    out = {"metric": "northstar_kge_wikidata5m_scale",
           "entities": E, "relations": R, "dim": d,
           "ms_per_step": round(dt * 1e3, 2),
           "triples_per_sec": round(B / dt, 1),
           "derived_epoch_s_20.6M_triples": round(dt * train_triples / B,
                                                  1)}
    if full_epoch:
        # measure one ACTUAL epoch end-to-end (every step ships a fresh
        # host batch + intent + planner round), not the slope-derived
        # steady state
        n_steps = -(-train_triples // B)
        progress(f"kge: full epoch ({n_steps} steps)")

        def fresh():
            return {"s": skewed(rng, E, B),
                    "r": rng.integers(E, E + R, B).astype(np.int64),
                    "o": skewed(rng, E, B)}

        t0 = time.perf_counter()
        loss = None
        nxt = fresh()
        for i in range(n_steps):
            b, nxt = nxt, fresh()
            # the pm_loop step shape: intent covers the NEXT batch one
            # clock ahead, then the current batch trains
            w.intent(np.unique(np.concatenate(
                [nxt["s"], nxt["r"], nxt["o"]])), w.current_clock + 1,
                w.current_clock + 2)
            loss = runner(b, None, 0.1)
            srv.sync.run_round()
            w.advance_clock()
        float(loss)
        out["measured_epoch_s"] = round(time.perf_counter() - t0, 1)
        progress(f"kge: epoch done in {out['measured_epoch_s']} s")
    if do_eval:
        # full-entity chunked eval at table scale (VERDICT r3 item 4):
        # candidates gathered from the pool in [B_ev, C] tiles, only [B_ev]
        # rank counts return to the host (models/kge.make_pool_eval_counts)
        from adapm_tpu.models.kge import make_pool_eval_counts
        from adapm_tpu.ops import DeviceRouter
        C = 65_536
        put = srv.ctx.put_replicated
        nch = -(-E // C)
        pad = np.zeros(nch * C, dtype=np.int64)
        pad[:E] = np.arange(E)
        ent_keys_dev = put(pad.reshape(nch, C))
        tables = DeviceRouter(srv, 0).tables()
        ent_main = srv.stores[0].main
        # shared_pool: entities and relations live in ONE length class at
        # this scale; passing the 8.8 GiB pool as two parameters doubles
        # the AOT argument budget and the compile is rejected (OOM)
        fn = make_pool_eval_counts("complex", 2 * d, 2 * d, C,
                                   shared_pool=True)
        # two batch sizes: 64 = the app default; 512 amortizes the same
        # candidate gathers over 8x the triples (the count program is
        # gather-dominated at B=64 — the [B, d] x [d, C] matmuls are too
        # skinny to feed the MXU)
        for B_ev in (64, 512):
            ev_batches = [
                (put(skewed(rng, E, B_ev)),
                 put(rng.integers(E, E + R, B_ev).astype(np.int64)),
                 put(skewed(rng, E, B_ev))) for _ in range(4)]
            progress(f"kge: eval compile + timing (B={B_ev})")

            def ev_step(i):
                s, r, o = ev_batches[i % 4]
                g_o, g_s, _ = fn(ent_main, tables, ent_keys_dev,
                                 np.int32(E), s, r, o)
                return g_o.sum() + g_s.sum()

            dt_ev = slope_time(ev_step, 12)
            out[f"eval_ms_per_batch{B_ev}"] = round(dt_ev * 1e3, 2)
            out[f"eval_triples_per_sec_b{B_ev}"] = round(B_ev / dt_ev, 1)
            out[f"derived_eval_s_per_10k_triples_b{B_ev}"] = \
                round(dt_ev / B_ev * 1e4, 1)
            progress(f"kge: eval {B_ev / dt_ev:.1f} triples/s "
                     f"({dt_ev * 1e3:.0f} ms / batch of {B_ev})")
    srv.shutdown()
    return out


def run_w2v(V=800_000, d=128, B=8192, N=5, steps=24):
    import adapm_tpu
    from adapm_tpu.models.sgns import build_alias_table, sgns_loss, syn1_key
    from adapm_tpu.ops import DeviceRoutedRunner

    progress(f"w2v: building server ({2 * V} keys x {2 * d} f32)")
    srv = adapm_tpu.setup(2 * V, 2 * d, opts=_sys_opts(2 * V))
    bulk_device_init(srv.stores[0], d, 0.05, seed=1)
    w = srv.make_worker(0)
    counts = 1.0 / (np.arange(V) + 10.0)  # zipf corpus frequencies
    runner = DeviceRoutedRunner(
        srv, sgns_loss, role_class={"center": 0, "ctx": 0, "neg": 0},
        role_dim={k: d for k in ("center", "ctx", "neg")},
        neg_role="neg", neg_shape=(B, N),
        neg_population=syn1_key(np.arange(V)),
        neg_alias=build_alias_table(counts))
    rng = np.random.default_rng(1)
    batches = [{"center": 2 * skewed(rng, V, B),
                "ctx": 2 * skewed(rng, V, B) + 1} for _ in range(4)]
    progress("w2v: compiling + warmup")
    dt = pm_loop(srv, w, runner, batches, None, 0.05, steps, warmup=3)
    srv.shutdown()
    return {"metric": "northstar_w2v_1bwords_scale", "vocab": V, "dim": d,
            "ms_per_step": round(dt * 1e3, 2),
            "pairs_per_sec": round(B / dt, 1)}


def run_w2v_app(V=800_000, sentences=8_000, sent_len=1000, d=128, B=8192,
                N=5):
    """w2v through the APP loop (VERDICT r3 item 8): corpus on disk,
    vocab build, per-sentence deterministic pair generation + subsampling
    + batching + intent readahead + device steps — the number the 1B-words
    north star actually needs, not the bare step rate."""
    import tempfile

    from adapm_tpu.apps import word2vec as w2v
    from adapm_tpu.io import text as textio

    path = os.path.join(tempfile.gettempdir(), f"ns_w2v_{V}.txt")
    if not os.path.exists(path):
        progress(f"w2v-app: generating corpus ({sentences} x {sent_len} "
                 f"tokens over {V} vocab)")
        textio.generate_synthetic_corpus(path, vocab_size=V,
                                         num_sentences=sentences,
                                         sentence_len=sent_len, seed=3)
    args = w2v.build_parser().parse_args(
        ["--data", path, "--dim", str(d), "--window", "5",
         "--negative", str(N), "--epochs", "1", "--batch_size", str(B),
         "--lr", "0.025", "--min_count", "1", "--readahead", "200",
         "--sys.sync.max_per_sec", "0"])
    progress("w2v-app: running one epoch through the app loop")
    t0 = time.perf_counter()
    w2v.run(args)
    dt = time.perf_counter() - t0
    # count the pairs the epoch actually trained (pair generation is
    # deterministic per sentence — a dry re-pass is exact and cheap with
    # the vectorized generator)
    words, counts, vocab = textio.build_vocab(path, 1)
    total = int(counts.sum())
    n_pairs = 0
    for si, sent in enumerate(textio.sentences(path, vocab)):
        c, _ = w2v._pairs_for(sent, si, args.window, args.seed, counts,
                              total, args.sample)
        n_pairs += len(c)
    progress(f"w2v-app: {n_pairs} pairs in {dt:.1f} s")
    return {"metric": "northstar_w2v_app_loop", "vocab": len(words),
            "corpus_tokens": total, "pairs": n_pairs,
            "epoch_s": round(dt, 1),
            "pairs_per_sec_app_loop": round(n_pairs / dt, 1)}


def run_mf(users=162_541, movies=59_047, rank=128, B=16_384, steps=24,
           ratings=25_000_095):
    import adapm_tpu
    from adapm_tpu.config import SystemOptions
    from adapm_tpu.models import make_mf_loss
    from adapm_tpu.ops import DeviceRoutedRunner

    K = users + movies
    progress(f"mf: building server ({K} keys x {2 * rank} f32)")
    srv = adapm_tpu.setup(K, 2 * rank, opts=_sys_opts(K))
    bulk_device_init(srv.stores[0], rank, 0.1, seed=2)
    w = srv.make_worker(0)
    runner = DeviceRoutedRunner(
        srv, make_mf_loss(l2=0.01), role_class={"w": 0, "h": 0},
        role_dim={"w": rank, "h": rank})
    rng = np.random.default_rng(2)
    batches = [{"w": skewed(rng, users, B),
                "h": users + skewed(rng, movies, B)} for _ in range(4)]
    aux = [rng.random(B).astype(np.float32) * 4 + 1 for _ in range(4)]
    progress("mf: compiling + warmup")
    dt = pm_loop(srv, w, runner, batches, aux, 0.05, steps, warmup=3)
    srv.shutdown()
    return {"metric": "northstar_mf_movielens25m_scale",
            "users": users, "movies": movies, "rank": rank,
            "ms_per_step": round(dt * 1e3, 2),
            "ratings_per_sec": round(B / dt, 1),
            "derived_epoch_s_25M_ratings": round(dt * ratings / B, 1)}


def main():
    global TIER
    argv = [a for a in sys.argv[1:]
            if a not in ("--epoch", "--eval", "--tier")]
    full_epoch = "--epoch" in sys.argv[1:]
    do_eval = "--eval" in sys.argv[1:]
    TIER = "--tier" in sys.argv[1:]
    which = argv or ["kge", "w2v", "mf"]
    runs = {"kge": lambda: run_kge(full_epoch=full_epoch, do_eval=do_eval),
            "w2v": run_w2v, "w2v_app": run_w2v_app, "mf": run_mf}
    if os.environ.get("ADAPM_NS_SMOKE", "0").lower() not in \
            ("", "0", "false"):
        # CPU smoke of every measurement path at toy scale: keeps the
        # scripts runnable-first-try when the chip comes back (the r4
        # round lost its TPU window partly to rediscovering breakage)
        runs = {
            "kge": lambda: run_kge(E=20_000, R=20, d=16, B=256, N=4,
                                   steps=6, train_triples=10_000,
                                   full_epoch=full_epoch, do_eval=do_eval),
            "w2v": lambda: run_w2v(V=5_000, d=16, B=512, N=3, steps=6),
            "w2v_app": lambda: run_w2v_app(V=2_000, sentences=200,
                                           sent_len=80, d=16, B=512),
            "mf": lambda: run_mf(users=2_000, movies=1_000, rank=8,
                                 B=1024, steps=6),
        }
    for name in which:
        out = runs[name]()
        print(json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
