"""Cross-process data-plane benchmark: what the DCN channel + GlobalPM
sustain between launched processes (the reference's ZMQ van numbers
analog — bytes and keys/s for remote Pull/Push and replica sync rounds).

Self-launches N processes through the launcher when run directly:

    python scripts/dcn_bench.py [n_procs]

Each rank times, against keys homed on the next rank:
  - remote pull  (keys/s, MiB/s)  — GlobalPM.request_pull round trips
  - remote push  (keys/s, MiB/s)  — GlobalPM.request_write round trips
  - sync rounds  (keys/s)         — replicate a working set via intent,
    then time planner rounds that extract deltas, ship them, and install
    fresh bases (pm.sync_replicas); reports the round's LIVE replica
    rows and raw-f32 vs --sys.sync.compress (fp16/int8) wire bytes per
    round (ISSUE 8 — the compressed program's future-DCN bytes)

Rank 0 prints one JSON line. Results recorded in docs/PERF.md ("DCN
data plane"). CPU platform: this path is host+DCN-bound by design — the
numbers transfer to TPU hosts, whose data plane is the same code.
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))

K = 200_000
L = 64          # f32 per key -> 256 B values, the reference's mid-size rows
BATCH = 4096
ROUNDS = 20


def child() -> None:
    os.environ.setdefault("ADAPM_PLATFORM", "cpu")
    import adapm_tpu
    from adapm_tpu.config import SystemOptions
    from adapm_tpu.parallel import control

    srv = adapm_tpu.setup(K, L, opts=SystemOptions(
        sync_max_per_sec=0, collective_sync=True,
        collective_bucket=BATCH))
    rank = control.process_id()
    P = control.num_processes()
    assert P >= 2, "dcn_bench measures the CROSS-process data plane; " \
                   "launch with >= 2 processes"
    w = srv.make_worker(0)
    rng = np.random.default_rng(rank)
    pm = srv.glob

    keys = np.arange(K, dtype=np.int64)
    theirs = keys[pm.home_proc(keys) == (rank + 1) % P]
    srv.barrier()

    def timed(fn, n=ROUNDS):
        fn()  # warm (routing caches, lazy conns)
        t0 = time.perf_counter()
        for _ in range(n):
            fn()
        return (time.perf_counter() - t0) / n

    batch = rng.choice(theirs, BATCH, replace=False)
    vals = np.ones(BATCH * L, np.float32)

    t_pull = timed(lambda: pm.request_pull(batch))
    t_push = timed(lambda: pm.request_write(batch, vals, is_set=False))

    # single-peer concurrency: aggregate pull rate with C requests in
    # flight to the SAME peer (the channel demuxes by request id; pre-r4
    # a per-peer lock serialized these head-of-line)
    from concurrent.futures import ThreadPoolExecutor

    def pull_rate_inflight(c: int) -> float:
        batches = [rng.choice(theirs, BATCH, replace=False)
                   for _ in range(c)]
        with ThreadPoolExecutor(c) as ex:
            list(ex.map(pm.request_pull, batches))  # warm
            t0 = time.perf_counter()
            for _ in range(ROUNDS):
                list(ex.map(pm.request_pull, batches))
            dt = (time.perf_counter() - t0) / ROUNDS
        return c * BATCH / dt

    inflight = {c: round(pull_rate_inflight(c)) for c in (1, 2, 4)}

    # replicate the batch here: the OWNER rank must hold competing
    # interest first (exclusive intent would relocate instead —
    # sync_manager.h:624-644), so every rank intents its own keys, then
    # the cross intents are granted as replicas
    mine = keys[pm.home_proc(keys) == rank]
    w.intent(mine, w.current_clock, w.current_clock + 10_000)
    srv.wait_sync()
    srv.barrier()
    w.intent(batch, w.current_clock, w.current_clock + 10_000)
    srv.wait_sync()
    all_shards = np.full(len(batch), w.shard, np.int32)
    assert (srv.ab.cache_slot[w.shard, batch] >= 0).mean() > 0.9, \
        "expected the working set to be replicated"
    t_sync = timed(lambda: pm.sync_replicas(batch, all_shards))
    # wire bytes one sync round ships, counted from the round's LIVE
    # replica population (the r8 dirty filter and drop races can shrink
    # a round below BATCH — assuming full-width batch-sized deltas
    # overstates the plane). Raw = today's full-width f32 delta
    # direction; fp16/int8 = what the --sys.sync.compress wire formats
    # cost for the SAME rows (ISSUE 8; tier/quant.py wire table — the
    # future-DCN bytes/round the compressed sync program produces). The
    # fresh-base return direction stays full-width in every mode.
    from adapm_tpu.tier.quant import wire_bytes_per_row
    sync_rows = int((srv.ab.cache_slot[w.shard, batch] >= 0).sum())
    sync_wire = {m: sync_rows * wire_bytes_per_row(m, L)
                 for m in ("off", "fp16", "int8")}

    # channel overlap (VERDICT r4 item 9): the working set spans all sync
    # channels (Knuth-hash partition); per-channel rounds hold only their
    # channel's delta lock, so their DCN round-trips can overlap. Serial
    # baseline = the pre-r5 planner loop shape.
    from adapm_tpu.core.sync import key_channel
    nch = srv.sync.num_channels
    ch = key_channel(batch, nch)
    per_chan = [(batch[ch == cc], all_shards[ch == cc])
                for cc in range(nch)]
    per_chan = [p for p in per_chan if len(p[0])]

    def chan_serial():
        for k, s in per_chan:
            pm.sync_replicas(k, s)

    chan_pool = ThreadPoolExecutor(len(per_chan))

    def chan_overlap():
        list(chan_pool.map(lambda p: pm.sync_replicas(*p), per_chan))

    t_chan_serial = timed(chan_serial)
    t_chan_overlap = timed(chan_overlap)
    chan_pool.shutdown(wait=True)
    # the same replica-refresh traffic over the BSP collective data plane
    # (parallel/collective.py): both transports measured in one run so the
    # comparison answers "where each path wins" (VERDICT r3 item 1). All
    # ranks run `timed` with identical round counts, so every
    # collective_sync call is globally matched. The barrier separates the
    # RPC-timed loops above from the exchanges (collective_pull's
    # DEADLOCK RULE: a rank waiting in an exchange cannot serve RPCs)
    srv.barrier()
    t_coll = timed(lambda: pm.collective_sync(batch, all_shards))
    # pull/push over the exchange (VERDICT r4 item 4): the RPC rows above
    # are the baseline; on loopback RPC usually wins (no bucket padding,
    # no BSP join) — this records the protocol floor the way r4 did for
    # sync. All ranks run identical call counts (collective contract).
    t_cpull = timed(lambda: pm.collective_pull(batch))
    t_cpush = timed(lambda: pm.collective_push(batch, vals))

    srv.barrier()
    mib = BATCH * L * 4 / 2**20
    out = {
        "metric": "dcn_data_plane",
        "procs": P, "batch": BATCH, "value_bytes": L * 4,
        "pull_keys_per_s": round(BATCH / t_pull),
        "pull_MiB_per_s": round(mib / t_pull, 1),
        "push_keys_per_s": round(BATCH / t_push),
        "push_MiB_per_s": round(mib / t_push, 1),
        "pull_keys_per_s_inflight": inflight,
        "sync_round_ms": round(t_sync * 1e3, 2),
        "sync_keys_per_s": round(BATCH / t_sync),
        "sync_rows_per_round": sync_rows,
        "sync_delta_bytes_per_round": {
            "raw_fp32": sync_wire["off"],
            "fp16": sync_wire["fp16"],
            "int8": sync_wire["int8"]},
        "sync_compress_ratio": {
            "fp16": round(sync_wire["fp16"] / sync_wire["off"], 4),
            "int8": round(sync_wire["int8"] / sync_wire["off"], 4)},
        "sync_delta_MiB_per_s_raw": round(
            sync_wire["off"] / 2**20 / t_sync, 1),
        "chan_rounds": len(per_chan),
        "chan_serial_ms": round(t_chan_serial * 1e3, 2),
        "chan_overlap_ms": round(t_chan_overlap * 1e3, 2),
        "chan_overlap_speedup": round(t_chan_serial / t_chan_overlap, 2),
        "coll_sync_round_ms": round(t_coll * 1e3, 2),
        "coll_sync_keys_per_s": round(BATCH / t_coll),
        "coll_pull_keys_per_s": round(BATCH / t_cpull),
        "coll_push_keys_per_s": round(BATCH / t_cpush),
    }
    if rank == 0:
        print(json.dumps(out), flush=True)
    srv.barrier()
    srv.shutdown()


def main() -> None:
    if os.environ.get("ADAPM_PROCESS_ID") is not None:
        child()
        return
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 2
    from adapm_tpu import launcher
    env = dict(os.environ)
    env["ADAPM_PLATFORM"] = "cpu"
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))
    import subprocess
    coordinator = f"localhost:{launcher.free_port()}"
    procs = [subprocess.Popen(
        [sys.executable, os.path.abspath(__file__)],
        env=launcher.make_env(r, n, coordinator, env))
        for r in range(n)]
    rc = []
    try:
        rc = [p.wait(timeout=420) for p in procs]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    assert all(c == 0 for c in rc), rc


if __name__ == "__main__":
    main()
