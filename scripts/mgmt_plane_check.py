"""Management-plane microbench guard (ISSUE 3 satellite; run by
scripts/run_tests.sh).

Times the planner's per-round host cost — snapshot + keep/drop/cross
partition + dirty filter over a replicated table, via real
`sync.run_round()` calls on an idle (fully dirty-filtered, zero
device dispatch) population — against a SHADOW implementation of the
pre-PR-3 set-based classification (per-key Python: `list(set)`,
`np.fromiter`, keep/drop listcomps) over the same population.

Methodology: same MEDIAN-pairwise-ratio pattern as
scripts/metrics_overhead_check.py — (vectorized, shadow) timings back
to back per repeat, guard on the median ratio. The guard is sized for
the real failure mode: reintroducing per-key Python into
`drain_intents`/`sync_channel`/`quiesce` makes the vectorized round
cost what the shadow costs, pushing the ratio to ~1.0 — an order of
magnitude past the threshold — while host-speed noise moves it by
percents. Recorded baseline on the reference host (2-core container,
8192 replicas): ratio ~0.04 (vectorized round ~0.2 ms vs shadow
~4 ms); threshold = a wide multiple of that, overridable via
ADAPM_MGMT_RATIO_MAX, and 1.15x headroom on a re-recorded baseline is
the intended tightening procedure when this host's numbers move.
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("ADAPM_PLATFORM", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    from xla_compat import mesh_flags
    os.environ["XLA_FLAGS"] = " ".join([_flags, mesh_flags(2)]).strip()

import numpy as np  # noqa: E402

REPLICAS = 8192


def build():
    import jax

    from adapm_tpu import Server
    from adapm_tpu.base import CLOCK_MAX, MgmtTechniques
    from adapm_tpu.config import SystemOptions
    from adapm_tpu.parallel.mesh import Mesh, MeshContext

    jax.config.update("jax_platforms", "cpu")
    mesh = MeshContext(Mesh(np.asarray(jax.devices("cpu")), ("kv",)))
    S = mesh.num_shards
    num_keys = int(REPLICAS * S / max(S - 1, 1)) + 256
    srv = Server(num_keys, 8, ctx=mesh, opts=SystemOptions(
        techniques=MgmtTechniques.REPLICATION_ONLY, sync_max_per_sec=0,
        prefetch=False, cache_slots_per_shard=REPLICAS + 256))
    w = srv.make_worker(1)
    keys = np.arange(num_keys)
    cand = keys[srv.ab.owner[keys] != w.shard][:REPLICAS]
    w.intent(cand, 0, CLOCK_MAX)
    srv.sync.run_round(force_intents=True, all_channels=True)
    srv.block()
    return srv, w


def shadow_classify(sync, items, min_clocks):
    """The pre-PR-3 per-key classification shape (set walk + fromiter +
    listcomps) — what sync_channel cost per round before the
    ReplicaTable rewrite, and what it must never cost again."""
    keep_mask = np.fromiter(
        (sync.intent_end[s, k] >= min_clocks[s] for k, s in items),
        np.uint8, len(items))
    keep = [it for it, m in zip(items, keep_mask) if m]
    drop = [it for it, m in zip(items, keep_mask) if not m]
    karr = np.fromiter((k for k, _ in keep), np.int64, len(keep))
    sarr = np.fromiter((s for _, s in keep), np.int32, len(keep))
    return karr, sarr, drop


def main() -> int:
    ratio_max = float(os.environ.get("ADAPM_MGMT_RATIO_MAX", "0.5"))
    rounds, repeats = 20, 7
    srv, w = build()
    live = int(sum(len(t) for t in srv.sync.replicas))
    assert live >= REPLICAS, f"setup failed: {live} replicas live"
    # the shadow's input: the replica population as the old set-of-tuples
    reps = set()
    for t in srv.sync.replicas:
        k, s = t.snapshot()
        reps |= {(int(a), int(b)) for a, b in zip(k, s)}
    shipped_before = srv.sync.stats.keys_synced
    pairs = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        for _ in range(rounds):
            srv.sync.run_round()
            w.advance_clock()
        t_vec = time.perf_counter() - t0
        mc = srv.shard_min_clocks()
        t0 = time.perf_counter()
        for _ in range(rounds):
            shadow_classify(srv.sync, list(reps), mc)
        t_shadow = time.perf_counter() - t0
        pairs.append(t_vec / t_shadow)
    # sanity: idle rounds over a clean table ship nothing (the dirty
    # filter is what makes the vectorized round O(live)-cheap)
    assert srv.sync.stats.keys_synced == shipped_before, \
        "idle rounds re-shipped clean replicas (dirty filter broken?)"
    srv.shutdown()
    pairs.sort()
    median = pairs[len(pairs) // 2]
    print(f"[mgmt-check] {live} replicas, {rounds} rounds x {repeats} "
          f"pairs: vec/shadow ratios min {pairs[0]:.3f} / median "
          f"{median:.3f} / max {pairs[-1]:.3f} (guard: median < "
          f"{ratio_max:.2f}; per-key Python in the round => ~1.0+)")
    if median >= ratio_max:
        print("[mgmt-check] FAILED: vectorized planner round costs a "
              "per-key-Python multiple — check drain_intents/"
              "sync_channel/quiesce for reintroduced set/fromiter/"
              "listcomp hot loops", file=sys.stderr)
        return 1
    print("[mgmt-check] OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
