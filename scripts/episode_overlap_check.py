"""Episodic-execution guard (ISSUE 14 satellite; run by
scripts/run_tests.sh — the exec_overlap_check pattern applied to the
episode/episode_commit stream pair).

Three assertions a regression would break silently:

1. **Idle dispatches nothing.** After the episodic runs settle, the
   executor must start ZERO programs and the stores must dispatch ZERO
   gathers over an idle second — episode prep work exists only while
   `EpisodicRunner.run` drives it; nothing polls.

2. **Episodic keeps up with sequential.** A beyond-hot-capacity zipf
   fused-step workload (every batch carries cold rows, so each
   sequential step pays its forced promotion inline) must run
   episodically at least as fast as plain sequential runner calls,
   within noise. Methodology: MEDIAN-pairwise ratio — (episodic,
   sequential) timed back to back per repeat, guard on the median
   episodic/sequential wall ratio < 1.35 (ADAPM_EPISODE_RATIO_MAX).
   The structural failure mode — a commit joined before the next prep
   starts, a prep blocking on device execution, or the episode streams
   serializing behind a held lock — costs a MULTIPLE, pushing every
   pair well above 1; on this shared 2-core container individual pairs
   swing with scheduler noise, so the guard is on the median and sized
   for that noise (recorded medians < 1.0: prep genuinely overlaps).

3. **Overlap is real.** The episodic server must record
   exec.overlap_fraction > 0 — prep (`episode` stream) genuinely ran
   while a commit (`episode_commit`) was active.
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("ADAPM_PLATFORM", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    from xla_compat import mesh_flags
    os.environ["XLA_FLAGS"] = " ".join([_flags, mesh_flags(2)]).strip()

import numpy as np  # noqa: E402

NK = 8192
D = 8                # embedding dim; row length 2*D
B = 128              # keys per role per batch
BATCHES = 32         # per timed repeat
EPISODE = 4          # batches per episode
REPEATS = 5
SKEW = 8


def build():
    import jax
    import jax.numpy as jnp

    import adapm_tpu
    from adapm_tpu.config import SystemOptions
    from adapm_tpu.ops import DeviceRoutedRunner

    jax.config.update("jax_platforms", "cpu")
    S = len(jax.devices())

    def loss_fn(embs, aux):
        return jnp.mean(jnp.sum(embs["a"] * embs["b"], axis=-1))

    srv = adapm_tpu.setup(NK, 2 * D, opts=SystemOptions(
        sync_max_per_sec=0, prefetch=False,
        tier=True, tier_hot_rows=max(8, NK // 4 // S),
        episode_batches=EPISODE))
    w = srv.make_worker(0)
    init = np.random.default_rng(1).normal(
        size=(NK, 2 * D)).astype(np.float32)
    init[:, D:] = np.abs(init[:, D:]) + 1e-3
    w.wait(w.set(np.arange(NK), init))
    srv.block()
    runner = DeviceRoutedRunner(srv, loss_fn, {"a": 0, "b": 0},
                                {"a": D, "b": D}, shard=0, seed=5)
    return srv, runner


def schedule(rng, n):
    def keys():
        return (NK * rng.random(B) ** SKEW).astype(np.int64) \
            .clip(0, NK - 1)
    return [{"a": keys(), "b": keys()} for _ in range(n)]


def run_episodic(srv, ep, batches) -> float:
    t0 = time.perf_counter()
    losses = ep.run(batches, lr=1e-3)
    float(losses[-1])
    srv.exec.drain("episode_commit", timeout=60)
    srv.block()
    return time.perf_counter() - t0


def run_sequential(srv, runner, batches) -> float:
    t0 = time.perf_counter()
    loss = None
    for b in batches:
        loss = runner(b, None, 1e-3)
    float(loss)
    srv.block()
    return time.perf_counter() - t0


def main() -> int:
    from adapm_tpu.device import EpisodicRunner
    ratio_max = float(os.environ.get("ADAPM_EPISODE_RATIO_MAX", "1.35"))
    rng = np.random.default_rng(7)

    srv_e, run_e = build()
    srv_s, run_s = build()
    ep = EpisodicRunner(run_e)

    # warm both (compiles the step variants + tier paths)
    warm = schedule(rng, 8)
    run_episodic(srv_e, ep, warm)
    run_sequential(srv_s, run_s, warm)

    pairs = []
    for _ in range(REPEATS):
        batches = schedule(rng, BATCHES)
        t_epi = run_episodic(srv_e, ep, batches)
        t_seq = run_sequential(srv_s, run_s, batches)
        pairs.append(t_epi / t_seq)
    overlap_frac = srv_e.exec.overlap_fraction()

    # -- idle guard: nothing polls between runs -------------------------
    time.sleep(0.1)
    p0 = srv_e.exec.stats()["programs_started"]
    g0 = sum(s.gathers for s in srv_e.stores)
    time.sleep(1.0)
    p1 = srv_e.exec.stats()["programs_started"]
    g1 = sum(s.gathers for s in srv_e.stores)
    idle_ok = (p1 == p0) and (g1 == g0)

    srv_e.shutdown()
    srv_s.shutdown()
    pairs.sort()
    median = pairs[len(pairs) // 2]
    print(f"[episode-check] {BATCHES} batches x {REPEATS} pairs, "
          f"episodes of {EPISODE}, beyond-hot-capacity zipf: "
          f"episodic/sequential ratios min {pairs[0]:.3f} / median "
          f"{median:.3f} / max {pairs[-1]:.3f} (guard: median < "
          f"{ratio_max:.2f}) | overlap_fraction {overlap_frac:.3f} | "
          f"idle: programs {p1 - p0:+d}, gathers {g1 - g0:+d}")
    rc = 0
    if median >= ratio_max:
        print("[episode-check] FAILED: episodic execution no longer "
              "keeps up with sequential — check that commits are "
              "submitted BEFORE the next episode's prep runs and that "
              "prep enqueues promotions without blocking on device "
              "execution", file=sys.stderr)
        rc = 1
    if overlap_frac <= 0.0:
        print("[episode-check] FAILED: exec.overlap_fraction stayed 0 "
              "— the episode and episode_commit streams never ran "
              "simultaneously; double-buffering is broken",
              file=sys.stderr)
        rc = 1
    if not idle_ok:
        print("[episode-check] FAILED: an idle server started programs "
              "or dispatched gathers after the episodic runs settled",
              file=sys.stderr)
        rc = 1
    if rc == 0:
        print("[episode-check] OK")
    return rc


if __name__ == "__main__":
    sys.exit(main())
