"""Freshness-SLO convergence guard (ISSUE 20 satellite; run by
scripts/run_tests.sh).

Drives continuous stream ingest (`adapm_tpu/stream/ingest.py`) plus an
inline serve-lookup load with `--sys.stream.freshness_slo_ms` set to a
DELIBERATELY tight target against lazy static knobs (250 ms replica
refresh, 2 rounds/s sync) — the uncontrolled event-to-servable
staleness sits at the refresh interval, far above target by
construction — and asserts the closed-loop controller
(stream/freshness.py):

1. **moves the levers in the correct direction** — at least one
   recorded adjustment, and the FIRST adjustment's levers are
   law-consistent with its own recorded windowed P99: above
   target*(1+tol) the sync rate must go UP and the refresh window
   DOWN (and vice versa below target*(1-tol); a move inside the
   deadband is itself a law violation);
2. **lands the tail inside the tolerance band** — the trailing-window
   freshness P99 (cumulative `flight.freshness_s` snapshots diffed per
   window, quantile via `hist_percentile` — the controller's own
   method), measured AFTER the controller has had time to walk the
   levers, must come within `ADAPM_FRESHNESS_BAND` (default 3x) of the
   target. Guard on the MEDIAN of the trailing windows (the
   slo_convergence_check.py pattern: on this shared 2-core box single
   windows spike on scheduler noise, but the failure mode — a
   controller that never tightens — leaves EVERY window's P99 pinned
   at the ~250 ms static refresh interval, ~8x this target).

The default-off path needs no guard here:
scripts/metrics_overhead_check.py pins `srv.stream is None` and zero
`stream.*` registry names with no `--sys.stream.*` knobs set.
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("ADAPM_PLATFORM", "cpu")

import numpy as np  # noqa: E402

NK = 4096
VLEN = 8
B = 64               # keys per lookup
TARGET_MS = 30.0     # tight: ~8x below the uncontrolled staleness
STATIC_REFRESH_MS = 250.0   # lazy static knobs the controller tightens
STATIC_SYNC_RATE = 2.0
STREAM_BATCH = 16
STREAM_RATE = 500.0  # events/s
SETTLE_S = 4.0       # controller reaction time before measuring
WINDOW_S = 0.75      # one P99 measurement window
WINDOWS = 4          # trailing windows; guard on their median
TOL = 0.25           # the controller's deadband half-width


def main() -> int:
    band = float(os.environ.get("ADAPM_FRESHNESS_BAND", "3.0"))
    import adapm_tpu
    from adapm_tpu.config import SystemOptions
    from adapm_tpu.obs.metrics import hist_percentile
    from adapm_tpu.serve import ServePlane
    from adapm_tpu.stream import EventLog, StreamTrainer

    srv = adapm_tpu.setup(NK, VLEN, opts=SystemOptions(
        sync_max_per_sec=STATIC_SYNC_RATE, prefetch=False,
        metrics=True, trace_flight=True,
        serve_replica_rows=1024,
        serve_replica_refresh_ms=STATIC_REFRESH_MS,
        serve_max_wait_us=200,
        stream_batch=STREAM_BATCH, stream_rate=STREAM_RATE,
        stream_freshness_slo_ms=TARGET_MS), num_workers=2)
    w = srv.make_worker(0)
    rng = np.random.default_rng(0)
    w.set(np.arange(NK),
          rng.normal(size=(NK, VLEN)).astype(np.float32))
    srv.block()
    assert srv.stream is not None and srv.stream.freshness is not None, \
        "stream plane + freshness controller must exist with the knobs set"
    plane = ServePlane(srv)
    sess = plane.session()
    hot = np.arange(512, dtype=np.int64)
    sess.lookup(hot)            # score the hot set into the replica
    if plane.replica is not None:
        plane.replica.refresh_now()
    trainer = StreamTrainer(srv, EventLog(NK, seed=5, keys_per_event=8))
    trainer.start()
    h_fresh = srv.flight.freshness.h_freshness

    def drive(seconds: float) -> None:
        # inline HOT-ONLY lookup load: unions fully covered by the
        # warmed replica take the lock-free path, whose freshness
        # cutoff is the SNAPSHOT's stamp (serve/replica.py) — so the
        # uncontrolled event-to-servable staleness tracks the 250 ms
        # static refresh interval, and the refresh lever is what the
        # controller must tighten. The EventLog writes head-heavy, so
        # probed pushes land inside this read set.
        t_end = time.monotonic() + seconds
        while time.monotonic() < t_end:
            sess.lookup(rng.choice(hot, B).astype(np.int64))

    drive(SETTLE_S)             # the controller walks the levers
    p99s = []
    for _ in range(WINDOWS):    # trailing measurement windows
        snap0 = h_fresh.snap()
        drive(WINDOW_S)
        snap1 = h_fresh.snap()
        count = snap1["count"] - snap0["count"]
        buckets = [a - b for a, b in zip(snap1["buckets"],
                                         snap0["buckets"])]
        if count:
            p99s.append(hist_percentile(
                {"count": count, "bounds": snap1["bounds"],
                 "buckets": buckets}, 0.99) * 1e3)
    rep = srv.stream.freshness.report()
    events = int(srv.stream.c_events.value)
    srv.shutdown()

    p99s.sort()
    median_p99 = p99s[len(p99s) // 2] if p99s else float("inf")
    first = rep["first_adjustment"]
    print(f"[freshness-check] target {TARGET_MS:.0f} ms vs static "
          f"refresh {STATIC_REFRESH_MS:.0f} ms / sync "
          f"{STATIC_SYNC_RATE:.0f}/s; {events} events ingested; "
          f"{rep['adjustments']} adjustments -> sync_rate "
          f"{rep['sync_rate']:.1f}, refresh {rep['refresh_ms']:.1f} ms; "
          f"trailing-window P99s {[round(p, 1) for p in p99s]} ms, "
          f"median {median_p99:.1f} (guard: median < "
          f"{TARGET_MS * band:.0f} = {band:.1f}x target)")
    rc = 0
    if rep["adjustments"] < 1 or first is None:
        print("[freshness-check] FAILED: the controller never moved a "
              "lever off the lazy static knobs — check "
              "stream/freshness.py tick scheduling and the tighten "
              "branch", file=sys.stderr)
        rc = 1
    if first is not None:
        # direction check against the move's OWN recorded windowed P99
        # (the quantity the law branched on)
        p99 = first["p99_ms"]
        if p99 > TARGET_MS * (1.0 + TOL):
            want = "tighten"
        elif p99 < TARGET_MS * (1.0 - TOL):
            want = "relax"
        else:
            want = None
            print(f"[freshness-check] FAILED: first adjustment fired "
                  f"inside the deadband (P99 {p99:.1f} ms vs target "
                  f"{TARGET_MS:.0f} +/- {TOL:.0%}) — hysteresis "
                  f"broken", file=sys.stderr)
            rc = 1
        for lv in first["levers"]:
            up = lv["new"] > lv["old"]
            # tighten = sync rate UP, refresh window DOWN
            ok = (up == (lv["lever"] == "sync_rate")) \
                if want == "tighten" else \
                (up == (lv["lever"] == "refresh_ms")) \
                if want == "relax" else True
            if not ok:
                print(f"[freshness-check] FAILED: first adjustment "
                      f"moved {lv['lever']} {lv['old']:.3f} -> "
                      f"{lv['new']:.3f} with P99 {p99:.1f} ms vs "
                      f"target {TARGET_MS:.0f} ms — control law "
                      f"direction inverted", file=sys.stderr)
                rc = 1
    if median_p99 >= TARGET_MS * band:
        print(f"[freshness-check] FAILED: median trailing-window "
              f"freshness P99 {median_p99:.1f} ms not within "
              f"{band:.1f}x of the {TARGET_MS:.0f} ms target — the "
              f"closed loop is not tracking the SLO "
              f"(ADAPM_FRESHNESS_BAND to override on a saturated box)",
              file=sys.stderr)
        rc = 1
    if rc == 0:
        print("[freshness-check] OK")
    return rc


if __name__ == "__main__":
    sys.exit(main())
