#!/usr/bin/env bash
# Test harness (reference tests/run_tests.sh). The reference loops its test
# binaries over --sys.techniques / --sampling.scheme variants from the
# shell; here those variants are pytest parameterizations inside the suite
# (test_consistency.py: all/replication_only/relocation_only;
# test_sampling.py: naive/preloc/pool/local x with/without replacement),
# so one pytest run covers the same matrix.
set -euo pipefail
cd "$(dirname "$0")/.."

# adapm-lint invariant gate FIRST (ISSUE 11): the AST analyzer checks
# the concurrency disciplines mechanically — gate coverage, the
# lock-narrowing rule, skip-wrappers, the raw-thread ban, donation
# lifetimes, revalidate-under-lock, metric-catalog drift — in
# milliseconds, before anything compiles a program. Zero unsuppressed
# findings, zero unused suppressions (docs/INVARIANTS.md;
# ADAPM_LINT_BASELINE is the incremental-adoption escape hatch)
python scripts/invariant_lint_check.py
# fast prefetch-pipeline smoke next: a staged-pull/plan-cache regression
# should fail in seconds, not after the full matrix (the pipeline is also
# exercised by bench.py's prefetch phase under ADAPM_BENCH_SMALL=1)
python -m pytest tests/test_prefetch.py -q
# metrics-overhead guard + duplicate-metric-name check (ISSUE 2): the
# registry must stay under its hot-path budget and no two subsystems may
# register the same metric (docs/OBSERVABILITY.md)
python scripts/metrics_overhead_check.py
# management-plane ratio guard (ISSUE 3): the vectorized planner round
# must stay a small fraction of the per-key-Python shadow cost —
# reintroduced set/fromiter/listcomp hot loops cost a multiple
python scripts/mgmt_plane_check.py
# serving-plane guard (ISSUE 4): coalesced lookups at 32 concurrent
# clients must beat sequential per-request pulls, and an idle serve
# loop must dispatch zero device programs
python scripts/serve_latency_check.py
# tiered-storage guard (ISSUE 5): under a zipf workload at 25% hot
# capacity the promotion policy must reach >= 0.9 hot-hit rate, the
# all-cold configuration must read bit-identically to untiered, and
# the all-hot tiered pull path must stay near parity with untiered
python scripts/tier_residency_check.py
# unified-executor guard (ISSUE 6): an idle executor starts zero
# programs (workers park on the condvar), and the overlapped default
# must keep up with the serialized single-stream fallback on a tiered
# promotion-churn workload (median pairwise ratio; overlap_fraction > 0)
python scripts/exec_overlap_check.py
# episodic-execution guard (ISSUE 14): on a beyond-hot-capacity zipf
# fused-step workload, the double-buffered episode/episode_commit
# pipeline must keep up with plain sequential execution (median
# pairwise ratio), record exec.overlap_fraction > 0 (prep genuinely
# overlapped compute), and dispatch nothing while idle
python scripts/episode_overlap_check.py
# compression-plane guard (ISSUE 8): a randomized push/promote/demote/
# sync storm with both features OFF must stay bit-identical to an
# untiered fp32 shadow (the pre-PR pin), the fp16/int8 storms must keep
# every read under the docs/MEMORY.md contract bound (the EF residual
# loop bounding drift), and compressed sync rounds must ship <= 0.55x
# (fp16) / 0.30x (int8) of the shadow's full-width bytes
python scripts/compress_drift_check.py
# SLO-autopilot guard (ISSUE 7): with --sys.serve.slo_ms set against an
# oversized micro-batch window, the closed-loop controller must walk
# max_wait_us DOWN and land the observed serve P99 within the tolerance
# band of the target (median of trailing measurement windows)
python scripts/slo_convergence_check.py
# trace-replay guard (ISSUE 15): a captured multi-plane storm must
# replay bit-identically (same seed + knobs, across 1x/10x logical
# speed), and a two-candidate knob sweep's ranked artifact must pick
# the same winner as the live-measured ordering on the same workload
python scripts/trace_replay_check.py
# fault drill (ISSUE 10): a seeded push/serve/promote/sync storm under
# injected transient faults must stay bit-identical to an uninjected
# shadow; a server killed mid-storm must restore from the incremental
# checkpoint chain bit-exactly within the recovery bound; lookups
# during the degraded restore window shed with ServeDegradedError
# (never a torn or stale read); and a 1%-dirty trickle's delta link
# must cost <= 10% of a full checkpoint
python scripts/fault_drill_check.py
# port-differential + fused-bag guard (ISSUE 16): the same seeded
# 5-plane storm run against the jax DevicePort and the pure-NumPy
# reference port must read bit-identically (plus a deterministic
# fp16/int8 wire-program differential on standalone tiered stores);
# device/refport.py must stay jax-free with zero lint suppressions;
# and the fused gather_pool bag read must beat gather-then-host-pool
# (median pairwise, < 0.9 on accelerators; near-parity bar on CPU
# hosts where the wire-byte saving is a memcpy — ADAPM_BAG_RATIO_MAX)
python scripts/portdiff_check.py
# decision-telemetry guard (ISSUE 17): a captured zipf storm's decision
# trace must carry a complete feature vector on every event, close
# >= 90% of outcome-attribution windows, export a byte-deterministic
# labeled dataset, and fold a strictly higher tier regret rate under a
# thrashing (tiny) hot pool than under an ample one
python scripts/decision_quality_check.py
# learned-policy promotion gate (ISSUE 18): the same thrashing-pool
# storm must train a byte-deterministic policy artifact whose learned
# tier policy strictly beats the heuristic on replayed tier regret
# while folding a bit-identical reads digest (a policy changes
# what/when, never values)
python scripts/policy_gate_check.py
# NetPort transport drill (ISSUE 19): a seeded two-node loopback storm
# under injected frame drop/dup/delay/partition must read bit-identical
# to an uninjected single-process shadow after every quiesce (lock-order
# sentinel armed); killing one node mid-storm must promote its replicas
# to mains within the bounded, recorded net.failover_s and the survivor
# must keep serving the covered keys bit-exactly
python scripts/net_storm_check.py
# freshness-SLO guard (ISSUE 20): with --sys.stream.freshness_slo_ms
# set tight against lazy static knobs (250 ms replica refresh, 2/s
# sync), the closed-loop controller must walk the levers in the
# correct direction on its first move and land the trailing-window
# event-to-servable freshness P99 within the tolerance band of the
# target (median of trailing windows; ADAPM_FRESHNESS_BAND)
python scripts/freshness_slo_check.py
python -m pytest tests/ -q "$@"
echo "ALL TESTS PASSED"
