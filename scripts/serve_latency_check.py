"""Serving-plane latency guard (ISSUE 4 satellite; run by
scripts/run_tests.sh).

Two assertions about adapm_tpu/serve that a regression would break
silently:

1. **Coalescing wins.** At 32 concurrent clients, the coalesced
   `ServeSession.lookup` path must beat sequential per-request
   `Worker.pull_sync` of the same request stream by a safe margin.
   Methodology: same MEDIAN-pairwise-ratio pattern as
   scripts/mgmt_plane_check.py / metrics_overhead_check.py —
   (coalesced, sequential) timings back to back per repeat, guard on
   the median ratio. The guard is sized for the real failure mode: if
   the batcher stops coalescing (one dispatch per request — e.g. the
   micro-batch window breaks, or the dispatcher serializes behind a
   lock it should not hold), the coalesced path costs what sequential
   costs PLUS queue/thread overhead, pushing EVERY pairwise ratio to
   ~1.0+. Unlike the single-threaded mgmt guard, the coalesced side
   runs 32 client threads on a (possibly loaded) 2-core container, so
   individual pairs can spike arbitrarily on scheduler noise — the
   guard is therefore on the MIN pairwise ratio: if even the best pair
   cannot beat sequential, coalescing is broken (the failure mode
   degrades all pairs together, so min loses no sensitivity). All
   gather bucket shapes are pre-compiled before timing (a mid-loop XLA
   compile of a new union bucket would otherwise dominate a pair).
   Recorded baseline on the reference host (2-core container,
   32 clients x 8 lookups of 64 skewed keys): min ratio ~0.15-0.45;
   threshold 0.8 (override: ADAPM_SERVE_RATIO_MAX), tighten per the
   1.15x-headroom procedure when this host's numbers move.

2. **Idle serves nothing.** An idle serving plane must dispatch ZERO
   device programs: the dispatcher parks on the admission queue's
   condition variable — no polling gathers, no busy loop. Checked
   against the stores' host-side gather-program counters AND the
   serve.batches_total counter over an idle second.
"""
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("ADAPM_PLATFORM", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    from xla_compat import mesh_flags
    os.environ["XLA_FLAGS"] = " ".join([_flags, mesh_flags(2)]).strip()

import numpy as np  # noqa: E402

CLIENTS = 32
LOOKUPS = 8          # per client per repeat
B = 64               # keys per lookup
NK = 4096
VLEN = 8
REPEATS = 5


def build():
    import jax

    import adapm_tpu
    from adapm_tpu.config import SystemOptions
    from adapm_tpu.serve import ServePlane

    jax.config.update("jax_platforms", "cpu")
    srv = adapm_tpu.setup(NK, VLEN, opts=SystemOptions(
        sync_max_per_sec=0, prefetch=False))
    w = srv.make_worker(0)
    rng = np.random.default_rng(0)
    w.wait(w.set(np.arange(NK),
                 rng.normal(size=(NK, VLEN)).astype(np.float32)))
    plane = ServePlane(srv)
    return srv, w, plane, rng


def run_coalesced(plane, batches) -> float:
    barrier = threading.Barrier(CLIENTS + 1)
    errs = []

    def client(ci):
        try:
            sess = plane.session()
            barrier.wait()
            for b in batches[ci]:
                sess.lookup(b)
        except BaseException as e:  # noqa: BLE001
            errs.append(e)

    threads = [threading.Thread(target=client, args=(ci,))
               for ci in range(CLIENTS)]
    for t in threads:
        t.start()
    barrier.wait()
    t0 = time.perf_counter()
    for t in threads:
        t.join()
    dt = time.perf_counter() - t0
    assert not errs, errs[:3]
    return dt


def run_sequential(w, batches) -> float:
    t0 = time.perf_counter()
    for cb in batches:
        for b in cb:
            w.pull_sync(b)
    return time.perf_counter() - t0


def main() -> int:
    ratio_max = float(os.environ.get("ADAPM_SERVE_RATIO_MAX", "0.8"))
    srv, w, plane, rng = build()

    def make_batches():
        # power-law key skew (embedding serving is zipfian, bench.py
        # _skewed_keys): concurrent clients hit the same hot rows, which
        # is exactly the union-dedup case the coalescer exists for
        return [[(NK * rng.random(B) ** 3).astype(np.int64)
                 .clip(0, NK - 1) for _ in range(LOOKUPS)]
                for _ in range(CLIENTS)]

    # warm both paths. Every gather bucket shape a coalesced union can
    # hit is compiled HERE: union sizes vary per repeat, and a mid-loop
    # XLA compile of a fresh power-of-two bucket would dominate that
    # pair's timing.
    n = B
    while True:
        w.pull_sync(np.arange(min(n, NK), dtype=np.int64))
        if n >= min(CLIENTS * B, NK):
            break
        n *= 2
    warm = make_batches()
    run_sequential(w, warm[:2])
    run_coalesced(plane, warm)

    pairs = []
    for _ in range(REPEATS):
        batches = make_batches()
        t_coal = run_coalesced(plane, batches)
        t_seq = run_sequential(w, batches)
        pairs.append(t_coal / t_seq)

    # -- idle guard: a parked serving plane dispatches nothing ----------
    time.sleep(0.05)  # let the dispatcher park after the last batch
    g0 = sum(s.gathers for s in srv.stores)
    b0 = srv.obs.find("serve.batches_total").value
    time.sleep(1.0)
    g1 = sum(s.gathers for s in srv.stores)
    b1 = srv.obs.find("serve.batches_total").value
    idle_ok = (g1 == g0) and (b1 == b0)

    srv.shutdown()
    pairs.sort()
    best, median = pairs[0], pairs[len(pairs) // 2]
    print(f"[serve-check] {CLIENTS} clients x {LOOKUPS} lookups x "
          f"{REPEATS} pairs: coalesced/sequential ratios min "
          f"{best:.3f} / median {median:.3f} / max {pairs[-1]:.3f} "
          f"(guard: min < {ratio_max:.2f}; a non-coalescing batcher "
          f"degrades every pair to ~1.0+) | idle: gathers {g1 - g0:+d}, "
          f"batches {b1 - b0:+.0f}")
    rc = 0
    if best >= ratio_max:
        print("[serve-check] FAILED: coalesced lookups no longer beat "
              "sequential per-request pulls — check the micro-batch "
              "window (take/max_wait), union dedup, and that the "
              "dispatcher is not serializing behind an extra lock",
              file=sys.stderr)
        rc = 1
    if not idle_ok:
        print("[serve-check] FAILED: an idle serving plane dispatched "
              "device programs — the dispatcher must park on the "
              "admission queue, never poll with gathers",
              file=sys.stderr)
        rc = 1
    if rc == 0:
        print("[serve-check] OK")
    return rc


if __name__ == "__main__":
    sys.exit(main())
