"""Serving-plane latency guard (ISSUE 4 satellite; run by
scripts/run_tests.sh).

Two assertions about adapm_tpu/serve that a regression would break
silently:

1. **Coalescing wins.** At 32 concurrent clients, the coalesced
   `ServeSession.lookup` path must beat sequential per-request
   `Worker.pull_sync` of the same request stream by a safe margin.
   Methodology: same MEDIAN-pairwise-ratio pattern as
   scripts/mgmt_plane_check.py / metrics_overhead_check.py —
   (coalesced, sequential) timings back to back per repeat, guard on
   the median ratio. The guard is sized for the real failure mode: if
   the batcher stops coalescing (one dispatch per request — e.g. the
   micro-batch window breaks, or the dispatcher serializes behind a
   lock it should not hold), the coalesced path costs what sequential
   costs PLUS queue/thread overhead, pushing EVERY pairwise ratio to
   ~1.0+. Unlike the single-threaded mgmt guard, the coalesced side
   runs 32 client threads on a (possibly loaded) 2-core container, so
   individual pairs can spike arbitrarily on scheduler noise — the
   guard is therefore on the MIN pairwise ratio: if even the best pair
   cannot beat sequential, coalescing is broken (the failure mode
   degrades all pairs together, so min loses no sensitivity). All
   gather bucket shapes are pre-compiled before timing (a mid-loop XLA
   compile of a new union bucket would otherwise dominate a pair).
   Recorded baseline on the reference host (2-core container,
   32 clients x 8 lookups of 64 skewed keys): min ratio ~0.15-0.45;
   threshold 0.8 (override: ADAPM_SERVE_RATIO_MAX), tighten per the
   1.15x-headroom procedure when this host's numbers move.

2. **Idle serves nothing.** An idle serving plane must dispatch ZERO
   device programs: the dispatcher parks on the admission queue's
   condition variable — no polling gathers, no busy loop. Checked
   against the stores' host-side gather-program counters AND the
   serve.batches_total counter over an idle second.

ISSUE 9 guards (the read fast path + tenancy):

3. **The replica path wins under write contention.** With
   `--sys.serve.replica_rows` set and a concurrent training pusher
   hammering the server lock, hot-row lookups served from the
   epoch-validated snapshot (no lock, no device dispatch) must beat
   the r13 locked path on the same load: MEDIAN pairwise wall ratio
   < 0.8 (override: ADAPM_SERVE_REPLICA_RATIO_MAX), with
   replica-path hits actually observed (hit counter floor) in every
   replica half — a snapshot that silently stops covering the hot set
   degrades every pair toward 1.0.

4. **Tenancy holds the high-priority tail under a flood.** A
   low-priority tenant flooding a small queue must SHED
   (shed+rejected > 0 — quota/pressure backpressure, never a hang)
   while the high-priority tenant's P99, served through priority
   claim (priority-pure batches) + the replica fast path, stays under
   ADAPM_SERVE_GOLD_P99_MS (default 400 ms — sized for a loaded
   2-core container where one in-flight bronze batch's locked gather
   bounds the gold wait; recorded ~230 ms on the reference host) with
   zero gold sheds.
"""
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("ADAPM_PLATFORM", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    from xla_compat import mesh_flags
    os.environ["XLA_FLAGS"] = " ".join([_flags, mesh_flags(2)]).strip()

import numpy as np  # noqa: E402

CLIENTS = 32
LOOKUPS = 8          # per client per repeat
B = 64               # keys per lookup
NK = 4096
VLEN = 8
REPEATS = 5


def build():
    import jax

    import adapm_tpu
    from adapm_tpu.config import SystemOptions
    from adapm_tpu.serve import ServePlane

    jax.config.update("jax_platforms", "cpu")
    srv = adapm_tpu.setup(NK, VLEN, opts=SystemOptions(
        sync_max_per_sec=0, prefetch=False))
    w = srv.make_worker(0)
    rng = np.random.default_rng(0)
    w.wait(w.set(np.arange(NK),
                 rng.normal(size=(NK, VLEN)).astype(np.float32)))
    plane = ServePlane(srv)
    return srv, w, plane, rng


def run_coalesced(plane, batches) -> float:
    barrier = threading.Barrier(CLIENTS + 1)
    errs = []

    def client(ci):
        try:
            sess = plane.session()
            barrier.wait()
            for b in batches[ci]:
                sess.lookup(b)
        except BaseException as e:  # noqa: BLE001
            errs.append(e)

    threads = [threading.Thread(target=client, args=(ci,))
               for ci in range(CLIENTS)]
    for t in threads:
        t.start()
    barrier.wait()
    t0 = time.perf_counter()
    for t in threads:
        t.join()
    dt = time.perf_counter() - t0
    assert not errs, errs[:3]
    return dt


def run_sequential(w, batches) -> float:
    t0 = time.perf_counter()
    for cb in batches:
        for b in cb:
            w.pull_sync(b)
    return time.perf_counter() - t0


def run_replica_guard(srv, w, rng) -> tuple:
    """Guard 3: replica-path vs locked-path pairwise ratios under a
    concurrent training pusher (same plane, replica detached for the
    locked half — the r13 baseline path, byte for byte)."""
    import threading

    from adapm_tpu.serve import ServePlane

    clients, lookups, hot_n = 6, 48, 256
    srv.opts.serve_replica_rows = 512
    srv.opts.serve_replica_refresh_ms = 10.0
    plane = ServePlane(srv)
    hot = np.arange(hot_n, dtype=np.int64)
    batches = [[rng.choice(hot, B) for _ in range(lookups)]
               for _ in range(clients)]

    def run(attach_replica) -> float:
        plane.batcher.replica = plane.replica if attach_replica else None
        barrier = threading.Barrier(clients + 1)
        errs = []

        def client(ci):
            try:
                sess = plane.session()
                barrier.wait()
                for b in batches[ci]:
                    sess.lookup(b)
            except BaseException as e:  # noqa: BLE001
                errs.append(e)

        threads = [threading.Thread(target=client, args=(ci,))
                   for ci in range(clients)]
        for t in threads:
            t.start()
        barrier.wait()
        t0 = time.perf_counter()
        for t in threads:
            t.join()
        dt = time.perf_counter() - t0
        assert not errs, errs[:3]
        return dt

    # warm scores + snapshot; pin that the fast path fires at all
    run(True)
    assert plane.replica.refresh_now() > 0, "empty replica snapshot"
    h0 = srv.obs.find("serve.replica_hits_total").value
    run(True)
    hits_ok = srv.obs.find("serve.replica_hits_total").value > h0

    # concurrent training pushes on DISJOINT keys: lock contention for
    # the locked half, epoch-silence for the snapshot's hot rows
    stop = threading.Event()
    push_keys = np.arange(1024, NK, dtype=np.int64)

    def pusher():
        prng = np.random.default_rng(5)
        while not stop.is_set():
            ks = np.unique(prng.choice(push_keys, 64))
            w.push(ks, np.ones((len(ks), VLEN), np.float32))

    pt = threading.Thread(target=pusher)
    pt.start()
    pairs = []
    try:
        for _ in range(9):
            h0 = srv.obs.find("serve.replica_hits_total").value
            t_rep = run(True)
            if srv.obs.find("serve.replica_hits_total").value <= h0:
                hits_ok = False
            t_lock = run(False)
            pairs.append(t_rep / t_lock)
    finally:
        stop.set()
        pt.join()
    plane.close()
    pairs.sort()
    return pairs, hits_ok


def run_tenant_guard(srv, w, rng) -> dict:
    """Guard 4: bronze flood sheds, gold P99 holds (see module doc)."""
    import threading

    from adapm_tpu.config import SystemOptions
    from adapm_tpu.serve import (DeadlineExceededError,
                                 ServeOverloadError, ServePlane)

    opts = SystemOptions(sync_max_per_sec=0, prefetch=False,
                         serve_queue=64, serve_max_batch=32,
                         serve_dispatchers=2, serve_replica_rows=512,
                         serve_replica_refresh_ms=10.0)
    plane = ServePlane(srv, opts=opts)
    plane.configure_tenant("gold", priority=2)
    plane.configure_tenant("bronze", priority=0)
    hot = np.arange(256, dtype=np.int64)
    # seed the snapshot with the gold working set
    sess0 = plane.session(tenant="gold")
    sess0.lookup(hot)    # score the whole gold working set
    plane.replica.refresh_now()
    h0 = srv.obs.find("serve.replica_hits_total").value
    b0 = srv.obs.find("serve.batches_total").value

    stop = threading.Event()
    errs = []
    gold_lat = []
    gold_sheds = [0]

    def pusher():
        prng = np.random.default_rng(6)
        ks_all = np.arange(1024, NK, dtype=np.int64)
        while not stop.is_set():
            ks = np.unique(prng.choice(ks_all, 64))
            w.push(ks, np.ones((len(ks), VLEN), np.float32))

    def bronze(ci):
        prng = np.random.default_rng(100 + ci)
        sess = plane.session(tenant="bronze")
        try:
            while not stop.is_set():
                try:
                    sess.lookup(prng.integers(0, NK, B),
                                deadline_ms=5.0)
                except (DeadlineExceededError, ServeOverloadError):
                    pass  # the expected backpressure under the flood
        except BaseException as e:  # noqa: BLE001
            errs.append(e)

    def gold():
        prng = np.random.default_rng(200)
        sess = plane.session(tenant="gold")
        try:
            for _ in range(60):
                t0 = time.perf_counter()
                try:
                    sess.lookup(prng.choice(hot, B), deadline_ms=1000.0)
                    gold_lat.append(time.perf_counter() - t0)
                except (DeadlineExceededError, ServeOverloadError):
                    gold_sheds[0] += 1
                time.sleep(0.01)   # paced open-loop arrivals
        except BaseException as e:  # noqa: BLE001
            errs.append(e)

    threads = [threading.Thread(target=pusher)] + \
              [threading.Thread(target=bronze, args=(ci,))
               for ci in range(4)] + [threading.Thread(target=gold)]
    for t in threads:
        t.start()
    threads[-1].join(timeout=120)
    stop.set()
    for t in threads[:-1]:
        t.join(timeout=60)
    assert not errs, errs[:3]
    bz = plane.queue.tenant("bronze")
    hits_d = srv.obs.find("serve.replica_hits_total").value - h0
    batches_d = srv.obs.find("serve.batches_total").value - b0
    out = {"gold_p99_ms": 1e3 * sorted(gold_lat)[
               max(0, int(0.99 * len(gold_lat)) - 1)] if gold_lat
           else float("inf"),
           "gold_served": len(gold_lat),
           "gold_sheds": gold_sheds[0],
           "bronze_shed": bz.c_shed.value + bz.c_rejected.value,
           # segment-windowed (the cumulative gauge is diluted by the
           # coalesce segment's batches on this shared server)
           "replica_hit_rate": hits_d / max(1.0, batches_d)}
    plane.close()
    return out


def main() -> int:
    ratio_max = float(os.environ.get("ADAPM_SERVE_RATIO_MAX", "0.8"))
    rep_ratio_max = float(os.environ.get(
        "ADAPM_SERVE_REPLICA_RATIO_MAX", "0.8"))
    gold_p99_max_ms = float(os.environ.get(
        "ADAPM_SERVE_GOLD_P99_MS", "400"))
    srv, w, plane, rng = build()

    def make_batches():
        # power-law key skew (embedding serving is zipfian, bench.py
        # _skewed_keys): concurrent clients hit the same hot rows, which
        # is exactly the union-dedup case the coalescer exists for
        return [[(NK * rng.random(B) ** 3).astype(np.int64)
                 .clip(0, NK - 1) for _ in range(LOOKUPS)]
                for _ in range(CLIENTS)]

    # warm both paths. Every gather bucket shape a coalesced union can
    # hit is compiled HERE: union sizes vary per repeat, and a mid-loop
    # XLA compile of a fresh power-of-two bucket would dominate that
    # pair's timing.
    n = B
    while True:
        w.pull_sync(np.arange(min(n, NK), dtype=np.int64))
        if n >= min(CLIENTS * B, NK):
            break
        n *= 2
    warm = make_batches()
    run_sequential(w, warm[:2])
    run_coalesced(plane, warm)

    pairs = []
    for _ in range(REPEATS):
        batches = make_batches()
        t_coal = run_coalesced(plane, batches)
        t_seq = run_sequential(w, batches)
        pairs.append(t_coal / t_seq)

    # -- idle guard: a parked serving plane dispatches nothing ----------
    time.sleep(0.05)  # let the dispatcher park after the last batch
    g0 = sum(s.gathers for s in srv.stores)
    b0 = srv.obs.find("serve.batches_total").value
    time.sleep(1.0)
    g1 = sum(s.gathers for s in srv.stores)
    b1 = srv.obs.find("serve.batches_total").value
    idle_ok = (g1 == g0) and (b1 == b0)

    # -- ISSUE 9 guards: replica fast path + tenancy --------------------
    plane.close()   # one live plane per server
    rep_pairs, rep_hits_ok = run_replica_guard(srv, w, rng)
    tenant = run_tenant_guard(srv, w, rng)

    srv.shutdown()
    pairs.sort()
    best, median = pairs[0], pairs[len(pairs) // 2]
    print(f"[serve-check] {CLIENTS} clients x {LOOKUPS} lookups x "
          f"{REPEATS} pairs: coalesced/sequential ratios min "
          f"{best:.3f} / median {median:.3f} / max {pairs[-1]:.3f} "
          f"(guard: min < {ratio_max:.2f}; a non-coalescing batcher "
          f"degrades every pair to ~1.0+) | idle: gathers {g1 - g0:+d}, "
          f"batches {b1 - b0:+.0f}")
    rep_median = rep_pairs[len(rep_pairs) // 2]
    print(f"[serve-check] replica guard: replica/locked wall ratios "
          f"min {rep_pairs[0]:.3f} / median {rep_median:.3f} / max "
          f"{rep_pairs[-1]:.3f} under concurrent pushes (guard: "
          f"median < {rep_ratio_max:.2f}; hits observed: "
          f"{rep_hits_ok})")
    print(f"[serve-check] tenant guard: gold p99 "
          f"{tenant['gold_p99_ms']:.1f} ms over "
          f"{tenant['gold_served']} served / {tenant['gold_sheds']} "
          f"shed (guard: < {gold_p99_max_ms:.0f} ms, 0 shed) | bronze "
          f"shed+rejected {tenant['bronze_shed']:.0f} (floor: > 0) | "
          f"replica_hit_rate {tenant['replica_hit_rate']:.3f}")
    rc = 0
    if best >= ratio_max:
        print("[serve-check] FAILED: coalesced lookups no longer beat "
              "sequential per-request pulls — check the micro-batch "
              "window (take/max_wait), union dedup, and that the "
              "dispatcher is not serializing behind an extra lock",
              file=sys.stderr)
        rc = 1
    if not idle_ok:
        print("[serve-check] FAILED: an idle serving plane dispatched "
              "device programs — the dispatcher must park on the "
              "admission queue, never poll with gathers",
              file=sys.stderr)
        rc = 1
    if rep_median >= rep_ratio_max or not rep_hits_ok:
        print("[serve-check] FAILED: the replica read fast path no "
              "longer beats the locked path under write contention "
              "(or the snapshot stopped covering the hot set) — check "
              "epoch validation, the refresh selection, and that "
              "try_serve stays lock-free", file=sys.stderr)
        rc = 1
    if (tenant["gold_p99_ms"] >= gold_p99_max_ms
            or tenant["gold_sheds"] > 0 or tenant["bronze_shed"] <= 0
            or tenant["replica_hit_rate"] <= 0):
        print("[serve-check] FAILED: tenancy guard — a low-priority "
              "flood must shed while the high-priority tenant's tail "
              "holds through priority claim + the replica fast path",
              file=sys.stderr)
        rc = 1
    if rc == 0:
        print("[serve-check] OK")
    return rc


if __name__ == "__main__":
    sys.exit(main())
