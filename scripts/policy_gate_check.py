"""Learned-policy promotion gate (ISSUE 18; run by scripts/run_tests.sh).

The replay lab is the promotion gate: a policy ships only when the
deterministic replay ranks it at least as well as the heuristic it
replaces, and NOTHING about the values a client reads may change. On a
seeded zipf storm against a deliberately starved hot pool (the
decision_quality_check contrast that makes the tier heuristic thrash —
promotions under churn evict rows before they are re-touched):

  1. **Capture -> dataset -> train.** The storm's `.dtrace`/`.wtrace`
     pair exports the labeled dataset and trains the per-plane regret
     scorers (`adapm_tpu/policy/train.py`). The tier plane must get a
     real logistic fit (enough labeled promote rows), and re-training
     from the same traces must write a BYTE-IDENTICAL artifact — the
     fit consumes no RNG and mints no timestamp.

  2. **Replay A/B promotion gate.** `rank_candidates` replays the same
     workload under {heuristic, learned-tier} with the metrics-only
     decision recorder attached (`score_decisions=True`) and ranks by
     `regret_rate_tier`. The learned policy must WIN — strictly lower
     tier regret (ties rank the heuristic first by name, so a
     do-nothing model cannot pass).

  3. **Value preservation.** Both candidates must fold the SAME
     `reads_digest`: the learned tier veto only holds background
     promotions, which never changes what a read returns — a policy
     changes *what/when*, never *values* (docs/POLICY.md).
"""
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("ADAPM_PLATFORM", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    from xla_compat import mesh_flags
    os.environ["XLA_FLAGS"] = " ".join([_flags, mesh_flags(8)]).strip()

import numpy as np  # noqa: E402

E = 1024          # keys
VL = 8            # value length
STEPS = 80        # storm steps
SKEW = 6.0        # zipf-ish skew (key = E * u^SKEW)
SEED = 29


def _storm(tmp):
    """The decision_quality_check tiny-pool storm: captures both trace
    planes under a starved hot pool so tier regret has signal."""
    from adapm_tpu import Server, SystemOptions, make_mesh
    from adapm_tpu.replay import per_shard_hot_rows
    dpath = os.path.join(tmp, "storm.dtrace")
    wpath = os.path.join(tmp, "storm.wtrace")
    tiny = max(8, per_shard_hot_rows(E, 0.05))
    opts = SystemOptions(sync_max_per_sec=0, prefetch=False,
                         tier=True, tier_hot_rows=tiny,
                         trace_decisions=dpath,
                         trace_workload=wpath)
    srv = Server(E, VL, opts=opts, ctx=make_mesh(8), num_workers=2)
    w0, w1 = srv.make_worker(0), srv.make_worker(1)
    w0.wait(w0.set(np.arange(E), np.ones((E, VL), np.float32)))
    rng = np.random.default_rng(SEED)
    for i in range(STEPS):
        w = w0 if i % 2 == 0 else w1
        ks = np.unique((E * rng.random(24) ** SKEW)
                       .astype(np.int64).clip(0, E - 1))
        w.pull_sync(ks)
        w.wait(w.push(ks, np.ones((len(ks), VL), np.float32)))
        if i % 4 == 0:
            w.intent(ks, w.current_clock, w.current_clock + 4)
            w.advance_clock()
        srv.wait_sync()
    srv.quiesce()
    srv.shutdown()
    return dpath, wpath


def main() -> int:
    from adapm_tpu.policy import train_policy
    from adapm_tpu.replay import load_wtrace, rank_candidates

    with tempfile.TemporaryDirectory(prefix="adapm-pgc-") as tmp:
        dpath, wpath = _storm(tmp)

        # 1. capture -> dataset -> train; byte-deterministic re-train
        p1, p2 = (os.path.join(tmp, n) for n in ("pol1.json",
                                                 "pol2.json"))
        bundle = train_policy(dpath, wpath, out_path=p1)
        train_policy(dpath, wpath, out_path=p2)
        with open(p1, "rb") as f1, open(p2, "rb") as f2:
            b1, b2 = f1.read(), f2.read()
        if b1 != b2:
            print("[policy-check] FAILED: re-training from the same "
                  "traces is not byte-deterministic", file=sys.stderr)
            return 1
        tm = bundle.meta["train"]
        print(f"[policy-check] trained from "
              f"{bundle.meta['dataset_rows']} dataset rows "
              f"({bundle.meta['truncated_rows']} truncated excluded); "
              f"two trainings byte-identical ({len(b1)} bytes)")
        for plane in sorted(tm):
            m = tm[plane]
            print(f"[policy-check]   {plane}: {m['fit']} fit, "
                  f"{m['used']}/{m['rows']} rows, {m['pos']} regretted")
        if tm["tier"]["fit"] != "logistic":
            print("[policy-check] FAILED: the tier plane fell back to "
                  f"the constant model ({tm['tier']}) — the storm "
                  "produced too few labeled promote rows",
                  file=sys.stderr)
            return 1

        # 2. replay A/B promotion gate on tier regret
        tr = load_wtrace(wpath)
        art = rank_candidates(
            tr,
            {"heuristic": {},
             "learned": {"policy_tier": "learned",
                         "policy_file": p1}},
            objective="regret_rate_tier", seed=7, speed=10.0,
            score_decisions=True)
        heur = art["candidates"]["heuristic"]
        lrn = art["candidates"]["learned"]
        r_h = heur["score"]["regret_rate_tier"]
        r_l = lrn["score"]["regret_rate_tier"]
        print(f"[policy-check] replay A/B regret_rate.tier: heuristic "
              f"{r_h} vs learned {r_l} -> winner {art['winner']} "
              f"(gate: learned strictly better)")
        if art["winner"] != "learned":
            print("[policy-check] FAILED: the learned tier policy did "
                  "not beat the heuristic on replay tier regret — not "
                  "promotable", file=sys.stderr)
            return 1

        # 3. value preservation: identical reads digests
        if heur["reads_digest"] != lrn["reads_digest"]:
            print(f"[policy-check] FAILED: reads digests diverge "
                  f"(heuristic {heur['reads_digest'][:16]}.. vs "
                  f"learned {lrn['reads_digest'][:16]}..) — the "
                  f"policy changed VALUES, not just what/when",
                  file=sys.stderr)
            return 1
        print(f"[policy-check] value preservation: both candidates "
              f"fold reads_digest {heur['reads_digest'][:16]}.. over "
              f"{heur['reads']} reads")

    print("[policy-check] OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
