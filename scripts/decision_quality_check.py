"""Decision-telemetry quality gate (ISSUE 17; run by scripts/run_tests.sh).

Four acceptance properties of the decision plane, end to end, on a
seeded zipf storm (the DLRM embedding-bag shape) captured with BOTH
`--sys.trace.decisions` and `--sys.trace.workload`:

  1. **Complete feature vectors.** Every decision event in the
     `.dtrace` carries every CORE_FEATURES key (logical clock, live
     replicas, dirty fraction, hot free/total rows, batch size) — a
     policy cannot train on rows with holes.

  2. **Attribution closure.** >= 90% of decisions have a resolved
     outcome event (immediate or window; `close()` force-resolves
     stragglers with `truncated: true`, which counts — a truncated
     label is a label).

  3. **Deterministic export.** `replay/dataset.py` run twice over the
     same (.dtrace, .wtrace) pair writes byte-identical artifacts.

  4. **Regret discriminates policies.** The same storm against a tiny
     hot pool must fold a strictly higher `decision.regret_rate.tier`
     than an amply-sized pool: promotion under churn evicts rows
     before they are re-touched (promoted_never_hit), which is
     exactly the signal the regret counters exist to surface. A
     telemetry plane whose regret metric cannot tell a thrashing
     policy from a healthy one is decoration.
"""
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("ADAPM_PLATFORM", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    from xla_compat import mesh_flags
    os.environ["XLA_FLAGS"] = " ".join([_flags, mesh_flags(8)]).strip()

import numpy as np  # noqa: E402

E = 1024          # keys
VL = 8            # value length
STEPS = 80        # storm steps
SKEW = 6.0        # zipf-ish skew (key = E * u^SKEW)
SEED = 29


def _storm(tmp, tag: str, hot_rows: int):
    """One seeded capture storm at the given per-shard hot-pool size;
    returns (dtrace_path, wtrace_path, decision_snapshot_section)."""
    from adapm_tpu import Server, SystemOptions, make_mesh
    dpath = os.path.join(tmp, f"{tag}.dtrace")
    wpath = os.path.join(tmp, f"{tag}.wtrace")
    opts = SystemOptions(sync_max_per_sec=0, prefetch=False,
                         tier=True, tier_hot_rows=hot_rows,
                         trace_decisions=dpath,
                         trace_workload=wpath)
    srv = Server(E, VL, opts=opts, ctx=make_mesh(8), num_workers=2)
    w0, w1 = srv.make_worker(0), srv.make_worker(1)
    w0.wait(w0.set(np.arange(E),
                   np.ones((E, VL), np.float32)))
    rng = np.random.default_rng(SEED)
    for i in range(STEPS):
        w = w0 if i % 2 == 0 else w1
        ks = np.unique((E * rng.random(24) ** SKEW)
                       .astype(np.int64).clip(0, E - 1))
        w.pull_sync(ks)
        w.wait(w.push(ks, np.ones((len(ks), VL), np.float32)))
        if i % 4 == 0:
            w.intent(ks, w.current_clock, w.current_clock + 4)
            w.advance_clock()
        srv.wait_sync()
    snap = srv.metrics_snapshot()["decision"]
    srv.shutdown()
    return dpath, wpath, snap


def main() -> int:
    from adapm_tpu.obs.decisions import CORE_FEATURES, load_dtrace
    from adapm_tpu.replay import export_dataset, per_shard_hot_rows

    with tempfile.TemporaryDirectory(prefix="adapm-dqc-") as tmp:
        ample = per_shard_hot_rows(E, 1.0)
        dpath, wpath, snap_ok = _storm(tmp, "ample", ample)
        tiny_rows = max(8, per_shard_hot_rows(E, 0.05))
        _, _, snap_tiny = _storm(tmp, "tiny", tiny_rows)

        tr = load_dtrace(dpath)
        decisions = tr.decisions()
        outcomes = tr.outcomes()
        if not decisions:
            print("[decision-check] FAILED: storm produced zero "
                  "decision events", file=sys.stderr)
            return 1
        planes = tr.planes()
        for must in ("tier", "sync"):
            if not planes.get(must):
                print(f"[decision-check] FAILED: no {must!r}-plane "
                      f"decisions captured (got {planes})",
                      file=sys.stderr)
                return 1

        # 1. complete feature vectors
        holes = [(d["seq"], k) for d in decisions
                 for k in CORE_FEATURES
                 if k not in d.get("features", {})]
        if holes:
            print(f"[decision-check] FAILED: {len(holes)} feature "
                  f"holes, first {holes[:5]}", file=sys.stderr)
            return 1
        print(f"[decision-check] {len(decisions)} decisions across "
              f"planes {planes}: every event carries all "
              f"{len(CORE_FEATURES)} core features")

        # 2. attribution closure
        closed = sum(1 for d in decisions if d["seq"] in outcomes)
        closure = closed / len(decisions)
        print(f"[decision-check] attribution closure "
              f"{closed}/{len(decisions)} = {closure:.3f} "
              f"(gate: >= 0.90)")
        if closure < 0.90:
            print("[decision-check] FAILED: attribution closure under "
                  "0.90", file=sys.stderr)
            return 1

        # 3. deterministic dataset export
        p1, p2 = (os.path.join(tmp, n) for n in ("ds1.json",
                                                 "ds2.json"))
        art = export_dataset(dpath, wpath, out_path=p1)
        export_dataset(dpath, wpath, out_path=p2)
        with open(p1, "rb") as f1, open(p2, "rb") as f2:
            b1, b2 = f1.read(), f2.read()
        if b1 != b2:
            print("[decision-check] FAILED: dataset export is not "
                  "byte-deterministic", file=sys.stderr)
            return 1
        print(f"[decision-check] dataset export: {art['n_rows']} rows "
              f"x {len(art['columns'])} columns, two exports "
              f"byte-identical ({len(b1)} bytes)")

        # 4. regret discriminates a thrashing tier policy
        r_ok = snap_ok.get("regret_rate.tier", 0.0)
        r_tiny = snap_tiny.get("regret_rate.tier", 0.0)
        print(f"[decision-check] regret_rate.tier: ample "
              f"({ample} rows/shard) {r_ok:.3f} vs tiny "
              f"({tiny_rows} rows/shard) {r_tiny:.3f} "
              f"(gate: tiny > ample)")
        if not r_tiny > r_ok:
            print("[decision-check] FAILED: tiny hot pool did not "
                  "raise tier regret over the ample pool",
                  file=sys.stderr)
            return 1

    print("[decision-check] OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
