#!/usr/bin/env bash
# Re-run a flaky-prone test N times (reference tests/repeat.sh).
# Usage: scripts/repeat.sh 20 tests/test_consistency.py::test_monotonic_pushes
set -euo pipefail
cd "$(dirname "$0")/.."
N=${1:?usage: repeat.sh N <pytest target>}
shift
for i in $(seq 1 "$N"); do
  echo "=== run $i/$N ==="
  python -m pytest "$@" -q
done
echo "PASSED $N/$N"
