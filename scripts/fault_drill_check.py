"""Fault/crash-recovery drill (ISSUE 10 acceptance; run by
scripts/run_tests.sh).

Four checks over the robustness spine (adapm_tpu/fault,
docs/failure_handling.md):

1. STORM CORRECTNESS UNDER INJECTED FAULTS: a seeded
   push/set/serve/promote/sync storm runs against a server with the
   fault plane injecting transient failures into the background sync
   tick, the serve drains, tier promotion commits, executor dispatch,
   and checkpoint saves — while an UNINJECTED, untiered shadow server
   applies the identical write sequence. Every serve lookup must be
   bit-identical to the shadow's Worker.pull of the same keys (no torn
   or stale read, ever — a retried drain serves the same bits a
   healthy one would), and after quiesce the two servers' full main
   tables must match bitwise. The drill also asserts the faults
   actually FIRED and were RETRIED (an inert plane would vacuously
   pass).

2. KILL + RESTORE: mid-storm the injected server checkpoints to an
   incremental chain (base + dirty-slot deltas; saves themselves are
   injected and retried), keeps storming PAST the last save (writes
   that are deliberately lost), and is then killed under concurrent
   serve load. A fresh server restores from the chain and must read
   bit-exactly the state at the last checkpoint — mains AND replica
   reads — within ADAPM_RECOVERY_MAX_S (default 60 s) of recovery
   wall time.

3. DEGRADED-MODE SHEDDING: while the restore applies (the window is
   held open with restore_chain's hold_degraded_s so the pin is
   deterministic on any machine), concurrent lookups must shed with
   the DISTINCT ServeDegradedError — every hammer outcome is either a
   clean pre/post-window value or that error; nothing hangs, nothing
   returns a mixed read.

4. INCREMENTAL BYTES: on a second server, a ~1%-dirty trickle's delta
   link must cost <= ADAPM_CKPT_DELTA_RATIO_MAX (default 0.10) of the
   full base checkpoint — the whole point of shipping only dirty
   slots.
"""
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("ADAPM_PLATFORM", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    from xla_compat import mesh_flags
    os.environ["XLA_FLAGS"] = " ".join([_flags, mesh_flags(4)]).strip()

import numpy as np  # noqa: E402

E = 2048
L = 8
SEED = int(os.environ.get("ADAPM_FAULT_DRILL_SEED", "1234"))
FAULT_SPEC = ("sync.round=0.25,serve.drain=0.2,tier.promote=0.2,"
              "exec.dispatch=0.02,ckpt.save=0.3")


def log(msg):
    print(f"[fault-drill] {msg}", flush=True)


def _mk(fault: bool, tier: bool):
    import adapm_tpu
    from adapm_tpu.config import SystemOptions
    opts = SystemOptions(
        sync_max_per_sec=0, prefetch=False,
        cache_slots_per_shard=64,
        tier=tier, tier_hot_rows=256,
        serve_max_wait_us=100,
        fault_spec=FAULT_SPEC if fault else "",
        fault_seed=SEED, fault_retries=12, fault_backoff_ms=2.0)
    return adapm_tpu.setup(E, L, opts=opts, num_workers=4)


def _save_retrying(ck, tries: int = 20):
    """ckpt.save is itself an injection point (p=0.3): the operator
    loop retries — atomic tmp+rename writes make a failed save
    invisible, so retrying is always safe."""
    from adapm_tpu.fault import InjectedFault
    for _ in range(tries):
        try:
            return ck.save()
        except InjectedFault:
            continue
    raise RuntimeError("checkpoint save exhausted its retry budget")


def main() -> int:
    import adapm_tpu  # noqa: F401
    from adapm_tpu.base import CLOCK_MAX
    from adapm_tpu.fault import IncrementalCheckpointer, restore_chain
    from adapm_tpu.serve import (DeadlineExceededError,
                                 ServeDegradedError, ServePlane)

    recovery_max_s = float(os.environ.get("ADAPM_RECOVERY_MAX_S", "60"))
    delta_ratio_max = float(os.environ.get(
        "ADAPM_CKPT_DELTA_RATIO_MAX", "0.10"))
    chain_dir = os.path.join("/tmp", f"adapm_fault_drill_{os.getpid()}")

    rng = np.random.default_rng(SEED)
    log(f"building injected server (spec {FAULT_SPEC!r}, seed {SEED}) "
        f"+ uninjected untiered shadow")
    srv = _mk(fault=True, tier=True)
    ref = _mk(fault=False, tier=False)
    w, wr = srv.make_worker(0), ref.make_worker(0)
    init = rng.normal(size=(E, L)).astype(np.float32)
    w.set(np.arange(E), init)
    wr.set(np.arange(E), init)
    # adapted placement on the injected side: replicas via competing
    # intents (the chain must carry them through the kill)
    w1 = srv.make_worker(1)
    shared = np.arange(0, 48)
    w.intent(shared, 0, CLOCK_MAX)
    w1.intent(shared, 0, CLOCK_MAX)
    srv.wait_sync()

    plane = ServePlane(srv)
    sess = plane.session()
    ck = IncrementalCheckpointer(srv, chain_dir)
    _save_retrying(ck)  # base
    srv.start_sync_thread()
    ref.start_sync_thread()

    # ---- 1. storm under injected faults, lookups vs the shadow ----------
    lookups = sheds = 0
    for step in range(60):
        keys = np.unique(rng.integers(0, E, 96))
        vals = rng.normal(size=(len(keys), L)).astype(np.float32)
        if step % 11 == 3:
            w.set(keys, vals)
            wr.set(keys, vals)
        else:
            w.push(keys, vals)
            wr.push(keys, vals)
        if step % 3 == 0:
            qk = np.unique(rng.integers(0, E, 64))
            try:
                got = np.asarray(sess.lookup(qk, deadline_ms=5000))
            except DeadlineExceededError:
                sheds += 1
                continue
            exp = np.asarray(wr.pull_sync(qk))
            assert np.array_equal(got, exp), (
                f"step {step}: serve lookup diverged from the "
                f"uninjected shadow ({int((got != exp).sum())} floats)"
                f" — torn or stale read under injected faults")
            lookups += 1
        if step % 15 == 14:
            _save_retrying(ck)
    srv.stop_sync_thread()
    ref.stop_sync_thread()
    srv.quiesce()
    ref.quiesce()
    a = np.asarray(srv.read_main(np.arange(E)))
    b = np.asarray(ref.read_main(np.arange(E)))
    assert np.array_equal(a, b), (
        f"post-quiesce main tables diverged "
        f"({int((a != b).sum())} floats): injected transient faults "
        f"corrupted state despite retries")
    snap = srv.metrics_snapshot()
    fired = snap["fault"]["injections_fired"]
    retries = snap["fault"]["retries"]          # executor policy
    loop_retries = snap["fault"]["loop_retries"]  # self-healing loops
    assert fired >= 5, f"only {fired} injections fired — drill vacuous"
    assert retries >= 1, \
        f"executor retry policy never engaged ({retries} retries)"
    assert retries + loop_retries >= 3, (
        f"only {retries}+{loop_retries} retries — recovery machinery "
        f"not engaged")
    log(f"storm OK: {lookups} verified bit-identical lookups "
        f"({sheds} deadline-shed), {fired} injections fired, "
        f"{retries} executor retries + {loop_retries} loop retries, "
        f"post-quiesce tables bit-equal")

    # ---- 2. final checkpoint, storm past it, kill under load ------------
    final = _save_retrying(ck)
    expected_main = a.copy()
    expected_pull = np.asarray(w.pull_sync(np.arange(E))).copy()
    log(f"final checkpoint: chain of {ck.stats()['chain_len']} links, "
        f"last {final['kind']} = {final['bytes']}B / "
        f"{final['slots']} slots")
    srv.start_sync_thread()
    stop_storm = threading.Event()
    kill_outcomes = []

    def kill_hammer():
        s2 = plane.session()
        while not stop_storm.is_set():
            try:
                s2.lookup(np.arange(16), deadline_ms=500)
                kill_outcomes.append("ok")
            except Exception as e:  # noqa: BLE001 — the kill races
                # everything; the assertion is "no hang, no crash"
                kill_outcomes.append(type(e).__name__)
            time.sleep(0.002)

    hammers = [threading.Thread(target=kill_hammer, daemon=True)
               for _ in range(3)]
    for t in hammers:
        t.start()
    for _ in range(10):  # post-checkpoint writes: deliberately lost
        keys = np.unique(rng.integers(0, E, 96))
        w.push(keys, rng.normal(size=(len(keys), L)).astype(np.float32))
    t_kill = time.perf_counter()
    srv.shutdown()  # the kill, under concurrent serve load
    stop_storm.set()
    for t in hammers:
        t.join(10)
    log(f"killed mid-storm in {time.perf_counter() - t_kill:.2f}s "
        f"({len(kill_outcomes)} concurrent lookups rode the kill: "
        f"{sorted(set(kill_outcomes))})")

    # ---- 3. restore into a fresh server, degraded window pinned ---------
    srv2 = _mk(fault=False, tier=True)
    w2 = srv2.make_worker(0)
    plane2 = ServePlane(srv2)
    sess2 = plane2.session()
    outcomes = []
    stop = threading.Event()

    def hammer():
        while not stop.is_set():
            try:
                v = np.asarray(sess2.lookup(np.arange(12),
                                            deadline_ms=2000))
                outcomes.append(("ok", v.copy()))
            except ServeDegradedError:
                outcomes.append(("degraded", None))
            except Exception as e:  # noqa: BLE001
                outcomes.append((type(e).__name__, None))
            time.sleep(0.002)

    ham = [threading.Thread(target=hammer, daemon=True)
           for _ in range(3)]
    for t in ham:
        t.start()
    recovery_s = restore_chain(srv2, chain_dir, hold_degraded_s=0.5)
    time.sleep(0.1)
    stop.set()
    for t in ham:
        t.join(10)

    got_main = np.asarray(srv2.read_main(np.arange(E)))
    assert np.array_equal(got_main, expected_main), (
        f"post-restore read_main not bit-exact vs the last checkpoint "
        f"({int((got_main != expected_main).sum())} floats)")
    got_pull = np.asarray(w2.pull_sync(np.arange(E)))
    assert np.array_equal(got_pull.ravel(), expected_pull.ravel()), \
        "post-restore replica reads not bit-exact"
    assert recovery_s <= recovery_max_s, (
        f"recovery took {recovery_s:.2f}s > bound {recovery_max_s}s")
    kinds = {}
    for k, _ in outcomes:
        kinds[k] = kinds.get(k, 0) + 1
    assert kinds.get("degraded", 0) >= 1, (
        f"no lookup observed the degraded window: {kinds}")
    bad = set(kinds) - {"ok", "degraded", "DeadlineExceededError"}
    assert not bad, f"unexpected lookup outcomes during restore: {kinds}"
    # every successful hammer read is a CLEAN state: the fresh server's
    # zeros (pre-window) or the restored bits (post-window) — never a
    # mix (keys 0..11 are uniform-length, so the slices align)
    pre = np.zeros((12, L), np.float32)
    post = expected_main[: 12 * L].reshape(12, L)
    for k, v in outcomes:
        if k == "ok":
            assert (np.array_equal(v, pre)
                    or np.array_equal(v, post)), \
                "hammer lookup returned a torn/mixed read"
    # post-restore serving is live and bit-exact
    assert np.array_equal(np.asarray(sess2.lookup(np.arange(12))), post)
    assert plane2.health.readiness()["ready"]
    log(f"restore OK: recovery_s={recovery_s:.3f} "
        f"(bound {recovery_max_s}), hammer outcomes {kinds}, "
        f"degraded sheds carried ServeDegradedError, post-restore "
        f"reads bit-exact")
    srv2.shutdown()

    # ---- 4. incremental bytes: 1%-dirty trickle -------------------------
    import adapm_tpu as _a
    from adapm_tpu.config import SystemOptions
    srv3 = _a.setup(8192, 16,
                    opts=SystemOptions(sync_max_per_sec=0,
                                       prefetch=False),
                    num_workers=2)
    w3 = srv3.make_worker(0)
    w3.set(np.arange(8192),
           rng.normal(size=(8192, 16)).astype(np.float32))
    ck3 = IncrementalCheckpointer(
        srv3, os.path.join(chain_dir, "trickle"))
    base = ck3.save()
    dirty = rng.choice(8192, size=82, replace=False)  # ~1%
    w3.push(dirty, np.ones((82, 16), np.float32))
    delta = ck3.save()
    ratio = delta["bytes"] / base["bytes"]
    log(f"incremental bytes: base {base['bytes']}B, 1%-dirty delta "
        f"{delta['bytes']}B ({delta['slots']} slots) -> ratio "
        f"{ratio:.4f} (bound {delta_ratio_max})")
    assert ratio <= delta_ratio_max, (
        f"1%-dirty delta costs {ratio:.3f} of a full checkpoint "
        f"(bound {delta_ratio_max}) — the dirty-slot filter is broken")
    srv3.shutdown()
    ref.shutdown()

    log("OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
