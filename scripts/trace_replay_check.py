"""Trace-replay guard (ISSUE 15; run by scripts/run_tests.sh).

Three acceptance properties of the workload-trace plane, end to end:

  1. **Determinism.** A seeded multi-plane storm (pull/push/set,
     intents, clocks, serve lookups, sync rounds, quiesce) is captured
     once; replaying the `.wtrace` twice with the same seed + knobs
     produces bit-identical reads (the sha256 digest over every
     pull/serve result), and replaying at 1x vs 10x logical speed
     produces the SAME digest — pacing is presentation, never data.

  2. **Ranked-artifact sanity.** A two-candidate knob sweep
     (`tier_hot_rows` at 25% vs 100% of the table) emits an artifact
     whose candidates both scored the objective and whose winner is
     ranked first.

  3. **Replay predicts live.** The same workload generator is run LIVE
     (no replay) under both candidates and the hot-hit-rate ordering
     is measured directly; the replay artifact's winner must match the
     live winner — the whole point of the offline policy lab is that
     its rankings transfer.

The storm is zipf-skewed (the DLRM embedding-bag shape the recorder
exists to capture faithfully) so the 25%-capacity candidate lands a
high-but-sub-1.0 hit rate and the orderings are non-degenerate.
"""
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("ADAPM_PLATFORM", "cpu")

import numpy as np  # noqa: E402

E = 2048          # keys
VL = 8            # value length
STEPS = 80        # storm steps
SKEW = 8.0        # zipf-ish skew (key = E * u^SKEW)
SEED = 123

def candidates():
    """Whole-table hot fractions via the shared per_shard_hot_rows
    helper (--sys.tier.hot_rows is PER SHARD per length class; an
    undivided fraction on a multi-shard mesh would make both
    candidates effectively all-hot — a near-tie proving nothing)."""
    from adapm_tpu.replay import per_shard_hot_rows
    return {
        "hot_25pct": {"tier": True,
                      "tier_hot_rows": per_shard_hot_rows(E, 0.25)},
        "hot_100pct": {"tier": True,
                       "tier_hot_rows": per_shard_hot_rows(E, 1.0)},
    }


def _sched(rng, n):
    return (E * rng.random(n) ** SKEW).astype(np.int64).clip(0, E - 1)


def drive_storm(srv, with_serve=True):
    """The seeded workload, shared verbatim between the capture run and
    the live-measurement runs (one generator, three uses)."""
    from adapm_tpu.serve import ServePlane
    w = srv.make_worker(0)
    rng = np.random.default_rng(SEED)
    slab = np.ones((E, VL), np.float32)
    w.wait(w.set(np.arange(E), slab))
    plane = ServePlane(srv) if with_serve else None
    sess = plane.session() if plane is not None else None
    for i in range(STEPS):
        ks = np.unique(_sched(rng, 64))
        w.pull_sync(ks)
        w.wait(w.push(ks, np.ones((len(ks), VL), np.float32)))
        if sess is not None and i % 4 == 0:
            sess.lookup(_sched(rng, 32))
        if i % 10 == 9:
            w.advance_clock()
            srv.wait_sync()
    srv.quiesce()
    if plane is not None:
        plane.close()
    return w


def capture(tmp) -> str:
    import adapm_tpu
    from adapm_tpu.config import SystemOptions
    path = os.path.join(tmp, "storm.wtrace")
    opts = SystemOptions(sync_max_per_sec=0, prefetch=False,
                         trace_workload=path,
                         trace_workload_keys=256)
    srv = adapm_tpu.setup(E, VL, opts=opts, num_workers=1)
    drive_storm(srv)
    srv.shutdown()
    return path


def live_hit_rate(overrides) -> float:
    """The live (no-replay) measurement of one candidate: same
    generator, same knobs, hot-hit rate from the same gauge."""
    import adapm_tpu
    from adapm_tpu.config import SystemOptions
    opts = SystemOptions(sync_max_per_sec=0, prefetch=False)
    for k, v in overrides.items():
        setattr(opts, k, v)
    srv = adapm_tpu.setup(E, VL, opts=opts, num_workers=1)
    drive_storm(srv)
    rate = float(srv.obs.find("tier.hot_hit_rate").value)
    srv.shutdown()
    return rate


def main() -> int:
    from adapm_tpu.replay import ReplayEngine, load_wtrace, \
        rank_candidates

    with tempfile.TemporaryDirectory() as tmp:
        print(f"[replay-check] capturing storm ({E} keys x {VL}, "
              f"{STEPS} steps, zipf skew {SKEW})")
        path = capture(tmp)
        tr = load_wtrace(path)
        kinds = tr.kinds()
        print(f"[replay-check] trace: {len(tr.events)} events {kinds}")
        for k in ("pull", "push", "serve", "sync", "quiesce"):
            assert kinds.get(k, 0) >= 1, f"storm recorded no {k} events"

        # 1) determinism: same seed+knobs twice, and across speeds
        r_a = ReplayEngine(tr, seed=5, speed=10.0).run()
        r_b = ReplayEngine(tr, seed=5, speed=10.0).run()
        if r_a["reads_digest"] != r_b["reads_digest"]:
            print("[replay-check] FAILED: same-speed replays disagree "
                  f"({r_a['reads_digest'][:12]} vs "
                  f"{r_b['reads_digest'][:12]})", file=sys.stderr)
            return 1
        r_1x = ReplayEngine(tr, seed=5, speed=1.0).run()
        if r_1x["reads_digest"] != r_a["reads_digest"]:
            print("[replay-check] FAILED: 1x vs 10x logical speed "
                  "changed the replayed reads — pacing leaked into "
                  "data", file=sys.stderr)
            return 1
        print(f"[replay-check] determinism OK: digest "
              f"{r_a['reads_digest'][:16]} stable across runs and "
              f"1x/10x speeds ({r_a['reads']} reads, "
              f"{r_a['events_replayed']} events)")

        # 2) ranked two-candidate sweep on the replay engine
        cands = candidates()
        art = rank_candidates(tr, cands,
                              objective="hot_hit_rate", seed=5,
                              speed=10.0,
                              out_path=os.path.join(tmp, "cmp.json"))
        scores = {n: art["candidates"][n]["score"]["hot_hit_rate"]
                  for n in cands}
        print(f"[replay-check] replay hot_hit_rate: {scores}, "
              f"winner {art['winner']}")
        for n, s in scores.items():
            if s is None:
                print(f"[replay-check] FAILED: candidate {n} scored "
                      f"no hot_hit_rate", file=sys.stderr)
                return 1
        if art["ranking"][0] != art["winner"]:
            print("[replay-check] FAILED: artifact winner is not "
                  "ranked first", file=sys.stderr)
            return 1

        # 3) the replay ordering must match the LIVE-measured ordering
        live = {n: live_hit_rate(o) for n, o in cands.items()}
        live_winner = max(sorted(live), key=lambda n: live[n])
        print(f"[replay-check] live hot_hit_rate: "
              f"{ {n: round(v, 4) for n, v in live.items()} }, "
              f"winner {live_winner}")
        if art["winner"] != live_winner:
            print(f"[replay-check] FAILED: replay winner "
                  f"{art['winner']} != live winner {live_winner} — "
                  f"the offline ranking does not transfer",
                  file=sys.stderr)
            return 1
        print("[replay-check] OK: replay ranking matches the "
              "live-measured ordering")
    return 0


if __name__ == "__main__":
    sys.exit(main())
