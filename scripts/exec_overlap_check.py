"""Unified-executor guard (ISSUE 6 satellite; run by
scripts/run_tests.sh).

Two assertions about adapm_tpu/exec that a regression would break
silently:

1. **Idle dispatches nothing.** An idle executor must start ZERO
   programs and dispatch ZERO device programs: its workers park on the
   executor condvar — no polling passes, no busy loop. Checked against
   `exec.programs_started` AND the stores' host-side gather/program
   counters over an idle second (same shape as serve_latency_check.py's
   idle guard).

2. **Overlap does not cost.** A tiered KGE-shaped workload with
   promotion churn (zipf pulls + pushes over a 25%-capacity hot pool,
   maintenance kicked throughout — promotion batch prep overlapping
   device scatters is exactly the GraphVite-style episodic overlap the
   executor exists for) must run at least as fast overlapped
   (multi-stream default) as serialized (--sys.exec.single_stream),
   within noise. Methodology: MEDIAN-pairwise-ratio per the
   mgmt_plane_check.py convention — (overlapped, serialized) timed back
   to back per repeat, guard on the median overlapped/serialized ratio.
   The real failure mode this catches is structural: an executor that
   serializes the training thread behind background streams (a lock
   held across dispatch, a gate held across device EXECUTION rather
   than enqueue) costs a MULTIPLE, pushing every pair well above 1. On
   this shared 2-core container individual pairs swing with scheduler
   noise (observed 0.57-1.70), so the guard is on the median and sized
   for that noise: median < 1.35 (override: ADAPM_EXEC_RATIO_MAX),
   recorded medians 1.00-1.17 — two cores leave little CPU for
   parallelism to win outright, so "within noise of serialized" is the
   honest pass bar here; the structural failure mode costs a multiple.
   The overlapped run must also record exec.overlap_fraction > 0 under
   churn (the acceptance criterion that >= 2 streams genuinely ran
   simultaneously at some point).
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("ADAPM_PLATFORM", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    from xla_compat import mesh_flags
    os.environ["XLA_FLAGS"] = " ".join([_flags, mesh_flags(2)]).strip()

import numpy as np  # noqa: E402

NK = 4096
VLEN = 8
B = 64               # keys per batch
BATCHES = 60         # per timed repeat
REPEATS = 5
SKEW = 3             # zipf-ish: key = NK * u^SKEW


def build(single_stream: bool):
    import jax

    import adapm_tpu
    from adapm_tpu.config import SystemOptions

    jax.config.update("jax_platforms", "cpu")
    S = len(jax.devices())
    srv = adapm_tpu.setup(NK, VLEN, opts=SystemOptions(
        sync_max_per_sec=0, prefetch=False,
        tier=True, tier_hot_rows=max(8, NK // 4 // S),
        exec_single_stream=single_stream))
    w = srv.make_worker(0)
    rng = np.random.default_rng(0)
    w.wait(w.set(np.arange(NK),
                 rng.normal(size=(NK, VLEN)).astype(np.float32)))
    srv.block()
    return srv, w


def schedule(rng, n):
    return [(NK * rng.random(B) ** SKEW).astype(np.int64).clip(0, NK - 1)
            for _ in range(n)]


def run_workload(srv, w, batches, vals) -> float:
    """One timed pass: zipf pull + push per batch (cold misses kick the
    maintenance worker; promotion churn overlaps the training thread's
    dispatches on the overlapped executor), then settle — the drain is
    INSIDE the timing so a serialized executor pays its queued backlog
    where the overlapped one already retired it concurrently."""
    t0 = time.perf_counter()
    for i, b in enumerate(batches):
        w.pull_sync(b)
        w.push(b, vals)
        if i % 8 == 0:
            srv.tier.engine.kick()
    srv.exec.drain("tier", timeout=60)
    srv.exec.drain("tier_commit", timeout=60)
    srv.block()
    return time.perf_counter() - t0


def main() -> int:
    ratio_max = float(os.environ.get("ADAPM_EXEC_RATIO_MAX", "1.35"))
    rng = np.random.default_rng(7)
    vals = np.full((B, VLEN), 1e-4, dtype=np.float32)

    srv_o, w_o = build(False)      # overlapped default
    srv_s, w_s = build(True)       # serialized fallback

    # warm both (compiles every gather/scatter bucket + tier paths)
    warm = schedule(rng, 10)
    run_workload(srv_o, w_o, warm, vals)
    run_workload(srv_s, w_s, warm, vals)

    pairs = []
    for _ in range(REPEATS):
        batches = schedule(rng, BATCHES)
        t_over = run_workload(srv_o, w_o, batches, vals)
        t_ser = run_workload(srv_s, w_s, batches, vals)
        pairs.append(t_over / t_ser)
    overlap_frac = srv_o.exec.overlap_fraction()

    # -- idle guard: a parked executor starts nothing -------------------
    time.sleep(0.1)   # let the last maintenance pass park
    p0 = srv_o.exec.stats()["programs_started"]
    g0 = sum(s.gathers for s in srv_o.stores)
    time.sleep(1.0)
    p1 = srv_o.exec.stats()["programs_started"]
    g1 = sum(s.gathers for s in srv_o.stores)
    idle_ok = (p1 == p0) and (g1 == g0)

    srv_o.shutdown()
    srv_s.shutdown()
    pairs.sort()
    median = pairs[len(pairs) // 2]
    print(f"[exec-check] {BATCHES} batches x {REPEATS} pairs tiered "
          f"churn workload: overlapped/serialized ratios min "
          f"{pairs[0]:.3f} / median {median:.3f} / max {pairs[-1]:.3f} "
          f"(guard: median < {ratio_max:.2f}) | "
          f"overlap_fraction {overlap_frac:.3f} | "
          f"idle: programs {p1 - p0:+d}, gathers {g1 - g0:+d}")
    rc = 0
    if median >= ratio_max:
        print("[exec-check] FAILED: the overlapped executor no longer "
              "keeps up with the serialized fallback — check that the "
              "dispatch gate brackets only the ENQUEUE (never device "
              "execution) and that no stream holds the server lock "
              "across dispatch", file=sys.stderr)
        rc = 1
    if overlap_frac <= 0.0:
        print("[exec-check] FAILED: exec.overlap_fraction stayed 0 "
              "under promotion churn — streams never ran "
              "simultaneously; double-buffering is broken",
              file=sys.stderr)
        rc = 1
    if not idle_ok:
        print("[exec-check] FAILED: an idle executor started programs "
              "or dispatched gathers — workers must park on the "
              "executor condvar, never poll", file=sys.stderr)
        rc = 1
    if rc == 0:
        print("[exec-check] OK")
    return rc


if __name__ == "__main__":
    sys.exit(main())
