"""Worker-thread scaling microbench (VERDICT r4 item 7).

The reference expects N worker THREADS per process to scale pull/push
throughput, protected by a 16384-entry per-key lock array
(handle.h:1069-1083). This bench measures BOTH locking disciplines:
`locked_routing` (route + stage + dispatch all under the one server
RLock — the pre-r5 design) and `optimistic` (the r5 default,
--sys.optimistic_routing: route + stage outside the lock against a
topology_version snapshot, only device dispatch serialized). Aggregate
pull and push ops/s at 1/2/4/8 threads hammering disjoint key slices
(the best case for per-key locks, the worst case for one coarse lock).

    python scripts/thread_bench.py            # prints one JSON line

Interpretation caveats, recorded with the numbers in docs/PERF.md:
  - on a 1-2 core host NOTHING scales (no parallelism to expose); run on
    a multi-core host to see the lock's cost, not the core count's
  - numpy routing and XLA dispatch release the GIL, so the RLock is the
    binding constraint once cores are available
"""
from __future__ import annotations

import json
import os
import sys
import time
from concurrent.futures import ThreadPoolExecutor

os.environ.setdefault("ADAPM_PLATFORM", "cpu")
sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    from xla_compat import mesh_flags
    os.environ["XLA_FLAGS"] = (flags + " " + mesh_flags(8)).strip()
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_cache")

import numpy as np  # noqa: E402

K = 100_000
L = 64
BATCH = 1024
OPS = 30  # batched ops per thread per timing


def main() -> None:
    import jax
    jax.config.update("jax_platforms", "cpu")
    import adapm_tpu
    from adapm_tpu.config import SystemOptions

    # declared worker budget covers the per-N thread teams (ids must be
    # < num_workers; finalize() retires each team after its run)
    srv = adapm_tpu.setup(K, L, num_workers=64,
                          opts=SystemOptions(sync_max_per_sec=0,
                                             cache_slots_per_shard=1))
    w0 = srv.make_worker(0)
    rng = np.random.default_rng(0)
    vals = rng.normal(size=(K, L)).astype(np.float32)
    slab = 50_000
    for lo in range(0, K, slab):
        w0.set(np.arange(lo, min(lo + slab, K)), vals[lo:lo + slab])
    srv.block()

    next_wid = [8]  # ids 0-7 reserved for the init worker's team

    def bench(n_threads: int) -> dict:
        base = next_wid[0]
        next_wid[0] += n_threads
        workers = [srv.make_worker(base + i) for i in range(n_threads)]
        # disjoint key slices per thread: per-key locks would make these
        # perfectly parallel; one server lock serializes them
        slices = np.array_split(np.arange(K, dtype=np.int64), n_threads)
        rngs = [np.random.default_rng(t) for t in range(n_threads)]
        batches = [[rngs[t].choice(sl, BATCH) for _ in range(4)]
                   for t, sl in enumerate(slices)]
        ones = np.ones((BATCH, L), np.float32)

        def puller(t):
            w = workers[t]
            for i in range(OPS):
                w.pull_sync(batches[t][i % 4])

        def pusher(t):
            w = workers[t]
            for i in range(OPS):
                w.wait(w.push(batches[t][i % 4], ones))

        out = {}
        with ThreadPoolExecutor(n_threads) as ex:
            for name, fn in (("pull", puller), ("push", pusher)):
                list(ex.map(fn, range(n_threads)))  # warm
                t0 = time.perf_counter()
                list(ex.map(fn, range(n_threads)))
                dt = time.perf_counter() - t0
                out[name] = round(n_threads * OPS * BATCH / dt)
        for w in workers:
            w.finalize()
        return out

    # both locking disciplines (r5: optimistic routing moves route+stage
    # out of the server lock; --sys.optimistic_routing 0 is the old
    # route-under-lock behavior). On a 1-core host expect parity; on a
    # multi-core host the optimistic mode is the one that can scale.
    out = {"metric": "worker_thread_scaling",
           "host_cores": os.cpu_count(),
           "batch": BATCH, "value_bytes": 4 * L}
    for mode, opt in (("locked_routing", False), ("optimistic", True)):
        srv.opts.optimistic_routing = opt
        results = {n: bench(n) for n in (1, 2, 4, 8)}
        out[mode] = {
            "keys_per_s": results,
            "pull_scaling_8v1": round(results[8]["pull"] /
                                      results[1]["pull"], 2),
            "push_scaling_8v1": round(results[8]["push"] /
                                      results[1]["push"], 2),
        }
    print(json.dumps(out))
    srv.shutdown()


if __name__ == "__main__":
    main()
