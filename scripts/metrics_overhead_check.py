"""Metrics-overhead guard (ISSUE 2 satellite; run by scripts/run_tests.sh).

Times the bench probe-phase shape — a pull/push loop through the full PM
dispatch path — with the hot-path instrumentation attached vs detached
and asserts the overhead stays under the budget.

Methodology: ONE server, the instrumentation toggled on its workers and
sync manager, (off, on) timings back to back, guard on the MEDIAN
pairwise ratio. Comparing two separately built servers swings >10% on
this shared 1-2-core container (different pool allocations / memory
layout), and individual pairs still swing ~0.5x-1.4x, so neither a
two-server ratio nor a min/max pair statistic can resolve the
documented <2% budget here. The median of interleaved pairs is robust
to that noise, and the failure mode this guard exists to catch — an
accidental lock, O(n) scan, or device sync on the pull/push path —
costs a MULTIPLE, not percents: it pushes every pair, hence the
median, far past the 1.15 default threshold
(ADAPM_METRICS_OVERHEAD_MAX). The 2% budget itself is established by
the micro-measurement in docs/OBSERVABILITY.md (~2 µs per op), not
re-measured per commit.

Also performs the duplicate-metric-name integrity check: constructing a
default Server registers every subsystem's metrics into one registry,
which raises on any name collision (obs/metrics.py).
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("ADAPM_PLATFORM", "cpu")

import numpy as np  # noqa: E402


def build():
    import adapm_tpu
    from adapm_tpu.config import SystemOptions
    srv = adapm_tpu.setup(
        4096, 32, opts=SystemOptions(sync_max_per_sec=0, prefetch=False))
    w = srv.make_worker(0)
    rng = np.random.default_rng(0)
    w.set(np.arange(4096), rng.normal(
        size=(4096, 32)).astype(np.float32))
    batches = [np.unique(rng.integers(0, 4096, 128)) for _ in range(8)]
    vals = [np.ones((len(b), 32), np.float32) for b in batches]
    return srv, w, batches, vals


def probe(w, batches, vals, steps: int) -> None:
    for i in range(steps):
        j = i % len(batches)
        w.pull_sync(batches[j])
        w.wait(w.push(batches[j], vals[j]))


def set_instrumentation(srv, w, saved, on: bool) -> None:
    """Attach/detach the hot-path metrics hooks (exactly what
    --sys.metrics 0 removes from the pull/push path)."""
    from adapm_tpu.obs.metrics import _NULL
    if on:
        (w._h_pull, w._h_push, w._h_set, srv.sync._h_round) = saved
    else:
        w._h_pull = w._h_push = w._h_set = None
        srv.sync._h_round = _NULL


def main() -> int:
    budget = float(os.environ.get("ADAPM_METRICS_OVERHEAD_MAX", "1.15"))
    steps, repeats = 100, 9
    srv, w, batches, vals = build()
    names = srv.obs.names()
    print(f"[overhead-check] registry catalog: {len(names)} metrics, "
          f"duplicate-name check passed (enforced at registration)")
    # ISSUE 7: request-flight tracing is compiled in but DEFAULT OFF —
    # the probe loop below therefore times the hot path with the flight
    # branch present (one `is None` check in Worker._instrumented), and
    # the same budget guard proves its default-off cost is nil. Pin the
    # default-off state structurally too: no tracer, zero flight.*
    # metric names.
    assert srv.flight is None, \
        "flight tracing must be DEFAULT OFF (--sys.trace.flight 0)"
    flight_names = [n for n in names if n.startswith("flight.")]
    assert not flight_names, \
        f"default-off flight tracing registered metrics: {flight_names}"
    print("[overhead-check] flight tracing default-off: no tracer, "
          "zero flight.* names; probe times the hot path with the "
          "flight branch compiled in")
    # ISSUE 10: the fault-injection plane is compiled in but DEFAULT
    # OFF — no FaultPlane object, zero fault.* registry names, and the
    # instrumented sites (executor dispatch, sync tick, serve drain,
    # tier commit, checkpoint I/O) each pay one `is None` check. The
    # unchanged median-ratio guard below times the pull/push hot path
    # with those branches present.
    assert srv.fault is None, \
        "fault injection must be DEFAULT OFF (--sys.fault.spec empty)"
    fault_names = [n for n in names if n.startswith("fault.")]
    assert not fault_names, \
        f"default-off fault plane registered metrics: {fault_names}"
    print("[overhead-check] fault injection default-off: no plane, "
          "zero fault.* names; injection points are zero-cost skips")
    # ISSUE 15: workload trace capture is compiled in but DEFAULT OFF —
    # no recorder object, zero wtrace.* registry names, and every
    # capture hook (worker pull/push/set, intent, clock, serve submit,
    # sync round, relocation, promotion) pays one `is None` check. The
    # unchanged median-ratio guard below times the pull/push hot path
    # with those branches present.
    assert srv.wtrace is None, \
        "workload capture must be DEFAULT OFF (--sys.trace.workload " \
        "unset)"
    wtrace_names = [n for n in names if n.startswith("wtrace.")]
    assert not wtrace_names, \
        f"default-off workload capture registered metrics: " \
        f"{wtrace_names}"
    print("[overhead-check] workload capture default-off: no recorder, "
          "zero wtrace.* names; capture hooks are zero-cost skips")
    # ISSUE 17: decision telemetry is compiled in but DEFAULT OFF — no
    # DecisionRecorder, zero decision.* registry names, and every
    # decision site (relocate-vs-replicate classify, landed moves, tier
    # promote/demote, dirty-sync ship/hold, SLO moves, prefetch
    # stage/skip, cost overrides) pays one `is None` check. The
    # unchanged median-ratio guard below times the pull/push hot path
    # with those branches present.
    assert srv.decisions is None, \
        "decision telemetry must be DEFAULT OFF (--sys.trace.decisions " \
        "unset)"
    decision_names = [n for n in names if n.startswith("decision.")]
    assert not decision_names, \
        f"default-off decision telemetry registered metrics: " \
        f"{decision_names}"
    print("[overhead-check] decision telemetry default-off: no "
          "recorder, zero decision.* names; decision sites are "
          "zero-cost skips")
    # ISSUE 18: the learned-policy plane is compiled in but DEFAULT
    # OFF — no PolicyPlane object, zero policy.* registry names, and
    # every hook site (relocate batches, background tier promotion,
    # dirty-mask sync filtering, SLO window moves, batcher close
    # accounting) pays one `is None` check. The unchanged median-ratio
    # guard below times the pull/push hot path with those branches
    # present.
    assert srv.policy is None, \
        "learned policies must be DEFAULT OFF (--sys.policy.file unset)"
    policy_names = [n for n in names if n.startswith("policy.")]
    assert not policy_names, \
        f"default-off policy plane registered metrics: {policy_names}"
    print("[overhead-check] learned-policy plane default-off: no "
          "PolicyPlane, zero policy.* names; hook sites are zero-cost "
          "skips")
    # ISSUE 19: the NetPort transport plane is compiled in but DEFAULT
    # OFF — a single-process server attaches NO net node/membership
    # plane (srv.net is None), registers zero net.* names, and the
    # snapshot `net` section stays empty. The loopback/tcp backends
    # exist only when a NetNode is passed at construction.
    assert srv.net is None, \
        "NetPort membership plane must be DEFAULT OFF (no net_node)"
    net_names = [n for n in names if n.startswith("net.")]
    assert not net_names, \
        f"default-off net plane registered metrics: {net_names}"
    print("[overhead-check] net transport plane default-off: no "
          "membership plane, zero net.* names; the dcn/legacy path is "
          "byte-identical")
    # ISSUE 20: the streaming plane is compiled in but DEFAULT OFF —
    # with no --sys.stream.* knobs set no StreamPlane object exists,
    # zero stream.* registry names, and the snapshot `stream` section
    # stays empty. The checkpoint aux writer and Server.shutdown each
    # pay one `is None` check; the unchanged median-ratio guard below
    # times the pull/push hot path with those branches present.
    assert srv.stream is None, \
        "streaming plane must be DEFAULT OFF (--sys.stream.batch 0, " \
        "--sys.stream.freshness_slo_ms 0)"
    stream_names = [n for n in names if n.startswith("stream.")]
    assert not stream_names, \
        f"default-off streaming plane registered metrics: {stream_names}"
    print("[overhead-check] streaming plane default-off: no "
          "StreamPlane, zero stream.* names; the ingest/freshness "
          "hooks are zero-cost skips")
    saved = (w._h_pull, w._h_push, w._h_set, srv.sync._h_round)
    probe(w, batches, vals, 30)  # warm the jit caches
    # per-pair (off, on) timings back to back; the guard is the MEDIAN
    # pairwise ratio (see module docstring for why min/max/two-server
    # statistics cannot work at this box's noise level)
    pairs = []
    for _ in range(repeats):
        t = {}
        for on in (False, True):
            set_instrumentation(srv, w, saved, on)
            t0 = time.perf_counter()
            probe(w, batches, vals, steps)
            t[on] = time.perf_counter() - t0
        pairs.append(t)
    set_instrumentation(srv, w, saved, True)
    srv.shutdown()
    ratios = sorted(p[True] / p[False] for p in pairs)
    ratio = ratios[len(ratios) // 2]
    print(f"[overhead-check] probe {steps} steps x {repeats} pairs: "
          f"pairwise on/off ratios min {ratios[0]:.3f} / median "
          f"{ratio:.3f} / max {ratios[-1]:.3f} "
          f"(guard: median < {budget:.2f}, documented budget < 1.02)")
    if ratio >= budget:
        print("[overhead-check] FAILED: metrics registry overhead over "
              "budget", file=sys.stderr)
        return 1
    print("[overhead-check] OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
