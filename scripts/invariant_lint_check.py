"""adapm-lint CI gate (ISSUE 11; run FIRST by scripts/run_tests.sh).

Runs the AST invariant analyzer (adapm_tpu/lint, docs/INVARIANTS.md)
over the whole package and fails on

  - any unsuppressed finding (APM001..APM007 — a violated concurrency
    discipline), or
  - any unused or malformed suppression (APM000 — a stale or
    unjustified escape hatch).

This is the cheapest guard in the harness: pure AST, no device stack,
milliseconds — which is why it runs before even the prefetch smoke
(the prefetch-smoke-first principle: a regression that CAN fail in
seconds MUST fail in seconds).

Escape hatch for incremental adoption (e.g. a branch that vendored a
pre-lint subsystem): ``ADAPM_LINT_BASELINE=<path>``. If the file
exists, findings already recorded in it are tolerated (and reported as
"baselined", so they stay visible); if it does not, the current
findings are written there and the run passes — commit the baseline,
then burn it down. NEW findings always fail regardless of baseline.

Exit status: 0 clean (or fully baselined), 1 otherwise.
"""
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main() -> int:
    from adapm_tpu.lint import Analyzer
    rep = Analyzer(ROOT).run()

    baseline_path = os.environ.get("ADAPM_LINT_BASELINE")
    baselined = set()
    if baseline_path:
        if os.path.exists(baseline_path):
            with open(baseline_path, "r", encoding="utf-8") as fh:
                data = json.load(fh)
            baselined = {(f["path"], f["rule"], f["message"])
                         for f in data.get("findings", ())}
        else:
            with open(baseline_path, "w", encoding="utf-8") as fh:
                fh.write(rep.to_json())
            print(f"[lint] baseline bootstrapped at {baseline_path} "
                  f"({len(rep.findings)} finding(s) recorded) — commit "
                  f"it, then burn it down")
            return 0

    fresh = [f for f in rep.findings
             if (f.path, f.rule, f.message) not in baselined]
    tolerated = len(rep.findings) - len(fresh)

    if fresh:
        for f in sorted(fresh):
            print(f.format())
        print(f"[lint] FAIL: {len(fresh)} finding(s) "
              f"({tolerated} baselined) over {rep.files_scanned} files "
              f"— fix the violation or add a justified "
              f"`# apm-lint: disable=` (docs/INVARIANTS.md)")
        return 1

    print(f"[lint] OK: {rep.files_scanned} files, "
          f"{len(rep.rules)} rules, "
          f"{len(rep.suppressions_used)} justified suppression(s) used"
          + (f", {tolerated} baselined finding(s) tolerated"
             if tolerated else ""))
    return 0


if __name__ == "__main__":
    sys.exit(main())
