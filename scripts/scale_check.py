import os, sys, time
os.environ["ADAPM_PLATFORM"] = "cpu"
sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))
from xla_compat import mesh_flags  # noqa: E402

os.environ["XLA_FLAGS"] = mesh_flags(8)
import jax; jax.config.update("jax_platforms", "cpu")
import numpy as np
import adapm_tpu
from adapm_tpu.config import SystemOptions

t0 = time.perf_counter()
srv = adapm_tpu.setup(5_000_000, 8, opts=SystemOptions(
    sync_max_per_sec=0, cache_slots_per_shard=4096))
t1 = time.perf_counter()
print(f"Server(5M keys) construction: {t1-t0:.2f}s")
assert t1 - t0 < 30.0, "too slow"  # generous: catches per-key loops only

w = srv.make_worker(0)
# a large intent batch through the vectorized register path
rng = np.random.default_rng(0)
keys = rng.choice(5_000_000, 100_000, replace=False)
t0 = time.perf_counter()
w.intent(keys, 0, 1000)
srv.wait_sync()
t1 = time.perf_counter()
print(f"100k-key intent drain + sync round: {t1-t0:.2f}s")
print("replicas:", srv.sync.stats.replicas_created,
      "relocations:", srv.sync.stats.relocations)

# steady-state step-shaped loop: 1k rounds of routed pushes at 5M keys
batch = rng.integers(0, 5_000_000, 4096)
vals = np.ones((4096, 8), np.float32)
w.push(batch, vals)  # warm compile
srv.block()
t0 = time.perf_counter()
for _ in range(50):
    w.push(batch, vals)
srv.block()
t1 = time.perf_counter()
print(f"push(4096 keys) steady state: {(t1-t0)/50*1e3:.2f} ms/op")

# full-model read (checkpoint/eval/export path): must be slice copies per
# class, never a per-key Python loop (VERDICT r2 weak #3)
t0 = time.perf_counter()
full = srv.read_main(np.arange(5_000_000))
t1 = time.perf_counter()
print(f"read_main(5M keys): {t1-t0:.2f}s ({full.nbytes/2**20:.0f} MiB)")
assert t1 - t0 < 60.0, "full-model read too slow (per-key loop?)"

srv.shutdown()
print("SCALE OK")
