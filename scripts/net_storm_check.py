"""NetPort loopback storm + dead-peer drill (ISSUE 19 acceptance; run
by scripts/run_tests.sh).

Three checks over the transport plane (adapm_tpu/net, docs/NETWORK.md):

1. BIT-IDENTITY UNDER WIRE FAULTS: a seeded two-node loopback storm —
   integer-valued pushes under full replication pressure — runs with
   the fault plane injecting frame drops (net.send / net.recv),
   duplicate deliveries (net.dup), delivery delays (net.delay), and
   pairwise partitions (net.partition) into every cross-node frame,
   with the lock-order sentinel armed. After EVERY round's quiesce
   (WaitSync -> Barrier -> WaitSync) each rank's full-table read must
   be bit-identical to an UNINJECTED single-process shadow server fed
   the same logical writes: a dropped frame must be retransmitted, a
   duplicated frame must NOT double-apply (receiver-side at-most-once
   dedup), and a delayed frame must not reorder visible state. The
   drill asserts the faults actually FIRED (an inert spec would pass
   vacuously) and that zero frames failed integrity checks.

2. DEAD-PEER KILL MID-STORM: rank 1 is killed between rounds. The
   survivor's membership plane must detect the death by heartbeat
   staleness, promote its replicas of dead-owned keys to mains
   (GlobalPM.failover_dead_peer), and record a recovery wall time
   `net.failover_s` <= ADAPM_NET_FAILOVER_MAX_S (default 30 s). The
   survivor then keeps storming ALONE on the covered keys and its
   reads must still match the shadow bitwise — a promoted replica
   carries the pre-kill pushes (pending delta merged, not dropped).

3. LOST-KEY ACCOUNTING: dead-owned keys WITHOUT a live replica are
   counted in net.lost_keys and promoted+lost must cover every
   dead-homed key — nothing silently disappears.
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("ADAPM_PLATFORM", "cpu")

import numpy as np  # noqa: E402

K = 96
L = 4
ROUNDS = int(os.environ.get("ADAPM_NET_STORM_ROUNDS", "6"))
SEED = int(os.environ.get("ADAPM_NET_STORM_SEED", "1234"))
FAULT_SPEC = ("net.send=0.08,net.recv=0.08,net.dup=0.10,"
              "net.delay=0.02,net.partition=0.02")


def _opts(**kw):
    from adapm_tpu.config import SystemOptions
    return SystemOptions(sync_max_per_sec=0, prefetch=False, **kw)


def main() -> int:
    from adapm_tpu.base import CLOCK_MAX
    from adapm_tpu.core.kv import Server
    from adapm_tpu.net import LoopbackCluster

    failover_max_s = float(os.environ.get(
        "ADAPM_NET_FAILOVER_MAX_S", "30"))

    # integer-valued float32 pushes: addition on the integer grid is
    # exact and order-independent, so ANY legal interleaving must land
    # bitwise on the shadow — a drop, dup, or reorder shows up as a
    # wrong integer, never as fp noise
    rng = np.random.default_rng(SEED)
    logs = [[(np.sort(rng.choice(K, size=12, replace=False))
              .astype(np.int64),
              rng.integers(-8, 9, size=(12, L)).astype(np.float32))
             for _ in range(ROUNDS)] for _ in range(2)]
    expect = np.zeros((K, L), np.float64)
    for rank_log in logs:
        for keys, vals in rank_log:
            expect[keys] += vals
    partial = np.zeros((K, L), np.float64)  # running shadow per round

    # the UNINJECTED single-process shadow: same writes, no net plane,
    # no faults — the bit-identity reference required by the drill
    shadow = Server(K, L, opts=_opts(), num_workers=1)
    sw = shadow.make_worker(0)
    sw.wait(sw.set(np.arange(K, dtype=np.int64),
                   np.zeros((K, L), np.float32)))

    cl = LoopbackCluster(
        2, num_keys=K, value_lengths=L,
        opts_factory=lambda r: _opts(fault_spec=FAULT_SPEC,
                                     lint_lockorder=True),
        heartbeat_ms=40.0)
    try:
        allk = np.arange(K, dtype=np.int64)

        def prep(rank, srv):
            w = srv.make_worker(0)
            if rank == 0:
                w.wait(w.set(allk, np.zeros((K, L), np.float32)))
            srv.barrier()
            # competing intents install replicas at rank 0 of rank-1-
            # homed keys (an uncontended intent would relocate instead)
            theirs = allk[srv.glob.home_proc(allk) == 1]
            if rank == 1:
                w.intent(theirs, 0, CLOCK_MAX)
                srv.wait_sync()
            srv.barrier()
            if rank == 0:
                w.intent(theirs, 0, CLOCK_MAX)
                srv.wait_sync()
            srv.barrier()

        cl.run(prep)

        def storm_round(r):
            def body(rank, srv):
                w = srv.make_worker(0)
                keys, vals = logs[rank][r]
                w.wait(w.push(keys, vals))
                srv.wait_sync()
                srv.barrier()
                srv.wait_sync()
                srv.barrier()
                return w.pull_sync(allk)

            return cl.run(body)

        t0 = time.monotonic()
        for r in range(ROUNDS):
            for keys, vals in (logs[0][r], logs[1][r]):
                partial[keys] += vals
                sw.wait(sw.push(keys, vals))
            outs = storm_round(r)
            ref = sw.pull_sync(allk)
            want = partial.astype(np.float32)
            assert np.array_equal(ref, want), \
                f"round {r}: shadow server diverged from numpy log"
            for rank, got in enumerate(outs):
                assert np.array_equal(got, ref), (
                    f"round {r} rank {rank}: read differs from the "
                    f"uninjected shadow (max abs diff "
                    f"{np.abs(got - ref).max()})")
        storm_s = time.monotonic() - t0

        s0 = cl.servers[0].net.stats()
        fired = sum(cl.servers[i].fault.counts(p)[1]
                    for i in range(2)
                    for p in ("net.send", "net.recv", "net.dup",
                              "net.delay", "net.partition"))
        assert fired > 0, \
            "no wire faults fired — the storm proved nothing"
        assert s0["decode_errors"] == 0, \
            f"frame integrity failures: {s0['decode_errors']}"
        print(f"[net-storm] {ROUNDS} rounds x 2 ranks bit-identical "
              f"to uninjected shadow in {storm_s:.1f}s; wire faults "
              f"fired={fired}, retransmits={s0['retransmits']}, "
              f"dups suppressed={s0['dup_suppressed']}")

        # ---- dead-peer kill mid-storm --------------------------------
        srv0 = cl.servers[0]
        theirs = allk[srv0.glob.home_proc(allk) == 1]
        covered = theirs[
            (srv0.ab.cache_slot[:, theirs] >= 0).any(axis=0)
            & (srv0.ab.owner[theirs] < 0)]
        assert len(covered) > 0, "prep installed no replicas"
        cl.kill(1)
        deadline = time.monotonic() + failover_max_s
        while time.monotonic() < deadline and \
                srv0.net.stats()["failovers"] == 0:
            time.sleep(0.02)
        s = srv0.net.stats()
        assert s["failovers"] == 1, \
            f"death not detected within {failover_max_s}s"
        assert 0.0 < s["failover_s"] <= failover_max_s, \
            f"failover_s={s['failover_s']:.3f}s out of bound"
        assert s["promoted_keys"] >= len(covered), \
            (f"promoted {s['promoted_keys']} < {len(covered)} "
             f"replica-covered keys")
        assert s["promoted_keys"] + s["lost_keys"] >= len(theirs), \
            "promoted+lost does not cover the dead rank's keys"

        # survivor keeps storming alone on the covered keys; reads must
        # still match the shadow (promoted replicas carry pre-kill
        # pushes — pending deltas merged by _adopt, not dropped)
        srng = np.random.default_rng(SEED + 99)
        for _ in range(2):
            idx = np.sort(srng.choice(len(covered),
                                      size=min(8, len(covered)),
                                      replace=False))
            keys = covered[idx]
            vals = srng.integers(-8, 9, size=(len(keys), L)).astype(
                np.float32)
            partial[keys] += vals
            sw.wait(sw.push(keys, vals))

            def body(rank, srv):
                w = srv.make_worker(0)
                w.wait(w.push(keys, vals))
                srv.wait_sync()
                srv.barrier()
                return w.pull_sync(keys)

            got = cl.run(body, ranks=[0])[0]
            ref = sw.pull_sync(keys)
            assert np.array_equal(got, ref), \
                "survivor read diverged from shadow after failover"
        print(f"[net-storm] kill mid-storm: failover in "
              f"{s['failover_s'] * 1e3:.0f}ms "
              f"(bound {failover_max_s:.0f}s), promoted="
              f"{s['promoted_keys']} lost={s['lost_keys']} of "
              f"{len(theirs)} dead-homed keys; survivor reads still "
              f"bit-identical")
        cl.shutdown(ranks=[0])
    finally:
        shadow.shutdown()
        from adapm_tpu.lint import lockorder
        lockorder.disable_sentinel()
    print("[net-storm] OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
