#!/usr/bin/env bash
# App smoke runs on toy data (reference tests/run_apps.sh: MF dsgd +
# columnwise, KGE, word2vec). Uses the CPU mesh unless run on TPU.
set -euo pipefail
cd "$(dirname "$0")/.."

FAST="--sys.sync.max_per_sec 0"

echo "=== simple ==="
python -m adapm_tpu.apps.simple --iterations 5 $FAST

echo "=== matrix_factorization (dsgd) ==="
python -m adapm_tpu.apps.matrix_factorization --rows 48 --cols 32 \
  --nnz 600 --rank 4 --epochs 2 --batch_size 16 --lr 0.1 \
  --algorithm dsgd $FAST

echo "=== matrix_factorization (columnwise) ==="
python -m adapm_tpu.apps.matrix_factorization --rows 48 --cols 32 \
  --nnz 600 --rank 4 --epochs 2 --batch_size 16 --lr 0.1 \
  --algorithm columnwise $FAST

echo "=== word2vec ==="
python -m adapm_tpu.apps.word2vec --synthetic_vocab 60 \
  --synthetic_sentences 80 --dim 8 --window 3 --negative 3 \
  --epochs 2 --batch_size 128 --readahead 20 $FAST

echo "=== knowledge_graph_embeddings (complex) ==="
python -m adapm_tpu.apps.knowledge_graph_embeddings --dim 8 \
  --neg_ratio 2 --synthetic_entities 60 --synthetic_relations 4 \
  --synthetic_triples 400 --epochs 2 --batch_size 32 --eval_every 2 \
  --eval_triples 40 $FAST

echo "=== knowledge_graph_embeddings, 2 launched processes ==="
# the reference smoke-runs every app under `dmlc_local.py -s 2`
# (tests/run_apps.sh); same shape here via the launcher
JAX_PLATFORMS=cpu ADAPM_PLATFORM=cpu \
XLA_FLAGS="--xla_force_host_platform_device_count=2" \
python -m adapm_tpu.launcher -n 2 --no-keepalive -- \
  python -m adapm_tpu.apps.knowledge_graph_embeddings --dim 8 \
  --neg_ratio 2 --synthetic_entities 60 --synthetic_relations 4 \
  --synthetic_triples 400 --epochs 2 --batch_size 32 --eval_every 2 \
  --eval_triples 40 $FAST

echo "=== bindings apps (CTR + GCN, adapm-pytorch-apps workload shapes) ==="
PYTHONPATH=. python examples/ctr_example.py
PYTHONPATH=. python examples/gcn_example.py

echo "ALL APPS PASSED"
