"""Test harness: multi-device without a cluster.

The reference tests launch N server processes + a scheduler on localhost via
tracker/dmlc_local.py (SURVEY.md §4). Here "multi-node" = an 8-device virtual
CPU mesh (XLA host-platform device count), which exercises the same sharded
programs the TPU path compiles. Must run before jax is imported anywhere.
"""
import os

os.environ["ADAPM_PLATFORM"] = "cpu"  # force CPU even if a TPU plugin is up
# Keep the TPU-tunnel backend from becoming the default: it adds a large
# per-dispatch round trip even when every pool array lives on CPU devices.
# The tunnel's sitecustomize imports jax at interpreter start with
# JAX_PLATFORMS baked in, so setting the env var here is too late — update
# the live config instead (backends initialize lazily, so this wins as long
# as it runs before the first jax.devices()/dispatch).
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    import sys as _sys

    _sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from xla_compat import mesh_flags

    # 8-virtual-device mesh + (when the installed jaxlib knows them) the
    # XLA CPU collective watchdog timeouts. The watchdog flags are
    # probed first: a jaxlib that does not know them ABORTS the process
    # on client init (xla_compat.py) — this round's image does exactly
    # that, which is why the r6 seed suite scored 0.
    os.environ["XLA_FLAGS"] = " ".join([flags, mesh_flags(8)]).strip()
# persistent compilation cache: amortize XLA compiles across pytest sessions
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_cache")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.1")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(0)


@pytest.fixture(autouse=True)
def _drop_lockorder_sentinel():
    """The lock-order sentinel (lint/lockorder.py) is process-global:
    any test that builds a --sys.lint.lockorder server installs it.
    Tear it down after EVERY test so a sentinel enabled (or a storm
    that failed before its own disable call) never leaks acquisition
    edges into unrelated tests."""
    yield
    from adapm_tpu.lint import lockorder
    lockorder.disable_sentinel()


# ---------------------------------------------------------------------------
# Isolate-and-retry for this image's known intermittent XLA-CPU abort
# (CHANGES.md r6 note): test_checkpoint.py::test_roundtrip_exact
# segfaults/aborts ~1/2 of isolated module runs ON THE UNMODIFIED SEED
# (an environment bug needing broader session state, not a code bug; the
# r6 restore-launder reduced but did not eliminate it). An in-process
# abort would take the WHOLE pytest session down, flickering the tier-1
# signal — so the affected test runs in a subprocess, and a CRASH
# (signal exit) retries exactly once with a loud log line. A normal
# assertion failure is reported immediately, never retried.
# ---------------------------------------------------------------------------

_ISOLATE_RETRY_NODEIDS = {
    "tests/test_checkpoint.py::test_roundtrip_exact",
}

_CRASH_RCS = {132, 133, 134, 135, 136, 137, 138, 139}  # 128 + SIG*


def _run_isolated(nodeid: str) -> None:
    import subprocess
    import sys as _s

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, ADAPM_ISOLATED="1")
    cmd = [_s.executable, "-m", "pytest", "-q", "-p", "no:cacheprovider",
           nodeid]
    for attempt in (1, 2):
        p = subprocess.run(cmd, env=env, cwd=root, capture_output=True,
                           text=True, timeout=600)
        if p.returncode == 0:
            return
        crashed = p.returncode < 0 or p.returncode in _CRASH_RCS
        if crashed and attempt == 1:
            _s.stderr.write(
                f"\n[conftest] ISOLATED TEST CRASHED (rc={p.returncode}) "
                f"— known image-level XLA-CPU abort (CHANGES.md r6); "
                f"retrying once: {nodeid}\n")
            _s.stderr.flush()
            continue
        tail = "\n".join((p.stdout + p.stderr).splitlines()[-30:])
        kind = "crashed twice (rc=%d)" % p.returncode if crashed \
            else "failed (rc=%d)" % p.returncode
        pytest.fail(f"isolated run of {nodeid} {kind}:\n{tail}",
                    pytrace=False)


def pytest_collection_modifyitems(config, items):
    if os.environ.get("ADAPM_ISOLATED"):
        return  # inside the isolated subprocess: run normally
    for item in items:
        if item.nodeid in _ISOLATE_RETRY_NODEIDS:
            item.runtest = (lambda nid=item.nodeid:
                            _run_isolated(nid))
