"""Pallas kernel correctness (interpret mode on the CPU mesh; the same
kernels compile for TPU — measured results in docs/PERF.md)."""
import numpy as np
import pytest

import jax.numpy as jnp


def test_gather_rows_matches_xla():
    from adapm_tpu.ops.pallas_kernels import gather_rows
    rng = np.random.default_rng(0)
    pool = jnp.asarray(rng.normal(size=(128, 256)).astype(np.float32))
    block_rows = 8
    idx = jnp.asarray(rng.integers(0, 128 // block_rows, 10)
                      .astype(np.int32))
    got = gather_rows(pool, idx, block_rows=block_rows, interpret=True)
    ref = np.asarray(pool).reshape(-1, block_rows, 256)[
        np.asarray(idx)].reshape(-1, 256)
    assert np.allclose(np.asarray(got), ref)


def test_adagrad_apply_matches_numpy():
    from adapm_tpu.ops.pallas_kernels import adagrad_apply
    rng = np.random.default_rng(1)
    n, L = 512, 128
    g = rng.normal(size=(n, L)).astype(np.float32)
    emb = rng.normal(size=(n, L)).astype(np.float32)
    acc = np.abs(rng.normal(size=(n, L))).astype(np.float32)
    lr, eps = 0.1, 1e-10
    new_emb, new_acc = adagrad_apply(jnp.asarray(g), jnp.asarray(emb),
                                     jnp.asarray(acc), lr, eps,
                                     interpret=True)
    ref_acc = acc + g * g
    ref_emb = emb - lr * g / np.sqrt(ref_acc + eps)
    assert np.allclose(np.asarray(new_acc), ref_acc, rtol=1e-5)
    assert np.allclose(np.asarray(new_emb), ref_emb, rtol=1e-4, atol=1e-6)
