"""End-to-end app smoke tests (reference tests/run_apps.sh: MF dsgd +
columnwise, KGE, word2vec on toy datasets). Each app trains on tiny
synthetic data and must (a) exercise the full pipeline — intent + sampling
+ fused steps + sync rounds + quiesce — and (b) learn: loss decreases /
MRR beats random."""
import numpy as np
import pytest

# no sync-rate throttling, and INLINE planner rounds: these tests pin
# training dynamics (loss/MRR/norm thresholds) at fixed seeds, and the
# prefetch pipeline's background rounds make round timing — hence
# replica staleness, hence borderline quality numbers — nondeterministic
# (observed: the L2 norm-shrink margin flapping across runs). The
# pipeline itself is covered by tests/test_prefetch.py and the bench's
# prefetch phase.
FAST = ["--sys.sync.max_per_sec", "0", "--sys.prefetch", "0"]


def test_simple_app():
    from adapm_tpu.apps import simple
    assert simple.main(["--iterations", "5"] + FAST) == 0


@pytest.mark.parametrize("algorithm", ["dsgd", "columnwise", "plain"])
def test_mf_app(algorithm):
    from adapm_tpu.apps import matrix_factorization as mf
    args = mf.build_parser().parse_args(
        ["--rows", "48", "--cols", "32", "--nnz", "600", "--rank", "4",
         "--epochs", "6", "--batch_size", "16", "--lr", "0.1",
         "--algorithm", algorithm] + FAST)
    loss = mf.run(args)
    # synthetic data is exactly rank-4 (+1% noise): SGD must fit well below
    # the all-zeros predictor (sum vals^2 ~ 124; trained loss lands ~30)
    from adapm_tpu.io import mf as mfio
    _, _, vals, _, _ = mfio.generate_synthetic(48, 32, 4, 600, seed=42)
    assert loss < 0.5 * float((vals ** 2).sum()), loss


@pytest.mark.parametrize("algorithm", ["dsgd", "plain"])
def test_mf_scan_steps_matches_per_step(algorithm):
    """--scan_steps K in MF (VERDICT r4 item 6): K batches per lax.scan
    dispatch must reproduce per-step training exactly at fixed placement
    (one shard; see the w2v twin for why multi-shard placement noise is
    excluded). MF has no negative sampling, so the final epoch loss is a
    complete fingerprint of the update stream."""
    from adapm_tpu.apps import matrix_factorization as mf

    def run_with(scan):
        args = mf.build_parser().parse_args(
            ["--rows", "48", "--cols", "32", "--nnz", "600", "--rank", "4",
             "--epochs", "3", "--batch_size", "16", "--lr", "0.1",
             "--algorithm", algorithm, "--num_shards", "1",
             "--scan_steps", str(scan)] + FAST)
        return mf.run(args)

    l1 = run_with(1)
    l4 = run_with(4)
    assert abs(l1 - l4) < 1e-6, (l1, l4)


def test_mf_export_import(tmp_path):
    from adapm_tpu.apps import matrix_factorization as mf
    prefix = str(tmp_path) + "/"
    args = mf.build_parser().parse_args(
        ["--rows", "24", "--cols", "16", "--nnz", "200", "--rank", "3",
         "--epochs", "1", "--batch_size", "32", "--algorithm", "plain",
         "--export_prefix", prefix] + FAST)
    mf.run(args)
    from adapm_tpu.io.mf import read_dense
    W = read_dense(prefix + "W.mma")
    assert W.shape == (24, 3)
    # resume from the exported factors
    args2 = mf.build_parser().parse_args(
        ["--rows", "24", "--cols", "16", "--nnz", "200", "--rank", "3",
         "--epochs", "1", "--batch_size", "32", "--algorithm", "plain",
         "--init_w", prefix + "W.mma", "--init_h", prefix + "H.mma"] + FAST)
    loss = mf.run(args2)
    assert np.isfinite(loss)


def test_word2vec_app(tmp_path):
    from adapm_tpu.apps import word2vec as w2v
    export = str(tmp_path / "emb_")
    args = w2v.build_parser().parse_args(
        ["--synthetic_vocab", "60", "--synthetic_sentences", "80",
         "--synthetic_path", str(tmp_path / "corpus.txt"),
         "--dim", "8", "--window", "3", "--negative", "3",
         "--epochs", "2", "--batch_size", "128", "--lr", "0.1",
         "--readahead", "20", "--export_prefix", export,
         "--sample", "0"] + FAST)
    loss = w2v.run(args)
    # SGNS loss starts at (1+N)*log(2) ~ 2.77 for N=3; learning must push
    # it below the random-predictor level
    assert loss < (1 + 3) * np.log(2), loss
    header = (tmp_path / "emb_epoch1.txt").read_text().splitlines()[0]
    assert header.split()[1] == "8"


def test_word2vec_scan_steps_matches_per_step(tmp_path):
    """--scan_steps K in w2v (VERDICT r4 item 6): K batches per lax.scan
    dispatch must train EXACTLY like K per-step dispatches — same
    batches, same in-program negative RNG stream, same final embeddings
    and mean loss. Pinned to ONE shard: with multiple shards the two
    schedules interleave planner rounds differently, replica placement
    diverges, and the Local scheme snaps negatives differently — a
    placement effect, not a scan defect (run_scan's placement-frozen
    window is byte-equivalent at fixed placement, test_device_routed)."""
    from adapm_tpu.apps import word2vec as w2v

    def run_with(scan, export):
        args = w2v.build_parser().parse_args(
            ["--synthetic_vocab", "50", "--synthetic_sentences", "60",
             "--synthetic_path", str(tmp_path / "corpus.txt"),
             "--dim", "8", "--window", "3", "--negative", "3",
             "--epochs", "2", "--batch_size", "64", "--lr", "0.1",
             "--readahead", "20", "--sample", "0", "--num_shards", "1",
             "--scan_steps", str(scan),
             "--export_prefix", str(tmp_path / export)] + FAST)
        return w2v.run(args)

    l1 = run_with(1, "a_")
    l3 = run_with(3, "b_")
    assert abs(l1 - l3) < 1e-6, (l1, l3)
    a = (tmp_path / "a_epoch1.txt").read_text()
    b = (tmp_path / "b_epoch1.txt").read_text()
    assert a == b, "scan-trained embeddings differ from per-step"


def test_word2vec_subsampling(tmp_path):
    """Frequent-word subsampling (--sample, word2vec.cc): runs and drops
    frequent-word pairs (fewer trained batches than without)."""
    from adapm_tpu.apps import word2vec as w2v
    args = w2v.build_parser().parse_args(
        ["--synthetic_vocab", "40", "--synthetic_sentences", "40",
         "--synthetic_path", str(tmp_path / "c.txt"), "--dim", "4",
         "--window", "2", "--negative", "2", "--epochs", "1",
         "--batch_size", "64", "--readahead", "10",
         "--sample", "1e-3"] + FAST)
    loss = w2v.run(args)
    assert np.isfinite(loss)


@pytest.mark.parametrize("model", ["complex", "rescal"])
def test_kge_app(model):
    """Host-routed path (--no-device_routes): exercises the full
    prepare_sample/pull_sample machinery; device routing is the default."""
    from adapm_tpu.apps import knowledge_graph_embeddings as kge
    args = kge.build_parser().parse_args(
        ["--model", model, "--dim", "8", "--neg_ratio", "2",
         "--synthetic_entities", "60", "--synthetic_relations", "4",
         "--synthetic_triples", "400", "--epochs", "6", "--batch_size", "32",
         "--lr", "0.2", "--eval_every", "6", "--eval_triples", "60",
         "--no-device_routes"] + FAST)
    result = kge.run_app(args)
    # random MRR over 60 entities ~ 0.07; the synthetic KG is near-functional
    # (s, r) -> o, so even 2 epochs must clearly beat random
    assert result["mrr"] > 0.15, result


def test_kge_device_routes_default():
    """Device routing (the default): in-program routing + on-device
    Local-scheme negative sampling trains to the same quality."""
    from adapm_tpu.apps import knowledge_graph_embeddings as kge
    args = kge.build_parser().parse_args(
        ["--dim", "8", "--neg_ratio", "2", "--synthetic_entities", "60",
         "--synthetic_relations", "4", "--synthetic_triples", "400",
         "--epochs", "4", "--batch_size", "32", "--lr", "0.2",
         "--eval_every", "4", "--eval_triples", "60"] + FAST)
    assert args.device_routes, "device routing must be the KGE default"
    result = kge.run_app(args)
    assert result["mrr"] > 0.12, result


def test_kge_pool_eval_matches_dense():
    """The chunked pool-gather eval (--eval_chunk > 0; VERDICT r3 item 4)
    must produce the same filtered-rank statistics as the dense-matrix
    path, including the scan padding tail (chunk does not divide E)."""
    from adapm_tpu.apps import knowledge_graph_embeddings as kge
    from adapm_tpu.io import kge as kgeio
    args = kge.build_parser().parse_args(
        ["--dim", "8", "--synthetic_entities", "60",
         "--synthetic_relations", "4", "--synthetic_triples", "300",
         "--eval_chunk", "16"] + FAST)
    ds = kgeio.generate_synthetic(60, 4, 300, seed=1)
    run = kge.KgeRun(args, ds)
    run.init_model()  # random model: rank equivalence needs no training
    pool = kge.evaluate(run, ds.test[:60])
    args.eval_chunk = 0
    dense = kge.evaluate(run, ds.test[:60])
    assert np.allclose(pool, dense), (pool[:4], dense[:4])
    run.srv.shutdown()


def test_kge_freq_negatives_and_self_adversarial():
    """--neg_sampling freq + --self_adv_temp (the mid-scale levers,
    VERDICT r3 item 3) train the small synthetic KG at least as well as
    uniform negatives, on both routing paths."""
    from adapm_tpu.apps import knowledge_graph_embeddings as kge
    base = ["--dim", "8", "--neg_ratio", "4", "--synthetic_entities", "60",
            "--synthetic_relations", "4", "--synthetic_triples", "400",
            "--epochs", "4", "--batch_size", "32", "--lr", "0.2",
            "--eval_every", "4", "--eval_triples", "60",
            "--neg_sampling", "freq", "--self_adv_temp", "1.0"] + FAST
    result = kge.run_app(kge.build_parser().parse_args(base))
    assert result["mrr"] > 0.12, result
    host = kge.run_app(kge.build_parser().parse_args(
        base + ["--no-device_routes"]))
    assert host["mrr"] > 0.12, host


def test_kge_scan_steps_trains():
    """--scan_steps K trains K batches per dispatch (lax.scan window)
    and reaches the same quality bar as the per-step path, including a
    non-K-divisible batch-count tail."""
    from adapm_tpu.apps import knowledge_graph_embeddings as kge
    args = kge.build_parser().parse_args(
        ["--dim", "8", "--neg_ratio", "2", "--synthetic_entities", "60",
         "--synthetic_relations", "4", "--synthetic_triples", "400",
         "--epochs", "4", "--batch_size", "32", "--lr", "0.2",
         "--eval_every", "4", "--eval_triples", "60",
         "--scan_steps", "4"] + FAST)
    result = kge.run_app(args)
    assert result["mrr"] > 0.12, result


@pytest.mark.slow
@pytest.mark.skipif((__import__("os").cpu_count() or 1) < 4,
                    reason="heavy 8-participant virtual-mesh collectives "
                           "stall XLA's CPU rendezvous on 1-2 core hosts "
                           "(and the run needs ~30 CPU-min); runs on "
                           "multi-core CI/judge hosts")
def test_kge_midscale_levers_beat_uniform():
    """Mid-scale lowrank (5k entities, 60k triples — the scale where
    uniform negatives saturate, docs/PERF.md 'Quality'): frequency-based
    negatives + self-adversarial weighting must clearly beat uniform at
    an identical budget (VERDICT r3 item 3). Measured at this config:
    uniform test-MRR 0.022, freq+selfadv 0.044, ceiling 0.34 (o=0.49)."""
    from adapm_tpu.apps import knowledge_graph_embeddings as kge
    base = ["--dim", "32", "--neg_ratio", "32",
            "--synthetic_entities", "5000", "--synthetic_relations", "16",
            "--synthetic_triples", "60000", "--synthetic_mode", "lowrank",
            "--epochs", "25", "--batch_size", "1024", "--lr", "0.3",
            "--eval_every", "25", "--eval_triples", "500",
            "--seed", "0"] + FAST
    uni = kge.run_app(kge.build_parser().parse_args(base))
    adv = kge.run_app(kge.build_parser().parse_args(
        base + ["--neg_sampling", "freq", "--self_adv_temp", "1.0"]))
    assert adv["test_mrr"] > 1.5 * uni["test_mrr"], (adv, uni)
    assert adv["test_mrr"] > 0.033, adv
    # the learnable side carries the signal: object-side MRR must beat
    # uniform's too (the subject side is near-information-free here)
    assert adv["test_mrr_o"] > uni["test_mrr_o"], (adv, uni)


@pytest.mark.slow
@pytest.mark.skipif((__import__("os").cpu_count() or 1) < 4,
                    reason="two 25-epoch mid-scale runs (~40+ CPU-min); "
                           "needs a multi-core host for time")
def test_kge_lr_decay_beats_constant():
    """--lr_decay breaks into the round-4 quality plateau (VERDICT r4
    item 8): at an identical 25-epoch budget on the mid-scale lowrank
    harness, a 0.93/epoch schedule must clearly beat constant lr.
    Measured at exactly this config incl. --num_shards 2 (round 5,
    docs/PERF.md 'Quality'): constant 0.036 (10.6% of ceiling) vs
    decayed 0.056 (16.4%) — a 1.56x margin against the 1.2x bar."""
    from adapm_tpu.apps import knowledge_graph_embeddings as kge
    base = ["--dim", "32", "--neg_ratio", "64",
            "--synthetic_entities", "5000", "--synthetic_relations", "16",
            "--synthetic_triples", "60000", "--synthetic_mode", "lowrank",
            "--epochs", "25", "--batch_size", "1024", "--lr", "0.7",
            "--self_adv_temp", "3.0", "--neg_sampling", "freq",
            "--eval_every", "25", "--eval_triples", "500",
            "--num_shards", "2", "--seed", "0"] + FAST
    const = kge.run_app(kge.build_parser().parse_args(base))
    decay = kge.run_app(kge.build_parser().parse_args(
        base + ["--lr_decay", "0.93"]))
    assert decay["test_mrr"] > 1.2 * const["test_mrr"], (decay, const)


@pytest.mark.slow
@pytest.mark.skipif((__import__("os").cpu_count() or 1) < 4,
                    reason="20-epoch dim-64 mid-scale run (~30+ CPU-min); "
                           "needs a multi-core host for time")
def test_kge_midscale_ceiling_fraction():
    """Pinned CEILING FRACTION at mid scale (VERDICT r4 item 2's 'not
    just 1.5x-uniform' bar): the round-5 recipe (dim 64 >= 4x the
    generator's dim_truth, lr 0.7 x 0.93/epoch, freq + self-adv 3.0)
    must reach >= 25% of the generating model's own filtered-MRR
    ceiling on the 5k-entity lowrank harness in 20 epochs. Measured
    0.150 / 0.340 = 44.1% at exactly this config (docs/PERF.md
    'Breaking the plateau'); the floor leaves ~1.75x margin for seed
    and scheduling noise."""
    from adapm_tpu.apps import knowledge_graph_embeddings as kge
    res = kge.run_app(kge.build_parser().parse_args(
        ["--dim", "64", "--neg_ratio", "64",
         "--synthetic_entities", "5000", "--synthetic_relations", "16",
         "--synthetic_triples", "60000", "--synthetic_mode", "lowrank",
         "--epochs", "20", "--batch_size", "1024", "--lr", "0.7",
         "--lr_decay", "0.93", "--self_adv_temp", "3.0",
         "--neg_sampling", "freq", "--eval_every", "20",
         "--eval_triples", "500", "--num_shards", "2", "--seed", "0"]
        + FAST))
    assert res["test_mrr"] >= 0.25 * res["truth_mrr"], res


def test_kge_checkpoint_resume(tmp_path):
    """Checkpoint -> resume (reference kge.cc checkpointing :327-401)."""
    from adapm_tpu.apps import knowledge_graph_embeddings as kge
    base = ["--dim", "4", "--neg_ratio", "2", "--synthetic_entities", "30",
            "--synthetic_relations", "2", "--synthetic_triples", "100",
            "--epochs", "1", "--batch_size", "32", "--eval_every", "0"] + FAST
    args = kge.build_parser().parse_args(
        base + ["--checkpoint_every", "1", "--checkpoint_dir",
                str(tmp_path)])
    kge.run_app(args)
    ck = tmp_path / "kge_epoch0.npz"
    assert ck.exists()
    args2 = kge.build_parser().parse_args(base + ["--init_from", str(ck)])
    result = kge.run_app(args2)
    assert np.isfinite(result["loss"])


def test_kge_full_replication_ablation():
    """enforce_full_replication (reference ablation flag): every key is
    replicated everywhere; training still converges."""
    from adapm_tpu.apps import knowledge_graph_embeddings as kge
    args = kge.build_parser().parse_args(
        ["--dim", "4", "--neg_ratio", "2", "--synthetic_entities", "24",
         "--synthetic_relations", "2", "--synthetic_triples", "80",
         "--epochs", "1", "--batch_size", "32", "--eval_every", "0",
         "--enforce_full_replication",
         "--sys.channels", "2"] + FAST)
    result = kge.run_app(args)
    assert np.isfinite(result["loss"])


def test_mf_random_keys():
    """enforce_random_keys: permuted key layout trains identically well."""
    from adapm_tpu.apps import matrix_factorization as mf
    args = mf.build_parser().parse_args(
        ["--rows", "24", "--cols", "16", "--nnz", "200", "--rank", "3",
         "--epochs", "2", "--batch_size", "32", "--algorithm", "plain",
         "--enforce_random_keys"] + FAST)
    loss = mf.run(args)
    assert np.isfinite(loss)


def test_kge_lowrank_reaches_truth_ceiling_fraction():
    """--synthetic_mode lowrank draws the KG from a ground-truth ComplEx
    model and reports that model's own filtered MRR as the ceiling; a
    trained model must reach a solid fraction of it (quality evidence on
    a graph that is learnable BY CONSTRUCTION, unlike the adversarial
    permutation KG — docs/PERF.md 'Quality on a learnable synthetic')."""
    from adapm_tpu.apps import knowledge_graph_embeddings as kge
    args = kge.build_parser().parse_args(
        ["--dim", "32", "--neg_ratio", "4", "--synthetic_entities", "200",
         "--synthetic_relations", "8", "--synthetic_triples", "3000",
         "--synthetic_mode", "lowrank", "--epochs", "40",
         "--batch_size", "128", "--lr", "0.3", "--eval_every", "40",
         "--eval_triples", "100", "--seed", "0"] + FAST)
    result = kge.run_app(args)
    ceiling = result["truth_mrr"]  # the app's own generation run
    assert ceiling > 0.5, f"generator ceiling unexpectedly low: {ceiling}"
    # the ceiling is computed on the TEST split, so compare test MRR;
    # measured 0.63x of ceiling at this config on the 8-shard test mesh —
    # 0.45 floor leaves margin for parallel-SGD stochasticity
    assert result["test_mrr"] > 0.45 * ceiling, \
        (result["test_mrr"], ceiling)


def test_lowrank_generator_device_matches_host():
    """The device generator path (io/kge.py _generate_lowrank_device,
    auto at E >= 20k — milliseconds per [4096, E] chunk where the host
    numpy path measured ~150 s/chunk at E=50k) must agree with the host
    path on the truth model's ceiling: same numpy ent/rel draw, same
    shared filtered-rank rule, different (JAX vs numpy) object-draw RNG
    only, so the ceilings match statistically, not bit-wise."""
    from adapm_tpu.io.kge import generate_lowrank
    ds_h, c_h = generate_lowrank(800, 8, 3000, 50, 50, seed=1,
                                 device=False)
    ds_d, c_d = generate_lowrank(800, 8, 3000, 50, 50, seed=1,
                                 device=True)
    assert ds_d.train.shape == ds_h.train.shape
    # same truth model, same rank rule: ceilings agree within sampling
    # noise of the 50-triple test split (measured 0.450 vs 0.466)
    assert abs(c_d - c_h) < 0.15 * max(c_h, 1e-6), (c_h, c_d)
    assert ds_d.truth_mrr_o > 0 and ds_d.truth_mrr_s > 0


def test_kge_l2_regularizer_shrinks_norms():
    """--l2 (lazy ComplEx-paper L2 on the positive triple's rows; the
    lever that first broke the 237-relation wall, docs/PERF.md 'The
    axis isolated') must actually shrink embedding norms vs the
    reference-parity unregularized loss at identical budget/seed."""
    import numpy as np
    from adapm_tpu.apps import knowledge_graph_embeddings as kge
    base = ["--dim", "8", "--neg_ratio", "4",
            "--synthetic_entities", "120", "--synthetic_relations", "4",
            "--synthetic_triples", "800", "--synthetic_mode", "lowrank",
            "--epochs", "6", "--batch_size", "128", "--lr", "0.5",
            "--eval_every", "0", "--seed", "0"] + FAST
    r0 = kge.run_app(kge.build_parser().parse_args(base))
    r1 = kge.run_app(kge.build_parser().parse_args(base + ["--l2", "0.1"]))
    assert np.isfinite(r1["loss"])
    assert r1["ent_norm"] < 0.9 * r0["ent_norm"], (r1, r0)
