"""Decision telemetry plane (ISSUE 17 tentpole).

Tier-1 coverage for adapm_tpu/obs/decisions.py + replay/dataset.py:

  - the off pin: no --sys.trace.decisions (default) => no recorder
    object, zero decision.* registry names, empty decision snapshot
    section (schema v13) — the r7 skip-wrapper shape
    (scripts/metrics_overhead_check.py pins the same thing in CI);
  - capture mechanics: a seeded zipf storm lands tier + sync + reloc
    decisions, every event carries the COMPLETE core feature vector
    AND both clock domains, outcomes reference real decisions, and
    the tallies ride the registry/snapshot;
  - the OBSERVER-EFFECT pin: the same storm captured with decisions ON
    vs OFF replays to a bit-identical reads digest — capture observes
    the run, never steers it;
  - corruption quartet: truncated body, flipped byte, wrong version,
    missing file each raise the NAMED DecisionTraceError during
    verification, before anything consumes the trace;
  - dataset export: deterministic bytes, one row per decision, the
    f./d./o./w. column prefixes joined from BOTH traces;
  - replay refuses to capture itself (the dataset comes from the
    CAPTURED run, never from the simulator observing itself);
  - recorder-level knob validation (config-level round-trips live in
    test_config_knobs.py).
"""
import json

import numpy as np
import pytest

from adapm_tpu import Server, SystemOptions, make_mesh
from adapm_tpu.obs.decisions import (CORE_FEATURES, DTRACE_VERSION,
                                     DecisionRecorder,
                                     DecisionTraceError, load_dtrace)
from adapm_tpu.replay import ReplayEngine, export_dataset, load_wtrace

NK = 256
VL = 4


@pytest.fixture(scope="module")
def ctx():
    return make_mesh(8)


def _storm(ctx, tmp_path, tag, decisions=True, wtrace=False, steps=40,
           tier=True, tier_rows=16, window=4):
    """Seeded zipf pull/push/intent storm; returns (dtrace_path,
    wtrace_path, server) AFTER shutdown (final flush)."""
    dpath = str(tmp_path / f"{tag}.dtrace") if decisions else None
    wpath = str(tmp_path / f"{tag}.wtrace") if wtrace else None
    opts = SystemOptions(sync_max_per_sec=0, prefetch=False,
                         tier=tier, tier_hot_rows=tier_rows,
                         trace_decisions=dpath,
                         trace_decisions_window=window,
                         trace_workload=wpath)
    srv = Server(NK, VL, opts=opts, ctx=ctx, num_workers=2)
    w0, w1 = srv.make_worker(0), srv.make_worker(1)
    w0.wait(w0.set(np.arange(NK), np.ones((NK, VL), np.float32)))
    rng = np.random.default_rng(17)
    for i in range(steps):
        w = w0 if i % 2 == 0 else w1
        ks = np.unique((NK * rng.random(16) ** 6.0)
                       .astype(np.int64).clip(0, NK - 1))
        w.pull_sync(ks)
        w.wait(w.push(ks, np.ones((len(ks), VL), np.float32)))
        if i % 4 == 0:
            w.intent(ks, w.current_clock, w.current_clock + 4)
            w.advance_clock()
        srv.wait_sync()
    srv.shutdown()
    return dpath, wpath, srv


# ---------------------------------------------------------------------------
# the off pin (metrics_overhead_check.py pins the same thing in CI)
# ---------------------------------------------------------------------------


def test_capture_off_pin(ctx):
    """Default server: no recorder, zero decision.* names, empty
    decision snapshot section — the r7 skip-wrapper shape."""
    srv = Server(NK, VL, opts=SystemOptions(sync_max_per_sec=0),
                 ctx=ctx)
    w = srv.make_worker(0)
    w.wait(w.set(np.arange(NK), np.ones((NK, VL), np.float32)))
    w.pull_sync(np.arange(8))
    assert srv.decisions is None
    assert not [n for n in srv.obs.names()
                if n.startswith("decision.")]
    snap = srv.metrics_snapshot()
    assert snap["schema_version"] == 16
    assert snap["decision"] == {}
    srv.shutdown()


# ---------------------------------------------------------------------------
# capture mechanics
# ---------------------------------------------------------------------------


def test_capture_storm_features_outcomes_and_clock_domains(ctx,
                                                           tmp_path):
    """The storm lands decisions on the tier, sync, and reloc planes;
    every decision event carries the complete CORE_FEATURES vector and
    both time domains; every outcome references a real decision; the
    tallies ride the registry and snapshot."""
    dpath, _, srv = _storm(ctx, tmp_path, "storm", steps=40)
    tr = load_dtrace(dpath)
    planes = tr.planes()
    for must in ("tier", "sync", "reloc"):
        assert planes.get(must, 0) >= 1, planes
    decisions, outcomes = tr.decisions(), tr.outcomes()
    assert decisions and outcomes
    monos = []
    for d in decisions:
        assert {"kind", "plane", "seq", "clock", "wall", "mono",
                "action", "features"} <= set(d), d
        for k in CORE_FEATURES:
            assert k in d["features"], (k, d)
        monos.append(d["mono"])
    assert monos == sorted(monos), \
        "recorded mono stamps must be non-decreasing in seq order"
    seqs = {d["seq"] for d in decisions}
    for ref, oc in outcomes.items():
        assert ref in seqs
        assert oc["kind"] == "outcome" and "truncated" in oc
    # >= 90% attribution closure, with close() force-resolving the tail
    closed = sum(1 for d in decisions if d["seq"] in outcomes)
    assert closed / len(decisions) >= 0.90
    # meta carries the knobs + both epoch stamps for the export join
    assert tr.meta["knobs"]["tier"] is True
    assert tr.meta["follow_events"] == 4
    assert tr.dropped == 0


def test_capture_registers_metrics_and_snapshot_section(ctx, tmp_path):
    opts = SystemOptions(sync_max_per_sec=0, prefetch=False,
                         tier=True, tier_hot_rows=16,
                         trace_decisions=str(tmp_path / "m.dtrace"))
    srv = Server(NK, VL, opts=opts, ctx=ctx)
    w = srv.make_worker(0)
    w.wait(w.set(np.arange(NK), np.ones((NK, VL), np.float32)))
    rng = np.random.default_rng(2)
    for _ in range(6):
        ks = np.unique(rng.integers(0, NK, 24))
        w.pull_sync(ks)
        w.wait(w.push(ks, np.ones((len(ks), VL), np.float32)))
        srv.wait_sync()
    names = srv.obs.names()
    for n in ("decision.events_total", "decision.dropped_total",
              "decision.bytes_written", "decision.promoted_never_hit",
              "decision.replicated_never_read",
              "decision.shipped_clean", "decision.regret_rate.tier",
              "decision.regret_rate.sync"):
        assert n in names, n
    snap = srv.metrics_snapshot()
    assert snap["decision"]["path"] == opts.trace_decisions
    assert snap["decision"]["closed"] is False
    srv.shutdown()
    snap2 = srv.metrics_snapshot()
    assert snap2["decision"]["closed"] is True
    assert snap2["decision"]["events_total"] >= 1


def test_event_budget_drops_loudly(ctx, tmp_path):
    opts = SystemOptions(sync_max_per_sec=0, prefetch=False,
                         tier=True, tier_hot_rows=16,
                         trace_decisions=str(tmp_path / "d.dtrace"))
    srv = Server(NK, VL, opts=opts, ctx=ctx)
    srv.decisions.max_events = 4
    w = srv.make_worker(0)
    w.wait(w.set(np.arange(NK), np.ones((NK, VL), np.float32)))
    rng = np.random.default_rng(4)
    for _ in range(12):
        ks = np.unique(rng.integers(0, NK, 24))
        w.pull_sync(ks)
        w.wait(w.push(ks, np.ones((len(ks), VL), np.float32)))
        srv.wait_sync()
    assert int(srv.obs.find("decision.dropped_total").value) >= 1
    srv.shutdown()
    tr = load_dtrace(str(tmp_path / "d.dtrace"))
    assert len(tr.events) == 4 and tr.dropped >= 1


# ---------------------------------------------------------------------------
# THE observer-effect pin
# ---------------------------------------------------------------------------


def test_decision_capture_does_not_steer_replay(ctx, tmp_path):
    """The same seeded storm captured WITH decision telemetry and
    WITHOUT replays to a bit-identical reads digest: the recorder's
    probes are lock-free host reads — capture observes the run, never
    steers it."""
    # tier=False keeps the op stream free of the BACKGROUND promotion
    # engine's timing-dependent promote events (present with capture
    # on OR off — not an observer effect) so the two captures are
    # stream-comparable; sync + reloc decisions still land
    d_on, w_on, _ = _storm(ctx, tmp_path, "on", decisions=True,
                           wtrace=True, steps=24, tier=False)
    _, w_off, _ = _storm(ctx, tmp_path, "off", decisions=False,
                         wtrace=True, steps=24, tier=False)
    assert load_dtrace(d_on).decisions(), \
        "the ON run must actually capture decisions"
    r_on = ReplayEngine(load_wtrace(w_on), seed=3, speed=100).run()
    r_off = ReplayEngine(load_wtrace(w_off), seed=3, speed=100).run()
    assert r_on["reads"] == r_off["reads"] > 0
    assert r_on["reads_digest"] == r_off["reads_digest"]


# ---------------------------------------------------------------------------
# corruption: named error BEFORE anything consumes the trace
# ---------------------------------------------------------------------------


def test_corrupt_dtrace_raises_named_error(ctx, tmp_path):
    dpath, _, _ = _storm(ctx, tmp_path, "c", steps=8)
    raw = open(dpath, "rb").read()
    # truncated body
    trunc = tmp_path / "trunc.dtrace"
    trunc.write_bytes(raw[:-20])
    with pytest.raises(DecisionTraceError, match="bytes"):
        load_dtrace(str(trunc))
    # flipped byte in the checksummed body
    nl = raw.find(b"\n")
    flip = bytearray(raw)
    flip[nl + 30] ^= 0xFF
    bad = tmp_path / "flip.dtrace"
    bad.write_bytes(bytes(flip))
    with pytest.raises(DecisionTraceError, match="sha256"):
        load_dtrace(str(bad))
    # wrong version in the header
    hdr = json.loads(raw[:nl])
    hdr["version"] = DTRACE_VERSION + 1
    vbad = tmp_path / "v.dtrace"
    vbad.write_bytes(json.dumps(hdr).encode() + raw[nl:])
    with pytest.raises(DecisionTraceError, match="version"):
        load_dtrace(str(vbad))
    # a wtrace is NOT a dtrace: format mismatch, named
    with pytest.raises(DecisionTraceError, match="format"):
        d2, w2, _ = _storm(ctx, tmp_path, "c2", wtrace=True, steps=8)
        load_dtrace(w2)
    # missing file
    with pytest.raises(DecisionTraceError, match="cannot read"):
        load_dtrace(str(tmp_path / "missing.dtrace"))
    # the exporter verifies at LOAD — a spliced/corrupt trace can
    # never produce a half-joined dataset
    with pytest.raises(DecisionTraceError):
        export_dataset(str(bad))


# ---------------------------------------------------------------------------
# dataset export
# ---------------------------------------------------------------------------


def test_dataset_export_deterministic_and_joined(ctx, tmp_path):
    dpath, wpath, _ = _storm(ctx, tmp_path, "ds", wtrace=True,
                             steps=24)
    p1, p2 = tmp_path / "a.json", tmp_path / "b.json"
    art = export_dataset(dpath, wpath, out_path=str(p1))
    export_dataset(dpath, wpath, out_path=str(p2))
    assert p1.read_bytes() == p2.read_bytes()
    tr = load_dtrace(dpath)
    assert art["n_rows"] == len(tr.decisions()) > 0
    assert art["rows"] == sorted(art["rows"],
                                 key=lambda r: r["seq"])
    cols = set(art["columns"])
    for k in CORE_FEATURES:
        assert f"f.{k}" in cols, k
    for w in ("w.events_after", "w.keys_read_after",
              "w.keys_written_after"):
        assert w in cols, w
    # every resolved row is labeled; regret is tri-state (True/False
    # per verdict planes, None where the plane records no verdict)
    for r in art["rows"]:
        if r["resolved"]:
            assert "outcome_latency_s" in r
        assert r["regret"] in (True, False, None)
    # without the wtrace the w.* columns are absent, rest identical
    solo = export_dataset(dpath)
    assert solo["source"]["wtrace"] is None
    assert not [c for c in solo["columns"] if c.startswith("w.")]
    assert solo["n_rows"] == art["n_rows"]
    with pytest.raises(ValueError, match="horizon"):
        export_dataset(dpath, horizon_clocks=0)


def test_replay_refuses_to_capture_itself(ctx, tmp_path):
    _, wpath, _ = _storm(ctx, tmp_path, "r", wtrace=True, steps=8)
    with pytest.raises(ValueError, match="capture itself"):
        ReplayEngine(wpath, overrides={
            "trace_decisions": "/tmp/x.dtrace"}).run()


# ---------------------------------------------------------------------------
# recorder-level validation
# ---------------------------------------------------------------------------


def test_recorder_rejects_empty_path(ctx):
    srv = Server(NK, VL, opts=SystemOptions(sync_max_per_sec=0),
                 ctx=ctx)
    with pytest.raises(ValueError, match="path"):
        DecisionRecorder(srv, "")
    srv.shutdown()
