"""Native host-runtime tests: the C++ router (adapm_tpu/native) must agree
exactly with the numpy fallback, since Server._route picks whichever is
available."""
import numpy as np
import pytest

from adapm_tpu import native
from adapm_tpu.base import NO_SLOT
from adapm_tpu.core.store import OOB


@pytest.fixture(scope="module")
def lib():
    lib = native.get_lib()
    if lib is None:
        pytest.skip("no C++ compiler / native build disabled")
    return lib


def _tables(rng, num_keys=64, S=4):
    owner = rng.integers(0, S, num_keys).astype(np.int32)
    slot = rng.integers(0, 100, num_keys).astype(np.int32)
    cache = np.full((S, num_keys), NO_SLOT, dtype=np.int32)
    # sprinkle replicas
    for s in range(S):
        ks = rng.choice(num_keys, 10, replace=False)
        cache[s, ks] = rng.integers(0, 32, 10)
    return owner, slot, cache


@pytest.mark.parametrize("write_through", [False, True])
def test_route_matches_numpy(lib, write_through):
    rng = np.random.default_rng(0)
    owner, slot, cache = _tables(rng)
    keys = rng.integers(0, 64, 200).astype(np.int64)
    shard = 2
    o_sh, o_sl, c_sh, c_sl, use_c, n_remote, local_mask = native.route(
        lib, keys, owner, slot, cache[shard], shard, int(OOB), write_through)
    # numpy reference (Server._route fallback semantics)
    ref_o_sh = owner[keys]
    ref_o_sl = slot[keys]
    cs = cache[shard, keys]
    ref_use = cs >= 0
    ref_c_sl = np.where(ref_use, cs, OOB).astype(np.int32)
    on_owner = ref_o_sh == shard
    local = on_owner if write_through else (ref_use | on_owner)
    assert (o_sh == ref_o_sh).all()
    assert (o_sl == ref_o_sl).all()
    assert (c_sl == ref_c_sl).all()
    assert (use_c == ref_use).all()
    assert (c_sh == shard).all()
    assert n_remote == int((~local).sum())
    assert (local_mask.astype(bool) == local).all()


def test_count(lib):
    acc = np.zeros(16, dtype=np.int64)
    loc = np.zeros(16, dtype=np.int64)
    keys = np.array([3, 3, 5, 3], dtype=np.int64)
    mask = np.array([1, 0, 1, 1], dtype=np.uint8)
    assert lib.adapm_count(keys, mask, 4, 16, acc, loc) == 0
    assert acc[3] == 3 and acc[5] == 1
    assert loc[3] == 2 and loc[5] == 1
    # out-of-range keys are skipped and reported
    assert lib.adapm_count(np.array([99], dtype=np.int64),
                           np.array([1], dtype=np.uint8), 1, 16,
                           acc, loc) == 1


def test_intent_max(lib):
    ie = np.full(8, -1, dtype=np.int32)
    assert lib.adapm_intent_max(np.array([1, 2, 1], dtype=np.int64),
                                3, 8, 10, ie) == 0
    assert lib.adapm_intent_max(np.array([1], dtype=np.int64),
                                1, 8, 5, ie) == 0
    assert ie[1] == 10 and ie[2] == 10 and ie[0] == -1


def test_route_bounds(lib):
    rng = np.random.default_rng(3)
    owner, slot, cache = _tables(rng)
    from adapm_tpu import native as n
    with pytest.raises(IndexError, match="outside the key range"):
        n.route(lib, np.array([99], dtype=np.int64), owner, slot,
                cache[0], 0, int(OOB), False)


def test_replica_scan(lib):
    num_keys = 8
    ie = np.full((2, num_keys), -1, dtype=np.int32)
    ie[0, 3] = 100
    ie[1, 4] = 1
    min_clock = np.array([50, 50], dtype=np.int64)
    keys = np.array([3, 4], dtype=np.int64)
    shards = np.array([0, 1], dtype=np.int32)
    keep = np.zeros(2, dtype=np.uint8)
    kept = lib.adapm_replica_scan(keys, shards, 2, ie.ravel(), min_clock,
                                  num_keys, keep)
    assert kept == 1 and keep.tolist() == [1, 0]


def test_replica_scan_partition(lib):
    """adapm_replica_scan2 emits the four keep/drop x local/cross index
    partitions in one pass (and agrees with the legacy keep mask)."""
    from adapm_tpu.native import replica_scan_partition
    num_keys = 16
    ie = np.full((2, num_keys), -1, dtype=np.int32)
    ie[0, 3] = 100   # keep (local)
    ie[1, 4] = 1     # drop (cross)
    ie[0, 7] = 100   # keep (cross)
    min_clock = np.array([50, 50], dtype=np.int64)
    keys = np.array([3, 4, 7, 9], dtype=np.int64)
    shards = np.array([0, 1, 0, 1], dtype=np.int32)
    cross = np.array([0, 1, 1, 0], dtype=np.uint8)
    kl, kx, dl, dx = replica_scan_partition(
        lib, keys, shards, ie, min_clock, num_keys, cross)
    assert kl.tolist() == [0]
    assert kx.tolist() == [2]
    assert dl.tolist() == [3]
    assert dx.tolist() == [1]
    # single-process shape: cross=None -> everything is local
    kl, kx, dl, dx = replica_scan_partition(
        lib, keys, shards, ie, min_clock, num_keys, None)
    assert kl.tolist() == [0, 2] and len(kx) == 0
    assert dl.tolist() == [1, 3] and len(dx) == 0


def test_server_uses_native(lib):
    """End-to-end: a Server built in this environment routes via the
    native library and produces correct pull/push results."""
    import adapm_tpu
    from adapm_tpu.config import SystemOptions
    srv = adapm_tpu.setup(32, 4, opts=SystemOptions(sync_max_per_sec=0))
    assert srv._native is not None
    w = srv.make_worker(0)
    w.set(np.arange(32), np.arange(32 * 4, dtype=np.float32).reshape(32, 4))
    got = w.pull_sync(np.array([0, 7, 31]))
    assert np.allclose(got[1], np.arange(28, 32))
    w.push(np.array([7]), np.ones(4, np.float32))
    got = w.pull_sync(np.array([7]))
    assert np.allclose(got[0], np.arange(28, 32) + 1)
    srv.shutdown()
