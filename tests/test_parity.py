"""Reproducible quality-parity harness on the reference's OWN bundled
datasets (/root/reference/apps/data — the exact files its CI trains on,
tests/run_apps.sh:3-13). Pins the quality floors recorded in BASELINE.md so
the parity evidence is one `pytest -m parity` away instead of a manual run
(VERDICT r2 item 4).

Floors are set well below the typical results (KGE toy MRR ~0.44, MF loss
~650 after 4 epochs) but far above chance, so they fail on a real
regression without flaking on seed wiggle.
"""
import os

import numpy as np
import pytest

REF_DATA = "/root/reference/apps/data"
# inline planner rounds for deterministic pinned-quality dynamics (same
# rationale as tests/test_apps.py FAST)
FAST = ["--sys.sync.max_per_sec", "0", "--sys.prefetch", "0"]

pytestmark = [
    pytest.mark.parity,
    pytest.mark.slow,
    pytest.mark.skipif(not os.path.isdir(REF_DATA),
                       reason="reference data not present"),
]


def test_parity_kge_complex_toy():
    """Reference CI config (run_apps.sh): 280 entities, 112 relations,
    dim 10, 4 epochs. BASELINE.md records test filtered MRR 0.445 /
    Hits@10 0.727 (random ~0.02); floor at MRR >= 0.30, Hits@10 >= 0.55."""
    from adapm_tpu.apps import knowledge_graph_embeddings as kge
    args = kge.build_parser().parse_args(
        ["--train", f"{REF_DATA}/kge/train.del",
         "--valid", f"{REF_DATA}/kge/valid.del",
         "--test", f"{REF_DATA}/kge/test.del",
         "--num_entities", "280", "--num_relations", "112",
         "--model", "complex", "--dim", "10", "--neg_ratio", "4",
         "--epochs", "4", "--batch_size", "16", "--lr", "0.5",
         "--eval_every", "4", "--eval_triples", "2000",
         "--init_scheme", "uniform", "--init_scale", "1.0"] + FAST)
    result = kge.run_app(args)
    assert result["test_mrr"] >= 0.30, result
    assert result["test_hits10"] >= 0.55, result
    assert np.isfinite(result["loss"])


@pytest.mark.parametrize("algorithm", ["dsgd", "columnwise"])
def test_parity_mf_toy(algorithm):
    """Reference CI config: 6x4 toy matrix, both access orders. The data
    file carries large entries (loss starts ~750); training must cut the
    squared error well below the untrained start (BASELINE.md: 751 -> 652
    in 4 epochs at rank 10; with more epochs it keeps falling)."""
    from adapm_tpu.apps import matrix_factorization as mf
    from adapm_tpu.io.mf import read_coo
    _, _, vals, _, _ = read_coo(f"{REF_DATA}/mf/train.mmc")
    start = float((vals ** 2).sum())
    args = mf.build_parser().parse_args(
        ["--data", f"{REF_DATA}/mf/train.mmc", "--rank", "10",
         "--epochs", "10", "--batch_size", "8", "--lr", "0.05",
         "--algorithm", algorithm] + FAST)
    loss = mf.run(args)
    assert np.isfinite(loss)
    assert loss < 0.95 * start, (loss, start)


def test_parity_word2vec_small():
    """Reference CI config: lm/small.txt, SGNS. The pipeline (readahead
    intent + PrepareSample negatives) must run on the real corpus and the
    sigmoid-CE loss must fall below the untrained level (~ln2 * (1+neg)
    per token pair ~ 4.16 for neg=5; BASELINE.md records 2.79 after one
    epoch)."""
    from adapm_tpu.apps import word2vec as w2v
    args = w2v.build_parser().parse_args(
        ["--data", f"{REF_DATA}/lm/small.txt", "--dim", "32",
         "--window", "5", "--negative", "5", "--epochs", "1",
         "--batch_size", "512", "--lr", "0.05",
         "--readahead", "200"] + FAST)
    loss = w2v.run(args)
    assert np.isfinite(loss)
    untrained = np.log(2.0) * (1 + 5)
    assert loss < 0.85 * untrained, loss
