"""Diagnostic harness (not collected by pytest): harsher version of the
stress scenario with subsystem toggles, used to corner rare cross-process
exactness bugs. argv: [mode] where mode in
{full, nointent, repl_only, reloc_only, nopull}."""
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["ADAPM_PLATFORM"] = "cpu"
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
from xla_compat import mesh_flags  # noqa: E402

os.environ.setdefault("XLA_FLAGS", mesh_flags(2))
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_cache")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.1")
os.environ.pop("PYTHONPATH", None)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import threading  # noqa: E402

import numpy as np  # noqa: E402

import adapm_tpu  # noqa: E402
from adapm_tpu.base import MgmtTechniques  # noqa: E402
from adapm_tpu.config import SystemOptions  # noqa: E402
from adapm_tpu.parallel import control  # noqa: E402

mode = sys.argv[1]
K = 32
tech = {"repl_only": MgmtTechniques.REPLICATION_ONLY,
        "reloc_only": MgmtTechniques.RELOCATION_ONLY}.get(
            mode, MgmtTechniques.ALL)
srv = adapm_tpu.setup(K, 2, opts=SystemOptions(
    sync_max_per_sec=1000, techniques=tech))
srv.start_sync_thread()
rank = control.process_id()
ws = [srv.make_worker(i) for i in range(2)]
counts = np.zeros(K, dtype=np.float64)
counts_lock = threading.Lock()
errs = []


def work(wi):
    w = ws[wi]
    rng = np.random.default_rng(1000 * rank + wi)
    try:
        for i in range(60):
            keys = np.unique((K * rng.random(5) ** 2).astype(np.int64))
            if mode != "nointent" and rng.random() < 0.6:
                w.intent(keys, w.current_clock, w.current_clock + 2)
            ts = w.push(keys, np.ones((len(keys), 2), np.float32))
            w.wait(ts)
            with counts_lock:
                counts[keys] += 1
            if mode != "nopull" and rng.random() < 0.4:
                w.pull_sync(keys)
            w.advance_clock()
    except Exception as e:  # noqa: BLE001
        import traceback
        errs.append(traceback.format_exc())
        errs.append(e)


threads = [threading.Thread(target=work, args=(wi,)) for wi in (0, 1)]
for t in threads:
    t.start()
for t in threads:
    t.join()
assert not errs, errs
for w in ws:
    w.wait_all()
srv.wait_sync()
srv.barrier()
srv.wait_sync()
srv.barrier()
total = control.allreduce(counts, "sum")
final = srv.read_main(np.arange(K)).reshape(K, 2)
diff = final[:, 0] - total
if srv._dbg_applies is not None:
    applies = control.allreduce(srv._dbg_applies, "sum")
    adiff = applies - total
    bad = np.nonzero(np.abs(adiff) > 1e-3)[0]
    sent = control.allreduce(srv.glob._dbg["sent"], "sum")
    served = control.allreduce(srv.glob._dbg["served"], "sum")
    print(f"rank={rank} apply-layer diff at {bad.tolist()}: "
          f"{adiff[bad].tolist()} sent={sent[bad].tolist()} "
          f"served={served[bad].tolist()} "
          f"local_direct={(applies - served)[bad].tolist()}", flush=True)
if not np.allclose(final, total[:, None], atol=1e-3):
    print(f"BISECT-FAIL rank={rank} mode={mode} diff={diff.tolist()}",
          flush=True)
    srv.barrier()
    srv.shutdown()
    sys.exit(1)
srv.barrier()
srv.shutdown()
print(f"BISECT-OK rank={rank} mode={mode}")
