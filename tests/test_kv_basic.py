"""Basic Pull/Push/Set semantics (reference apps/simple.cc smoke +
test_many_key_operations.cc phase 1)."""
import numpy as np
import pytest

import adapm_tpu
from adapm_tpu import LOCAL, Server, SystemOptions, make_mesh


@pytest.fixture(scope="module")
def ctx():
    return make_mesh(8)


def make_server(ctx, num_keys=64, vlen=4, **kw):
    opts = kw.pop("opts", SystemOptions())
    return Server(num_keys, vlen, opts=opts, ctx=ctx, **kw)


def test_zero_init_pull(ctx):
    s = make_server(ctx)
    w = s.make_worker()
    vals = w.pull_sync(np.arange(10))
    assert vals.shape == (10, 4)
    np.testing.assert_allclose(vals, 0.0)


def test_push_then_pull_roundtrip(ctx):
    s = make_server(ctx)
    w = s.make_worker()
    keys = np.array([1, 5, 9])
    vals = np.arange(12, dtype=np.float32).reshape(3, 4)
    ts = w.push(keys, vals)
    w.wait(ts)
    got = w.pull_sync(keys)
    np.testing.assert_allclose(got, vals)


def test_push_is_additive(ctx):
    s = make_server(ctx)
    w = s.make_worker()
    keys = np.array([3])
    v = np.ones((1, 4), np.float32)
    for _ in range(5):
        w.wait(w.push(keys, v))
    np.testing.assert_allclose(w.pull_sync(keys), 5.0)


def test_push_duplicate_keys_accumulate(ctx):
    # same key twice in one batch: both increments must land
    s = make_server(ctx)
    w = s.make_worker()
    keys = np.array([7, 7])
    vals = np.ones((2, 4), np.float32)
    w.wait(w.push(keys, vals))
    np.testing.assert_allclose(w.pull_sync([7]), 2.0)


def test_set_overwrites(ctx):
    s = make_server(ctx)
    w = s.make_worker()
    keys = np.array([2])
    w.wait(w.push(keys, np.full((1, 4), 5.0, np.float32)))
    w.wait(w.set(keys, np.full((1, 4), 1.5, np.float32)))
    np.testing.assert_allclose(w.pull_sync(keys), 1.5)
    w.wait(w.push(keys, np.ones((1, 4), np.float32)))
    np.testing.assert_allclose(w.pull_sync(keys), 2.5)


def test_local_fast_path(ctx):
    """Keys owned by the worker's shard answer locally with ts == -1
    (reference coloc_kv_worker.h:120-186)."""
    s = make_server(ctx)
    w0 = s.make_worker(0)  # shard 0
    own_keys = np.array([0, 8, 16])  # key % 8 == 0 -> shard 0
    out = np.zeros(12, np.float32)
    assert w0.pull(own_keys, out=out) == LOCAL
    assert w0.push(own_keys, np.ones((3, 4), np.float32)) == LOCAL
    remote_keys = np.array([1, 2])
    ts = w0.pull(remote_keys)
    assert ts != LOCAL
    w0.wait(ts)


def test_multi_worker_concurrent_pushes(ctx):
    """All workers push to one contended key; total must be exact
    (reference test_dynamic_allocation.cc:84-103)."""
    s = make_server(ctx, num_workers=8)
    ws = [s.make_worker(i) for i in range(8)]
    key = np.array([13])
    runs = 10
    for _ in range(runs):
        for w in ws:
            w.push(key, np.full((1, 4), 1.0, np.float32))
    for w in ws:
        w.wait_all()
    s.barrier()
    expected = 8 * runs
    for w in ws:
        np.testing.assert_allclose(w.pull_sync(key), expected)


def test_flat_value_buffers(ctx):
    """Reference semantics: vals is a flat concat buffer of per-key lengths."""
    s = make_server(ctx)
    w = s.make_worker()
    keys = np.array([4, 6])
    flat = np.arange(8, dtype=np.float32)
    w.wait(w.push(keys, flat))
    out = np.zeros(8, np.float32)
    ts = w.pull(keys, out=out)
    w.wait(ts)
    np.testing.assert_allclose(out, flat)


def test_per_key_value_lengths(ctx):
    """Mixed lengths (reference per-key value_lengths, kge.cc:1296-1306)."""
    lens = np.array([2, 3, 2, 3, 1])
    s = Server(5, lens, ctx=ctx)
    w = s.make_worker()
    keys = np.array([0, 1, 4])
    flat = np.array([1, 1, 2, 2, 2, 3], dtype=np.float32)
    w.wait(w.push(keys, flat))
    got = w.pull(keys)
    got = w.wait(got) if got != LOCAL else w._last_result
    np.testing.assert_allclose(got, flat)


def test_pull_if_local(ctx):
    s = make_server(ctx)
    w0 = s.make_worker(0)
    ok, vals = w0.pull_if_local(np.array([0, 8]))
    assert ok and vals.shape[0] == 8  # flat: 2 keys x len 4
    ok, vals = w0.pull_if_local(np.array([1]))
    assert not ok and vals is None


def test_setup_helper():
    s = adapm_tpu.setup(16, 2, num_shards=4)
    w = s.make_worker()
    w.wait(w.push([0], np.ones(2, np.float32)))
    np.testing.assert_allclose(w.pull_sync([0]), 1.0)


def test_optimistic_plan_revalidation(ctx):
    """The optimistic-routing contract (core/kv.py _plan_pull/_plan_push,
    reference per-key lock array handle.h:1069-1083): a plan computed
    BEFORE a topology change must be discarded at the lock, not
    dispatched with stale coordinates. The race is forced
    deterministically: the planner relocates the key between the
    worker's (hooked) plan phase and its dispatch."""
    s = make_server(ctx)
    assert s.opts.optimistic_routing
    w0, w1 = s.make_worker(0), s.make_worker(1)
    key = np.array([3], dtype=np.int64)
    w0.wait(w0.set(key, np.full((1, 4), 7.0, np.float32)))

    plans = {"n": 0}
    orig = s._plan_pull

    def racy_plan(keys, shard):
        plan = orig(keys, shard)
        if plans["n"] == 0:
            plans["n"] += 1
            # concurrent planner action lands after the plan was taken:
            # move the key's main copy to another shard (bumps
            # topology_version under the lock)
            s._relocate([(int(key[0]), (shard + 1) % s.num_shards)])
        else:
            plans["n"] += 1
        return plan

    s._plan_pull = racy_plan
    try:
        got = w0.pull_sync(key)
    finally:
        s._plan_pull = orig
    # the stale plan pointed at the old main slot (possibly freed);
    # revalidation must re-plan and still read the authoritative value
    np.testing.assert_allclose(got, 7.0)
    assert plans["n"] >= 2, "stale plan was dispatched without re-plan"

    # same for push: the stale plan's scatter coordinates must not leak
    plans["n"] = 0
    orig_push = s._plan_push

    def racy_plan_push(keys, vals, shard, is_set=False, routes=None):
        # `routes` (the plan-cached skeleton) is deliberately dropped:
        # this hook forces a full stale plan either way
        plan = orig_push(keys, vals, shard, is_set=is_set)
        if plans["n"] == 0:
            plans["n"] += 1
            s._relocate([(int(key[0]), (shard + 1) % s.num_shards)])
        else:
            plans["n"] += 1
        return plan

    s._plan_push = racy_plan_push
    try:
        w1.wait(w1.push(key, np.ones((1, 4), np.float32)))
    finally:
        s._plan_push = orig_push
    assert plans["n"] >= 2
    np.testing.assert_allclose(w0.pull_sync(key), 8.0)
