"""Device-backend probe (ISSUE 14 satellite; xla_compat.py).

The bench r04 death mode was the TPU path dying AT SETUP — client
construction aborting before any phase ran, taking the artifact with
it. `probe_device_backend` detects that in a throwaway subprocess and
`require_device_backend` turns it into the NAMED
AcceleratorUnavailableError; bench.py records `backend: skipped`.
"""
import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from xla_compat import (AcceleratorUnavailableError,  # noqa: E402
                        probe_device_backend, require_device_backend)


def test_probe_cpu_backend_usable():
    verdict, detail = probe_device_backend("cpu", timeout=240.0)
    assert verdict is True, detail
    assert detail.startswith("cpu")


def test_probe_bogus_backend_definitively_unusable():
    verdict, detail = probe_device_backend("nosuchaccelerator",
                                           timeout=240.0)
    assert verdict is False
    assert "died at setup" in detail


def test_require_raises_named_error():
    with pytest.raises(AcceleratorUnavailableError,
                       match="nosuchaccelerator"):
        require_device_backend("nosuchaccelerator", timeout=240.0)
    # and the usable path returns the detail string
    assert require_device_backend("cpu", timeout=240.0).startswith("cpu")
