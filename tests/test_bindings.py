"""Bindings-surface tests (reference bindings/bindings.cc + example.py:
torch/numpy zero-copy ops, async flags, validation errors, built-in
sampling distributions)."""
import numpy as np
import pytest
import torch

from adapm_tpu import bindings as adapm
from adapm_tpu.base import LOCAL


@pytest.fixture
def server():
    adapm.setup(50, 2, use_techniques="all", num_channels=2)
    s = adapm.Server(4, num_keys=50)
    yield s
    s.shutdown()


def test_pull_push_torch_tensor(server):
    w = adapm.Worker(0, server)
    keys = torch.tensor([1, 2, 3], dtype=torch.int64)
    vals = torch.zeros(3, 4)
    w.pull(keys, vals)
    assert vals.abs().sum() == 0
    w.push(keys, torch.ones(3, 4))
    w.pull(keys, vals)
    assert torch.allclose(vals, torch.ones(3, 4))
    # in-place: the same tensor object is filled (zero-copy contract)
    w.push(keys, torch.full((3, 4), 2.0))
    w.pull(keys, vals)
    assert torch.allclose(vals, torch.full((3, 4), 3.0))


def test_pull_push_numpy(server):
    w = adapm.Worker(0, server)
    keys = np.array([7, 8], dtype=np.int64)
    vals = np.zeros((2, 4), dtype=np.float32)
    w.set(keys, np.full((2, 4), 5.0, dtype=np.float32))
    w.pull(keys, vals)
    assert np.allclose(vals, 5.0)


def test_async_contract(server):
    w = adapm.Worker(0, server)
    keys = torch.tensor([10], dtype=torch.int64)
    vals = torch.zeros(1, 4)
    ts = w.pull(keys, vals, asynchronous=True)
    if ts != LOCAL:
        w.wait(ts)
    w.waitall()


def test_validation_errors(server):
    w = adapm.Worker(0, server)
    with pytest.raises(IndexError, match="outside the key range"):
        w.pull(torch.tensor([99], dtype=torch.int64), torch.zeros(1, 4))
    with pytest.raises(ValueError, match="does not match the size"):
        w.pull(torch.tensor([1], dtype=torch.int64), torch.zeros(1, 3))


def test_intent_and_clock(server):
    w = adapm.Worker(0, server)
    w.intent(torch.tensor([5], dtype=torch.int64), 0, 10)
    assert w.advance_clock() == 1
    assert w.current_clock == 1
    w.wait_sync()


def test_sampling_uniform(server):
    server.enable_sampling_support("naive", True, "uniform", 0, 50)
    w = adapm.Worker(0, server)
    h = w.prepare_sample(8, 0)
    keys = np.zeros(8, dtype=np.int64)
    vals = np.zeros((8, 4), dtype=np.float32)
    w.pull_sample(h, keys, vals)
    assert keys.min() >= 0 and keys.max() < 50


def test_sampling_log_uniform(server):
    server.enable_sampling_support("naive", True, "log-uniform", 0, 50)
    w = adapm.Worker(0, server)
    h = w.prepare_sample(64, 0)
    keys = np.zeros(64, dtype=np.int64)
    vals = np.zeros((64, 4), dtype=np.float32)
    w.pull_sample(h, keys, vals)
    assert keys.min() >= 0 and keys.max() < 50
    # log-uniform skews toward small keys
    assert np.median(keys) < 25


def test_misc_api():
    # 1 declared worker thread: barrier() is a rendezvous over ALL declared
    # workers (reference kWorkerThreadGroup barrier counts nodes x threads,
    # src/postoffice.cc:62-65), so only the sole worker may call it here
    adapm.setup(50, 1)
    server = adapm.Server(4, num_keys=50)
    w = adapm.Worker(0, server)
    assert w.num_keys == 50
    assert w.get_key_size(3) == 4
    w.begin_setup()
    w.end_setup()
    w.barrier()
    assert server.my_rank() == 0
    adapm.scheduler(50, 2)  # no-op, must not raise
    server.shutdown()


def test_per_key_value_lengths():
    adapm.setup(10, 1)
    lens = torch.tensor([2] * 5 + [6] * 5, dtype=torch.int64)
    s = adapm.Server(lens)
    w = adapm.Worker(0, s)
    keys = torch.tensor([0, 7], dtype=torch.int64)
    vals = torch.zeros(8)  # 2 + 6 flat
    w.set(keys, torch.arange(8.0))
    got = torch.zeros(8)
    w.pull(keys, got)
    assert torch.allclose(got, torch.arange(8.0))
    assert w.get_key_size(0) == 2 and w.get_key_size(7) == 6
    s.shutdown()


def test_example_runs():
    """The bundled example (reference bindings/example.py analog)."""
    import examples.bindings_example as ex
    ex.main()


def test_ctr_example_runs():
    """FM-over-sparse-features CTR app through the bindings (the
    adapm-pytorch-apps CTR workload shape, reference README.md:23)."""
    import examples.ctr_example as ex
    ex.main()


def test_gcn_example_runs():
    """GCN node classification through the bindings (the
    adapm-pytorch-apps GCN workload shape, reference README.md:23)."""
    import examples.gcn_example as ex
    ex.main()


def test_pull_sample_async_contract(server):
    """bindings.cc:330-337: pull_sample returns the underlying pull's
    timestamp; async skips the wait and the value buffer fills on wait."""
    server.enable_sampling_support("naive", True, "uniform", 0, 50)
    w = adapm.Worker(0, server)
    # seed known values so the filled buffer is recognizable
    allk = np.arange(50, dtype=np.int64)
    w.set(allk, np.full((50, 4), 7.0, np.float32))
    w.wait_sync()
    h = w.prepare_sample(8, 0)
    keys = np.zeros(8, dtype=np.int64)
    vals = np.zeros((8, 4), dtype=np.float32)
    ts = w.pull_sample(h, keys, vals, asynchronous=True)
    if ts != -1:          # remote keys: wait fills the buffer
        w.wait(ts)
    assert np.allclose(vals, 7.0)
    # sync path returns a timestamp too (possibly LOCAL = -1)
    vals2 = np.zeros((8, 4), dtype=np.float32)
    h2 = w.prepare_sample(8, 0)
    ts2 = w.pull_sample(h2, keys, vals2)
    assert isinstance(ts2, int)
    assert np.allclose(vals2, 7.0)
