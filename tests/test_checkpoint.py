"""Manager checkpoint/restore: exact state round-trip including adapted
placement (replicas + relocations), which the reference loses on restart
(its checkpointing is app-level only, SURVEY.md §5). Plus the
incremental chain's corruption handling (ISSUE 10 satellite): a
truncated shard, a flipped checksum byte, and a missing manifest link
each fail loudly with a NAMED error and leave the live server
untouched."""
import json
import os

import numpy as np
import pytest

import adapm_tpu
from adapm_tpu.base import CLOCK_MAX, MgmtTechniques
from adapm_tpu.config import SystemOptions
from adapm_tpu.utils.checkpoint import restore_server, save_server


def _adapted_server():
    opts = SystemOptions(sync_max_per_sec=0, cache_slots_per_shard=16)
    srv = adapm_tpu.setup(32, 4, opts=opts)
    w0, w1 = srv.make_worker(0), srv.make_worker(1)
    rng = np.random.default_rng(0)
    w0.set(np.arange(32), rng.normal(size=(32, 4)).astype(np.float32))
    # competing intents -> replicas; exclusive intent -> relocation
    shared = np.array([5, 9, 13])
    w0.intent(shared, 0, CLOCK_MAX)
    w1.intent(shared, 0, CLOCK_MAX)
    own = np.array([k for k in range(32)
                    if srv.ab.owner[k] not in (0,)][:2])
    w0.intent(own, 0, CLOCK_MAX)
    srv.wait_sync()
    # pending replica deltas too
    w0.push(shared, np.ones((3, 4), np.float32))
    srv.block()
    return srv, (w0, w1)


def test_roundtrip_exact(tmp_path):
    srv, (w0, w1) = _adapted_server()
    path = str(tmp_path / "ck.npz")
    save_server(srv, path)
    before_main = srv.read_main(np.arange(32))
    before_owner = srv.ab.owner.copy()
    before_cache = srv.ab.cache_slot.copy()
    srv.shutdown()

    # fresh server, same geometry
    srv2 = adapm_tpu.setup(
        32, 4, opts=SystemOptions(sync_max_per_sec=0,
                                  cache_slots_per_shard=16))
    w0b = srv2.make_worker(0)
    w1b = srv2.make_worker(1)
    restore_server(srv2, path)

    assert (srv2.ab.owner == before_owner).all()
    assert (srv2.ab.cache_slot == before_cache).all()
    assert np.allclose(srv2.read_main(np.arange(32)), before_main)
    # replica reads include the restored pending delta
    got = w0b.pull_sync(np.array([5]))
    assert np.isfinite(got).all()

    # the restored manager keeps working: quiesce flushes restored deltas
    srv2.quiesce()
    after = srv2.read_main(np.array([5, 9, 13]))
    assert np.isfinite(after).all()
    # allocators were rebuilt: new replicas/relocations still possible
    free_keys = np.array([k for k in range(32)
                          if srv2.ab.owner[k] != 0][:2])
    w0b.intent(free_keys, w0b.current_clock, CLOCK_MAX)
    w1b.intent(free_keys, w1b.current_clock, CLOCK_MAX)
    srv2.wait_sync()
    srv2.shutdown()


def test_restore_rejects_mismatch(tmp_path):
    srv, _ = _adapted_server()
    path = str(tmp_path / "ck.npz")
    save_server(srv, path)
    srv.shutdown()
    other = adapm_tpu.setup(16, 4,
                            opts=SystemOptions(sync_max_per_sec=0))
    try:
        restore_server(other, path)
        raise RuntimeError("should have failed")
    except AssertionError as e:
        assert "mismatch" in str(e)
    other.shutdown()


def test_restore_reseeds_existing_worker_clocks(tmp_path):
    """A worker created before restore must not regress the restored clocks
    on its first advance (intent windows / replica expiry read these)."""
    srv, (w0, w1) = _adapted_server()
    for _ in range(7):
        w0.advance_clock()
    for _ in range(3):
        w1.advance_clock()
    path = str(tmp_path / "ck.npz")
    save_server(srv, path)
    srv.shutdown()

    srv2 = adapm_tpu.setup(
        32, 4, opts=SystemOptions(sync_max_per_sec=0,
                                  cache_slots_per_shard=16))
    w0b = srv2.make_worker(0)
    w1b = srv2.make_worker(1)
    restore_server(srv2, path)
    assert w0b.current_clock == 7 and w1b.current_clock == 3
    assert w0b.advance_clock() == 8
    assert (srv2._clocks[:2] == [8, 3]).all()
    srv2.shutdown()

    # restore-first ordering (the natural resume sequence): a worker created
    # AFTER restore seeds from the restored clock table
    srv3 = adapm_tpu.setup(
        32, 4, opts=SystemOptions(sync_max_per_sec=0,
                                  cache_slots_per_shard=16))
    restore_server(srv3, path)
    w0c = srv3.make_worker(0)
    assert w0c.current_clock == 7
    assert w0c.advance_clock() == 8
    srv3.shutdown()


# ---------------------------------------------------------------------------
# incremental-chain corruption (ISSUE 10 satellite): every broken-chain
# shape fails LOUDLY with a named error BEFORE any server mutation
# ---------------------------------------------------------------------------


def _chain_with_live_server(tmp_path):
    from adapm_tpu.fault import IncrementalCheckpointer
    srv, (w0, w1) = _adapted_server()
    path = str(tmp_path / "chain")
    ck = IncrementalCheckpointer(srv, path)
    ck.save()
    w0.push(np.arange(4), np.ones((4, 4), np.float32))
    ck.save()
    w0.push(np.arange(8, 12), np.ones((4, 4), np.float32))
    ck.save()
    return srv, path


def _assert_untouched_and_live(srv, before):
    # verification failed before mutation: same bits, still serving
    assert np.array_equal(
        np.asarray(srv.read_main(np.arange(32))), before)
    assert not srv.degraded
    srv.quiesce()  # the live server keeps working end to end
    assert np.isfinite(np.asarray(srv.read_main(np.arange(32)))).all()


def test_chain_truncated_shard_fails_loudly(tmp_path):
    from adapm_tpu.fault import CheckpointCorruptError, restore_chain
    srv, path = _chain_with_live_server(tmp_path)
    try:
        before = np.asarray(srv.read_main(np.arange(32)))
        f = os.path.join(path, "delta-000001.npz")
        data = open(f, "rb").read()
        with open(f, "wb") as fh:
            fh.write(data[: len(data) // 2])  # torn write
        with pytest.raises(CheckpointCorruptError,
                           match="delta-000001"):
            restore_chain(srv, path)
        _assert_untouched_and_live(srv, before)
    finally:
        srv.shutdown()


def test_chain_flipped_byte_fails_loudly(tmp_path):
    from adapm_tpu.fault import CheckpointCorruptError, restore_chain
    srv, path = _chain_with_live_server(tmp_path)
    try:
        before = np.asarray(srv.read_main(np.arange(32)))
        f = os.path.join(path, "base-000000.npz")
        data = bytearray(open(f, "rb").read())
        data[len(data) // 2] ^= 0xFF  # one flipped byte
        with open(f, "wb") as fh:
            fh.write(bytes(data))
        with pytest.raises(CheckpointCorruptError, match="checksum"):
            restore_chain(srv, path)
        _assert_untouched_and_live(srv, before)
    finally:
        srv.shutdown()


def test_chain_missing_link_fails_loudly(tmp_path):
    from adapm_tpu.fault import CheckpointChainError, restore_chain
    srv, path = _chain_with_live_server(tmp_path)
    try:
        before = np.asarray(srv.read_main(np.arange(32)))
        # a deleted middle link is a MISSING link, named
        os.remove(os.path.join(path, "delta-000001.npz"))
        with pytest.raises(CheckpointChainError,
                           match="missing chain link delta-000001"):
            restore_chain(srv, path)
        _assert_untouched_and_live(srv, before)
    finally:
        srv.shutdown()


def test_chain_spliced_manifest_fails_loudly(tmp_path):
    """Editing the manifest (dropping a middle entry) breaks the
    predecessor-digest chain even though every remaining file's own
    checksum passes — a restore must never quietly skip a delta."""
    from adapm_tpu.fault import CheckpointChainError, restore_chain
    srv, path = _chain_with_live_server(tmp_path)
    try:
        before = np.asarray(srv.read_main(np.arange(32)))
        mp = os.path.join(path, "chain.json")
        m = json.load(open(mp))
        del m["entries"][1]  # splice out the middle delta
        with open(mp, "w") as fh:
            json.dump(m, fh)
        with pytest.raises(CheckpointChainError):
            restore_chain(srv, path)
        _assert_untouched_and_live(srv, before)
    finally:
        srv.shutdown()
