"""Episodic device plane (ISSUE 14; adapm_tpu/device).

The load-bearing test is THE episodic acceptance storm: a tiered server
driven by an EpisodicRunner (episode rotation: pin/promote + key
staging of window N+1 overlapping window N's fused-step commits on the
`episode`/`episode_commit` streams) under a randomized interleaving of
push / set / relocate / replica churn / sync rounds / serve lookups,
against an UNTIERED NON-EPISODIC shadow applying the identical
operation sequence — every read (whole-table read_main, worker pulls,
serve lookups) bit-identical at every step and after quiesce. Episodic
execution changes WHEN values move, never WHAT a read returns.

Plus: the DevicePort surface (programs counted, pool swap-out), the
partition helper, the serialized/inline degradation, FusedStepRunner
support (pin-only prep, no key staging), and the v10 device/episode
snapshot sections.
"""
import numpy as np
import jax.numpy as jnp
import pytest

import adapm_tpu
from adapm_tpu.config import SystemOptions
from adapm_tpu.device import EpisodicRunner
from adapm_tpu.device.episode import plan_episodes
from adapm_tpu.ops import DeviceRoutedRunner

E = 384
L = 8
D = L // 2


def _loss(embs, aux):
    return jnp.mean(jnp.sum(embs["a"] * embs["b"], axis=-1))


def _mk(tier: bool, hot_rows: int = 16, **kw):
    opts = SystemOptions(sync_max_per_sec=0, prefetch=False,
                         tier=tier, tier_hot_rows=hot_rows, **kw)
    return adapm_tpu.setup(E, L, opts=opts)


def _init_vals(rng):
    vals = rng.normal(size=(E, L)).astype(np.float32)
    # AdaGrad accumulator columns must be positive (rsqrt domain)
    vals[:, D:] = np.abs(vals[:, D:]) + 1e-3
    return vals


def _runner(srv, seed=7):
    return DeviceRoutedRunner(srv, _loss, {"a": 0, "b": 0},
                              {"a": D, "b": D}, shard=0, seed=seed)


def _read_all(srv):
    return np.asarray(srv.read_main(np.arange(E)))


def _batches(rng, n, bsz=16):
    return [{"a": rng.integers(0, E, bsz), "b": rng.integers(0, E, bsz)}
            for _ in range(n)]


# ---------------------------------------------------------------------------
# THE episodic acceptance storm
# ---------------------------------------------------------------------------


def test_episodic_storm_bit_identical_to_sequential_shadow(rng):
    from adapm_tpu.serve import ServePlane
    srv = _mk(True, hot_rows=16, lint_lockorder=True)
    ref = _mk(False)
    w, wr = srv.make_worker(0), ref.make_worker(0)
    vals = _init_vals(rng)
    for ww in (w, wr):
        ww.set(np.arange(E), vals)
    run_e = EpisodicRunner(_runner(srv), episode_batches=3)
    run_s = _runner(ref)
    plane, plane_r = ServePlane(srv), ServePlane(ref)
    sess, sess_r = plane.session(), plane_r.session()
    keys = np.arange(E)
    for step in range(14):
        # episode rotation: a window of fused-step batches runs
        # episodically on srv (prep of window k+1 overlapping commit of
        # window k) and strictly sequentially on the shadow
        bs = _batches(rng, int(rng.integers(3, 7)))
        le = run_e.run(bs, lr=0.05)
        ls = [run_s(b, None, lr=0.05) for b in bs]
        assert len(le) == len(bs)
        for a, b in zip(le, ls):
            assert float(a) == float(b), f"step {step}: loss diverged"
        op = rng.integers(0, 6)
        if op == 0:      # additive push with in-batch duplicates
            ks = rng.integers(0, E, 24)
            v = rng.normal(size=(24, L)).astype(np.float32) * 1e-3
            w.push(ks, v)
            wr.push(ks, v)
        elif op == 1:    # set (keep acc columns positive)
            ks = rng.choice(E, 16, replace=False)
            v = _init_vals(rng)[:16]
            w.set(ks, v)
            wr.set(ks, v)
        elif op == 2:    # relocation (identical on both servers)
            ks = rng.choice(E, 12, replace=False)
            dest = int(rng.integers(0, srv.num_shards))
            srv._relocate_to(ks, dest)
            ref._relocate_to(ks, dest)
        elif op == 3:    # replica churn: intent + forced round
            cand = keys[srv.ab.owner[keys] != w.shard]
            ks = rng.choice(cand, min(16, len(cand)), replace=False)
            end = int(w.current_clock + rng.integers(1, 4))
            w.intent(ks, w.current_clock, end)
            wr.intent(ks, wr.current_clock, end)
            srv.sync.run_round(force_intents=True, all_channels=True)
            ref.sync.run_round(force_intents=True, all_channels=True)
        elif op == 4:    # forced sync round (flush + expiry drops)
            srv.sync.run_round(force_intents=True, all_channels=True)
            ref.sync.run_round(force_intents=True, all_channels=True)
        else:            # serve lookups, compared bitwise
            ks = rng.integers(0, E, 20)
            assert np.array_equal(np.asarray(sess.lookup(ks)),
                                  np.asarray(sess_r.lookup(ks))), \
                f"step {step}: serve lookup diverged"
        if rng.integers(0, 3) == 0:
            w.advance_clock()
            wr.advance_clock()
        a, b = _read_all(srv), _read_all(ref)
        assert np.array_equal(a, b), (
            f"step {step} (op {op}): episodic read diverged from "
            f"sequential shadow ({int((a != b).sum())} floats differ)")
        pk = rng.integers(0, E, 20)
        assert np.array_equal(np.asarray(w.pull_sync(pk)),
                              np.asarray(wr.pull_sync(pk))), \
            f"step {step}: pull diverged"
    srv.quiesce()
    ref.quiesce()
    assert np.array_equal(_read_all(srv), _read_all(ref)), \
        "post-quiesce tables diverged"
    plane.close()
    plane_r.close()
    srv.shutdown()
    ref.shutdown()
    from adapm_tpu.lint import lockorder
    sen = lockorder.get_sentinel()
    assert sen is not None and sen.edges(), \
        "sentinel saw no lock edges: the storm exercised nothing"
    sen.assert_clean()
    lockorder.disable_sentinel()


# ---------------------------------------------------------------------------
# mechanics
# ---------------------------------------------------------------------------


def test_plan_episodes_partition_preserves_order():
    bs = [{"a": np.array([i])} for i in range(10)]
    eps = plan_episodes(bs, None, 4)
    assert [len(e.batches) for e in eps] == [4, 4, 2]
    flat = [int(b["a"][0]) for e in eps for b in e.batches]
    assert flat == list(range(10))
    aux = list(range(10))
    eps = plan_episodes(bs, aux, 3)
    assert [e.auxes for e in eps] == [[0, 1, 2], [3, 4, 5], [6, 7, 8],
                                      [9]]


def test_episodic_single_stream_degrades_inline(rng):
    """--sys.exec.single_stream: the runner degrades to inline
    prep+commit — same results, no pipelining machinery."""
    vals = _init_vals(rng)
    kb = np.random.default_rng(11)
    bs = [{"a": kb.integers(0, E, 16), "b": kb.integers(0, E, 16)}
          for _ in range(7)]
    outs = []
    for single in (True, False):
        srv = _mk(True, hot_rows=16, exec_single_stream=single)
        w = srv.make_worker(0)
        w.set(np.arange(E), vals)
        losses = EpisodicRunner(_runner(srv),
                                episode_batches=2).run(bs, lr=0.05)
        assert len(losses) == len(bs)
        outs.append(_read_all(srv))
        srv.shutdown()
    assert np.array_equal(outs[0], outs[1])


def test_episodic_fused_step_runner_pin_only_prep(rng):
    """FusedStepRunner (host routes, no prefetch_keys): episodic prep
    degrades to pin/promote only and stays bit-identical."""
    from adapm_tpu.ops import FusedStepRunner
    vals = _init_vals(rng)
    kb = np.random.default_rng(13)
    bs = [{"a": kb.integers(0, E, 16), "b": kb.integers(0, E, 16)}
          for _ in range(6)]
    outs = []
    for episodic in (True, False):
        srv = _mk(True, hot_rows=16)
        w = srv.make_worker(0)
        w.set(np.arange(E), vals)
        run = FusedStepRunner(srv, _loss, {"a": 0, "b": 0},
                              {"a": D, "b": D})
        if episodic:
            EpisodicRunner(run, episode_batches=2).run(bs, lr=0.05)
        else:
            for b in bs:
                run(b, None, 0.05)
        outs.append(_read_all(srv))
        srv.shutdown()
    assert np.array_equal(outs[0], outs[1])


def test_device_and_episode_snapshot_sections_v10(rng):
    srv = _mk(True, hot_rows=16)
    w = srv.make_worker(0)
    w.set(np.arange(E), _init_vals(rng))
    kb = np.random.default_rng(17)
    bs = [{"a": kb.integers(0, E, 16), "b": kb.integers(0, E, 16)}
          for _ in range(4)]
    EpisodicRunner(_runner(srv), episode_batches=2).run(bs, lr=0.05)
    snap = srv.metrics_snapshot()
    assert snap["schema_version"] == 16
    dev = snap["device"]
    assert dev["backend"] == "jax"
    assert dev["programs_total"] > 0
    assert dev["wire_ingest_rows_total"] >= 0
    ep = snap["episode"]
    assert ep["episodes_total"] == 2
    assert ep["staged_batches_total"] == 4
    assert ep["prep_s"]["count"] == 2 and ep["commit_s"]["count"] == 2
    srv.shutdown()
    # metrics off: sections present but empty (the r7 contract)
    srv2 = _mk(False, metrics=False)
    snap2 = srv2.metrics_snapshot()
    assert snap2["device"] == {} and snap2["episode"] == {}
    srv2.shutdown()


def test_port_swap_is_the_backend_boundary(rng):
    """A wrapped port observes every store dispatch — the 'a new
    backend is one port implementation' claim, exercised: swap the
    default port for a counting delegator, run traffic, and assert the
    programs flowed through it."""
    from adapm_tpu.device import default_port, set_default_port

    class CountingPort:
        def __init__(self, inner):
            self._inner = inner
            self.calls = 0

        def __getattr__(self, name):
            attr = getattr(self._inner, name)
            if callable(attr) and not name.startswith("_"):
                def wrapped(*a, **kw):
                    self.calls += 1
                    return attr(*a, **kw)
                return wrapped
            return attr

    counting = CountingPort(default_port())
    set_default_port(counting)
    try:
        srv = _mk(True, hot_rows=16)
        w = srv.make_worker(0)
        w.set(np.arange(E), _init_vals(rng))
        w.pull_sync(np.arange(64))
        srv.tier.promote_keys(np.arange(32))
        assert counting.calls > 0, \
            "store traffic bypassed the installed port"
        assert srv.stores[0].port is counting
        srv.shutdown()
    finally:
        set_default_port(None)


def test_episode_batches_knob_validation():
    with pytest.raises(ValueError, match="episode.batches"):
        SystemOptions(episode_batches=0).validate_serve()
    SystemOptions(episode_batches=3).validate_serve()  # fine
