"""Streaming plane (ISSUE 20; adapm_tpu/stream, docs/STREAMING.md):

  - default-off discipline: no --sys.stream.* knob -> no plane object,
    zero stream.* registry names, `stream` snapshot section `{}`;
  - EventLog determinism (event i is a pure function of (seed, i) —
    the property the kill/restore replay leans on) and memo bounds;
  - StreamTrainer exactly-once accounting (cursor/counter wiring,
    the plane requirement failing loudly);
  - THE DRILL: a seeded run killed mid-stream with its checkpoint
    chain lagging the live ack watermark, restored, and tail-replayed
    must hold every ACKED event exactly once — main-store values
    bitwise identical to an unkilled shadow of the same prefix;
  - the cursor riding the chain as aux state, including a restore
    into a plane-LESS server (surfaced, not dropped);
  - FreshnessSLO control law units: window extension below
    min_samples, tighten/relax direction, static-anchor bounds,
    tightest-class target;
  - per-priority-class serve SLO windows (obs/slo.py
    `_control_classes`): overridden classes walk their own lane
    window; the no-override path keeps every hook None and its
    report byte-identical.
"""
import os
import tempfile

import numpy as np
import pytest

import adapm_tpu
from adapm_tpu.config import SystemOptions

NK = 256
VLEN = 8


def _stream_opts(**kw):
    base = dict(sync_max_per_sec=0, prefetch=False, stream_batch=8)
    base.update(kw)
    return SystemOptions(**base)


def _init_vals(srv):
    w = srv.make_worker(0)
    rng = np.random.default_rng(11)
    w.wait(w.set(np.arange(NK),
                 rng.normal(size=(NK, VLEN)).astype(np.float32)))
    return w


# -- default-off ------------------------------------------------------------

def test_stream_default_off():
    srv = adapm_tpu.setup(NK, VLEN, opts=SystemOptions(
        sync_max_per_sec=0, prefetch=False))
    assert srv.stream is None
    assert not [n for n in srv.obs.names() if n.startswith("stream.")]
    snap = srv.metrics_snapshot()
    assert snap["schema_version"] == 16 and snap["stream"] == {}
    # no plane -> a trainer cannot exist (loud, not a silent no-op)
    from adapm_tpu.stream import EventLog, StreamTrainer
    with pytest.raises(RuntimeError):
        StreamTrainer(srv, EventLog(NK))
    srv.shutdown()


# -- EventLog ---------------------------------------------------------------

def test_event_log_deterministic_and_bounded():
    from adapm_tpu.stream import EventLog
    vlen = np.full(NK, VLEN, dtype=np.int64)
    a = EventLog(NK, seed=3, keys_per_event=8, bound=4)
    b = EventLog(NK, seed=3, keys_per_event=8, bound=4096)
    for i in (0, 1, 17, 1000):
        ka, va = a.event(i, vlen)
        kb, vb = b.event(i, vlen)
        assert np.array_equal(ka, kb) and np.array_equal(va, vb)
        assert len(np.unique(ka)) == len(ka)  # unique within one event
        assert ka.max() < NK and ka.min() >= 0
    # memo bound respected; evicted events regenerate bit-identically
    assert len(a._memo) <= 4
    k0, v0 = a.event(0, vlen)
    kb0, vb0 = b.event(0, vlen)
    assert np.array_equal(k0, kb0) and np.array_equal(v0, vb0)
    # different seed -> different stream
    c = EventLog(NK, seed=4, keys_per_event=8)
    kc, vc = c.event(0, vlen)
    assert not (np.array_equal(k0, kc) and np.array_equal(v0, vc))


# -- trainer accounting -----------------------------------------------------

def test_trainer_cursor_and_counters():
    from adapm_tpu.stream import EventLog, StreamTrainer
    srv = adapm_tpu.setup(NK, VLEN, opts=_stream_opts(), num_workers=2)
    _init_vals(srv)
    tr = StreamTrainer(srv, EventLog(NK, seed=5))
    assert tr.batch == 8 and tr.resumed_from == 0
    assert tr.step() == 8 and tr.cursor == 8
    assert tr.run_until(24) == 24
    st = srv.stream.stats()
    assert st["cursor"] == 24
    assert st["events_total"] == 24 and st["batches_total"] == 3
    assert st["acked_events_total"] == 24
    assert st["replayed_events_total"] == 0
    snap = srv.metrics_snapshot()
    assert snap["stream"]["cursor"] == 24
    assert snap["stream"]["trainer"]["batch"] == 8
    srv.shutdown()


# -- the kill/restore drill -------------------------------------------------

def test_kill_restore_drill_bitwise_vs_shadow():
    """Mid-stream kill with the chain LAGGING the ack watermark,
    restore, replay the acked tail: every acked event applied exactly
    once — bitwise vs an unkilled shadow of the same prefix."""
    from adapm_tpu.fault.ckpt import IncrementalCheckpointer, \
        restore_chain
    from adapm_tpu.stream import EventLog, StreamTrainer
    allk = np.arange(NK)
    with tempfile.TemporaryDirectory() as tmp:
        chain = os.path.join(tmp, "chain")
        # -- run A: ingest to 72, but the last chain link is at 40 ----
        srv = adapm_tpu.setup(NK, VLEN, opts=_stream_opts(),
                              num_workers=2)
        _init_vals(srv)
        tr = StreamTrainer(srv, EventLog(NK, seed=5))
        ck = IncrementalCheckpointer(srv, chain)
        ck.save()                       # base link (cursor 0)
        tr.run_until(40)
        ck.save()                       # delta link (cursor 40)
        tr.run_until(72)                # acked past the chain: 72
        acked = tr.cursor
        assert acked == 72
        srv.shutdown()                  # the kill
        # -- restore: chain lands BEHIND the watermark ----------------
        srv2 = adapm_tpu.setup(NK, VLEN, opts=_stream_opts(),
                               num_workers=2)
        srv2.make_worker(0)             # worker-id parity with run A
        restore_chain(srv2, chain)
        assert int(srv2.stream.cursor[0]) == 40
        tr2 = StreamTrainer(srv2, EventLog(NK, seed=5))
        assert tr2.resumed_from == 40
        replayed = tr2.replay_tail(acked)
        assert replayed == 32 and tr2.cursor == 72
        assert int(srv2.stream.c_replayed.value) == 32
        got = srv2.read_main(allk)
        srv2.shutdown()
        # -- unkilled shadow: same seed, same prefix, no kill ---------
        srv3 = adapm_tpu.setup(NK, VLEN, opts=_stream_opts(),
                               num_workers=2)
        _init_vals(srv3)
        tr3 = StreamTrainer(srv3, EventLog(NK, seed=5))
        tr3.run_until(72)
        want = srv3.read_main(allk)
        srv3.shutdown()
        # exactly once, bitwise: a lost acked event or a double-applied
        # replay both break float-add equality
        assert np.array_equal(got, want)


def test_cursor_restore_into_planeless_server():
    """A chain carrying the cursor restored into a server with NO
    stream plane surfaces the watermark instead of dropping it."""
    from adapm_tpu.fault.ckpt import IncrementalCheckpointer, \
        restore_chain
    from adapm_tpu.stream import EventLog, StreamTrainer
    with tempfile.TemporaryDirectory() as tmp:
        chain = os.path.join(tmp, "chain")
        srv = adapm_tpu.setup(NK, VLEN, opts=_stream_opts(),
                              num_workers=2)
        _init_vals(srv)
        StreamTrainer(srv, EventLog(NK, seed=5)).run_until(16)
        IncrementalCheckpointer(srv, chain).save()
        srv.shutdown()
        srv2 = adapm_tpu.setup(NK, VLEN, opts=SystemOptions(
            sync_max_per_sec=0, prefetch=False), num_workers=2)
        assert srv2.stream is None
        restore_chain(srv2, chain)
        assert srv2._restored_stream_cursor == 16
        srv2.shutdown()


# -- freshness controller law ----------------------------------------------

def _fresh_srv(slo_ms=50.0, **kw):
    return adapm_tpu.setup(NK, VLEN, opts=SystemOptions(
        sync_max_per_sec=2.0, prefetch=False, metrics=True,
        trace_flight=True, stream_freshness_slo_ms=slo_ms, **kw))


def test_freshness_law_direction_and_bounds():
    srv = _fresh_srv()
    ctl = srv.stream.freshness
    assert ctl is not None and ctl.target_s == 0.05
    h = srv.flight.freshness.h_freshness
    sm = srv.sync
    assert sm.effective_max_per_sec == 2.0
    # prime tick (no previous window mark): never moves
    ctl._control()
    assert int(ctl.c_adjust.value) == 0
    # window extension: 2 samples < min_samples leaves the mark put...
    h.observe(1.0), h.observe(1.0)
    ctl._control()
    assert int(ctl.c_adjust.value) == 0
    # ...two more complete the SAME window -> tighten (P99 1s >> 50ms)
    h.observe(1.0), h.observe(1.0)
    ctl._control()
    assert int(ctl.c_adjust.value) == 1
    assert sm.effective_max_per_sec > 2.0
    assert ctl.first_adjustment is not None
    (lever, old, new) = ctl.first_adjustment[2][0]
    assert lever == "sync_rate" and new > old
    # keep tightening: the rate caps at 64x static, never beyond
    for _ in range(30):
        for _ in range(4):
            h.observe(1.0)
        ctl._control()
    assert sm.effective_max_per_sec == pytest.approx(128.0)
    # relax on a far-below-target window: walks back, floored at the
    # operator's static knob
    for _ in range(40):
        for _ in range(4):
            h.observe(1e-4)
        ctl._control()
    assert sm.effective_max_per_sec == pytest.approx(2.0)
    rep = ctl.report()
    assert rep["active"] and rep["target_ms"] == 50.0
    assert rep["adjustments"] == int(ctl.c_adjust.value) >= 2
    srv.shutdown()


def test_freshness_steers_to_tightest_class_target():
    srv = _fresh_srv(slo_ms=400.0, stream_freshness_slo_class="1=200")
    ctl = srv.stream.freshness
    # per-class freshness is a write-path property: the controller
    # honestly steers to the TIGHTEST class (docs/STREAMING.md)
    assert ctl.target_s == pytest.approx(0.2)
    rep = ctl.report()
    assert rep["base_target_ms"] == 400.0
    assert rep["target_ms"] == 200.0
    assert rep["class_targets"] == {"1": 200.0}
    srv.shutdown()


# -- per-priority-class serve windows (obs/slo.py) --------------------------

def test_serve_class_windows_walk_independently():
    import time

    from adapm_tpu.serve import ServePlane
    srv = adapm_tpu.setup(NK, VLEN, opts=SystemOptions(
        sync_max_per_sec=0, prefetch=False, serve_max_wait_us=200,
        serve_slo_ms=20.0, serve_slo_class="1=5"))
    plane = ServePlane(srv)
    ctl = plane.slo
    b = plane.batcher
    assert ctl is not None and b.class_wait_us == {1: 200}
    assert b._class_samples is not None
    ctl._control_classes()              # prime the window cut
    # class-1 latencies far above its 5 ms target -> its window
    # shrinks; the base window (class-0 traffic) is untouched
    now = time.perf_counter()
    for _ in range(8):
        b._class_samples.append((now, 0.050, 1))
    ctl._control_classes()
    assert b.class_wait_us[1] < 200
    rep = ctl.report()
    assert rep["class_targets_ms"] == {"1": 5.0}
    assert rep["class_adjustments"] and \
        rep["class_adjustments"][-1]["priority"] == 1
    assert rep["class_wait_us"] == {
        str(p): int(w) for p, w in b.class_wait_us.items()}
    srv.shutdown()


def test_serve_no_class_override_path_untouched():
    from adapm_tpu.serve import ServePlane
    srv = adapm_tpu.setup(NK, VLEN, opts=SystemOptions(
        sync_max_per_sec=0, prefetch=False, serve_slo_ms=20.0))
    plane = ServePlane(srv)
    b = plane.batcher
    # no overrides: every per-class hook stays None and the report
    # carries no class keys (byte-identical to the pre-class path)
    assert b.class_wait_us is None and b._class_samples is None
    rep = plane.slo.report()
    assert "class_targets_ms" not in rep
    assert "class_wait_us" not in rep and "class_adjustments" not in rep
    srv.shutdown()


# -- replay hygiene ---------------------------------------------------------

def test_replay_zeroes_stream_knobs():
    """Replay re-drives captured pushes from the op stream — a replay
    server must never ALSO ingest (double-training) nor demand the
    flight sensor the hygiene pass already zeroed."""
    from adapm_tpu.replay.engine import _build_opts

    class _Trace:
        meta = {"knobs": {"stream_batch": 32, "stream_rate": 2000.0,
                          "stream_freshness_slo_ms": 400.0,
                          "stream_freshness_slo_class": "1=200"}}

    opts, _ = _build_opts(_Trace(), overrides=None)
    assert opts.stream_batch == 0 and opts.stream_rate == 0.0
    assert opts.stream_freshness_slo_ms == 0.0
    assert opts.stream_freshness_slo_class == ""
