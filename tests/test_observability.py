"""Observability tests.

Pre-existing surfaces (reference §5: PS_TRACE_KEYS trace events ->
traces.<rank>.tsv, PS_LOCALITY_STATS counters ->
locality_stats.rank.<r>.tsv, sync shutdown report) plus the unified
telemetry layer (ISSUE 2): metrics registry semantics, snapshot schema
stability, span traces, crash breadcrumbs, `--sys.metrics 0` inertness,
and TSV determinism."""
import json
import sys
import threading

import numpy as np
import pytest

import adapm_tpu
from adapm_tpu.base import CLOCK_MAX
from adapm_tpu.config import SystemOptions
from adapm_tpu.utils.stats import (LOCALITY_COLUMNS, TRACE_COLUMNS,
                                   parse_trace_spec)


def test_parse_trace_spec():
    assert len(parse_trace_spec("all", 10)) == 10
    ks = parse_trace_spec("3,7,7,1", 10)
    assert ks.tolist() == [1, 3, 7]
    r = parse_trace_spec("random-5-seed-3-range-0-100", 1000)
    assert len(r) <= 5 and r.max() < 100
    assert parse_trace_spec("", 10) is None


def test_trace_events_and_locality_files(tmp_path):
    opts = SystemOptions(trace_keys="all", locality_stats=True,
                         stats_out=str(tmp_path), sync_max_per_sec=0,
                         cache_slots_per_shard=16)
    srv = adapm_tpu.setup(32, 4, opts=opts)
    w0 = srv.make_worker(0)
    w1 = srv.make_worker(1)

    keys = np.arange(8, dtype=np.int64)
    w0.set(keys, np.ones((8, 4), np.float32))
    w0.pull_sync(keys)
    # both workers want key 5 -> replication; only w0 wants key 9 -> may
    # relocate
    w0.intent(np.array([5]), 0, CLOCK_MAX)
    w1.intent(np.array([5]), 0, CLOCK_MAX)
    w0.intent(np.array([9]), 0, CLOCK_MAX)
    srv.wait_sync()
    w0.pull_sync(np.array([5, 9]))
    files = srv.write_stats()
    srv.shutdown()

    paths = {p.split("/")[-1] for p in files}
    assert "traces.0.tsv" in paths
    assert "locality_stats.rank.0.tsv" in paths

    trace = (tmp_path / "traces.0.tsv").read_text().splitlines()
    events = {ln.split("\t")[2] for ln in trace[1:]}
    assert "ALLOC" in events and "INTENT_START" in events
    assert ("REPLICA_SETUP" in events) or ("RELOCATE" in events)

    loc = (tmp_path / "locality_stats.rank.0.tsv").read_text().splitlines()
    assert loc[0].startswith("key\taccesses")
    rows = {int(ln.split("\t")[0]): [int(x) for x in ln.split("\t")[1:]]
            for ln in loc[1:]}
    # every access count >= local count
    for k, (acc, local, _samp) in rows.items():
        assert acc >= local


def test_locality_counts_fused_path(tmp_path):
    """The fused-step routing records locality too (the hot loop is where
    the reference counts most accesses)."""
    import jax.numpy as jnp
    from adapm_tpu.ops import FusedStepRunner

    opts = SystemOptions(locality_stats=True, sync_max_per_sec=0)
    srv = adapm_tpu.setup(16, 8, opts=opts)
    w = srv.make_worker(0)
    w.set(np.arange(16), np.ones((16, 8), np.float32))

    def loss_fn(embs, aux):
        return (embs["x"] ** 2).mean()

    runner = FusedStepRunner(srv, loss_fn, role_class={"x": 0},
                             role_dim={"x": 4})
    runner({"x": np.arange(8, dtype=np.int64)}, None, 0.1)
    assert int(srv.locality.accesses.sum()) >= 8
    summ = srv.locality_summary()
    srv.shutdown()


def test_sync_report_string():
    opts = SystemOptions(sync_max_per_sec=0)
    srv = adapm_tpu.setup(8, 2, opts=opts)
    w = srv.make_worker(0)
    w.intent(np.arange(4), 0, 10)
    srv.wait_sync()
    rep = srv.sync.report()
    assert "rounds=" in rep and "intents=" in rep
    srv.shutdown()


# ---------------------------------------------------------------------------
# unified telemetry (ISSUE 2): registry semantics
# ---------------------------------------------------------------------------


def test_counter_sharded_across_threads():
    from adapm_tpu.obs.metrics import Counter
    c = Counter("t.c")
    threads = [threading.Thread(
        target=lambda: [c.inc() for _ in range(1000)])
        for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.value == 4000


def test_histogram_bucket_counts():
    from adapm_tpu.obs.metrics import Histogram
    h = Histogram("t.h", bounds=(1.0, 10.0, 100.0))
    for v in (0.5, 0.1, 1.0, 5.0, 50.0, 500.0):
        h.observe(v)
    s = h.snap()
    # bisect_left: v <= bound lands in that bound's bucket, the last
    # bucket is the +inf overflow
    assert s["buckets"] == [3, 1, 1, 1]
    assert s["count"] == 6 and sum(s["buckets"]) == s["count"]
    assert s["max"] == 500.0
    assert abs(s["sum"] - 556.6) < 1e-9
    assert s["bounds"] == [1.0, 10.0, 100.0]


def test_duplicate_metric_name_check():
    from adapm_tpu.obs.metrics import MetricsRegistry
    reg = MetricsRegistry()
    reg.counter("a.b")
    # two subsystems cannot silently split one counter...
    with pytest.raises(ValueError):
        reg.counter("a.b")
    # ...nor register different kinds under one name, even shared
    with pytest.raises(ValueError):
        reg.histogram("a.b", shared=True)
    # declared-shared metrics are the get-or-create exception
    c1 = reg.counter("a.c", shared=True)
    c2 = reg.counter("a.c", shared=True)
    assert c1 is c2


def test_registry_snapshot_sections_and_gauges():
    from adapm_tpu.obs.metrics import MetricsRegistry
    reg = MetricsRegistry()
    reg.counter("kv.ops").inc(3)
    reg.gauge("staging.occ", fn=lambda: 7)
    reg.histogram("sync.lat_s").observe(0.01)
    s = reg.snapshot()
    assert s["kv"]["ops"] == 3
    assert s["staging"]["occ"] == 7
    assert s["sync"]["lat_s"]["count"] == 1


def test_counter_group_legacy_dict_api():
    from adapm_tpu.obs.metrics import CounterGroup, MetricsRegistry
    reg = MetricsRegistry()
    g = CounterGroup(reg, "prefetch", ("hits", "staged"))
    g.inc("hits")
    g["staged"] += 2          # legacy += path applies the delta
    assert g["hits"] == 1 and g["staged"] == 2
    assert dict(g.items()) == {"hits": 1, "staged": 2}
    assert reg.snapshot()["prefetch"] == {"hits": 1, "staged": 2}


# ---------------------------------------------------------------------------
# unified telemetry: Server.metrics_snapshot end to end
# ---------------------------------------------------------------------------


def _run_instrumented(opts, n_keys=32, vlen=4):
    srv = adapm_tpu.setup(n_keys, vlen, opts=opts, num_workers=2)
    w = srv.make_worker(0)
    keys = np.arange(8, dtype=np.int64)
    w.set(keys, np.ones((8, vlen), np.float32))
    w.pull_sync(keys)
    w.intent(keys, 0, 100)
    if srv.prefetch is not None:
        srv.prefetch.flush()
    w.pull_sync(keys)
    w.push(keys, np.ones((8, vlen), np.float32))
    srv.wait_sync()
    return srv, w


def test_metrics_snapshot_schema_stable():
    srv, w = _run_instrumented(SystemOptions(sync_max_per_sec=0,
                                             prefetch_pull="always"))
    snap = srv.metrics_snapshot()
    # the documented schema contract (docs/OBSERVABILITY.md); v3 = the
    # PR 4 serve section (the online serving plane's metrics +
    # readiness; {} until a ServePlane is attached); v4 = the PR 5 tier
    # section (tiered-storage hot-hit/promotion metrics; {} while
    # --sys.tier is off); v6 = the PR 7 flight/slo sections
    # (request-flight tracing + the SLO autopilot; flight carries only
    # the crash-ride flight-recorder summary until --sys.trace.flight,
    # slo is {} until --sys.serve.slo_ms)
    assert snap["schema_version"] == 16 and snap["metrics_enabled"]
    assert snap["serve"] == {}  # no ServePlane on this server
    assert snap["tier"] == {}   # --sys.tier off on this server
    assert snap["slo"] == {}    # no --sys.serve.slo_ms target set
    # flight tracing is off, but the executor flight-recorder rides
    # --sys.crash_dumps (default on): the section carries its summary
    assert set(snap["flight"]) == {"recorder"}
    assert snap["flight"]["recorder"]["programs_recorded"] >= 0
    for sec in srv._SNAPSHOT_SECTIONS:
        assert isinstance(snap[sec], dict), sec
    # v2 sync surface: shipped vs considered + table-occupancy gauges
    assert snap["sync"]["keys_shipped"] == snap["sync"]["keys_synced"]
    assert snap["sync"]["keys_considered"] >= snap["sync"]["keys_synced"]
    assert snap["sync"]["replicas_live"] >= 0
    assert 0.0 <= snap["sync"]["dirty_fraction"] <= 1.0
    assert "replicas_live.c0" in snap["sync"]
    # kv: latency histograms + op counters + the ts=-1 rate
    assert snap["kv"]["pull_s"]["count"] >= 2
    assert snap["kv"]["push_s"]["count"] >= 1
    assert snap["kv"]["pull_ops"] >= 2
    assert 0.0 <= snap["kv"]["local_answer_frac"] <= 1.0
    # prefetch / plan-cache / staging / sync coverage
    assert snap["prefetch"]["staged"] >= 1 and snap["prefetch"]["hits"] >= 1
    assert snap["plan_cache"]["hits"] + snap["plan_cache"]["misses"] >= 1
    assert snap["staging"]["rows_hwm"] >= 1
    assert snap["sync"]["rounds"] >= 1
    assert snap["sync"]["round_s"]["count"] >= 1
    # JSON-serializable as-is (bench embeds it in the artifact)
    json.dumps(snap)
    # schema stability: a second snapshot has the same key structure
    snap2 = srv.metrics_snapshot()
    assert set(snap2) == set(snap)
    for sec in srv._SNAPSHOT_SECTIONS:
        assert set(snap2[sec]) == set(snap[sec]), sec
    srv.shutdown()


def test_snapshot_is_single_source_for_legacy_views():
    """The pre-existing ad-hoc surfaces are views over the registry:
    the numbers agree by construction."""
    srv, w = _run_instrumented(SystemOptions(sync_max_per_sec=0,
                                             prefetch_pull="always"))
    snap = srv.metrics_snapshot()
    for k, v in srv.prefetch.stats.items():
        assert snap["prefetch"][k] == v
    pc = srv._plan_cache.stats()
    for k in ("hits", "misses", "stale"):
        assert snap["plan_cache"][k] == pc[k]
    srv.shutdown()


def test_metrics_off_empty_registry_and_no_reporter_import():
    """--sys.metrics 0: null registry (empty snapshot, no metric names,
    no latency bracketing) and ZERO imports of the reporter module."""
    sys.modules.pop("adapm_tpu.obs.reporter", None)
    srv, w = _run_instrumented(SystemOptions(sync_max_per_sec=0,
                                             metrics=False))
    assert not srv.obs.enabled
    assert srv.obs.names() == []
    snap = srv.metrics_snapshot()
    assert snap["metrics_enabled"] is False
    for sec in srv._SNAPSHOT_SECTIONS:
        assert snap[sec] == {}, sec
    assert w._h_pull is None  # hot path skips even the perf_counter
    # prefetch's own accounting survives metrics-off (standalone view)
    assert srv.prefetch.stats["hits"] >= 1
    assert "adapm_tpu.obs.reporter" not in sys.modules
    srv.shutdown()


def test_metrics_reporter_runs_and_stops():
    srv, w = _run_instrumented(SystemOptions(sync_max_per_sec=0,
                                             metrics_report_s=0.05))
    assert srv._reporter is not None
    from adapm_tpu.obs.reporter import _fmt
    line = _fmt(srv.obs.snapshot())
    assert "pull=" in line  # the one-line summary carries kv latency
    srv.shutdown()
    assert srv._reporter is None


# ---------------------------------------------------------------------------
# unified telemetry: span traces + crash breadcrumbs
# ---------------------------------------------------------------------------


def test_span_trace_chrome_json(tmp_path):
    opts = SystemOptions(sync_max_per_sec=0, trace_spans=True,
                         stats_out=str(tmp_path), prefetch_pull="always")
    srv, w = _run_instrumented(opts)
    path = srv.write_trace()
    srv.shutdown()
    doc = json.load(open(path))
    evs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    assert evs, "no complete events recorded"
    names = {e["name"] for e in evs}
    # the instrumented phases of this scenario all appear
    for must in ("kv.pull", "kv.push", "kv.set", "kv.plan_pull",
                 "sync.round", "sync.drain_intents", "prefetch.stage",
                 "prefetch.take"):
        assert must in names, must
    for e in evs:
        assert e["ts"] >= 0 and e["dur"] >= 0 and e["pid"] == 0
    # thread metadata present (Perfetto track naming)
    assert any(e.get("ph") == "M" and e.get("name") == "thread_name"
               for e in doc["traceEvents"])


def test_crash_dump_and_breadcrumb(tmp_path):
    import faulthandler
    opts = SystemOptions(sync_max_per_sec=0, trace_spans=True,
                         stats_out=str(tmp_path))
    srv, w = _run_instrumented(opts)
    assert faulthandler.is_enabled()
    import os
    assert os.path.exists(srv.crash_dump_path)
    bc = sorted(tmp_path.glob("adapm_breadcrumb.*.txt"))
    assert bc, "breadcrumb file missing"
    # the last-open-span breadcrumb names an instrumented phase
    content = bc[-1].read_text().split()[0]
    assert content.split(".")[0] in ("kv", "sync", "prefetch",
                                     "collective")
    srv.shutdown()


# ---------------------------------------------------------------------------
# TSV determinism + event ordering (satellites)
# ---------------------------------------------------------------------------


def test_trace_event_ordering_and_column_schema(tmp_path):
    opts = SystemOptions(trace_keys="all", locality_stats=True,
                         stats_out=str(tmp_path), sync_max_per_sec=0,
                         cache_slots_per_shard=16, metrics=False)
    srv = adapm_tpu.setup(32, 4, opts=opts)
    w0 = srv.make_worker(0)
    w1 = srv.make_worker(1)
    keys = np.arange(8, dtype=np.int64)
    w0.set(keys, np.ones((8, 4), np.float32))
    # shared interest with a FINITE window -> replica now, drop later
    w0.intent(np.array([5]), 0, 1)
    w1.intent(np.array([5]), 0, 1)
    srv.wait_sync()
    w0.pull_sync(np.array([5]))
    for _ in range(4):  # advance past the intent window
        w0.advance_clock()
        w1.advance_clock()
    srv.wait_sync()  # expiry: INTENT_STOP + REPLICA_DROP
    files = srv.write_stats()
    srv.shutdown()

    trace = (tmp_path / "traces.0.tsv").read_text().splitlines()
    assert trace[0] == "\t".join(TRACE_COLUMNS)
    rows = [ln.split("\t") for ln in trace[1:]]
    # deterministic order: rows sorted by (time, key, event, shard)
    keyed = [(float(t), int(k), e, int(s)) for t, k, e, s in rows]
    assert keyed == sorted(keyed)
    by_key = {}
    for t, k, e, s in keyed:
        by_key.setdefault(k, []).append((t, e))
    # ALLOC precedes REPLICA_SETUP for every replicated key
    for k, evs in by_key.items():
        times = {e: t for t, e in reversed(evs)}  # first occurrence
        if "REPLICA_SETUP" in times:
            assert "ALLOC" in times
            assert times["ALLOC"] <= times["REPLICA_SETUP"], k
        # INTENT_START/STOP pairing: stops never exceed starts, and the
        # first start precedes the first stop
        starts = [t for t, e in evs if e == "INTENT_START"]
        stops = [t for t, e in evs if e == "INTENT_STOP"]
        assert len(stops) <= len(starts)
        if stops:
            assert min(starts) <= min(stops)
    # the finite-window scenario actually produced a paired stop
    assert any(e == "INTENT_STOP" for _, k, e, _ in keyed)

    loc = (tmp_path / "locality_stats.rank.0.tsv").read_text().splitlines()
    assert loc[0] == "\t".join(LOCALITY_COLUMNS)
    ks = [int(ln.split("\t")[0]) for ln in loc[1:]]
    assert ks == sorted(ks)


def test_stopwatch_concurrent_readers():
    from adapm_tpu.utils import Stopwatch
    sw = Stopwatch()
    stop = threading.Event()
    errs = []

    def hammer():
        try:
            while not stop.is_set():
                sw.start()
                sw.stop()
        except Exception as e:  # noqa: BLE001
            errs.append(e)

    def read():
        try:
            last = -1.0
            while not stop.is_set():
                v = sw.elapsed_s
                assert v >= 0.0
                # cumulative elapsed never regresses while stopped jobs
                # only add time
                assert v >= last - 1e-3
                last = v
        except Exception as e:  # noqa: BLE001
            errs.append(e)

    threads = [threading.Thread(target=hammer),
               threading.Thread(target=hammer),
               threading.Thread(target=read)]
    for t in threads:
        t.start()
    import time
    time.sleep(0.3)
    stop.set()
    for t in threads:
        t.join()
    assert not errs, errs
    assert sw.elapsed_s >= 0.0
