"""Tracing / locality-stats tests (reference §5: PS_TRACE_KEYS trace events
-> traces.<rank>.tsv, PS_LOCALITY_STATS counters ->
locality_stats.rank.<r>.tsv, sync shutdown report)."""
import numpy as np

import adapm_tpu
from adapm_tpu.base import CLOCK_MAX
from adapm_tpu.config import SystemOptions
from adapm_tpu.utils.stats import parse_trace_spec


def test_parse_trace_spec():
    assert len(parse_trace_spec("all", 10)) == 10
    ks = parse_trace_spec("3,7,7,1", 10)
    assert ks.tolist() == [1, 3, 7]
    r = parse_trace_spec("random-5-seed-3-range-0-100", 1000)
    assert len(r) <= 5 and r.max() < 100
    assert parse_trace_spec("", 10) is None


def test_trace_events_and_locality_files(tmp_path):
    opts = SystemOptions(trace_keys="all", locality_stats=True,
                         stats_out=str(tmp_path), sync_max_per_sec=0,
                         cache_slots_per_shard=16)
    srv = adapm_tpu.setup(32, 4, opts=opts)
    w0 = srv.make_worker(0)
    w1 = srv.make_worker(1)

    keys = np.arange(8, dtype=np.int64)
    w0.set(keys, np.ones((8, 4), np.float32))
    w0.pull_sync(keys)
    # both workers want key 5 -> replication; only w0 wants key 9 -> may
    # relocate
    w0.intent(np.array([5]), 0, CLOCK_MAX)
    w1.intent(np.array([5]), 0, CLOCK_MAX)
    w0.intent(np.array([9]), 0, CLOCK_MAX)
    srv.wait_sync()
    w0.pull_sync(np.array([5, 9]))
    files = srv.write_stats()
    srv.shutdown()

    paths = {p.split("/")[-1] for p in files}
    assert "traces.0.tsv" in paths
    assert "locality_stats.rank.0.tsv" in paths

    trace = (tmp_path / "traces.0.tsv").read_text().splitlines()
    events = {ln.split("\t")[2] for ln in trace[1:]}
    assert "ALLOC" in events and "INTENT_START" in events
    assert ("REPLICA_SETUP" in events) or ("RELOCATE" in events)

    loc = (tmp_path / "locality_stats.rank.0.tsv").read_text().splitlines()
    assert loc[0].startswith("key\taccesses")
    rows = {int(ln.split("\t")[0]): [int(x) for x in ln.split("\t")[1:]]
            for ln in loc[1:]}
    # every access count >= local count
    for k, (acc, local, _samp) in rows.items():
        assert acc >= local


def test_locality_counts_fused_path(tmp_path):
    """The fused-step routing records locality too (the hot loop is where
    the reference counts most accesses)."""
    import jax.numpy as jnp
    from adapm_tpu.ops import FusedStepRunner

    opts = SystemOptions(locality_stats=True, sync_max_per_sec=0)
    srv = adapm_tpu.setup(16, 8, opts=opts)
    w = srv.make_worker(0)
    w.set(np.arange(16), np.ones((16, 8), np.float32))

    def loss_fn(embs, aux):
        return (embs["x"] ** 2).mean()

    runner = FusedStepRunner(srv, loss_fn, role_class={"x": 0},
                             role_dim={"x": 4})
    runner({"x": np.arange(8, dtype=np.int64)}, None, 0.1)
    assert int(srv.locality.accesses.sum()) >= 8
    summ = srv.locality_summary()
    srv.shutdown()


def test_sync_report_string():
    opts = SystemOptions(sync_max_per_sec=0)
    srv = adapm_tpu.setup(8, 2, opts=opts)
    w = srv.make_worker(0)
    w.intent(np.arange(4), 0, 10)
    srv.wait_sync()
    rep = srv.sync.report()
    assert "rounds=" in rep and "intents=" in rep
    srv.shutdown()
