"""adapm-lint (ISSUE 11): engine + rule + sentinel tests.

Three layers:

  1. the fixture corpus (tests/lint_fixtures/): one known-bad and one
     known-good file per rule — every rule must FIRE on its bad
     fixture and stay quiet on its good one (rules run in isolation so
     a fixture for rule X never trips on rule Y's noise);
  2. the engine: suppression round-trip (trailing and comment-block
     forms), unused-suppression failure, malformed-suppression
     failure, byte-identical JSON determinism;
  3. the real tree: the package lints clean (the same check
     scripts/invariant_lint_check.py runs in run_tests.sh), the
     intentional-exception suppressions are USED, and the fixes this
     PR landed stay fixed (rule IDs in the test names, per the ISSUE);
     plus the runtime lock-order sentinel's unit behavior (cycle,
     gate-leaf, reentrancy, condvar release, skip-wrapper shape).
"""
import glob
import os
import threading

import numpy as np
import pytest

import adapm_tpu
from adapm_tpu.config import SystemOptions
from adapm_tpu.lint import Analyzer, default_rules, lockorder
from adapm_tpu.lint.rules import (DeviceApiConfinementRule,
                                  DonationAfterDispatchRule,
                                  GateCoverageRule, MetricCatalogRule,
                                  NoBlockingUnderLockRule,
                                  RawThreadBanRule,
                                  RevalidateBeforeEnqueueRule,
                                  SkipWrapperRule)

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(ROOT, "tests", "lint_fixtures")
FIXTURE_CATALOG = os.path.join(FIXTURES, "apm007_catalog.md")

_RULE_BY_ID = {
    "APM001": GateCoverageRule,
    "APM002": NoBlockingUnderLockRule,
    "APM003": SkipWrapperRule,
    "APM004": RawThreadBanRule,
    "APM005": DonationAfterDispatchRule,
    "APM006": RevalidateBeforeEnqueueRule,
    "APM007": MetricCatalogRule,
    "APM008": DeviceApiConfinementRule,
}


def _analyze(paths, rules=None, docs=None):
    return Analyzer(ROOT, rules=rules, paths=paths,
                    docs=docs if docs is not None else {}).run()


# ---------------------------------------------------------------------------
# 1. fixture corpus: every rule fires on bad, stays quiet on good
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("rule_id", sorted(_RULE_BY_ID))
def test_rule_fires_on_bad_fixture(rule_id):
    bad = os.path.join(FIXTURES, f"{rule_id.lower()}_bad.py")
    docs = {"observability": FIXTURE_CATALOG} if rule_id == "APM007" \
        else {}
    rep = _analyze([bad], rules=[_RULE_BY_ID[rule_id]()], docs=docs)
    fired = [f for f in rep.findings if f.rule == rule_id]
    assert fired, f"{rule_id} did not fire on its known-bad fixture"
    assert all(f.path.endswith(f"{rule_id.lower()}_bad.py")
               or f.path.endswith(".md") for f in fired)


@pytest.mark.parametrize("rule_id", sorted(_RULE_BY_ID))
def test_rule_quiet_on_good_fixture(rule_id):
    good = os.path.join(FIXTURES, f"{rule_id.lower()}_good.py")
    docs = {"observability": FIXTURE_CATALOG} if rule_id == "APM007" \
        else {}
    rep = _analyze([good], rules=[_RULE_BY_ID[rule_id]()], docs=docs)
    # APM007's fixture catalog intentionally carries one doc->code
    # drift row (`kv.ghost_total`) proving that direction — findings
    # anchored in the GOOD .py file are what must be zero
    code_findings = [f for f in rep.findings
                     if f.path.endswith("_good.py")]
    assert not code_findings, \
        f"{rule_id} false-positived on its known-good fixture: " \
        f"{[f.format() for f in code_findings]}"


def test_apm007_doc_to_code_direction_fires():
    """The fixture catalog's `kv.ghost_total` row has no registration
    anywhere — the rule must flag the DOC side too."""
    good = os.path.join(FIXTURES, "apm007_good.py")
    rep = _analyze([good], rules=[MetricCatalogRule()],
                   docs={"observability": FIXTURE_CATALOG})
    doc_findings = [f for f in rep.findings if f.path.endswith(".md")]
    assert any("kv.ghost_total" in f.message for f in doc_findings)
    # the derived-kind row is exempt by design
    assert not any("local_answer_frac" in f.message
                   for f in rep.findings)


# ---------------------------------------------------------------------------
# 2. engine: suppressions + determinism
# ---------------------------------------------------------------------------


def test_suppression_round_trip_both_forms():
    """Both violations in suppressed.py carry justified suppressions
    (trailing-comment and comment-block-above forms): zero findings,
    both suppressions counted USED."""
    rep = _analyze([os.path.join(FIXTURES, "suppressed.py")])
    assert not rep.findings, [f.format() for f in rep.findings]
    assert len(rep.suppressions_used) == 2
    assert all(s.justification for s in rep.suppressions_used)


def test_unused_suppression_fails():
    rep = _analyze([os.path.join(FIXTURES, "unused_suppression.py")])
    assert [f.rule for f in rep.findings] == ["APM000"]
    assert "unused suppression" in rep.findings[0].message


def test_suppression_without_justification_fails():
    """A bare `disable=APM004` is APM000 AND does not suppress — the
    underlying APM004 still reports."""
    rep = _analyze([os.path.join(FIXTURES, "bad_suppression.py")])
    rules = sorted(f.rule for f in rep.findings)
    assert rules == ["APM000", "APM004"]


def test_suppression_in_string_literal_is_inert():
    """Suppressions are COMMENT tokens: a suppression-shaped string
    (doc example, the analyzer's own regex) neither suppresses nor
    counts as unused — the analyzer lints its own source clean."""
    path = os.path.join(ROOT, "adapm_tpu", "lint", "analyzer.py")
    rep = _analyze([path])
    assert not [f for f in rep.findings if f.rule == "APM000"], \
        [f.format() for f in rep.findings]


def test_json_report_deterministic():
    """Same tree -> byte-identical JSON (no timestamps, sorted
    findings/keys, repo-relative posix paths)."""
    paths = sorted(glob.glob(os.path.join(FIXTURES, "apm00*_bad.py")))
    docs = {"observability": FIXTURE_CATALOG}
    a = Analyzer(ROOT, paths=paths, docs=docs).run().to_json()
    b = Analyzer(ROOT, paths=paths, docs=docs).run().to_json()
    assert a == b
    assert isinstance(a, str) and a.encode() == b.encode()
    assert "\\\\" not in a, "paths must be posix, not os-native"


# ---------------------------------------------------------------------------
# 3. the real tree: clean, suppressions used, fixes stay fixed
# ---------------------------------------------------------------------------


def _run_tree():
    return Analyzer(ROOT).run()


def test_package_lints_clean():
    """The check run_tests.sh enforces: zero unsuppressed findings and
    zero unused suppressions over adapm_tpu/."""
    rep = _run_tree()
    assert rep.ok(), "\n" + rep.to_text()
    assert len(rep.rules) >= 7


def test_apm002_server_block_suppression_used():
    """Server.block() holds the lock across the device wait BY DESIGN
    (a racing op would donate the buffer being blocked on) — the
    justified suppression must exist and be exercised."""
    rep = _run_tree()
    assert any(s.path == "adapm_tpu/core/kv.py" and "APM002" in s.rules
               for s in rep.suppressions_used)


def test_apm003_push_op_binds_flight_handle():
    """The r7 skip-wrapper fix this PR landed: Worker._push_op binds
    `fl = srv.flight` once and reuses the local — no unguarded call
    through the optional handle survives in core/kv.py."""
    path = os.path.join(ROOT, "adapm_tpu", "core", "kv.py")
    rep = _analyze([path], rules=[SkipWrapperRule()])
    assert not [f for f in rep.findings if f.rule == "APM003"], \
        [f.format() for f in rep.findings]


def test_apm004_parallel_thread_suppressions_used():
    """The two intentional raw threads (collective watchdog, control
    heartbeat) are suppressed WITH justification, not allowlisted —
    and both suppressions fire."""
    rep = _run_tree()
    used = {s.path for s in rep.suppressions_used
            if "APM004" in s.rules}
    assert "adapm_tpu/parallel/collective.py" in used
    assert "adapm_tpu/parallel/control.py" in used


def test_apm008_device_api_confined_to_port():
    """The ISSUE 14 refactor contract: core/ops/tier/serve/fault/
    parallel hold ZERO direct jax.jit/device_put/shard_map uses — the
    device plane lives behind adapm_tpu/device/ — and the intentional
    exceptions (model-math eval programs, Pallas kernels) carry USED
    justified suppressions, never a widened allowlist."""
    rep = _run_tree()
    assert not [f for f in rep.findings if f.rule == "APM008"], \
        "\n" + rep.to_text()
    used = {s.path for s in rep.suppressions_used
            if "APM008" in s.rules}
    assert "adapm_tpu/models/kge.py" in used
    assert "adapm_tpu/io/kge.py" in used
    assert "adapm_tpu/ops/pallas_kernels.py" in used


def test_apm008_no_jit_in_refactored_modules():
    """The five refactored construction sites named by ISSUE 14 stay
    port-routed: zero APM008 findings (no suppressions either) in
    store/fused/dequant/promote/coldpath."""
    paths = [os.path.join(ROOT, "adapm_tpu", *p) for p in (
        ("core", "store.py"), ("ops", "fused.py"),
        ("tier", "promote.py"), ("tier", "coldpath.py"))]
    rep = _analyze(paths, rules=[DeviceApiConfinementRule()])
    assert not rep.findings, [f.format() for f in rep.findings]
    assert not rep.suppressions_used


def test_apm007_catalog_in_sync():
    """The metric catalog drift this PR fixed (tier.* rows,
    fault.loop_retries_total) stays fixed: zero APM007 findings over
    the real tree + real docs/OBSERVABILITY.md."""
    rep = _run_tree()
    assert not [f for f in rep.findings if f.rule == "APM007"], \
        "\n" + rep.to_text()


# ---------------------------------------------------------------------------
# runtime lock-order sentinel (lint/lockorder.py)
# ---------------------------------------------------------------------------


@pytest.fixture()
def sentinel():
    lockorder.disable_sentinel()
    sen = lockorder.enable_sentinel()
    yield sen
    lockorder.disable_sentinel()


def test_lockorder_cycle_detected(sentinel):
    a = lockorder.SentinelLock("lock_a")
    b = lockorder.SentinelLock("lock_b")
    with a:
        with b:
            pass  # records a -> b
    with b:
        with pytest.raises(lockorder.LockOrderError, match="cycle"):
            a.acquire()  # b -> a inverts the recorded order
    assert sentinel.violations == 1


def test_lockorder_gate_is_leaf(sentinel):
    from adapm_tpu.exec import dispatch_gate
    other = lockorder.SentinelLock("server")
    # server -> gate is the sanctioned order (enqueue under the lock)
    with other:
        with dispatch_gate():
            pass
    # gate -> anything is a held-across-dispatch edge: raises
    with dispatch_gate():
        with pytest.raises(lockorder.LockOrderError, match="LEAF"):
            other.acquire()
    assert sentinel.violations == 1


def test_lockorder_gate_leaf_survives_reentrant_hold_above(sentinel):
    """A reentrant re-acquire ABOVE the gate (server -> gate -> server
    again) must not mask the leaf contract for the next new lock —
    the check scans the whole held stack, not just its top."""
    from adapm_tpu.exec import dispatch_gate
    server = lockorder.SentinelLock("server")
    reg = lockorder.SentinelLock("metrics_registry")
    with server:
        with dispatch_gate():
            with server:  # reentrant: pushes 'server' above the gate
                with pytest.raises(lockorder.LockOrderError,
                                   match="LEAF"):
                    reg.acquire()
    assert sentinel.violations == 1


def test_lockorder_same_name_distinct_locks_not_conflated(sentinel):
    """Two servers' locks share the display name 'server' but are
    DISTINCT lock objects: nesting A under B is an orderable edge (not
    reentrancy), and the inversion is detected — the multi-server
    storm configuration."""
    a = lockorder.SentinelLock("server")
    b = lockorder.SentinelLock("server")
    with a:
        with b:  # records A -> B (identity-keyed, same display name)
            pass
    with b:
        with pytest.raises(lockorder.LockOrderError, match="cycle"):
            a.acquire()
    assert sentinel.violations == 1


def test_lockorder_reentrant_and_condvar(sentinel):
    lk = lockorder.SentinelLock("reentrant")
    with lk:
        with lk:  # RLock reentrancy: no new edge, no violation
            pass
    # condvar wait RELEASES the hold in the sentinel's view: another
    # lock acquired by the waker while the waiter parks is no edge
    cv = threading.Condition(lockorder.SentinelLock("cv"))
    hit = []

    def waker():
        with cv:
            hit.append(1)
            cv.notify()

    with cv:
        t = threading.Thread(target=waker)
        t.start()
        cv.wait(timeout=5)
    t.join(5)
    assert hit == [1]
    sentinel.assert_clean()


def test_lockorder_skip_wrapper_shape():
    """--sys.lint.lockorder off (default): Server builds PLAIN RLocks
    (zero wrapper on the hot path); on: SentinelLock wrappers + the
    process sentinel installed — the r7 skip-wrapper contract applied
    to this plane."""
    lockorder.disable_sentinel()
    srv = adapm_tpu.setup(16, 4, opts=SystemOptions(sync_max_per_sec=0))
    try:
        assert not isinstance(srv._lock, lockorder.SentinelLock)
        assert lockorder.get_sentinel() is None
    finally:
        srv.shutdown()
    srv = adapm_tpu.setup(16, 4, opts=SystemOptions(
        sync_max_per_sec=0, lint_lockorder=True))
    try:
        assert isinstance(srv._lock, lockorder.SentinelLock)
        sen = lockorder.get_sentinel()
        assert sen is not None
        w = srv.make_worker(0)
        w.set(np.arange(16), np.ones((16, 4), np.float32))
        w.pull_sync(np.arange(4))
        assert ("server", "dispatch_gate") in sen.edges()
        sen.assert_clean()
    finally:
        srv.shutdown()
        lockorder.disable_sentinel()
