"""Fault-injection plane, executor error policy, incremental
checkpoint chains, and degraded-mode serving (ISSUE 10 tentpole).

The load-bearing pins:
  - the seeded injection plane is DETERMINISTIC per point and free
    when off (`Server.fault is None`, zero fault.* registry names —
    also guarded by scripts/metrics_overhead_check.py);
  - transient executor-program failures retry with bounded exponential
    backoff and the completion sees ONE final outcome; fatal failures
    surface unchanged; the watchdog names a wedged stream without
    blocking behind it;
  - an incremental chain (base + dirty-slot deltas) restores BIT-EXACT
    manager state — mains, dirty replica bases+deltas, placement
    tables, clocks — and a 1%-dirty trickle's delta is a small
    fraction of the base (the full end-to-end drill with a killed
    server lives in scripts/fault_drill_check.py);
  - during a degraded window (restore in progress) serve lookups shed
    loudly with ServeDegradedError — at the session door AND for
    already-queued requests — and readiness reports the reason.
"""
import os
import time

import numpy as np
import pytest

import adapm_tpu
from adapm_tpu.base import CLOCK_MAX
from adapm_tpu.config import SystemOptions
from adapm_tpu.fault import (CheckpointChainError, FatalInjectedFault,
                             FaultPlane, IncrementalCheckpointer,
                             InjectedFault, RetryPolicy,
                             TransientFaultError, parse_fault_spec,
                             restore_chain)

E = 128
L = 4


def _mk(**kw):
    opts = SystemOptions(sync_max_per_sec=0, prefetch=False, **kw)
    return adapm_tpu.setup(E, L, opts=opts, num_workers=2)


# ---------------------------------------------------------------------------
# injection plane
# ---------------------------------------------------------------------------


def test_fault_spec_parse_and_rejection():
    assert parse_fault_spec("a.b=0.5, c=1; d.e.f=0") == {
        "a.b": 0.5, "c": 1.0, "d.e.f": 0.0}
    for bad in ("nope", "x=2", "x=-0.1", "x=abc", "=0.5"):
        with pytest.raises(ValueError):
            parse_fault_spec(bad)
    # the same validation runs at options-validation time
    with pytest.raises(ValueError):
        SystemOptions(fault_spec="x=7").validate_serve()
    with pytest.raises(ValueError):
        SystemOptions(fault_watchdog_s=0).validate_serve()
    with pytest.raises(ValueError):
        SystemOptions(ckpt_every_s=1.0).validate_serve()  # no path


def test_fault_plane_deterministic_per_point_and_off_by_default():
    def fire_seq(plane, point, n):
        out = []
        for _ in range(n):
            try:
                plane.fire(point)
                out.append(False)
            except InjectedFault:
                out.append(True)
        return out

    a = FaultPlane("p.one=0.5,p.two=0.3", seed=42)
    b = FaultPlane("p.one=0.5,p.two=0.3", seed=42)
    # interleave differently on b: per-point RNG streams make the Nth
    # evaluation of a point identical regardless of other points
    seq_a = fire_seq(a, "p.one", 50)
    fire_seq(b, "p.two", 17)
    assert fire_seq(b, "p.one", 50) == seq_a
    assert any(seq_a) and not all(seq_a)
    # a different seed draws a different sequence
    c = FaultPlane("p.one=0.5", seed=43)
    assert fire_seq(c, "p.one", 50) != seq_a
    # unconfigured point: silent no-op
    a.fire("never.configured")
    # counts surface per point
    evals, fired = a.counts("p.one")
    assert evals == 50 and fired == sum(seq_a)
    # fatal variant raises the non-transient class
    d = FaultPlane("x=1.0", seed=0)
    with pytest.raises(FatalInjectedFault):
        d.fire("x", transient=False)
    assert not issubclass(FatalInjectedFault, TransientFaultError)


def test_fault_off_by_default_zero_cost_shape():
    """Default server: no plane, no fault.* registry names, fault/ckpt
    snapshot sections present but empty (schema v9)."""
    srv = _mk()
    try:
        assert srv.fault is None
        assert not [n for n in srv.obs.names()
                    if n.startswith("fault.")]
        snap = srv.metrics_snapshot()
        assert snap["schema_version"] == 16
        assert snap["fault"] == {} and snap["ckpt"] == {}
    finally:
        srv.shutdown()


# ---------------------------------------------------------------------------
# executor error policy: retry / backoff / watchdog
# ---------------------------------------------------------------------------


def test_executor_retries_transient_and_surfaces_fatal():
    srv = _mk(fault_backoff_ms=1.0)
    try:
        calls = {"n": 0}

        def flaky():
            calls["n"] += 1
            if calls["n"] < 3:
                raise TransientFaultError("flaky")
            return "ok"

        c = srv.exec.submit("t", flaky)
        assert c.result(10) == "ok"
        assert calls["n"] == 3
        st = srv.exec.fault_stats()
        assert st["retries"] >= 2 and st["backoff_s"] > 0

        # fatal errors surface unchanged, no retry
        fatal = {"n": 0}

        def boom():
            fatal["n"] += 1
            raise ValueError("fatal")

        c2 = srv.exec.submit("t", boom)
        with pytest.raises(ValueError):
            c2.result(10)
        assert fatal["n"] == 1
    finally:
        srv.shutdown()


def test_executor_retry_budget_exhausts_loudly():
    srv = _mk(fault_retries=2, fault_backoff_ms=1.0)
    try:
        calls = {"n": 0}

        def always():
            calls["n"] += 1
            raise TransientFaultError("always")

        c = srv.exec.submit("t", always)
        with pytest.raises(TransientFaultError):
            c.result(10)
        # initial attempt + exactly the retry budget
        assert calls["n"] == 3
    finally:
        srv.shutdown()


def test_executor_retry_preserves_stream_fifo():
    """A retrying head program still blocks its stream (ordered means
    ordered): the program queued behind it runs only after the final
    attempt."""
    srv = _mk(fault_backoff_ms=1.0)
    try:
        order = []

        def flaky():
            order.append("a")
            if order.count("a") < 2:
                raise TransientFaultError("once")

        srv.exec.submit("s", flaky)
        c2 = srv.exec.submit("s", lambda: order.append("b"))
        c2.result(10)
        assert order == ["a", "a", "b"]
    finally:
        srv.shutdown()


def test_executor_watchdog_marks_wedged_stream():
    srv = _mk()
    try:
        import threading
        release = threading.Event()
        started = threading.Event()

        def stuck():
            started.set()
            release.wait(10)

        c = srv.exec.submit("w", stuck)
        assert started.wait(5)
        time.sleep(0.1)
        wedged = srv.exec.wedged_streams(0.05)
        assert [w["stream"] for w in wedged] == ["w"]
        assert srv.exec.fault_stats()["wedge_flips"] == 1
        # excluded streams are skipped (the serve drains' contract)
        assert srv.exec.wedged_streams(0.05, exclude=("w",)) == []
        release.set()
        c.result(10)
        assert srv.exec.wedged_streams(0.05) == []
        # the flip counter counts EDGES, not probes
        assert srv.exec.fault_stats()["wedge_flips"] == 1
    finally:
        srv.shutdown()


def test_background_sync_survives_injected_faults():
    """The pre-PR failure mode: one transient tick failure silently
    killed the background sync loop. With the plane injecting and the
    policy retrying, rounds keep flowing and the injections are
    visible in the fault section."""
    srv = _mk(fault_spec="sync.round=0.4", fault_seed=3,
              fault_backoff_ms=1.0, fault_retries=10)
    try:
        w = srv.make_worker(0)
        w.set(np.arange(E), np.ones((E, L), np.float32))
        srv.start_sync_thread()
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            if (srv.sync.stats.rounds >= 5
                    and srv.fault.counts("sync.round")[1] >= 2):
                break
            time.sleep(0.05)
        srv.stop_sync_thread()
        assert srv.sync.stats.rounds >= 5, "sync loop died under faults"
        assert srv.fault.counts("sync.round")[1] >= 2
        snap = srv.metrics_snapshot()
        assert snap["fault"]["injections_fired"] >= 2
        # the tick is a SELF-HEALING loop: it catches its own failures
        # and reschedules with backoff (fault.loop_retries_total) —
        # the executor policy's bounded budget must not be its lifeline
        assert snap["fault"]["loop_retries"] >= 2
    finally:
        srv.shutdown()


def test_background_sync_immortal_past_retry_budget():
    """The review-caught gap: a failure streak LONGER than the
    executor retry budget must still not kill the loop. With p=1.0
    every tick fails forever — the loop keeps rescheduling itself with
    backoff, and turning injection off (end of the streak, simulated
    by zeroing the point's probability) lets rounds flow again."""
    srv = _mk(fault_spec="sync.round=1.0", fault_seed=0,
              fault_retries=1, fault_backoff_ms=1.0)
    try:
        srv.start_sync_thread()
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and \
                srv.fault.counts("sync.round")[1] < 5:
            time.sleep(0.02)
        assert srv.fault.counts("sync.round")[1] >= 5, \
            "loop died inside the failure streak"
        assert srv.sync.stats.rounds == 0
        # streak ends: the still-alive loop resumes real rounds
        srv.fault._points["sync.round"].prob = 0.0
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and srv.sync.stats.rounds < 3:
            time.sleep(0.02)
        srv.stop_sync_thread()
        assert srv.sync.stats.rounds >= 3, \
            "loop did not recover after the failure streak ended"
    finally:
        srv.shutdown()


# ---------------------------------------------------------------------------
# incremental checkpoint chain
# ---------------------------------------------------------------------------


def _chained_state(tmp_path, rng):
    """Server with an adapted placement + a 3-link chain; returns
    (path, expected read_main, expected pull, owner/cache tables)."""
    srv = _mk(cache_slots_per_shard=16)
    w0, w1 = srv.make_worker(0), srv.make_worker(1)
    w0.set(np.arange(E), rng.normal(size=(E, L)).astype(np.float32))
    path = str(tmp_path / "chain")
    ck = IncrementalCheckpointer(srv, path)
    base = ck.save()
    assert base["kind"] == "base"
    # delta 1: plain trickle
    w0.push(np.arange(7), np.ones((7, L), np.float32))
    d1 = ck.save()
    assert d1["kind"] == "delta" and d1["slots"] >= 7
    # delta 2: replica churn + a dirty (unshipped) replica delta
    shared = np.array([5, 9, 13])
    w0.intent(shared, 0, CLOCK_MAX)
    w1.intent(shared, 0, CLOCK_MAX)
    srv.wait_sync()
    w0.push(shared, np.full((3, L), 0.25, np.float32))
    srv.block()
    ck.save()
    expected_main = np.asarray(srv.read_main(np.arange(E)))
    expected_pull = np.asarray(w0.pull_sync(np.arange(E)))
    owner = srv.ab.owner.copy()
    cache_slot = srv.ab.cache_slot.copy()
    srv.shutdown()
    return path, expected_main, expected_pull, owner, cache_slot


def test_chain_roundtrip_bit_exact(tmp_path, rng):
    path, exp_main, exp_pull, owner, cache_slot = \
        _chained_state(tmp_path, rng)
    srv2 = _mk(cache_slots_per_shard=16)
    w0b = srv2.make_worker(0)
    recovery_s = restore_chain(srv2, path)
    assert recovery_s > 0
    assert not srv2.degraded  # cleared on success
    assert (srv2.ab.owner == owner).all()
    assert (srv2.ab.cache_slot == cache_slot).all()
    got_main = np.asarray(srv2.read_main(np.arange(E)))
    assert np.array_equal(got_main, exp_main), "read_main not bit-exact"
    # replica reads (base + pending delta) survive the chain bitwise
    got_pull = np.asarray(w0b.pull_sync(np.arange(E)))
    assert np.array_equal(got_pull, exp_pull), "pull not bit-exact"
    # recovery_s lands in the ckpt snapshot section
    assert srv2.metrics_snapshot()["ckpt"]["recovery_s"] == recovery_s
    # the restored manager keeps working: flush the restored deltas
    srv2.quiesce()
    assert np.isfinite(srv2.read_main(np.arange(E))).all()
    srv2.shutdown()


def test_chain_delta_bytes_small_for_sparse_trickle(tmp_path, rng):
    """A ~1%-dirty trickle's delta link must be a small fraction of
    the base (the incremental contract; the 10% acceptance bound at
    bench scale is enforced by scripts/fault_drill_check.py)."""
    opts = SystemOptions(sync_max_per_sec=0, prefetch=False)
    srv = adapm_tpu.setup(4096, 16, opts=opts, num_workers=2)
    try:
        w = srv.make_worker(0)
        w.set(np.arange(4096),
              rng.normal(size=(4096, 16)).astype(np.float32))
        ck = IncrementalCheckpointer(srv, str(tmp_path / "chain"))
        base = ck.save()
        dirty = rng.choice(4096, size=41, replace=False)
        w.push(dirty, np.ones((41, 16), np.float32))
        delta = ck.save()
        assert delta["slots"] == 41
        assert delta["bytes"] <= 0.10 * base["bytes"], (
            f"1%-dirty delta {delta['bytes']}B vs base "
            f"{base['bytes']}B")
    finally:
        srv.shutdown()


def test_periodic_checkpointer_runs_on_ckpt_stream(tmp_path):
    srv = _mk(ckpt_every_s=0.03, ckpt_path=str(tmp_path / "chain"))
    try:
        w = srv.make_worker(0)
        w.set(np.arange(E), np.ones((E, L), np.float32))
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and srv.ckpt.saves_total < 2:
            time.sleep(0.02)
        assert srv.ckpt.saves_total >= 2, "periodic ckpt never ran"
        snap = srv.metrics_snapshot()
        assert snap["ckpt"]["saves_total"] >= 2
        assert snap["ckpt"]["bases_total"] == 1
    finally:
        srv.shutdown()
    # shutdown drained the stream; the chain restores cleanly
    srv2 = _mk()
    restore_chain(srv2, str(tmp_path / "chain"))
    assert np.allclose(srv2.read_main(np.arange(E)), 1.0)
    srv2.shutdown()


def test_restore_rejects_geometry_mismatch_untouched(tmp_path, rng):
    path, exp_main, _, _, _ = _chained_state(tmp_path, rng)
    other = adapm_tpu.setup(
        64, L, opts=SystemOptions(sync_max_per_sec=0, prefetch=False))
    try:
        before = np.asarray(other.read_main(np.arange(64)))
        with pytest.raises(CheckpointChainError, match="mismatch"):
            restore_chain(other, path)
        # verification failed BEFORE mutation: live server untouched
        assert not other.degraded
        assert np.array_equal(
            np.asarray(other.read_main(np.arange(64))), before)
    finally:
        other.shutdown()


# ---------------------------------------------------------------------------
# degraded-mode serving
# ---------------------------------------------------------------------------


def test_degraded_window_sheds_with_distinct_error():
    from adapm_tpu.serve import ServeDegradedError, ServePlane
    srv = _mk()
    plane = ServePlane(srv)
    try:
        sess = plane.session()
        w = srv.make_worker(0)
        w.set(np.arange(E), np.ones((E, L), np.float32))
        assert np.array_equal(sess.lookup(np.arange(4)),
                              np.ones((4, L), np.float32))
        srv.begin_degraded("unit-test window")
        # session door: shed before touching the queue
        with pytest.raises(ServeDegradedError, match="unit-test"):
            sess.lookup(np.arange(4))
        # readiness reports the reason
        rd = plane.health.readiness()
        assert not rd["ready"]
        assert rd["degraded"] == "unit-test window"
        assert any("degraded" in x for x in rd["reasons"])
        # a request already queued when the window opens is shed by the
        # dispatcher with the same distinct error
        from adapm_tpu.serve.admission import LookupRequest
        req = LookupRequest(np.arange(4, dtype=np.int64))
        plane.queue.submit(req)
        assert req.wait(10)
        with pytest.raises(ServeDegradedError):
            req.take_result()
        assert plane.queue.c_degraded.value >= 2
        srv.end_degraded()
        # recovery: bit-exact serving resumes
        assert np.array_equal(sess.lookup(np.arange(4)),
                              np.ones((4, L), np.float32))
        assert plane.health.readiness()["ready"]
    finally:
        plane.close()
        srv.shutdown()


def test_restore_chain_brackets_degraded_and_holds(tmp_path, rng):
    """restore_chain flips the server degraded while applying (plus
    the operational hold), and lookups during the window shed with
    ServeDegradedError — the drill's deterministic pin."""
    import threading

    from adapm_tpu.serve import ServeDegradedError, ServePlane
    path, exp_main, _, _, _ = _chained_state(tmp_path, rng)
    srv = _mk(cache_slots_per_shard=16)
    plane = ServePlane(srv)
    sess = plane.session()
    try:
        outcomes = []
        stop = threading.Event()

        def hammer():
            while not stop.is_set():
                try:
                    v = sess.lookup(np.arange(8))
                    outcomes.append(("ok", np.asarray(v).copy()))
                except ServeDegradedError:
                    outcomes.append(("degraded", None))
                except Exception as e:  # noqa: BLE001
                    outcomes.append((type(e).__name__, None))
                time.sleep(0.002)

        t = threading.Thread(target=hammer, daemon=True)
        t.start()
        restore_chain(srv, path, hold_degraded_s=0.3)
        stop.set()
        t.join(5)
        kinds = {k for k, _ in outcomes}
        assert "degraded" in kinds, (
            f"no lookup shed during the degraded window: {kinds}")
        assert kinds <= {"ok", "degraded"}, kinds
        # post-restore serving is bit-exact against the chain state
        lens = srv.value_lengths[np.arange(8)]
        exp8 = exp_main[: int(lens.sum())].reshape(8, L)
        got = np.asarray(sess.lookup(np.arange(8)))
        assert np.array_equal(got, exp8)
    finally:
        plane.close()
        srv.shutdown()
