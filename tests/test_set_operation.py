"""Set-vs-Push interleavings under intent chaos (reference
tests/test_set_operation.cc)."""
import numpy as np
import pytest

from adapm_tpu import Server, SystemOptions, make_mesh


@pytest.fixture(scope="module")
def ctx():
    return make_mesh(4)


def test_set_then_push_orders(ctx):
    s = Server(16, 2, ctx=ctx, num_workers=4)
    ws = [s.make_worker(i) for i in range(4)]
    k = np.array([6])
    ws[0].wait(ws[0].push(k, np.full(2, 10.0, np.float32)))
    ws[1].wait(ws[1].set(k, np.full(2, 3.0, np.float32)))
    ws[2].wait(ws[2].push(k, np.full(2, 2.0, np.float32)))
    s.quiesce()
    for w in ws:
        np.testing.assert_allclose(w.pull_sync(k), 5.0)


def test_set_visible_through_replicas(ctx):
    """A Set must be observed by replica holders after sync (their stale
    base is refreshed)."""
    s = Server(16, 2, ctx=ctx, num_workers=4,
               opts=SystemOptions(sync_max_per_sec=0))
    ws = [s.make_worker(i) for i in range(4)]
    k = np.array([9])  # home shard 1
    ws[0].intent(k, 0, 100)
    ws[1].intent(k, 0, 100)
    s.wait_sync()
    assert s.ab.has_replica(k, 0).all() or s.ab.owner[9] == 0
    ws[1].wait(ws[1].set(k, np.full(2, 42.0, np.float32)))
    s.quiesce()
    np.testing.assert_allclose(ws[0].pull_sync(k), 42.0)
    np.testing.assert_allclose(ws[1].pull_sync(k), 42.0)


def test_set_on_replica_holder_clears_pending_delta(ctx):
    """If a worker holds a replica with pending delta and then Sets the key,
    its pending delta must not resurface later."""
    s = Server(16, 2, ctx=ctx, num_workers=4,
               opts=SystemOptions(sync_max_per_sec=0))
    ws = [s.make_worker(i) for i in range(4)]
    k = np.array([9])
    ws[0].intent(k, 0, 100)
    ws[1].intent(k, 0, 100)
    s.wait_sync()
    ws[0].push(k, np.full(2, 5.0, np.float32))   # pending in replica delta
    ws[0].wait_all()
    ws[0].wait(ws[0].set(k, np.full(2, 1.0, np.float32)))
    s.quiesce()
    for w in ws:
        np.testing.assert_allclose(w.pull_sync(k), 1.0)
