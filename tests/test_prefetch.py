"""Intent-driven prefetch pipeline + routing-plan cache (r6 tentpole).

Tier-1 coverage for core/intent.py's PrefetchScheduler/PlanCache and the
Server._topology_mutation discipline they revalidate against:

  - staged-hit correctness: a pull served from a pre-gathered staged
    buffer is BIT-identical to the plain pull it replaced;
  - read-your-writes through a staged buffer (push/set between staging
    and consumption invalidates + re-stages);
  - staleness invalidation when a relocation lands between staging and
    consumption (topology_version revalidation at take time);
  - plan-cache hits for repeated batches and invalidation on a
    topology_version bump;
  - the addressbook-mutation discipline assertion (ADVICE r5 #1);
  - staging-pool bounds and the auto pull-gating;
  - control-plane payload framing (ADVICE r5 #2).
"""
import numpy as np
import pytest

from adapm_tpu import Server, SystemOptions, make_mesh


@pytest.fixture(scope="module")
def ctx():
    return make_mesh(8)


def make_server(ctx, num_keys=64, vlen=4, **kw):
    opts = kw.pop("opts", None) or SystemOptions(prefetch_pull="always")
    return Server(num_keys, vlen, opts=opts, ctx=ctx, **kw)


def _seed(w, keys, base=0.0):
    vals = (np.arange(len(keys) * 4, dtype=np.float32)
            .reshape(len(keys), 4) + base)
    w.wait(w.set(keys, vals))
    return vals


def _stage(s, w, keys, horizon=50):
    """Declare intent for `keys` now and wait for the pipeline to stage."""
    w.intent(keys, w.current_clock, w.current_clock + horizon)
    s.prefetch.flush()


def test_staged_pull_bit_identical(ctx):
    s = make_server(ctx)
    w = s.make_worker(0)
    keys = np.unique(np.array([1, 5, 9, 17, 33]))
    vals = _seed(w, keys)
    _stage(s, w, keys)
    assert s.prefetch.report()["live"] == 1
    got = w.pull_sync(keys)
    assert s.prefetch.stats["hits"] == 1
    # bit-identical, not merely close: the staged gather is the same
    # program over the same pools the plain pull would have run
    assert (got == vals).all()
    # a second pull has no staged entry left: plain path, same values
    assert (w.pull_sync(keys) == vals).all()
    s.shutdown()


def test_read_your_writes_through_staged(ctx):
    s = make_server(ctx)
    w = s.make_worker(0)
    keys = np.unique(np.array([2, 10, 18]))
    vals = _seed(w, keys)
    _stage(s, w, keys)
    # overlapping push AFTER staging: the staged buffer must not serve
    # the pre-write values
    w.wait(w.push(keys, np.ones((3, 4), np.float32)))
    assert s.prefetch.stats["invalidated_write"] >= 1
    s.prefetch.flush()  # the pipeline re-stages in the background
    got = w.pull_sync(keys)
    assert (got == vals + 1.0).all()
    s.shutdown()


def test_set_invalidates_staged(ctx):
    s = make_server(ctx)
    w = s.make_worker(0)
    keys = np.unique(np.array([3, 11]))
    _seed(w, keys)
    _stage(s, w, keys)
    new = np.full((2, 4), 7.5, np.float32)
    w.wait(w.set(keys, new))
    s.prefetch.flush()
    assert (w.pull_sync(keys) == new).all()
    s.shutdown()


def test_disjoint_write_keeps_staged(ctx):
    s = make_server(ctx)
    w = s.make_worker(0)
    keys = np.unique(np.array([4, 12]))
    vals = _seed(w, keys)
    _stage(s, w, keys)
    w.wait(w.push(np.array([40, 48]), np.ones((2, 4), np.float32)))
    assert s.prefetch.report()["live"] == 1  # disjoint: entry survives
    assert (w.pull_sync(keys) == vals).all()
    assert s.prefetch.stats["hits"] == 1
    s.shutdown()


def test_relocation_between_stage_and_pull(ctx):
    """A relocation landing between staging and consumption must fail the
    staged buffer's revalidation (the moved row may fold in a stale
    replica base); the pull then replans and returns current values."""
    s = make_server(ctx)
    w = s.make_worker(0)
    keys = np.unique(np.array([1, 9, 25]))  # home shard 1
    vals = _seed(w, keys)
    _stage(s, w, keys)
    assert s.prefetch.report()["live"] == 1
    moved = s._relocate_to(keys, 3)
    assert moved == len(keys)
    got = w.pull_sync(keys)
    assert (got == vals).all()
    assert s.prefetch.stats["invalidated_topology"] >= 1
    assert s.prefetch.stats["hits"] == 0
    s.shutdown()


def test_plan_cache_hits_and_topology_invalidation(ctx):
    s = make_server(ctx)
    w = s.make_worker(0)
    keys = np.unique(np.array([6, 14, 22]))
    vals = _seed(w, keys)
    h0 = s._plan_cache.hits
    assert (w.pull_sync(keys) == vals).all()
    assert (w.pull_sync(keys) == vals).all()  # same batch: cached plan
    assert s._plan_cache.hits > h0
    st0 = s._plan_cache.stale
    s._relocate_to(keys, 5)  # topology bump invalidates the entry
    assert (w.pull_sync(keys) == vals).all()
    assert s._plan_cache.stale > st0
    s.shutdown()


def test_plan_cache_push_routes(ctx):
    s = make_server(ctx)
    w = s.make_worker(0)
    keys = np.unique(np.array([7, 15]))
    _seed(w, keys, base=0.0)
    one = np.ones((2, 4), np.float32)
    for _ in range(3):  # repeated push batches ride the cached skeleton
        w.wait(w.push(keys, one))
    expect = (np.arange(8, dtype=np.float32).reshape(2, 4) + 3.0)
    assert (w.pull_sync(keys) == expect).all()
    s.shutdown()


def test_plan_cache_collision_is_exact(ctx):
    """Same-length different-key batches must never share a plan."""
    s = make_server(ctx)
    w = s.make_worker(0)
    a = np.unique(np.array([8, 16, 24]))
    b = np.unique(np.array([9, 17, 25]))
    va = _seed(w, a, base=0.0)
    vb = _seed(w, b, base=100.0)
    for _ in range(2):
        assert (w.pull_sync(a) == va).all()
        assert (w.pull_sync(b) == vb).all()
    s.shutdown()


def test_topology_mutation_discipline(ctx):
    """An addressbook mutation outside _topology_mutation() is caught by
    the discipline assertion (ADVICE r5 #1)."""
    s = make_server(ctx)
    with s._lock:
        with s._topology_mutation():
            cs = s.ab.add_replicas(np.array([1]), 0)  # paired: fine
            assert len(cs) == 1
        v = s.topology_version
        s.ab.add_replicas(np.array([2]), 0)  # UNPAIRED mutation
        with pytest.raises(AssertionError, match="outside"):
            with s._topology_mutation():
                pass
        assert s.topology_version == v  # the failed section did not bump
    s.shutdown()


def test_topology_mutation_cancel(ctx):
    s = make_server(ctx)
    v = s.topology_version
    with s._topology_mutation() as tm:
        tm.cancel()  # mutated nothing
    assert s.topology_version == v
    with s._topology_mutation():
        pass  # uncancelled: bumps even without ab mutations (restore path)
    assert s.topology_version == v + 1
    s.shutdown()


def test_staging_pool_bounds_memory(ctx):
    opts = SystemOptions(prefetch_pull="always", prefetch_staging_rows=4)
    s = make_server(ctx, opts=opts)
    w = s.make_worker(0)
    keys = np.arange(32)  # bucket of 32 rows > 4-row budget
    vals = _seed(w, keys)
    _stage(s, w, keys)
    assert s.prefetch.report()["live"] == 0
    assert s.prefetch.stats["pool_full"] >= 1
    assert (w.pull_sync(keys) == vals).all()  # plain path, still right
    s.shutdown()


def test_prefetch_pull_auto_gating(ctx):
    """auto mode stages only for workers that actually use the Pull API
    (fused-runner loops never pull; staging for them is wasted work)."""
    s = make_server(ctx, opts=SystemOptions())  # prefetch_pull="auto"
    w = s.make_worker(0)
    keys = np.unique(np.array([5, 13]))
    vals = _seed(w, keys)
    _stage(s, w, keys)
    assert s.prefetch.report()["live"] == 0  # never pulled: not staged
    assert (w.pull_sync(keys) == vals).all()
    _stage(s, w, keys)  # now a known Pull user
    assert s.prefetch.report()["live"] == 1
    assert (w.pull_sync(keys) == vals).all()
    s.shutdown()


def test_staged_entry_expires_with_clock(ctx):
    s = make_server(ctx)
    w = s.make_worker(0)
    keys = np.unique(np.array([20, 28]))
    vals = _seed(w, keys)
    w.intent(keys, w.current_clock, w.current_clock)  # end = now
    s.prefetch.flush()
    w.advance_clock()  # window passed
    w.advance_clock()
    s.prefetch.pump(0)  # wake the expiry sweep
    s.prefetch.flush()
    assert s.prefetch.report()["live"] == 0
    assert (w.pull_sync(keys) == vals).all()
    s.shutdown()


def test_drive_rounds_delegates_planner(ctx):
    """drive_rounds with the pipeline on runs planner rounds on the
    background thread: intents still get acted on (replication or
    relocation makes the keys local to the worker's shard)."""
    s = make_server(ctx)
    w = s.make_worker(0)
    keys = np.unique(np.array([3, 11, 19]))  # home shard 3
    _seed(w, keys)
    assert not s.ab.is_local(keys, w.shard).any()
    w.intent(keys, w.current_clock, w.current_clock + 10)
    s.drive_rounds()
    s.prefetch.flush()
    assert s.ab.is_local(keys, w.shard).all()
    assert s.prefetch.stats["rounds_driven"] >= 1
    s.shutdown()


def test_runner_staged_keys(ctx):
    """DeviceRoutedRunner.prefetch_keys: staged uploads feed the step;
    a handle for a different batch is rejected."""
    from adapm_tpu.models import make_kge_loss
    from adapm_tpu.ops import DeviceRoutedRunner

    s = make_server(ctx, num_keys=40, vlen=8)
    w = s.make_worker(0)
    w.wait(w.set(np.arange(40),
                 np.full((40, 8), 0.1, np.float32)))
    runner = DeviceRoutedRunner(
        s, make_kge_loss("complex"),
        role_class={"s": 0, "r": 0, "o": 0, "neg": 0},
        role_dim={k: 4 for k in ("s", "r", "o", "neg")})
    rng = np.random.default_rng(0)
    roles = {k: rng.integers(0, 40, 8).astype(np.int64)
             for k in ("s", "r", "o", "neg")}
    stg = runner.prefetch_keys(roles)
    loss = runner(roles, None, 0.1, staged=stg)
    assert np.isfinite(float(loss))
    other = {k: (v + 1) % 40 for k, v in roles.items()}
    with pytest.raises(ValueError, match="staged keys differ"):
        runner(other, None, 0.1, staged=stg)
    s.shutdown()


def test_fused_step_invalidates_staged(ctx):
    """The fused step is a batched Push in PM terms: it must invalidate
    staged pull buffers covering the trained keys (review finding r6)."""
    from adapm_tpu.models import make_kge_loss
    from adapm_tpu.ops import FusedStepRunner

    s = make_server(ctx, num_keys=40, vlen=8)  # row = [emb 4 | acc 4]
    w = s.make_worker(0)
    w.wait(w.set(np.arange(40), np.full((40, 8), 0.1, np.float32)))
    runner = FusedStepRunner(
        s, make_kge_loss("complex"),
        role_class={"s": 0, "r": 0, "o": 0, "neg": 0},
        role_dim={k: 4 for k in ("s", "r", "o", "neg")})
    uk = np.unique(np.array([1, 2, 3, 4]))
    _stage(s, w, uk)
    assert s.prefetch.report()["live"] == 1
    runner({"s": uk, "r": uk, "o": uk,
            "neg": uk}, None, 0.5, shard=w.shard)
    assert s.prefetch.stats["invalidated_write"] >= 1
    got = w.pull_sync(uk)
    expect = s.read_main(uk).reshape(4, 8)
    assert (got == expect).all()
    assert not np.allclose(got, 0.1)  # the step really moved the rows
    s.shutdown()


def test_control_payload_framing():
    """ADVICE r5 #2: dtype/shape ride the payload; mismatches raise."""
    from adapm_tpu.parallel.control import _pack_array, _unpack_array

    arr = np.arange(6, dtype=np.float64).reshape(2, 3)
    out = _unpack_array(_pack_array(arr), arr, "t")
    assert out.dtype == arr.dtype and (out == arr).all()
    out[0, 0] = -1  # writable copy

    # byte-order-free dtypes whose .str BEGINS with '|' (bool, uint8):
    # the header separator must not collide with them
    for dt in (np.bool_, np.uint8):
        a = np.array([1, 0, 1, 1]).astype(dt)
        got = _unpack_array(_pack_array(a), a, "t")
        assert got.dtype == a.dtype and (got == a).all()

    # same nbytes, different dtype: the silent-reinterpret case
    as_int = arr.astype(np.int64)
    with pytest.raises(ValueError, match="disagree"):
        _unpack_array(_pack_array(as_int), arr, "t")
    # same dtype, different shape
    with pytest.raises(ValueError, match="disagree"):
        _unpack_array(_pack_array(arr.reshape(3, 2)), arr, "t")
    # truncated body
    with pytest.raises(ValueError, match="bytes"):
        _unpack_array(_pack_array(arr)[:-8], arr, "t")


def test_prefetch_config_knobs():
    import argparse

    from adapm_tpu.config import SystemOptions as SO

    p = argparse.ArgumentParser()
    SO.add_arguments(p)
    args = p.parse_args([
        "--sys.prefetch", "0", "--sys.prefetch.max_batches", "2",
        "--sys.prefetch.staging_rows", "1024",
        "--sys.prefetch.pull", "always", "--sys.plan_cache", "16"])
    opts = SO.from_args(args)
    assert opts.prefetch is False  # the kill switch
    assert opts.prefetch_max_batches == 2
    assert opts.prefetch_staging_rows == 1024
    assert opts.prefetch_pull == "always"
    assert opts.plan_cache_entries == 16
    # defaults: pipeline on
    d = p.parse_args([])
    assert SO.from_args(d).prefetch is True


def test_kill_switch_disables_pipeline(ctx):
    s = make_server(ctx, opts=SystemOptions(prefetch=False,
                                            plan_cache_entries=0))
    assert s.prefetch is None and s._plan_cache is None
    w = s.make_worker(0)
    keys = np.unique(np.array([1, 2, 3]))
    vals = _seed(w, keys)
    w.intent(keys, w.current_clock, w.current_clock + 5)
    assert (w.pull_sync(keys) == vals).all()
    s.drive_rounds()  # inline fallback
    s.shutdown()
