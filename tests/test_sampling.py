"""Sampling correctness per scheme (reference tests/test_sampling.cc): value
correctness of sampled pulls, WOR uniqueness, distribution sanity — and
sampling under concurrent serve readers (ISSUE 4 satellite)."""
import threading

import numpy as np
import pytest

from adapm_tpu import Server, SystemOptions, make_mesh

NK = 100


@pytest.fixture(scope="module")
def ctx():
    return make_mesh(4)


def make(ctx, scheme, with_replacement=True):
    opts = SystemOptions(sampling_scheme=scheme,
                         sampling_with_replacement=with_replacement,
                         sync_max_per_sec=0)
    s = Server(NK, 2, opts=opts, ctx=ctx, num_workers=4)
    ws = [s.make_worker(i) for i in range(4)]
    # values = key id so sampled pulls are checkable (reference
    # test_sampling.cc: value correctness)
    keys = np.arange(NK)
    vals = np.repeat(keys.astype(np.float32)[:, None], 2, axis=1)
    ws[0].wait(ws[0].set(keys, vals))
    s.quiesce()
    s.enable_sampling_support(
        lambda n, rng: rng.integers(0, NK, size=n))
    return s, ws


@pytest.mark.parametrize("scheme", ["naive", "preloc", "pool", "local"])
def test_sampled_values_correct(ctx, scheme):
    s, ws = make(ctx, scheme)
    w = ws[1]
    h = w.prepare_sample(20)
    if scheme == "preloc":
        s.wait_sync()  # act on the intent the scheme signalled
    keys, vals = w.pull_sample(h)
    assert len(keys) == 20
    np.testing.assert_allclose(vals[:, 0], keys.astype(np.float32))
    w.finish_sample(h)


@pytest.mark.parametrize("scheme", ["naive", "preloc", "pool", "local"])
def test_without_replacement_unique(ctx, scheme):
    s, ws = make(ctx, scheme, with_replacement=False)
    w = ws[2]
    h = w.prepare_sample(30)
    if scheme == "preloc":
        s.wait_sync()
    keys, _ = w.pull_sample(h)
    assert len(np.unique(keys)) == len(keys), "WOR produced duplicates"


def test_partial_pulls(ctx):
    """PullSample may be called repeatedly for portions of the prepared
    budget (reference PullSample(handle, keys, vals) with n < N)."""
    s, ws = make(ctx, "naive")
    w = ws[0]
    h = w.prepare_sample(10)
    k1, _ = w.pull_sample(h, 4)
    k2, _ = w.pull_sample(h, 6)
    assert len(k1) == 4 and len(k2) == 6
    with pytest.raises(AssertionError):
        w.pull_sample(h, 1)  # over budget


def test_local_scheme_stays_local(ctx):
    """The local scheme must never leave the worker's shard (that is its
    contract; distribution distortion is the documented trade-off,
    sampling.h:361-365)."""
    s, ws = make(ctx, "local")
    w = ws[3]
    before = dict(w.stats)
    h = w.prepare_sample(50)
    keys, _ = w.pull_sample(h)
    local = s.ab.is_local(keys, w.shard)
    assert local.all(), "local scheme sampled a non-local key"
    assert w.stats["pull_params_local"] - before["pull_params_local"] == 50


@pytest.mark.parametrize("scheme", ["local", "pool"])
def test_sampling_races_serve_lookups(ctx, scheme):
    """PrepareSample / pull_sample racing coalesced serve lookups on the
    same server (ISSUE 4 satellite): neither path may corrupt the other
    — sampled pulls keep returning the sampled keys' values, serve
    lookups stay bit-correct (values are key-id constants, so every
    read has exactly one right answer), and nothing hangs."""
    from adapm_tpu.serve import ServePlane
    s, ws = make(ctx, scheme)
    plane = ServePlane(s)
    errs = []
    stop = threading.Event()

    def looker(seed):
        sess = plane.session()
        rng = np.random.default_rng(seed)
        try:
            while not stop.is_set():
                k = rng.integers(0, NK, 8)
                v = sess.lookup(k)
                if not np.array_equal(v[:, 0], k.astype(np.float32)):
                    errs.append(("lookup", k, v[:, 0]))
                    return
        except BaseException as e:  # noqa: BLE001
            errs.append(e)

    threads = [threading.Thread(target=looker, args=(i,))
               for i in range(2)]
    for t in threads:
        t.start()
    w = ws[1]
    try:
        for _ in range(25):
            h = w.prepare_sample(16)
            keys, vals = w.pull_sample(h)
            assert len(keys) == 16
            # the sampling index survived the racing reads: values
            # still match the sampled keys exactly
            np.testing.assert_array_equal(vals[:, 0],
                                          keys.astype(np.float32))
            w.finish_sample(h)
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=60)
            assert not t.is_alive(), "serve looker hung"
    assert not errs, errs[:2]
    plane.close()


def test_distribution_sanity(ctx):
    """Sampled frequencies should roughly follow the app distribution for
    the exact schemes (naive/preloc/pool with reuse=1)."""
    s, ws = make(ctx, "naive")
    w = ws[0]
    h = w.prepare_sample(4000)
    keys, _ = w.pull_sample(h)
    counts = np.bincount(keys, minlength=NK)
    # uniform distribution: each key ~40 hits; allow generous slack
    assert counts.min() > 5 and counts.max() < 120
