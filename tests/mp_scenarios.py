"""Multi-process test scenarios, run as child processes by
test_multiprocess.py (one per rank, rendezvoused through the launcher env
contract). Each scenario is the multi-process twin of the reference's
self-checking test binaries (tests/test_many_key_operations.cc,
tests/test_locality_api.cc) launched by tracker/dmlc_local.py.

Usage: ADAPM_* env set by the launcher; argv[1] = scenario name.
"""
import faulthandler
import os
import sys

# hung-scenario diagnostics: dump all thread stacks and exit BEFORE the
# harness's subprocess timeout, so the test failure carries the stacks
# instead of a bare TimeoutExpired (run_mp sets the budget)
faulthandler.dump_traceback_later(
    int(os.environ.get("ADAPM_FAULT_T", "280")), exit=True)

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["ADAPM_PLATFORM"] = "cpu"
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
from xla_compat import mesh_flags  # noqa: E402

os.environ.setdefault("XLA_FLAGS", mesh_flags(2))
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_cache")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.1")
os.environ.pop("PYTHONPATH", None)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

import adapm_tpu  # noqa: E402
from adapm_tpu.base import CLOCK_MAX, LOCAL, NO_SLOT, NOT_CACHED, REMOTE  # noqa: E402
from adapm_tpu.config import SystemOptions  # noqa: E402
from adapm_tpu.parallel import control  # noqa: E402


def owned_by_proc(srv, proc, n=None):
    """Keys whose INITIAL home process is `proc` (key % (S*P) // S)."""
    keys = np.arange(srv.num_keys, dtype=np.int64)
    mine = keys[srv.glob.home_proc(keys) == proc]
    return mine if n is None else mine[:n]


def scenario_pullpush():
    """Cross-process Pull/Push/Set with exact values (the reference's
    test_many_key_operations value checks, phases 1-2)."""
    srv = adapm_tpu.setup(64, 4, opts=SystemOptions(sync_max_per_sec=0))
    rank = control.process_id()
    P = control.num_processes()
    w = srv.make_worker(0)
    keys = np.arange(64, dtype=np.int64)
    base = np.arange(64, dtype=np.float32)[:, None] * np.ones(4, np.float32)
    if rank == 0:
        ts = w.set(keys, base)
        w.wait(ts)
    srv.barrier()
    vals = w.pull_sync(keys)
    assert np.allclose(vals, base), f"pull after set mismatch\n{vals[:4]}"
    # every rank pushes +1 to every key -> each key gains +P exactly
    ts = w.push(keys, np.ones((64, 4), np.float32))
    w.wait(ts)
    srv.barrier()
    vals = w.pull_sync(keys)
    assert np.allclose(vals, base + P), f"pull after pushes\n{vals[:4]}"
    rm = srv.read_main(keys).reshape(64, 4)
    assert np.allclose(rm, base + P), "read_main disagrees"
    # locality: this worker's own keys answered locally
    mine = owned_by_proc(srv, rank)
    mine = mine[srv.ab.owner[mine] == w.shard]
    assert w.pull(mine) == LOCAL, "own-shard keys should be LOCAL"
    srv.barrier()
    srv.shutdown()
    print(f"MP-OK pullpush rank={rank}")


def scenario_intent_locality():
    """Rank 1's intent MOVES rank-0-owned keys (exclusive -> relocation);
    rank 0's competing intent then REPLICATES them back (reference
    test_locality_api semantics, cross-process)."""
    srv = adapm_tpu.setup(64, 4, opts=SystemOptions(sync_max_per_sec=0))
    rank = control.process_id()
    w = srv.make_worker(0)
    keys = owned_by_proc(srv, 0, 8)
    if rank == 0:
        ts = w.set(keys, np.full((8, 4), 7.0, np.float32))
        w.wait(ts)
    srv.barrier()
    if rank == 1:
        w.intent(keys, 0, CLOCK_MAX)
        srv.wait_sync()
        assert (srv.ab.owner[keys] >= 0).all(), \
            "exclusive intent should relocate cross-process"
        assert srv.glob.stats["relocations_in"] >= 8
        v = w.pull_sync(keys)
        assert np.allclose(v, 7.0), f"value lost in relocation: {v}"
    srv.barrier()
    if rank == 0:
        assert (srv.ab.owner[keys] == REMOTE).all(), \
            "rank 0 should have released ownership"
        assert (srv.glob.owner_hint[keys] == 1).all(), \
            "manager/owner hint should track the transfer"
        # competing intent: rank 1 still holds intent -> replicate here
        w.intent(keys, 0, CLOCK_MAX)
        srv.wait_sync()
        assert (srv.ab.cache_slot[w.shard, keys] != NO_SLOT).all(), \
            "competing intent should replicate"
        assert w.pull(keys) == LOCAL, "replicated keys should be LOCAL"
    srv.barrier()
    # rank 1 pushes on its (now owned) keys; rank 0's replicas converge
    # after the quiesce protocol (WaitSync -> Barrier -> WaitSync)
    if rank == 1:
        ts = w.push(keys, np.ones((8, 4), np.float32))
        w.wait(ts)
    w.wait_all()
    srv.wait_sync()
    srv.barrier()
    srv.wait_sync()
    srv.barrier()
    v = w.pull_sync(keys)
    assert np.allclose(v, 8.0), f"rank {rank} sees {v[:2]} after quiesce"
    srv.shutdown()
    print(f"MP-OK intent_locality rank={rank}")


def scenario_monotonic():
    """Concurrent contended pushes under intent churn with the background
    sync thread running: a worker's own applied pushes are never lost
    (monotonicity), and after quiesce the value is exactly P * R
    (reference test_many_key_operations phases 2-3 +
    test_dynamic_allocation)."""
    srv = adapm_tpu.setup(32, 2, opts=SystemOptions(sync_max_per_sec=500))
    rank = control.process_id()
    P = control.num_processes()
    srv.start_sync_thread()
    w = srv.make_worker(0)
    contended = int(owned_by_proc(srv, 0, 1)[0])
    rng = np.random.default_rng(rank)
    R = 30
    applied = 0
    kk = np.array([contended], dtype=np.int64)
    for i in range(R):
        if rng.random() < 0.4:
            w.intent(kk, w.current_clock, w.current_clock + 3)
        ts = w.push(kk, np.ones((1, 2), np.float32))
        w.wait(ts)
        applied += 1
        v = float(w.pull_sync(kk)[0, 0])
        assert v + 1e-3 >= applied, \
            f"rank {rank}: pulled {v} < own applied {applied}"
        w.advance_clock()
    w.wait_all()
    srv.wait_sync()
    srv.barrier()
    srv.wait_sync()
    srv.barrier()
    final = float(srv.read_main(kk)[0])
    assert abs(final - P * R) < 1e-3, \
        f"rank {rank}: final {final} != {P * R} (lost/duplicated updates)"
    v = float(w.pull_sync(kk)[0, 0])
    assert abs(v - P * R) < 1e-3, f"rank {rank}: pull {v} != {P * R}"
    srv.barrier()
    srv.shutdown()
    print(f"MP-OK monotonic rank={rank}")


def scenario_eventual():
    """Eventual consistency: every rank pushes then reverts on a shared key
    set under replication; after the quiesce protocol all ranks read the
    exact base everywhere (reference test_many_key_operations phase 3).
    argv[2] selects --sys.techniques (the reference's run_tests.sh
    variants: all / replication_only / relocation_only); argv[3] == "coll"
    runs the BSP collective sync data plane (--sys.collective_sync,
    parallel/collective.py) with a small bucket so the exchange loop runs
    several padded iterations."""
    from adapm_tpu.base import MgmtTechniques
    tech = MgmtTechniques(sys.argv[2]) if len(sys.argv) > 2 \
        else MgmtTechniques.ALL
    coll = len(sys.argv) > 3 and sys.argv[3] == "coll"
    srv = adapm_tpu.setup(48, 4, opts=SystemOptions(
        sync_max_per_sec=0, techniques=tech,
        collective_sync=coll, collective_bucket=16))
    rank = control.process_id()
    w = srv.make_worker(0)
    keys = np.arange(48, dtype=np.int64)
    base = np.arange(48, dtype=np.float32)[:, None] * np.ones(4, np.float32)
    if rank == 0:
        w.wait(w.set(keys, base))
    srv.barrier()
    # everyone subscribes everywhere -> full replication pressure
    w.intent(keys, 0, CLOCK_MAX)
    srv.wait_sync()
    srv.barrier()
    x = np.full((48, 4), 2.5 + rank, np.float32)
    w.wait(w.push(keys, x))
    w.wait(w.push(keys, -x))
    w.wait_all()
    srv.wait_sync()
    srv.barrier()
    srv.wait_sync()
    srv.barrier()
    v = w.pull_sync(keys)
    assert np.allclose(v, base, atol=1e-4), \
        f"rank {rank}: not restored\n{(v - base)[:4]}"
    rm = srv.read_main(keys).reshape(48, 4)
    assert np.allclose(rm, base, atol=1e-4), f"rank {rank}: main differs"
    srv.barrier()
    srv.shutdown()
    print(f"MP-OK eventual rank={rank}")


def scenario_cadence():
    """Bounded staleness with --sys.collective_cadence K (VERDICT r4 item
    3): rank 1 holds a replica of a rank-0-owned key; rank 0 pushes and
    NOBODY calls WaitSync — the replica must still observe the push
    within ~K clock advances, because every process joins a BSP exchange
    at each K-clock boundary of its run_round loop. All ranks run the
    same fixed number of steps (no early exit: an exchange needs every
    process)."""
    K = 4
    srv = adapm_tpu.setup(16, 4, opts=SystemOptions(
        sync_max_per_sec=0, collective_sync=True, collective_bucket=8,
        collective_cadence=K))
    rank = control.process_id()
    w = srv.make_worker(0)
    k = owned_by_proc(srv, 0, 1)
    if rank == 0:
        w.wait(w.set(k, np.full((1, 4), 1.0, np.float32)))
    srv.barrier()
    # every rank subscribes: the owner-local interest forces REPLICATE
    # (not relocate) for rank 1 (sync_manager.h:624-644 decision)
    w.intent(k, 0, CLOCK_MAX)
    srv.wait_sync()
    srv.barrier()
    if rank == 1:
        ok, v = w.pull_if_local(k)
        assert ok and abs(float(np.ravel(v)[0]) - 1.0) < 1e-6, \
            f"rank 1: replica not installed ({ok}, {v})"
    if rank == 0:
        w.wait(w.push(k, np.full((1, 4), 1.0, np.float32)))
    srv.barrier()  # push applied at the owner before anyone counts clocks
    seen_at = None
    for step in range(4 * K):
        w.advance_clock()
        srv.sync.run_round()
        if rank == 1 and seen_at is None:
            ok, v = w.pull_if_local(k)
            if ok and abs(float(np.ravel(v)[0]) - 2.0) < 1e-6:
                seen_at = step
    if rank == 1:
        assert seen_at is not None, \
            f"replica never observed the push in {4 * K} clocks"
        assert seen_at <= K + 1, \
            f"staleness bound violated: observed at step {seen_at} > K={K}"
        print(f"[cadence] observed after {seen_at + 1} clocks (K={K})")
    # quiesce protocol still holds in cadence mode
    srv.quiesce()
    srv.barrier()
    srv.quiesce()
    final = 2.0
    v = srv.read_main(k) if rank == 0 else None
    if rank == 0:
        assert abs(float(np.asarray(v)[0]) - final) < 1e-6
    srv.barrier()
    srv.shutdown()
    print(f"MP-OK cadence rank={rank}")


def scenario_location_caches():
    """3 processes: after a relocation 0 -> 1, rank 2's first pull routes
    via the manager (redirect) and LEARNS the owner; the second goes one
    hop. With --sys.location_caches 0 the hint table stays cold and every
    access re-routes via the manager (reference addressbook.h:114-133)."""
    caches = bool(int(sys.argv[2])) if len(sys.argv) > 2 else True
    srv = adapm_tpu.setup(12, 4, opts=SystemOptions(
        sync_max_per_sec=0, location_caches=caches))
    rank = control.process_id()
    w = srv.make_worker(0)
    k = owned_by_proc(srv, 0, 1)  # managed (and initially owned) by rank 0
    if rank == 0:
        w.wait(w.set(k, np.full((1, 4), 5.0, np.float32)))
    srv.barrier()
    if rank == 1:
        w.intent(k, 0, CLOCK_MAX)
        srv.wait_sync()
        assert (srv.ab.owner[k] >= 0).all()
    srv.barrier()
    if rank == 2:
        assert float(w.pull_sync(k)[0, 0]) == 5.0
        if caches:
            assert srv.glob.owner_hint[k[0]] == 1, \
                "location cache should have learned the relocated owner"
        else:
            assert srv.glob.owner_hint[k[0]] == NOT_CACHED, \
                "caches off: hint table must stay cold"
        # second pull: with caches, one hop straight to the owner
        before = srv.glob.stats["redirects"]
        assert float(w.pull_sync(k)[0, 0]) == 5.0
        if caches:
            assert srv.glob.stats["redirects"] == before, \
                "cached owner should not redirect"
    srv.barrier()
    if rank == 0 and caches:
        # the manager redirected rank 2's first pull instead of serving it
        assert srv.glob.stats["pulls_in"] >= 1
    srv.barrier()
    srv.shutdown()
    print(f"MP-OK location_caches rank={rank}")


def scenario_ckpt_save():
    """Phase 1 of the crash-recovery test: adapt placement (cross-process
    relocation + replication), push values, checkpoint, then 'crash'
    (exit). Phase 2 (ckpt_restore) runs as a fresh launch."""
    from adapm_tpu.utils.checkpoint import save_server
    path = sys.argv[2]
    srv = adapm_tpu.setup(48, 4, opts=SystemOptions(sync_max_per_sec=0))
    rank = control.process_id()
    w = srv.make_worker(0)
    keys = np.arange(48, dtype=np.int64)
    if rank == 0:
        w.wait(w.set(keys, np.arange(48, dtype=np.float32)[:, None]
                     * np.ones(4, np.float32)))
    srv.barrier()
    # rank 1 takes exclusive ownership of some rank-0 keys; rank 0 then
    # subscribes to two of them -> cross-process replicas exist at save
    moved = owned_by_proc(srv, 0, 6)
    if rank == 1:
        w.intent(moved, 0, CLOCK_MAX)
        srv.wait_sync()
        assert (srv.ab.owner[moved] >= 0).all()
    srv.barrier()
    if rank == 0:
        w.intent(moved[:2], 0, CLOCK_MAX)
        srv.wait_sync()
    srv.barrier()
    w.wait(w.push(keys, np.ones((48, 4), np.float32)))
    w.wait_all()
    save_server(srv, path)  # runs the distributed quiesce internally
    srv.shutdown()
    print(f"MP-OK ckpt_save rank={rank}")


def scenario_ckpt_restore():
    """Phase 2: fresh launch restores the rank shards; values, adapted
    placement, and the consistency invariant must survive."""
    from adapm_tpu.utils.checkpoint import restore_server
    path = sys.argv[2]
    srv = adapm_tpu.setup(48, 4, opts=SystemOptions(sync_max_per_sec=0))
    rank = control.process_id()
    w = srv.make_worker(0)
    restore_server(srv, path)
    keys = np.arange(48, dtype=np.int64)
    # set(k) + one push(+1) from each of the two ranks before the save
    base = (np.arange(48, dtype=np.float32)[:, None]
            * np.ones(4, np.float32)) + 2.0
    v = w.pull_sync(keys)
    assert np.allclose(v, base), f"rank {rank}: restored values wrong"
    moved = owned_by_proc(srv, 0, 6)
    if rank == 1:
        assert (srv.ab.owner[moved] >= 0).all(), \
            "adapted ownership lost in restore"
    if rank == 0:
        assert (srv.ab.owner[moved] == REMOTE).all()
        assert (srv.glob.owner_hint[moved] == 1).all(), \
            "manager table lost in restore"
        assert (srv.ab.cache_slot[w.shard, moved[:2]] != NO_SLOT).any(), \
            "cross-process replicas lost in restore"
    srv.barrier()
    # the restored manager still satisfies eventual consistency
    w.wait(w.push(keys, np.ones((48, 4), np.float32)))
    w.wait(w.push(keys, -np.ones((48, 4), np.float32)))
    w.wait_all()
    srv.wait_sync()
    srv.barrier()
    srv.wait_sync()
    srv.barrier()
    v = w.pull_sync(keys)
    assert np.allclose(v, base, atol=1e-4), f"rank {rank}: not consistent"
    srv.shutdown()
    print(f"MP-OK ckpt_restore rank={rank}")


def scenario_kge_app():
    """Full KGE app, data-parallel across processes: global worker data
    partition, cross-process parameter traffic via intent/ensure_local,
    PS-key loss/eval allreduce, distributed eval. The whole stack,
    end to end (reference: the same binary runs on every node)."""
    from adapm_tpu.apps import knowledge_graph_embeddings as kge
    args = kge.build_parser().parse_args(
        ["--dim", "8", "--neg_ratio", "2", "--synthetic_entities", "60",
         "--synthetic_relations", "4", "--synthetic_triples", "400",
         "--epochs", "6", "--batch_size", "32", "--lr", "0.2",
         "--eval_every", "6", "--eval_triples", "60",
         "--sys.sync.max_per_sec", "0"])
    result = kge.run_app(args)
    rank = control.process_id()
    assert np.isfinite(result["loss"]), result
    assert result["mrr"] > 0.12, f"rank {rank}: no learning: {result}"
    print(f"MP-OK kge_app rank={rank}")


def scenario_coll_pullpush():
    """Pull/Push data plane over device collectives (VERDICT r4 item 4;
    SURVEY's remaining ICI mapping): request keys ride the all-to-all to
    their owners, values/deltas ride back — no DCN RPC for the data.
    Exact-value checks mirror scenario_pullpush; bucket 8 forces several
    packed exchange iterations."""
    srv = adapm_tpu.setup(64, 4, opts=SystemOptions(
        sync_max_per_sec=0, collective_sync=True, collective_bucket=8))
    rank = control.process_id()
    P = control.num_processes()
    w = srv.make_worker(0)
    keys = np.arange(64, dtype=np.int64)
    base = np.arange(64, dtype=np.float32)[:, None] * np.ones(4, np.float32)
    if rank == 0:
        w.wait(w.set(keys, base))
    srv.barrier()
    # collective pull: every rank reads the whole table via the exchange
    vals = srv.collective_pull(keys).reshape(64, 4)
    assert np.allclose(vals, base), f"rank {rank}: coll pull\n{vals[:4]}"
    # collective push: every rank adds +1 everywhere -> each key gains +P
    srv.collective_push(keys, np.ones((64, 4), np.float32))
    srv.barrier()
    vals = srv.collective_pull(keys).reshape(64, 4)
    assert np.allclose(vals, base + P), \
        f"rank {rank}: after coll push\n{vals[:4]}"
    # the RPC read path agrees (same owner state, different transport)
    rm = srv.read_main(keys).reshape(64, 4)
    assert np.allclose(rm, base + P), f"rank {rank}: read_main disagrees"
    # RPC ops and the NEXT exchange must be separated by a barrier: a
    # rank already waiting inside an exchange parks its devices there,
    # and serving a peer's read_main needs a device gather — without the
    # barrier that is a cross-program device-queue deadlock (the barrier
    # itself is device-free, so pending serves drain during it); see
    # GlobalPM.collective_pull docstring
    srv.barrier()
    # empty-keys join: a rank with nothing to pull still participates
    srv.collective_pull(keys if rank == 0 else keys[:0])
    srv.barrier()
    srv.shutdown()
    print(f"MP-OK coll_pullpush rank={rank}")


def scenario_kge_eval_chunk():
    """Candidate-partitioned chunked eval across processes (VERDICT r4
    item 5): every rank scores only its OWNED entities from its local
    pool and the merged counts must match the dense-matrix path (which
    reads the full entity matrix via read_main) on the same triples."""
    from adapm_tpu.apps import knowledge_graph_embeddings as kge
    from adapm_tpu.io import kge as kgeio
    args = kge.build_parser().parse_args(
        ["--dim", "8", "--synthetic_entities", "60",
         "--synthetic_relations", "4", "--synthetic_triples", "300",
         "--eval_chunk", "16", "--sys.sync.max_per_sec", "0"])
    ds = kgeio.generate_synthetic(60, 4, 300, seed=1)
    # KgeRun joins the distributed runtime; jax.process_index() before it
    # would initialize the backend and break jax.distributed.initialize
    run = kge.KgeRun(args, ds)
    rank = control.process_id()
    run.init_model()  # random model: rank equivalence needs no training
    trip = ds.test[:60]
    pool = kge.evaluate(run, trip)   # mp pool path: counts merge inside
    assert run._pool_eval_n > 0, \
        f"rank {rank}: expected to own some entities"
    assert run._pool_eval_n < run.E, \
        f"rank {rank}: candidate partition is not a partition"
    args.eval_chunk = 0
    dense = kge.evaluate(run, trip)  # dense path: full set, global stats
    assert np.allclose(pool, dense), f"rank {rank}:\n{pool}\n{dense}"
    run.srv.barrier()
    run.srv.shutdown()
    print(f"MP-OK kge_eval_chunk rank={rank}")


def scenario_stress():
    """True-concurrency cross-process stress: 2 worker THREADS per process
    push into overlapping skewed key sets under intent churn with the
    background sync thread running; after the quiesce protocol every key's
    main copy equals the exact global push count (reference
    test_dynamic_allocation's contended exactness, scaled to threads x
    processes)."""
    import threading
    K = 48
    srv = adapm_tpu.setup(K, 2, opts=SystemOptions(sync_max_per_sec=300))
    srv.start_sync_thread()
    rank = control.process_id()
    ws = [srv.make_worker(i) for i in range(2)]
    counts = np.zeros(K, dtype=np.float64)
    counts_lock = threading.Lock()
    errs = []

    def work(wi):
        w = ws[wi]
        rng = np.random.default_rng(1000 * rank + wi)
        try:
            for i in range(25):
                keys = np.unique((K * rng.random(6) ** 2).astype(np.int64))
                if rng.random() < 0.5:
                    w.intent(keys, w.current_clock, w.current_clock + 3)
                ts = w.push(keys, np.ones((len(keys), 2), np.float32))
                w.wait(ts)
                with counts_lock:
                    counts[keys] += 1
                if rng.random() < 0.3:
                    v = w.pull_sync(keys)
                    assert np.isfinite(v).all()
                w.advance_clock()
        except Exception as e:  # noqa: BLE001
            errs.append(e)

    threads = [threading.Thread(target=work, args=(wi,)) for wi in (0, 1)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs, errs
    for w in ws:
        w.wait_all()
    srv.wait_sync()
    srv.barrier()
    srv.wait_sync()
    srv.barrier()
    total = control.allreduce(counts, "sum")
    final = srv.read_main(np.arange(K)).reshape(K, 2)
    assert np.allclose(final, total[:, None], atol=1e-3), \
        f"rank {rank}: lost/duplicated updates\n{final[:, 0] - total}"
    srv.barrier()
    srv.shutdown()
    print(f"MP-OK stress rank={rank}")


def scenario_sampling():
    """Sampling across processes (reference tests/test_sampling.cc run under
    dmlc_local with multiple nodes, run_tests.sh:21-42): every scheme draws
    keys whose main copies live on OTHER processes; sampled values must be
    exact (value[0] == key), WOR draws unique, and the Local scheme must
    only ever return process-locally-resident keys."""
    scheme = sys.argv[2]
    K = 48
    srv = adapm_tpu.setup(K, 4, opts=SystemOptions(
        sync_max_per_sec=0, sampling_scheme=scheme,
        sampling_with_replacement=False))
    rank = control.process_id()
    w = srv.make_worker(0)
    keys = np.arange(K, dtype=np.int64)
    if rank == 0:  # value[0] = key, recognizable everywhere
        vals = np.zeros((K, 4), np.float32)
        vals[:, 0] = keys
        w.wait(w.set(keys, vals))
    srv.barrier()
    srv.enable_sampling_support(
        lambda n, rng: rng.integers(0, K, n).astype(np.int64))
    h = w.prepare_sample(12)
    if scheme == "preloc":
        srv.wait_sync()  # act on the signalled intent (replicate/relocate)
    drawn, vals = w.pull_sample(h)
    assert len(drawn) == 12
    np.testing.assert_allclose(vals[:, 0], drawn.astype(np.float32))
    assert len(np.unique(drawn)) == len(drawn), "WOR produced duplicates"
    if scheme == "local":
        # Local draws only process-resident keys (reference
        # sampling.h:476-505 probes the local store)
        loc = (srv.ab.owner[drawn] >= 0) | \
            (srv.ab.cache_slot[:, drawn] >= 0).any(axis=0)
        assert loc.all(), f"local scheme drew non-resident keys {drawn[~loc]}"
    w.finish_sample(h)
    srv.barrier()
    srv.shutdown()
    print(f"MP-OK sampling rank={rank}")


def scenario_bindings():
    """The torch/numpy bindings surface works across launched processes
    (the reference's bindings example runs 4 simulated nodes —
    bindings/example.py): cross-process push/pull through the bindings
    Worker, intent-driven locality, exact sums after barrier."""
    import adapm_tpu.bindings as adapm
    adapm.setup(num_keys=32, num_threads=1)  # joins jax.distributed FIRST
    rank = control.process_id()
    P = control.num_processes()
    srv = adapm.Server(4, 32)
    w = adapm.Worker(0, srv)
    keys = np.arange(32, dtype=np.int64)
    vals = np.ones((32, 4), np.float32)
    ts = w.push(keys, vals, asynchronous=True)
    w.wait(ts)
    srv.barrier()
    out = np.zeros((32, 4), np.float32)
    w.pull(keys, out)
    assert np.allclose(out, P), out[:2]
    w.intent(keys[:4], w.current_clock, w.current_clock + 10)
    w.wait_sync()
    srv.barrier()
    w.finalize()
    srv.shutdown()
    print(f"MP-OK bindings rank={rank}")


def scenario_heartbeat():
    """Heartbeat + dead-node detection (reference van heartbeats +
    Postoffice::GetDeadNodes): rank 1 stops beating; rank 0 must report it
    dead within the age window, while a beating rank stays undetected."""
    import time
    srv = adapm_tpu.setup(16, 4, opts=SystemOptions(
        sync_max_per_sec=0, heartbeat_s=0.3))
    rank = control.process_id()
    time.sleep(1.0)  # everyone has beaten at least once
    assert srv.dead_nodes(max_age_s=5.0) == [], "live peers reported dead"
    srv.barrier()
    if rank == 1:
        control.stop_heartbeat()
    srv.barrier()
    if rank == 0:
        deadline = time.time() + 20
        while time.time() < deadline:
            dead = srv.dead_nodes(max_age_s=1.5)
            if dead == [1]:
                break
            time.sleep(0.3)
        assert dead == [1], f"rank 1 not detected dead: {dead}"
    srv.barrier()
    srv.shutdown()
    print(f"MP-OK heartbeat rank={rank}")


def scenario_elastic():
    """The documented recovery loop (docs/failure_handling.md) end to end,
    driven by the LAUNCHER KEEPALIVE rather than a scripted second launch:
    train -> checkpoint -> crash with exit code 254 mid-epoch (work after
    the checkpoint is lost) -> keepalive restarts the ranks with the same
    env -> restore_server -> values, adapted placement, and the
    consistency invariant hold (reference dmlc_local.py:15-25 restart
    contract + this repo's whole-manager checkpoints)."""
    from adapm_tpu.utils.checkpoint import restore_server, save_server
    path = sys.argv[2]
    srv = adapm_tpu.setup(48, 4, opts=SystemOptions(sync_max_per_sec=0))
    rank = control.process_id()
    P = control.num_processes()
    marker = f"{path}.attempt.rank{rank}"
    first_attempt = not os.path.exists(marker)
    w = srv.make_worker(0)
    keys = np.arange(48, dtype=np.int64)
    if first_attempt:
        open(marker, "w").write("1")
        if rank == 0:
            w.wait(w.set(keys, np.ones((48, 4), np.float32)))
        srv.barrier()
        # adapt placement so the restore must carry it: rank 1 takes
        # ownership of rank-0 keys before the checkpoint
        moved = owned_by_proc(srv, 0, 4)
        if rank == 1:
            w.intent(moved, 0, CLOCK_MAX)
            srv.wait_sync()
        srv.barrier()
        w.wait(w.push(keys, np.ones((48, 4), np.float32)))
        w.wait_all()
        save_server(srv, path)  # the per-epoch checkpoint
        # mid-epoch work after the checkpoint: lost in the crash
        w.wait(w.push(keys, np.full((48, 4), 7.0, np.float32)))
        w.wait_all()
        srv.barrier()  # both ranks reach the crash point
        # crash: no shutdown, no coordinator teardown — the keepalive
        # contract restarts this rank with the same rank/env
        sys.stdout.flush()
        os._exit(254)
    # restarted attempt: recover from the checkpoint
    restore_server(srv, path)
    base = np.full((48, 4), 1.0 + P, np.float32)  # set(1) + P pushes(+1)
    v = w.pull_sync(keys)
    assert np.allclose(v, base), \
        f"rank {rank}: restored values wrong (lost work resurrected?)\n{v[:2]}"
    moved = owned_by_proc(srv, 0, 4)
    if rank == 1:
        assert (srv.ab.owner[moved] >= 0).all(), "adapted ownership lost"
    if rank == 0:
        assert (srv.ab.owner[moved] == REMOTE).all(), "relocation lost"
    srv.barrier()
    # the restored manager still satisfies eventual consistency
    w.wait(w.push(keys, np.ones((48, 4), np.float32)))
    w.wait(w.push(keys, -np.ones((48, 4), np.float32)))
    w.wait_all()
    srv.wait_sync()
    srv.barrier()
    srv.wait_sync()
    srv.barrier()
    v = w.pull_sync(keys)
    assert np.allclose(v, base, atol=1e-4), f"rank {rank}: not consistent"
    srv.shutdown()
    open(f"{path}.done.rank{rank}", "w").write("1")
    print(f"MP-OK elastic rank={rank}")


SCENARIOS = {
    "pullpush": scenario_pullpush,
    "elastic": scenario_elastic,
    "intent_locality": scenario_intent_locality,
    "monotonic": scenario_monotonic,
    "eventual": scenario_eventual,
    "cadence": scenario_cadence,
    "kge_eval_chunk": scenario_kge_eval_chunk,
    "coll_pullpush": scenario_coll_pullpush,
    "location_caches": scenario_location_caches,
    "ckpt_save": scenario_ckpt_save,
    "ckpt_restore": scenario_ckpt_restore,
    "heartbeat": scenario_heartbeat,
    "sampling": scenario_sampling,
    "kge_app": scenario_kge_app,
    "bindings": scenario_bindings,
    "stress": scenario_stress,
}

if __name__ == "__main__":
    SCENARIOS[sys.argv[1]]()
