"""Learned adaptive-policy plane (ISSUE 18 tentpole).

Tier-1 coverage for adapm_tpu/policy/ + the replay promotion gate:

  - the off pin: no --sys.policy.file (default) => no PolicyPlane
    object, zero policy.* registry names, empty policy snapshot
    section (schema v14) — the r7 skip-wrapper shape
    (scripts/metrics_overhead_check.py pins the same thing in CI);
  - training: byte-deterministic re-train from the same traces, a
    real logistic fit on the tier plane, truncated rows excluded and
    counted loudly;
  - artifact hygiene: missing file, flipped byte, and wrong-format
    input each raise the NAMED PolicyError during verification; a
    feature-spec mismatch (stale artifact vs this build's
    PLANE_FEATURES contract) is rejected at load;
  - the OBSERVER-EFFECT pin: a shadow-mode replay folds
    agree/disagree verdicts yet reads bit-identically to the plain
    heuristic replay — shadow scores, never steers;
  - the VALUE-PRESERVATION pin: the learned tier policy applies real
    vetoes during replay and STILL reproduces the heuristic
    `reads_digest` bitwise, ranking no worse on tier regret — a
    policy changes what/when, never values (the full strict-win gate
    runs in scripts/policy_gate_check.py on a bigger storm);
  - live mechanics: a server built with --sys.policy.* consults the
    models on the real decision sites and carries the policy section
    in its snapshot.
"""
import numpy as np
import pytest

from adapm_tpu import Server, SystemOptions, make_mesh
from adapm_tpu.policy import (PLANE_FEATURES, PlaneModel, PolicyError,
                              load_policy, train_policy)
from adapm_tpu.replay import ReplayEngine, load_wtrace, rank_candidates

NK = 256
VL = 4


@pytest.fixture(scope="module")
def ctx():
    return make_mesh(8)


def _storm(ctx, out_dir, tag, steps=40, tier_rows=8):
    """Seeded zipf pull/push/intent storm against a starved hot pool
    (tier regret has signal); returns (dtrace, wtrace) paths after
    shutdown."""
    dpath = str(out_dir / f"{tag}.dtrace")
    wpath = str(out_dir / f"{tag}.wtrace")
    opts = SystemOptions(sync_max_per_sec=0, prefetch=False,
                         tier=True, tier_hot_rows=tier_rows,
                         trace_decisions=dpath, trace_workload=wpath)
    srv = Server(NK, VL, opts=opts, ctx=ctx, num_workers=2)
    w0, w1 = srv.make_worker(0), srv.make_worker(1)
    w0.wait(w0.set(np.arange(NK), np.ones((NK, VL), np.float32)))
    rng = np.random.default_rng(17)
    for i in range(steps):
        w = w0 if i % 2 == 0 else w1
        ks = np.unique((NK * rng.random(16) ** 6.0)
                       .astype(np.int64).clip(0, NK - 1))
        w.pull_sync(ks)
        w.wait(w.push(ks, np.ones((len(ks), VL), np.float32)))
        if i % 4 == 0:
            w.intent(ks, w.current_clock, w.current_clock + 4)
            w.advance_clock()
        srv.wait_sync()
    srv.shutdown()
    return dpath, wpath


@pytest.fixture(scope="module")
def trained(ctx, tmp_path_factory):
    """One storm + one training, shared by the replay/load tests:
    (dtrace, wtrace, policy_path, bundle)."""
    out = tmp_path_factory.mktemp("policy")
    dpath, wpath = _storm(ctx, out, "cap")
    ppath = str(out / "policy.json")
    bundle = train_policy(dpath, wpath, out_path=ppath)
    return dpath, wpath, ppath, bundle


# ---------------------------------------------------------------------------
# the off pin (metrics_overhead_check.py pins the same thing in CI)
# ---------------------------------------------------------------------------


def test_policy_off_pin(ctx):
    """Default server: no PolicyPlane, zero policy.* names, empty
    policy snapshot section — the r7 skip-wrapper shape."""
    srv = Server(NK, VL, opts=SystemOptions(sync_max_per_sec=0),
                 ctx=ctx)
    w = srv.make_worker(0)
    w.wait(w.set(np.arange(NK), np.ones((NK, VL), np.float32)))
    w.pull_sync(np.arange(8))
    assert srv.policy is None
    assert not [n for n in srv.obs.names() if n.startswith("policy.")]
    snap = srv.metrics_snapshot()
    assert snap["schema_version"] == 16
    assert snap["policy"] == {}
    srv.shutdown()


# ---------------------------------------------------------------------------
# training
# ---------------------------------------------------------------------------


def test_train_is_byte_deterministic(trained, tmp_path):
    """Re-training from the same traces writes a byte-identical
    artifact (no RNG, no timestamps), the thrashing-pool tier plane
    gets a real logistic fit, and truncated rows are excluded from the
    fit but counted loudly in the meta."""
    dpath, wpath, ppath, bundle = trained
    p2 = str(tmp_path / "again.json")
    train_policy(dpath, wpath, out_path=p2)
    with open(ppath, "rb") as f1, open(p2, "rb") as f2:
        assert f1.read() == f2.read()
    tm = bundle.meta["train"]
    assert set(tm) == set(PLANE_FEATURES)
    assert tm["tier"]["fit"] == "logistic", tm
    # default truncated_weight=0.0: forced-close rows never train
    assert bundle.meta["truncated_weight"] == 0.0
    for plane in tm:
        assert tm[plane]["truncated_rows"] >= 0
    assert bundle.meta["truncated_rows"] == sum(
        tm[p]["truncated_rows"] for p in tm)
    # up-weighting forced outcomes is rejected — they are not labels
    with pytest.raises(ValueError, match="truncated_weight"):
        train_policy(dpath, wpath, truncated_weight=1.5)


# ---------------------------------------------------------------------------
# artifact hygiene
# ---------------------------------------------------------------------------


def test_artifact_corruption_raises_named_error(trained, tmp_path):
    """Missing file, flipped body byte, and a wrong-format trace each
    raise PolicyError during verification — before anything consults
    a model."""
    dpath, _, ppath, _ = trained
    with pytest.raises(PolicyError):
        load_policy(str(tmp_path / "nope.json"))
    with open(ppath, "rb") as f:
        raw = bytearray(f.read())
    raw[-10] ^= 0x40  # flip one body byte: sha256 mismatch
    bad = tmp_path / "flipped.json"
    bad.write_bytes(bytes(raw))
    with pytest.raises(PolicyError):
        load_policy(str(bad))
    # a verified file of the WRONG format is rejected by name
    with pytest.raises(PolicyError):
        load_policy(dpath)


def test_feature_spec_mismatch_rejected(trained):
    """An artifact trained against a different PLANE_FEATURES contract
    (reordered columns, wrong width) must not load — silent skew
    between capture and inference is the failure mode features.py
    exists to prevent."""
    _, _, ppath, _ = trained
    d = load_policy(ppath).planes["tier"].to_dict()
    d["features"] = list(reversed(d["features"]))
    with pytest.raises(PolicyError, match="feature"):
        PlaneModel.from_dict(d)
    with pytest.raises(PolicyError):
        PlaneModel("tier", [0.0], [1.0], [0.0], 0.0)  # wrong width
    with pytest.raises(PolicyError, match="plane"):
        PlaneModel.constant("parking", 0.5)  # unknown plane


# ---------------------------------------------------------------------------
# observer-effect + value-preservation pins (deterministic replay)
# ---------------------------------------------------------------------------


def test_shadow_mode_scores_without_steering(trained):
    """Shadow replay folds agree/disagree verdicts, yet the reads
    digest is bit-identical to the plain heuristic replay — shadow
    scores the model, never applies it."""
    _, wpath, ppath, _ = trained
    tr = load_wtrace(wpath)
    base = ReplayEngine(tr, seed=3, speed=100.0).run()
    sh = ReplayEngine(tr, overrides={"policy_file": ppath,
                                     "policy_shadow": True},
                      seed=3, speed=100.0).run(include_snapshot=True)
    assert sh["reads_digest"] == base["reads_digest"]
    pol = sh["snapshot"]["policy"]
    assert pol["shadow"] is True
    consults = pol["shadow_agree"] + pol["shadow_disagree"]
    assert consults > 0 and pol["consults_total"] == consults
    # nothing applied, ever, in shadow mode
    assert pol["applied_total"] == 0


def test_learned_policy_preserves_reads_and_ranks_on_regret(trained):
    """The promotion-gate shape: heuristic vs learned-tier replay A/B
    with the metrics-only decision recorder attached. The learned
    candidate must apply real vetoes, fold a tier regret no worse than
    the heuristic's, and reproduce the heuristic reads digest BITWISE
    (the strict-win gate on a bigger storm is
    scripts/policy_gate_check.py)."""
    _, wpath, ppath, _ = trained
    tr = load_wtrace(wpath)
    art = rank_candidates(
        tr,
        {"heuristic": {},
         "learned": {"policy_tier": "learned", "policy_file": ppath}},
        objective="regret_rate_tier", seed=5, speed=100.0,
        score_decisions=True)
    heur = art["candidates"]["heuristic"]
    lrn = art["candidates"]["learned"]
    # value preservation: a policy changes what/when, never values
    assert lrn["reads_digest"] == heur["reads_digest"]
    r_h = heur["score"]["regret_rate_tier"]
    r_l = lrn["score"]["regret_rate_tier"]
    assert r_h is not None and r_l is not None
    assert r_l <= r_h, (r_l, r_h)
    # determinism: the same learned replay re-runs bit-identically
    redo = ReplayEngine(tr, overrides={"policy_tier": "learned",
                                       "policy_file": ppath},
                        seed=5, speed=100.0,
                        score_decisions=True).run(include_snapshot=True)
    assert redo["reads_digest"] == lrn["reads_digest"]
    pol = redo["snapshot"]["policy"]
    assert pol["mode.tier"] == "learned"
    assert pol["consults.tier"] > 0
    # the veto path genuinely ran (applied, or guard-refused)
    assert pol["applied_total"] + pol["guard_vetoes_total"] > 0


# ---------------------------------------------------------------------------
# live mechanics
# ---------------------------------------------------------------------------


def test_live_server_consults_policy_and_snapshots(ctx, trained,
                                                   tmp_path):
    """A live server with --sys.policy.file + learned tier consults
    the model at the real decision sites, registers the policy.*
    counters, and carries the plane detail in its snapshot."""
    _, _, ppath, bundle = trained
    opts = SystemOptions(sync_max_per_sec=0, prefetch=False,
                         tier=True, tier_hot_rows=8,
                         policy_file=ppath, policy_tier="learned")
    srv = Server(NK, VL, opts=opts, ctx=ctx, num_workers=1)
    assert srv.policy is not None
    assert srv.policy.active("tier")
    assert not srv.policy.active("serve")  # heuristic mode, no shadow
    w = srv.make_worker(0)
    w.wait(w.set(np.arange(NK), np.ones((NK, VL), np.float32)))
    rng = np.random.default_rng(23)
    for i in range(12):
        ks = np.unique((NK * rng.random(16) ** 6.0)
                       .astype(np.int64).clip(0, NK - 1))
        w.pull_sync(ks)
        w.wait(w.push(ks, np.ones((len(ks), VL), np.float32)))
        w.advance_clock()
        srv.wait_sync()
    assert [n for n in srv.obs.names() if n.startswith("policy.")]
    snap = srv.metrics_snapshot()
    pol = snap["policy"]
    assert pol["file"] == ppath
    assert pol["mode.tier"] == "learned"
    assert pol["planes_loaded"] == sorted(bundle.planes)
    assert pol["consults.tier"] > 0
    assert pol["consults_total"] >= pol["consults.tier"]
    srv.shutdown()
