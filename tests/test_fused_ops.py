"""Fused step correctness: against a numpy re-implementation, and PM-semantics
preservation (updates through replicas flow back to main copies on sync).

Reference invariant source: the fused step is a batched Push, so the same
additive-merge guarantees as test_consistency apply (handle.h:404-415).
"""
import numpy as np
import pytest

import adapm_tpu
from adapm_tpu.base import MgmtTechniques
from adapm_tpu.config import SystemOptions
from adapm_tpu.models import (complex_score, make_kge_loss, make_mf_loss,
                              sgns_loss)
from adapm_tpu.ops import FusedStepRunner


def _server(num_keys, val_len, **opts):
    return adapm_tpu.setup(num_keys, val_len,
                           opts=SystemOptions(**opts))


def test_complex_score_matches_numpy(rng):
    d = 4
    s, r, o = (rng.normal(size=(5, 2 * d)).astype(np.float32)
               for _ in range(3))
    got = np.asarray(complex_score(s, r, o))
    sc = s[:, :d] + 1j * s[:, d:]
    rc = r[:, :d] + 1j * r[:, d:]
    oc = o[:, :d] + 1j * o[:, d:]
    want = np.real((sc * rc * np.conj(oc)).sum(-1))
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_fused_mf_step_matches_numpy_adagrad(rng):
    rank, nrow, ncol = 4, 6, 5
    num_keys = nrow + ncol
    srv = _server(num_keys, 2 * rank)
    w = srv.make_worker(0)

    init = rng.normal(size=(num_keys, 2 * rank)).astype(np.float32) * 0.1
    init[:, rank:] = 0.01  # adagrad accumulators start small-positive
    w.set(np.arange(num_keys), init)
    srv.block()

    i = np.array([0, 1, 2, 3], dtype=np.int64)
    j = np.array([0, 1, 0, 4], dtype=np.int64) + nrow
    x = rng.normal(size=4).astype(np.float32)
    lr, eps = 0.1, 1e-10

    runner = FusedStepRunner(srv, make_mf_loss(l2=0.01),
                             role_class={"w": 0, "h": 0},
                             role_dim={"w": rank, "h": rank})
    runner({"w": i, "h": j}, x, lr, eps, shard=w.shard)
    srv.block()

    # numpy reference with the *batched* semantics the fused step defines:
    # every occurrence's update is computed against the pre-step accumulator,
    # then all updates (and grad^2 increments) merge additively — duplicate
    # keys accumulate, exactly like concurrent reference Pushes
    # (handle.h:404-415).
    W = init[:nrow, :rank].copy()
    H = init[nrow:, :rank].copy()
    Wa = init[:nrow, rank:].copy()
    Ha = init[nrow:, rank:].copy()
    B = len(i)
    pred = (W[i] * H[j - nrow]).sum(-1)
    gw = (2 * (pred - x)[:, None] * H[j - nrow] + 2 * 0.01 * W[i]) / B
    gh = (2 * (pred - x)[:, None] * W[i] + 2 * 0.01 * H[j - nrow]) / B
    dW, dWa = np.zeros_like(W), np.zeros_like(Wa)
    dH, dHa = np.zeros_like(H), np.zeros_like(Ha)
    for b in range(B):
        dW[i[b]] += -lr * gw[b] / np.sqrt(Wa[i[b]] + gw[b] ** 2 + eps)
        dWa[i[b]] += gw[b] ** 2
        dH[j[b] - nrow] += -lr * gh[b] / np.sqrt(Ha[j[b] - nrow]
                                                 + gh[b] ** 2 + eps)
        dHa[j[b] - nrow] += gh[b] ** 2
    W += dW; Wa += dWa; H += dH; Ha += dHa

    got = srv.read_main(np.arange(num_keys)).reshape(num_keys, 2 * rank)
    want = np.concatenate(
        [np.concatenate([W, Wa], -1), np.concatenate([H, Ha], -1)])
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-6)
    srv.shutdown()


def test_fused_mf_training_decreases_loss(rng):
    rank, nrow, ncol = 8, 16, 12
    srv = _server(nrow + ncol, 2 * rank)
    w = srv.make_worker(0)
    init = rng.normal(size=(nrow + ncol, 2 * rank)).astype(np.float32) * 0.1
    init[:, rank:] = 1e-6
    w.set(np.arange(nrow + ncol), init)

    Wt = rng.normal(size=(nrow, rank))
    Ht = rng.normal(size=(ncol, rank))
    i = rng.integers(0, nrow, 64).astype(np.int64)
    j = rng.integers(0, ncol, 64).astype(np.int64)
    x = (Wt[i] * Ht[j]).sum(-1).astype(np.float32)

    runner = FusedStepRunner(srv, make_mf_loss(),
                             role_class={"w": 0, "h": 0},
                             role_dim={"w": rank, "h": rank})
    losses = [float(runner({"w": i, "h": j + nrow}, x, 0.5))
              for _ in range(30)]
    assert losses[-1] < 0.5 * losses[0]
    srv.shutdown()


def test_fused_updates_flow_through_replicas(rng):
    """A fused step whose routes hit replica rows must land in the delta pool
    and reach the main copy after a sync round (batched-Push semantics)."""
    rank = 4
    srv = _server(16, 2 * rank, techniques=MgmtTechniques.REPLICATION_ONLY,
                  cache_slots_per_shard=16)
    workers = [srv.make_worker(i) for i in range(srv.num_shards)]
    w0 = workers[0]
    init = np.full((16, 2 * rank), 1.0, dtype=np.float32)
    w0.set(np.arange(16), init)
    srv.block()

    # worker 0 declares intent on keys owned elsewhere -> replicas on shard 0
    remote = np.array([k for k in range(16)
                       if srv.ab.owner[k] != w0.shard][:4], dtype=np.int64)
    w0.intent(remote, 0, 100)
    srv.sync.run_round(force_intents=True, all_channels=True)
    assert srv.ab.has_replica(remote, w0.shard).all()

    keys = remote
    x = np.zeros(len(keys) // 2, dtype=np.float32)
    runner = FusedStepRunner(srv, make_mf_loss(),
                             role_class={"w": 0, "h": 0},
                             role_dim={"w": rank, "h": rank})
    runner({"w": keys[: len(keys) // 2], "h": keys[len(keys) // 2:]},
           x, 0.1, shard=w0.shard)
    assert runner.n_remote == 0  # all served from replicas

    # local read-your-writes via replica (cache+delta)
    local_view = w0.pull_sync(keys)
    assert not np.allclose(local_view[:, :rank], 1.0)

    # after quiesce the main copies converge to the local view
    srv.quiesce()
    main_view = srv.read_main(keys).reshape(len(keys), 2 * rank)
    np.testing.assert_allclose(main_view, local_view, rtol=1e-5)
    srv.shutdown()


def test_kge_and_sgns_losses_train(rng):
    d = 4
    # entities+relations same class (2d emb + 2d acc)
    srv = _server(24, 4 * d)
    w = srv.make_worker(0)
    init = rng.normal(size=(24, 4 * d)).astype(np.float32) * 0.1
    init[:, 2 * d:] = 1e-6
    w.set(np.arange(24), init)

    runner = FusedStepRunner(
        srv, make_kge_loss("complex"),
        role_class={"s": 0, "r": 0, "o": 0, "neg": 0},
        role_dim={r: 2 * d for r in ("s", "r", "o", "neg")})
    s = rng.integers(0, 16, 8).astype(np.int64)
    r = rng.integers(16, 24, 8).astype(np.int64)
    o = rng.integers(0, 16, 8).astype(np.int64)
    neg = rng.integers(0, 16, (8, 3)).astype(np.int64)
    losses = [float(runner({"s": s, "r": r, "o": o, "neg": neg}, None, 0.3))
              for _ in range(20)]
    assert losses[-1] < losses[0]
    srv.shutdown()

    srv2 = _server(32, 2 * d)
    w2 = srv2.make_worker(0)
    init2 = rng.normal(size=(32, 2 * d)).astype(np.float32) * 0.1
    init2[:, d:] = 1e-6
    w2.set(np.arange(32), init2)
    runner2 = FusedStepRunner(
        srv2, sgns_loss,
        role_class={"center": 0, "ctx": 0, "neg": 0},
        role_dim={r: d for r in ("center", "ctx", "neg")})
    c = rng.integers(0, 16, 8).astype(np.int64) * 2
    ctx = rng.integers(0, 16, 8).astype(np.int64) * 2 + 1
    neg2 = rng.integers(0, 16, (8, 3)).astype(np.int64) * 2 + 1
    losses2 = [float(runner2({"center": c, "ctx": ctx, "neg": neg2},
                             None, 0.3)) for _ in range(20)]
    assert losses2[-1] < losses2[0]
    srv2.shutdown()
