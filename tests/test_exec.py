"""Unified async executor (ISSUE 6 tentpole; adapm_tpu/exec).

Two layers of coverage:

 1. Executor mechanics — per-stream FIFO, free cross-stream
    interleaving, `after` edges, coalescing, delayed eligibility,
    error containment, idempotent close with cancellation, drain,
    the serialized single-stream fallback, and the overlap accounting.

 2. THE enqueue-order property test — a randomized interleaving of all
    five subsystem producers (fused-path writes, prefetch
    intents/pumped rounds, tier promotion/demotion churn, serve
    lookups, sync rounds) driven IDENTICALLY through a default
    (overlapped, multi-stream) server and a --sys.exec.single_stream
    (serialized shadow) server: every read — whole-table read_main,
    duplicate-heavy pulls, and served lookups — must be bit-identical
    between the two at every step and after quiesce. Stream
    interleaving is a scheduling freedom, never a value-visible one.
"""
import threading
import time

import numpy as np
import pytest

import adapm_tpu
from adapm_tpu.config import SystemOptions
from adapm_tpu.exec import AsyncExecutor, dispatch_gate

E = 384
L = 8


# ---------------------------------------------------------------------------
# 1. executor mechanics
# ---------------------------------------------------------------------------


def test_stream_fifo_order():
    ex = AsyncExecutor(workers=4)
    order = []
    lock = threading.Lock()

    def mk(i):
        def fn():
            with lock:
                order.append(i)
        return fn

    last = None
    for i in range(50):
        last = ex.submit("s", mk(i))
    assert last.wait(10)
    assert order == list(range(50)), "stream order must be submission order"
    ex.close()


def test_streams_interleave_and_after_edges():
    ex = AsyncExecutor(workers=4)
    events = []
    lock = threading.Lock()
    gate_a = threading.Event()

    def slow_a():
        gate_a.wait(10)
        with lock:
            events.append("a")

    def fast_b():
        with lock:
            events.append("b")

    ca = ex.submit("a", slow_a)
    cb = ex.submit("b", fast_b)
    assert cb.wait(10)           # b finishes while a is still blocked:
    assert not ca.done()         # distinct streams interleave freely
    gate_a.set()
    assert ca.wait(10)
    # after= orders across streams without any lock held
    c1 = ex.submit("a", lambda: events.append("first"))
    c2 = ex.submit("b", lambda: events.append("second"), after=[c1])
    assert c2.wait(10)
    assert events.index("first") < events.index("second")
    ex.close()


def test_coalesce_key_absorbs_queued_duplicates():
    ex = AsyncExecutor(workers=1)
    block = threading.Event()
    ran = []
    ex.submit("s", lambda: block.wait(10))          # occupy the stream
    c1 = ex.submit("s", lambda: ran.append(1), coalesce_key="k")
    c2 = ex.submit("s", lambda: ran.append(2), coalesce_key="k")
    assert c2 is c1, "queued same-key program is reused, not duplicated"
    block.set()
    assert c1.wait(10)
    assert ran == [1]
    ex.close()


def test_delay_and_coalesce_tightening():
    ex = AsyncExecutor(workers=2)
    t0 = time.monotonic()
    c = ex.submit("s", lambda: time.monotonic(), delay=0.15)
    done_at = c.result(10)
    assert done_at - t0 >= 0.14, "delayed program ran before eligibility"
    # a later zero-delay submission with the same key tightens the
    # existing program's eligibility to now
    c1 = ex.submit("s", lambda: "x", coalesce_key="k", delay=30.0)
    c2 = ex.submit("s", lambda: "y", coalesce_key="k", delay=0.0)
    assert c2 is c1
    assert c1.wait(10), "tightened program must run promptly, not in 30s"
    ex.close()


def test_error_containment_and_result():
    ex = AsyncExecutor(workers=2)

    def boom():
        raise ValueError("program failed")

    c = ex.submit("s", boom)
    with pytest.raises(ValueError, match="program failed"):
        c.result(10)
    # the pool survives a failing program
    assert ex.submit("s", lambda: 41 + 1).result(10) == 42
    ex.close()


def test_close_idempotent_cancels_queued():
    ex = AsyncExecutor(workers=1)
    block = threading.Event()
    ex.submit("s", lambda: block.wait(10))
    queued = ex.submit("s", lambda: "never")
    block.set()
    ex.close()
    ex.close()  # idempotent
    assert ex.closed
    # a queued program either ran or was cancelled — and after close,
    # late submissions come back pre-cancelled instead of crashing
    assert queued.done()
    late = ex.submit("s", lambda: 1)
    assert late.done() and late.cancelled
    assert ex.live_streams() == []


def test_drain_and_queue_depth():
    ex = AsyncExecutor(workers=2)
    started = threading.Event()
    block = threading.Event()

    def blocker():
        started.set()
        block.wait(10)

    ex.submit("s", blocker)
    assert started.wait(10)              # the runner has DEQUEUED it
    ex.submit("s", lambda: None)
    assert ex.queue_depth("s") == 1      # one queued behind the runner
    assert not ex.drain("s", timeout=0.2)
    block.set()
    assert ex.drain("s", timeout=10)
    assert ex.queue_depth() == 0
    ex.close()


def test_single_stream_serializes_everything():
    ex = AsyncExecutor(workers=4, single_stream=True)
    assert ex.max_workers == 1
    order = []
    lock = threading.Lock()

    def mk(tag):
        def fn():
            with lock:
                order.append(tag)
            time.sleep(0.002)
        return fn

    cs = []
    for i in range(10):
        cs.append(ex.submit(f"stream{i % 3}", mk(i)))
    for c in cs:
        assert c.wait(10)
    assert order == list(range(10)), \
        "single-stream fallback must run ready programs strictly " \
        "oldest-first across streams (one worker)"
    assert ex.stats()["overlap_fraction"] == 0.0
    ex.close()


def test_overlap_accounting_sees_concurrent_streams():
    ex = AsyncExecutor(workers=4)
    b1, b2 = threading.Event(), threading.Event()
    c1 = ex.submit("a", lambda: b1.wait(10))
    c2 = ex.submit("b", lambda: b2.wait(10))
    time.sleep(0.15)             # both streams demonstrably busy
    b1.set(), b2.set()
    assert c1.wait(10) and c2.wait(10)
    st = ex.stats()
    assert st["overlap_s"] > 0.1, "two busy streams must count as overlap"
    assert 0.0 < st["overlap_fraction"] <= 1.0
    ex.close()


def test_single_stream_keeps_stream_identity():
    """Streams are NOT collapsed in the serialized fallback: a drain of
    one subsystem's stream must not wait on another subsystem's
    self-rescheduling program, and a delayed head blocks only its own
    stream (regression: the collapsed design made drain('serve') wait
    on a perpetually-resubmitting sync tick — every single-stream
    shutdown stalled its full timeout and raised)."""
    ex = AsyncExecutor(workers=4, single_stream=True)
    stop = threading.Event()

    def tick():
        if not stop.is_set():
            ex.submit("sync", tick, delay=0.01)  # self-rescheduling

    ex.submit("sync", tick)
    # a delayed program parked on another stream must not gate this one
    ex.submit("prefetch", lambda: None, delay=30.0)
    ran = ex.submit("serve", lambda: "served")
    assert ran.result(5) == "served"
    t0 = time.monotonic()
    assert ex.drain("serve", timeout=5), \
        "draining 'serve' must not wait on the sync stream"
    assert time.monotonic() - t0 < 2.0
    stop.set()
    ex.close()


def test_single_stream_server_shutdown_with_sync_and_serve(rng):
    """End-to-end single-stream regression: a --sys.exec.single_stream
    server running the background sync rounds AND a serve plane AND
    tier maintenance shuts down promptly (the per-subsystem drains in
    stop()/close() target their own streams)."""
    from adapm_tpu.serve import ServePlane
    srv = _mk_server(True)
    w = srv.make_worker(0)
    w.set(np.arange(E), rng.normal(size=(E, L)).astype(np.float32))
    plane = ServePlane(srv)
    sess = plane.session()
    srv.start_sync_thread()
    srv.tier.engine.kick()
    assert np.asarray(sess.lookup(np.arange(8))).shape == (8, L)
    t0 = time.monotonic()
    srv.shutdown()
    assert time.monotonic() - t0 < 25.0, \
        "single-stream shutdown stalled on a cross-subsystem drain"
    assert srv.exec.live_streams() == []


def test_dispatch_gate_is_reentrant_process_wide():
    g1, g2 = dispatch_gate(), dispatch_gate()
    assert g1 is g2, "one gate per process"
    with g1:
        with g2:     # reentrant: nested store ops must not self-deadlock
            pass


# ---------------------------------------------------------------------------
# 2. THE enqueue-order property test: five producers, overlapped vs
#    serialized shadow, bit-identical reads
# ---------------------------------------------------------------------------


def _mk_server(single_stream: bool):
    opts = SystemOptions(sync_max_per_sec=0, prefetch=True,
                         prefetch_pull="off",  # staging value-invisible
                         # anyway; off keeps the pumped-round count (the
                         # value-visible part) exactly 1 per pump on
                         # both servers
                         tier=True, tier_hot_rows=16,
                         # runtime lock-order sentinel (ISSUE 11): the
                         # five-producer storm is exactly the
                         # interleaving the acquisition-graph checker
                         # exists for — a cycle or gate-leaf violation
                         # fails here deterministically
                         lint_lockorder=True,
                         exec_single_stream=single_stream)
    return adapm_tpu.setup(E, L, opts=opts)


def test_enqueue_order_property_five_producers(rng):
    from adapm_tpu.serve import ServePlane
    srv = _mk_server(False)          # overlapped default
    ref = _mk_server(True)           # serialized shadow
    w, wr = srv.make_worker(0), ref.make_worker(0)
    plane, rplane = ServePlane(srv), ServePlane(ref)
    sess, rsess = plane.session(), rplane.session()
    vals = rng.normal(size=(E, L)).astype(np.float32)
    for ww in (w, wr):
        ww.set(np.arange(E), vals)
    keys = np.arange(E)

    def settle():
        # drain the value-visible background work (pumped planner
        # rounds) so both servers compare at the same logical point;
        # tier maintenance and staging stay free-running — they are
        # value-invisible by contract
        srv.prefetch.flush()
        ref.prefetch.flush()

    for step in range(40):
        op = int(rng.integers(0, 6))
        if op == 0:      # fused-path writes (producer 1: main stream)
            ks = rng.integers(0, E, 24)
            v = rng.normal(size=(24, L)).astype(np.float32)
            w.push(ks, v)
            wr.push(ks, v)
        elif op == 1:    # prefetch pipeline (producer 2): intent + one
            #                pumped planner round on the exec stream
            ks = rng.choice(keys[srv.ab.owner[keys] != w.shard], 16,
                            replace=False)
            end = int(w.current_clock + rng.integers(1, 4))
            w.intent(ks, w.current_clock, end)
            wr.intent(ks, wr.current_clock, end)
            srv.drive_rounds(1)
            ref.drive_rounds(1)
            settle()
        elif op == 2:    # tier maintenance (producer 3): churn + kick
            ks = rng.choice(E, 24, replace=False)
            srv.tier.promote_keys(ks)
            ref.tier.promote_keys(ks)
            srv.tier.demote_keys(ks[:12])
            ref.tier.demote_keys(ks[:12])
            srv.tier.engine.kick()
            ref.tier.engine.kick()
        elif op == 3:    # serve plane (producer 4): coalesced lookups
            ks = rng.integers(0, E, 20)
            got = sess.lookup(ks)
            expect = rsess.lookup(ks)
            assert np.array_equal(np.asarray(got), np.asarray(expect)), \
                f"step {step}: served lookup diverged"
        elif op == 4:    # sync rounds (producer 5)
            srv.sync.run_round(force_intents=True, all_channels=True)
            ref.sync.run_round(force_intents=True, all_channels=True)
        else:            # relocation (topology churn under everything)
            ks = rng.choice(E, 12, replace=False)
            dest = int(rng.integers(0, srv.num_shards))
            srv._relocate_to(ks, dest)
            ref._relocate_to(ks, dest)
        if rng.integers(0, 3) == 0:
            w.advance_clock()
            wr.advance_clock()
        settle()
        a = np.asarray(srv.read_main(keys))
        b = np.asarray(ref.read_main(keys))
        assert np.array_equal(a, b), (
            f"step {step} (op {op}): overlapped read diverged from "
            f"serialized shadow ({int((a != b).sum())} floats differ)")
        pk = rng.integers(0, E, 20)
        assert np.array_equal(np.asarray(w.pull_sync(pk)),
                              np.asarray(wr.pull_sync(pk))), \
            f"step {step}: pull diverged"
    srv.quiesce()
    ref.quiesce()
    assert np.array_equal(np.asarray(srv.read_main(keys)),
                          np.asarray(ref.read_main(keys))), \
        "after quiesce: overlapped state diverged from serialized shadow"
    # the overlapped server used multiple streams; the shadow used one
    assert ref.exec.single_stream and not srv.exec.single_stream
    plane.close()
    rplane.close()
    srv.shutdown()
    ref.shutdown()
    assert srv.exec.live_streams() == [] and ref.exec.live_streams() == []
    # lock-order sentinel (ISSUE 11): the storm must have recorded a
    # non-trivial acquisition graph and ZERO ordering violations — the
    # dynamic validation of the APM001/APM002 static claims
    from adapm_tpu.lint import lockorder
    sen = lockorder.get_sentinel()
    assert sen is not None and sen.edges(), \
        "sentinel saw no lock edges: the storm exercised nothing"
    sen.assert_clean()
    lockorder.disable_sentinel()
