"""APM001 fixture (good): every dispatch under the gate (bare and
combined with-items, plus the dispatch_gate() call form)."""
from functools import partial

import jax

from adapm_tpu.exec import dispatch_gate

_GATE = dispatch_gate()


@partial(jax.jit, donate_argnums=(0,))
def _write_main_rows(main, sh, row, vals):
    return main.at[sh, row].set(vals, mode="drop")


def promote(store, sh, row, vals):
    with _GATE:
        store.main = _write_main_rows(store.main, sh, row, vals)
    return store.main


def promote_tracked(store, srv, sh, row, vals):
    with srv.exec.track("tier"), _GATE:
        store.main = _write_main_rows(store.main, sh, row, vals)


def promote_call_form(store, sh, row, vals):
    with dispatch_gate():
        store.main = _write_main_rows(store.main, sh, row, vals)
