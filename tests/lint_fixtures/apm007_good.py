"""APM007 fixture (good): registrations agreeing with
apm007_catalog.md — literal, CounterGroup expansion, and a dynamic
per-instance suffix covered by the catalog's pattern row."""
from adapm_tpu.obs.metrics import CounterGroup


class Plane:
    def __init__(self, registry, lanes):
        self.h_pull = registry.histogram("kv.pull_s")
        self.stats = CounterGroup(registry, "kv", ("hits", "misses"))
        for i in range(lanes):
            registry.gauge(f"kv.lane_depth.{i}")
