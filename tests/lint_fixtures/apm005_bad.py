"""APM005 fixture (bad): donated local read after the dispatch."""
from functools import partial

import jax


@partial(jax.jit, donate_argnums=(0,))
def _scatter(pool, idx, vals):
    return pool.at[idx].add(vals)


def push(pool, idx, vals):
    out = _scatter(pool, idx, vals)
    return pool.sum() + out.sum()  # BAD: `pool` was donated above
