"""APM006 fixture (good): the under-lock revalidation (r6 staged-pull
discipline), and the no-optimism path that never snapshots outside."""


def pull(self, srv, keys):
    tv = srv.topology_version
    plan = self.plan_cache.get(keys, tv)
    with srv._lock:
        if plan is not None and srv.topology_version != tv:
            plan = None  # topology moved underneath us: re-plan
        groups = srv._pull(keys, self.shard, plan=plan)
    return groups


def pull_locked(self, srv, keys):
    with srv._lock:
        groups = srv._pull(keys, self.shard)
    return groups
