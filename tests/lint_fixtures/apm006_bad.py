"""APM006 fixture (bad): optimistic topology snapshot, enqueue under
the lock, no under-lock re-read."""


def pull(self, srv, keys):
    tv = srv.topology_version          # snapshot OUTSIDE the lock
    plan = self.plan_cache.get(keys, tv)
    with srv._lock:
        groups = srv._pull(keys, self.shard, plan=plan)  # BAD: stale?
    return groups
