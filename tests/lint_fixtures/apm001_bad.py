"""APM001 fixture (bad): sharded program dispatched outside the gate."""
from functools import partial

import jax

from adapm_tpu.exec import dispatch_gate

_GATE = dispatch_gate()


@partial(jax.jit, donate_argnums=(0,))
def _write_main_rows(main, sh, row, vals):
    return main.at[sh, row].set(vals, mode="drop")


def promote(store, sh, row, vals):
    store.main = _write_main_rows(store.main, sh, row, vals)  # BAD: no gate
    return store.main
