"""APM003 fixture (bad): unguarded optional-handle use + import-time
metric registration."""
from adapm_tpu.obs.metrics import MetricsRegistry

registry = MetricsRegistry()
_C = registry.counter("fixture.imported")  # BAD: import-time name


def record(self, srv, keys):
    srv.flight.freshness.note_push(keys)  # BAD: no `is None` guard


def fire(self, srv):
    srv.fault.fire("fixture.point")  # BAD: no `is None` guard
