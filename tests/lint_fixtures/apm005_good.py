"""APM005 fixture (good): the result replaces the donated buffer —
rebinding (`pool = _scatter(pool, ...)`) and attribute-held pools are
both fine."""
from functools import partial

import jax


@partial(jax.jit, donate_argnums=(0,))
def _scatter(pool, idx, vals):
    return pool.at[idx].add(vals)


def push(pool, idx, vals):
    pool = _scatter(pool, idx, vals)  # rebind: donation consumed it
    return pool.sum()


def push_attr(store, idx, vals):
    store.main = _scatter(store.main, idx, vals)
    return store.main.sum()


def push_multiline(pool, idx, vals):
    # the donated Name sits on a CONTINUATION line of its own call: its
    # argument load must not read as "after the dispatch"
    pool = _scatter(
        pool, idx, vals)
    return pool.sum()
