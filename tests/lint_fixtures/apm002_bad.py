"""APM002 fixture (bad): blocking calls under a `with *._lock:`."""
import time


def flush(self, completion):
    with self._lock:
        completion.result(timeout=30)  # BAD: wait under the lock


def throttle(self):
    with self._lock:
        time.sleep(0.01)  # BAD: sleep under the lock


def quiesce(self, srv):
    with srv._lock:
        srv.exec.drain("sync", timeout=5)  # BAD: drain under the lock
