"""APM003 fixture (good): every sanctioned guard shape — bind-to-local,
enclosing `if`, early return, getattr probe — and construction-time
registration."""


class Plane:
    def __init__(self, registry):
        self.c_ops = registry.counter("fixture.ops")  # runtime: fine


def record_local_bind(self, srv, keys):
    fl = srv.flight
    if fl is not None:
        fl.freshness.note_push(keys)


def record_enclosing_if(self, srv, keys):
    if srv.flight is not None:
        srv.flight.freshness.note_push(keys)


def record_early_return(self, srv, keys):
    if srv.flight is None:
        return
    srv.flight.freshness.note_push(keys)


def count(self, server, n):
    if n and getattr(server, "tier", None) is not None:
        server.tier.c_demotions.inc(n)
