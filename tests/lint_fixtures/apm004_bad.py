"""APM004 fixture (bad): raw thread outside the allowlist."""
import threading


def start_worker(fn):
    t = threading.Thread(target=fn, daemon=True)  # BAD: not allowlisted
    t.start()
    return t
