"""APM002 fixture (good): enqueue under the lock, wait outside — plus
the condvar exemption (a condvar wait RELEASES its lock)."""


def flush(self, make_program):
    with self._lock:
        completion = make_program()  # enqueue only
    completion.result(timeout=30)    # wait with the lock released


def park(self):
    with self._lock:
        while not self._work:
            self._cond.wait(0.5)     # condvar: releases the lock
