"""Suppression round-trip fixture: one APM004 violation carrying a
justified suppression (trailing form) and one carrying the
comment-block-above form — both must report clean and count as USED."""
import threading


def start_watchdog(fn):
    return threading.Thread(target=fn)  # apm-lint: disable=APM004 fixture watchdog must outlive the pool


def start_reporter(fn):
    # apm-lint: disable=APM004 fixture reporter thread predates the
    # executor and is import-gated (multi-line justification form)
    return threading.Thread(target=fn)
