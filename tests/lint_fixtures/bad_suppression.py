"""Malformed-suppression fixture: no justification text — APM000 (the
reason is the point of the escape hatch)."""
import threading


def start_worker(fn):
    return threading.Thread(target=fn)  # apm-lint: disable=APM004
