"""Unused-suppression fixture: the comment names a rule that no longer
fires here — the run must FAIL with APM000 (stale suppressions are
deleted, not kept)."""


def quiet():
    # apm-lint: disable=APM004 the thread this once justified is gone
    return None
