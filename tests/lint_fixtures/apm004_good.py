"""APM004 fixture (good): background work rides the executor."""


def start_worker(server, fn):
    return server.exec.submit("fixture", fn, label="fixture.pass",
                              coalesce_key="fixture.pass")
