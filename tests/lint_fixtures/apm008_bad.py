"""APM008 known-bad fixture: jax program-construction APIs outside
adapm_tpu/device/ — every shape the rule must catch."""
import functools

import jax
import jax.experimental.shard_map  # plain-import evasion form
from jax.experimental.shard_map import shard_map  # import form


@jax.jit  # decorator form
def prog(x):
    return x + 1


@functools.partial(jax.jit, donate_argnums=(0,))  # partial form
def donated(x):
    return x * 2


def stage(arr, sharding):
    return jax.device_put(arr, sharding)  # transfer form


def build_collective(fn, mesh, spec):
    return jax.jit(shard_map(fn, mesh=mesh, in_specs=spec,
                             out_specs=spec))  # bare-name use


def build_collective_chained(fn, mesh, spec):
    # attribute-chain use of the plain import
    return jax.experimental.shard_map.shard_map(fn, mesh=mesh,
                                                in_specs=spec,
                                                out_specs=spec)
