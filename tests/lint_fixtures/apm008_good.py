"""APM008 known-good fixture: device work reaches the accelerator
through the DevicePort — no direct jax program-construction APIs."""
import numpy as np

from adapm_tpu.device import default_port


def make_step(body):
    # program construction through the port
    return default_port().compile(body, donate_argnums=(0,))


def stage(arr, sharding):
    return default_port().put_replicated(np.asarray(arr), sharding)


def build_collective(fn, mesh, spec):
    return default_port().compile_collective(fn, mesh=mesh,
                                             in_specs=spec,
                                             out_specs=spec)


def dispatch(store, a):
    # data-plane dispatch through the store's port methods
    return store.port.gather(store.main, store.cache, store.delta, *a)
