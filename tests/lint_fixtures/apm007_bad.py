"""APM007 fixture (bad): a registered metric missing from the catalog,
and (paired with apm007_catalog.md) a catalog row with no
registration."""


class Plane:
    def __init__(self, registry):
        # NOT in apm007_catalog.md -> code->doc drift
        self.c_rogue = registry.counter("kv.rogue_total")
        # section `nowhere` absent from the schema block -> drift
        self.g_lost = registry.gauge("nowhere.lost")
