"""Launcher + multi-host control plane tests (reference tracker/dmlc_local.py
thread-per-process launch, keepalive restart on exit code 254, and the
scheduler barrier/allreduce protocol — SURVEY.md §2.4, §4)."""
import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

from adapm_tpu import launcher
from adapm_tpu.parallel import control


def test_control_single_process_fallbacks():
    """All control primitives degrade to local no-ops in one process."""
    control.barrier("t")
    assert control.allreduce(3.0, "sum").tolist() == [3.0]
    assert control.allreduce([1.0, 2.0], "mean").tolist() == [1.0, 2.0]
    assert control.broadcast(np.arange(3)).tolist() == [0, 1, 2]
    assert control.num_processes() == 1
    assert control.process_id() == 0


def test_launch_local_env_contract(tmp_path):
    """launch_local spawns N ranks with the ADAPM_* env contract."""
    out = tmp_path / "ranks"
    out.mkdir()
    script = tmp_path / "prog.py"
    script.write_text(textwrap.dedent(f"""
        import os
        rank = os.environ["ADAPM_PROCESS_ID"]
        n = os.environ["ADAPM_NUM_PROCESSES"]
        coord = os.environ["ADAPM_COORDINATOR"]
        open(r"{out}" + "/" + rank, "w").write(n + " " + coord)
    """))
    code = launcher.launch_local(3, [sys.executable, str(script)])
    assert code == 0
    files = sorted(os.listdir(out))
    assert files == ["0", "1", "2"]
    contents = {(out / f).read_text() for f in files}
    assert len(contents) == 1  # same num + coordinator for all ranks


def test_launch_local_keepalive(tmp_path):
    """Exit code 254 triggers a restart (reference dmlc_local.py:15-25)."""
    marker = tmp_path / "ran_once"
    script = tmp_path / "prog.py"
    script.write_text(textwrap.dedent(f"""
        import os, sys
        m = r"{marker}"
        if not os.path.exists(m):
            open(m, "w").write("x")
            sys.exit(254)
        sys.exit(0)
    """))
    code = launcher.launch_local(1, [sys.executable, str(script)])
    assert code == 0 and marker.exists()


def test_launch_local_propagates_failure(tmp_path):
    script = tmp_path / "prog.py"
    script.write_text("import sys; sys.exit(7)")
    assert launcher.launch_local(
        2, [sys.executable, str(script)], keepalive=False) == 7


def test_launch_local_restart_budget_stops_crash_loop(tmp_path):
    """ISSUE 10 satellite: a rank that ALWAYS exits 254 used to be
    restarted forever at a fixed 0.5 s cadence (the reference
    dmlc_local.py contract). The hardened keepalive applies capped
    exponential backoff and gives up after the restart budget,
    propagating the 254 as the job's failure code."""
    import time as _time
    attempts = tmp_path / "attempts"
    script = tmp_path / "prog.py"
    script.write_text(textwrap.dedent(f"""
        import sys
        with open(r"{attempts}", "a") as f:
            f.write("x")
        sys.exit(254)
    """))
    t0 = _time.monotonic()
    code = launcher.launch_local(
        1, [sys.executable, str(script)], keepalive=True,
        max_restarts=3, backoff_base_s=0.01, backoff_max_s=0.04)
    elapsed = _time.monotonic() - t0
    # budget exhausted: the crash loop stops and the 254 surfaces
    assert code == launcher.KEEPALIVE_EXIT_CODE
    # initial run + exactly max_restarts restarts, never unbounded
    assert attempts.read_text() == "x" * 4
    # backoff actually waited: 0.01 + 0.02 + 0.04 (capped) >= 0.07 s
    assert elapsed >= 0.07


def test_launch_local_keepalive_still_recovers_within_budget(tmp_path):
    """A transiently-crashing rank (254 once, then clean) still
    recovers under the hardened keepalive — the budget bounds crash
    LOOPS, not legitimate restarts."""
    marker = tmp_path / "ran_once"
    script = tmp_path / "prog.py"
    script.write_text(textwrap.dedent(f"""
        import os, sys
        m = r"{marker}"
        if not os.path.exists(m):
            open(m, "w").write("x")
            sys.exit(254)
        sys.exit(0)
    """))
    code = launcher.launch_local(
        1, [sys.executable, str(script)], keepalive=True,
        max_restarts=3, backoff_base_s=0.01)
    assert code == 0 and marker.exists()


def _rank_recorder(tmp_path):
    """A program that records its ADAPM_* env, used to verify the env
    contract each launch mode assembles."""
    out = tmp_path / "ranks"
    out.mkdir(exist_ok=True)
    script = tmp_path / "prog.py"
    script.write_text(textwrap.dedent(f"""
        import os
        rank = os.environ["ADAPM_PROCESS_ID"]
        n = os.environ["ADAPM_NUM_PROCESSES"]
        coord = os.environ["ADAPM_COORDINATOR"]
        open(r"{out}" + "/" + rank, "w").write(n + " " + coord)
    """))
    return out, script


def test_launch_ssh_with_path_shim(tmp_path, monkeypatch):
    """ssh mode (reference tracker/dmlc_ssh.py): a PATH-shim `ssh` records
    argv and runs the remote command locally, verifying per-host command +
    env assembly without sshd."""
    out, script = _rank_recorder(tmp_path)
    bin_dir = tmp_path / "bin"
    bin_dir.mkdir()
    log = tmp_path / "ssh.log"
    shim = bin_dir / "ssh"
    # the remote command is the last argv; preceding args are opts + host
    shim.write_text(textwrap.dedent(f"""\
        #!/bin/sh
        printf '%s\\n' "$*" >> {log}
        for last; do :; done
        exec sh -c "$last"
    """))
    shim.chmod(0o755)
    monkeypatch.setenv("PATH", f"{bin_dir}:{os.environ['PATH']}")
    hosts = ["nodeA", "nodeB", "nodeC"]
    code = launcher.launch_ssh(hosts, [sys.executable, str(script)],
                               coordinator_port=23456)
    assert code == 0
    files = sorted(os.listdir(out))
    assert files == ["0", "1", "2"]
    contents = {(out / f).read_text() for f in files}
    # all ranks agree; coordinator is host 0 at the pinned port
    assert contents == {"3 nodeA:23456"}
    lines = log.read_text().splitlines()
    assert len(lines) == 3
    # the ssh processes run concurrently, so log lines may interleave in
    # any order — match each host's line by content, not position
    for rank, host in enumerate(hosts):
        ln = next(l for l in lines if f" {host} " in l)
        assert "StrictHostKeyChecking=no" in ln
        assert f"ADAPM_PROCESS_ID={rank}" in ln
        assert f"cd {os.getcwd()}" in ln


def test_launch_mpi_with_path_shim(tmp_path, monkeypatch):
    """mpi mode (reference tracker/dmlc_mpi.py): a PATH-shim `mpirun`
    records argv and spawns -n local copies with OMPI_COMM_WORLD_RANK set,
    verifying the MPI-env -> ADAPM-env bootstrap translation."""
    out, script = _rank_recorder(tmp_path)
    bin_dir = tmp_path / "bin"
    bin_dir.mkdir()
    log = tmp_path / "mpirun.log"
    shim = bin_dir / "mpirun"
    shim.write_text(textwrap.dedent(f"""\
        #!{sys.executable}
        import os, subprocess, sys
        args = sys.argv[1:]
        open(r"{log}", "a").write(" ".join(args) + chr(10))
        n, cmd, i = 1, [], 0
        while i < len(args):
            if args[i] == "-n":
                n = int(args[i + 1]); i += 2
            else:
                cmd.append(args[i]); i += 1
        procs = []
        for r in range(n):
            env = dict(os.environ)
            env["OMPI_COMM_WORLD_RANK"] = str(r)
            procs.append(subprocess.Popen(cmd, env=env))
        code = 0
        for p in procs:
            p.wait(); code = code or p.returncode
        sys.exit(code)
    """))
    shim.chmod(0o755)
    monkeypatch.setenv("PATH", f"{bin_dir}:{os.environ['PATH']}")
    code = launcher.launch_mpi(2, [sys.executable, str(script)],
                               coordinator_port=24567)
    assert code == 0
    files = sorted(os.listdir(out))
    assert files == ["0", "1"]
    contents = {(out / f).read_text() for f in files}
    assert len(contents) == 1  # same num + coordinator on every rank
    assert next(iter(contents)).startswith("2 ")
    assert ":24567" in next(iter(contents))
    assert "-n 2" in log.read_text()


def test_launcher_main_dispatches_all_modes(tmp_path, monkeypatch):
    """`python -m adapm_tpu.launcher --mode {local,ssh,mpi}` reaches the
    right launch function with parsed hostfile/port/keepalive flags."""
    calls = {}
    monkeypatch.setattr(
        launcher, "launch_local",
        lambda n, cmd, keepalive=True, **kw: calls.setdefault(
            "local", (n, cmd, keepalive)) and 0 or 0)
    monkeypatch.setattr(
        launcher, "launch_ssh",
        lambda hosts, cmd, coordinator_port=0: calls.setdefault(
            "ssh", (hosts, cmd, coordinator_port)) and 0 or 0)
    monkeypatch.setattr(
        launcher, "launch_mpi",
        lambda n, cmd, coordinator_port=0: calls.setdefault(
            "mpi", (n, cmd, coordinator_port)) and 0 or 0)
    hostfile = tmp_path / "hosts"
    hostfile.write_text("a\nb\n")
    launcher.main(["-n", "4", "--no-keepalive", "--", "prog", "--x"])
    launcher.main(["--mode", "ssh", "--hostfile", str(hostfile),
                   "--coordinator-port", "2222", "--", "prog"])
    launcher.main(["--mode", "mpi", "-n", "3",
                   "--coordinator-port", "3333", "--", "prog"])
    assert calls["local"] == (4, ["prog", "--x"], False)
    assert calls["ssh"] == (["a", "b"], ["prog"], 2222)
    assert calls["mpi"] == (3, ["prog"], 3333)


@pytest.mark.slow
def test_two_process_distributed_allreduce(tmp_path):
    """Real 2-process rendezvous through the jax.distributed coordinator
    (the scheduler's replacement): each rank contributes rank+1; the
    allreduce sum must be 3 in both processes."""
    script = tmp_path / "prog.py"
    script.write_text(textwrap.dedent("""
        import os
        os.environ["JAX_PLATFORMS"] = "cpu"
        os.environ.pop("PYTHONPATH", None)
        import jax
        jax.config.update("jax_platforms", "cpu")
        from adapm_tpu.parallel import control
        assert control.init_from_env()
        rank = control.process_id()
        control.barrier("start")
        total = control.allreduce(float(rank + 1), "sum")
        assert total.tolist() == [3.0], total
        control.barrier("end")
        print("RANK", rank, "OK", flush=True)
    """))
    env = dict(os.environ)
    # child processes need the repo importable but NOT the TPU-tunnel site
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(
        os.path.abspath(launcher.__file__)))
    coordinator = f"localhost:{launcher.free_port()}"
    procs = [subprocess.Popen(
        [sys.executable, str(script)],
        env=launcher.make_env(r, 2, coordinator, env),
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
        for r in range(2)]
    outs = [p.communicate(timeout=300)[0].decode() for p in procs]
    for r, (p, o) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {r} failed:\n{o}"
        assert f"RANK {r} OK" in o
