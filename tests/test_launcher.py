"""Launcher + multi-host control plane tests (reference tracker/dmlc_local.py
thread-per-process launch, keepalive restart on exit code 254, and the
scheduler barrier/allreduce protocol — SURVEY.md §2.4, §4)."""
import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

from adapm_tpu import launcher
from adapm_tpu.parallel import control


def test_control_single_process_fallbacks():
    """All control primitives degrade to local no-ops in one process."""
    control.barrier("t")
    assert control.allreduce(3.0, "sum").tolist() == [3.0]
    assert control.allreduce([1.0, 2.0], "mean").tolist() == [1.0, 2.0]
    assert control.broadcast(np.arange(3)).tolist() == [0, 1, 2]
    assert control.num_processes() == 1
    assert control.process_id() == 0


def test_launch_local_env_contract(tmp_path):
    """launch_local spawns N ranks with the ADAPM_* env contract."""
    out = tmp_path / "ranks"
    out.mkdir()
    script = tmp_path / "prog.py"
    script.write_text(textwrap.dedent(f"""
        import os
        rank = os.environ["ADAPM_PROCESS_ID"]
        n = os.environ["ADAPM_NUM_PROCESSES"]
        coord = os.environ["ADAPM_COORDINATOR"]
        open(r"{out}" + "/" + rank, "w").write(n + " " + coord)
    """))
    code = launcher.launch_local(3, [sys.executable, str(script)])
    assert code == 0
    files = sorted(os.listdir(out))
    assert files == ["0", "1", "2"]
    contents = {(out / f).read_text() for f in files}
    assert len(contents) == 1  # same num + coordinator for all ranks


def test_launch_local_keepalive(tmp_path):
    """Exit code 254 triggers a restart (reference dmlc_local.py:15-25)."""
    marker = tmp_path / "ran_once"
    script = tmp_path / "prog.py"
    script.write_text(textwrap.dedent(f"""
        import os, sys
        m = r"{marker}"
        if not os.path.exists(m):
            open(m, "w").write("x")
            sys.exit(254)
        sys.exit(0)
    """))
    code = launcher.launch_local(1, [sys.executable, str(script)])
    assert code == 0 and marker.exists()


def test_launch_local_propagates_failure(tmp_path):
    script = tmp_path / "prog.py"
    script.write_text("import sys; sys.exit(7)")
    assert launcher.launch_local(
        2, [sys.executable, str(script)], keepalive=False) == 7


@pytest.mark.slow
def test_two_process_distributed_allreduce(tmp_path):
    """Real 2-process rendezvous through the jax.distributed coordinator
    (the scheduler's replacement): each rank contributes rank+1; the
    allreduce sum must be 3 in both processes."""
    script = tmp_path / "prog.py"
    script.write_text(textwrap.dedent("""
        import os
        os.environ["JAX_PLATFORMS"] = "cpu"
        os.environ.pop("PYTHONPATH", None)
        import jax
        jax.config.update("jax_platforms", "cpu")
        from adapm_tpu.parallel import control
        assert control.init_from_env()
        rank = control.process_id()
        control.barrier("start")
        total = control.allreduce(float(rank + 1), "sum")
        assert total.tolist() == [3.0], total
        control.barrier("end")
        print("RANK", rank, "OK", flush=True)
    """))
    env = dict(os.environ)
    # child processes need the repo importable but NOT the TPU-tunnel site
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(
        os.path.abspath(launcher.__file__)))
    coordinator = f"localhost:{launcher.free_port()}"
    procs = [subprocess.Popen(
        [sys.executable, str(script)],
        env=launcher.make_env(r, 2, coordinator, env),
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
        for r in range(2)]
    outs = [p.communicate(timeout=120)[0].decode() for p in procs]
    for r, (p, o) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {r} failed:\n{o}"
        assert f"RANK {r} OK" in o
