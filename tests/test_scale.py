"""Host-metadata scalability: the reference's addressbook is O(1)/key in
C++ (addressbook.h:110-151); the tables here must construct and operate in
vectorized batches, never per key in Python. Sizes are trimmed for CI; the
5M-key check (VERDICT criterion) runs in scripts/scale_check.py."""
import time

import numpy as np

import adapm_tpu
from adapm_tpu.base import NO_SLOT
from adapm_tpu.config import SystemOptions
from adapm_tpu.core.addressbook import SlotAllocator


def test_million_key_server_constructs_fast():
    t0 = time.perf_counter()
    srv = adapm_tpu.setup(1_000_000, 8, opts=SystemOptions(
        sync_max_per_sec=0, cache_slots_per_shard=1024))
    dt = time.perf_counter() - t0
    # generous bound: catches an accidental per-key Python loop (minutes at
    # 1M keys) without flaking on a loaded CI host
    assert dt < 30.0, f"1M-key construction took {dt:.2f}s"
    # spot-check the vectorized initial allocation: home = k % S, slots
    # contiguous per (class, shard)
    ab = srv.ab
    S = srv.num_shards
    ks = np.array([0, 1, S, S + 1, 999_999])
    assert (ab.owner[ks] == ks % S).all()
    assert (ab.slot[ks] == ks // S).all()
    # a mixed-length-class server allocates consistently too
    lens = np.where(np.arange(10_000) % 3 == 0, 4, 8)
    srv2 = adapm_tpu.setup(10_000, lens, opts=SystemOptions(
        sync_max_per_sec=0))
    ab2 = srv2.ab
    for cid in range(len(srv2.stores)):
        cls_keys = np.nonzero(ab2.key_class == cid)[0]
        for s in range(S):
            slots = ab2.slot[cls_keys[ab2.owner[cls_keys] == s]]
            assert len(np.unique(slots)) == len(slots), "slot collision"
    srv.shutdown()
    srv2.shutdown()


def test_large_intent_batch_vectorized():
    srv = adapm_tpu.setup(200_000, 4, opts=SystemOptions(
        sync_max_per_sec=0, cache_slots_per_shard=4096))
    w0, w1 = srv.make_worker(0), srv.make_worker(1)
    rng = np.random.default_rng(0)
    keys = rng.choice(200_000, 10_000, replace=False)
    # phase 1: exclusive intent -> batched relocation (free main slots:
    # 200k/4 * 0.25 over-alloc = 12.5k per shard > ~7.5k non-local keys)
    w0.intent(keys, 0, 1000)
    t0 = time.perf_counter()
    srv.wait_sync()
    dt = time.perf_counter() - t0
    # generous bound: a per-key drain would take minutes (see above)
    assert dt < 60.0, f"10k-key intent drain took {dt:.2f}s"
    assert srv.sync.stats.relocations > 0, "exclusive intents should relocate"
    assert srv.ab.is_local(keys, w0.shard).all()
    # phase 2: competing intent on keys now owned by shard 0 -> replication
    # onto shard 1 (bounded by the 4096-slot cache pool)
    w1.intent(keys[:2000], 0, 1000)
    srv.wait_sync()
    assert srv.sync.stats.replicas_created > 0, \
        "competing intents should replicate"
    assert srv.ab.is_local(keys[:2000], w1.shard).all()
    srv.shutdown()


def test_slot_allocator_batch_semantics():
    a = SlotAllocator(2, 10)
    s = a.alloc_batch(0, 4)
    assert s.tolist() == [0, 1, 2, 3]
    a.free_batch(0, np.array([1, 3]))
    assert a.num_free(0) == 8
    s2 = a.alloc_batch(0, 3)
    # returned slots reused (LIFO) before fresh watermark slots
    assert set(s2.tolist()) == {1, 3, 4}
    # capacity-bounded: asking for more than free returns fewer
    s3 = a.alloc_batch(0, 100)
    assert len(s3) == 5 and a.num_free(0) == 0
    assert a.num_free(1) == 10
    # exhaustion raises on the scalar path
    try:
        a.alloc(0)
        raise RuntimeError("should have raised")
    except RuntimeError as e:
        assert "out of pool slots" in str(e)


def test_relocation_batch_upgrades_replicas():
    """A relocation to a shard that already holds a replica merges the
    pending delta (replica -> owner upgrade) — batched path."""
    from adapm_tpu.base import CLOCK_MAX, MgmtTechniques
    # 256 keys / 8 shards -> 32 per shard, 25% over-alloc = 8 free main
    # slots per shard, enough for the 3-key relocation batch
    srv = adapm_tpu.setup(256, 4, opts=SystemOptions(
        techniques=MgmtTechniques.REPLICATION_ONLY, sync_max_per_sec=0,
        cache_slots_per_shard=16))
    w0, w1 = srv.make_worker(0), srv.make_worker(1)
    w0.set(np.arange(256), np.ones((256, 4), np.float32))
    keys = np.array([k for k in range(256)
                     if srv.ab.owner[k] not in (w0.shard,)][:3])
    w0.intent(keys, 0, CLOCK_MAX)
    w1.intent(keys, 0, CLOCK_MAX)
    srv.wait_sync()
    assert (srv.ab.cache_slot[w0.shard, keys] != NO_SLOT).all()
    # pending delta on the replicas
    w0.push(keys, np.full((3, 4), 2.0, np.float32))
    # force relocation of those keys to w0's shard through the batch path
    moved = srv._relocate([(int(k), w0.shard) for k in keys])
    assert moved == 3
    assert (srv.ab.owner[keys] == w0.shard).all()
    assert (srv.ab.cache_slot[w0.shard, keys] == NO_SLOT).all()
    # delta survived the upgrade
    assert np.allclose(srv.read_main(keys).reshape(3, 4), 3.0)
    srv.shutdown()
