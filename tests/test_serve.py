"""Online serving plane (ISSUE 4 tentpole): coalesced read path with
admission control, deadlines, and snapshot-consistent lookups.

Tier-1 coverage for adapm_tpu/serve:

  - lookup correctness vs `Worker.pull` (duplicates, mixed length
    classes, empty batches);
  - micro-batch coalescing: N queued requests -> ONE dispatcher batch,
    deduplicated union keys;
  - admission control: a full bounded queue rejects loudly
    (ServeOverloadError), a passed deadline sheds loudly
    (DeadlineExceededError) — never a hang;
  - the ACCEPTANCE storm: randomized interleaving of serve lookups,
    pushes, sets, relocations, replica churn, and sync rounds — every
    lookup bit-identical to a plain `Worker.pull` of the same keys at
    the same point, read-your-writes included;
  - a concurrent (threaded) storm: serve clients + pushers + a
    relocator + a sync driver, exact additive-sum invariants, bounded
    joins (no hang);
  - readiness: a stale peer heartbeat flips readiness (detection-only,
    docs/failure_handling.md) WITHOUT hanging the request queue;
  - the serve section of metrics_snapshot (schema_version 3) and the
    plane lifecycle (one live plane per server, close/reopen, shutdown
    closes the plane).
"""
import threading
import time

import numpy as np
import pytest

from adapm_tpu import Server, SystemOptions, make_mesh
from adapm_tpu.serve import (DeadlineExceededError, LookupRequest,
                             ServeOverloadError, ServePlane)

NK = 96
VL = 4


@pytest.fixture(scope="module")
def ctx():
    return make_mesh(8)


def make_server(ctx, num_keys=NK, vlen=VL, **kw):
    opts = kw.pop("opts", None) or SystemOptions(sync_max_per_sec=0)
    return Server(num_keys, vlen, opts=opts, ctx=ctx, **kw)


def _seed(w, num_keys=NK, vlen=VL):
    keys = np.arange(num_keys)
    vals = (np.arange(num_keys * vlen, dtype=np.float32)
            .reshape(num_keys, vlen))
    w.wait(w.set(keys, vals))
    return vals


def test_lookup_matches_pull(ctx):
    s = make_server(ctx)
    w = s.make_worker(0)
    _seed(w)
    with ServePlane(s) as plane:
        sess = plane.session()
        for batch in (np.array([1, 5, 9]),
                      np.array([7, 7, 3, 7]),          # duplicates
                      np.arange(NK),                    # everything
                      np.array([42])):
            got = sess.lookup(batch)
            ref = w.pull_sync(batch)
            assert np.array_equal(got, ref), batch
        assert sess.lookup([]).size == 0
        # an out-of-range key fails ITS client at the session boundary
        # (it must not reach the dispatcher and poison a co-batch)
        with pytest.raises(IndexError):
            sess.lookup(np.array([NK]))
        with pytest.raises(IndexError):
            sess.lookup(np.array([-1]))
        # the plane still serves after the rejection
        assert np.array_equal(sess.lookup(np.array([0])),
                              w.pull_sync(np.array([0])))
    s.shutdown()


def test_lookup_mixed_length_classes(ctx):
    """Ragged batches span length classes: one fused gather per class,
    reassembled flat exactly like pull_sync."""
    lens = np.where(np.arange(32) % 3 == 0, 8, 4)
    s = Server(32, lens, opts=SystemOptions(sync_max_per_sec=0), ctx=ctx)
    w = s.make_worker(0)
    flat = np.arange(lens.sum(), dtype=np.float32)
    w.wait(w.set(np.arange(32), flat))
    with ServePlane(s) as plane:
        sess = plane.session()
        batch = np.array([0, 1, 3, 6, 2, 0])  # mixed classes + duplicate
        got = sess.lookup(batch)
        ref = w.pull_sync(batch)
        assert got.ndim == 1 and np.array_equal(got, ref)
    s.shutdown()


def test_coalesced_batch_single_dispatch(ctx):
    """N requests queued while the dispatcher is paused are served by
    ONE micro-batch: one deduplicated union gather, every request's
    values correct (deterministic — no timing assumptions)."""
    s = make_server(ctx)
    w = s.make_worker(0)
    vals = _seed(w)
    plane = ServePlane(s, start=False)
    reqs = [LookupRequest(np.array([i, i + 1, 40])) for i in range(8)]
    for r in reqs:
        plane.queue.submit(r)
    b0 = s.obs.find("serve.batches_total").value
    plane.start()
    for i, r in enumerate(reqs):
        assert r.wait(30), "request not served"
        got = r.take_result().reshape(3, VL)
        assert np.array_equal(got, vals[[i, i + 1, 40]])
    assert s.obs.find("serve.batches_total").value == b0 + 1
    assert s.obs.find("serve.batch_size").snap()["max"] == 8.0
    # the union was deduplicated: 8 requests x 3 keys share key 40 and
    # overlap pairwise -> far fewer unique keys than submitted keys
    assert s.obs.find("serve.keys_deduped_total").value < \
        s.obs.find("serve.keys_total").value
    plane.close()
    s.shutdown()


def test_backpressure_rejects_loudly(ctx):
    s = make_server(ctx)
    w = s.make_worker(0)
    vals = _seed(w)
    opts = SystemOptions(sync_max_per_sec=0, serve_queue=4,
                         serve_max_batch=4)
    plane = ServePlane(s, opts=opts, start=False)
    reqs = [LookupRequest(np.array([i])) for i in range(4)]
    for r in reqs:
        plane.queue.submit(r)
    sess = plane.session()
    with pytest.raises(ServeOverloadError):
        sess.lookup(np.array([9]))
    assert s.obs.find("serve.rejected_total").value >= 1
    # backpressure is transient: once the dispatcher drains, admission
    # resumes and the queued requests were all served correctly
    plane.start()
    for i, r in enumerate(reqs):
        assert r.wait(30)
        assert np.array_equal(r.take_result(), vals[i])
    assert np.array_equal(sess.lookup(np.array([9]))[0], vals[9])
    plane.close()
    s.shutdown()


def test_deadline_sheds_never_hangs(ctx):
    s = make_server(ctx)
    w = s.make_worker(0)
    vals = _seed(w)
    plane = ServePlane(s, start=False)  # paused: nothing will serve
    sess = plane.session()
    t0 = time.monotonic()
    with pytest.raises(DeadlineExceededError):
        sess.lookup(np.array([1]), deadline_ms=30)
    assert time.monotonic() - t0 < 5.0, "shed was not prompt"
    assert s.obs.find("serve.shed_total").value >= 1
    # the shed corpse still sits in the deque, but it is NOT live work:
    # depth (and hence readiness/queue_depth) must not count it
    assert plane.queue.depth() == 0
    # an already-expired request queued behind a live one is shed at
    # take time (dispatcher-side deadline check), the live one served
    dead = LookupRequest(np.array([2]), deadline_s=0.0)
    live = LookupRequest(np.array([3]))
    plane.queue.submit(dead)
    plane.queue.submit(live)
    plane.start()
    assert live.wait(30)
    assert np.array_equal(live.take_result(), vals[3])
    assert dead.wait(30)
    with pytest.raises(DeadlineExceededError):
        dead.take_result()
    # the plane keeps serving after sheds
    assert np.array_equal(sess.lookup(np.array([4]))[0], vals[4])
    plane.close()
    s.shutdown()


def test_serve_storm_bit_identical(ctx):
    """THE acceptance storm: a randomized (but deterministic) sequence
    of pushes, sets, relocations, replica churn, and sync rounds, with
    a serve lookup + plain `Worker.pull` of the same keys after every
    mutation — bit-identical at every read, read-your-writes included
    (the pull and the lookup route from the same shard as the serving
    plane, which is the consistency contract; docs/SERVING.md)."""
    s = make_server(ctx, opts=SystemOptions(sync_max_per_sec=0,
                                            cache_slots_per_shard=64))
    w0 = s.make_worker(0)   # shard 0 — the serve plane's shard
    w1 = s.make_worker(1)   # shard 1 — a second writer + replica holder
    _seed(w0)
    plane = ServePlane(s)
    sess = plane.session(worker=w0)
    rng = np.random.default_rng(7)
    for step in range(50):
        op = rng.integers(0, 6)
        kset = np.unique(rng.integers(0, NK, rng.integers(1, 9)))
        if op == 0:
            w0.push(kset, rng.normal(size=(len(kset), VL))
                    .astype(np.float32))
        elif op == 1:
            w1.push(kset, rng.normal(size=(len(kset), VL))
                    .astype(np.float32))
        elif op == 2:
            w0.set(kset, rng.normal(size=(len(kset), VL))
                   .astype(np.float32))
        elif op == 3:
            s._relocate_to(kset, int(rng.integers(0, s.num_shards)))
        elif op == 4:
            # replica churn: a short-lived intent window on shard 1
            w1.intent(kset, w1.current_clock, w1.current_clock + 2)
            with s._round_lock:
                s.sync.run_round(force_intents=True, all_channels=True)
            w1.advance_clock()
        else:
            with s._round_lock:
                s.sync.run_round(all_channels=True)
        batch = rng.integers(0, NK, 12)  # duplicates allowed
        got = sess.lookup(batch)
        ref = w0.pull_sync(batch)
        assert np.array_equal(got, ref), f"step {step} (op {op}) diverged"
    assert s.obs.find("serve.lookups_total").value == 50
    plane.close()
    s.shutdown()


def test_serve_concurrent_storm_no_hang(ctx):
    """Concurrent clients, writers, a relocator, and a sync driver: the
    additive-sum invariant holds exactly (each client's disjoint key
    slice reads exactly its own push count — coalesced lookups are
    ordered with the client's pushes), and every thread joins within
    its bound (reject/shed loudly, never hang)."""
    s = make_server(ctx, num_keys=64,
                    opts=SystemOptions(sync_max_per_sec=0))
    w0, w1 = s.make_worker(0), s.make_worker(1)
    w0.wait(w0.set(np.arange(64), np.zeros((64, VL), np.float32)))
    plane = ServePlane(s)
    errs = []
    stop = threading.Event()

    def client(w, lo, hi):
        # pushes land on owner main rows (no replicas of these keys —
        # no intents are signalled for them), so a coalesced lookup
        # observes exactly the pushes dispatched before it
        try:
            sess = plane.session(worker=w)
            mine = np.arange(lo, hi)
            for n in range(1, 31):
                w.push(mine, np.ones((len(mine), VL), np.float32))
                got = sess.lookup(mine)
                if not np.array_equal(
                        got, np.full((len(mine), VL), float(n))):
                    errs.append((lo, n, got))
                    return
        except BaseException as e:  # noqa: BLE001
            errs.append(e)

    def relocator():
        rng = np.random.default_rng(11)
        try:
            while not stop.is_set():
                keys = np.unique(rng.integers(0, 64, 6))
                s._relocate_to(keys, int(rng.integers(0, s.num_shards)))
                time.sleep(0.001)
        except BaseException as e:  # noqa: BLE001
            errs.append(e)

    def syncer():
        try:
            while not stop.is_set():
                with s._round_lock:
                    s.sync.run_round(all_channels=True)
                time.sleep(0.001)
        except BaseException as e:  # noqa: BLE001
            errs.append(e)

    threads = [threading.Thread(target=client, args=(w0, 0, 16)),
               threading.Thread(target=client, args=(w1, 16, 32)),
               threading.Thread(target=relocator),
               threading.Thread(target=syncer)]
    for t in threads[:2]:
        t.start()
    for t in threads[2:]:
        t.start()
    for t in threads[:2]:
        t.join(timeout=120)
        assert not t.is_alive(), "serve client hung"
    stop.set()
    for t in threads[2:]:
        t.join(timeout=60)
        assert not t.is_alive()
    assert not errs, errs[:3]
    plane.close()
    s.shutdown()


def test_readiness_flips_on_stale_peer(ctx):
    """ISSUE 4 satellite: heartbeat/dead-node detection is DETECTION-
    ONLY — a stale peer flips the readiness signal while the request
    queue keeps serving (never hangs)."""
    s = make_server(ctx)
    w = s.make_worker(0)
    vals = _seed(w)
    dead = []
    plane = ServePlane(s, dead_nodes_fn=lambda: list(dead))
    sess = plane.session()
    r = plane.health.readiness()
    assert r["ready"] and r["dead_nodes"] == []
    assert plane.health.liveness()["dispatcher_alive"]
    # a peer's heartbeat goes stale: not ready, reason names it...
    dead.append(2)
    r = plane.health.readiness()
    assert not r["ready"] and r["dead_nodes"] == [2]
    assert any("stale peer" in x for x in r["reasons"])
    snap = s.metrics_snapshot()
    assert snap["serve"]["ready"] == 0
    assert snap["serve"]["dead_peers"] == 1
    assert snap["serve"]["readiness"]["dead_nodes"] == [2]
    # ...but the queue is NOT hung: lookups still serve promptly
    t0 = time.monotonic()
    assert np.array_equal(sess.lookup(np.array([5]))[0], vals[5])
    assert time.monotonic() - t0 < 10.0
    # detection clears -> ready again
    dead.clear()
    assert plane.health.readiness()["ready"]
    plane.close()
    s.shutdown()


def test_serve_snapshot_section_and_lifecycle(ctx):
    s = make_server(ctx)
    w = s.make_worker(0)
    _seed(w)
    # before any plane: the section exists (schema stability) but is {}
    snap = s.metrics_snapshot()
    assert snap["schema_version"] == 7 and snap["serve"] == {}
    plane = ServePlane(s)
    # one live plane per server
    with pytest.raises(RuntimeError):
        ServePlane(s)
    sess = plane.session()
    sess.lookup(np.array([1, 2, 3]))
    snap = s.metrics_snapshot()
    for key in ("lookups_total", "batches_total", "keys_total",
                "keys_deduped_total", "latency_s", "batch_size",
                "queue_depth", "shed_total", "rejected_total", "ready",
                "dead_peers", "readiness"):
        assert key in snap["serve"], key
    assert snap["serve"]["lookups_total"] >= 1
    assert snap["serve"]["latency_s"]["count"] >= 1
    plane.close()
    # close() is loud for queued work and final for this plane...
    with pytest.raises(RuntimeError):
        sess.lookup(np.array([1]))
    # ...but a NEW plane may be built on the same server (shared serve.*
    # metrics are reused; gauges rebind to the new plane's structures)
    plane2 = ServePlane(s)
    assert np.array_equal(plane2.session().lookup(np.array([1])),
                          w.pull_sync(np.array([1])))
    assert s.metrics_snapshot()["serve"]["ready"] == 1
    # Server.shutdown closes an attached plane (no dangling dispatcher)
    s.shutdown()
    assert not plane2.batcher.is_alive()


def test_serve_works_with_metrics_off(ctx):
    """--sys.metrics 0: the plane serves correctly on null metrics (the
    shed/reject accounting degrades to standalone counters)."""
    s = make_server(ctx, opts=SystemOptions(sync_max_per_sec=0,
                                            metrics=False))
    w = s.make_worker(0)
    vals = _seed(w)
    plane = ServePlane(s, start=False)
    sess = plane.session()
    with pytest.raises(DeadlineExceededError):
        sess.lookup(np.array([1]), deadline_ms=20)
    assert plane.queue.c_shed.value >= 1  # standalone counter
    plane.start()
    assert np.array_equal(sess.lookup(np.array([8]))[0], vals[8])
    assert s.metrics_snapshot()["serve"] == {}
    plane.close()
    s.shutdown()


def test_serve_default_deadline_from_opts(ctx):
    """--sys.serve.deadline_ms sets the per-request default."""
    s = make_server(ctx, opts=SystemOptions(sync_max_per_sec=0,
                                            serve_deadline_ms=25.0))
    w = s.make_worker(0)
    _seed(w)
    plane = ServePlane(s, start=False)
    sess = plane.session()
    with pytest.raises(DeadlineExceededError):
        sess.lookup(np.array([1]))   # default deadline applies
    # an explicit deadline_ms=0 overrides to "no deadline"
    req_served = []

    def late():
        req_served.append(sess.lookup(np.array([2]), deadline_ms=0))

    t = threading.Thread(target=late)
    t.start()
    time.sleep(0.1)
    plane.start()
    t.join(timeout=30)
    assert not t.is_alive() and len(req_served) == 1
    plane.close()
    s.shutdown()
