"""Online serving plane (ISSUE 4 tentpole): coalesced read path with
admission control, deadlines, and snapshot-consistent lookups.

Tier-1 coverage for adapm_tpu/serve:

  - lookup correctness vs `Worker.pull` (duplicates, mixed length
    classes, empty batches);
  - micro-batch coalescing: N queued requests -> ONE dispatcher batch,
    deduplicated union keys;
  - admission control: a full bounded queue rejects loudly
    (ServeOverloadError), a passed deadline sheds loudly
    (DeadlineExceededError) — never a hang;
  - the ACCEPTANCE storm: randomized interleaving of serve lookups,
    pushes, sets, relocations, replica churn, and sync rounds — every
    lookup bit-identical to a plain `Worker.pull` of the same keys at
    the same point, read-your-writes included;
  - a concurrent (threaded) storm: serve clients + pushers + a
    relocator + a sync driver, exact additive-sum invariants, bounded
    joins (no hang);
  - readiness: a stale peer heartbeat flips readiness (detection-only,
    docs/failure_handling.md) WITHOUT hanging the request queue;
  - the serve section of metrics_snapshot (schema_version 3) and the
    plane lifecycle (one live plane per server, close/reopen, shutdown
    closes the plane).
"""
import threading
import time

import numpy as np
import pytest

from adapm_tpu import Server, SystemOptions, make_mesh
from adapm_tpu.serve import (DeadlineExceededError, LookupRequest,
                             ServeOverloadError, ServePlane)

NK = 96
VL = 4


@pytest.fixture(scope="module")
def ctx():
    return make_mesh(8)


def make_server(ctx, num_keys=NK, vlen=VL, **kw):
    opts = kw.pop("opts", None) or SystemOptions(sync_max_per_sec=0)
    return Server(num_keys, vlen, opts=opts, ctx=ctx, **kw)


def _seed(w, num_keys=NK, vlen=VL):
    keys = np.arange(num_keys)
    vals = (np.arange(num_keys * vlen, dtype=np.float32)
            .reshape(num_keys, vlen))
    w.wait(w.set(keys, vals))
    return vals


def test_lookup_matches_pull(ctx):
    s = make_server(ctx)
    w = s.make_worker(0)
    _seed(w)
    with ServePlane(s) as plane:
        sess = plane.session()
        for batch in (np.array([1, 5, 9]),
                      np.array([7, 7, 3, 7]),          # duplicates
                      np.arange(NK),                    # everything
                      np.array([42])):
            got = sess.lookup(batch)
            ref = w.pull_sync(batch)
            assert np.array_equal(got, ref), batch
        assert sess.lookup([]).size == 0
        # an out-of-range key fails ITS client at the session boundary
        # (it must not reach the dispatcher and poison a co-batch)
        with pytest.raises(IndexError):
            sess.lookup(np.array([NK]))
        with pytest.raises(IndexError):
            sess.lookup(np.array([-1]))
        # the plane still serves after the rejection
        assert np.array_equal(sess.lookup(np.array([0])),
                              w.pull_sync(np.array([0])))
    s.shutdown()


def test_lookup_mixed_length_classes(ctx):
    """Ragged batches span length classes: one fused gather per class,
    reassembled flat exactly like pull_sync."""
    lens = np.where(np.arange(32) % 3 == 0, 8, 4)
    s = Server(32, lens, opts=SystemOptions(sync_max_per_sec=0), ctx=ctx)
    w = s.make_worker(0)
    flat = np.arange(lens.sum(), dtype=np.float32)
    w.wait(w.set(np.arange(32), flat))
    with ServePlane(s) as plane:
        sess = plane.session()
        batch = np.array([0, 1, 3, 6, 2, 0])  # mixed classes + duplicate
        got = sess.lookup(batch)
        ref = w.pull_sync(batch)
        assert got.ndim == 1 and np.array_equal(got, ref)
    s.shutdown()


def test_coalesced_batch_single_dispatch(ctx):
    """N requests queued while the dispatcher is paused are served by
    ONE micro-batch: one deduplicated union gather, every request's
    values correct (deterministic — no timing assumptions)."""
    s = make_server(ctx)
    w = s.make_worker(0)
    vals = _seed(w)
    plane = ServePlane(s, start=False)
    reqs = [LookupRequest(np.array([i, i + 1, 40])) for i in range(8)]
    for r in reqs:
        plane.queue.submit(r)
    b0 = s.obs.find("serve.batches_total").value
    plane.start()
    for i, r in enumerate(reqs):
        assert r.wait(30), "request not served"
        got = r.take_result().reshape(3, VL)
        assert np.array_equal(got, vals[[i, i + 1, 40]])
    assert s.obs.find("serve.batches_total").value == b0 + 1
    assert s.obs.find("serve.batch_size").snap()["max"] == 8.0
    # the union was deduplicated: 8 requests x 3 keys share key 40 and
    # overlap pairwise -> far fewer unique keys than submitted keys
    assert s.obs.find("serve.keys_deduped_total").value < \
        s.obs.find("serve.keys_total").value
    plane.close()
    s.shutdown()


def test_backpressure_rejects_loudly(ctx):
    s = make_server(ctx)
    w = s.make_worker(0)
    vals = _seed(w)
    opts = SystemOptions(sync_max_per_sec=0, serve_queue=4,
                         serve_max_batch=4)
    plane = ServePlane(s, opts=opts, start=False)
    reqs = [LookupRequest(np.array([i])) for i in range(4)]
    for r in reqs:
        plane.queue.submit(r)
    sess = plane.session()
    with pytest.raises(ServeOverloadError):
        sess.lookup(np.array([9]))
    assert s.obs.find("serve.rejected_total").value >= 1
    # backpressure is transient: once the dispatcher drains, admission
    # resumes and the queued requests were all served correctly
    plane.start()
    for i, r in enumerate(reqs):
        assert r.wait(30)
        assert np.array_equal(r.take_result(), vals[i])
    assert np.array_equal(sess.lookup(np.array([9]))[0], vals[9])
    plane.close()
    s.shutdown()


def test_deadline_sheds_never_hangs(ctx):
    s = make_server(ctx)
    w = s.make_worker(0)
    vals = _seed(w)
    plane = ServePlane(s, start=False)  # paused: nothing will serve
    sess = plane.session()
    t0 = time.monotonic()
    with pytest.raises(DeadlineExceededError):
        sess.lookup(np.array([1]), deadline_ms=30)
    assert time.monotonic() - t0 < 5.0, "shed was not prompt"
    assert s.obs.find("serve.shed_total").value >= 1
    # the shed corpse still sits in the deque, but it is NOT live work:
    # depth (and hence readiness/queue_depth) must not count it
    assert plane.queue.depth() == 0
    # an already-expired request queued behind a live one is shed at
    # take time (dispatcher-side deadline check), the live one served
    dead = LookupRequest(np.array([2]), deadline_s=0.0)
    live = LookupRequest(np.array([3]))
    plane.queue.submit(dead)
    plane.queue.submit(live)
    plane.start()
    assert live.wait(30)
    assert np.array_equal(live.take_result(), vals[3])
    assert dead.wait(30)
    with pytest.raises(DeadlineExceededError):
        dead.take_result()
    # the plane keeps serving after sheds
    assert np.array_equal(sess.lookup(np.array([4]))[0], vals[4])
    plane.close()
    s.shutdown()


def test_serve_storm_bit_identical(ctx):
    """THE acceptance storm: a randomized (but deterministic) sequence
    of pushes, sets, relocations, replica churn, and sync rounds, with
    a serve lookup + plain `Worker.pull` of the same keys after every
    mutation — bit-identical at every read, read-your-writes included
    (the pull and the lookup route from the same shard as the serving
    plane, which is the consistency contract; docs/SERVING.md)."""
    s = make_server(ctx, opts=SystemOptions(sync_max_per_sec=0,
                                            cache_slots_per_shard=64,
                                            # lock-order sentinel rides
                                            # the storm (ISSUE 11)
                                            lint_lockorder=True))
    w0 = s.make_worker(0)   # shard 0 — the serve plane's shard
    w1 = s.make_worker(1)   # shard 1 — a second writer + replica holder
    _seed(w0)
    plane = ServePlane(s)
    sess = plane.session(worker=w0)
    rng = np.random.default_rng(7)
    for step in range(50):
        op = rng.integers(0, 6)
        kset = np.unique(rng.integers(0, NK, rng.integers(1, 9)))
        if op == 0:
            w0.push(kset, rng.normal(size=(len(kset), VL))
                    .astype(np.float32))
        elif op == 1:
            w1.push(kset, rng.normal(size=(len(kset), VL))
                    .astype(np.float32))
        elif op == 2:
            w0.set(kset, rng.normal(size=(len(kset), VL))
                   .astype(np.float32))
        elif op == 3:
            s._relocate_to(kset, int(rng.integers(0, s.num_shards)))
        elif op == 4:
            # replica churn: a short-lived intent window on shard 1
            w1.intent(kset, w1.current_clock, w1.current_clock + 2)
            with s._round_lock:
                s.sync.run_round(force_intents=True, all_channels=True)
            w1.advance_clock()
        else:
            with s._round_lock:
                s.sync.run_round(all_channels=True)
        batch = rng.integers(0, NK, 12)  # duplicates allowed
        got = sess.lookup(batch)
        ref = w0.pull_sync(batch)
        assert np.array_equal(got, ref), f"step {step} (op {op}) diverged"
    assert s.obs.find("serve.lookups_total").value == 50
    plane.close()
    s.shutdown()
    # lock-order sentinel: the serve/admission locks joined the graph
    # and nothing cycled (dynamic half of APM001/APM002; ISSUE 11)
    from adapm_tpu.lint import lockorder
    sen = lockorder.get_sentinel()
    assert sen is not None and sen.edges(), \
        "sentinel saw no lock edges: the storm exercised nothing"
    sen.assert_clean()
    lockorder.disable_sentinel()


def test_serve_concurrent_storm_no_hang(ctx):
    """Concurrent clients, writers, a relocator, and a sync driver: the
    additive-sum invariant holds exactly (each client's disjoint key
    slice reads exactly its own push count — coalesced lookups are
    ordered with the client's pushes), and every thread joins within
    its bound (reject/shed loudly, never hang)."""
    s = make_server(ctx, num_keys=64,
                    opts=SystemOptions(sync_max_per_sec=0))
    w0, w1 = s.make_worker(0), s.make_worker(1)
    w0.wait(w0.set(np.arange(64), np.zeros((64, VL), np.float32)))
    plane = ServePlane(s)
    errs = []
    stop = threading.Event()

    def client(w, lo, hi):
        # pushes land on owner main rows (no replicas of these keys —
        # no intents are signalled for them), so a coalesced lookup
        # observes exactly the pushes dispatched before it
        try:
            sess = plane.session(worker=w)
            mine = np.arange(lo, hi)
            for n in range(1, 31):
                w.push(mine, np.ones((len(mine), VL), np.float32))
                got = sess.lookup(mine)
                if not np.array_equal(
                        got, np.full((len(mine), VL), float(n))):
                    errs.append((lo, n, got))
                    return
        except BaseException as e:  # noqa: BLE001
            errs.append(e)

    def relocator():
        rng = np.random.default_rng(11)
        try:
            while not stop.is_set():
                keys = np.unique(rng.integers(0, 64, 6))
                s._relocate_to(keys, int(rng.integers(0, s.num_shards)))
                time.sleep(0.001)
        except BaseException as e:  # noqa: BLE001
            errs.append(e)

    def syncer():
        try:
            while not stop.is_set():
                with s._round_lock:
                    s.sync.run_round(all_channels=True)
                time.sleep(0.001)
        except BaseException as e:  # noqa: BLE001
            errs.append(e)

    threads = [threading.Thread(target=client, args=(w0, 0, 16)),
               threading.Thread(target=client, args=(w1, 16, 32)),
               threading.Thread(target=relocator),
               threading.Thread(target=syncer)]
    for t in threads[:2]:
        t.start()
    for t in threads[2:]:
        t.start()
    for t in threads[:2]:
        t.join(timeout=120)
        assert not t.is_alive(), "serve client hung"
    stop.set()
    for t in threads[2:]:
        t.join(timeout=60)
        assert not t.is_alive()
    assert not errs, errs[:3]
    plane.close()
    s.shutdown()


def test_readiness_flips_on_stale_peer(ctx):
    """ISSUE 4 satellite: heartbeat/dead-node detection is DETECTION-
    ONLY — a stale peer flips the readiness signal while the request
    queue keeps serving (never hangs)."""
    s = make_server(ctx)
    w = s.make_worker(0)
    vals = _seed(w)
    dead = []
    plane = ServePlane(s, dead_nodes_fn=lambda: list(dead))
    sess = plane.session()
    r = plane.health.readiness()
    assert r["ready"] and r["dead_nodes"] == []
    assert plane.health.liveness()["dispatcher_alive"]
    # a peer's heartbeat goes stale: not ready, reason names it...
    dead.append(2)
    r = plane.health.readiness()
    assert not r["ready"] and r["dead_nodes"] == [2]
    assert any("stale peer" in x for x in r["reasons"])
    snap = s.metrics_snapshot()
    assert snap["serve"]["ready"] == 0
    assert snap["serve"]["dead_peers"] == 1
    assert snap["serve"]["readiness"]["dead_nodes"] == [2]
    # ...but the queue is NOT hung: lookups still serve promptly
    t0 = time.monotonic()
    assert np.array_equal(sess.lookup(np.array([5]))[0], vals[5])
    assert time.monotonic() - t0 < 10.0
    # detection clears -> ready again
    dead.clear()
    assert plane.health.readiness()["ready"]
    plane.close()
    s.shutdown()


def test_serve_snapshot_section_and_lifecycle(ctx):
    s = make_server(ctx)
    w = s.make_worker(0)
    _seed(w)
    # before any plane: the section exists (schema stability) but is {}
    snap = s.metrics_snapshot()
    assert snap["schema_version"] == 16 and snap["serve"] == {}
    plane = ServePlane(s)
    # one live plane per server
    with pytest.raises(RuntimeError):
        ServePlane(s)
    sess = plane.session()
    sess.lookup(np.array([1, 2, 3]))
    snap = s.metrics_snapshot()
    for key in ("lookups_total", "batches_total", "keys_total",
                "keys_deduped_total", "latency_s", "batch_size",
                "queue_depth", "shed_total", "rejected_total", "ready",
                "dead_peers", "readiness"):
        assert key in snap["serve"], key
    assert snap["serve"]["lookups_total"] >= 1
    assert snap["serve"]["latency_s"]["count"] >= 1
    plane.close()
    # close() is loud for queued work and final for this plane...
    with pytest.raises(RuntimeError):
        sess.lookup(np.array([1]))
    # ...but a NEW plane may be built on the same server (shared serve.*
    # metrics are reused; gauges rebind to the new plane's structures)
    plane2 = ServePlane(s)
    assert np.array_equal(plane2.session().lookup(np.array([1])),
                          w.pull_sync(np.array([1])))
    assert s.metrics_snapshot()["serve"]["ready"] == 1
    # Server.shutdown closes an attached plane (no dangling dispatcher)
    s.shutdown()
    assert not plane2.batcher.is_alive()


def test_serve_works_with_metrics_off(ctx):
    """--sys.metrics 0: the plane serves correctly on null metrics (the
    shed/reject accounting degrades to standalone counters)."""
    s = make_server(ctx, opts=SystemOptions(sync_max_per_sec=0,
                                            metrics=False))
    w = s.make_worker(0)
    vals = _seed(w)
    plane = ServePlane(s, start=False)
    sess = plane.session()
    with pytest.raises(DeadlineExceededError):
        sess.lookup(np.array([1]), deadline_ms=20)
    assert plane.queue.c_shed.value >= 1  # standalone counter
    plane.start()
    assert np.array_equal(sess.lookup(np.array([8]))[0], vals[8])
    assert s.metrics_snapshot()["serve"] == {}
    plane.close()
    s.shutdown()


def test_serve_default_deadline_from_opts(ctx):
    """--sys.serve.deadline_ms sets the per-request default."""
    s = make_server(ctx, opts=SystemOptions(sync_max_per_sec=0,
                                            serve_deadline_ms=25.0))
    w = s.make_worker(0)
    _seed(w)
    plane = ServePlane(s, start=False)
    sess = plane.session()
    with pytest.raises(DeadlineExceededError):
        sess.lookup(np.array([1]))   # default deadline applies
    # an explicit deadline_ms=0 overrides to "no deadline"
    req_served = []

    def late():
        req_served.append(sess.lookup(np.array([2]), deadline_ms=0))

    t = threading.Thread(target=late)
    t.start()
    time.sleep(0.1)
    plane.start()
    t.join(timeout=30)
    assert not t.is_alive() and len(req_served) == 1
    plane.close()
    s.shutdown()


# ---------------------------------------------------------------------------
# ISSUE 9: read-only serve replicas, sharded dispatch, tenant-aware admission
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("tiered", [False, True])
def test_replica_storm_bit_identical(ctx, tiered):
    """THE r9 acceptance storm extended to the replica path (ISSUE 9):
    randomized push/set/relocate/sync/replica-churn (+ tier
    promote/demote when tiered) with the read-only snapshot refreshed
    mid-storm — every lookup bit-identical to `Worker.pull` of the same
    keys, including snapshot-stale fallbacks (a bumped write epoch or a
    moved topology forces the exact locked path) and same-session
    read-your-writes. Asserts the fast path actually fired (hits > 0)
    AND actually fell back (stale fallbacks > 0), so neither branch is
    vacuously green."""
    opts = SystemOptions(sync_max_per_sec=0, cache_slots_per_shard=64,
                         serve_replica_rows=48,
                         serve_replica_refresh_ms=1.0)
    if tiered:
        opts.tier = True
        opts.tier_hot_rows = 8   # force a live cold path under the storm
    s = make_server(ctx, opts=opts)
    w0 = s.make_worker(0)   # shard 0 — the serve plane's shard
    w1 = s.make_worker(1)   # shard 1 — a second writer + replica holder
    _seed(w0)
    plane = ServePlane(s)
    sess = plane.session(worker=w0)
    rep = plane.replica
    assert rep is not None
    hot = np.arange(24)     # the working set the snapshot should cover
    # deterministic warm-up: build serve-load scores, snapshot, and pin
    # the first replica-path hit + the first epoch-staleness fallback
    assert np.array_equal(sess.lookup(hot), w0.pull_sync(hot))
    assert rep.refresh_now() > 0
    h0 = s.obs.find("serve.replica_hits_total").value
    assert np.array_equal(sess.lookup(hot), w0.pull_sync(hot))
    assert s.obs.find("serve.replica_hits_total").value == h0 + 1
    w0.wait(w0.push(hot[:2], np.ones((2, VL), np.float32)))
    # the push bumped the rows' write epochs: the very next lookup must
    # fall back to the locked path and still read its own write
    assert np.array_equal(sess.lookup(hot), w0.pull_sync(hot))
    assert s.obs.find("serve.replica_stale_fallbacks_total").value >= 1
    rng = np.random.default_rng(7)
    for step in range(50):
        op = rng.integers(0, 7)
        kset = np.unique(rng.integers(0, NK, rng.integers(1, 9)))
        if op == 0:
            w0.push(kset, rng.normal(size=(len(kset), VL))
                    .astype(np.float32))
        elif op == 1:
            w1.push(kset, rng.normal(size=(len(kset), VL))
                    .astype(np.float32))
        elif op == 2:
            w0.set(kset, rng.normal(size=(len(kset), VL))
                   .astype(np.float32))
        elif op == 3:
            s._relocate_to(kset, int(rng.integers(0, s.num_shards)))
        elif op == 4:
            # replica churn: a short-lived intent window on shard 1
            w1.intent(kset, w1.current_clock, w1.current_clock + 2)
            with s._round_lock:
                s.sync.run_round(force_intents=True, all_channels=True)
            w1.advance_clock()
        elif op == 5:
            with s._round_lock:
                s.sync.run_round(all_channels=True)
        else:
            if s.tier is not None:  # promotion/demotion churn (tiered)
                s.tier.demote_keys(kset)
                s.tier.promote_keys(kset[: len(kset) // 2 + 1])
        if step % 6 == 0:
            rep.refresh_now()   # mid-storm snapshot rebuilds
        for batch in (np.concatenate([rng.integers(0, NK, 6),
                                      rng.choice(hot, 6)]),
                      hot):
            got = sess.lookup(batch)
            ref = w0.pull_sync(batch)
            assert np.array_equal(got, ref), \
                f"step {step} (op {op}) diverged"
    assert s.obs.find("serve.replica_hits_total").value > h0
    plane.close()
    s.shutdown()


def test_replica_mixed_length_classes(ctx):
    """Replica-path hits across length classes assemble the ragged flat
    result exactly like the locked path."""
    lens = np.where(np.arange(32) % 3 == 0, 8, 4)
    opts = SystemOptions(sync_max_per_sec=0, serve_replica_rows=32,
                         serve_replica_refresh_ms=1.0)
    s = Server(32, lens, opts=opts, ctx=ctx)
    w = s.make_worker(0)
    flat = np.arange(lens.sum(), dtype=np.float32)
    w.wait(w.set(np.arange(32), flat))
    with ServePlane(s) as plane:
        sess = plane.session()
        batch = np.array([0, 1, 3, 6, 2, 0])  # mixed classes + duplicate
        ref = w.pull_sync(batch)
        assert np.array_equal(sess.lookup(batch), ref)
        assert plane.replica.refresh_now() > 0
        h0 = s.obs.find("serve.replica_hits_total").value
        assert np.array_equal(sess.lookup(batch), ref)
        assert s.obs.find("serve.replica_hits_total").value == h0 + 1
    s.shutdown()


def test_multi_consumer_take_exactly_once(ctx):
    """N concurrent consumers on ONE queue claim disjoint request sets
    (the claim/shed state machine is N-consumer safe — the property the
    sharded dispatchers rely on), with client sheds racing the claims:
    every request ends exactly one of claimed / shed, never both."""
    from adapm_tpu.serve.admission import AdmissionQueue
    q = AdmissionQueue(1024)
    reqs = [LookupRequest(np.array([i])) for i in range(300)]
    for r in reqs:
        q.submit(r)
    # a racing client sheds a third of them while consumers claim
    shed_set = [r for i, r in enumerate(reqs) if i % 3 == 0]
    claimed = [[] for _ in range(4)]
    errs = []

    def consumer(ci):
        try:
            while True:
                batch = q.take(7, 0.0, block=False)
                if not batch:
                    return
                claimed[ci].extend(batch)
        except BaseException as e:  # noqa: BLE001
            errs.append(e)

    def shedder():
        try:
            for r in shed_set:
                r.try_shed()
        except BaseException as e:  # noqa: BLE001
            errs.append(e)

    threads = [threading.Thread(target=consumer, args=(ci,))
               for ci in range(4)] + [threading.Thread(target=shedder)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
        assert not t.is_alive()
    assert not errs, errs[:3]
    got = [int(r.keys[0]) for c in claimed for r in c]
    assert len(got) == len(set(got)), "a request was claimed twice"
    for r in reqs:  # exactly one terminal state each
        assert r.claimed != (r._state == 2), int(r.keys[0])
    assert q.depth() == 0


def test_admission_priority_preemption_and_compaction_race(ctx):
    """ISSUE 9 satellite: at a full queue a higher-priority submission
    preempts (sheds) the lowest-priority pending request instead of
    being rejected; bound accounting stays exact while low-priority
    corpses are compacted out under a racing high-priority take."""
    from adapm_tpu.serve.admission import AdmissionQueue
    q = AdmissionQueue(8)
    lo = q.configure_tenant("lo", priority=0)
    hi = q.configure_tenant("hi", priority=2)
    lows = [LookupRequest(np.array([i]), tenant=lo, priority=0)
            for i in range(8)]
    for r in lows:
        q.submit(r)
    assert q.depth() == 8
    # same-priority submission at bound: plain rejection (no preemption
    # of an equal class)
    with pytest.raises(ServeOverloadError):
        q.submit(LookupRequest(np.array([90]), tenant=lo, priority=0))
    assert lo.c_rejected.value == 1
    # higher priority preempts: one low sheds loudly, the high admits
    h0 = LookupRequest(np.array([91]), tenant=hi, priority=2)
    q.submit(h0)
    assert q.depth() == 8          # bound exact: 7 lows + 1 high
    shed = [r for r in lows if r._done.is_set()]
    assert len(shed) == 1 and lo.c_shed.value == 1
    with pytest.raises(ServeOverloadError):
        shed[0].take_result()
    # fair-share take: the high-priority request is claimed FIRST even
    # though it arrived last (no FIFO starvation under pressure)
    batch = q.take(3, 0.0, block=False)
    assert batch[0] is h0
    # racing segment: a taker drains while high-priority submissions
    # keep preempting/admitting — conservation must hold exactly
    taken = list(batch)
    stop = threading.Event()
    errs = []

    def taker():
        try:
            while not stop.is_set():
                taken.extend(q.take(2, 0.0, block=False))
        except BaseException as e:  # noqa: BLE001
            errs.append(e)

    t = threading.Thread(target=taker)
    t.start()
    highs = []
    rejected = 0
    for i in range(64):
        r = LookupRequest(np.array([100 + i]), tenant=hi, priority=2)
        try:
            q.submit(r)
            highs.append(r)
        except ServeOverloadError:
            rejected += 1
    time.sleep(0.05)
    stop.set()
    t.join(timeout=30)
    assert not t.is_alive()
    taken.extend(q.take(64, 0.0, block=False))
    assert not errs, errs[:3]
    # exact accounting: every admitted request is exactly one of
    # claimed / shed; nothing lost, nothing double-counted
    for r in lows + [h0] + highs:
        assert r.claimed != (r._state == 2), int(r.keys[0])
    n_shed = sum(1 for r in lows + [h0] + highs if r._state == 2)
    assert len(taken) + n_shed == len(lows) + 1 + len(highs)
    assert len(set(id(r) for r in taken)) == len(taken)
    assert q.depth() == 0


def test_tenant_quota_and_fair_share(ctx):
    """Token-bucket quotas reject at submit (quota backpressure, typed
    + counted per tenant); batch formation serves the higher priority
    class first and fair-shares slots across tenants within a class."""
    s = make_server(ctx)
    w = s.make_worker(0)
    vals = _seed(w)
    plane = ServePlane(s, start=False)
    bz = plane.configure_tenant("bronze", priority=0, qps=0.5, burst=2)
    plane.configure_tenant("gold", priority=1)
    gold = plane.queue.tenant("gold")
    silver = plane.configure_tenant("silver", priority=1)
    # bronze burst=2: two admits, third rejects on the dry bucket
    b1 = LookupRequest(np.array([1]), tenant=bz)
    b2 = LookupRequest(np.array([2]), tenant=bz)
    plane.queue.submit(b1)
    plane.queue.submit(b2)
    with pytest.raises(ServeOverloadError):
        plane.queue.submit(LookupRequest(np.array([3]), tenant=bz))
    assert bz.c_rejected.value == 1
    # queue now: bronze, bronze; add gold+silver (priority 1) — a
    # 4-slot batch claims the priority-1 class first, round-robin
    # across gold/silver, and stays PRIORITY-PURE (bronze keys must
    # not ride the high class's union gather); the next take serves
    # the bronzes
    g1 = LookupRequest(np.array([4]), tenant=gold, priority=1)
    g2 = LookupRequest(np.array([5]), tenant=gold, priority=1)
    s1 = LookupRequest(np.array([6]), tenant=silver, priority=1)
    for r in (g1, g2, s1):
        plane.queue.submit(r)
    batch = plane.queue.take(4, 0.0, block=False)
    assert [int(r.priority) for r in batch] == [1, 1, 1]
    assert {r.tenant.name for r in batch[:2]} == {"gold", "silver"}, \
        "fair share must alternate tenants within the priority class"
    batch2 = plane.queue.take(4, 0.0, block=False)
    assert set(batch2) == {b1, b2}
    # end to end: a started plane serves tenant sessions and counts
    # per-tenant serves in the snapshot (schema v8)
    plane.start()
    sess = plane.session(tenant="gold")
    assert np.array_equal(sess.lookup(np.array([7]))[0], vals[7])
    snap = s.metrics_snapshot()
    assert snap["serve"]["tenant.gold.served_total"] >= 1
    assert snap["serve"]["tenant.bronze.rejected_total"] == 1
    plane.close()
    s.shutdown()


def test_sharded_dispatchers_serve_concurrently(ctx):
    """--sys.serve.dispatchers N: N lanes on N executor streams serve
    concurrent clients correctly (exactly-once, bit-identical), the
    per-lane depth gauges exist (schema v8), and all N streams were
    exercised."""
    opts = SystemOptions(sync_max_per_sec=0, serve_dispatchers=3)
    s = make_server(ctx, opts=opts)
    w = s.make_worker(0)
    vals = _seed(w)
    plane = ServePlane(s)
    errs = []

    def client(ci):
        try:
            sess = plane.session()
            rng = np.random.default_rng(ci)
            for _ in range(20):
                batch = rng.integers(0, NK, 8)
                got = sess.lookup(batch)
                if not np.array_equal(got, vals[batch]):
                    errs.append((ci, batch))
                    return
        except BaseException as e:  # noqa: BLE001
            errs.append(e)

    threads = [threading.Thread(target=client, args=(ci,))
               for ci in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
        assert not t.is_alive()
    assert not errs, errs[:3]
    snap = s.metrics_snapshot()
    for i in range(3):
        assert f"lane_depth.{i}" in snap["serve"]
        assert snap["serve"][f"lane_depth.{i}"] == 0  # all drained
    # round-robin lane assignment spread the load over every stream
    assert "queue_depth.serve.1" in snap["exec"]
    assert "queue_depth.serve.2" in snap["exec"]
    assert snap["serve"]["lookups_total"] >= 120
    plane.close()
    s.shutdown()


def test_wedged_dispatcher_flips_readiness(ctx):
    """ISSUE 9 satellite: ONE wedged dispatcher of N flips
    `serve.ready` within the wedge bound — the probe reads busy stamps
    lock-free, never hanging behind the stuck drain — while the
    healthy dispatchers keep serving; recovery clears the signal."""
    opts = SystemOptions(sync_max_per_sec=0, serve_dispatchers=2)
    s = make_server(ctx, opts=opts)
    w = s.make_worker(0)
    vals = _seed(w)
    plane = ServePlane(s)
    plane.health.wedge_s = 0.3   # injectable bound (default 30 s)
    gate = threading.Event()
    orig = plane.batcher._serve_batch

    def stuck(reqs):
        if any(int(r.keys[0]) == 77 for r in reqs):
            gate.wait(30)   # the injected wedge
        return orig(reqs)

    plane.batcher._serve_batch = stuck
    assert plane.health.readiness()["ready"]
    wedge_req = LookupRequest(np.array([77]), lane=1)
    plane.queue.submit(wedge_req)
    deadline = time.monotonic() + 10
    flipped = False
    while time.monotonic() < deadline:
        t0 = time.monotonic()
        rd = plane.health.readiness()
        assert time.monotonic() - t0 < 5.0, "readiness probe blocked"
        if not rd["ready"] and rd["wedged_dispatchers"] == [1]:
            assert any("wedged" in x for x in rd["reasons"])
            flipped = True
            break
        time.sleep(0.02)
    assert flipped, "wedged dispatcher did not flip readiness in bound"
    assert s.metrics_snapshot()["serve"]["ready"] == 0
    # the healthy dispatcher (lane 0) still serves while 1 is stuck
    ok_req = LookupRequest(np.array([3]), lane=0)
    plane.queue.submit(ok_req)
    assert ok_req.wait(30)
    assert np.array_equal(ok_req.take_result(), vals[3])
    # release the wedge: the claimed request completes, ready recovers
    gate.set()
    assert wedge_req.wait(30)
    assert np.array_equal(wedge_req.take_result(), vals[77])
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        rd = plane.health.readiness()
        if rd["ready"] and rd["wedged_dispatchers"] == []:
            break
        time.sleep(0.02)
    assert plane.health.readiness()["ready"]
    plane.batcher._serve_batch = orig
    plane.close()
    s.shutdown()


def test_dispatchers_one_no_tenants_is_r13_inert(ctx):
    """Acceptance pin: the default knobs (--sys.serve.dispatchers 1, no
    tenants, no replica) keep the single-consumer FIFO path and carry
    the schema-v8 serve sections present-but-inert."""
    s = make_server(ctx)
    w = s.make_worker(0)
    vals = _seed(w)
    plane = ServePlane(s)
    assert plane.batcher.dispatchers == 1
    assert plane.replica is None
    assert plane.queue.lanes == 1 and not plane.queue._has_qos
    sess = plane.session()
    assert np.array_equal(sess.lookup(np.array([5]))[0], vals[5])
    snap = s.metrics_snapshot()
    assert snap["serve"]["replica_hit_rate"] == 0.0
    assert snap["serve"]["replica_hits_total"] == 0
    assert snap["serve"]["lane_depth.0"] == 0
    assert snap["serve"]["readiness"]["dispatchers"] == 1
    assert snap["serve"]["readiness"]["wedged_dispatchers"] == []
    assert not any(k.startswith("tenant.") for k in snap["serve"])
    plane.close()
    s.shutdown()
