"""The main correctness suite, mirroring reference
tests/test_many_key_operations.cc three phases (:93-345):
  (1) pull+intent storm, (2) monotonic pushes (a pulled value may never be
  below the known floor), (3) eventual consistency (push then revert,
  quiesce, assert exact restore)."""
import numpy as np
import pytest

from adapm_tpu import Server, SystemOptions, MgmtTechniques, make_mesh

NK = 48
VL = 2


@pytest.fixture(scope="module")
def ctx():
    return make_mesh(4)


@pytest.fixture(params=[MgmtTechniques.ALL, MgmtTechniques.REPLICATION_ONLY,
                        MgmtTechniques.RELOCATION_ONLY])
def server(ctx, request):
    opts = SystemOptions(techniques=request.param, sync_max_per_sec=0)
    s = Server(NK, VL, opts=opts, ctx=ctx, num_workers=4)
    ws = [s.make_worker(i) for i in range(4)]
    return s, ws


def test_pull_intent_storm(server, rng):
    """Phase 1: random pulls and intents interleaved with sync rounds never
    produce wrong values (all zeros here since nothing is pushed)."""
    s, ws = server
    for it in range(15):
        w = ws[it % 4]
        keys = rng.choice(NK, size=rng.integers(1, 8), replace=False)
        w.intent(keys, w.current_clock, w.current_clock + 3)
        vals = w.pull_sync(keys)
        np.testing.assert_allclose(vals, 0.0)
        w.advance_clock()
        if it % 3 == 0:
            s.sync.run_round(all_channels=True)
    s.quiesce()


def test_monotonic_pushes(server, rng):
    """Phase 2: workers push only positive increments to a tracked key; any
    pull must see >= the per-worker known floor (own pushes are never lost)
    and <= the global total (nothing is double-applied)."""
    s, ws = server
    key = np.array([17])
    own_floor = np.zeros(4)
    total = 0.0
    for it in range(30):
        wid = int(rng.integers(4))
        w = ws[wid]
        inc = float(rng.integers(1, 3))
        w.push(key, np.full(VL, inc, np.float32))
        own_floor[wid] += inc
        total += inc
        if rng.random() < 0.3:
            w.intent(key, w.current_clock, w.current_clock + 2)
        if rng.random() < 0.4:
            s.sync.run_round(all_channels=True)
        v = w.pull_sync(key)[0, 0]
        assert v >= own_floor[wid] - 1e-4, (
            f"read-your-writes violated: {v} < {own_floor[wid]}")
        assert v <= total + 1e-4, f"over-applied: {v} > {total}"
        if rng.random() < 0.2:
            w.advance_clock()
    s.quiesce()
    for w in ws:
        np.testing.assert_allclose(w.pull_sync(key)[0, 0], total, rtol=1e-6)


def test_eventual_consistency_exact_restore(server, rng):
    """Phase 3: push a delta then its negation from another worker; after
    quiesce every worker reads the original value exactly
    (test_many_key_operations.cc:375-385)."""
    s, ws = server
    keys = np.arange(NK)
    base = rng.normal(size=(NK, VL)).astype(np.float32)
    ws[0].wait(ws[0].set(keys, base))
    s.quiesce()
    # storm: random +d then -d pairs from random workers under intents
    for it in range(20):
        w = ws[int(rng.integers(4))]
        k = rng.choice(NK, size=4, replace=False)
        d = rng.normal(size=(4, VL)).astype(np.float32)
        w.intent(k, w.current_clock, w.current_clock + 2)
        w.push(k, d)
        w2 = ws[int(rng.integers(4))]
        w2.push(k, -d)
        if it % 4 == 0:
            s.sync.run_round(all_channels=True)
        w.advance_clock()
    for w in ws:
        w.wait_all()
    s.quiesce()
    for w in ws:
        got = w.pull_sync(keys)
        np.testing.assert_allclose(got, base, atol=1e-4)


def test_relocation_preserves_value(ctx):
    """Stress the relocation path: bounce ownership of one key around while
    pushing; the final total must be exact (test_dynamic_allocation.cc)."""
    opts = SystemOptions(techniques=MgmtTechniques.RELOCATION_ONLY,
                         sync_max_per_sec=0)
    s = Server(NK, VL, opts=opts, ctx=ctx, num_workers=4)
    ws = [s.make_worker(i) for i in range(4)]
    key = np.array([5])
    total = 0.0
    for it in range(24):
        w = ws[it % 4]
        w.intent(key, w.current_clock, w.current_clock + 1)
        s.sync.run_round(force_intents=True, all_channels=True)  # relocate now
        w.push(key, np.ones(VL, np.float32))
        total += 1.0
        w.advance_clock()
    s.quiesce()
    for w in ws:
        np.testing.assert_allclose(w.pull_sync(key), total)
