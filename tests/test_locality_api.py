"""Locality semantics under intent: which shard holds which key, before and
after Intent, after expiry — mirroring reference tests/test_locality_api.cc
(:49-132, pinned to 3 servers there; we pin a 3-shard mesh here)."""
import numpy as np
import pytest

from adapm_tpu import LOCAL, Server, SystemOptions, MgmtTechniques, make_mesh


@pytest.fixture(scope="module")
def ctx():
    return make_mesh(3)


def fresh(ctx, **kw):
    opts = kw.pop("opts", SystemOptions())
    s = Server(30, 2, opts=opts, ctx=ctx, num_workers=3, **kw)
    ws = [s.make_worker(i) for i in range(3)]
    return s, ws


def test_initial_partition(ctx):
    """Before any intent, key k lives on its home shard k % S
    (reference addressbook.h:110-112)."""
    s, ws = fresh(ctx)
    for k in range(9):
        assert s.ab.owner[k] == k % 3
        assert s.ab.is_local(np.array([k]), k % 3).all()
        assert not s.ab.is_local(np.array([k]), (k + 1) % 3).any()


def test_local_op_returns_minus_one(ctx):
    s, ws = fresh(ctx)
    # worker 1 owns keys k % 3 == 1
    assert ws[1].pull(np.array([1, 4, 7])) == LOCAL
    assert ws[1].push(np.array([1]), np.ones(2, np.float32)) == LOCAL
    assert ws[1].pull(np.array([0])) != LOCAL
    ws[1].wait_all()


def test_exclusive_intent_relocates(ctx):
    """Single-shard interest => ownership moves (reference
    sync_manager.h:624-644: relocate iff nobody else wants it)."""
    s, ws = fresh(ctx)
    ws[0].intent([4], 0, 10)  # home shard 1
    ws[0].wait_sync()
    assert s.ab.owner[4] == 0
    assert len(s.ab.replica_shards(4)) == 0
    # relocated key now answers locally
    assert ws[0].pull(np.array([4])) == LOCAL


def test_competing_intent_replicates(ctx):
    s, ws = fresh(ctx)
    ws[0].intent([5], 0, 100)
    ws[0].wait_sync()
    assert s.ab.owner[5] == 0          # relocated to 0 (exclusive)
    ws[1].intent([5], 0, 100)
    ws[1].wait_sync()
    assert s.ab.owner[5] == 0          # stays: 0 still has interest
    assert list(s.ab.replica_shards(5)) == [1]
    # both shards answer locally now
    assert ws[0].pull(np.array([5])) == LOCAL
    assert ws[1].pull(np.array([5])) == LOCAL


def test_replica_expiry(ctx):
    """After workers' clocks pass the intent end, the replica is dropped
    (reference handle.h:542-578 lazy intent GC)."""
    s, ws = fresh(ctx)
    ws[0].intent([8], 0, 3)            # home shard 2
    ws[2].intent([8], 0, 3)            # competing interest
    s.wait_sync()
    # both interested shards are now local (one owns, one replicates —
    # which is which depends on drain order, as in the reference)
    assert s.ab.is_local(np.array([8]), 0).all()
    assert s.ab.is_local(np.array([8]), 2).all()
    assert s.ab.replica_count[8] == 1
    for _ in range(5):
        for w in ws:
            w.advance_clock()
    s.wait_sync()
    assert s.ab.replica_count[8] == 0
    # pending replica deltas were flushed, not lost
    # (drop goes through sync first)


def test_replica_drop_flushes_delta(ctx):
    s, ws = fresh(ctx)
    ws[0].intent([8], 0, 3)
    ws[2].intent([8], 0, 3)
    s.wait_sync()
    ws[0].push([8], np.full(2, 7.0, np.float32))  # lands in replica delta
    ws[0].wait_all()
    for _ in range(5):
        for w in ws:
            w.advance_clock()
    s.wait_sync()  # drop + flush
    np.testing.assert_allclose(ws[2].pull_sync([8]), 7.0)


def test_techniques_replication_only(ctx):
    opts = SystemOptions(techniques=MgmtTechniques.REPLICATION_ONLY)
    s, ws = fresh(ctx, opts=opts)
    ws[0].intent([4], 0, 10)
    ws[0].wait_sync()
    assert s.ab.owner[4] == 1           # home; never moved
    assert list(s.ab.replica_shards(4)) == [0]


def test_techniques_relocation_only(ctx):
    opts = SystemOptions(techniques=MgmtTechniques.RELOCATION_ONLY)
    s, ws = fresh(ctx, opts=opts)
    ws[0].intent([5], 0, 100)
    ws[0].wait_sync()
    assert s.ab.owner[5] == 0
    ws[1].intent([5], 0, 100)
    ws[1].wait_sync()
    # no replicas ever; ownership bounces to the latest requester
    assert s.ab.owner[5] == 1
    assert len(s.ab.replica_shards(5)) == 0


def test_intent_for_future_clock_not_acted_early(ctx):
    """Intents far in the future are registered but not acted on until the
    clock window reaches them (ActionTimer, sync_manager.h:62-105)."""
    s, ws = fresh(ctx)
    ws[0].intent([7], 1000, 1010)      # home shard 1; far future
    s.sync.run_round(all_channels=True)  # non-forced round
    assert s.ab.owner[7] == 1          # untouched: start is beyond window
    # once clocks approach, it acts
    for _ in range(999):
        ws[0].advance_clock()
    s.sync.run_round(all_channels=True)
    assert s.ab.is_local(np.array([7]), 0).all()
