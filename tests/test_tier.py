"""Tiered parameter storage (ISSUE 5 tentpole; adapm_tpu/tier).

The load-bearing test is THE acceptance storm: a randomized interleaving
of push / set / relocate / replica churn / sync rounds / promote /
demote against a tiered server, with an UNTIERED shadow server applying
the identical operation sequence — every read (read_main of the whole
table plus worker pulls of random batches) must be bit-identical at
every step and after quiesce. Residency moves values between the
device-hot pool and the host cold store; it must never change them.

Plus: capacity bounds (hot pool never exceeds --sys.tier.hot_rows),
intent pinning (pinned rows survive pressure demotion), checkpoint
save/restore with tiering (restored values bit-identical regardless of
pre-save residency; residency reset all-cold; dirty-delta sync tracking
consistent after restore), the tier metrics section (schema v4), and
the deterministic double-close shutdown contract.
"""
import numpy as np
import pytest

import adapm_tpu
from adapm_tpu.base import CLOCK_MAX
from adapm_tpu.config import SystemOptions

E = 384
L = 8


def _mk(tier: bool, hot_rows: int = 16, **kw):
    # Until PR 6, two-server tests had to null the tier worker's kick:
    # concurrent sharded-program dispatch from two lock domains could
    # deadlock XLA-CPU's collective rendezvous. The unified executor's
    # dispatch gate serializes every sharded enqueue process-wide
    # (docs/EXECUTOR.md), so the worker now runs EVERYWHERE — including
    # the two-servers-on-one-device storm below (the regression shape).
    opts = SystemOptions(sync_max_per_sec=0, prefetch=False,
                         tier=tier, tier_hot_rows=hot_rows, **kw)
    return adapm_tpu.setup(E, L, opts=opts)


def _read_all(srv):
    return np.asarray(srv.read_main(np.arange(E)))


def _assert_bitwise(srv, ref, tag):
    a, b = _read_all(srv), _read_all(ref)
    assert np.array_equal(a, b), (
        f"{tag}: tiered read diverged from untiered shadow "
        f"({int((a != b).sum())} floats differ)")


# ---------------------------------------------------------------------------
# THE acceptance storm
# ---------------------------------------------------------------------------


def test_tier_storm_bit_identical_to_untiered_shadow(rng):
    # runtime lock-order sentinel (ISSUE 11): the promote/demote/sync/
    # relocate churn takes server lock + gate + registry in every
    # combination this plane knows — a cycle raises here, named
    srv = _mk(True, hot_rows=16, lint_lockorder=True)
    ref = _mk(False)
    w, wr = srv.make_worker(0), ref.make_worker(0)
    vals = rng.normal(size=(E, L)).astype(np.float32)
    for ww in (w, wr):
        ww.set(np.arange(E), vals)
    keys = np.arange(E)
    for step in range(50):
        op = rng.integers(0, 7)
        if op == 0:      # additive push (with in-batch duplicates)
            ks = rng.integers(0, E, 24)
            v = rng.normal(size=(24, L)).astype(np.float32)
            w.push(ks, v)
            wr.push(ks, v)
        elif op == 1:    # set
            ks = rng.choice(E, 16, replace=False)
            v = rng.normal(size=(16, L)).astype(np.float32)
            w.set(ks, v)
            wr.set(ks, v)
        elif op == 2:    # relocation (identical on both servers)
            ks = rng.choice(E, 12, replace=False)
            dest = int(rng.integers(0, srv.num_shards))
            srv._relocate_to(ks, dest)
            ref._relocate_to(ks, dest)
        elif op == 3:    # replica churn: intent + forced round
            ks = rng.choice(keys[srv.ab.owner[keys] != w.shard], 16,
                            replace=False)
            end = int(w.current_clock + rng.integers(1, 4))
            w.intent(ks, w.current_clock, end)
            wr.intent(ks, wr.current_clock, end)
            srv.sync.run_round(force_intents=True, all_channels=True)
            ref.sync.run_round(force_intents=True, all_channels=True)
        elif op == 4:    # forced sync round (flush + expiry drops)
            srv.sync.run_round(force_intents=True, all_channels=True)
            ref.sync.run_round(force_intents=True, all_channels=True)
        elif op == 5:    # promotion (tiered only: must be value-invisible)
            srv.tier.promote_keys(rng.choice(E, 32, replace=False))
        else:            # demotion + a maintenance pass (tiered only)
            srv.tier.demote_keys(rng.choice(E, 32, replace=False))
            srv.tier.maintain()
        if rng.integers(0, 3) == 0:
            w.advance_clock()
            wr.advance_clock()
        # reads at every step: whole table + a duplicate-heavy pull
        _assert_bitwise(srv, ref, f"step {step} (op {op})")
        pk = rng.integers(0, E, 20)
        assert np.array_equal(np.asarray(w.pull_sync(pk)),
                              np.asarray(wr.pull_sync(pk))), \
            f"step {step}: pull diverged"
    srv.quiesce()
    ref.quiesce()
    _assert_bitwise(srv, ref, "after quiesce")
    srv.shutdown()
    ref.shutdown()
    # lock-order sentinel: non-vacuous graph, zero violations (the
    # dynamic half of the APM001/APM002 static claims; ISSUE 11)
    from adapm_tpu.lint import lockorder
    sen = lockorder.get_sentinel()
    assert sen is not None and sen.edges(), \
        "sentinel saw no lock edges: the storm exercised nothing"
    sen.assert_clean()
    lockorder.disable_sentinel()


# ---------------------------------------------------------------------------
# capacity + residency mechanics
# ---------------------------------------------------------------------------


def test_hot_pool_capacity_bounded(rng):
    srv = _mk(True, hot_rows=8)
    w = srv.make_worker(0)
    w.set(np.arange(E), rng.normal(size=(E, L)).astype(np.float32))
    # ask for far more than fits: promotion must truncate, never exceed
    srv.tier.promote_keys(np.arange(E))
    st = srv.stores[0]
    for s in range(st.res.num_shards):
        assert st.res.hot_count(s) <= st.res.hot_rows
    # reads still correct with a mostly-cold table
    assert np.array_equal(
        np.asarray(w.pull_sync(np.arange(E))).ravel(),
        _read_all(srv))
    assert st.tier_cold_hits > 0  # the cold path actually served
    srv.shutdown()


def test_intent_pins_survive_pressure_demotion(rng):
    from adapm_tpu.base import MgmtTechniques
    # REPLICATION_ONLY keeps owners in place, so the pinned owner rows
    # stay spread over the shards (4 per shard — within hot capacity);
    # with relocation on, the intent would pull all 32 owners onto one
    # shard, where they legitimately exceed a 16-row hot pool
    srv = _mk(True, hot_rows=16, tier_demote_batch=4,
              techniques=MgmtTechniques.REPLICATION_ONLY)
    w = srv.make_worker(0)
    w.set(np.arange(E), rng.normal(size=(E, L)).astype(np.float32))
    pinned = np.arange(0, 32)
    w.intent(pinned, 0, CLOCK_MAX)
    srv.sync.run_round(force_intents=True, all_channels=True)
    srv.tier.maintain()  # drains the intent promotion wants
    st = srv.stores[0]
    o_sh, o_sl = srv.ab.owner[pinned], srv.ab.slot[pinned]
    assert (st.res.dev_row[o_sh, o_sl] >= 0).all(), \
        "intent-pinned keys were not promoted"
    # pressure: promote lots of other keys; pinned rows must stay hot
    srv.tier.promote_keys(np.arange(64, E))
    srv.tier.maintain()
    assert (st.res.dev_row[srv.ab.owner[pinned],
                           srv.ab.slot[pinned]] >= 0).all(), \
        "pressure demotion evicted intent-pinned rows"
    srv.shutdown()


def test_residency_epoch_bumps_on_moves(rng):
    srv = _mk(True, hot_rows=16)
    w = srv.make_worker(0)
    w.set(np.arange(E), rng.normal(size=(E, L)).astype(np.float32))
    e0 = srv.tier.epoch
    srv.tier.promote_keys(np.arange(0, 16))
    e1 = srv.tier.epoch
    assert e1 > e0
    srv.tier.demote_keys(np.arange(0, 8))
    assert srv.tier.epoch > e1
    srv.shutdown()


def test_tier_metrics_section_schema_v4(rng):
    srv = _mk(True, hot_rows=16)
    w = srv.make_worker(0)
    w.set(np.arange(E), rng.normal(size=(E, L)).astype(np.float32))
    w.pull_sync(np.arange(0, 64))
    srv.tier.promote_keys(np.arange(0, 16))
    snap = srv.metrics_snapshot()
    assert snap["schema_version"] == 16
    t = snap["tier"]
    assert t["promotions"] >= 16
    assert 0.0 <= t["hot_hit_rate"] <= 1.0
    assert t["hot_rows_used"] <= t["hot_rows_capacity"]
    assert "cold_serve_s" in t  # the cold-serve latency histogram
    srv.shutdown()


def test_compose_slot_table_cold_is_oob(rng):
    """Cold rows in the composed device mirror must carry OOB, never
    -1: JAX `.at[]` drops/fills only LARGE positive out-of-bounds
    indices — a negative index WRAPS to the last row, so a -1 sentinel
    would silently read/corrupt whichever slot owns the last hot row."""
    from adapm_tpu.core.store import OOB
    srv = _mk(True, hot_rows=16)
    w = srv.make_worker(0)
    w.set(np.arange(E), rng.normal(size=(E, L)).astype(np.float32))
    srv.tier.promote_keys(np.arange(0, 32))
    eff = srv.tier.compose_slot_table()
    assert (eff >= 0).all()
    res = srv.stores[0].res
    rows = res.dev_row[srv.ab.owner[np.arange(E)],
                       srv.ab.slot[np.arange(E)]]
    assert (eff[rows < 0] == OOB).all(), "cold rows must mirror as OOB"
    assert np.array_equal(eff[rows >= 0], rows[rows >= 0])
    srv.shutdown()


def test_device_routed_negatives_bit_identical(rng):
    """Device-routed fused steps WITH device-drawn negatives under tier
    vs the untiered shadow: with the negative population kept
    device-resident (intent-pinned before the runs), the hot-restricted
    draw equals the untiered local draw, so the whole training
    trajectory must stay bit-identical — this exercises the composed
    slot mirror and the in-program sampler the host-routed storm
    cannot reach."""
    import jax.numpy as jnp

    from adapm_tpu.ops import DeviceRoutedRunner

    d = L // 2

    def loss_fn(embs, aux):
        return jnp.mean(jnp.sum(embs["a"][:, None, :] * embs["n"],
                                axis=-1))

    pop = np.arange(0, 64)
    outs = []
    for tier in (True, False):
        srv = _mk(tier, hot_rows=32)
        w = srv.make_worker(0)
        vals = np.random.default_rng(5).normal(
            size=(E, L)).astype(np.float32)
        vals[:, d:] = np.abs(vals[:, d:])
        w.set(np.arange(E), vals)
        # make the neg population local (and, tiered, device-resident)
        w.intent(pop, 0, CLOCK_MAX)
        srv.sync.run_round(force_intents=True, all_channels=True)
        if tier:
            srv.tier.promote_keys(pop)
        run = DeviceRoutedRunner(
            srv, loss_fn, {"a": 0, "n": 0}, {"a": d, "n": d}, shard=0,
            neg_role="n", neg_shape=(8, 4), neg_population=pop, seed=11)
        kb = np.random.default_rng(6)
        for _ in range(5):
            run({"a": kb.choice(pop, 8, replace=False)}, None, lr=0.05)
        outs.append(_read_all(srv))
        srv.shutdown()
    assert np.array_equal(outs[0], outs[1]), \
        "device-drawn negatives diverged under tier"


def test_tiered_negative_fallback_promotes_all_cold(rng):
    """All-cold shard with zero resident population keys: the tiered
    negative-index fallback must PROMOTE a slice of the population and
    draw from the resident subset (never silently sample cold keys,
    whose mirror rows are OOB and would read zeros / drop scatters)."""
    import jax.numpy as jnp

    from adapm_tpu.ops import DeviceRoutedRunner

    d = L // 2

    def loss_fn(embs, aux):
        return jnp.mean(jnp.sum(embs["a"][:, None, :] * embs["n"],
                                axis=-1))

    srv = _mk(True, hot_rows=32)
    w = srv.make_worker(0)
    vals = np.random.default_rng(5).normal(size=(E, L)).astype(np.float32)
    vals[:, d:] = np.abs(vals[:, d:])
    w.set(np.arange(E), vals)
    # population owned by OTHER shards, everything cold, no replicas:
    # the untiered code would fall back to full-population draws
    pop = np.arange(E)[srv.ab.owner[np.arange(E)] != 0][:48]
    run = DeviceRoutedRunner(
        srv, loss_fn, {"a": 0, "n": 0}, {"a": d, "n": d}, shard=0,
        neg_role="n", neg_shape=(8, 4), neg_population=pop, seed=3)
    run({"a": np.arange(0, 8)}, None, lr=0.05)
    res = srv.stores[0].res
    o_sh, o_sl = srv.ab.owner[pop], srv.ab.slot[pop]
    assert (res.dev_row[o_sh, o_sl] >= 0).any(), \
        "fallback did not promote any population rows"
    srv.shutdown()


# ---------------------------------------------------------------------------
# r10 known-limit regression (retired by the PR 6 dispatch gate)
# ---------------------------------------------------------------------------


def test_two_servers_concurrent_sharded_dispatch_bounded(rng):
    """Two servers sharing this process's virtual device set dispatch
    sharded programs CONCURRENTLY — tier maintenance enabled on both
    (executor `tier` streams) plus a driving thread per server pushing,
    pulling, and churning residency — and every join is bounded. The
    old failure mode was an indefinite XLA-CPU collective-rendezvous
    stall whenever two lock domains interleaved per-device enqueue
    orders; the process-wide dispatch gate (adapm_tpu/exec) makes the
    orders identical by construction, so the former workaround (nulling
    the worker's kick and driving tier.maintain() synchronously) is
    gone for good."""
    import threading
    srv1 = _mk(True, hot_rows=16)
    srv2 = _mk(True, hot_rows=16)
    vals = rng.normal(size=(E, L)).astype(np.float32)
    w1, w2 = srv1.make_worker(0), srv2.make_worker(0)
    w1.set(np.arange(E), vals)
    w2.set(np.arange(E), vals)
    errs = []

    def churn(srv, w, seed):
        r = np.random.default_rng(seed)
        try:
            for _ in range(12):
                ks = r.integers(0, E, 16)
                w.push(ks, r.normal(size=(16, L)).astype(np.float32))
                srv.tier.promote_keys(r.choice(E, 24, replace=False))
                srv.tier.demote_keys(r.choice(E, 24, replace=False))
                srv.tier.engine.kick()  # async passes on the executor
                w.pull_sync(r.integers(0, E, 16))
        except BaseException as e:  # noqa: BLE001 — surface in-thread
            errs.append(e)

    ts = [threading.Thread(target=churn, args=(srv1, w1, 1)),
          threading.Thread(target=churn, args=(srv2, w2, 2))]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=60)
    assert not any(t.is_alive() for t in ts), \
        "concurrent sharded dispatch stalled — rendezvous deadlock?"
    assert not errs, errs
    # bounded shutdown too: both executors drain without a stall
    srv1.shutdown()
    srv2.shutdown()


# ---------------------------------------------------------------------------
# shutdown ordering satellite
# ---------------------------------------------------------------------------


def test_shutdown_deterministic_and_double_close(rng, tmp_path):
    from adapm_tpu.serve import ServePlane
    srv = _mk(True, hot_rows=16,
              ckpt_every_s=0.02, ckpt_path=str(tmp_path / "chain"))
    w = srv.make_worker(0)
    w.set(np.arange(E), rng.normal(size=(E, L)).astype(np.float32))
    plane = ServePlane(srv)
    plane.session().lookup(np.arange(8))
    srv.tier.engine.kick()   # queue real tier maintenance work
    srv.start_sync_thread()
    # race an in-flight checkpoint program against shutdown (ISSUE 10
    # satellite): a zero-delay save is queued on the `ckpt` stream
    # right as teardown begins; close must DRAIN it before pool
    # teardown, never cancel it into a half-written chain or read
    # through torn-down pools
    srv.exec.submit("ckpt", srv.ckpt.save, label="ckpt.save.race")
    srv.shutdown()
    # every background producer is down after the first shutdown, and
    # the unified executor closed LAST with nothing left on its streams
    assert srv._sync_thread is None
    assert not plane.batcher.is_alive()
    assert srv.exec.closed
    assert srv.exec.live_streams() == [], \
        "orphaned executor streams survived shutdown"
    # the raced save drained (not cancelled): the chain manifest
    # describes only durably-written, checksum-valid links
    from adapm_tpu.fault.ckpt import _load_verified_chain
    assert len(_load_verified_chain(str(tmp_path / "chain"))) >= 1
    srv.shutdown()  # double-close must be a no-op, not a crash
    # ... and the checkpointer's own close is idempotent too
    srv.ckpt.close()
    # a submit against the closed executor is a cancelled no-op, not a
    # crash (late kicks during teardown)
    c = srv.exec.submit("tier", lambda: 1)
    assert c.done() and c.cancelled
    # and a manually-closed plane before shutdown stays tolerated
    srv2 = _mk(True, hot_rows=16)
    p2 = ServePlane(srv2)
    p2.close()
    p2.close()
    srv2.shutdown()
    srv2.shutdown()


# ---------------------------------------------------------------------------
# checkpoint save/restore with tiering (satellite)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("restore_tier", [True, False])
def test_checkpoint_roundtrip_across_tiers(tmp_path, rng, restore_tier):
    from adapm_tpu.utils.checkpoint import restore_server, save_server
    srv = _mk(True, hot_rows=16)
    w = srv.make_worker(0)
    w.set(np.arange(E), rng.normal(size=(E, L)).astype(np.float32))
    # mixed residency before the save: some hot, some cold, plus live
    # replicas carrying unshipped deltas
    srv.tier.promote_keys(np.arange(0, 128))
    rem = np.arange(E)[srv.ab.owner[np.arange(E)] != w.shard][:32]
    w.intent(rem, 0, CLOCK_MAX)
    srv.sync.run_round(force_intents=True, all_channels=True)
    w.push(rem, rng.normal(size=(len(rem), L)).astype(np.float32))
    path = str(tmp_path / "ck.npz")
    save_server(srv, path)
    before = _read_all(srv)
    srv2 = _mk(restore_tier, hot_rows=16)
    restore_server(srv2, path)
    if restore_tier:
        # residency reset cleanly: everything cold. Checked BEFORE the
        # first read — a read's cold misses kick the (executor-run)
        # maintenance worker, which starts re-promoting immediately
        for st in srv2.stores:
            assert (st.res.dev_row < 0).all()
            assert (st.res.row_slot < 0).all()
            assert st.res.alloc.num_free(0) == st.res.hot_rows
    # bit-identical regardless of pre-save residency or restore tiering
    assert np.array_equal(_read_all(srv2), before)
    if restore_tier:
        # lazy re-promotion works and is value-invisible
        srv2.tier.promote_keys(np.arange(0, 64))
        assert np.array_equal(_read_all(srv2), before)
    # dirty-delta tracking consistent after restore: the checkpoint
    # carries unshipped replica deltas (restore marks everything dirty
    # once), and flushing them post-restore must land bit-identically
    # to flushing them on the original server
    w2 = srv2.make_worker(0)
    srv2.sync.run_round(force_intents=True, all_channels=True)
    srv.sync.run_round(force_intents=True, all_channels=True)
    before = _read_all(srv)  # post-flush authoritative state
    assert np.array_equal(_read_all(srv2), before)
    # and new writes flow through sync correctly post-restore
    ks = np.arange(0, 16)
    v = rng.normal(size=(16, L)).astype(np.float32)
    w2.push(ks, v)
    srv2.quiesce()
    expect = before.reshape(E, L).copy()
    expect[ks] += v
    assert np.array_equal(_read_all(srv2).reshape(E, L), expect)
    srv.shutdown()
    srv2.shutdown()


def test_untiered_checkpoint_restores_into_tiered(tmp_path, rng):
    """A checkpoint written by an untiered server restores into a tiered
    one (the saved main table is tier-independent geometry)."""
    from adapm_tpu.utils.checkpoint import restore_server, save_server
    src = _mk(False)
    w = src.make_worker(0)
    w.set(np.arange(E), rng.normal(size=(E, L)).astype(np.float32))
    path = str(tmp_path / "ck.npz")
    save_server(src, path)
    before = _read_all(src)
    dst = _mk(True, hot_rows=16)
    restore_server(dst, path)
    assert np.array_equal(_read_all(dst), before)
    src.shutdown()
    dst.shutdown()
