"""Workload trace capture + deterministic replay (ISSUE 15 tentpole).

Tier-1 coverage for adapm_tpu/obs/wtrace.py + adapm_tpu/replay/:

  - THE determinism property test: a randomized 5-plane storm
    (pull/push/set, intents + relocations, clock advances, serve
    lookups, sync rounds, quiesce) is recorded once and replayed
    repeatedly — same trace + same seed + same knobs => bit-identical
    replayed reads (the sha256 reads digest), at different logical
    speeds, and EVEN ACROSS value-preserving knob candidates (the
    tiered store's bit-identity contract carries into replay);
  - corruption quartet: truncated body, flipped byte, wrong version,
    missing header each raise the NAMED WorkloadTraceError during
    verification — before any replay server exists;
  - the off pin: no --sys.trace.workload (default) => no recorder
    object, zero wtrace.* registry names, empty wtrace/replay snapshot
    sections, and the plain op path untouched;
  - capture mechanics: event kinds + clock domains (wall AND mono on
    every event), the lossless-or-loudly-sampled key budget, the
    bounded event buffer's loud drop counter, atomic flush/close;
  - ranked comparison artifact sanity (rank_candidates).
"""
import json

import numpy as np
import pytest

from adapm_tpu import Server, SystemOptions, make_mesh
from adapm_tpu.obs.wtrace import (WTRACE_VERSION, WorkloadTraceError,
                                  WorkloadTraceRecorder, event_keys,
                                  load_wtrace)
from adapm_tpu.replay import ReplayEngine, rank_candidates, replay_trace
from adapm_tpu.serve import ServePlane

NK = 128
VL = 4


@pytest.fixture(scope="module")
def ctx():
    return make_mesh(8)


def make_server(ctx, tmp_path=None, num_keys=NK, vlen=VL, **kw):
    opts = kw.pop("opts", None)
    if opts is None:
        opts = SystemOptions(sync_max_per_sec=0)
    if tmp_path is not None and not opts.trace_workload:
        opts.trace_workload = str(tmp_path / "capture.wtrace")
    return Server(num_keys, vlen, opts=opts, ctx=ctx, **kw)


def _seed(w, num_keys=NK, vlen=VL):
    w.wait(w.set(np.arange(num_keys),
                 np.ones((num_keys, vlen), np.float32)))


def _capture_storm(ctx, tmp_path, steps=40, key_budget=4096,
                   with_serve=True):
    """One seeded multi-plane storm under capture; returns the trace
    path after a clean shutdown (final flush)."""
    opts = SystemOptions(sync_max_per_sec=0, prefetch=False,
                         trace_workload=str(tmp_path / "storm.wtrace"),
                         trace_workload_keys=key_budget)
    srv = Server(NK, VL, opts=opts, ctx=ctx, num_workers=2)
    w0, w1 = srv.make_worker(0), srv.make_worker(1)
    _seed(w0)
    rng = np.random.default_rng(7)
    plane = ServePlane(srv) if with_serve else None
    sessions = {}
    n_serves = 0
    if plane is not None:
        plane.configure_tenant("gold", priority=1)
        sessions["gold"] = plane.session(tenant="gold")
        sessions[None] = plane.session()
    for i in range(steps):
        w = w0 if i % 2 == 0 else w1
        op = rng.integers(0, 6)
        ks = np.unique(rng.integers(0, NK, int(rng.integers(1, 24))))
        if op == 0:
            w.pull_sync(ks)
        elif op == 1:
            w.wait(w.push(ks, rng.normal(
                size=(len(ks), VL)).astype(np.float32)))
        elif op == 2:
            w.wait(w.set(ks, rng.normal(
                size=(len(ks), VL)).astype(np.float32)))
        elif op == 3:
            w.intent(ks, w.current_clock, w.current_clock + 4)
            w.advance_clock()
        elif op == 4 and plane is not None:
            # alternate tenanted / untenanted lookups so both admission
            # shapes land in the trace
            sess = sessions["gold" if n_serves % 2 else None]
            n_serves += 1
            sess.lookup(rng.integers(0, NK, 16))
        else:
            srv.wait_sync()
    srv.quiesce()
    path = srv.opts.trace_workload
    if plane is not None:
        plane.close()
    srv.shutdown()
    return path


# ---------------------------------------------------------------------------
# the off pin (metrics_overhead_check.py pins the same thing in CI)
# ---------------------------------------------------------------------------


def test_capture_off_pin(ctx):
    """Default server: no recorder, zero wtrace.* names, empty
    wtrace/replay snapshot sections — the r7 skip-wrapper shape."""
    srv = make_server(ctx)
    w = srv.make_worker(0)
    _seed(w)
    w.pull_sync(np.arange(8))
    assert srv.wtrace is None and srv.replay_stats is None
    assert not [n for n in srv.obs.names() if n.startswith("wtrace.")]
    snap = srv.metrics_snapshot()
    assert snap["schema_version"] == 16
    assert snap["wtrace"] == {} and snap["replay"] == {}
    srv.shutdown()


# ---------------------------------------------------------------------------
# capture mechanics
# ---------------------------------------------------------------------------


def test_capture_event_stream_and_clock_domains(ctx, tmp_path):
    """Every op kind lands in the trace with its logical clock AND both
    time domains (wall + mono — the ISSUE 15 clock-domain rule); the
    wtrace.* counters ride the registry; the file verifies."""
    path = _capture_storm(ctx, tmp_path)
    tr = load_wtrace(path)
    kinds = tr.kinds()
    for k in ("pull", "push", "set", "intent", "clock", "serve",
              "sync", "quiesce"):
        assert kinds.get(k, 0) >= 1, (k, kinds)
    monos = []
    for ev in tr.events:
        assert {"kind", "clock", "wall", "mono", "seq"} <= set(ev), ev
        monos.append(ev["mono"])
    assert monos == sorted(monos), \
        "recorded mono stamps must be non-decreasing in seq order"
    # serve events carry the admission attributes
    sv = [e for e in tr.events if e["kind"] == "serve"]
    assert {e["tenant"] for e in sv} >= {None, "gold"}
    assert any(e["priority"] == 1 for e in sv)
    # meta carries geometry + knobs for the replay server
    assert tr.meta["num_keys"] == NK
    assert tr.meta["value_lengths"] == VL
    assert tr.meta["knobs"]["prefetch"] is False
    assert tr.dropped == 0


def test_capture_registers_metrics_and_snapshot_section(ctx, tmp_path):
    srv = make_server(ctx, tmp_path)
    w = srv.make_worker(0)
    _seed(w)
    w.pull_sync(np.arange(4))
    names = srv.obs.names()
    for n in ("wtrace.events_total", "wtrace.dropped_total",
              "wtrace.sampled_batches_total", "wtrace.bytes_written"):
        assert n in names, n
    snap = srv.metrics_snapshot()
    assert snap["wtrace"]["events_total"] >= 2
    assert snap["wtrace"]["path"] == srv.opts.trace_workload
    assert snap["wtrace"]["closed"] is False
    srv.shutdown()
    snap2 = srv.metrics_snapshot()
    assert snap2["wtrace"]["closed"] is True


def test_key_budget_lossless_or_loudly_sampled(ctx, tmp_path):
    """Batches within the budget record exact keys; beyond it an
    evenly-strided sample + the true count, counted loudly — and
    event_keys reconstructs deterministically from a seeded rng."""
    opts = SystemOptions(sync_max_per_sec=0, prefetch=False,
                         trace_workload=str(tmp_path / "b.wtrace"),
                         trace_workload_keys=16)
    srv = Server(NK, VL, opts=opts, ctx=ctx)
    w = srv.make_worker(0)
    _seed(w)                      # set of 128 keys: sampled
    small = np.arange(10)
    w.pull_sync(small)            # exact
    big = np.arange(100)
    w.pull_sync(big)              # sampled
    assert int(srv.obs.find("wtrace.sampled_batches_total").value) == 2
    srv.shutdown()
    tr = load_wtrace(str(tmp_path / "b.wtrace"))
    pulls = [e for e in tr.events if e["kind"] == "pull"]
    exact = next(e for e in pulls if e["n"] == 10)
    assert exact["keys"] == [int(k) for k in small]
    assert "sampled" not in exact
    samp = next(e for e in pulls if e["n"] == 100)
    assert samp["sampled"] is True and "keys" not in samp
    assert 1 <= len(samp["sample"]) <= 16
    assert set(samp["sample"]) <= set(int(k) for k in big)
    # reconstruction: deterministic given the rng seed, loud without
    rng = np.random.default_rng(5)
    k1 = event_keys(samp, rng=np.random.default_rng(5))
    k2 = event_keys(samp, rng=np.random.default_rng(5))
    assert len(k1) == 100 and np.array_equal(k1, k2)
    with pytest.raises(ValueError, match="key-sampled"):
        event_keys(samp)
    assert np.array_equal(event_keys(exact), small)
    del rng


def test_event_buffer_bound_drops_loudly(ctx, tmp_path):
    opts = SystemOptions(sync_max_per_sec=0, prefetch=False,
                         trace_workload=str(tmp_path / "d.wtrace"))
    srv = Server(NK, VL, opts=opts, ctx=ctx)
    srv.wtrace.max_events = 4
    w = srv.make_worker(0)
    _seed(w)
    for _ in range(8):
        w.pull_sync(np.arange(4))
    assert int(srv.obs.find("wtrace.dropped_total").value) >= 4
    srv.shutdown()
    tr = load_wtrace(str(tmp_path / "d.wtrace"))
    assert len(tr.events) == 4 and tr.dropped >= 4


def test_flush_is_atomic_and_mid_run_readable(ctx, tmp_path):
    srv = make_server(ctx, tmp_path)
    w = srv.make_worker(0)
    _seed(w)
    w.pull_sync(np.arange(6))
    p = srv.wtrace.flush()
    mid = load_wtrace(p)            # verifies header + checksum
    assert mid.kinds().get("pull", 0) >= 1
    assert not list(tmp_path.glob("*.tmp")), "tmp file left behind"
    w.pull_sync(np.arange(6))
    srv.shutdown()                  # final flush supersedes
    assert len(load_wtrace(p).events) > len(mid.events)


# ---------------------------------------------------------------------------
# corruption: named error BEFORE any server mutation
# ---------------------------------------------------------------------------


def test_corrupt_trace_raises_named_error(ctx, tmp_path):
    path = _capture_storm(ctx, tmp_path, steps=10, with_serve=False)
    raw = open(path, "rb").read()
    # truncated body
    trunc = tmp_path / "trunc.wtrace"
    trunc.write_bytes(raw[:-20])
    with pytest.raises(WorkloadTraceError, match="bytes"):
        load_wtrace(str(trunc))
    # flipped byte in the checksummed body
    nl = raw.find(b"\n")
    flip = bytearray(raw)
    flip[nl + 30] ^= 0xFF
    bad = tmp_path / "flip.wtrace"
    bad.write_bytes(bytes(flip))
    with pytest.raises(WorkloadTraceError, match="sha256"):
        load_wtrace(str(bad))
    # wrong version in the header
    hdr = json.loads(raw[:nl])
    hdr["version"] = WTRACE_VERSION + 1
    vbad = tmp_path / "v.wtrace"
    vbad.write_bytes(json.dumps(hdr).encode() + raw[nl:])
    with pytest.raises(WorkloadTraceError, match="version"):
        load_wtrace(str(vbad))
    # not a wtrace at all / missing header line
    junk = tmp_path / "junk.wtrace"
    junk.write_bytes(b"{}")
    with pytest.raises(WorkloadTraceError):
        load_wtrace(str(junk))
    with pytest.raises(WorkloadTraceError, match="cannot read"):
        load_wtrace(str(tmp_path / "missing.wtrace"))
    # the engine verifies at CONSTRUCTION — before any replay server
    # exists, so a corrupt trace can never half-drive one
    with pytest.raises(WorkloadTraceError):
        ReplayEngine(str(bad))


# ---------------------------------------------------------------------------
# THE determinism property test
# ---------------------------------------------------------------------------


def test_capture_replay_determinism_property(ctx, tmp_path):
    """Randomized 5-plane storm recorded once; replayed repeatedly:
    same seed => bit-identical reads digest, across logical speeds,
    and across value-preserving knob candidates (the tiered store's
    bit-identity contract holds under replay). A different seed
    changes the synthesized values, hence the digest — the digest is
    a real function of the replayed data, not a constant."""
    path = _capture_storm(ctx, tmp_path, steps=48, key_budget=12)
    tr = load_wtrace(path)
    assert tr.kinds().get("serve", 0) >= 1
    r1 = ReplayEngine(tr, seed=11, speed=100).run()
    r2 = ReplayEngine(tr, seed=11, speed=100).run()
    assert r1["reads_digest"] == r2["reads_digest"]
    assert r1["reads"] == r2["reads"] > 0
    assert r1["events_replayed"] == r2["events_replayed"] > 0
    # speed changes pacing, never reads
    r_fast = ReplayEngine(tr, seed=11, speed=10.0).run()
    assert r_fast["reads_digest"] == r1["reads_digest"]
    # a value-preserving knob candidate (tiered residency) replays the
    # SAME bits — the r10 bit-identity contract carried into replay
    r_tier = ReplayEngine(tr, overrides={"tier": True,
                                         "tier_hot_rows": 16},
                          seed=11, speed=100).run()
    assert r_tier["reads_digest"] == r1["reads_digest"]
    assert r_tier["score"]["hot_hit_rate"] is not None
    # the digest is data: a different seed synthesizes different
    # pushed values and must move it
    r_other = ReplayEngine(tr, seed=12, speed=100).run()
    assert r_other["reads_digest"] != r1["reads_digest"]


def test_replay_rejects_bad_knobs_and_bad_speed(ctx, tmp_path):
    path = _capture_storm(ctx, tmp_path, steps=8, with_serve=False)
    with pytest.raises(ValueError, match="unknown replay knob"):
        ReplayEngine(path, overrides={"hot_rows": 8}).run()
    with pytest.raises(ValueError, match="speed"):
        ReplayEngine(path, speed=0)
    with pytest.raises(ValueError, match="metrics"):
        ReplayEngine(path, overrides={"metrics": False}).run()
    with pytest.raises(ValueError, match="capture itself"):
        ReplayEngine(path, overrides={
            "trace_workload": "/tmp/x.wtrace"}).run()
    # determinism pins are not candidate knobs: re-enabling deadlines
    # or the timer loops turns wall-clock races back into "behavior"
    for pin in ("serve_deadline_ms", "sync_max_per_sec", "prefetch"):
        with pytest.raises(ValueError, match="determinism pin"):
            ReplayEngine(path, overrides={pin: 1}).run()


def test_replay_snapshot_section_and_decisions_skipped(ctx, tmp_path):
    """The replay engine re-decides management decisions (reloc /
    promote observed events are skipped, counted) and stamps the
    `replay` snapshot section on the driven server (schema v11)."""
    path = _capture_storm(ctx, tmp_path, steps=32)
    tr = load_wtrace(path)
    assert tr.kinds().get("reloc", 0) >= 1, \
        "storm should have landed at least one relocation decision"
    res = replay_trace(tr, seed=1, speed=100)
    assert res["events_skipped"].get("reloc", 0) >= 1
    assert res["events_total"] == len(tr.events)
    # the engine folded its stats into the driven server's snapshot
    # before shutdown (include_snapshot exposes it)
    res2 = ReplayEngine(tr, seed=1).run(include_snapshot=True)
    rep = res2["snapshot"]["replay"]
    assert rep["reads_digest"] == res["reads_digest"]
    assert rep["events_replayed"] == res["events_replayed"]
    assert rep["trace"] == path


def test_rank_candidates_artifact(ctx, tmp_path):
    """Two-candidate knob sweep: ranked artifact carries per-candidate
    scores + a deterministic winner by the named objective (the full
    live-vs-replay ordering guard is scripts/trace_replay_check.py)."""
    path = _capture_storm(ctx, tmp_path, steps=24, with_serve=False)
    art = rank_candidates(
        path,
        {"hot_all": {"tier": True, "tier_hot_rows": NK},
         "hot_8": {"tier": True, "tier_hot_rows": 8}},
        objective="hot_hit_rate", seed=2, speed=100,
        out_path=str(tmp_path / "compare.json"))
    assert art["winner"] in ("hot_all", "hot_8")
    assert sorted(art["ranking"]) == ["hot_8", "hot_all"]
    assert art["objective"] == "hot_hit_rate"
    for name, cand in art["candidates"].items():
        assert cand["score"]["hot_hit_rate"] is not None, name
        assert cand["reads_digest"]
    # all-hot must not LOSE to a tiny hot pool on hit rate
    s_all = art["candidates"]["hot_all"]["score"]["hot_hit_rate"]
    s_8 = art["candidates"]["hot_8"]["score"]["hot_hit_rate"]
    assert s_all >= s_8
    assert art["winner"] == "hot_all" or s_all == s_8
    on_disk = json.loads((tmp_path / "compare.json").read_text())
    assert on_disk["winner"] == art["winner"]
    with pytest.raises(ValueError, match="objective"):
        rank_candidates(path, {"a": None}, objective="nope")


def test_replay_inherits_recorded_knobs(ctx, tmp_path):
    """The replay baseline is the RECORDED configuration, not library
    defaults — a candidate's overrides are a diff against the config
    that produced the workload — with the determinism/hygiene pins
    applied on top."""
    from adapm_tpu.replay.engine import _build_opts
    opts = SystemOptions(sync_max_per_sec=0, prefetch=False,
                         serve_max_batch=32, channels=2,
                         trace_workload=str(tmp_path / "k.wtrace"))
    srv = Server(NK, VL, opts=opts, ctx=ctx)
    w = srv.make_worker(0)
    _seed(w)
    srv.shutdown()
    tr = load_wtrace(str(tmp_path / "k.wtrace"))
    built, ns = _build_opts(tr, None)
    # recorded non-defaults carry over
    assert built.serve_max_batch == 32 and built.channels == 2
    assert ns == srv.ctx.num_shards
    # pins win over the recorded values
    assert built.sync_max_per_sec == 0 and built.prefetch is False
    assert built.trace_workload is None and built.metrics is True
    assert built.ckpt_every_s == 0.0 and built.stats_out is None
    # candidate overrides still land on top of the recorded base
    built2, _ = _build_opts(tr, {"serve_max_batch": 16})
    assert built2.serve_max_batch == 16


def test_recorder_knob_validation():
    """Hand-built options reject a zero key budget (the CLI round-trip
    lives in test_config_knobs); the recorder itself refuses an empty
    path."""
    with pytest.raises(ValueError, match="workload_keys"):
        SystemOptions(trace_workload_keys=0).validate_serve()
    with pytest.raises(ValueError, match="path"):
        WorkloadTraceRecorder(None, "")
