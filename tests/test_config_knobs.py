"""Config knobs that tune the data plane: --sys.sync.threshold,
--sampling.batch_size, remote_bucket_min (reference sync_manager.h:805-814,
sampling.h:394-405)."""
import numpy as np

import adapm_tpu
from adapm_tpu.base import CLOCK_MAX, MgmtTechniques
from adapm_tpu.config import SystemOptions


def _replicated_key(srv, w0):
    """Force a replica of a non-local key onto w0's shard."""
    key = next(k for k in range(srv.num_keys)
               if srv.ab.owner[k] != w0.shard)
    w0.intent(np.array([key]), 0, CLOCK_MAX)
    srv.wait_sync()
    assert srv.ab.cache_slot[w0.shard, key] >= 0, "replica not created"
    return key


def test_sync_threshold_holds_back_small_deltas():
    opts = SystemOptions(techniques=MgmtTechniques.REPLICATION_ONLY,
                         sync_threshold=1e-3, sync_max_per_sec=0,
                         cache_slots_per_shard=8)
    srv = adapm_tpu.setup(16, 4, opts=opts)
    w0 = srv.make_worker(0)
    w0.set(np.arange(16), np.ones((16, 4), np.float32))
    key = _replicated_key(srv, w0)

    # tiny delta: below threshold, stays pending through a sync round
    w0.push(np.array([key]), np.full((1, 4), 1e-5, np.float32))
    srv.wait_sync()
    assert np.allclose(srv.read_main(np.array([key])), 1.0)
    # read-your-writes on the replica still holds
    assert np.allclose(w0.pull_sync(np.array([key])), 1.0 + 1e-5)

    # once the delta grows past the threshold it ships
    w0.push(np.array([key]), np.ones((1, 4), np.float32))
    srv.wait_sync()
    assert np.allclose(srv.read_main(np.array([key])), 2.0 + 1e-5)

    # quiesce flushes unconditionally — no delta is ever lost
    w0.push(np.array([key]), np.full((1, 4), 1e-5, np.float32))
    srv.quiesce()
    assert np.allclose(srv.read_main(np.array([key])), 2.0 + 2e-5)
    srv.shutdown()


def test_sync_threshold_drop_flushes_pending_delta():
    """Replica drop (intent expiry) must flush even sub-threshold deltas."""
    opts = SystemOptions(techniques=MgmtTechniques.REPLICATION_ONLY,
                         sync_threshold=1e-3, sync_max_per_sec=0,
                         cache_slots_per_shard=8)
    srv = adapm_tpu.setup(16, 4, opts=opts)
    w0 = srv.make_worker(0)
    w0.set(np.arange(16), np.ones((16, 4), np.float32))
    key = next(k for k in range(srv.num_keys)
               if srv.ab.owner[k] != w0.shard)
    w0.intent(np.array([key]), 0, 2)  # expires at clock 3
    srv.wait_sync()
    assert srv.ab.cache_slot[w0.shard, key] >= 0
    w0.push(np.array([key]), np.full((1, 4), 1e-5, np.float32))
    for _ in range(4):
        w0.advance_clock()
    srv.wait_sync()  # intent expired -> replica dropped, delta flushed
    assert srv.ab.cache_slot[w0.shard, key] < 0, "replica should be dropped"
    assert np.allclose(srv.read_main(np.array([key])), 1.0 + 1e-5)
    srv.shutdown()


def test_sampling_batch_size_buffers_rng_draws():
    calls = []

    def sample_fn(n, rng):
        calls.append(n)
        return rng.integers(0, 32, n)

    opts = SystemOptions(sampling_scheme="naive", sampling_batch_size=64,
                         sync_max_per_sec=0)
    srv = adapm_tpu.setup(32, 4, opts=opts)
    w = srv.make_worker(0)
    w.set(np.arange(32), np.ones((32, 4), np.float32))
    srv.enable_sampling_support(sample_fn)
    for _ in range(8):
        h = w.prepare_sample(5)
        keys, vals = w.pull_sample(h)
        assert len(keys) == 5 and vals.shape == (5, 4)
        w.finish_sample(h)
    # 8 * 5 = 40 draws served by a single 64-key buffered call
    assert calls == [64], calls
    # large draws bypass the buffer
    h = w.prepare_sample(200)
    keys, _ = w.pull_sample(h)
    assert len(keys) == 200
    assert calls == [64, 200], calls
    srv.shutdown()


def test_remote_bucket_min_sets_padding_floor():
    opts = SystemOptions(remote_bucket_min=32, sync_max_per_sec=0)
    srv = adapm_tpu.setup(64, 4, opts=opts)
    assert all(s.bucket_min == 32 for s in srv.stores)
    w = srv.make_worker(0)
    w.set(np.arange(64), np.ones((64, 4), np.float32))
    # tiny op still correct under the larger padding floor
    w.push(np.array([3]), np.full((1, 4), 2.0, np.float32))
    srv.block()
    assert np.allclose(srv.read_main(np.array([3])), 3.0)
    srv.shutdown()


def test_dcn_threads_sizes_pm_executors():
    """--sys.dcn_threads (reference --sys.zmq_threads analog) sizes the
    GlobalPM's executors; single-process has no PM, so check the parse
    path and the multi-process consumption site directly."""
    import argparse

    from adapm_tpu.config import SystemOptions
    p = argparse.ArgumentParser()
    SystemOptions.add_arguments(p)
    opts = SystemOptions.from_args(p.parse_args(["--sys.dcn_threads", "3"]))
    assert opts.dcn_threads == 3
    # behavior of the consumption site: GlobalPM sizes its executors via
    # executor_widths (end-to-end coverage lives in the mp suite)
    from adapm_tpu.parallel.pm import executor_widths
    assert executor_widths(opts) == (3, 2)
    wide = SystemOptions.from_args(p.parse_args(["--sys.dcn_threads", "8"]))
    assert executor_widths(wide) == (8, 4)


def test_serve_knobs_round_trip():
    """--sys.serve.* parse into the options ServePlane consumes
    (ISSUE 4 satellite)."""
    import argparse

    from adapm_tpu.config import SystemOptions
    p = argparse.ArgumentParser()
    SystemOptions.add_arguments(p)
    dflt = SystemOptions.from_args(p.parse_args([]))
    assert (dflt.serve_max_batch, dflt.serve_max_wait_us,
            dflt.serve_queue, dflt.serve_deadline_ms) == (64, 200,
                                                          1024, 0.0)
    on = SystemOptions.from_args(p.parse_args(
        ["--sys.serve.max_batch", "16", "--sys.serve.max_wait_us", "500",
         "--sys.serve.queue", "256", "--sys.serve.deadline_ms", "50"]))
    assert on.serve_max_batch == 16 and on.serve_max_wait_us == 500
    assert on.serve_queue == 256 and on.serve_deadline_ms == 50.0


def test_serve_knobs_rejected_at_parse_time():
    """Out-of-range / inconsistent --sys.serve.* combinations fail
    loudly at parse time, not when the first lookup misbehaves."""
    import argparse

    import pytest

    from adapm_tpu.config import SystemOptions
    p = argparse.ArgumentParser()
    SystemOptions.add_arguments(p)
    bad = (["--sys.serve.max_batch", "0"],
           ["--sys.serve.max_wait_us", "-1"],
           ["--sys.serve.queue", "0"],
           ["--sys.serve.deadline_ms", "-5"],
           # inconsistent: queue bound below max_batch makes the
           # configured batch size unreachable
           ["--sys.serve.queue", "8", "--sys.serve.max_batch", "16"])
    for argv in bad:
        with pytest.raises(ValueError):
            SystemOptions.from_args(p.parse_args(argv))
    # hand-built options are validated again at ServePlane construction
    with pytest.raises(ValueError):
        SystemOptions(serve_max_batch=-3).validate_serve()


def test_flight_and_slo_knobs_round_trip_and_rejection():
    """--sys.trace.flight / --sys.serve.slo_ms parse into the options
    the flight tracer and SLO controller consume, and invalid
    combinations fail loudly at parse time (ISSUE 7)."""
    import argparse

    import pytest

    from adapm_tpu.config import SystemOptions
    p = argparse.ArgumentParser()
    SystemOptions.add_arguments(p)
    dflt = SystemOptions.from_args(p.parse_args([]))
    # both DEFAULT OFF: no tracer, no controller, static knob path
    assert dflt.trace_flight is False and dflt.trace_flight_out is None
    assert dflt.serve_slo_ms == 0.0
    on = SystemOptions.from_args(p.parse_args(
        ["--sys.trace.flight", "1",
         "--sys.trace.flight_out", "/tmp/f.json",
         "--sys.serve.slo_ms", "12.5"]))
    assert on.trace_flight is True
    assert on.trace_flight_out == "/tmp/f.json"
    assert on.serve_slo_ms == 12.5
    # negative target / controller without its histogram: rejected
    with pytest.raises(ValueError):
        SystemOptions.from_args(p.parse_args(
            ["--sys.serve.slo_ms", "-1"]))
    with pytest.raises(ValueError):
        SystemOptions.from_args(p.parse_args(
            ["--sys.serve.slo_ms", "10", "--sys.metrics", "0"]))


def test_tier_knobs_round_trip_and_rejection():
    """--sys.tier.* parse into the options the TierManager consumes,
    and bad ranges fail loudly at parse time (ISSUE 5)."""
    import argparse

    import pytest

    from adapm_tpu.config import SystemOptions
    p = argparse.ArgumentParser()
    SystemOptions.add_arguments(p)
    dflt = SystemOptions.from_args(p.parse_args([]))
    assert (dflt.tier, dflt.tier_hot_rows, dflt.tier_pin_intent,
            dflt.tier_demote_batch) == (False, 65536, True, 1024)
    on = SystemOptions.from_args(p.parse_args(
        ["--sys.tier", "1", "--sys.tier.hot_rows", "4096",
         "--sys.tier.pin_intent", "0", "--sys.tier.demote_batch",
         "128"]))
    assert on.tier and on.tier_hot_rows == 4096
    assert not on.tier_pin_intent and on.tier_demote_batch == 128
    for argv in (["--sys.tier", "1", "--sys.tier.hot_rows", "4"],
                 ["--sys.tier", "1", "--sys.tier.demote_batch", "0"]):
        with pytest.raises(ValueError):
            SystemOptions.from_args(p.parse_args(argv))
    # tier off: hot_rows range is irrelevant and must not reject
    SystemOptions.from_args(p.parse_args(["--sys.tier.hot_rows", "4"]))


def test_compression_knobs_round_trip_and_rejection():
    """--sys.tier.cold_dtype / --sys.sync.compress parse into the
    options the compression plane consumes, and invalid names or
    inconsistent combinations fail loudly at parse time (ISSUE 8)."""
    import argparse

    import pytest

    from adapm_tpu.config import SystemOptions
    p = argparse.ArgumentParser()
    SystemOptions.add_arguments(p)
    dflt = SystemOptions.from_args(p.parse_args([]))
    # both DEFAULT to the pre-PR exact wire: fp32 at rest, no sync
    # compression (the bit-identity pin run_tests.sh guards)
    assert dflt.tier_cold_dtype == "fp32"
    assert dflt.sync_compress == "off"
    on = SystemOptions.from_args(p.parse_args(
        ["--sys.tier", "1", "--sys.tier.cold_dtype", "fp16",
         "--sys.sync.compress", "fp16"]))
    assert on.tier_cold_dtype == "fp16" and on.sync_compress == "fp16"
    i8 = SystemOptions.from_args(p.parse_args(
        ["--sys.tier", "1", "--sys.tier.cold_dtype", "int8",
         "--sys.sync.compress", "int8"]))
    assert i8.tier_cold_dtype == "int8" and i8.sync_compress == "int8"
    # invalid dtype names: argparse choices reject unknown wire formats
    # before the options object even exists
    with pytest.raises(SystemExit):
        p.parse_args(["--sys.tier.cold_dtype", "fp8"])
    with pytest.raises(SystemExit):
        p.parse_args(["--sys.sync.compress", "bf16"])
    # hand-built options (no argparse choices) reject through validate
    with pytest.raises(ValueError):
        SystemOptions(tier_cold_dtype="fp8").validate_serve()
    with pytest.raises(ValueError):
        SystemOptions(sync_compress="bf16").validate_serve()
    # int8 sync without metrics: the EF residual loop would be invisible
    # (no sync.ef_residual_norm gauge) — a silent-quality-loss trap
    with pytest.raises(ValueError):
        SystemOptions.from_args(p.parse_args(
            ["--sys.sync.compress", "int8", "--sys.metrics", "0"]))
    # fp16 sync is allowed without metrics (residual bounded by the
    # representation, not the feedback loop alone)
    SystemOptions.from_args(p.parse_args(
        ["--sys.sync.compress", "fp16", "--sys.metrics", "0"]))
    # compression requires the dirty filter: the full-resync path has
    # no epoch state for residual-parked-but-clean replicas
    with pytest.raises(ValueError):
        SystemOptions.from_args(p.parse_args(
            ["--sys.sync.compress", "fp16", "--sys.sync.dirty_only", "0"]))


def test_collective_sync_knobs():
    """--sys.collective_sync / --sys.collective_bucket parse into the
    options GlobalPM consults when choosing the sync data plane."""
    import argparse

    from adapm_tpu.config import SystemOptions
    p = argparse.ArgumentParser()
    SystemOptions.add_arguments(p)
    off = SystemOptions.from_args(p.parse_args([]))
    assert off.collective_sync is False and off.collective_bucket == 1024
    assert off.collective_cadence == 0
    on = SystemOptions.from_args(p.parse_args(
        ["--sys.collective_sync", "1", "--sys.collective_bucket", "256",
         "--sys.collective_cadence", "8"]))
    assert on.collective_sync is True and on.collective_bucket == 256
    assert on.collective_cadence == 8


def test_fault_and_ckpt_knobs_round_trip_and_rejection():
    """--sys.fault.* / --sys.checkpoint.* parse into the options the
    fault plane, executor policy, and periodic checkpointer consume
    (ISSUE 10); bad combinations fail loudly at parse time."""
    import argparse

    import pytest

    from adapm_tpu.config import SystemOptions
    p = argparse.ArgumentParser()
    SystemOptions.add_arguments(p)
    dflt = SystemOptions.from_args(p.parse_args([]))
    # defaults: NO injection plane, inert retry policy, no periodic ckpt
    assert dflt.fault_spec == "" and dflt.fault_seed == 0
    assert (dflt.fault_retries, dflt.fault_watchdog_s) == (3, 30.0)
    assert dflt.ckpt_every_s == 0.0 and dflt.ckpt_path is None
    on = SystemOptions.from_args(p.parse_args(
        ["--sys.fault.spec", "sync.round=0.2,serve.drain=0.1",
         "--sys.fault.seed", "7", "--sys.fault.retries", "5",
         "--sys.fault.backoff_ms", "2", "--sys.fault.watchdog_s", "9",
         "--sys.checkpoint.every", "30",
         "--sys.checkpoint.path", "/tmp/chain"]))
    assert on.fault_spec == "sync.round=0.2,serve.drain=0.1"
    assert on.fault_seed == 7 and on.fault_retries == 5
    assert on.fault_backoff_ms == 2.0 and on.fault_watchdog_s == 9.0
    assert on.ckpt_every_s == 30.0 and on.ckpt_path == "/tmp/chain"
    bad = (["--sys.fault.spec", "oops"],           # not point=prob
           ["--sys.fault.spec", "x=1.5"],          # prob out of range
           ["--sys.fault.retries", "-1"],
           ["--sys.fault.watchdog_s", "0"],
           ["--sys.checkpoint.every", "-2"],
           # periodic checkpoints without a chain directory
           ["--sys.checkpoint.every", "30"])
    for argv in bad:
        with pytest.raises(ValueError):
            SystemOptions.from_args(p.parse_args(argv))
    # hand-built options are validated the same way
    with pytest.raises(ValueError):
        SystemOptions(fault_spec="x=nan").validate_serve()


def test_lint_lockorder_knob_round_trip_and_rejection():
    """--sys.lint.lockorder (ISSUE 11): parses into the option the
    Server's lock wiring consumes, defaults OFF (the skip-wrapper
    shape — plain RLocks, no sentinel — is pinned by
    tests/test_lint.py::test_lockorder_skip_wrapper_shape), and a
    non-integer value is rejected at the parser."""
    import argparse

    import pytest

    from adapm_tpu.config import SystemOptions
    p = argparse.ArgumentParser()
    SystemOptions.add_arguments(p)
    dflt = SystemOptions.from_args(p.parse_args([]))
    assert dflt.lint_lockorder is False
    on = SystemOptions.from_args(p.parse_args(
        ["--sys.lint.lockorder", "1"]))
    assert on.lint_lockorder is True
    off = SystemOptions.from_args(p.parse_args(
        ["--sys.lint.lockorder", "0"]))
    assert off.lint_lockorder is False
    with pytest.raises(SystemExit):  # argparse type=int rejection
        p.parse_args(["--sys.lint.lockorder", "maybe"])


def test_episode_batches_knob_round_trip_and_rejection():
    """--sys.episode.batches (ISSUE 14): parses into the option
    EpisodicRunner defaults from, defaults to 8, and zero is rejected
    by validate_serve at parse time (an episode must hold a batch)."""
    import argparse

    import pytest

    from adapm_tpu.config import SystemOptions
    p = argparse.ArgumentParser()
    SystemOptions.add_arguments(p)
    dflt = SystemOptions.from_args(p.parse_args([]))
    assert dflt.episode_batches == 8
    got = SystemOptions.from_args(p.parse_args(
        ["--sys.episode.batches", "3"]))
    assert got.episode_batches == 3
    with pytest.raises(ValueError, match="episode.batches"):
        SystemOptions.from_args(p.parse_args(
            ["--sys.episode.batches", "0"]))


def test_workload_trace_knobs_round_trip_and_rejection():
    """--sys.trace.workload / --sys.trace.workload_keys (ISSUE 15):
    parse into the options the WorkloadTraceRecorder consumes, default
    OFF (no recorder, zero wtrace.* names — pinned by
    tests/test_wtrace.py and scripts/metrics_overhead_check.py), and a
    zero key budget is rejected at parse time AND on hand-built
    options."""
    import argparse

    import pytest

    from adapm_tpu.config import SystemOptions
    p = argparse.ArgumentParser()
    SystemOptions.add_arguments(p)
    dflt = SystemOptions.from_args(p.parse_args([]))
    assert dflt.trace_workload is None
    assert dflt.trace_workload_keys == 4096
    on = SystemOptions.from_args(p.parse_args(
        ["--sys.trace.workload", "/tmp/run.wtrace",
         "--sys.trace.workload_keys", "256"]))
    assert on.trace_workload == "/tmp/run.wtrace"
    assert on.trace_workload_keys == 256
    # zero/negative key budget: an unreplayable trace, rejected loudly
    with pytest.raises(ValueError, match="workload_keys"):
        SystemOptions.from_args(p.parse_args(
            ["--sys.trace.workload", "/tmp/run.wtrace",
             "--sys.trace.workload_keys", "0"]))
    with pytest.raises(ValueError, match="workload_keys"):
        SystemOptions(trace_workload_keys=-1).validate_serve()
    # non-integer budget rejected by argparse itself
    with pytest.raises(SystemExit):
        p.parse_args(["--sys.trace.workload_keys", "lots"])


def test_bag_and_costs_knobs_round_trip_and_rejection():
    """--sys.serve.bags / --sys.costs.table / --sys.costs.calibrate
    (ISSUE 16): parse into the options the serve batcher's bag
    dispatch and the kernel cost table consume; bags default ON (the
    fused path), the cost table defaults absent; an empty table path
    and a calibrate without a table are rejected at parse time AND on
    hand-built options."""
    import argparse

    import pytest

    from adapm_tpu.config import SystemOptions
    p = argparse.ArgumentParser()
    SystemOptions.add_arguments(p)
    dflt = SystemOptions.from_args(p.parse_args([]))
    assert dflt.serve_bags is True
    assert dflt.costs_table is None
    assert dflt.costs_calibrate is False
    on = SystemOptions.from_args(p.parse_args(
        ["--sys.serve.bags", "0",
         "--sys.costs.table", "/tmp/costs.json",
         "--sys.costs.calibrate", "1"]))
    assert on.serve_bags is False
    assert on.costs_table == "/tmp/costs.json"
    assert on.costs_calibrate is True
    # an empty table path can persist nothing — rejected loudly
    with pytest.raises(ValueError, match="costs.table"):
        SystemOptions.from_args(p.parse_args(
            ["--sys.costs.table", ""]))
    with pytest.raises(ValueError, match="costs.table"):
        SystemOptions(costs_table="").validate_serve()
    # a calibration pass with nowhere to persist is a no-op trap
    with pytest.raises(ValueError, match="costs.calibrate"):
        SystemOptions.from_args(p.parse_args(
            ["--sys.costs.calibrate", "1"]))
    with pytest.raises(ValueError, match="costs.calibrate"):
        SystemOptions(costs_calibrate=True).validate_serve()
    # non-integer bag flag rejected by argparse itself
    with pytest.raises(SystemExit):
        p.parse_args(["--sys.serve.bags", "maybe"])


def test_decision_trace_knobs_round_trip_and_rejection():
    """--sys.trace.decisions / --sys.trace.decisions_window /
    --sys.trace.spans.max_events (ISSUE 17): parse into the options
    the DecisionRecorder and SpanTracer consume, decisions default OFF
    (no recorder, zero decision.* names — pinned by
    tests/test_decisions.py and scripts/metrics_overhead_check.py);
    an empty .dtrace path, a zero follow window, and a sub-1000 span
    bound are each rejected at parse time AND on hand-built options."""
    import argparse

    import pytest

    from adapm_tpu.config import SystemOptions
    p = argparse.ArgumentParser()
    SystemOptions.add_arguments(p)
    dflt = SystemOptions.from_args(p.parse_args([]))
    assert dflt.trace_decisions is None
    assert dflt.trace_decisions_window == 8
    assert dflt.trace_spans_max_events == 1_000_000
    on = SystemOptions.from_args(p.parse_args(
        ["--sys.trace.decisions", "/tmp/run.dtrace",
         "--sys.trace.decisions_window", "16",
         "--sys.trace.spans.max_events", "5000"]))
    assert on.trace_decisions == "/tmp/run.dtrace"
    assert on.trace_decisions_window == 16
    assert on.trace_spans_max_events == 5000
    # an empty path can flush nothing — rejected loudly
    with pytest.raises(ValueError, match="trace.decisions"):
        SystemOptions.from_args(p.parse_args(
            ["--sys.trace.decisions", ""]))
    with pytest.raises(ValueError, match="trace.decisions"):
        SystemOptions(trace_decisions="").validate_serve()
    # a zero-event follow window can never resolve an outcome
    with pytest.raises(ValueError, match="decisions_window"):
        SystemOptions.from_args(p.parse_args(
            ["--sys.trace.decisions", "/tmp/run.dtrace",
             "--sys.trace.decisions_window", "0"]))
    with pytest.raises(ValueError, match="decisions_window"):
        SystemOptions(trace_decisions_window=0).validate_serve()
    # a tiny span buffer silently truncates every trace — floor 1000
    with pytest.raises(ValueError, match="spans.max_events"):
        SystemOptions.from_args(p.parse_args(
            ["--sys.trace.spans.max_events", "100"]))
    with pytest.raises(ValueError, match="spans.max_events"):
        SystemOptions(trace_spans_max_events=999).validate_serve()
    # non-integer values rejected by argparse itself
    with pytest.raises(SystemExit):
        p.parse_args(["--sys.trace.decisions_window", "soon"])


def test_policy_knobs_round_trip_and_rejection():
    """--sys.policy.{reloc,tier,sync,serve}/file/shadow (ISSUE 18):
    parse into the options PolicyPlane consumes, everything defaults
    OFF (no plane, zero policy.* names — pinned by tests/test_policy.py
    and scripts/metrics_overhead_check.py); an unknown mode, an empty
    artifact path, and learned/shadow without a file are each rejected
    at parse time AND on hand-built options."""
    import argparse

    import pytest

    from adapm_tpu.config import SystemOptions
    p = argparse.ArgumentParser()
    SystemOptions.add_arguments(p)
    dflt = SystemOptions.from_args(p.parse_args([]))
    assert dflt.policy_reloc == "heuristic"
    assert dflt.policy_tier == "heuristic"
    assert dflt.policy_sync == "heuristic"
    assert dflt.policy_serve == "heuristic"
    assert dflt.policy_file is None
    assert dflt.policy_shadow is False
    on = SystemOptions.from_args(p.parse_args(
        ["--sys.policy.file", "/tmp/policy.json",
         "--sys.policy.tier", "learned",
         "--sys.policy.serve", "learned",
         "--sys.policy.shadow", "1"]))
    assert on.policy_file == "/tmp/policy.json"
    assert on.policy_tier == "learned"
    assert on.policy_serve == "learned"
    assert on.policy_reloc == "heuristic"  # untouched planes stay off
    assert on.policy_sync == "heuristic"
    assert on.policy_shadow is True
    # unknown mode rejected by argparse choices AND hand-built options
    with pytest.raises(SystemExit):
        p.parse_args(["--sys.policy.tier", "oracle"])
    with pytest.raises(ValueError, match="policy.tier"):
        SystemOptions(policy_tier="oracle",
                      policy_file="/tmp/p.json").validate_serve()
    # an empty artifact path can load nothing — rejected loudly
    with pytest.raises(ValueError, match="policy.file"):
        SystemOptions.from_args(p.parse_args(
            ["--sys.policy.file", ""]))
    with pytest.raises(ValueError, match="policy.file"):
        SystemOptions(policy_file="").validate_serve()
    # learned mode without an artifact has nothing to consult
    with pytest.raises(ValueError, match="policy.file"):
        SystemOptions.from_args(p.parse_args(
            ["--sys.policy.sync", "learned"]))
    with pytest.raises(ValueError, match="policy.file"):
        SystemOptions(policy_sync="learned").validate_serve()
    # shadow mode scores the trained policy — meaningless without one
    with pytest.raises(ValueError, match="policy.shadow"):
        SystemOptions.from_args(p.parse_args(
            ["--sys.policy.shadow", "1"]))
    with pytest.raises(ValueError, match="policy.shadow"):
        SystemOptions(policy_shadow=True).validate_serve()
    # non-integer shadow flag rejected by argparse itself
    with pytest.raises(SystemExit):
        p.parse_args(["--sys.policy.shadow", "maybe"])


def test_net_knobs_round_trip_and_rejection():
    """--sys.net.{backend,queue,timeout_ms,heartbeat_ms} parse into
    the options the NetPort backends consume, with bad values failing
    loudly at parse time (ISSUE 19 satellite)."""
    import argparse

    import pytest

    from adapm_tpu.config import SystemOptions
    p = argparse.ArgumentParser()
    SystemOptions.add_arguments(p)
    dflt = SystemOptions.from_args(p.parse_args([]))
    assert (dflt.net_backend, dflt.net_queue, dflt.net_timeout_ms,
            dflt.net_heartbeat_ms) == ("auto", 64, 5000.0, 100.0)
    on = SystemOptions.from_args(p.parse_args(
        ["--sys.net.backend", "tcp", "--sys.net.queue", "128",
         "--sys.net.timeout_ms", "750", "--sys.net.heartbeat_ms",
         "40"]))
    assert on.net_backend == "tcp" and on.net_queue == 128
    assert on.net_timeout_ms == 750.0 and on.net_heartbeat_ms == 40.0
    bad = (["--sys.net.backend", "carrier-pigeon"],
           ["--sys.net.queue", "0"],
           ["--sys.net.timeout_ms", "0"],
           ["--sys.net.heartbeat_ms", "-5"])
    for argv in bad:
        with pytest.raises(ValueError):
            SystemOptions.from_args(p.parse_args(argv))
    # hand-built options are validated again at server construction
    with pytest.raises(ValueError, match="net.backend"):
        SystemOptions(net_backend="ipx").validate_serve()
    with pytest.raises(ValueError, match="net.queue"):
        SystemOptions(net_queue=-1).validate_serve()


def test_stream_knobs_round_trip_and_rejection():
    """--sys.stream.* and --sys.flight.freshness_samples parse into
    the options the streaming plane consumes, and inconsistent
    combinations fail loudly at parse time (ISSUE 20)."""
    import argparse

    import pytest

    from adapm_tpu.config import SystemOptions
    p = argparse.ArgumentParser()
    SystemOptions.add_arguments(p)
    dflt = SystemOptions.from_args(p.parse_args([]))
    # all DEFAULT OFF: no plane, zero stream.* names
    assert (dflt.stream_batch, dflt.stream_rate,
            dflt.stream_freshness_slo_ms,
            dflt.stream_freshness_slo_class) == (0, 0.0, 0.0, "")
    assert dflt.flight_freshness_samples == 1024
    on = SystemOptions.from_args(p.parse_args(
        ["--sys.stream.batch", "32", "--sys.stream.rate", "2000",
         "--sys.stream.freshness_slo_ms", "400,1=200",
         "--sys.trace.flight", "1",
         "--sys.flight.freshness_samples", "64"]))
    assert on.stream_batch == 32 and on.stream_rate == 2000.0
    # the flag carries "base,prio=ms,..." — split at parse time
    assert on.stream_freshness_slo_ms == 400.0
    assert on.stream_freshness_slo_class == "1=200"
    assert on.flight_freshness_samples == 64
    bad = (["--sys.stream.batch", "-1"],
           ["--sys.stream.rate", "-2"],
           # rate needs a batch to pace
           ["--sys.stream.rate", "100"],
           ["--sys.stream.freshness_slo_ms", "-5"],
           # the controller without its sensor / its registry
           ["--sys.stream.freshness_slo_ms", "50"],
           ["--sys.stream.freshness_slo_ms", "50",
            "--sys.trace.flight", "1", "--sys.metrics", "0"],
           # probe bound floor
           ["--sys.flight.freshness_samples", "4"],
           # per-class semantics: dup class / non-positive target
           ["--sys.stream.freshness_slo_ms", "400,1=200,1=100",
            "--sys.trace.flight", "1"],
           ["--sys.stream.freshness_slo_ms", "400,1=-5",
            "--sys.trace.flight", "1"])
    for argv in bad:
        with pytest.raises(ValueError):
            SystemOptions.from_args(p.parse_args(argv))
    # malformed class SYNTAX is rejected by argparse itself
    with pytest.raises(SystemExit):
        p.parse_args(["--sys.stream.freshness_slo_ms", "400,x=oops"])
    # hand-built options are validated again at plane construction
    with pytest.raises(ValueError, match="stream.rate"):
        SystemOptions(stream_rate=100.0).validate_serve()
    with pytest.raises(ValueError, match="freshness_samples"):
        SystemOptions(flight_freshness_samples=2).validate_serve()


def test_serve_slo_class_spec_round_trip_and_rejection():
    """--sys.serve.slo_ms accepts per-priority-class overrides
    ("20,1=5"); the no-override spec stays byte-identical (ISSUE 20
    satellite)."""
    import argparse

    import pytest

    from adapm_tpu.config import SystemOptions, parse_class_targets
    p = argparse.ArgumentParser()
    SystemOptions.add_arguments(p)
    plain = SystemOptions.from_args(p.parse_args(
        ["--sys.serve.slo_ms", "20"]))
    assert plain.serve_slo_ms == 20.0 and plain.serve_slo_class == ""
    assert parse_class_targets(plain.serve_slo_ms,
                               plain.serve_slo_class) == {}
    on = SystemOptions.from_args(p.parse_args(
        ["--sys.serve.slo_ms", "20,1=5,0=50"]))
    assert on.serve_slo_ms == 20.0 and on.serve_slo_class == "1=5,0=50"
    assert parse_class_targets(on.serve_slo_ms, on.serve_slo_class) \
        == {1: 5.0, 0: 50.0}
    # overrides demand a base target; negative classes are rejected
    with pytest.raises(ValueError):
        parse_class_targets(0.0, "1=5")
    with pytest.raises(ValueError):
        SystemOptions.from_args(p.parse_args(
            ["--sys.serve.slo_ms", "20,-1=5"]))
