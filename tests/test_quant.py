"""Compression co-design (ISSUE 8): quantized cold tier
(--sys.tier.cold_dtype; tier/quant.py) + error-fed delta-compressed
sync (--sys.sync.compress; store._sync_replicas_compressed).

The load-bearing tests are the two storms:

  - the QUANTIZED tier storm — a randomized push / set / relocate /
    replica-churn / sync / promote / demote interleaving on a tiered
    fp16/int8 server vs an untiered fp32 shadow, with every read (and
    the post-quiesce final read) bounded by the documented numeric
    contract (docs/MEMORY.md "Cold-row numeric contract"): visible
    error never exceeds a couple of grid steps, regardless of how many
    promote/demote/write cycles a row went through (the EF residual is
    what makes that a bound instead of a random walk);
  - the EXACT-case pin — values on the fp16 grid survive promote /
    demote / relocation cycles BIT-identically (the "exact on the
    fp16-representable cases" half of the contract).

Plus: wire-format units (host and the jitted device twins must agree),
EF sum preservation, sub-grid update accumulation (the classic EF-SGD
property: a stream of updates each too small to quantize still lands),
sync byte accounting (half / quarter), exact flush at drop/quiesce,
and the beyond-HBM host-RAM contract for `_read_owned_bulk` + the
dequant read path (no transient second full-table copy).
"""
import tracemalloc

import numpy as np
import pytest

import adapm_tpu
from adapm_tpu.base import CLOCK_MAX, MgmtTechniques
from adapm_tpu.config import SystemOptions
from adapm_tpu.tier.quant import (QuantCold, compress_delta,
                                  dequantize_rows, grid_step,
                                  quantize_rows, wire_bytes_per_row)

E = 384
L = 8


def _mk(tier: bool, hot_rows: int = 16, **kw):
    opts = SystemOptions(sync_max_per_sec=0, prefetch=False,
                         tier=tier, tier_hot_rows=hot_rows, **kw)
    return adapm_tpu.setup(E, L, opts=opts)


def _read_all(srv):
    return np.asarray(srv.read_main(np.arange(E)))


def _grid_tol(mode: str, rows: np.ndarray) -> np.ndarray:
    """Per-row bound from the documented contract (docs/MEMORY.md):
    two grid steps of the row's max-abs — one for the at-rest rounding,
    one for a parked residual's worth of slack."""
    return 2.0 * grid_step(mode, rows) + 1e-6


# ---------------------------------------------------------------------------
# wire-format units
# ---------------------------------------------------------------------------


def test_wire_bytes_per_row_table():
    assert wire_bytes_per_row("off", 16) == 64
    assert wire_bytes_per_row("fp32", 16) == 64
    assert wire_bytes_per_row("fp16", 16) == 32   # half
    assert wire_bytes_per_row("int8", 16) == 18   # quarter + f16 scale
    with pytest.raises(ValueError):
        wire_bytes_per_row("fp8", 16)


def test_quantize_exact_on_grid(rng):
    # fp16: values already representable round-trip exactly
    v = rng.normal(size=(32, L)).astype(np.float16).astype(np.float32)
    q, s = quantize_rows("fp16", v)
    assert np.array_equal(dequantize_rows("fp16", q, s), v)
    # int8: rows of integers with max 127 -> scale 1.0 (f16-exact),
    # every element on the grid
    vi = rng.integers(-127, 128, size=(32, L)).astype(np.float32)
    vi[:, 0] = 127.0  # pin the scale
    q, s = quantize_rows("int8", vi)
    assert np.array_equal(s, np.ones(32, np.float32))
    assert np.array_equal(dequantize_rows("int8", q, s), vi)


@pytest.mark.parametrize("mode", ["fp16", "int8"])
def test_compress_delta_ef_preserves_sum(rng, mode):
    d = (rng.normal(size=(64, L)) * 10.0 ** rng.integers(
        -3, 3, size=(64, 1))).astype(np.float32)
    d[0] = 0.0  # all-zero row: ships zero, residual zero
    shipped, resid = compress_delta(mode, d)
    # EF identity: what the owner merges plus what stays parked is the
    # original delta (up to one f32 rounding of the subtraction)
    err = np.abs((shipped + resid) - d)
    assert err.max() <= 4 * np.spacing(np.abs(d).max(), dtype=np.float32)
    # the parked residual is sub-grid: bounded by one step
    step = (np.max(np.abs(d), axis=1) * 2.0 ** -11 if mode == "fp16"
            else np.max(np.abs(d), axis=1) / 127.0)
    assert (np.max(np.abs(resid), axis=1) <= step + 1e-7).all()
    assert not shipped[1:].any() or np.abs(shipped).max() > 0


def test_device_and_host_wire_transforms_agree(rng):
    """The jitted compressed-sync program and quant.compress_delta must
    produce the SAME shipped values (the tiered cold-owner path runs
    the host twin against device rounds) — including the overflow clamp
    (the 1e9 row: beyond-f16-range values saturate at F16_MAX instead
    of casting to inf and poisoning the EF loop with inf - inf = NaN;
    the int8 row's f16-rounded scale clips the same way)."""
    import jax.numpy as jnp

    from adapm_tpu.core.store import OOB
    from adapm_tpu.device.jaxport import _sync_replicas_compressed
    n, vlen = 8, L
    d = (rng.normal(size=(n, vlen)) * [[0.01], [0.1], [1], [10], [100],
                                       [1000], [0.001], [1e9]]
         ).astype(np.float32)
    for mode in ("fp16", "int8"):
        shipped_host, resid_host = compress_delta(mode, d)
        assert np.isfinite(shipped_host).all(), mode
        assert np.isfinite(resid_host).all(), mode
        # EF identity holds for the saturated row too: the clipped
        # excess is carried in the residual, nothing became inf/NaN
        err = np.abs((shipped_host + resid_host) - d)
        assert err.max() <= 4 * np.spacing(np.abs(d).max(),
                                           dtype=np.float32), mode
        main = jnp.zeros((1, n, vlen), jnp.float32)
        cache = jnp.zeros((1, n, vlen), jnp.float32)
        # the program DONATES delta, and jnp.asarray of a numpy array
        # can be zero-copy on CPU — hand it its OWN buffer or the
        # donation clobbers `d` in place (timing-dependent)
        delta = jnp.asarray(d.reshape(1, n, vlen).copy())
        z = np.zeros(n, np.int32)
        idx = np.arange(n, dtype=np.int32)
        main2, cache2, delta2, norm = _sync_replicas_compressed(
            main, cache, delta, z, idx, z, idx,
            jnp.float32(0.0), mode=mode)
        assert np.array_equal(np.asarray(main2)[0], shipped_host), mode
        assert np.array_equal(np.asarray(delta2)[0], resid_host), mode
        assert float(norm) == np.abs(resid_host).max()


# ---------------------------------------------------------------------------
# QuantCold mechanics
# ---------------------------------------------------------------------------


def test_quantcold_ef_accumulates_subgrid_adds(rng):
    """The EF-SGD property at rest: a stream of updates each below the
    int8 grid must still land — without the residual every one of them
    would round to zero and the row would never move."""
    qc = QuantCold(1, 4, L, mode="int8")
    base = np.full((1, L), 100.0, np.float32)  # grid step ~ 0.787
    qc.set_at(np.array([0]), np.array([1]), base)
    tiny = np.full((1, L), 0.1, np.float32)    # ~ step / 8
    for _ in range(40):
        qc.add_at(np.array([0]), np.array([1]), tiny)
    true = 100.0 + 40 * 0.1
    vis = qc.read(np.array([0]), np.array([1]))[0]
    step = true / 127.0
    assert np.abs(vis - true).max() <= step + 1e-5
    # take_true folds the parked remainder back: sub-step accurate
    full = qc.take_true(np.array([0]), np.array([1]))[0]
    assert np.abs(full - true).max() <= 1e-3


def test_quantcold_duplicate_adds_batch_order(rng):
    """In-batch duplicate coordinates accumulate like np.add.at on
    every mode (the device-scatter contract)."""
    sh = np.array([0, 0, 0, 0])
    sl = np.array([2, 3, 2, 2])
    rows = rng.normal(size=(4, L)).astype(np.float32) * 100
    for mode in ("fp32", "fp16", "int8"):
        qc = QuantCold(1, 4, L, mode=mode)
        qc.add_at(sh, sl, rows)
        want2 = rows[0] + rows[2] + rows[3]
        got2 = qc.take_true(np.array([0]), np.array([2]))[0]
        tol = _grid_tol("int8" if mode == "int8" else "fp16",
                        want2[None])[0] if mode != "fp32" else 0.0
        assert np.abs(got2 - want2).max() <= tol + 1e-4
        if mode == "fp32":
            assert np.array_equal(got2, want2)


def test_quantcold_resid_cap_evicts_counted(rng):
    qc = QuantCold(1, 64, L, mode="int8", resid_cap=8)
    vals = rng.normal(size=(32, L)).astype(np.float32) * 3.14159
    qc.set_at(np.zeros(32, np.int64), np.arange(32), vals)
    assert qc.resid_rows() <= 8
    assert qc.ef_evicted > 0  # overflow is counted, never silent
    # accounting covers the parked rows
    assert qc.nbytes() >= qc.q.nbytes + qc.scale.nbytes


# ---------------------------------------------------------------------------
# THE quantized-tier drift storm (vs fp32 shadow)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("mode", ["fp16", "int8"])
def test_quant_storm_drift_bounded(rng, mode):
    srv = _mk(True, hot_rows=16, tier_cold_dtype=mode,
              sync_compress=mode)
    ref = _mk(False)
    w, wr = srv.make_worker(0), ref.make_worker(0)
    vals = rng.normal(size=(E, L)).astype(np.float32)
    for ww in (w, wr):
        ww.set(np.arange(E), vals)
    keys = np.arange(E)
    for step in range(40):
        op = rng.integers(0, 7)
        if op == 0:
            ks = rng.integers(0, E, 24)
            v = rng.normal(size=(24, L)).astype(np.float32)
            w.push(ks, v)
            wr.push(ks, v)
        elif op == 1:
            ks = rng.choice(E, 16, replace=False)
            v = rng.normal(size=(16, L)).astype(np.float32)
            w.set(ks, v)
            wr.set(ks, v)
        elif op == 2:
            ks = rng.choice(E, 12, replace=False)
            dest = int(rng.integers(0, srv.num_shards))
            srv._relocate_to(ks, dest)
            ref._relocate_to(ks, dest)
        elif op == 3:
            ks = rng.choice(keys[srv.ab.owner[keys] != w.shard], 16,
                            replace=False)
            end = int(w.current_clock + rng.integers(1, 4))
            w.intent(ks, w.current_clock, end)
            wr.intent(ks, wr.current_clock, end)
            srv.sync.run_round(force_intents=True, all_channels=True)
            ref.sync.run_round(force_intents=True, all_channels=True)
        elif op == 4:
            srv.sync.run_round(force_intents=True, all_channels=True)
            ref.sync.run_round(force_intents=True, all_channels=True)
        elif op == 5:
            srv.tier.promote_keys(rng.choice(E, 32, replace=False))
        else:
            srv.tier.demote_keys(rng.choice(E, 32, replace=False))
            srv.tier.maintain()
        if rng.integers(0, 3) == 0:
            w.advance_clock()
            wr.advance_clock()
        a = _read_all(srv).reshape(E, L)
        b = _read_all(ref).reshape(E, L)
        tol = _grid_tol(mode, b)
        assert (np.abs(a - b).max(axis=1) <= tol).all(), (
            f"step {step} (op {op}): drift "
            f"{np.abs(a - b).max():.3g} exceeds the {mode} contract")
    srv.quiesce()
    ref.quiesce()
    a = _read_all(srv).reshape(E, L)
    b = _read_all(ref).reshape(E, L)
    tol = _grid_tol(mode, b)
    assert (np.abs(a - b).max(axis=1) <= tol).all(), "post-quiesce drift"
    # the EF residual map never exceeded its bound silently
    assert sum(st.coldq.ef_evicted for st in srv.stores) == 0
    srv.shutdown()
    ref.shutdown()


def test_fp16_exact_values_survive_cycles_bitwise(rng):
    """The exact half of the contract: values on the fp16 grid move
    through promote / demote / relocation cycles bit-identically."""
    srv = _mk(True, hot_rows=16, tier_cold_dtype="fp16")
    ref = _mk(False)
    w, wr = srv.make_worker(0), ref.make_worker(0)
    vals = rng.normal(size=(E, L)).astype(np.float16).astype(np.float32)
    for ww in (w, wr):
        ww.set(np.arange(E), vals)
    for step in range(12):
        srv.tier.promote_keys(rng.choice(E, 48, replace=False))
        srv.tier.demote_keys(rng.choice(E, 48, replace=False))
        srv.tier.maintain()
        ks = rng.choice(E, 12, replace=False)
        dest = int(rng.integers(0, srv.num_shards))
        srv._relocate_to(ks, dest)
        ref._relocate_to(ks, dest)
        a, b = _read_all(srv), _read_all(ref)
        assert np.array_equal(a, b), f"step {step}: fp16-exact drifted"
        pk = rng.integers(0, E, 20)
        assert np.array_equal(np.asarray(w.pull_sync(pk)),
                              np.asarray(wr.pull_sync(pk)))
    # no residuals were ever parked: everything was exact
    assert sum(st.coldq.resid_rows() for st in srv.stores) == 0
    srv.shutdown()
    ref.shutdown()


# ---------------------------------------------------------------------------
# delta-compressed sync (untiered): bytes, EF, exact flush
# ---------------------------------------------------------------------------


def _replicate(srv, w, n=48):
    keys = np.arange(E)
    ks = keys[srv.ab.owner[keys] != w.shard][:n]
    w.intent(ks, 0, CLOCK_MAX)
    srv.sync.run_round(force_intents=True, all_channels=True)
    assert (srv.ab.cache_slot[w.shard, ks] >= 0).all()
    return ks


@pytest.mark.parametrize("mode", ["fp16", "int8"])
def test_sync_compress_bytes_and_quiesce_exactness(rng, mode):
    opts = dict(sync_max_per_sec=0, prefetch=False,
                techniques=MgmtTechniques.REPLICATION_ONLY,
                cache_slots_per_shard=64)
    srv = adapm_tpu.setup(E, L, opts=SystemOptions(
        sync_compress=mode, **opts))
    ref = adapm_tpu.setup(E, L, opts=SystemOptions(**opts))
    w, wr = srv.make_worker(0), ref.make_worker(0)
    vals = rng.normal(size=(E, L)).astype(np.float32)
    w.set(np.arange(E), vals)
    wr.set(np.arange(E), vals)
    ks = _replicate(srv, w)
    kr = _replicate(ref, wr)
    assert np.array_equal(ks, kr)
    b0_shipped = sum(st.sync_bytes_shipped for st in srv.stores)
    b0_full = sum(st.sync_bytes_full for st in srv.stores)
    for _ in range(6):
        v = rng.normal(size=(len(ks), L)).astype(np.float32)
        w.push(ks, v)
        wr.push(ks, v)
        srv.sync.run_round(force_intents=True, all_channels=True)
        ref.sync.run_round(force_intents=True, all_channels=True)
        # read-your-writes through the parked residual: replica read =
        # fresh + residual, within a grid step of the shadow
        a = np.asarray(w.pull_sync(ks))
        b = np.asarray(wr.pull_sync(ks))
        tol = _grid_tol(mode, b.reshape(len(ks), L))
        assert (np.abs(a - b).reshape(len(ks), L).max(axis=1)
                <= tol).all()
    shipped = sum(st.sync_bytes_shipped for st in srv.stores) - b0_shipped
    full = sum(st.sync_bytes_full for st in srv.stores) - b0_full
    assert full > 0
    ratio = shipped / full
    want = wire_bytes_per_row(mode, L) / (4 * L)
    assert abs(ratio - want) < 1e-6, (ratio, want)
    # the residual gauge saw the parked remainders
    assert max(st.ef_residual_norm() for st in srv.stores) > 0.0
    # quiesce flushes residuals EXACTLY (compression bypassed): the
    # long-run sum is unbiased — only f32 merge-order rounding remains
    srv.quiesce()
    ref.quiesce()
    a, b = _read_all(srv), _read_all(ref)
    assert np.allclose(a, b, rtol=1e-6, atol=1e-6), (
        f"post-quiesce max drift {np.abs(a - b).max():.3g}: the exact "
        f"flush must leave no quantization bias behind")
    srv.shutdown()
    ref.shutdown()


def test_sync_compress_off_is_pre_pr_path(rng):
    """Defaults pin: with compress off, no compressed program ever
    runs (no device residual scalar), and the byte accounting records
    full-width rows — the pre-PR wire."""
    srv = _mk(False, techniques=MgmtTechniques.REPLICATION_ONLY,
              cache_slots_per_shard=64)
    w = srv.make_worker(0)
    w.set(np.arange(E), rng.normal(size=(E, L)).astype(np.float32))
    ks = _replicate(srv, w)
    w.push(ks, np.ones((len(ks), L), np.float32))
    srv.sync.run_round(force_intents=True, all_channels=True)
    st = srv.stores[0]
    assert st._ef_resid_dev is None
    assert st.ef_residual_norm() == 0.0
    assert st.sync_bytes_shipped == st.sync_bytes_full > 0
    snap = srv.metrics_snapshot()
    assert snap["sync"]["ef_residual_norm"] == 0.0
    assert snap["sync"]["bytes_per_round"] >= 0
    srv.shutdown()


def test_drop_flushes_residual_before_slot_free(rng):
    """A replica dropped after compressed rounds must not lose its
    parked residual: the drop path's flush bypasses compression, so
    the owner ends at the true sum (not the quantized one)."""
    opts = dict(sync_max_per_sec=0, prefetch=False,
                techniques=MgmtTechniques.REPLICATION_ONLY,
                cache_slots_per_shard=64)
    srv = adapm_tpu.setup(E, L, opts=SystemOptions(
        sync_compress="int8", **opts))
    w = srv.make_worker(0)
    w.set(np.arange(E), np.zeros((E, L), np.float32))
    keys = np.arange(E)
    k = keys[srv.ab.owner[keys] != w.shard][:1]
    w.intent(k, 0, 3)
    srv.sync.run_round(force_intents=True, all_channels=True)
    assert srv.ab.cache_slot[w.shard, k[0]] >= 0
    # a push whose int8 wire loses low bits: 100 + 0.05 off-grid
    v = np.full((1, L), 100.0, np.float32)
    v[0, 0] = 100.05
    w.push(k, v)
    srv.sync.run_round(force_intents=True, all_channels=True)  # compressed
    # expire the intent -> the next rounds flush-and-drop the replica
    for _ in range(8):
        w.advance_clock()
        srv.sync.run_round(force_intents=True, all_channels=True)
    assert srv.ab.cache_slot[w.shard, k[0]] < 0, "replica not dropped"
    got = np.asarray(srv.read_main(k)).reshape(L)
    assert np.abs(got - v[0]).max() < 1e-4, (
        f"residual lost on drop: {got[0]} vs {v[0, 0]}")
    srv.shutdown()


# ---------------------------------------------------------------------------
# beyond-HBM host-RAM contract (ISSUE 8 satellite)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("mode", ["fp32", "fp16"])
def test_read_owned_bulk_no_second_full_table_copy(rng, mode):
    """docs/MEMORY.md beyond-HBM contract, now actually tested: the
    bulk read path (checkpoint/eval/export) must fancy-index the
    requested rows out of the cold store — full-table f32 temporaries
    beyond the returned rows themselves (e.g. a main_full_host()
    assembly) would transiently double host RAM at exactly the scale
    tiering exists for. Applies to the fp16 dequant path too: the wire
    copy is half-width, dequantized straight into the output."""
    E_big, L_big = 6000, 64
    srv = adapm_tpu.setup(E_big, L_big, opts=SystemOptions(
        sync_max_per_sec=0, prefetch=False, tier=True,
        tier_hot_rows=64, tier_cold_dtype=mode))
    w = srv.make_worker(0)
    slab = 2000
    for lo in range(0, E_big, slab):
        w.set(np.arange(lo, lo + slab),
              rng.normal(size=(slab, L_big)).astype(np.float32))
    srv.block()
    keys = np.arange(E_big)
    table_bytes = E_big * L_big * 4
    tracemalloc.start()
    out = srv._read_owned_bulk(keys)
    _, peak = tracemalloc.get_traced_memory()
    tracemalloc.stop()
    # budget: the flat output + the per-class row gather + the wire
    # copy (<= half-width for fp16) + slack. A second full f32 table
    # (the failure mode) adds another 1.0x and must trip this.
    budget = (2.75 if mode == "fp32" else 3.25) * table_bytes
    assert peak < budget, (
        f"bulk read peaked at {peak / table_bytes:.2f}x the table "
        f"({mode}); a full-table temporary has crept back in")
    assert out.shape == (E_big * L_big,)
    srv.shutdown()
