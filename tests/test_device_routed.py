"""Device-routed fused step: must produce the same training updates as the
host-routed FusedStepRunner (same routing policy, resolved in-program), and
its table mirrors must track planner placement changes."""
import numpy as np
import pytest

import adapm_tpu
from adapm_tpu.base import CLOCK_MAX
from adapm_tpu.config import SystemOptions
from adapm_tpu.ops import DeviceRoutedRunner, FusedStepRunner


def _loss(embs, aux):
    return ((embs["a"] * embs["b"]).sum(-1) ** 2).mean()


def _make(num_keys=24, L=8):
    srv = adapm_tpu.setup(num_keys, L,
                          opts=SystemOptions(sync_max_per_sec=0,
                                             cache_slots_per_shard=8))
    w = srv.make_worker(0)
    rng = np.random.default_rng(0)
    init = rng.normal(size=(num_keys, L)).astype(np.float32)
    init[:, L // 2:] = 1e-6
    w.set(np.arange(num_keys), init)
    return srv, w


def test_matches_host_routed():
    kw = dict(role_class={"a": 0, "b": 0}, role_dim={"a": 4, "b": 4})
    srv1, w1 = _make()
    host = FusedStepRunner(srv1, _loss, **kw)
    srv2, w2 = _make()
    dev = DeviceRoutedRunner(srv2, _loss, shard=0, **kw)

    rng = np.random.default_rng(1)
    for _ in range(5):
        batch = {"a": rng.integers(0, 24, 16).astype(np.int64),
                 "b": rng.integers(0, 24, 16).astype(np.int64)}
        l1 = host(batch, None, 0.1)
        l2 = dev(batch, None, 0.1)
        assert np.allclose(float(l1), float(l2), rtol=1e-5)
    v1 = srv1.read_main(np.arange(24))
    v2 = srv2.read_main(np.arange(24))
    assert np.allclose(v1, v2, atol=1e-5)
    srv1.shutdown()
    srv2.shutdown()


def test_tracks_placement_changes():
    """After the planner creates replicas / relocates keys, the device
    tables refresh and updates land in the replica delta pool."""
    from adapm_tpu.base import MgmtTechniques
    kw = dict(role_class={"a": 0, "b": 0}, role_dim={"a": 4, "b": 4})
    srv, w = _make()
    srv.opts.techniques = MgmtTechniques.REPLICATION_ONLY
    dev = DeviceRoutedRunner(srv, _loss, shard=0, **kw)
    remote = np.array([k for k in range(24)
                       if srv.ab.owner[k] != 0][:4], dtype=np.int64)
    batch = {"a": remote, "b": remote}
    dev(batch, None, 0.1)
    before = srv.read_main(remote)

    # intent -> replicas on shard 0 (replication_only pins the decision)
    w.intent(remote, 0, CLOCK_MAX)
    srv.wait_sync()
    assert srv.ab.has_replica(remote, 0).all()
    dev(batch, None, 0.1)
    # the update went into the delta pool: mains unchanged until sync
    after = srv.read_main(remote)
    assert np.allclose(before, after)
    srv.quiesce()
    synced = srv.read_main(remote)
    assert not np.allclose(before, synced)
    srv.shutdown()


def test_device_side_negative_sampling():
    """neg keys drawn in-program from the locally-resident population
    (the Local sampling scheme on device)."""
    srv, w = _make()

    def loss(embs, aux):
        pos = (embs["a"] * embs["b"]).sum(-1)
        neg = (embs["a"][:, None, :] * embs["neg"]).sum(-1)
        import jax
        return (jax.nn.softplus(-pos) + jax.nn.softplus(neg).sum(-1)).mean()

    dev = DeviceRoutedRunner(
        srv, loss, role_class={"a": 0, "b": 0, "neg": 0},
        role_dim={"a": 4, "b": 4, "neg": 4}, shard=0,
        neg_role="neg", neg_shape=(16, 3),
        neg_population=np.arange(24))
    rng = np.random.default_rng(2)
    batch = {"a": rng.integers(0, 24, 16).astype(np.int64),
             "b": rng.integers(0, 24, 16).astype(np.int64)}
    l1 = dev(batch, None, 0.1)
    l2 = dev(batch, None, 0.1)
    assert np.isfinite(float(l1)) and np.isfinite(float(l2))
    # sampler population restricted to shard-0-resident keys
    padded, count = dev._local_neg_index()
    idx = np.asarray(padded)[: int(count)]
    assert ((srv.ab.owner[idx] == 0) |
            (srv.ab.cache_slot[0, idx] >= 0)).all()
    srv.shutdown()


def test_alias_table_distribution():
    """build_alias_table reproduces unigram^0.75 (Vose correctness)."""
    from adapm_tpu.models.sgns import build_alias_table
    counts = np.array([1, 10, 100, 1000, 5])
    prob, alias = build_alias_table(counts)
    p = counts.astype(np.float64) ** 0.75
    p /= p.sum()
    rng = np.random.default_rng(0)
    n = 200_000
    u = rng.integers(0, len(p), n)
    v = rng.random(n)
    draws = np.where(v < prob[u], u, alias[u])
    freq = np.bincount(draws, minlength=len(p)) / n
    assert np.allclose(freq, p, atol=0.01), (freq, p)


def test_device_alias_negative_sampling():
    """Non-uniform on-device negatives: alias draw + Local-scheme snap
    stays inside the locally-resident population and skews toward the
    heavy head of the distribution."""
    import jax
    srv, w = _make()

    def loss(embs, aux):
        pos = (embs["a"] * embs["b"]).sum(-1)
        neg = (embs["a"][:, None, :] * embs["neg"]).sum(-1)
        return (jax.nn.softplus(-pos) + jax.nn.softplus(neg).sum(-1)).mean()

    from adapm_tpu.models.sgns import build_alias_table
    counts = np.zeros(24)
    counts[:4] = 1000            # heavy head
    counts[4:] = 1
    dev = DeviceRoutedRunner(
        srv, loss, role_class={"a": 0, "b": 0, "neg": 0},
        role_dim={"a": 4, "b": 4, "neg": 4}, shard=0,
        neg_role="neg", neg_shape=(16, 3),
        neg_population=np.arange(24),
        neg_alias=build_alias_table(counts))
    rng = np.random.default_rng(2)
    batch = {"a": rng.integers(0, 24, 16).astype(np.int64),
             "b": rng.integers(0, 24, 16).astype(np.int64)}
    assert np.isfinite(float(dev(batch, None, 0.1)))
    # draw through the step's sampler logic directly for the skew check
    padded, count = dev._local_neg_index()
    prob, alias_t, key_table = dev._alias
    k1, k2 = jax.random.split(jax.random.PRNGKey(0))
    u = jax.random.randint(k1, (4000,), 0, prob.shape[0])
    v = jax.random.uniform(k2, (4000,))
    import jax.numpy as jnp
    cand = key_table[jnp.where(v < prob[u], u, alias_t[u])]
    pos = jnp.searchsorted(padded, cand)
    pos = jnp.where(pos >= count, 0, pos)
    drawn = np.asarray(padded)[np.asarray(pos)]
    idx = np.asarray(padded)[: int(count)]
    assert np.isin(drawn, idx).all(), "snap left the local population"
    srv.shutdown()


def test_w2v_device_routes_matches_host(tmp_path):
    """The w2v app trains with on-device unigram^0.75 negatives and lands
    at a loss comparable to the host-routed run on the same fixed seed
    (VERDICT r2 item 5 'done' criterion)."""
    from adapm_tpu.apps import word2vec as w2v
    base = ["--synthetic_vocab", "80", "--synthetic_sentences", "120",
            "--synthetic_path", str(tmp_path / "c.txt"),
            "--dim", "8", "--window", "3", "--negative", "4",
            "--epochs", "3", "--batch_size", "256", "--lr", "0.03",
            "--readahead", "30", "--seed", "11",
            "--sys.sync.max_per_sec", "0", "--sys.prefetch", "0"]
    host = w2v.run(w2v.build_parser().parse_args(
        base + ["--no-device_routes"]))
    dev = w2v.run(w2v.build_parser().parse_args(base + ["--device_routes"]))
    untrained = np.log(2.0) * 5
    assert dev < 0.9 * untrained, f"device path did not learn: {dev}"
    assert abs(dev - host) < 0.35 * max(host, 1e-6), (dev, host)


def test_run_scan_matches_sequential_steps():
    """K steps in one lax.scan dispatch (run_scan, VERDICT r3 item 2) must
    produce exactly the same pools and losses as K sequential __call__
    steps (same RNG pool order, same routing)."""
    kw = dict(role_class={"a": 0, "b": 0}, role_dim={"a": 4, "b": 4})
    srv1, _ = _make()
    seq = DeviceRoutedRunner(srv1, _loss, shard=0, **kw)
    srv2, _ = _make()
    scn = DeviceRoutedRunner(srv2, _loss, shard=0, **kw)

    rng = np.random.default_rng(7)
    batches = [{"a": rng.integers(0, 24, 16).astype(np.int64),
                "b": rng.integers(0, 24, 16).astype(np.int64)}
               for _ in range(4)]
    seq_losses = [float(seq(b, None, 0.1)) for b in batches]
    scan_losses = np.asarray(scn.run_scan(batches, None, 0.1))
    assert np.allclose(scan_losses, seq_losses, rtol=1e-5), \
        (scan_losses, seq_losses)
    v1 = srv1.read_main(np.arange(24))
    v2 = srv2.read_main(np.arange(24))
    assert np.allclose(v1, v2, atol=1e-5)
    # locality accounting covers the whole window
    assert scn.locality_counts() == seq.locality_counts()
    srv1.shutdown()
    srv2.shutdown()


def test_run_scan_with_aux_and_negatives():
    """run_scan with per-step aux values and on-device negative sampling
    must match the sequential path EXACTLY — including the RNG stream
    that draws the negatives (same seed => same _next_rng sequence,
    refills included)."""
    import jax

    def loss(embs, aux):
        pos = (embs["a"] * embs["b"]).sum(-1)
        neg = (embs["a"][:, None, :] * embs["neg"]).sum(-1)
        return (aux * jax.nn.softplus(-pos)
                + jax.nn.softplus(neg).sum(-1)).mean()

    kw = dict(role_class={"a": 0, "b": 0, "neg": 0},
              role_dim={"a": 4, "b": 4, "neg": 4}, shard=0,
              neg_role="neg", neg_shape=(16, 3),
              neg_population=np.arange(24), seed=3)
    srv1, _ = _make()
    seq = DeviceRoutedRunner(srv1, loss, **kw)
    srv2, _ = _make()
    scn = DeviceRoutedRunner(srv2, loss, **kw)
    rng = np.random.default_rng(9)
    batches = [{"a": rng.integers(0, 24, 16).astype(np.int64),
                "b": rng.integers(0, 24, 16).astype(np.int64)}
               for _ in range(3)]
    auxes = [np.full(16, w, np.float32) for w in (1.0, 0.5, 2.0)]
    seq_losses = [float(seq(b, a, 0.1)) for b, a in zip(batches, auxes)]
    losses = np.asarray(scn.run_scan(batches, auxes, 0.1))
    assert losses.shape == (3,) and np.isfinite(losses).all()
    assert np.allclose(losses, seq_losses, rtol=1e-5), (losses, seq_losses)
    assert np.allclose(srv1.read_main(np.arange(24)),
                       srv2.read_main(np.arange(24)), atol=1e-5)
    assert scn.locality_counts()["ops"] == 3
    srv1.shutdown()
    srv2.shutdown()


def test_device_routed_locality_stats():
    """The device-routed step accumulates locality counters in-program
    (VERDICT r3 item 7): counts match the host-side routing truth and flow
    into Server.locality_summary like Worker.stats do."""
    kw = dict(role_class={"a": 0, "b": 0}, role_dim={"a": 4, "b": 4})
    srv, w = _make()
    dev = DeviceRoutedRunner(srv, _loss, shard=0, **kw)
    rng = np.random.default_rng(3)
    exp_params = exp_local = 0
    exp_ops = exp_ops_local = 0
    for _ in range(4):
        batch = {"a": rng.integers(0, 24, 16).astype(np.int64),
                 "b": rng.integers(0, 24, 16).astype(np.int64)}
        dev(batch, None, 0.1)
        ks = np.concatenate([batch["a"], batch["b"]])
        local = (srv.ab.owner[ks] == 0) | (srv.ab.cache_slot[0, ks] >= 0)
        exp_params += len(ks)
        exp_local += int(local.sum())
        exp_ops += 1
        exp_ops_local += int(local.all())
    c = dev.locality_counts()
    assert c["params"] == exp_params and c["ops"] == exp_ops
    assert c["params_local"] == exp_local, (c, exp_local)
    assert c["ops_local"] == exp_ops_local
    # drain is cumulative and idempotent at reporting time
    assert dev.locality_counts() == c
    summ = srv.locality_summary()
    frac = exp_local / exp_params
    assert np.isclose(summ["pull_params_local_frac"], frac)
    assert np.isclose(summ["push_params_local_frac"], frac)
    # multi-shard default mesh: some keys of this batch must be non-local
    # for the fraction to be meaningful; guard the setup assumption
    if srv.num_shards > 1:
        assert frac < 1.0
    srv.shutdown()


def test_mf_device_routes_matches_host():
    """MF app with --device_routes converges like the host-routed run."""
    from adapm_tpu.apps import matrix_factorization as mf
    base = ["--rows", "48", "--cols", "32", "--nnz", "600", "--rank", "4",
            "--epochs", "5", "--batch_size", "16", "--lr", "0.1",
            "--algorithm", "plain", "--seed", "5",
            "--sys.sync.max_per_sec", "0", "--sys.prefetch", "0"]
    host = mf.run(mf.build_parser().parse_args(
        base + ["--no-device_routes"]))
    dev = mf.run(mf.build_parser().parse_args(base + ["--device_routes"]))
    assert np.isfinite(dev)
    assert dev < 1.3 * host + 1e-6, (dev, host)
