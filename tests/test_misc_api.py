"""API-parity odds and ends (reference ColoKVWorker surface)."""
import numpy as np

import adapm_tpu
from adapm_tpu.config import SystemOptions


def test_staggered_push():
    """StaggeredPush (coloc_kv_worker.h:556-580): grouped pushes over a
    large key set, flat and 2-D value layouts."""
    srv = adapm_tpu.setup(40, 4, opts=SystemOptions(sync_max_per_sec=0))
    w = srv.make_worker(0)
    keys = np.arange(40)
    vals = np.ones((40, 4), np.float32)
    w.staggered_push(keys, vals, group_size=7)
    w.wait_all()
    got = w.pull_sync(keys)
    assert np.allclose(got, 1.0)
    # flat layout too
    w.staggered_push(keys, np.ones(160, np.float32) * 2, group_size=11)
    w.wait_all()
    got = w.pull_sync(keys)
    assert np.allclose(got, 3.0)
    srv.shutdown()


def test_begin_setup_pauses_management():
    """BeginSetup/EndSetup bracket (reference coloc_kv_worker.h): sync
    rounds are no-ops while in setup, so bulk init runs management-free;
    EndSetup resumes (and barriers)."""
    from adapm_tpu.base import CLOCK_MAX
    srv = adapm_tpu.setup(32, 4, opts=SystemOptions(sync_max_per_sec=0))
    w = srv.make_worker(0)
    w.begin_setup()
    remote = np.array([k for k in range(32) if srv.ab.owner[k] != w.shard])
    w.intent(remote[:4], 0, CLOCK_MAX)
    srv.sync.run_round(all_channels=True)
    assert srv.sync.stats.intents_processed == 0, \
        "management must pause during setup"
    w.end_setup()
    srv.wait_sync()
    assert srv.sync.stats.intents_processed > 0, \
        "management must resume after setup"
    assert srv.ab.is_local(remote[:4], w.shard).all()
    srv.shutdown()


def test_pull_if_local():
    srv = adapm_tpu.setup(16, 2, opts=SystemOptions(sync_max_per_sec=0))
    w = srv.make_worker(0)
    local_keys = np.array([k for k in range(16)
                           if srv.ab.owner[k] == w.shard])
    ok, vals = w.pull_if_local(local_keys)
    assert ok and vals is not None
    remote = np.array([k for k in range(16) if srv.ab.owner[k] != w.shard])
    if len(remote):
        ok, vals = w.pull_if_local(remote[:1])
        assert not ok and vals is None
    srv.shutdown()


def test_worker_barrier_rendezvous():
    """Worker.barrier synchronizes ALL worker threads (reference
    ColoKVWorker::Barrier is a barrier over the worker group, not just
    processes): no thread passes the barrier before every active worker
    arrives."""
    import threading

    srv = adapm_tpu.setup(8, 2, num_workers=3,
                          opts=SystemOptions(sync_max_per_sec=0))
    ws = [srv.make_worker(i) for i in range(3)]
    arrived = []
    passed = []
    lock = threading.Lock()

    def run(i):
        if i == 2:
            # last worker delays: nobody may pass before it arrives
            import time
            time.sleep(0.2)
        with lock:
            arrived.append(i)  # arrival AT the barrier, not thread start
        ws[i].barrier()
        with lock:
            assert len(arrived) == 3, \
                "a worker passed the barrier before all arrived"
            passed.append(i)

    ts = [threading.Thread(target=run, args=(i,)) for i in range(3)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert sorted(passed) == [0, 1, 2]
    srv.shutdown()


def test_worker_barrier_excludes_finalized():
    """A worker that finalizes while others wait at a barrier is removed
    from the participant set (otherwise mixed-lifetime apps deadlock)."""
    import threading

    srv = adapm_tpu.setup(8, 2, num_workers=2,
                          opts=SystemOptions(sync_max_per_sec=0))
    w0, w1 = srv.make_worker(0), srv.make_worker(1)
    done = threading.Event()

    def waiter():
        w0.barrier()
        done.set()

    t = threading.Thread(target=waiter)
    t.start()
    assert not done.wait(0.2), "barrier must hold until w1 acts"
    w1.finalize()
    assert done.wait(5.0), "finalize must release the barrier"
    t.join()
    srv.shutdown()
