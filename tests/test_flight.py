"""Request-flight tracing + SLO autopilot (ISSUE 7 tentpole).

Tier-1 coverage for adapm_tpu/obs/flight.py + obs/slo.py and their
threading through serve/session, serve/admission, serve/batcher,
exec/executor, and core/kv:

  - THE acceptance walk: one served lookup renders as a single
    connected Perfetto flow in the exported JSON — the test loads the
    export and walks the flow-event links mint -> queue -> batch ->
    program -> reply, anchoring every step inside its phase slice;
  - the trace-propagation storm: every served lookup's chain is
    complete (no orphaned spans) under concurrent pushes, relocations,
    and sync rounds;
  - the off pin: `--sys.trace.flight 0` (default) leaves the registry
    untouched (zero flight.* names) and the hot path pays one
    `is None` check (the r7 skip-wrapper discipline);
  - SLO autopilot: control-law unit tests (shrink / grow / deadband /
    bounds) against a synthetic latency histogram, the
    static-knob-path-untouched pin for `--sys.serve.slo_ms 0`, and an
    end-to-end convergence smoke (the full guard is
    scripts/slo_convergence_check.py);
  - flight recorder: the per-stream ring + ring FILE ride
    `--sys.crash_dumps` and surface in `metrics_snapshot()["flight"]`;
  - freshness probe: push wall-time -> first servable read;
  - satellites: `hist_percentile` edge cases (empty / overflow /
    single-bucket) and the reporter's stable line format.
"""
import json
import threading
import time

import numpy as np
import pytest

from adapm_tpu import Server, SystemOptions, make_mesh
from adapm_tpu.obs.flight import (FLIGHT_PHASES, FlightRecorder,
                                  FreshnessProbe)
from adapm_tpu.serve import DeadlineExceededError, ServePlane

NK = 96
VL = 4


@pytest.fixture(scope="module")
def ctx():
    return make_mesh(8)


def make_server(ctx, num_keys=NK, vlen=VL, **kw):
    opts = kw.pop("opts", None) or SystemOptions(sync_max_per_sec=0)
    return Server(num_keys, vlen, opts=opts, ctx=ctx, **kw)


def _seed(w, num_keys=NK, vlen=VL):
    keys = np.arange(num_keys)
    vals = (np.arange(num_keys * vlen, dtype=np.float32)
            .reshape(num_keys, vlen))
    w.wait(w.set(keys, vals))
    return vals


def _load_flight(srv, tmp_path):
    path = srv.write_flight_trace()
    assert path is not None
    return json.load(open(path))


def _flow_chains(doc):
    """{trace_id: [flow events in emission order]} from the export."""
    chains = {}
    for e in doc["traceEvents"]:
        if e.get("ph") in ("s", "t", "f") and e.get("cat") == "flight":
            chains.setdefault(e["id"], []).append(e)
    return chains


def _phase_slices(doc):
    """{phase_name: [X slices]} for the five causal phases."""
    out = {n: [] for n in FLIGHT_PHASES}
    for e in doc["traceEvents"]:
        if e.get("ph") == "X" and e["name"] in out:
            out[e["name"]].append(e)
    return out


# ---------------------------------------------------------------------------
# THE acceptance walk: one lookup = one connected flow
# ---------------------------------------------------------------------------


def test_flight_flow_export_walk(ctx, tmp_path):
    """Acceptance: a served lookup's trace renders as a single
    connected flow (mint -> admission -> batch -> executor program ->
    reply). The test walks the flow-event links: 5 steps per trace id
    (one `s` start, three `t` steps, one `f` finish), each anchored
    INSIDE an `X` slice of the matching causal phase that carries the
    trace id in its membership args, with non-decreasing timestamps."""
    opts = SystemOptions(sync_max_per_sec=0, trace_flight=True,
                         stats_out=str(tmp_path))
    s = make_server(ctx, opts=opts)
    w = s.make_worker(0)
    vals = _seed(w)
    with ServePlane(s) as plane:
        sess = plane.session()
        for batch in (np.array([1, 5, 9]), np.array([7, 7, 3]),
                      np.array([42])):
            assert np.array_equal(sess.lookup(batch),
                                  w.pull_sync(batch))
    doc = _load_flight(s, tmp_path)
    s.shutdown()

    assert doc["adapm_flight"]["complete_flows"] >= 3
    chains = _flow_chains(doc)
    slices = _phase_slices(doc)
    assert len(chains) >= 3
    walked = 0
    for trace_id, evs in chains.items():
        # one start, three steps, one finish — a single connected chain
        assert [e["ph"] for e in evs] == ["s", "t", "t", "t", "f"], \
            trace_id
        assert all(e["id"] == trace_id for e in evs)
        # causal order: the flow's timestamps never regress (tolerance
        # covers the 3-decimal µs rounding of the export)
        ts = [e["ts"] for e in evs]
        assert all(a <= b + 1e-3 for a, b in zip(ts, ts[1:])), \
            (trace_id, ts)
        # each step anchors inside an X slice of its causal phase that
        # lists this trace in its batch membership
        for phase, ev in zip(FLIGHT_PHASES, evs):
            hits = [
                sl for sl in slices[phase]
                if sl["tid"] == ev["tid"]
                and sl["ts"] - 1e-3 <= ev["ts"] <= sl["ts"] + sl["dur"]
                + 1e-3 and trace_id in sl["args"]["traces"]]
            assert hits, (trace_id, phase, ev)
        walked += 1
    assert walked == len(chains)
    # batch-membership attribution: the program slice says how many
    # requests rode it and how many unique keys were gathered
    progs = slices["flight.program"]
    assert progs and all("traces" in p["args"] for p in progs)
    batches = slices["flight.batch"]
    assert batches
    for b in batches:
        assert b["args"]["requests"] >= 1
        assert b["args"]["unique_keys"] <= b["args"]["keys"]


def test_flight_storm_every_chain_complete(ctx, tmp_path):
    """Trace-propagation storm: concurrent serve clients vs a pusher, a
    relocator, and a sync driver — every SERVED lookup's chain is
    complete (mint -> queue -> batch -> program -> reply) and no trace
    id dangles with a partial chain (no orphaned spans)."""
    opts = SystemOptions(sync_max_per_sec=0, trace_flight=True,
                         stats_out=str(tmp_path))
    s = make_server(ctx, opts=opts)
    w0, w1 = s.make_worker(0), s.make_worker(1)
    _seed(w0)
    plane = ServePlane(s)
    errs: list = []
    served = [0, 0]
    stop = threading.Event()

    def client(ci):
        try:
            sess = plane.session()
            rng = np.random.default_rng(100 + ci)
            for _ in range(20):
                batch = rng.integers(0, NK, 8)
                got = sess.lookup(batch)
                assert got.shape == (8, VL)
                served[ci] += 1
        except BaseException as e:  # noqa: BLE001
            errs.append(e)

    def pusher():
        try:
            rng = np.random.default_rng(5)
            while not stop.is_set():
                ks = np.unique(rng.integers(0, NK, 6))
                w1.push(ks, rng.normal(size=(len(ks), VL))
                        .astype(np.float32))
                time.sleep(0.001)
        except BaseException as e:  # noqa: BLE001
            errs.append(e)

    def relocator():
        try:
            rng = np.random.default_rng(11)
            while not stop.is_set():
                keys = np.unique(rng.integers(0, NK, 4))
                s._relocate_to(keys, int(rng.integers(0, s.num_shards)))
                time.sleep(0.002)
        except BaseException as e:  # noqa: BLE001
            errs.append(e)

    def syncer():
        try:
            while not stop.is_set():
                with s._round_lock:
                    s.sync.run_round(all_channels=True)
                time.sleep(0.002)
        except BaseException as e:  # noqa: BLE001
            errs.append(e)

    clients = [threading.Thread(target=client, args=(ci,))
               for ci in range(2)]
    churn = [threading.Thread(target=f)
             for f in (pusher, relocator, syncer)]
    for t in clients + churn:
        t.start()
    for t in clients:
        t.join(timeout=120)
        assert not t.is_alive(), "serve client hung"
    stop.set()
    for t in churn:
        t.join(timeout=60)
        assert not t.is_alive()
    assert not errs, errs[:3]
    n_served = sum(served)
    assert n_served == 40

    doc = _load_flight(s, tmp_path)
    # every served lookup completed its chain...
    assert doc["adapm_flight"]["complete_flows"] == n_served
    chains = _flow_chains(doc)
    assert len(chains) == n_served
    # ...and no id with any causal-phase slice has a partial chain:
    # ids on phase slices either completed or were terminal-marked
    phase_ids = set()
    shed_ids = set()
    for e in doc["traceEvents"]:
        if e.get("ph") != "X" or e["name"] not in FLIGHT_PHASES:
            continue
        ids = set(e["args"]["traces"])
        phase_ids |= ids
        if e["args"].get("status") == "shed":
            shed_ids |= ids
    orphans = phase_ids - set(chains) - shed_ids
    assert not orphans, f"orphaned trace ids: {sorted(orphans)[:8]}"
    # the per-request breakdown ladder observed every served lookup
    snap = s.metrics_snapshot()
    for h in ("queue_s", "batch_wait_s", "dispatch_s", "device_s"):
        assert snap["flight"][h]["count"] == n_served, h
    assert snap["flight"]["complete"] == n_served
    plane.close()
    s.shutdown()


def test_flight_shed_records_terminal_slice(ctx, tmp_path):
    """A shed request's trace does not dangle silently: the terminal
    lookup slice carries status=shed, and no flow chain is fabricated
    for the incomplete phases."""
    opts = SystemOptions(sync_max_per_sec=0, trace_flight=True,
                         stats_out=str(tmp_path))
    s = make_server(ctx, opts=opts)
    w = s.make_worker(0)
    _seed(w)
    plane = ServePlane(s, start=False)  # paused: nothing will serve
    sess = plane.session()
    with pytest.raises(DeadlineExceededError):
        sess.lookup(np.array([1]), deadline_ms=20)
    doc = _load_flight(s, tmp_path)
    assert doc["adapm_flight"]["complete_flows"] == 0
    assert _flow_chains(doc) == {}
    sheds = [e for e in doc["traceEvents"]
             if e.get("ph") == "X" and e["name"] == "flight.lookup"
             and e["args"].get("status") == "shed"]
    assert len(sheds) == 1
    plane.close()
    s.shutdown()


def test_flight_worker_ops_single_segment(ctx, tmp_path):
    """Plain Worker.pull/push/set mint single-segment flights: one
    slice on the caller's thread, counted in flight.traces_total."""
    opts = SystemOptions(sync_max_per_sec=0, trace_flight=True,
                         stats_out=str(tmp_path))
    s = make_server(ctx, opts=opts)
    w = s.make_worker(0)
    _seed(w)
    w.pull_sync(np.array([1, 2]))
    w.push(np.array([1, 2]), np.ones((2, VL), np.float32))
    doc = _load_flight(s, tmp_path)
    names = {e["name"] for e in doc["traceEvents"]
             if e.get("ph") == "X"}
    assert "flight.kv.pull" in names
    assert "flight.kv.push" in names and "flight.kv.set" in names
    assert s.flight.stats()["traces"] >= 3  # set + pull + push
    s.shutdown()


def test_flight_off_default_untouched(ctx):
    """The off pin (`--sys.trace.flight 0`, the default): no tracer on
    the server, ZERO flight.* metric names in the registry, requests
    carry trace=None, and the worker wrapper's flight branch is the one
    `is None` check (r7 skip-wrapper discipline — the overhead guard in
    scripts/metrics_overhead_check.py runs with this default)."""
    s = make_server(ctx)
    w = s.make_worker(0)
    _seed(w)
    assert s.flight is None
    assert s.write_flight_trace() is None
    with ServePlane(s) as plane:
        sess = plane.session()
        sess.lookup(np.array([1, 2, 3]))
    assert not [n for n in s.obs.names() if n.startswith("flight.")]
    snap = s.metrics_snapshot()
    # the section stays schema-present; only the crash-ride recorder
    # summary lives there until --sys.trace.flight
    assert set(snap["flight"]) <= {"recorder"}
    s.shutdown()
    # ...and with metrics AND spans AND flight all off, the wrapper
    # degrades to a plain call (h/sp/fl all None on the server/worker)
    s2 = make_server(ctx, opts=SystemOptions(sync_max_per_sec=0,
                                             metrics=False))
    w2 = s2.make_worker(0)
    assert w2._h_pull is None and s2.spans is None and s2.flight is None
    s2.shutdown()


def test_flight_tracer_bounded_drops():
    """Slice memory is bounded: past max_slices new slices are counted
    as dropped, never stored."""
    from adapm_tpu.obs.flight import FlightTracer
    tr = FlightTracer(registry=None, max_slices=4)
    for _ in range(10):
        tr.record_op("kv.pull", time.perf_counter())
    st = tr.stats()
    assert st["slices"] == 4 and st["dropped"] == 6
    assert st["traces"] == 10


# ---------------------------------------------------------------------------
# freshness probe (ROADMAP-5 pre-work)
# ---------------------------------------------------------------------------


def test_freshness_probe_unit():
    p = FreshnessProbe(registry=None, sample_every=1, bound=4)
    tok = p.note_push(np.array([5, 6]))
    assert tok == 5
    # a gather enqueued BEFORE the push became visible read old data:
    # it must not retire the probe (even though the key matches)
    t_before = time.perf_counter()
    p.push_visible(tok)
    p.note_read(np.array([5, 9]), t_before)
    assert p.h_freshness.snap()["count"] == 0
    p.note_read(np.array([7]))          # miss: nothing resolved
    assert p.h_freshness.snap()["count"] == 0
    p.note_read(np.array([5, 9]))       # first servable read of key 5
    assert p.h_freshness.snap()["count"] == 1
    p.note_read(np.array([5]))          # measured once per probe entry
    assert p.h_freshness.snap()["count"] == 1
    # a push never marked visible (scatter not enqueued) never observes
    p.note_push(np.array([6]))
    p.note_read(np.array([6]))
    assert p.h_freshness.snap()["count"] == 1
    # the probe table is bounded, and filling it with never-served
    # keys does NOT silence the gauge: the oldest probe is evicted so
    # new pushes keep getting probed
    for k in range(100):
        assert p.note_push(np.array([100 + k])) == 100 + k
    assert len(p._pending) <= 4
    assert p.evicted > 0
    tok = p.note_push(np.array([999]))
    assert tok == 999
    p.push_visible(tok)
    p.note_read(np.array([999]))
    assert p.h_freshness.snap()["count"] == 2


def test_freshness_probe_end_to_end(ctx, tmp_path):
    """Event-to-servable staleness: the Nth push of a key is probed and
    the first serve lookup reading it lands one flight.freshness_s
    observation."""
    opts = SystemOptions(sync_max_per_sec=0, trace_flight=True,
                         stats_out=str(tmp_path))
    s = make_server(ctx, opts=opts)
    w = s.make_worker(0)
    _seed(w)
    with ServePlane(s) as plane:
        sess = plane.session()
        # sample_every pushes of the same key guarantee it is probed
        for _ in range(s.flight.freshness._sample):
            w.push(np.array([7]), np.ones((1, VL), np.float32))
        sess.lookup(np.array([7, 8]))
        snap = s.metrics_snapshot()
        assert snap["flight"]["freshness_s"]["count"] >= 1
        assert snap["flight"]["freshness_samples"] >= 1
    s.shutdown()


# ---------------------------------------------------------------------------
# flight recorder (rides --sys.crash_dumps)
# ---------------------------------------------------------------------------


def test_flight_recorder_ring_and_crash_tail(ctx, tmp_path):
    """The executor flight-recorder ring rides --sys.crash_dumps
    (default on, flight tracing NOT required): per-stream tail in
    memory, fixed-width ring FILE next to the crash dump (the
    post-mortem of what was in flight), and the recorder summary in
    metrics_snapshot()["flight"]."""
    s = make_server(ctx, opts=SystemOptions(sync_max_per_sec=0,
                                            stats_out=str(tmp_path)))
    w = s.make_worker(0)
    _seed(w)
    assert s.flight is None and s.flight_recorder is not None
    with ServePlane(s) as plane:
        sess = plane.session()
        for _ in range(4):
            sess.lookup(np.array([1, 2, 3]))
    tail = s.flight_recorder.tail()
    assert tail, "no executor programs recorded"
    assert {e["stream"] for e in tail} >= {"serve"}
    for e in tail:
        assert e["run_s"] >= 0.0 and e["wait_s"] >= 0.0
    serve_tail = s.flight_recorder.tail("serve")
    assert serve_tail and all(e["stream"] == "serve" for e in serve_tail)
    snap = s.metrics_snapshot()
    rec = snap["flight"]["recorder"]
    assert rec["programs_recorded"] >= len(serve_tail)
    assert rec["per_stream"].get("serve", 0) >= 1
    # the ring FILE sits next to the crash dump and names the programs
    rings = sorted(tmp_path.glob("adapm_flightring.*.log"))
    assert rings, "flight ring file missing"
    content = rings[-1].read_text()
    assert "stream=serve" in content and "label=serve.drain" in content
    s.shutdown()
    assert rings[-1].exists()  # the post-mortem survives shutdown


# ---------------------------------------------------------------------------
# SLO autopilot (obs/slo.py)
# ---------------------------------------------------------------------------


class _FakeBatcher:
    def __init__(self, wait_us, h):
        self.max_wait_us = wait_us
        self.h_latency = h


class _FakeServer:
    def __init__(self):
        from adapm_tpu.obs.metrics import MetricsRegistry
        self.obs = MetricsRegistry()
        self.decisions = None  # decision telemetry off (ISSUE 17)


def _mk_controller(target_ms=10.0, wait_us=20_000):
    from adapm_tpu.obs.metrics import SERVE_LATENCY_BOUNDS_S, Histogram
    from adapm_tpu.obs.slo import SLOController
    h = Histogram("serve.latency_s", bounds=SERVE_LATENCY_BOUNDS_S)
    b = _FakeBatcher(wait_us, h)
    c = SLOController(_FakeServer(), b, target_ms=target_ms)
    c._control()  # first tick: baseline snapshot only, never adjusts
    return c, b, h


def test_slo_control_law_shrink_grow_deadband():
    c, b, h = _mk_controller(target_ms=10.0, wait_us=20_000)
    # P99 far above target -> the window SHRINKS (multiplicative)
    for _ in range(10):
        h.observe(0.050)
    c._control()
    assert b.max_wait_us < 20_000
    assert int(c.c_adjust.value) == 1
    first = b.max_wait_us
    # P99 far below target -> the window GROWS back toward the cap
    for _ in range(10):
        h.observe(0.001)
    c._control()
    assert b.max_wait_us > first
    # P99 inside the deadband -> hysteresis: no change
    cur = b.max_wait_us
    for _ in range(10):
        h.observe(0.010)
    adjusts = int(c.c_adjust.value)
    c._control()
    assert b.max_wait_us == cur and int(c.c_adjust.value) == adjusts
    # every adjustment landed in the bounded log with old/new/p99
    rep = c.report()
    assert rep["adjustments"] == adjusts == 2
    assert len(rep["recent_adjustments"]) == 2
    a0 = rep["recent_adjustments"][0]
    assert a0["old_us"] == 20_000 and a0["new_us"] == first
    assert rep["target_ms"] == 10.0


def test_slo_control_law_bounded():
    c, b, h = _mk_controller(target_ms=10.0, wait_us=20_000)
    # sustained overshoot walks the window to the floor... and stops
    for _ in range(60):
        for _ in range(10):
            h.observe(0.050)
        c._control()
    assert b.max_wait_us == 0
    ticks_at_floor = int(c.c_adjust.value)
    for _ in range(10):
        h.observe(0.050)
    c._control()
    assert b.max_wait_us == 0 and int(c.c_adjust.value) == ticks_at_floor
    # sustained undershoot grows back (escaping 0 via the minimum step)
    # and caps at hi_us = max(static knob, 75% of the SLO)
    for _ in range(60):
        for _ in range(10):
            h.observe(0.001)
        c._control()
    assert b.max_wait_us == c.hi_us == 20_000


def test_slo_too_few_samples_no_adjustment():
    """A control window with fewer than min_samples observations never
    adjusts — one straggler must not yank the knob."""
    c, b, h = _mk_controller(target_ms=10.0, wait_us=20_000)
    for _ in range(c.min_samples - 1):
        h.observe(0.050)
    c._control()
    assert b.max_wait_us == 20_000 and int(c.c_adjust.value) == 0


def test_slo_static_path_untouched(ctx):
    """--sys.serve.slo_ms unset (default): no controller exists, no
    slo.* metric names, no `slo` executor stream, and the effective
    window IS the static knob before and after load — the pre-PR
    behavior bit-identically."""
    s = make_server(ctx)
    w = s.make_worker(0)
    _seed(w)
    with ServePlane(s) as plane:
        assert plane.slo is None
        assert plane.batcher.max_wait_us == s.opts.serve_max_wait_us
        sess = plane.session()
        for _ in range(5):
            sess.lookup(np.array([1, 2, 3]))
        assert plane.batcher.max_wait_us == s.opts.serve_max_wait_us
    assert not [n for n in s.obs.names() if n.startswith("slo.")]
    assert "slo" not in s.exec._streams
    assert s.metrics_snapshot()["slo"] == {}
    s.shutdown()


def test_slo_autopilot_end_to_end_shrinks(ctx):
    """Convergence smoke (the sized guard is
    scripts/slo_convergence_check.py): with a coalescing window 25x the
    SLO target, the controller must walk the window DOWN under load and
    the slo section must carry the adjustments."""
    opts = SystemOptions(sync_max_per_sec=0, serve_max_wait_us=50_000,
                         serve_slo_ms=2.0)
    s = make_server(ctx, opts=opts)
    w = s.make_worker(0)
    _seed(w)
    plane = ServePlane(s)
    assert plane.slo is not None
    # concurrent clients: each 50 ms micro-batch then carries several
    # requests, so a 100 ms control tick sees >= min_samples and the
    # law can act (a single serial client would starve the window)
    stop = threading.Event()
    errs: list = []

    def client():
        try:
            sess = plane.session()
            while not stop.is_set():
                sess.lookup(np.arange(8))
        except BaseException as e:  # noqa: BLE001
            errs.append(e)

    threads = [threading.Thread(target=client) for _ in range(4)]
    for t in threads:
        t.start()
    deadline = time.monotonic() + 30.0
    while time.monotonic() < deadline \
            and int(plane.slo.c_adjust.value) < 1:
        time.sleep(0.05)
    stop.set()
    for t in threads:
        t.join(timeout=60)
        assert not t.is_alive(), "serve client hung"
    assert not errs, errs[:3]
    assert int(plane.slo.c_adjust.value) >= 1, \
        "controller never adjusted the window"
    assert plane.batcher.max_wait_us < 50_000
    snap = s.metrics_snapshot()
    assert snap["slo"]["active"] is True
    assert snap["slo"]["target_ms"] == 2.0
    assert snap["slo"]["adjustments"] >= 1
    assert snap["slo"]["recent_adjustments"]
    assert snap["slo"]["wait_us"] == plane.batcher.max_wait_us
    assert snap["slo"]["ticks_total"] >= 1
    plane.close()
    # close() stops the reschedule: the tick counter settles
    s.exec.drain("slo", timeout=10)
    s.shutdown()


def test_slo_controller_survives_plane_rebuild(ctx):
    """A ServePlane closed and rebuilt within one tick interval gets a
    LIVE controller: the new instance's first tick must not coalesce
    into the predecessor's still-queued tick (which sees its own
    _closed flag and exits without rescheduling — the rebuilt
    autopilot would silently never run)."""
    opts = SystemOptions(sync_max_per_sec=0, serve_slo_ms=2.0)
    s = make_server(ctx, opts=opts)
    w = s.make_worker(0)
    _seed(w)
    p1 = ServePlane(s)
    assert p1.slo is not None
    p1.close()          # a queued delayed tick exists at close time
    p2 = ServePlane(s)  # rebuilt immediately, well inside 100 ms
    assert p2.slo is not None and p2.slo is not p1.slo
    deadline = time.monotonic() + 10.0
    while time.monotonic() < deadline and p2.slo._prev_snap is None:
        time.sleep(0.05)
    assert p2.slo._prev_snap is not None, \
        "rebuilt controller never ticked (coalesced into stale tick?)"
    p2.close()
    s.exec.drain("slo", timeout=10)
    s.shutdown()


def test_slo_requires_metrics():
    with pytest.raises(ValueError, match="requires --sys.metrics"):
        SystemOptions(serve_slo_ms=5.0, metrics=False).validate_serve()
    with pytest.raises(ValueError, match="slo_ms"):
        SystemOptions(serve_slo_ms=-1.0).validate_serve()


# ---------------------------------------------------------------------------
# satellites: hist_percentile edges + reporter line format
# ---------------------------------------------------------------------------


def test_hist_percentile_edges():
    from adapm_tpu.obs.metrics import Histogram, hist_percentile
    # empty histogram -> 0
    h = Histogram("t.h", bounds=(1.0, 10.0))
    assert hist_percentile(h.snap(), 0.99) == 0.0
    # overflow bucket: clamp to the last finite bound, never
    # interpolate past the ladder
    for v in (0.5, 5.0, 100.0, 200.0):
        h.observe(v)
    assert hist_percentile(h.snap(), 0.99) == 10.0
    assert hist_percentile(h.snap(), 0.75) == 10.0  # lands in overflow
    # in-bucket interpolation stays inside the containing bucket
    p50 = hist_percentile(h.snap(), 0.50)
    assert 1.0 <= p50 <= 10.0
    # every observation in the overflow bucket -> still the last bound
    h2 = Histogram("t.h2", bounds=(1.0, 10.0))
    for _ in range(5):
        h2.observe(50.0)
    assert hist_percentile(h2.snap(), 0.50) == 10.0
    # single-bucket ladder: interpolation within, clamp above
    h3 = Histogram("t.h3", bounds=(8.0,))
    for v in (2.0, 4.0, 6.0, 8.0):
        h3.observe(v)
    assert 0.0 < hist_percentile(h3.snap(), 0.50) <= 8.0
    h3.observe(100.0)
    assert hist_percentile(h3.snap(), 0.99) == 8.0


def test_reporter_line_format():
    """The one-line summary's format is STABLE (reporter module
    docstring): field order and formatting are pinned here so
    log-scraping tooling can rely on them."""
    from adapm_tpu.obs.reporter import _fmt
    assert _fmt({}) == "no activity yet"
    snap = {
        "kv": {"pull_s": {"count": 2, "avg": 1.05e-3}},
        "serve": {"lookups_total": 4,
                  "latency_s": {"count": 4, "bounds": [0.001],
                                "buckets": [4, 0]}},
        "exec": {"programs_total": 3, "overlap_fraction": 0.25},
        "tier": {"hot_hits": 9, "cold_hits": 1, "hot_hit_rate": 0.9},
        "flight": {"freshness_s": {"count": 2, "bounds": [0.002],
                                   "buckets": [2, 0]}},
        "decision": {"events_total": 10, "regret_rate.tier": 0.25,
                     "regret_rate.sync": 0.10},
    }
    assert _fmt(snap) == ("pull=2 avg=1.05ms "
                          "serve=4 p50=0.50ms p99=0.99ms "
                          "overlap=0.25 hot_hit=0.90 "
                          "fresh=1.98ms regret=0.25")
    # net part (ISSUE 19): msgs/bytes + live/total peers, appended last
    snap["net"] = {"msgs_out": 12, "bytes_out": 3456,
                   "peers_live": 2, "peers_total": 3}
    assert _fmt(snap).endswith(" net=12/3456 peers=2/3")
    # a subsystem with no activity contributes nothing (no empty fields)
    assert _fmt({"serve": {"latency_s": {"count": 0}},
                 "exec": {"programs_total": 0},
                 "tier": {"hot_hits": 0, "cold_hits": 0},
                 "flight": {"freshness_s": {"count": 0}},
                 "decision": {"events_total": 0,
                              "regret_rate.tier": 0.0},
                 "net": {"msgs_out": 0, "msgs_in": 0,
                         "peers_live": 1, "peers_total": 1}}) \
        == "no activity yet"


def test_clock_domains_recorded_everywhere(tmp_path):
    """ISSUE 15 clock-domain satellite: the flight-recorder ring and
    the SLO move log each stamp BOTH wall time and a monotonic clock —
    merged timelines (and replay alignment) must not skew when NTP
    steps the wall clock. The tail merge orders by the MONOTONIC
    stamp, which cannot step backwards."""
    rec = FlightRecorder(path=str(tmp_path / "r.log"))
    m0, w0 = time.monotonic(), time.time()
    rec.record("sync", "a", None, 0.0, 0.001)
    rec.record("serve", "b", None, 0.0, 0.001)
    m1, w1 = time.monotonic(), time.time()
    tail = rec.tail()
    assert len(tail) == 2
    for e in tail:
        # both domains present, each bracketed by its own clock
        assert m0 <= e["t_mono"] <= m1
        assert w0 <= e["t"] <= w1 + 1.0
    # merged tail is mono-ordered (wall could lie under an NTP step)
    assert tail[0]["t_mono"] <= tail[1]["t_mono"]
    rec.close()
    # SLO move log: drive one adjustment and check the report entries
    c, b, h = _mk_controller(target_ms=10.0, wait_us=20_000)
    m0 = time.monotonic()
    for _ in range(10):
        h.observe(0.050)    # far over target -> shrink
    c._control()
    m1 = time.monotonic()
    rep = c.report()
    assert rep["adjustments"] == 1
    first = rep["first_adjustment"]
    last = rep["recent_adjustments"][-1]
    for entry in (first, last):
        assert m0 <= entry["t_mono"] <= m1
        assert entry["t"] > 1e9  # epoch wall seconds, not monotonic
    assert first == last


def test_flight_recorder_unit(tmp_path):
    """FlightRecorder mechanics: bounded per-stream rings, mono-merged
    tail, fixed-slot ring file overwrites (no unbounded growth)."""
    path = str(tmp_path / "ring.log")
    rec = FlightRecorder(path=path, per_stream=2, file_slots=4)
    for i in range(6):
        rec.record("sync", f"prog{i}", None, 0.001, 0.002)
    rec.record("serve", "drain", "serve.drain", 0.0, 0.001, failed=True)
    tail = rec.tail()
    # per-stream bound: only the last 2 sync programs survive
    assert [e["label"] for e in tail if e["stream"] == "sync"] \
        == ["prog4", "prog5"]
    assert tail[-1]["stream"] == "serve" and tail[-1]["failed"]
    assert rec.summary()["programs_recorded"] == 7
    assert rec.summary()["per_stream"] == {"serve": 1, "sync": 6}
    rec.close()
    data = open(path, "rb").read()
    # fixed-size ring: file_slots fixed-width slots, never more
    assert len(data) <= 4 * 192
    assert b"FAILED" in data
