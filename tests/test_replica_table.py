"""PR 3 management-plane tests: the array-native ReplicaTable against a
shadow Python set (the structure it replaced), and the dirty-delta sync
filter proven bit-identical to full sync on a randomized push/intent/
round storm (ISSUE 3 acceptance: dirty-filtered rounds may skip ONLY
bit-for-bit no-op syncs)."""
import numpy as np
import pytest

from adapm_tpu import MgmtTechniques, Server, SystemOptions, make_mesh
from adapm_tpu.core.sync import ReplicaTable, key_channel

NK = 48
VL = 3


@pytest.fixture(scope="module")
def ctx():
    return make_mesh(4)


# ---------------------------------------------------------------------------
# ReplicaTable property tests (randomized, vs a shadow set)
# ---------------------------------------------------------------------------


def _pairs(keys, shards):
    return {(int(k), int(s)) for k, s in zip(keys, shards)}


def test_replica_table_matches_shadow_set(rng):
    S, K = 4, 200
    t = ReplicaTable(S, K)
    shadow = set()
    for step in range(400):
        n = int(rng.integers(1, 16))
        # duplicates on purpose: intra-batch duplicate pairs must count
        # once, and re-adding present pairs must count zero
        keys = rng.integers(0, K, size=n)
        shards = rng.integers(0, S, size=n)
        op = rng.random()
        if op < 0.5:
            added = t.add(keys, shards)
            fresh = _pairs(keys, shards) - shadow
            assert added == len(fresh)
            shadow |= fresh
        elif op < 0.85:
            removed = t.remove(keys, shards)
            gone = _pairs(keys, shards) & shadow
            assert removed == len(gone)
            shadow -= gone
        else:
            got = t.contains(keys, shards)
            want = [(int(k), int(s)) in shadow
                    for k, s in zip(keys, shards)]
            assert got.tolist() == want
        assert len(t) == len(shadow)
        if step % 37 == 0:
            k, s = t.snapshot()
            assert len(k) == len(shadow)
            assert _pairs(k, s) == shadow
    k, s = t.snapshot()
    assert _pairs(k, s) == shadow


def test_replica_table_scalar_shard_and_growth():
    t = ReplicaTable(2, 5000)
    keys = np.arange(4000, dtype=np.int64)  # forces column growth
    assert t.add(keys, 1) == 4000
    assert t.contains(keys, 1).all()
    assert not t.contains(keys, 0).any()
    assert t.remove(keys[::2], 1) == 2000
    assert len(t) == 2000
    # free-list reuse keeps the row watermark from growing again
    top = t._top
    assert t.add(keys[::2], 0) == 2000
    assert t._top == top
    k, s = t.snapshot()
    assert len(k) == 4000 and (np.sort(k[s == 0]) == keys[::2]).all()


def test_replica_tables_shared_lookup_interleaved_channels(rng):
    """Channel tables share one row-lookup; interleaved add/remove across
    channels (keys routed by the Knuth hash, like the SyncManager) never
    cross-corrupt, including duplicate keys on different shards."""
    S, K, C = 4, 256, 4
    row = np.full((S, K), -1, dtype=np.int32)
    tables = [ReplicaTable(S, K, row_lookup=row) for _ in range(C)]
    shadows = [set() for _ in range(C)]
    for _ in range(300):
        n = int(rng.integers(1, 24))
        keys = rng.integers(0, K, size=n).astype(np.int64)
        shards = rng.integers(0, S, size=n)
        ch = key_channel(keys, C)
        add = rng.random() < 0.6
        for c in np.unique(ch):
            m = ch == c
            if add:
                shadows[c] |= _pairs(keys[m], shards[m])
                tables[c].add(keys[m], shards[m])
            else:
                shadows[c] -= _pairs(keys[m], shards[m])
                tables[c].remove(keys[m], shards[m])
    for c in range(C):
        k, s = tables[c].snapshot()
        assert _pairs(k, s) == shadows[c], f"channel {c} diverged"


# ---------------------------------------------------------------------------
# dirty-delta sync: bit-identical to full sync
# ---------------------------------------------------------------------------


def _storm(ctx, dirty_only: bool):
    """Deterministic push/intent/round storm; returns every intermediate
    read, the post-quiesce state, and the ship/consider counters."""
    opts = SystemOptions(sync_max_per_sec=0, prefetch=False,
                         sync_dirty_only=dirty_only)
    s = Server(NK, VL, opts=opts, ctx=ctx, num_workers=4)
    ws = [s.make_worker(i) for i in range(4)]
    rng = np.random.default_rng(11)
    base = rng.normal(size=(NK, VL)).astype(np.float32)
    ws[0].wait(ws[0].set(np.arange(NK), base))
    expected = base.copy()
    reads = []
    for it in range(40):
        w = ws[int(rng.integers(4))]
        k = np.unique(rng.choice(NK, size=6, replace=False))
        if rng.random() < 0.6:
            w.intent(k, w.current_clock, w.current_clock + 3)
        d = rng.normal(size=(len(k), VL)).astype(np.float32)
        w.push(k, d)
        expected[k] += d
        if rng.random() < 0.5:
            s.sync.run_round(all_channels=(it % 3 == 0))
        if rng.random() < 0.4:
            w.advance_clock()
        reads.append(w.pull_sync(np.arange(NK)).copy())
    for w in ws:
        w.wait_all()
    s.quiesce()
    final = np.stack([w.pull_sync(np.arange(NK)) for w in ws])
    mains = s.read_main(np.arange(NK)).reshape(NK, VL).copy()
    stats = (s.sync.stats.keys_synced, s.sync.stats.keys_considered)
    s.shutdown()
    return reads, final, mains, stats, expected


def test_dirty_filtered_sync_bit_identical_to_full(ctx):
    """The acceptance test: a dirty-filtered run reads bit-identically to
    a full-sync run at EVERY intermediate pull and after quiesce — the
    filter may only skip syncs that would not change a single bit — and
    it must actually filter (ship fewer keys than it considers)."""
    reads_f, final_f, mains_f, (ship_f, cons_f), expected = \
        _storm(ctx, dirty_only=False)
    reads_d, final_d, mains_d, (ship_d, cons_d), _ = \
        _storm(ctx, dirty_only=True)
    for i, (a, b) in enumerate(zip(reads_f, reads_d)):
        assert np.array_equal(a, b), f"read {i} diverged under the filter"
    assert np.array_equal(final_f, final_d)
    assert np.array_equal(mains_f, mains_d)
    # eventual consistency: every worker sees the exact converged state
    assert np.array_equal(final_d[0], final_d[1])
    np.testing.assert_allclose(mains_d, expected, atol=1e-4)
    # full sync ships everything it considers; the filter ships less on
    # the same (deterministic) workload
    assert ship_f == cons_f
    assert cons_d == cons_f
    assert ship_d < ship_f, (ship_d, ship_f)


def test_dirty_filter_skips_clean_rounds(ctx):
    """A replica with no writes since its refresh is not re-shipped:
    rounds over an idle replicated table ship zero keys (the planner
    rounds/sec headline depends on this) — until a write re-dirties."""
    opts = SystemOptions(techniques=MgmtTechniques.REPLICATION_ONLY,
                         sync_max_per_sec=0, prefetch=False,
                         cache_slots_per_shard=NK)
    s = Server(NK, VL, opts=opts, ctx=ctx, num_workers=2)
    w0, w1 = s.make_worker(0), s.make_worker(1)
    w0.wait(w0.set(np.arange(NK), np.ones((NK, VL), np.float32)))
    remote = np.arange(NK)[s.ab.owner[: NK] != w1.shard]
    w1.intent(remote, 0, 10_000)
    s.wait_sync()  # creates the replicas and flushes the first syncs
    assert (s.ab.cache_slot[w1.shard, remote] >= 0).all()
    before = s.sync.stats.keys_synced
    for _ in range(8):
        s.sync.run_round(all_channels=True)
    assert s.sync.stats.keys_synced == before, \
        "idle replicas were re-shipped"
    assert s.sync.stats.keys_considered > 0
    # a write re-dirties exactly its replica, and the value round-trips
    w1.push(remote[:4], np.full((4, VL), 2.0, np.float32))
    s.sync.run_round(all_channels=True)
    assert s.sync.stats.keys_synced == before + 4
    assert np.allclose(s.read_main(remote[:4]).reshape(4, VL), 3.0)
    s.shutdown()
