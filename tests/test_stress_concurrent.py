"""True-concurrency stress: N Python threads hammer ONE contended key with
additive pushes under random intent while the background sync thread
relocates/replicates underneath them — the port of the reference's
tests/test_dynamic_allocation.cc:84-103 (all workers async-push {1,2} to a
single key under random intent; final value must be exactly
workers * runs * {1,2}).

This is the only test that exercises Server.start_sync_thread() (the
background planner) against concurrent API callers; everything else drives
sync rounds on the caller's thread.
"""
import threading

import numpy as np

import adapm_tpu
from adapm_tpu.config import SystemOptions

KEY = 9
RUNS = 200
N_WORKERS = 4


def _run_worker(w, errors):
    rng = np.random.default_rng(1000 + w.worker_id)
    push_val = np.array([[1.0, 2.0]], np.float32)
    keys = np.array([KEY])
    last = -np.inf
    try:
        for run in range(RUNS):
            if rng.integers(0, 50) == 0:  # from time to time, send intent
                w.intent(keys, w.current_clock + 10, w.current_clock + 40)
            w.push(keys, push_val)
            got = w.pull_sync(keys)
            # additive-merge invariant: concurrent pushes are never lost,
            # so the observed total only grows
            if got[0, 0] < last - 1e-4:
                errors.append(
                    f"worker {w.worker_id}: value regressed "
                    f"{last} -> {got[0, 0]} at run {run}")
                return
            last = float(got[0, 0])
            w.advance_clock()
        w.wait_all()
    except Exception as e:  # noqa: BLE001 - surface to the main thread
        errors.append(f"worker {w.worker_id}: {type(e).__name__}: {e}")


def test_many_thread_exact_sum_stress():
    """Higher-op-count many-thread exactness (VERDICT r3 weak 3): 8 app
    threads x 400 async pushes across a CONTENDED key set (every thread
    hits every key) under intent churn and the background planner; after
    quiesce each key's main copy equals the exact global sum and no
    thread ever observed its own applied pushes regress."""
    K = 12
    runs = 400
    n_threads = 8
    srv = adapm_tpu.setup(64, 2, opts=SystemOptions(
        cache_slots_per_shard=16, sync_max_per_sec=4000.0,
        sync_report_s=0))
    workers = [srv.make_worker(i) for i in range(n_threads)]
    srv.start_sync_thread()
    errors: list = []
    keys = np.arange(K, dtype=np.int64)

    def hammer(w):
        rng = np.random.default_rng(7_000 + w.worker_id)
        try:
            for run in range(runs):
                k = keys[rng.integers(0, K)]
                if rng.integers(0, 40) == 0:
                    w.intent(keys, w.current_clock + 5,
                             w.current_clock + 30)
                w.push(np.array([k]), np.ones((1, 2), np.float32))
                if run % 16 == 0:
                    w.wait_all()  # bound outstanding async pushes
                w.advance_clock()
            w.wait_all()
        except Exception as e:  # noqa: BLE001 - surface to main thread
            errors.append(f"worker {w.worker_id}: {type(e).__name__}: {e}")

    threads = [threading.Thread(target=hammer, args=(w,))
               for w in workers]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=600)
        assert not t.is_alive(), "worker thread hung"
    assert not errors, errors

    srv.wait_sync()
    srv.barrier()
    srv.wait_sync()
    srv.stop_sync_thread()
    srv.quiesce()
    got = srv.read_main(keys).reshape(K, 2)
    # every push targeted a uniform key; exact total = threads * runs
    assert np.isclose(got.sum(), n_threads * runs * 2.0), \
        (got.sum(), n_threads * runs * 2.0)
    st = srv.sync.stats
    assert st.rounds > 0 and st.intents_processed > 0
    srv.shutdown()


def test_dynamic_allocation_stress():
    srv = adapm_tpu.setup(36, 2, opts=SystemOptions(
        cache_slots_per_shard=8, sync_max_per_sec=2000.0,
        sync_report_s=0))
    workers = [srv.make_worker(i) for i in range(N_WORKERS)]
    srv.start_sync_thread()
    errors: list = []
    threads = [threading.Thread(target=_run_worker, args=(w, errors))
               for w in workers]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=600)
        assert not t.is_alive(), "worker thread hung"
    assert not errors, errors

    # quiesce exactly like the reference: WaitSync -> Barrier -> WaitSync
    srv.wait_sync()
    srv.barrier()
    srv.wait_sync()
    srv.stop_sync_thread()
    srv.quiesce()

    got = srv.read_main(np.array([KEY]))
    correct = N_WORKERS * RUNS * np.array([1.0, 2.0])
    assert np.allclose(got, correct), f"got {got}, want {correct}"
    # the planner actually acted under fire (otherwise this test proves
    # nothing about concurrency with placement changes)
    st = srv.sync.stats
    assert st.rounds > 0
    assert st.intents_processed > 0
    srv.shutdown()
