"""NetPort transport plane tests (ISSUE 19; adapm_tpu/net).

Four layers, mirroring docs/NETWORK.md:
  - frame codec: round trips + the corruption quartet (truncated /
    flipped byte / wrong version / spliced), each raising its NAMED
    error BEFORE any handler/server mutation;
  - port semantics: request/reply demux, error-tuple propagation
    (DcnChannel parity), at-most-once execution under duplicate
    delivery, dead-peer fast-fail;
  - TCP backend: a real socket pair in-process through DictRendezvous;
  - loopback cluster: the mp matrix in-container — cross-node
    pull/push/set, intent relocation/replication, eventual consistency,
    the seeded fault storm bit-identical to a NumPy shadow, and the
    dead-peer kill -> replica-promotion failover drill.
"""
import threading
import time

import numpy as np
import pytest

import adapm_tpu
from adapm_tpu.config import SystemOptions
from adapm_tpu.net import (FAMILY_CTRL, FAMILY_RELOC, FAMILY_SERVE,
                           FAMILY_SYNC, FrameChecksumError,
                           FrameFamilyError, FrameSpliceError,
                           FrameTruncatedError, FrameVersionError,
                           LoopbackCluster, NetPeerDeadError,
                           NetTimeoutError, WIRE_VERSION)
from adapm_tpu.net.port import (HEADER_SIZE, NetPort, decode_frame,
                                encode_frame, family_for_msg)


# ---------------------------------------------------------------------------
# codec
# ---------------------------------------------------------------------------


def test_frame_round_trip_all_families():
    payloads = [
        (FAMILY_SYNC, ("sync", np.arange(8), b"compressed-bytes")),
        (FAMILY_RELOC, ("intent", np.arange(4, dtype=np.int64), 7, 1)),
        (FAMILY_SERVE, ("pull", np.array([1, 2, 3]))),
        (FAMILY_CTRL, ("beat", 0)),
    ]
    for fam, obj in payloads:
        buf = encode_frame(fam, rid=42, src=3, obj=obj)
        f2, flags, rid, src, obj2 = decode_frame(buf)
        assert (f2, flags, rid, src) == (fam, 0, 42, 3)
        assert obj2[0] == obj[0]
        np.testing.assert_array_equal(np.asarray(obj2[1]),
                                      np.asarray(obj[1]))


def test_family_for_msg_op_map():
    assert family_for_msg(("sync", 1)) == FAMILY_SYNC
    assert family_for_msg(("unsub", 1)) == FAMILY_SYNC
    assert family_for_msg(("intent", 1)) == FAMILY_RELOC
    assert family_for_msg(("pull", 1)) == FAMILY_SERVE
    assert family_for_msg(("beat", 1)) == FAMILY_CTRL
    assert family_for_msg(("unknown-op", 1)) == FAMILY_SERVE
    assert family_for_msg("not-a-tuple") == FAMILY_SERVE


def test_corruption_quartet_named_errors():
    """Truncated / flipped byte / wrong version / spliced each raise
    their NAMED decode error (the r15/r18 integrity discipline)."""
    buf = encode_frame(FAMILY_SERVE, rid=7, src=0,
                       obj=("pull", np.arange(16)))
    # 1. truncated: short header AND short payload both named
    with pytest.raises(FrameTruncatedError):
        decode_frame(buf[: HEADER_SIZE - 4])
    with pytest.raises(FrameTruncatedError):
        decode_frame(buf[:-3])
    # 2. flipped payload byte -> checksum
    flipped = bytearray(buf)
    flipped[HEADER_SIZE + 5] ^= 0xFF
    with pytest.raises(FrameChecksumError):
        decode_frame(bytes(flipped))
    # 3. wrong wire version
    vbuf = bytearray(buf)
    vbuf[4:6] = (WIRE_VERSION + 1).to_bytes(2, "big")
    with pytest.raises(FrameVersionError):
        decode_frame(bytes(vbuf))
    # 4. spliced/misaligned stream -> bad magic
    with pytest.raises(FrameSpliceError):
        decode_frame(b"XXXX" + buf[4:])
    # bonus: unknown family byte
    fbuf = bytearray(buf)
    fbuf[6] = 99
    with pytest.raises(FrameFamilyError):
        decode_frame(bytes(fbuf))


# ---------------------------------------------------------------------------
# port semantics (in-memory pair: _send_bytes wired directly)
# ---------------------------------------------------------------------------


class _PairPort(NetPort):
    """Minimal transport: frames go straight to the peer's _on_frame
    on the sender's thread (or are captured for replay tests)."""

    def __init__(self, pid, handler):
        super().__init__(pid, 2, handler)
        self.peer_port = None
        self.sent = []  # captured (dest, buf) for duplicate-replay

    def _send_bytes(self, dest, buf):
        self.sent.append((dest, buf))
        self.peer_port._on_frame(buf)


def _make_pair(handler_b):
    a = _PairPort(0, lambda msg: ("ok-from-a", msg))
    b = _PairPort(1, handler_b)
    a.peer_port, b.peer_port = b, a
    return a, b


def test_request_reply_and_error_tuple():
    a, b = _make_pair(lambda msg: ("served", msg[0]))
    assert a.request(1, ("pull", 1), timeout_s=5.0) == ("served", "pull")

    def boom(msg):
        raise KeyError("nope")
    a2, b2 = _make_pair(boom)
    with pytest.raises(RuntimeError, match="peer 1: KeyError"):
        a2.request(1, ("pull", 1), timeout_s=5.0)


def test_at_most_once_duplicate_suppressed():
    """A duplicated request frame must NOT re-execute the handler
    (pushes are additive): the cached reply is re-sent instead."""
    calls = []

    def handler(msg):
        calls.append(msg)
        return ("applied", len(calls))

    a, b = _make_pair(handler)
    assert a.request(1, ("push", 5), timeout_s=5.0) == ("applied", 1)
    # replay the exact request frame (retransmit / net.dup delivery)
    req = next(buf for d, buf in a.sent if d == 1)
    b._on_frame(req)
    assert len(calls) == 1, "duplicate delivery re-executed the handler"
    assert b.stats["dup_suppressed"] == 1


def test_decode_error_counted_never_reaches_handler():
    calls = []
    a, b = _make_pair(lambda msg: calls.append(msg) or "ok")
    buf = encode_frame(FAMILY_SERVE, rid=1, src=0, obj=("push", 1))
    bad = bytearray(buf)
    bad[HEADER_SIZE] ^= 0xFF
    with pytest.raises(FrameChecksumError):
        b._on_frame(bytes(bad))
    assert calls == [] and b.stats["decode_errors"] == 1


def test_timeout_and_dead_peer_fastfail():
    class _BlackHole(NetPort):
        def _send_bytes(self, dest, buf):
            pass  # the wire eats everything

    p = _BlackHole(0, 2, lambda m: m)
    t0 = time.monotonic()
    with pytest.raises(NetTimeoutError):
        p.request(1, ("pull", 1), timeout_s=0.05, retries=2)
    assert time.monotonic() - t0 < 5.0
    assert p.stats["retransmits"] == 2

    # fail_pending_to releases only the named peer's waiters
    p2 = _BlackHole(0, 3, lambda m: m)
    errs = {}

    def waiter(peer):
        try:
            p2.request(peer, ("pull", 1), timeout_s=30.0)
        except Exception as e:  # noqa: BLE001 — recorded for asserts
            errs[peer] = e

    ts = [threading.Thread(target=waiter, args=(pr,)) for pr in (1, 2)]
    for t in ts:
        t.start()
    time.sleep(0.1)
    p2.fail_pending_to(1, NetPeerDeadError("peer 1 gone"))
    ts[0].join(5.0)
    assert isinstance(errs.get(1), NetPeerDeadError)
    assert 2 not in errs, "peer 2's pending request was wrongly failed"
    p2.fail_pending_to(2, NetPeerDeadError("peer 2 gone"))
    ts[1].join(5.0)
    assert isinstance(errs.get(2), NetPeerDeadError)


# ---------------------------------------------------------------------------
# TCP backend (real sockets, in-process rendezvous)
# ---------------------------------------------------------------------------


def test_tcp_port_pair_round_trip():
    from adapm_tpu.net.socket import DictRendezvous, TcpNetPort
    rv = DictRendezvous()
    a = TcpNetPort(0, 2, lambda m: ("a-serves", m[0]), rendezvous=rv,
                   timeout_s=10.0)
    b = TcpNetPort(1, 2, lambda m: ("b-serves", m[0]), rendezvous=rv,
                   timeout_s=10.0)
    a.start()
    b.start()
    try:
        assert a.request(1, ("pull", np.arange(4))) == \
            ("b-serves", "pull")
        assert b.request(0, ("push", 1)) == ("a-serves", "push")
        # big numpy payload survives framing
        big = np.random.default_rng(0).random((256, 32)).astype(
            np.float32)
        reply = a.request(1, ("set", big))
        assert reply == ("b-serves", "set")
        assert a.stats["msgs_out"] >= 2 and b.stats["replies_out"] >= 2
    finally:
        a.shutdown()
        b.shutdown()


# ---------------------------------------------------------------------------
# loopback cluster: the mp matrix in-container
# ---------------------------------------------------------------------------


def _opts(**kw):
    return SystemOptions(sync_max_per_sec=0, prefetch=False, **kw)


def _cluster(world=2, num_keys=64, L=4, fault_spec="", **kw):
    def factory(rank):
        return _opts(fault_spec=fault_spec)
    return LoopbackCluster(world, num_keys=num_keys, value_lengths=L,
                           opts_factory=factory, **kw)


def test_loopback_cluster_pull_push_set():
    """scenario_pullpush rerouted through the loopback backend: the 7-
    seed mp matrix's core value checks run fully in-container."""
    cl = _cluster()
    try:
        base = np.tile(np.arange(64, dtype=np.float32)[:, None], (1, 4))

        def scenario(rank, srv):
            w = srv.make_worker(0)
            keys = np.arange(64, dtype=np.int64)
            if rank == 0:
                w.wait(w.set(keys, base))
            srv.barrier()
            v = w.pull_sync(keys)
            assert np.array_equal(v, base), "pull after set"
            w.wait(w.push(keys, np.ones((64, 4), np.float32)))
            srv.barrier()
            return w.pull_sync(keys)

        outs = cl.run(scenario)
        for rank, v in enumerate(outs):
            assert np.array_equal(v, base + 2.0), f"rank {rank}"
        s = cl.servers[0].net.stats()
        assert s["msgs_serve"] > 0 and s["decode_errors"] == 0
        assert s["peers_live"] == 2
    finally:
        cl.shutdown()


def test_loopback_intent_relocation_and_eventual_consistency():
    """Intent moves/replicates keys across loopback nodes; push+revert
    restores the exact base after the quiesce protocol."""
    from adapm_tpu.base import CLOCK_MAX
    cl = _cluster()
    try:
        base = np.tile(np.arange(64, dtype=np.float32)[:, None], (1, 4))

        def scenario(rank, srv):
            w = srv.make_worker(0)
            keys = np.arange(64, dtype=np.int64)
            if rank == 0:
                w.wait(w.set(keys, base))
            srv.barrier()
            if rank == 1:
                w.intent(keys, 0, CLOCK_MAX)
                srv.wait_sync()
                moved = (srv.ab.owner[keys] >= 0) | \
                    (srv.ab.cache_slot[:, keys] >= 0).any(axis=0)
                assert moved.any(), "intent moved/replicated nothing"
            srv.barrier()
            x = np.full((64, 4), 3.0, np.float32)
            w.wait(w.push(keys, x))
            w.wait(w.push(keys, -x))
            # quiesce: WaitSync -> Barrier -> WaitSync
            srv.wait_sync()
            srv.barrier()
            srv.wait_sync()
            srv.barrier()
            return w.pull_sync(keys)

        outs = cl.run(scenario)
        for rank, v in enumerate(outs):
            assert np.array_equal(v, base), \
                f"rank {rank} not restored to base"
    finally:
        cl.shutdown()


def test_loopback_storm_bit_identical_under_faults():
    """Seeded integer-push storm under injected drop/dup/delay: every
    post-quiesce read bit-identical to a NumPy shadow. Exercises the
    retransmit + at-most-once machinery for real (dropped frames MUST
    be retransmitted, duplicated frames MUST NOT double-apply)."""
    K, L, ROUNDS = 48, 4, 6
    cl = _cluster(
        num_keys=K, L=L,
        fault_spec="net.send=0.08,net.recv=0.08,net.dup=0.1,"
                   "net.delay=0.02,net.partition=0.02")
    try:
        shadow = np.zeros((K, L), np.float64)
        # integer-valued pushes: fp addition on the integer grid is
        # exact and order-independent, so shadow == device bitwise
        per_rank = []
        for rank in range(2):
            rng = np.random.default_rng(1000 + rank)
            rounds = []
            for r in range(ROUNDS):
                keys = np.sort(rng.choice(K, size=8, replace=False))
                vals = rng.integers(-8, 9, size=(8, L)).astype(
                    np.float32)
                rounds.append((keys.astype(np.int64), vals))
                shadow[keys] += vals
            per_rank.append(rounds)

        def scenario(rank, srv):
            w = srv.make_worker(0)
            allk = np.arange(K, dtype=np.int64)
            if rank == 0:
                w.wait(w.set(allk, np.zeros((K, L), np.float32)))
            srv.barrier()
            for r in range(ROUNDS):
                keys, vals = per_rank[rank][r]
                w.wait(w.push(keys, vals))
                srv.wait_sync()
                srv.barrier()
                srv.wait_sync()
                srv.barrier()
            return w.pull_sync(allk)

        outs = cl.run(scenario)
        expect = shadow.astype(np.float32)
        for rank, v in enumerate(outs):
            np.testing.assert_array_equal(
                v, expect, err_msg=f"rank {rank} diverged from shadow")
        s = cl.servers[0].net.stats()
        # the storm must actually have exercised the machinery
        fired = sum(cl.servers[i].fault.counts(p)[1]
                    for i in range(2)
                    for p in ("net.send", "net.recv", "net.dup"))
        assert fired > 0, "no wire faults fired — storm proved nothing"
        assert s["decode_errors"] == 0
    finally:
        cl.shutdown()


def test_loopback_dead_peer_failover_promotes_replicas():
    """Kill one node: the survivor's membership plane detects the death
    by beat staleness, promotes its replicas of dead-owned keys to
    mains via GlobalPM.failover_dead_peer, serves them correctly, and
    records a bounded failover_s; dead-owned keys WITHOUT a replica
    are counted lost and fail fast."""
    from adapm_tpu.base import CLOCK_MAX
    cl = _cluster(heartbeat_ms=40.0)
    try:
        base = np.tile(np.arange(64, dtype=np.float32)[:, None], (1, 4))

        def prep(rank, srv):
            w = srv.make_worker(0)
            keys = np.arange(64, dtype=np.int64)
            if rank == 0:
                w.wait(w.set(keys, base))
            srv.barrier()
            # COMPETING intents replicate (an uncontended exclusive
            # intent would relocate instead): rank 1 claims its own
            # homed keys first, then rank 0 claims the same keys —
            # rank 1 keeps ownership, rank 0 gets replica rows
            theirs = keys[srv.glob.home_proc(keys) == 1]
            if rank == 1:
                w.intent(theirs, 0, CLOCK_MAX)
                srv.wait_sync()
            srv.barrier()
            if rank == 0:
                w.intent(theirs, 0, CLOCK_MAX)
                srv.wait_sync()
            srv.barrier()

        cl.run(prep)
        srv0 = cl.servers[0]
        keys = np.arange(64, dtype=np.int64)
        theirs = keys[srv0.glob.home_proc(keys) == 1]
        covered = theirs[
            (srv0.ab.cache_slot[:, theirs] >= 0).any(axis=0)
            & (srv0.ab.owner[theirs] < 0)]
        assert len(covered) > 0, "prep installed no replicas"

        cl.kill(1)
        deadline = time.monotonic() + 15.0
        while time.monotonic() < deadline and \
                srv0.net.stats()["failovers"] == 0:
            time.sleep(0.02)
        s = srv0.net.stats()
        assert s["failovers"] == 1, "death never detected/failed-over"
        assert s["peers_dead"] == 1 and s["peers_live"] == 1
        assert s["promoted_keys"] > 0
        assert 0.0 < s["failover_s"] < 10.0
        assert srv0.dead_nodes() == [1]

        # the survivor serves every covered (non-lost) key correctly
        w = srv0.make_worker(0)
        v = w.pull_sync(covered)
        assert np.array_equal(v, base[covered])
        # readiness reflects the failover action, not bare detection
        rep = srv0.net.stats()
        assert rep["lost_keys"] + rep["promoted_keys"] >= len(theirs)
        cl.shutdown(ranks=[0])
    finally:
        pass


def test_loopback_net_section_and_metrics_names():
    """The snapshot `net` section (schema v15) and net.* registry
    names exist on loopback servers — and a single-process server has
    NEITHER (plane default-off, r7 discipline)."""
    cl = _cluster()
    try:
        srv = cl.servers[0]
        assert srv.net is not None
        snap = srv.metrics_snapshot(drain_device=False)
        assert snap["schema_version"] == 16
        net = snap["net"]
        assert net["peers_total"] == 2 and net["backend"] == "loopback"
        for k in ("msgs_out", "bytes_out", "retransmits",
                  "dup_suppressed", "decode_errors", "failovers",
                  "failover_s", "lost_keys"):
            assert k in net, f"net section missing {k}"
        names = [m for m in srv.obs.names() if m.startswith("net.")]
        assert "net.msgs_out" in names and "net.peers_live" in names
    finally:
        cl.shutdown()


def test_single_process_server_has_no_net_plane():
    srv = adapm_tpu.setup(32, 4, opts=_opts(), num_workers=2)
    try:
        assert srv.net is None
        snap = srv.metrics_snapshot(drain_device=False)
        assert snap["net"] == {}
        assert not [m for m in srv.obs.names()
                    if m.startswith("net.")]
    finally:
        srv.shutdown()


def test_collective_sync_rejected_on_loopback():
    with pytest.raises(ValueError, match="collective_sync"):
        LoopbackCluster(
            2, num_keys=32, value_lengths=4,
            opts_factory=lambda r: _opts(collective_sync=True))
