"""Multi-process parameter-manager integration tests.

The reference's core test strategy is N real server processes + a scheduler
on localhost (tracker/dmlc_local.py, SURVEY.md §4); here N real Python
processes rendezvous through the jax.distributed coordinator and exchange
parameter traffic over the DCN channel (parallel/pm.py). Scenarios live in
tests/mp_scenarios.py — the multi-process twins of
test_many_key_operations.cc / test_locality_api.cc phases.
"""
import os
import subprocess
import sys

import pytest

from adapm_tpu import launcher

HERE = os.path.dirname(os.path.abspath(__file__))
SCENARIOS = os.path.join(HERE, "mp_scenarios.py")
REPO = os.path.dirname(HERE)


def run_mp(n, scenario, devices=2, args=(), timeout=300):
    """Launch `n` ranks of a scenario; assert all exit 0."""
    env = dict(os.environ)
    # children need the repo importable but NOT the TPU-tunnel site
    env["PYTHONPATH"] = REPO
    env["JAX_PLATFORMS"] = "cpu"
    env["ADAPM_PLATFORM"] = "cpu"
    from xla_compat import mesh_flags
    env["XLA_FLAGS"] = mesh_flags(devices)
    # a hung scenario dumps its thread stacks + exits before our timeout
    env["ADAPM_FAULT_T"] = str(max(timeout - 20, 30))
    # oversubscribed CI host: a rank's coordination heartbeat can stall
    # past jax's 100 s default during concurrent XLA compiles and get
    # declared dead (PollForError flake); raise it for tests only
    env.setdefault("ADAPM_COORD_HEARTBEAT_S", "300")
    coordinator = f"localhost:{launcher.free_port()}"
    procs = [subprocess.Popen(
        [sys.executable, SCENARIOS, scenario, *map(str, args)],
        env=launcher.make_env(r, n, coordinator, env),
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
        for r in range(n)]
    outs = []
    try:
        outs = [p.communicate(timeout=timeout)[0].decode() for p in procs]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for r, (p, o) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {r} failed:\n{o[-4000:]}"
        assert f"MP-OK {scenario} rank={r}" in o
    return outs


# ---------------------------------------------------------------------------
# Collective-sync gating (ISSUE 19 satellite). The BSP collective data
# plane rides jaxlib's cross-process CPU collectives, which this image's
# jaxlib lacks (client init aborts on the watchdog flags — the r6 seed
# note in CHANGES.md; these were the 7 seed failures). The tests stay,
# gated on an explicit opt-in for images that have them; the SAME
# consistency/staleness invariants run in-container through the NetPort
# loopback backend (tests/test_netport.py and the reroute test below —
# docs/NETWORK.md).
# ---------------------------------------------------------------------------

requires_cpu_collectives = pytest.mark.skipif(
    os.environ.get("ADAPM_MP_COLLECTIVES", "") != "1",
    reason="needs jaxlib cross-process CPU collectives, absent from this "
           "image (set ADAPM_MP_COLLECTIVES=1 where available); the "
           "NetPort loopback reroute covers the same invariants "
           "in-container (tests/test_netport.py, docs/NETWORK.md)")


@pytest.mark.slow
@pytest.mark.parametrize("n,devices", [(2, 2), (4, 1)])
def test_mp_pull_push_set(n, devices):
    """Cross-process Pull/Push/Set land exactly (2 procs x 2 shards and
    4 procs x 1 shard — the reference tests run 3-4 nodes)."""
    run_mp(n, "pullpush", devices=devices)


@pytest.mark.slow
def test_mp_intent_relocation_replication():
    """Rank 1's intent moves rank-0-owned keys; a competing intent
    replicates them back; pushes converge after quiesce."""
    run_mp(2, "intent_locality")


@pytest.mark.slow
def test_mp_monotonic_contended_pushes():
    """Own pushes never lost under churn; final value exact (3 procs)."""
    run_mp(3, "monotonic")


@pytest.mark.slow
@pytest.mark.parametrize("tech", ["all", "replication_only",
                                  "relocation_only"])
def test_mp_eventual_consistency(tech):
    """Push+revert restores the exact base on every rank after
    WaitSync -> Barrier -> WaitSync (2 procs), under every management
    technique (reference run_tests.sh --sys.techniques variants)."""
    run_mp(2, "eventual", args=(tech,))


@pytest.mark.slow
@requires_cpu_collectives
@pytest.mark.parametrize("tech", ["all", "replication_only",
                                  "relocation_only"])
def test_mp_eventual_consistency_collective(tech):
    """The same invariant with the BSP COLLECTIVE sync data plane
    (--sys.collective_sync, parallel/collective.py — VERDICT r3 item 1):
    replica deltas and fresh values ride device all-to-all exchanges at
    the WaitSync points instead of DCN RPC; bucket 16 forces several
    padded exchange iterations."""
    run_mp(2, "eventual", args=(tech, "coll"), timeout=420)


@pytest.mark.slow
@requires_cpu_collectives
def test_mp_collective_cadence_staleness_bound():
    """--sys.collective_cadence K: a replica observes a remote push
    within ~K clock advances with NO WaitSync anywhere in between — the
    bounded-staleness contract of collective mode (VERDICT r4 item 3;
    reference: the continuously-running sync loop,
    sync_manager.h:452-520)."""
    run_mp(2, "cadence", timeout=420)


@pytest.mark.slow
@requires_cpu_collectives
@pytest.mark.parametrize("n", [2, 3])
def test_mp_collective_pull_push(n):
    """Pull/Push values ride the device-collective exchange instead of
    DCN RPC, exactly (VERDICT r4 item 4 — the SURVEY ICI mapping's
    remaining half, prototyped)."""
    run_mp(n, "coll_pullpush", devices=1 if n == 3 else 2, timeout=420)


@pytest.mark.slow
def test_mp_kge_eval_chunk_matches_dense():
    """Candidate-partitioned chunked pool eval across 2 processes equals
    the dense-matrix path on the same triples (VERDICT r4 item 5)."""
    run_mp(2, "kge_eval_chunk", timeout=420)


@pytest.mark.slow
@requires_cpu_collectives
def test_mp_eventual_collective_three_procs():
    """Collective sync with P=3: routing by owner, per-destination
    buckets, and the global-backlog loop all span more than one peer."""
    run_mp(3, "eventual", args=("all", "coll"), devices=1, timeout=420)


@pytest.mark.slow
def test_mp_location_caches_on():
    """Second pull of a relocated key takes one hop (3 procs x 1 device)."""
    run_mp(3, "location_caches", devices=1, args=(1,))


@pytest.mark.slow
def test_mp_checkpoint_crash_recovery(tmp_path):
    """Distributed checkpoint + whole-job restart: per-rank shards restore
    values, adapted placement (cross-process relocations/replicas), and
    the consistency invariant in a FRESH launch (VERDICT r2 item 8)."""
    path = str(tmp_path / "ck")
    run_mp(2, "ckpt_save", args=(path,))
    assert os.path.exists(path + ".manifest.npz")
    assert os.path.exists(path + ".rank0.npz")
    assert os.path.exists(path + ".rank1.npz")
    run_mp(2, "ckpt_restore", args=(path,))


@pytest.mark.slow
def test_mp_thread_process_stress():
    """2 worker threads x 2 processes hammer overlapping keys under intent
    churn + background sync; final main copies equal the exact global
    push counts."""
    run_mp(2, "stress", timeout=420)


@pytest.mark.slow
def test_mp_bindings():
    """The bindings surface (reference bindings/example.py's multi-node
    shape) works across 2 launched processes."""
    run_mp(2, "bindings")


@pytest.mark.slow
def test_mp_kge_app_data_parallel():
    """The full KGE app trains data-parallel across 2 processes and
    reaches the same quality bar as the single-process run."""
    run_mp(2, "kge_app", timeout=600)


@pytest.mark.slow
def test_mp_heartbeat_dead_node_detection():
    """--sys.heartbeat: a rank that stops beating is reported by
    dead_nodes() (reference GetDeadNodes, src/postoffice.cc:202-221)."""
    run_mp(2, "heartbeat")


@pytest.mark.slow
def test_mp_location_caches_off():
    """--sys.location_caches 0: hint table stays cold, routing still
    converges via the manager."""
    run_mp(3, "location_caches", devices=1, args=(0,))


@pytest.mark.slow
@pytest.mark.parametrize("scheme", ["naive", "preloc", "pool", "local"])
def test_mp_sampling_schemes(scheme):
    """All four sampling schemes draw remotely-owned keys correctly across
    processes (reference run_tests.sh sampling-scheme variants)."""
    run_mp(3, "sampling", devices=1, args=(scheme,))


@pytest.mark.slow
def test_mp_elastic_recovery_under_keepalive(tmp_path, monkeypatch):
    """The recovery loop of docs/failure_handling.md driven END TO END by
    the launcher keepalive (VERDICT r3 item 10): both ranks crash with
    exit code 254 mid-epoch after a checkpoint, launch_local restarts
    them with the same rank/env, the restarted job restores the manager
    and passes the value/placement/consistency checks."""
    path = str(tmp_path / "ck")
    # launch_local spawns with os.environ + the ADAPM contract; give the
    # children the same env run_mp does (CPU mesh, repo importable)
    monkeypatch.setenv("PYTHONPATH", REPO)
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    monkeypatch.setenv("ADAPM_PLATFORM", "cpu")
    from xla_compat import mesh_flags
    monkeypatch.setenv("XLA_FLAGS", mesh_flags(2))
    code = launcher.launch_local(
        2, [sys.executable, SCENARIOS, "elastic", path], keepalive=True)
    assert code == 0
    for r in range(2):
        assert os.path.exists(f"{path}.attempt.rank{r}"), \
            f"rank {r} never ran its first attempt"
        assert os.path.exists(f"{path}.done.rank{r}"), \
            f"rank {r} did not complete the restarted attempt"


@pytest.mark.parametrize("tech", ["all", "replication_only",
                                  "relocation_only"])
def test_mp_eventual_consistency_loopback_reroute(tech):
    """scenario_eventual rerouted through the NetPort loopback backend
    (ISSUE 19): the exact invariant the collective-gated tests pin —
    push+revert under full replication pressure restores the exact base
    on every rank after WaitSync -> Barrier -> WaitSync — runs fully
    in-container, two Servers in one process wired through
    adapm_tpu/net. Not slow-marked: this is the tier-1 stand-in for the
    gated runs above."""
    import numpy as np

    from adapm_tpu.base import CLOCK_MAX, MgmtTechniques
    from adapm_tpu.config import SystemOptions
    from adapm_tpu.net import LoopbackCluster

    cl = LoopbackCluster(
        2, num_keys=48, value_lengths=4,
        opts_factory=lambda r: SystemOptions(
            sync_max_per_sec=0, prefetch=False,
            techniques=MgmtTechniques(tech)))
    try:
        keys = np.arange(48, dtype=np.int64)
        base = np.arange(48, dtype=np.float32)[:, None] * \
            np.ones(4, np.float32)

        def scenario(rank, srv):
            w = srv.make_worker(0)
            if rank == 0:
                w.wait(w.set(keys, base))
            srv.barrier()
            w.intent(keys, 0, CLOCK_MAX)
            srv.wait_sync()
            srv.barrier()
            x = np.full((48, 4), 2.5 + rank, np.float32)
            w.wait(w.push(keys, x))
            w.wait(w.push(keys, -x))
            srv.wait_sync()
            srv.barrier()
            srv.wait_sync()
            srv.barrier()
            return w.pull_sync(keys)

        outs = cl.run(scenario)
        for rank, v in enumerate(outs):
            assert np.allclose(v, base, atol=1e-4), \
                f"rank {rank}: not restored"
    finally:
        cl.shutdown()
