"""Bindings usage example (reference bindings/example.py: 4 simulated
nodes training a shared embedding with intent + sampling).

Run: PYTHONPATH=. python examples/bindings_example.py
"""
import threading

import numpy as np
import torch

from adapm_tpu import bindings as adapm

NUM_KEYS = 100
VALUE_LEN = 8
NUM_WORKERS = 4
ITERS = 20


def run_worker(worker_id: int, server: adapm.Server, results: list) -> None:
    w = adapm.Worker(worker_id, server)
    keys = torch.tensor([worker_id, NUM_WORKERS + worker_id],
                        dtype=torch.int64)
    vals = torch.zeros(2, VALUE_LEN)
    for it in range(ITERS):
        w.intent(keys, w.current_clock, w.current_clock + 2)
        w.pull(keys, vals)
        grad = torch.ones(2, VALUE_LEN) * 0.1
        w.push(keys, grad)
        # negative samples through the managed sampling support
        h = w.prepare_sample(4, w.current_clock)
        skeys = torch.zeros(4, dtype=torch.int64)
        svals = torch.zeros(4, VALUE_LEN)
        w.pull_sample(h, skeys, svals)
        w.advance_clock()
    w.wait_sync()
    w.pull(keys, vals)
    results[worker_id] = vals.clone()
    w.finalize()


def main() -> None:
    adapm.setup(NUM_KEYS, NUM_WORKERS)
    server = adapm.Server(VALUE_LEN, num_keys=NUM_KEYS)
    server.enable_sampling_support("local", True, "uniform", 0, NUM_KEYS)

    results = [None] * NUM_WORKERS
    threads = [threading.Thread(target=run_worker, args=(i, server, results))
               for i in range(NUM_WORKERS)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    server.barrier()
    for i, r in enumerate(results):
        print(f"worker {i}: {r[0, :4].tolist()}")
    expect = ITERS * 0.1
    assert all(abs(float(r[0, 0]) - expect) < 1e-4 for r in results), \
        "each worker owns its keys; pushes are additive"
    print("bindings example PASSED")
    server.shutdown()


if __name__ == "__main__":
    main()
