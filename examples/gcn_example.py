"""GCN node classification through the bindings: the workload shape of the
reference's external PyTorch apps (adapm-pytorch-apps GCN; reference
README.md:23).

All trainable state lives in the parameter manager with per-key value
lengths (reference per-key `value_lengths`, coloc_kv_server.h:76):

  keys [0, N)            node embeddings, row = [x(D) | adagrad(D)]
  keys [N, N+H)          W1 rows (D -> H), row = [w(D) | adagrad(D)]
  keys [N+H, N+H+C)      W2 rows (H -> C), row = [w(H) | adagrad(H)]

A 2-layer GCN  logits = A_hat @ relu(A_hat @ X @ W1) @ W2  is autograded by
torch; workers are data-parallel over the labeled nodes (each computes the
loss on its node partition) and push additive AdaGrad deltas for the node
rows and the shared dense W1/W2 keys — the hot shared keys every worker
touches each step, exactly what the PM's replication serves.

Run: PYTHONPATH=. python examples/gcn_example.py
"""
import threading

import numpy as np
import torch

from adapm_tpu import bindings as adapm

N, C = 240, 4         # nodes, classes (stochastic block model)
D, H = 16, 16         # embedding dim, hidden dim
EPOCHS = 40
NUM_WORKERS = 2
LR = 0.3
EPS = 1e-8
KEY_W1, KEY_W2 = N, N + H
NUM_KEYS = N + H + C


def make_graph(rng):
    labels = np.repeat(np.arange(C), N // C)
    same = labels[:, None] == labels[None, :]
    p = np.where(same, 0.10, 0.004)
    adj = (rng.random((N, N)) < p)
    adj = np.triu(adj, 1)
    adj = adj | adj.T | np.eye(N, dtype=bool)      # self loops
    deg = adj.sum(1)
    dinv = 1.0 / np.sqrt(deg)
    a_hat = (adj * dinv[:, None] * dinv[None, :]).astype(np.float32)
    return torch.from_numpy(a_hat), torch.from_numpy(labels)


def pull_matrix(w, keys, width):
    buf = torch.zeros(len(keys), 2 * width)
    w.pull(keys, buf)
    return buf[:, :width].clone().requires_grad_(True), buf[:, width:]


def push_adagrad(w, keys, param, acc):
    g = param.grad
    delta = torch.cat([-LR * g / torch.sqrt(acc + g * g + EPS), g * g], 1)
    w.push(keys, delta, asynchronous=True)


def run_worker(wid, server, a_hat, labels, out):
    w = adapm.Worker(wid, server)
    node_keys = np.arange(N, dtype=np.int64)
    w1_keys = np.arange(KEY_W1, KEY_W1 + H, dtype=np.int64)
    w2_keys = np.arange(KEY_W2, KEY_W2 + C, dtype=np.int64)
    mine = torch.arange(wid, N, NUM_WORKERS)       # labeled-node partition
    # standing intent on the dense weights (hot keys shared by all
    # workers) + this worker's node rows
    w.intent(np.concatenate([w1_keys, w2_keys, node_keys]),
             w.current_clock, w.current_clock + EPOCHS + 1)
    for ep in range(EPOCHS):
        x, accx = pull_matrix(w, node_keys, D)
        w1, acc1 = pull_matrix(w, w1_keys, D)      # [H, D] (rows = units)
        w2, acc2 = pull_matrix(w, w2_keys, H)      # [C, H]
        h1 = torch.relu(a_hat @ (x @ w1.t()))
        logits = a_hat @ (h1 @ w2.t())
        loss = torch.nn.functional.cross_entropy(logits[mine],
                                                 labels[mine])
        loss.backward()
        push_adagrad(w, node_keys, x, accx)
        push_adagrad(w, w1_keys, w1, acc1)
        push_adagrad(w, w2_keys, w2, acc2)
        w.advance_clock()
        w.waitall()
        w.barrier()         # all-worker rendezvous: epochs stay in step
        if wid == 0 and ep % 10 == 0:
            acc = float((logits.argmax(1) == labels).float().mean())
            print(f"gcn epoch {ep}: loss {loss.item():.3f} acc {acc:.2f}")
    # final accuracy from fresh PM state
    w.wait_sync()
    x, _ = pull_matrix(w, node_keys, D)
    w1, _ = pull_matrix(w, w1_keys, D)
    w2, _ = pull_matrix(w, w2_keys, H)
    with torch.no_grad():
        logits = a_hat @ (torch.relu(a_hat @ (x @ w1.t())) @ w2.t())
        out[wid] = float((logits.argmax(1) == labels).float().mean())
    w.finalize()


def main():
    rng = np.random.default_rng(3)
    a_hat, labels = make_graph(rng)
    adapm.setup(NUM_KEYS, NUM_WORKERS)
    lens = np.concatenate([np.full(N, 2 * D), np.full(H, 2 * D),
                           np.full(C, 2 * H)]).astype(np.int64)
    server = adapm.Server(lens)

    w0 = adapm.Worker(0, server)
    w0.begin_setup()
    flat = []
    for width, count in ((D, N), (D, H), (H, C)):
        rows = np.zeros((count, 2 * width), dtype=np.float32)
        rows[:, :width] = rng.normal(0, 0.3, (count, width))
        rows[:, width:] = 1e-6
        flat.append(rows.ravel())
    w0.set(np.arange(NUM_KEYS), np.concatenate(flat))
    w0.end_setup()
    w0.wait_sync()

    out = [None] * NUM_WORKERS
    threads = [threading.Thread(target=run_worker,
                                args=(i, server, a_hat, labels, out))
               for i in range(NUM_WORKERS)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    print(f"gcn: final accuracy {out[0]:.2f}")
    assert out[0] > 0.85, "GCN failed to classify the block-model graph"
    print("gcn example PASSED")
    server.shutdown()


if __name__ == "__main__":
    main()
