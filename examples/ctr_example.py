"""CTR (click-through-rate) training through the bindings: a factorization
machine over sparse categorical features, the workload shape of the
reference's external PyTorch apps (adapm-pytorch-apps CTR on Criteo;
reference README.md:23, bindings/README.md).

Everything trainable lives in the parameter manager: one key per feature
value across all fields, value row = [w | v(d) | adagrad(1+d)] — linear
weight, FM factor, and optimizer state co-located the way the reference
apps pack AdaGrad next to weights (e.g. apps/matrix_factorization.cc
param_len = 2*rank). The torch side is a plain autograd FM:

  score(x) = sum_i w_i + 0.5 * sum_d [(sum_i v_id)^2 - sum_i v_id^2]

Workers partition the click log (data parallelism over workers), signal
Intent for the NEXT batch's feature keys one clock ahead (the reference
apps' pipelined lookahead), pull the current batch's unique rows, autograd
the logistic loss, and push additive AdaGrad deltas.

Run: PYTHONPATH=. python examples/ctr_example.py
"""
import threading

import numpy as np
import torch

from adapm_tpu import bindings as adapm

FIELDS = 6            # categorical fields (Criteo has 26)
VOCAB = 50            # feature values per field
DIM = 8               # FM factor dimension
NUM_KEYS = FIELDS * VOCAB
ROW = 2 * (1 + DIM)   # [w | v | acc_w | acc_v]
NUM_WORKERS = 2
BATCH = 64
EPOCHS = 4
SAMPLES = 2048
LR = 0.1
EPS = 1e-8


def make_click_log(rng):
    """Synthetic Criteo-like log: clicks follow a ground-truth FM."""
    w_true = rng.normal(0, 0.5, NUM_KEYS)
    v_true = rng.normal(0, 0.5, (NUM_KEYS, DIM))
    feats = np.stack([rng.integers(0, VOCAB, SAMPLES) + f * VOCAB
                      for f in range(FIELDS)], axis=1)
    inter = 0.5 * ((v_true[feats].sum(1) ** 2
                    - (v_true[feats] ** 2).sum(1)).sum(1))
    score = w_true[feats].sum(1) + inter
    p = 1.0 / (1.0 + np.exp(-score / max(score.std(), 1e-6)))
    clicks = (rng.random(SAMPLES) < p).astype(np.float32)
    return feats.astype(np.int64), clicks


def fm_forward(rows: torch.Tensor, inv: torch.Tensor) -> torch.Tensor:
    """rows: [U, 1+DIM] trainable (w|v) for the batch's unique keys;
    inv: [B, FIELDS] positions into rows."""
    w = rows[:, 0][inv]                       # [B, F]
    v = rows[:, 1:][inv]                      # [B, F, D]
    inter = 0.5 * ((v.sum(1) ** 2 - (v ** 2).sum(1)).sum(1))
    return w.sum(1) + inter


def run_worker(wid, server, feats, clicks, out):
    w = adapm.Worker(wid, server)
    part = np.arange(wid, SAMPLES, NUM_WORKERS)
    losses = []
    for ep in range(EPOCHS):
        for lo in range(0, len(part), BATCH):
            idx = part[lo:lo + BATCH]
            nxt = part[lo + BATCH:lo + 2 * BATCH]
            if len(nxt):  # pipelined lookahead, one clock ahead
                w.intent(np.unique(feats[nxt]), w.current_clock + 1,
                         w.current_clock + 2)
            uniq, inv = np.unique(feats[idx], return_inverse=True)
            buf = torch.zeros(len(uniq), ROW)
            w.pull(uniq, buf)
            rows = buf[:, :1 + DIM].clone().requires_grad_(True)
            acc = buf[:, 1 + DIM:]
            score = fm_forward(rows, torch.from_numpy(
                inv.reshape(len(idx), FIELDS)))
            y = torch.from_numpy(clicks[idx])
            loss = torch.nn.functional.binary_cross_entropy_with_logits(
                score, y)
            loss.backward()
            g = rows.grad
            # additive AdaGrad delta: [-lr*g/sqrt(acc+g^2) | g^2] updates
            # both the weights and the co-located accumulator in one push
            delta = torch.cat(
                [-LR * g / torch.sqrt(acc + g * g + EPS), g * g], dim=1)
            w.push(uniq, delta, asynchronous=True)
            losses.append(loss.item())
            w.advance_clock()
        w.waitall()
        w.barrier()
    out[wid] = losses
    w.finalize()


def main():
    rng = np.random.default_rng(7)
    feats, clicks = make_click_log(rng)
    adapm.setup(NUM_KEYS, NUM_WORKERS)
    server = adapm.Server(ROW, num_keys=NUM_KEYS)
    # init: worker-0-initializes pattern (accumulator floor via Set)
    init = np.zeros((NUM_KEYS, ROW), dtype=np.float32)
    init[:, 1:1 + DIM] = rng.normal(0, 0.05, (NUM_KEYS, DIM))
    init[:, 1 + DIM:] = 1e-6
    w0 = adapm.Worker(0, server)
    w0.begin_setup()
    w0.set(np.arange(NUM_KEYS), init)
    w0.end_setup()
    w0.wait_sync()

    out = [None] * NUM_WORKERS
    threads = [threading.Thread(target=run_worker,
                                args=(i, server, feats, clicks, out))
               for i in range(NUM_WORKERS)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()

    first = float(np.mean(out[0][:4]))
    last = float(np.mean(out[0][-4:]))
    print(f"ctr: logloss {first:.3f} -> {last:.3f}")
    assert last < 0.92 * first, "FM failed to learn the click model"
    print("ctr example PASSED")
    server.shutdown()


if __name__ == "__main__":
    main()
