"""CTR (click-through-rate) training through the bindings: a factorization
machine over sparse categorical features, the workload shape of the
reference's external PyTorch apps (adapm-pytorch-apps CTR on Criteo;
reference README.md:23, bindings/README.md).

Everything trainable lives in the parameter manager: one key per feature
value across all fields, value row = [w | v(d) | adagrad(1+d)] — linear
weight, FM factor, and optimizer state co-located the way the reference
apps pack AdaGrad next to weights (e.g. apps/matrix_factorization.cc
param_len = 2*rank). The torch side is a plain autograd FM:

  score(x) = sum_i w_i + 0.5 * sum_d [(sum_i v_id)^2 - sum_i v_id^2]

Workers partition the click log (data parallelism over workers), signal
Intent for the NEXT batch's feature keys one clock ahead (the reference
apps' pipelined lookahead), pull the current batch's unique rows, autograd
the logistic loss, and push additive AdaGrad deltas.

After training, the INFERENCE half serves the same model through the
online serving plane (adapm_tpu/serve; docs/SERVING.md): several client
threads score held-out samples, fetching the FM's per-sample feature
SUMS via fused `ServeSession.lookup_bags` reads (one bag per sample
over its FIELDS keys — the DLRM embedding-bag shape) next to a flat
`lookup` for the quadratic term's squared member rows — the end-to-end
train-then-serve shape of a production CTR system — and both reads are
checked bit-identical against each other and against the training-path
pull (the serving plane's consistency contract).

Run: PYTHONPATH=. python examples/ctr_example.py
"""
import threading

import numpy as np
import torch

from adapm_tpu import bindings as adapm

FIELDS = 6            # categorical fields (Criteo has 26)
VOCAB = 50            # feature values per field
DIM = 8               # FM factor dimension
NUM_KEYS = FIELDS * VOCAB
ROW = 2 * (1 + DIM)   # [w | v | acc_w | acc_v]
NUM_WORKERS = 2
BATCH = 64
EPOCHS = 4
SAMPLES = 2048
LR = 0.1
EPS = 1e-8


def make_click_log(rng):
    """Synthetic Criteo-like log: clicks follow a ground-truth FM."""
    w_true = rng.normal(0, 0.5, NUM_KEYS)
    v_true = rng.normal(0, 0.5, (NUM_KEYS, DIM))
    feats = np.stack([rng.integers(0, VOCAB, SAMPLES) + f * VOCAB
                      for f in range(FIELDS)], axis=1)
    inter = 0.5 * ((v_true[feats].sum(1) ** 2
                    - (v_true[feats] ** 2).sum(1)).sum(1))
    score = w_true[feats].sum(1) + inter
    p = 1.0 / (1.0 + np.exp(-score / max(score.std(), 1e-6)))
    clicks = (rng.random(SAMPLES) < p).astype(np.float32)
    return feats.astype(np.int64), clicks


def fm_forward(rows: torch.Tensor, inv: torch.Tensor) -> torch.Tensor:
    """rows: [U, 1+DIM] trainable (w|v) for the batch's unique keys;
    inv: [B, FIELDS] positions into rows."""
    w = rows[:, 0][inv]                       # [B, F]
    v = rows[:, 1:][inv]                      # [B, F, D]
    inter = 0.5 * ((v.sum(1) ** 2 - (v ** 2).sum(1)).sum(1))
    return w.sum(1) + inter


def run_worker(wid, server, feats, clicks, out):
    w = adapm.Worker(wid, server)
    part = np.arange(wid, SAMPLES, NUM_WORKERS)
    losses = []
    for ep in range(EPOCHS):
        for lo in range(0, len(part), BATCH):
            idx = part[lo:lo + BATCH]
            nxt = part[lo + BATCH:lo + 2 * BATCH]
            if len(nxt):  # pipelined lookahead, one clock ahead
                w.intent(np.unique(feats[nxt]), w.current_clock + 1,
                         w.current_clock + 2)
            uniq, inv = np.unique(feats[idx], return_inverse=True)
            buf = torch.zeros(len(uniq), ROW)
            w.pull(uniq, buf)
            rows = buf[:, :1 + DIM].clone().requires_grad_(True)
            acc = buf[:, 1 + DIM:]
            score = fm_forward(rows, torch.from_numpy(
                inv.reshape(len(idx), FIELDS)))
            y = torch.from_numpy(clicks[idx])
            loss = torch.nn.functional.binary_cross_entropy_with_logits(
                score, y)
            loss.backward()
            g = rows.grad
            # additive AdaGrad delta: [-lr*g/sqrt(acc+g^2) | g^2] updates
            # both the weights and the co-located accumulator in one push
            delta = torch.cat(
                [-LR * g / torch.sqrt(acc + g * g + EPS), g * g], dim=1)
            w.push(uniq, delta, asynchronous=True)
            losses.append(loss.item())
            w.advance_clock()
        w.waitall()
        w.barrier()
    out[wid] = losses
    w.finalize()


def serve_inference(server, feats, clicks, n_clients=4, batch=32,
                    samples=256):
    """Serve the trained FM: each client thread scores its share of the
    held-out samples through coalesced lookups (concurrent clients hit
    the same hot feature rows — the micro-batcher deduplicates the
    union), with a generous per-request deadline so an overloaded box
    sheds instead of hanging.

    The FM's linear term and factor sum are BAG reads — each sample is
    one bag over its FIELDS feature keys, and `lookup_bags` returns the
    sum-pooled [sum w | sum v | sum acc] row per sample straight from
    the fused gather+pool program (docs/SERVING.md "Bag reads"), so the
    per-member rows never cross the wire. The quadratic term needs
    sum_i v_i^2 — a sum of SQUARED member rows, which no linear pooling
    can produce — so the squared correction still rides a flat `lookup`
    of the batch's unique keys; that flat read doubles as the
    bit-identity witness: host-pooling it must reproduce the bag read
    exactly (the serve/bags.py contract)."""
    from adapm_tpu.serve import ServePlane
    from adapm_tpu.serve.bags import pool_bags_host

    plane = ServePlane(server._srv)  # knobs from --sys.serve.* defaults
    held = np.arange(samples)
    parts = np.array_split(held, n_clients)
    preds = [None] * n_clients
    rows_seen = [None] * n_clients

    def client(ci):
        sess = plane.session()
        out, seen = [], {}
        for lo in range(0, len(parts[ci]), batch):
            idx = parts[ci][lo:lo + batch]
            fk = feats[idx]                      # [b, FIELDS]
            b = len(idx)
            ks = fk.ravel().astype(np.int64)
            bg = np.arange(0, len(ks) + 1, FIELDS)
            # one bag per sample: sum-pooled [w|v|acc] rows off the wire
            (pooled,) = sess.lookup_bags([ks], [bg], pooling="sum",
                                         deadline_ms=10_000)
            # flat read for the quadratic term's squared member rows
            uniq, inv = np.unique(fk, return_inverse=True)
            inv = inv.reshape(-1)   # numpy >= 2.1 returns fk's 2-D shape
            rows = sess.lookup(uniq, deadline_ms=10_000)
            host = pool_bags_host(rows[inv],
                                  np.repeat(np.arange(b), FIELDS)
                                  .astype(np.int32), b, "sum")
            assert np.array_equal(pooled, host), \
                "bag read diverged from host pool of the flat read"
            sw = pooled[:, 0]                    # sum_i w_i
            sv = pooled[:, 1:1 + DIM]            # sum_i v_i
            v = rows[:, 1:1 + DIM][inv.reshape(b, FIELDS)]
            out.append(sw + 0.5 * ((sv ** 2).sum(1)
                                   - (v ** 2).sum((1, 2))))
            for k, r in zip(uniq, rows):
                seen[int(k)] = r
        preds[ci] = np.concatenate(out)
        rows_seen[ci] = seen

    threads = [threading.Thread(target=client, args=(ci,))
               for ci in range(n_clients)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()

    # the serving plane's consistency contract: every served row is
    # bit-identical to a plain training-path pull of the same key
    wchk = adapm.Worker(0, server)
    for seen in rows_seen:
        keys = np.fromiter(seen, np.int64, len(seen))
        buf = np.zeros((len(keys), ROW), np.float32)
        wchk.pull(keys, buf)
        assert np.array_equal(
            np.stack([seen[int(k)] for k in keys]), buf), \
            "serve lookup diverged from Worker.pull"

    scores = np.concatenate(preds)
    y = clicks[held]
    p = 1.0 / (1.0 + np.exp(-scores))
    logloss = float(-np.mean(y * np.log(p + 1e-9)
                             + (1 - y) * np.log(1 - p + 1e-9)))
    snap = server._srv.metrics_snapshot()["serve"]
    print(f"serve: {len(held)} samples via {n_clients} clients, "
          f"logloss {logloss:.3f}, {snap['batches_total']} coalesced "
          f"batches for {snap['lookups_total']} lookups + "
          f"{snap['bag_lookups_total']} bag lookups "
          f"({snap['bag_pooled_total']} pooled bags, "
          f"{snap['bag_fused_total']} fused), "
          f"ready={bool(snap['ready'])}")
    plane.close()
    return logloss


def main():
    rng = np.random.default_rng(7)
    feats, clicks = make_click_log(rng)
    adapm.setup(NUM_KEYS, NUM_WORKERS)
    server = adapm.Server(ROW, num_keys=NUM_KEYS)
    # init: worker-0-initializes pattern (accumulator floor via Set)
    init = np.zeros((NUM_KEYS, ROW), dtype=np.float32)
    init[:, 1:1 + DIM] = rng.normal(0, 0.05, (NUM_KEYS, DIM))
    init[:, 1 + DIM:] = 1e-6
    w0 = adapm.Worker(0, server)
    w0.begin_setup()
    w0.set(np.arange(NUM_KEYS), init)
    w0.end_setup()
    w0.wait_sync()

    out = [None] * NUM_WORKERS
    threads = [threading.Thread(target=run_worker,
                                args=(i, server, feats, clicks, out))
               for i in range(NUM_WORKERS)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()

    first = float(np.mean(out[0][:4]))
    last = float(np.mean(out[0][-4:]))
    print(f"ctr: logloss {first:.3f} -> {last:.3f}")
    assert last < 0.92 * first, "FM failed to learn the click model"

    # inference half: serve the trained model through the serving plane
    serve_logloss = serve_inference(server, feats, clicks)
    assert serve_logloss < first, \
        "served model scored worse than the untrained baseline"
    print("ctr example PASSED")
    server.shutdown()


if __name__ == "__main__":
    main()
