"""XLA_FLAGS compatibility probing.

XLA's flag parser ABORTS the whole process (parse_flags_from_env.cc
SIGABRT, not a Python exception) when XLA_FLAGS contains a flag the
installed jaxlib does not know. The tuning flags this repo sets for the
CPU test/bench harness (the in-process collective watchdog timeouts) do
not exist in every jaxlib vintage, so baking them into XLA_FLAGS
unconditionally kills EVERY test and bench process on such an install —
observed in this image: `make_cpu_client` aborts before the first test
runs.

`filter_xla_flags` vets optional flags in a throwaway subprocess (the
only way to survive the abort) and caches the verdict per jaxlib
version, so the probe costs one interpreter start per environment, not
per run.
"""
from __future__ import annotations

import hashlib
import os
import subprocess
import sys
import tempfile
from typing import List, Sequence

# flags old enough to be universally safe are not probed
_ALWAYS_SAFE_PREFIXES = ("--xla_force_host_platform_device_count",)


def _cache_path(flags: Sequence[str]) -> str:
    try:
        from importlib.metadata import version
        ver = version("jaxlib")
    except Exception:  # pragma: no cover - jaxlib always installed here
        ver = "unknown"
    h = hashlib.sha1((" ".join(flags)).encode()).hexdigest()[:12]
    return os.path.join(tempfile.gettempdir(),
                        f"adapm_xla_flags_{ver}_{h}")


def _probe(flags: Sequence[str], timeout: float = 120.0):
    """True/False: a fresh interpreter could / could not build the CPU
    client with `flags` in XLA_FLAGS (an unknown flag ABORTS that
    subprocess, so rc != 0 is a definitive rejection). None: the probe
    itself failed to produce a verdict (timeout on a loaded host, spawn
    error) — the caller must not CACHE that as a rejection."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["ADAPM_PLATFORM"] = "cpu"
    env["XLA_FLAGS"] = " ".join(flags)
    try:
        r = subprocess.run(
            [sys.executable, "-c",
             "import jax; jax.config.update('jax_platforms', 'cpu'); "
             "jax.devices()"],
            env=env, capture_output=True, timeout=timeout)
        return r.returncode == 0
    except Exception:
        return None


def filter_xla_flags(flags: Sequence[str]) -> List[str]:
    """Return the subset of `flags` the installed jaxlib accepts.

    Probes all candidate flags at once (the common case: all supported
    or the whole same-vintage group missing); on a definitive rejection
    retries each flag individually. Definitive verdicts are cached under
    the system temp dir, keyed by jaxlib version + flag set; an
    inconclusive probe (timeout on a loaded host) conservatively omits
    the flags for THIS run only — caching it would strip supported
    watchdog flags forever.
    """
    need_probe = [f for f in flags
                  if not f.startswith(_ALWAYS_SAFE_PREFIXES)]
    safe = [f for f in flags if f.startswith(_ALWAYS_SAFE_PREFIXES)]
    if not need_probe:
        return list(flags)
    cache = _cache_path(need_probe)
    if os.path.exists(cache):
        with open(cache) as f:
            kept = f.read().split()
        return safe + [f for f in need_probe if f in kept]
    verdict = _probe(safe + need_probe)
    if verdict is None:
        return safe  # inconclusive: omit but do not cache
    if verdict:
        kept = need_probe
    else:
        per_flag = {f: _probe(safe + [f]) for f in need_probe}
        if None in per_flag.values():
            return safe + [f for f, ok in per_flag.items() if ok]
        kept = [f for f, ok in per_flag.items() if ok]
    tmp = cache + f".tmp{os.getpid()}"
    with open(tmp, "w") as f:  # atomic: concurrent pytest workers race
        f.write(" ".join(kept))
    os.replace(tmp, cache)
    return safe + kept


class AcceleratorUnavailableError(RuntimeError):
    """An accelerator backend cannot be used in this environment —
    NAMED (ISSUE 14 satellite). The bench r04 death mode was the TPU
    path dying AT SETUP (client construction aborts / hangs before the
    first program); callers that see this error skip the backend and
    record it (`bench.py` writes `backend: skipped`) instead of taking
    the whole run down or silently degrading."""


def probe_device_backend(platform=None, timeout: float = 180.0):
    """Can `platform` (None = the environment's default backend)
    initialize and enumerate devices? Probed in a throwaway subprocess
    — an unusable backend often ABORTS or wedges client construction,
    which no in-process try/except survives (the filter_xla_flags
    lesson, applied to backends).

    Returns (verdict, detail):
      True,  "tpu x4"      — usable; detail names platform + count
      False, "...rc=134.." — definitively unusable (died at setup)
      None,  "...timeout"  — inconclusive (wedged relay / loaded host);
                             treat as unusable for THIS run, but do not
                             record it as a permanent verdict.
    """
    env = dict(os.environ)
    if platform:
        env["JAX_PLATFORMS"] = platform
        env["ADAPM_PLATFORM"] = platform
    try:
        r = subprocess.run(
            [sys.executable, "-c",
             "import jax; ds = jax.devices(); "
             "print(ds[0].platform, len(ds))"],
            env=env, capture_output=True, timeout=timeout, text=True)
    except subprocess.TimeoutExpired:
        return None, (f"backend probe timed out after {timeout:.0f}s "
                      f"(wedged relay / loaded host)")
    except Exception as e:  # pragma: no cover - spawn failure
        return None, f"backend probe failed to spawn: {e}"
    if r.returncode != 0:
        tail = " | ".join((r.stderr or "").strip().splitlines()[-3:])
        return False, (f"backend died at setup (rc={r.returncode}): "
                       f"{tail or 'no stderr'}")
    parts = r.stdout.split()
    detail = f"{parts[0]} x{parts[1]}" if len(parts) >= 2 else "ok"
    return True, detail


def require_device_backend(platform=None, timeout: float = 180.0) -> str:
    """Raise AcceleratorUnavailableError unless `platform` probes
    usable; returns the probe detail on success. The setup-death guard
    for scripts that would otherwise die mid-construction (the bench
    r04 mode)."""
    verdict, detail = probe_device_backend(platform, timeout=timeout)
    if verdict is not True:
        raise AcceleratorUnavailableError(
            f"accelerator backend "
            f"{platform or os.environ.get('JAX_PLATFORMS', 'default')!r}"
            f" is unusable here: {detail}")
    return detail


def mesh_flags(devices: int) -> str:
    """The harness's XLA_FLAGS value for an N-virtual-device CPU mesh:
    the device-count flag plus — only when the installed jaxlib knows
    them — the in-process collective watchdog timeouts (XLA CPU kills
    the process after 40 s if rendezvous participants straggle, which N
    participants serialized on a 1-2 core host legitimately do on big
    programs). One probe per environment; every caller (conftest,
    bench.py, the mp test harness, scripts) shares the cached verdict."""
    return " ".join(filter_xla_flags([
        f"--xla_force_host_platform_device_count={devices}",
        "--xla_cpu_collective_call_warn_stuck_timeout_seconds=120",
        "--xla_cpu_collective_call_terminate_timeout_seconds=900",
    ]))
