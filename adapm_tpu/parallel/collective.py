"""BSP cross-process sync data plane over XLA collectives.

The default cross-process transport (parallel/dcn.py) is host TCP — the
reference's ZMQ van as data plane (include/zmq_van.h:124-220). This module
implements SURVEY.md's TPU-native mapping for the SYNC traffic instead
(SURVEY.md: "sync-manager traffic -> asynchronous ICI collectives"): every
process contributes its outgoing replica-delta rows to a device all-to-all
over a one-device-per-process mesh, owners merge and the fresh values ride
the return exchange. On a real multi-host TPU the rows move HBM-to-HBM
over ICI/DCN; on the CPU test harness the same program runs over gloo —
identical code, identical semantics (VERDICT r3 item 1).

Execution model: XLA collectives are SPMD — every process must enter the
same exchange the same number of times. The PM's asynchronous per-request
traffic (pull/push misses, intent decisions, replica drops) therefore
stays on the DCN channel (it is the thin tail by design: intent makes keys
local before use), and the BULK flow — replica delta ship + fresh-value
refresh — runs as bulk-synchronous rounds at the points the API already
requires every process to reach together: WaitSync and quiesce (the
documented WaitSync -> Barrier -> WaitSync protocol). Enable with
--sys.collective_sync; round geometry is fixed by --sys.collective_bucket
so all processes compile the same exchange program.

Within a round the item count per destination varies per process; the loop
iterates while the GLOBAL backlog (control.allreduce — itself a collective
every process calls) is nonzero, so all processes run identical iteration
counts with empty-padded buckets where they have nothing to send.
"""
from __future__ import annotations


from typing import Dict, List, Tuple

import numpy as np

from . import control

NO_KEY = np.int64(-1)  # bucket padding
MAX_ROUNDS = 64        # convergence bound, mirrors pm.MAX_TRIES


class _JoinWatchdog:
    """Logs while a process sits at a collective join point.

    The collective contract is stricter than the reference's WaitSync —
    EVERY process must reach the exchange together — so a unilateral
    Server.wait_sync() (e.g. the bindings' per-worker wait_sync on one
    rank only) blocks forever here. Without this, the only symptom is a
    bare hang (faulthandler at best); with it, the stuck rank says what
    it is waiting for every `warn_after` seconds."""

    def __init__(self, pid: int, what: str, warn_after: float = 20.0):
        import threading
        self._msg = (f"pm{pid}: collective sync point ({what}): still "
                     f"waiting for peers after %.0fs — with "
                     f"--sys.collective_sync EVERY process must reach "
                     f"WaitSync/quiesce together; an asymmetric "
                     f"wait_sync hangs here")
        self._warn_after = warn_after
        self._stop = threading.Event()
        # apm-lint: disable=APM004 liveness watchdog for a BSP exchange
        # that may be stuck waiting on peers: it must be able to report
        # even when every executor worker is parked inside that exchange
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="adapm-coll-watchdog")

    def _run(self):
        from ..utils.log import alog
        import time as _time
        t0 = _time.monotonic()
        while not self._stop.wait(self._warn_after):
            alog(self._msg % (_time.monotonic() - t0))

    def __enter__(self):
        self._thread.start()
        return self

    def __exit__(self, *exc):
        self._stop.set()
        return False


class CollectiveSync:
    """The exchange engine: one device per process, jitted all-to-all
    programs cached per (bucket, row_length) pair."""

    def __init__(self, pm, bucket: int):
        import jax

        self.pm = pm
        self.bucket = int(bucket)
        P = pm.num_procs
        devs = sorted(jax.devices(), key=lambda d: (d.process_index, d.id))
        per_proc = [next(d for d in devs if d.process_index == p)
                    for p in range(P)]
        from jax.sharding import Mesh, NamedSharding, PartitionSpec
        self._P = P
        self._mesh = Mesh(np.array(per_proc), ("p",))
        self._sharding = NamedSharding(self._mesh, PartitionSpec("p"))
        self._mine = per_proc[pm.pid]
        self._fns: Dict[Tuple, object] = {}
        self._first_exchange = True
        self.stats = {"rounds": 0, "iterations": 0, "rows_out": 0,
                      "rows_in": 0}
        # obs: wall time of each device all-to-all (upload + exchange +
        # readback) — the ICI/gloo wait the BSP sync path spends per
        # iteration (docs/OBSERVABILITY.md)
        self._h_xchg = pm.server.obs.histogram("collective.exchange_s")

    # -- the exchange primitive ---------------------------------------------

    def _fn(self, nleaves: int):
        import jax
        from jax.sharding import PartitionSpec

        from ..device import default_port
        fn = self._fns.get(nleaves)
        if fn is None:
            def xchg(tree):
                def one(x):  # local block [1, P, B, ...]
                    return jax.lax.all_to_all(x[0], "p", 0, 0)[None]
                return jax.tree_util.tree_map(one, tree)

            # collective-program construction through the DevicePort
            # (ISSUE 14): the port owns the shard_map/jit plumbing (and
            # the jax.shard_map vs jax.experimental fallback)
            fn = self._fns[nleaves] = default_port().compile_collective(
                xchg, mesh=self._mesh, in_specs=PartitionSpec("p"),
                out_specs=PartitionSpec("p"))
        return fn

    def exchange(self, local_tree):
        """All-to-all a pytree of [P, B, ...] buffers (leaf[d] = payload
        for process d). Returns same-shaped leaves with leaf[s] = payload
        process s sent here. EVERY process must call this together."""
        from ..obs.metrics import timed
        with timed(self._h_xchg):
            return self._exchange_impl(local_tree)

    def _exchange_impl(self, local_tree):
        import jax
        P = self._P

        from ..device import default_port
        port = default_port()

        def to_global(x):
            x = np.ascontiguousarray(x)
            blk = port.put_single(x[None], self._mine)
            return jax.make_array_from_single_device_arrays(
                (P,) + x.shape, self._sharding, [blk])

        leaves, treedef = jax.tree_util.tree_flatten(local_tree)
        g = [to_global(x) for x in leaves]
        out = self._fn(len(leaves))(
            jax.tree_util.tree_unflatten(treedef, g))
        return jax.tree_util.tree_map(
            lambda o: np.asarray(o.addressable_shards[0].data)[0], out)

    # -- the sync protocol --------------------------------------------------

    def request_sync(self, karr: np.ndarray, flat: np.ndarray,
                     lens: np.ndarray,
                     quiescing: bool = True) -> Tuple[np.ndarray, bool]:
        """BSP twin of GlobalPM._request_sync: ship delta rows to owners,
        return `(fresh values for every key, all_quiescing)`. `karr` MAY
        be empty — the process still joins every exchange iteration
        (collective contract). Iterates per length class in globally-
        agreed order.

        `quiescing` rides the up-front allreduce: it is True when this
        process is at a WaitSync/quiesce point and False for a cadence
        exchange (--sys.collective_cadence). `all_quiescing` tells a
        waiting process whether every peer has reached its wait point —
        the termination test of the quiesce-time flag loop that absorbs
        skewed per-process cadence counts (core/sync.py)."""
        pm = self.pm
        from .pm import _offsets
        offs = _offsets(lens)
        fresh = np.empty(offs[-1], dtype=np.float32)
        self.stats["rounds"] += 1
        with pm.server._span("collective.bsp_round"), \
                _JoinWatchdog(pm.pid, "request_sync"):
            if self._first_exchange:
                # Align ranks before the FIRST gloo/ICI context creation:
                # the backend's collective-context init has a hard ~30 s
                # peer deadline, and per-rank first-compiles (e.g. one
                # rank just compiled its replica-install program, the
                # others did not) can skew arrival past it. The
                # coordination-service barrier has a long timeout and
                # absorbs that skew once; later exchanges reuse the
                # established context. Inside the watchdog: an asymmetric
                # first join must log, not hang bare.
                control.barrier("adapm-coll-init")
                self._first_exchange = False
            return self._request_sync_inner(karr, flat, lens, offs, fresh,
                                            quiescing)

    def _request_sync_inner(self, karr, flat, lens, offs, fresh,
                            quiescing):
        pm = self.pm
        from .pm import _select_flat
        # one up-front allreduce of per-class counts (+ the quiescing
        # flag in the last slot): classes nobody has items for are
        # skipped entirely (a WaitSync point with nothing to ship costs
        # one tiny collective, not 2 exchanges per class)
        ncls = len(pm.server.class_lengths)
        my_counts = np.zeros(ncls + 1, dtype=np.float64)
        cls_pos = []
        for cid in range(ncls):
            pos = np.nonzero(pm.server.ab.key_class[karr] == cid)[0] \
                if len(karr) else np.empty(0, dtype=np.int64)
            cls_pos.append(pos)
            my_counts[cid] = len(pos)
        my_counts[ncls] = 1.0 if quiescing else 0.0
        # own collective site: the exchange may be driven from a sync/
        # prefetch thread while the app thread runs its own "ar"-site
        # allreduces (RuntimeGuard, loss merges) — distinct sites pair
        # independently per rank (control.allreduce contract)
        global_counts = control.allreduce(my_counts, "sum",
                                          site="coll-counts")
        all_quiescing = bool(global_counts[ncls] >= self._P)
        for cid, L in enumerate(pm.server.class_lengths):
            if global_counts[cid] == 0:
                continue
            pos = cls_pos[cid]
            rows = _select_flat(flat, offs, lens, pos).reshape(-1, L)
            self._class_loop(cid, L, karr[pos] if len(karr) else
                             np.empty(0, np.int64), rows, pos, fresh,
                             offs, lens)
        return fresh, all_quiescing

    def _class_loop(self, cid: int, L: int, keys: np.ndarray,
                    rows: np.ndarray, pos: np.ndarray, fresh: np.ndarray,
                    offs: np.ndarray, lens: np.ndarray) -> None:
        """One class's bucket loop. keys/rows are this process's items
        (possibly empty); pos maps them into the caller's flat layout."""
        pm = self.pm
        from .pm import _fill_flat
        P, B = self._P, self.bucket

        def install(sel: np.ndarray, vals: np.ndarray,
                    owners: np.ndarray) -> None:
            _fill_flat(fresh, offs, lens, pos[sel], vals.ravel())
            pm._learn(keys[sel], owners)

        pend = np.arange(len(keys), dtype=np.int64)
        it = 0
        # per-item destination override from redirect hints (the role of
        # `dest` mutation in _drive; kept OFF the shared location caches,
        # which _learn updates under its own --sys.location_caches gate)
        redirect = np.full(len(keys), -1, dtype=np.int64)

        def route(p):
            if not len(p):
                return np.empty(0, dtype=np.int64)
            d = pm._route_dest(keys[p])
            return np.where(redirect[p] >= 0, redirect[p], d)

        while True:
            # items routed to SELF serve inline (a key may have been
            # adopted locally since it was classified remote)
            dest = route(pend)
            own = dest == pm.pid
            if own.any():
                mine = pend[own]
                reply = pm._serve_sync(
                    ("sync", keys[mine], rows[mine].ravel(), pm.pid))
                served = reply[0].astype(bool)
                vals = np.asarray(reply[1], np.float32).reshape(-1, L)
                if served.any():
                    install(mine[served], vals[served],
                            np.asarray(reply[2])[served])
                # unserved self-routed items retry (hint or manager next)
                bad = mine[~served]
                if len(bad):
                    hints = np.asarray(reply[2])[~served]
                    redirect[bad] = np.where(
                        hints >= 0, hints, pm.home_proc(keys[bad]))
                pend = np.concatenate([pend[~own], bad])
                dest = route(pend)
            # fill outgoing buckets (up to B per destination); the rest
            # stays pending for the next iteration
            out_k = np.full((P, B), NO_KEY, dtype=np.int64)
            out_r = np.zeros((P, B, L), dtype=np.float32)
            sent: List[np.ndarray] = [np.empty(0, np.int64)
                                      for _ in range(P)]
            taken = np.zeros(len(pend), dtype=bool)
            for d in range(P):
                if d == pm.pid:
                    continue
                where = np.nonzero(dest == d)[0][:B]
                sel = pend[where]
                sent[d] = sel
                taken[where] = True
                out_k[d, : len(sel)] = keys[sel]
                out_r[d, : len(sel)] = rows[sel]
            self.stats["rows_out"] += int(taken.sum())
            # X1: deltas travel to their owners
            in_k, in_r = self.exchange((out_k, out_r))
            # owner side: serve each source's bucket like a sync message
            rep_served = np.zeros((P, B), dtype=np.int32)
            rep_vals = np.zeros((P, B, L), dtype=np.float32)
            rep_own = np.full((P, B), -1, dtype=np.int32)
            for src in range(P):
                if src == pm.pid:
                    continue
                n = int((in_k[src] >= 0).sum())  # valid prefix (packed)
                if n == 0:
                    continue
                self.stats["rows_in"] += n
                reply = pm._serve_sync(
                    ("sync", in_k[src, :n], in_r[src, :n].ravel(), src))
                rep_served[src, :n] = reply[0].astype(np.int32)
                rep_vals[src, :n] = np.asarray(
                    reply[1], np.float32).reshape(n, L)
                rep_own[src, :n] = reply[2]
            # X2: replies ride back
            r_served, r_vals, r_own = self.exchange(
                (rep_served, rep_vals, rep_own))
            # requester side: install fresh values; unserved keys learn the
            # redirect hint and retry (the _drive retry loop, BSP-shaped)
            still: List[np.ndarray] = [pend[~taken]]
            for d in range(P):
                sel = sent[d]
                if len(sel) == 0:
                    continue
                m = r_served[d, : len(sel)].astype(bool)
                if m.any():
                    install(sel[m], r_vals[d, : len(sel)][m],
                            r_own[d, : len(sel)][m])
                if (~m).any():
                    bad = sel[~m]
                    hints = r_own[d, : len(sel)][~m]
                    redirect[bad] = np.where(
                        hints >= 0, hints, pm.home_proc(keys[bad]))
                    still.append(bad)
            pend = np.concatenate(still)
            self.stats["iterations"] += 1
            it += 1
            if it > 4 and len(pend):
                import time
                time.sleep(0.002)  # give in-flight adoptions time to land
            # globally-agreed termination: every process sees the same sum
            backlog = float(control.allreduce(float(len(pend)), "sum",
                                              site="coll-backlog")[0])
            if backlog == 0.0:
                return
            if it > MAX_ROUNDS:
                # same convergence bound as the RPC driver (_drive's
                # MAX_TRIES): the global count is identical on all
                # processes, so everyone raises together instead of
                # livelocking the exchange loop
                raise RuntimeError(
                    f"collective sync: ownership metadata did not "
                    f"converge after {it} rounds (global backlog "
                    f"{int(backlog)}, e.g. keys "
                    f"{keys[pend[:5]].tolist() if len(pend) else []})")
