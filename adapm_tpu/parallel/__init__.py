from .mesh import MeshContext, get_mesh_context, make_mesh  # noqa: F401
