"""Multi-host control plane.

Replaces the reference's scheduler + Van control machinery (ADD_NODE
rendezvous, BARRIER counting, heartbeats — src/van.cc:40-210,
src/postoffice.cc:149-187) with JAX's distributed runtime: the coordinator
service (`jax.distributed.initialize`) plays the scheduler, process ranks
replace node ids, and barriers/aggregations ride the coordinator's gRPC
channel or device collectives. ZeroMQ is gone entirely; data-plane traffic
is XLA collectives over ICI/DCN (see ARCHITECTURE.md).

All primitives degrade to no-ops / local computation in a single-process
run, so the same app code runs on one host or many.

`allreduce` is the replacement for the reference's PS-based scalar/vector
allreduce (`ps_allreduce`, include/utils.h:163-197) used by the apps for
loss/eval aggregation.
"""
from __future__ import annotations

import os
from typing import Optional

import numpy as np

# env names follow the launcher contract (launcher.py), mirroring the
# reference's DMLC_* topology env vars (docs/env.md)
ENV_COORD = "ADAPM_COORDINATOR"       # host:port of process 0
ENV_NUM_PROCS = "ADAPM_NUM_PROCESSES"
ENV_PROC_ID = "ADAPM_PROCESS_ID"


def init_from_env() -> bool:
    """Initialize `jax.distributed` from launcher env vars; returns True if
    a multi-process runtime was set up (reference Postoffice::Start +
    Van ADD_NODE handshake, collapsed into one call). Idempotent: a second
    call (e.g. explicit init_from_env followed by adapm_tpu.setup) is a
    no-op, like the reference's Postoffice::Start start_stage_ guard."""
    coord = os.environ.get(ENV_COORD)
    if not coord:
        return False
    n = int(os.environ[ENV_NUM_PROCS])
    pid = int(os.environ[ENV_PROC_ID])
    if n <= 1:
        return False
    from jax._src import distributed
    if distributed.global_state.client is not None:
        return True  # already joined
    import jax
    # ADAPM_COORD_HEARTBEAT_S (docs/env.md): coordination-service
    # heartbeat timeout override. Unset = jax's own default (100 s in
    # jax 0.9) — production dead-rank detection latency is unchanged.
    # The mp TEST harness sets 300: on an oversubscribed CI host, N
    # ranks x XLA compiles on 1-2 cores can stall a rank's heartbeat
    # past 100 s, which surfaces as a CoordinationService PollForError
    # on the OTHER ranks (observed flake in the mp app tests).
    kw = {}
    hb = int(round(float(os.environ.get("ADAPM_COORD_HEARTBEAT_S", "0"))))
    if hb > 0:
        kw["heartbeat_timeout_seconds"] = hb
    try:
        jax.distributed.initialize(coordinator_address=coord,
                                   num_processes=n, process_id=pid, **kw)
    except TypeError:
        # older jax without the heartbeat kwarg: fall back to bare init
        jax.distributed.initialize(coordinator_address=coord,
                                   num_processes=n, process_id=pid)
    return True


def num_processes() -> int:
    import jax
    return jax.process_count()


def process_id() -> int:
    import jax
    return jax.process_index()


_barrier_seq = 0
_barrier_lock = __import__("threading").Lock()


def barrier(name: str = "adapm") -> None:
    """Global process barrier (reference Postoffice::Barrier via the
    scheduler, src/postoffice.cc:149-174). Rides the coordinator's gRPC
    barrier — no device collectives, so it is safe to call from planner /
    background threads while device programs are in flight. Callers must
    barrier in the same ORDER on every process (the reference's scheduler
    counts BARRIER messages under the same contract)."""
    import jax
    if jax.process_count() == 1:
        return
    global _barrier_seq
    from jax._src import distributed
    client = distributed.global_state.client
    if client is not None:
        # id allocation is atomic; the wait happens outside the lock so
        # concurrent barriers from different threads both make progress
        seq = _next_seq("barrier")
        # generous timeout: a peer may be inside a cold XLA compile
        client.wait_at_barrier(f"adapm/{name}/{seq}", 600_000)
        return
    from jax.experimental import multihost_utils
    multihost_utils.sync_global_devices(name)


_hb_stop = None


def start_heartbeat(interval_s: float = 2.0) -> None:
    """Publish a periodic liveness beat to the coordinator's KV store
    (reference Van heartbeats, src/van.cc:515-527; off by default there
    and opt-in here). No-op in a single process."""
    import threading
    import time as _time

    import jax
    if jax.process_count() == 1:
        return
    global _hb_stop
    if _hb_stop is not None:
        return
    from jax._src import distributed
    client = distributed.global_state.client
    pid = jax.process_index()
    _hb_stop = threading.Event()

    def loop():
        while True:
            client.key_value_set(f"adapm/hb/{pid}",
                                 str(_time.time()), allow_overwrite=True)
            if _hb_stop.wait(interval_s):
                return

    threading.Thread(target=loop, daemon=True,
                     name="adapm-heartbeat").start()


def stop_heartbeat() -> None:
    global _hb_stop
    if _hb_stop is not None:
        _hb_stop.set()
        _hb_stop = None


def dead_processes(max_age_s: float = 10.0) -> list:
    """Process ids whose last heartbeat is older than `max_age_s` (the
    reference's Postoffice::GetDeadNodes, src/postoffice.cc:202-221).
    Processes that never published a beat are not reported (heartbeats
    are opt-in, as in the reference). Empty in a single process."""
    import time as _time

    import jax
    if jax.process_count() == 1:
        return []
    from jax._src import distributed
    client = distributed.global_state.client
    now = _time.time()
    dead = []
    for p in range(jax.process_count()):
        if p == jax.process_index():
            continue
        try:
            beat = client.key_value_try_get(f"adapm/hb/{p}")
        except Exception:  # noqa: BLE001 — no beat published yet
            continue
        if now - float(beat) > max_age_s:
            dead.append(p)
    return dead


_kv_seq = 0


def _next_seq(counter: str) -> int:
    """Allocate the next per-primitive sequence number (shared allocator
    for barrier and KV gather/broadcast ids; both contracts already
    require identical call order on every process)."""
    global _kv_seq, _barrier_seq
    with _barrier_lock:
        if counter == "barrier":
            _barrier_seq += 1
            return _barrier_seq
        _kv_seq += 1
        return _kv_seq


def _kv_gather(tag: str, payload: bytes, timeout_ms: int = 600_000):
    """Publish this rank's payload under a fresh sequence id and collect
    every rank's, via the coordinator KV store. HOST-ONLY on purpose: a
    device collective here can deadlock the PM — a rank parked inside
    the collective holds its device queue, its DCN serve threads then
    cannot dispatch the gather a PEER's in-flight read needs, and that
    peer never reaches the collective (observed: guard.expired()'s
    allreduce vs a peer still inside the chunked eval's filter
    correction). The control plane must ride the control plane
    (reference: ps_allreduce goes through the PS/scheduler, never the
    data path — include/utils.h:163-197).

    Callers must invoke in the same ORDER on every process (same
    contract as barrier()). Keys are deleted after a trailing barrier so
    the store does not grow with call count. Requires the coordination
    client (callers fall back to multihost_utils without one — e.g.
    multi-host TPU auto-topology launched outside the ADAPM env)."""
    import base64
    import jax
    from jax._src import distributed
    client = distributed.global_state.client
    seq = _next_seq("kv")
    pid = jax.process_index()
    key = f"adapm/{tag}/{seq}"
    client.key_value_set(f"{key}/{pid}", base64.b64encode(payload).decode())
    parts = []
    for p in range(jax.process_count()):
        s = client.blocking_key_value_get(f"{key}/{p}", timeout_ms)
        parts.append(base64.b64decode(s))
    # all ranks have read everything once all have passed this barrier;
    # deleting one's own key is then race-free
    barrier(f"{tag}-gc")
    client.key_value_delete(f"{key}/{pid}")
    return parts


def _kv_client():
    from jax._src import distributed
    return distributed.global_state.client


def allreduce(values, op: str = "sum") -> np.ndarray:
    """Sum/mean/max a host scalar or vector across processes (reference
    ps_allreduce, include/utils.h:163-197: push to a shared PS key, barrier,
    pull). Single-process: returns the input unchanged (as float64 array).
    Rides the coordinator KV store — never a device collective (see
    _kv_gather for why that would deadlock)."""
    import jax
    if op not in ("sum", "mean", "max"):
        raise ValueError(f"unknown allreduce op {op}")
    arr = np.atleast_1d(np.asarray(values, dtype=np.float64))
    if jax.process_count() == 1:
        return arr
    if _kv_client() is None:  # no coordination service: last resort only
        from jax.experimental import multihost_utils
        gathered = np.asarray(multihost_utils.process_allgather(arr))
    else:
        parts = _kv_gather("ar", arr.tobytes())
        gathered = np.stack([np.frombuffer(b, dtype=np.float64).reshape(
            arr.shape) for b in parts])
    return {"sum": gathered.sum, "mean": gathered.mean,
            "max": gathered.max}[op](axis=0)


def broadcast(values, root: int = 0) -> np.ndarray:
    """Broadcast a host array from `root` to all processes (worker-0
    initialization across hosts). KV-store transport, same rationale as
    allreduce; one root-published key, O(P) coordinator messages."""
    import base64
    import jax
    arr = np.asarray(values)
    if jax.process_count() == 1:
        return arr
    client = _kv_client()
    if client is None:  # no coordination service: last resort only
        from jax.experimental import multihost_utils
        return np.asarray(multihost_utils.broadcast_one_to_all(
            arr, is_source=jax.process_index() == root)).copy()
    seq = _next_seq("kv")
    key = f"adapm/bc/{seq}"
    if jax.process_index() == root:
        client.key_value_set(key, base64.b64encode(arr.tobytes()).decode())
    raw = base64.b64decode(client.blocking_key_value_get(key, 600_000))
    barrier("bc-gc")
    if jax.process_index() == root:
        client.key_value_delete(key)
    # .copy(): frombuffer over bytes is read-only; callers may mutate
    return np.frombuffer(raw, dtype=arr.dtype).reshape(arr.shape).copy()


# NOTE: an earlier draft exposed intent_summary_allgather here for a
# planner-side global interest exchange. The implemented design keeps the
# reference's shape instead: interest is tracked OWNER-side as per-key
# process bitmasks updated by intent/unsub traffic (parallel/pm.py
# GlobalPM.interest — the node_intent sets of sync_manager.h:182, 571,
# 644), so no allgather is needed on the decision path.
