"""Multi-host control plane.

Replaces the reference's scheduler + Van control machinery (ADD_NODE
rendezvous, BARRIER counting, heartbeats — src/van.cc:40-210,
src/postoffice.cc:149-187) with JAX's distributed runtime: the coordinator
service (`jax.distributed.initialize`) plays the scheduler, process ranks
replace node ids, and barriers/aggregations ride the coordinator's gRPC
channel or device collectives. ZeroMQ is gone entirely; data-plane traffic
is XLA collectives over ICI/DCN (see ARCHITECTURE.md).

All primitives degrade to no-ops / local computation in a single-process
run, so the same app code runs on one host or many.

`allreduce` is the replacement for the reference's PS-based scalar/vector
allreduce (`ps_allreduce`, include/utils.h:163-197) used by the apps for
loss/eval aggregation.
"""
from __future__ import annotations

import os
from typing import Optional

import numpy as np

# env names follow the launcher contract (launcher.py), mirroring the
# reference's DMLC_* topology env vars (docs/env.md)
ENV_COORD = "ADAPM_COORDINATOR"       # host:port of process 0
ENV_NUM_PROCS = "ADAPM_NUM_PROCESSES"
ENV_PROC_ID = "ADAPM_PROCESS_ID"


def init_from_env() -> bool:
    """Initialize `jax.distributed` from launcher env vars; returns True if
    a multi-process runtime was set up (reference Postoffice::Start +
    Van ADD_NODE handshake, collapsed into one call). Idempotent: a second
    call (e.g. explicit init_from_env followed by adapm_tpu.setup) is a
    no-op, like the reference's Postoffice::Start start_stage_ guard."""
    coord = os.environ.get(ENV_COORD)
    if not coord:
        return False
    n = int(os.environ[ENV_NUM_PROCS])
    pid = int(os.environ[ENV_PROC_ID])
    if n <= 1:
        return False
    from jax._src import distributed
    if distributed.global_state.client is not None:
        return True  # already joined
    import jax
    # ADAPM_COORD_HEARTBEAT_S (docs/env.md): coordination-service
    # heartbeat timeout override. Unset = jax's own default (100 s in
    # jax 0.9) — production dead-rank detection latency is unchanged.
    # The mp TEST harness sets 300: on an oversubscribed CI host, N
    # ranks x XLA compiles on 1-2 cores can stall a rank's heartbeat
    # past 100 s, which surfaces as a CoordinationService PollForError
    # on the OTHER ranks (observed flake in the mp app tests).
    kw = {}
    hb = int(round(float(os.environ.get("ADAPM_COORD_HEARTBEAT_S", "0"))))
    if hb > 0:
        kw["heartbeat_timeout_seconds"] = hb
    try:
        jax.distributed.initialize(coordinator_address=coord,
                                   num_processes=n, process_id=pid, **kw)
    except TypeError:
        # older jax without the heartbeat kwarg: fall back to bare init
        jax.distributed.initialize(coordinator_address=coord,
                                   num_processes=n, process_id=pid)
    return True


def num_processes() -> int:
    import jax
    return jax.process_count()


def process_id() -> int:
    import jax
    return jax.process_index()


_barrier_lock = __import__("threading").Lock()


def barrier(name: str = "adapm") -> None:
    """Global process barrier (reference Postoffice::Barrier via the
    scheduler, src/postoffice.cc:149-174). Rides the coordinator's gRPC
    barrier — no device collectives, so it is safe to call from planner /
    background threads while device programs are in flight.

    Ordering contract: barriers of the SAME `name` must be invoked in
    the same order on every process (sequence ids are per name, so
    differently-named barriers interleaved differently across ranks
    still pair correctly — the calling-site tag IS part of the id;
    ADVICE r5 #4). Same-name barriers from two local threads racing each
    other remain undefined — one caller thread per name.

    Wait time is observed into the `collective.barrier_wait_s`
    histogram of the process-default metrics registry (the Server
    registers it; no-op before a Server exists or with --sys.metrics
    0)."""
    import jax
    if jax.process_count() == 1:
        return
    from ..obs.metrics import timed
    with timed("collective.barrier_wait_s"):
        from jax._src import distributed
        client = distributed.global_state.client
        if client is not None:
            # id allocation is atomic; the wait happens outside the lock
            # so concurrent barriers from different threads both progress
            seq = _next_seq(f"barrier/{name}")
            # generous timeout: a peer may be inside a cold XLA compile
            client.wait_at_barrier(f"adapm/{name}/{seq}", 600_000)
            return
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices(name)


_hb_stop = None


def start_heartbeat(interval_s: float = 2.0) -> None:
    """Publish a periodic liveness beat to the coordinator's KV store
    (reference Van heartbeats, src/van.cc:515-527; off by default there
    and opt-in here). No-op in a single process."""
    import threading
    import time as _time

    import jax
    if jax.process_count() == 1:
        return
    global _hb_stop
    if _hb_stop is not None:
        return
    from jax._src import distributed
    client = distributed.global_state.client
    pid = jax.process_index()
    _hb_stop = threading.Event()

    def loop():
        while True:
            client.key_value_set(f"adapm/hb/{pid}",
                                 str(_time.time()), allow_overwrite=True)
            if _hb_stop.wait(interval_s):
                return

    # apm-lint: disable=APM004 process-level heartbeat with no Server
    # (hence no executor) in scope: the control plane outlives and
    # predates any Server on this rank (launcher-adjacent, like dcn.py)
    threading.Thread(target=loop, daemon=True,
                     name="adapm-heartbeat").start()


def stop_heartbeat() -> None:
    global _hb_stop
    if _hb_stop is not None:
        _hb_stop.set()
        _hb_stop = None


def dead_processes(max_age_s: float = 10.0) -> list:
    """Process ids whose last heartbeat is older than `max_age_s` (the
    reference's Postoffice::GetDeadNodes, src/postoffice.cc:202-221).
    Processes that never published a beat are not reported (heartbeats
    are opt-in, as in the reference). Empty in a single process."""
    import time as _time

    import jax
    if jax.process_count() == 1:
        return []
    from jax._src import distributed
    client = distributed.global_state.client
    now = _time.time()
    dead = []
    for p in range(jax.process_count()):
        if p == jax.process_index():
            continue
        try:
            beat = client.key_value_try_get(f"adapm/hb/{p}")
        except Exception:  # noqa: BLE001 — no beat published yet
            continue
        if now - float(beat) > max_age_s:
            dead.append(p)
    return dead


_seqs: dict = {}
_inflight: set = set()


def _next_seq(counter: str) -> int:
    """Allocate the next sequence number for `counter`. PER-NAME
    counters (ADVICE r5 #4): the calling-site tag is part of every KV
    key and barrier id, so two DIFFERENT sites invoked in different
    orders on different ranks still pair correctly instead of
    cross-wiring each other's keys into a 600 s timeout. (The pre-r6
    shared allocator made ANY cross-rank reordering — even of unrelated
    primitives — a silent deadlock.)"""
    with _barrier_lock:
        _seqs[counter] = _seqs.get(counter, 0) + 1
        return _seqs[counter]


class _exclusive:
    """Immediate-error guard for the single-caller-thread contract: two
    local threads driving the same collective site concurrently (e.g. a
    sync-report thread racing an eval's allreduce) would interleave
    sequence allocation differently across ranks — an undebuggable
    cross-wire that used to surface as a 600 s timeout. Raise at the
    second local entry instead (ADVICE r5 #4)."""

    def __init__(self, site: str):
        self.site = site

    def __enter__(self):
        with _barrier_lock:
            if self.site in _inflight:
                raise RuntimeError(
                    f"concurrent collective call on site {self.site!r}: "
                    "allreduce/broadcast/_kv_gather are single-caller-"
                    "thread per site — give each calling site its own "
                    "`site` tag, or serialize the callers")
            _inflight.add(self.site)
        return self

    def __exit__(self, *exc):
        with _barrier_lock:
            _inflight.discard(self.site)


def _pack_array(arr: np.ndarray) -> bytes:
    """Frame an array payload with its dtype/shape so the receiver can
    verify instead of reinterpreting bytes (ADVICE r5 #2: a root/
    non-root template mismatch with coincidentally equal nbytes — e.g.
    int64 vs float64 — used to silently decode garbage). ':' separators
    on purpose: dtype.str itself BEGINS with '|' for byte-order-free
    dtypes (bool, uint8, bytes), so '|' cannot delimit it."""
    head = f"{arr.dtype.str}:{','.join(map(str, arr.shape))}:"
    return head.encode() + arr.tobytes()


def _unpack_array(raw: bytes, expect: np.ndarray,
                  what: str) -> np.ndarray:
    """Decode a _pack_array payload, failing loudly on any dtype/shape/
    size mismatch against the receiver's template."""
    sep1 = raw.index(b":")
    sep2 = raw.index(b":", sep1 + 1)
    dt = np.dtype(raw[:sep1].decode())
    shape_s = raw[sep1 + 1:sep2].decode()
    shape = tuple(int(x) for x in shape_s.split(",")) if shape_s else ()
    if dt != expect.dtype or shape != expect.shape:
        raise ValueError(
            f"{what}: payload is {dt}{list(shape)} but this rank's "
            f"template is {expect.dtype}{list(expect.shape)} — ranks "
            "disagree on the collective's array layout")
    body = raw[sep2 + 1:]
    if len(body) != expect.nbytes:
        raise ValueError(
            f"{what}: payload carries {len(body)} bytes for a "
            f"{expect.nbytes}-byte template")
    # .copy(): frombuffer over bytes is read-only; callers may mutate
    return np.frombuffer(body, dtype=dt).reshape(shape).copy()


def _kv_gather(tag: str, payload: bytes, timeout_ms: int = 600_000):
    """Publish this rank's payload under a fresh sequence id and collect
    every rank's, via the coordinator KV store. HOST-ONLY on purpose: a
    device collective here can deadlock the PM — a rank parked inside
    the collective holds its device queue, its DCN serve threads then
    cannot dispatch the gather a PEER's in-flight read needs, and that
    peer never reaches the collective (observed: guard.expired()'s
    allreduce vs a peer still inside the chunked eval's filter
    correction). The control plane must ride the control plane
    (reference: ps_allreduce goes through the PS/scheduler, never the
    data path — include/utils.h:163-197).

    Contract (ADVICE r5 #4): ONE caller thread per `tag`, invoking in
    the same order on every process. Sequence ids are per tag, so
    different tags may interleave freely across ranks; a second local
    thread entering the same tag concurrently raises immediately
    (_exclusive) instead of cross-wiring KV keys into a 600 s timeout.
    Keys are deleted after a trailing barrier so the store does not grow
    with call count. Requires the coordination client (callers fall back
    to multihost_utils without one — e.g. multi-host TPU auto-topology
    launched outside the ADAPM env)."""
    import base64
    import jax
    from jax._src import distributed
    client = distributed.global_state.client
    with _exclusive(f"kv/{tag}"):
        seq = _next_seq(f"kv/{tag}")
        pid = jax.process_index()
        key = f"adapm/{tag}/{seq}"
        client.key_value_set(f"{key}/{pid}",
                             base64.b64encode(payload).decode())
        parts = []
        for p in range(jax.process_count()):
            s = client.blocking_key_value_get(f"{key}/{p}", timeout_ms)
            parts.append(base64.b64decode(s))
        # all ranks have read everything once all have passed this
        # barrier; deleting one's own key is then race-free
        barrier(f"{tag}-gc")
        client.key_value_delete(f"{key}/{pid}")
        return parts


def _kv_client():
    from jax._src import distributed
    return distributed.global_state.client


def allreduce(values, op: str = "sum", site: str = "ar") -> np.ndarray:
    """Sum/mean/max a host scalar or vector across processes (reference
    ps_allreduce, include/utils.h:163-197: push to a shared PS key, barrier,
    pull). Single-process: returns the input unchanged (as float64 array).
    Rides the coordinator KV store — never a device collective (see
    _kv_gather for why that would deadlock).

    Contract: ONE caller thread per `site`, same per-site call order on
    every process (see _kv_gather). Callers that may run concurrently
    with other allreduces (e.g. a guard thread vs an eval merge) must
    pass their own `site` tag. Payloads are dtype/shape-framed, so ranks
    disagreeing on the array layout fail loudly instead of silently
    reinterpreting bytes (ADVICE r5 #2)."""
    import jax
    if op not in ("sum", "mean", "max"):
        raise ValueError(f"unknown allreduce op {op}")
    arr = np.atleast_1d(np.asarray(values, dtype=np.float64))
    if jax.process_count() == 1:
        return arr
    from ..obs.metrics import timed
    with timed("collective.allreduce_wait_s"):
        if _kv_client() is None:  # no coordination service: last resort
            from jax.experimental import multihost_utils
            gathered = np.asarray(multihost_utils.process_allgather(arr))
        else:
            parts = _kv_gather(site, _pack_array(arr))
            gathered = np.stack([
                _unpack_array(b, arr, f"allreduce[{site}] rank {p}")
                for p, b in enumerate(parts)])
    return {"sum": gathered.sum, "mean": gathered.mean,
            "max": gathered.max}[op](axis=0)


def broadcast(values, root: int = 0, site: str = "bc") -> np.ndarray:
    """Broadcast a host array from `root` to all processes (worker-0
    initialization across hosts). KV-store transport, same rationale and
    single-caller-thread-per-site contract as allreduce; one
    root-published key, O(P) coordinator messages. The payload carries
    the root's dtype/shape, so a root/non-root template mismatch — even
    with coincidentally equal nbytes (int64 vs float64) — raises instead
    of silently reinterpreting bytes (ADVICE r5 #2)."""
    import base64
    import jax
    arr = np.asarray(values)
    if jax.process_count() == 1:
        return arr
    client = _kv_client()
    if client is None:  # no coordination service: last resort only
        from jax.experimental import multihost_utils
        return np.asarray(multihost_utils.broadcast_one_to_all(
            arr, is_source=jax.process_index() == root)).copy()
    with _exclusive(f"kv/{site}"):
        seq = _next_seq(f"kv/{site}")
        key = f"adapm/{site}/{seq}"
        if jax.process_index() == root:
            client.key_value_set(
                key, base64.b64encode(_pack_array(arr)).decode())
        raw = base64.b64decode(client.blocking_key_value_get(key, 600_000))
        barrier(f"{site}-gc")
        if jax.process_index() == root:
            client.key_value_delete(key)
    return _unpack_array(raw, arr, f"broadcast[{site}]")


# NOTE: an earlier draft exposed intent_summary_allgather here for a
# planner-side global interest exchange. The implemented design keeps the
# reference's shape instead: interest is tracked OWNER-side as per-key
# process bitmasks updated by intent/unsub traffic (parallel/pm.py
# GlobalPM.interest — the node_intent sets of sync_manager.h:182, 571,
# 644), so no allgather is needed on the decision path.
