"""GlobalPM: the cross-process parameter manager.

This wires the DCN data channel (parallel/dcn.py) into the Server so that N
launched processes form ONE parameter manager, the way the reference's nodes
do (SURVEY.md §1):

  - The key space is partitioned over `P * S_local` global shards
    (Addressbook multi-process init); keys whose home lands on another
    process carry `owner == REMOTE` locally.
  - Pull/Push/Set of remotely-owned keys ride `DcnChannel.request` to the
    owner process. Where the reference *forwards* server-side when the
    target no longer owns a key (coloc_kv_server.h:455-476), here the
    server replies with a redirect hint and the REQUESTER retries — same
    number of network hops, but handler threads never issue nested
    requests, so two processes serving each other can never deadlock on
    their per-peer channel locks.
  - Every reply carries the authoritative owner per served key, feeding
    per-process **location caches** (reference addressbook.h:114-133;
    `NOT_CACHED` sentinel; honored `--sys.location_caches`): with caches
    on, the second access to a relocated key takes one hop; with caches
    off, requests route via the key's manager (home process) every time.
  - Intent on a remote key asks the owner to decide **relocate vs
    replicate** (reference sync_manager.h:624-644): relocate iff no *other*
    process and no owner-local worker holds interest; the owner tracks
    interest as a per-key bitmask of subscribed processes (the reference's
    per-sender node_intent sets, sync_manager.h:182, 571, 644).
  - Ownership transfers carry **relocation counters**; the key's manager
    accepts owner updates only with a newer counter, rejecting stale moves
    (reference addressbook.h:92-102).
  - Cross-process replicas live in the local cache/delta pools like local
    ones; sync rounds extract delta rows, ship them to the owner, and
    install the returned fresh value as the new base while subtracting
    exactly the shipped delta — a local read observes base+delta
    throughout, so a worker's own pushes never transiently vanish (the
    reference keeps `val` intact and advances `sync_state`,
    handle.h:601-662).

Locking discipline: device/table mutations happen under `server._lock`;
DCN round-trips NEVER happen while holding it (a peer's handler needs its
own lock to serve us). Handler threads take only `server._lock` and issue
no blocking requests (the manager notification is dispatched to the
executor), so the request graph is acyclic.
"""
from __future__ import annotations

import time
from concurrent.futures import Future, ThreadPoolExecutor
from contextlib import contextmanager
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..base import NOT_CACHED, MgmtTechniques
from . import control
from .dcn import DcnChannel
from ..utils.log import alog

# client-side redirect-retry budget: transient misses (a request racing an
# ownership transfer) resolve within a hop or two once the adoption lands;
# later tries back off to give it time
MAX_TRIES = 64


def executor_widths(opts) -> Tuple[int, int]:
    """(read-pool, write-pool) worker counts from --sys.dcn_threads
    (reference --sys.zmq_threads analog): pulls may block on write futures,
    so writes get a separate, never-starved pool."""
    nt = max(1, int(opts.dcn_threads))
    return nt, max(2, nt // 2)


def _offsets(lens: np.ndarray) -> np.ndarray:
    offs = np.zeros(len(lens) + 1, dtype=np.int64)
    np.cumsum(lens, out=offs[1:])
    return offs


def _uniform(lens: np.ndarray) -> Optional[int]:
    return int(lens[0]) if len(lens) and (lens == lens[0]).all() else None


def _ragged_arange(lens: np.ndarray) -> np.ndarray:
    """concat([arange(l) for l in lens]) without the Python loop."""
    offs = _offsets(lens)
    return np.arange(offs[-1]) - np.repeat(offs[:-1], lens)


def _select_flat(flat: np.ndarray, offs: np.ndarray, lens: np.ndarray,
                 pos: np.ndarray) -> np.ndarray:
    """Extract the value segments of key positions `pos` from a flat concat
    buffer over the full key batch."""
    if len(pos) == 0:
        return np.empty(0, dtype=np.float32)
    u = _uniform(lens)
    if u is not None:
        return np.ascontiguousarray(flat.reshape(-1, u)[pos]).ravel()
    # mixed lengths: one repeat-based index build, no per-key loop
    sub = lens[pos]
    idx = np.repeat(offs[pos], sub) + _ragged_arange(sub)
    return flat[idx]


def _fill_flat(out: np.ndarray, offs: np.ndarray, lens: np.ndarray,
               pos: np.ndarray, part: np.ndarray) -> None:
    """Write `part` (flat concat for positions `pos`) into the right
    segments of `out` (flat concat for the full batch)."""
    if len(pos) == 0:
        return
    u = _uniform(lens)
    if u is not None:
        out.reshape(-1, u)[pos] = part.reshape(len(pos), u)
        return
    sub = lens[pos]
    idx = np.repeat(offs[pos], sub) + _ragged_arange(sub)
    out[idx] = part


class GlobalPM:
    """One per Server when `jax.process_count() > 1`."""

    def __init__(self, server, node=None):
        self.server = server
        # The node abstraction (net/port.py NetNode): identity, channel
        # factory, barriers, liveness. Default DcnNode = byte-identical
        # pre-NetPort behavior; a LoopbackNode runs the SAME code paths
        # fully in-process (tests, storms, failover drills).
        if node is None:
            from ..net.port import DcnNode
            node = DcnNode(server.opts)
        self.node = node
        self.pid = node.pid
        self.num_procs = node.num_procs
        assert self.num_procs <= 64, \
            "interest bitmask is uint64 (one bit per process)"
        self._gs = server.num_shards * self.num_procs
        K = server.num_keys

        home = self.home_proc(np.arange(K, dtype=np.int64))
        # owner_hint[k]: authoritative current owner for keys managed here
        # (home == pid; maintained via counter-checked owner updates and our
        # own transfers); elsewhere a location-cache hint, NOT_CACHED when
        # caches are off or nothing has been learned yet
        if server.opts.location_caches:
            self.owner_hint = home.astype(np.int32)  # initially owner==home
        else:
            self.owner_hint = np.where(home == self.pid, home,
                                       NOT_CACHED).astype(np.int32)
        # dual-role relocation counters (reference addressbook.h:92-102):
        # at the key's owner, the current counter (travels with ownership);
        # at its manager, the newest counter seen (staleness filter)
        self.reloc = np.zeros(K, dtype=np.int32)
        # at the owner: bit p set = process p holds a replica of the key
        self.interest = np.zeros(K, dtype=np.uint64)

        import os as _os
        self._dbg = None
        if _os.environ.get("ADAPM_DEBUG_APPLIES"):
            self._dbg = {"sent": np.zeros(K), "served": np.zeros(K)}

        self.stats = {"pulls_in": 0, "pushes_in": 0, "redirects": 0,
                      "intents_in": 0, "relocations_out": 0,
                      "relocations_in": 0, "replicas_granted": 0,
                      "syncs_in": 0, "keys_synced_out": 0}
        # registry counters (obs): ownership transfers the manager
        # ACCEPTED vs REJECTED as stale by relocation counter — the
        # per-round planner-churn signal metrics_snapshot()'s pm section
        # carries alongside the relocations/replications counts above
        self._c_ou_acc = server.obs.counter("pm.owner_updates_accepted")
        self._c_ou_stale = server.obs.counter(
            "pm.owner_updates_rejected_stale")
        # hop histogram: keys SERVED at try 1 / 2 / 3+ of the redirect-
        # retry driver (the reference prints a refresh hop histogram,
        # sync_manager.h:504-519; hops==1 means the location cache or
        # manager pointed straight at the owner)
        self.hops = np.zeros(3, dtype=np.int64)
        # guards hops/stats increments from concurrent _drive invocations
        # (_exec_r threads) and serve-pool handlers: numpy/int in-place
        # adds are not atomic, so unguarded counts silently undercount
        import threading as _threading
        self._stats_lock = _threading.Lock()

        # Serializes "delta in flight" windows: a cross-process sync round
        # holds its keys' locks across extract -> ship -> refresh;
        # anything that CONSUMES a replica's pending delta (adoption's
        # replica->owner upgrade, Set's replica invalidation) must take
        # them first — otherwise the consumed delta double-applies when
        # the in-flight round lands at the (possibly now-local) owner.
        # ONE LOCK PER SYNC CHANNEL (keys partition by the Knuth hash),
        # so per-channel sync rounds overlap their DCN round-trips
        # (VERDICT r4 item 9; the reference runs C sync threads
        # concurrently, coloc_kv_server.h:100-105). Lock order: delta
        # locks in CHANNEL ORDER, all BEFORE server._lock; handler
        # threads never take them.
        import threading
        self._delta_locks = [threading.Lock()
                             for _ in range(server.opts.channels)]
        self._all_channels = tuple(range(server.opts.channels))

        # separate pools: pull tasks may block on write futures, so writes
        # must never queue behind blocked pulls. Widths follow
        # --sys.dcn_threads (reference --sys.zmq_threads analog), which
        # also sizes the channel's serve pool (handler concurrency)
        nr, nw = executor_widths(server.opts)
        self.chan = node.make_channel(self._handle, serve_threads=nr)
        self.chan.start()
        self._exec_r = ThreadPoolExecutor(max_workers=nr,
                                          thread_name_prefix="adapm-pm-r")
        self._exec_w = ThreadPoolExecutor(max_workers=nw,
                                          thread_name_prefix="adapm-pm-w")
        # fan-out pool for _drive's concurrent per-destination round trips.
        # Dedicated (never _exec_r/_exec_w): its tasks only block on
        # channel futures and never submit back into it, so it cannot
        # deadlock even when _drive itself runs on _exec_r
        self._exec_fan = ThreadPoolExecutor(max_workers=max(2, nr),
                                            thread_name_prefix="adapm-pm-f")
        # BSP collective sync engine (--sys.collective_sync): replica
        # delta/fresh rows ride device all-to-all at WaitSync/quiesce
        # points instead of DCN RPC (parallel/collective.py)
        self.coll = None
        if server.opts.collective_sync:
            if node.kind != "dcn":
                raise ValueError(
                    "--sys.collective_sync requires the dcn backend "
                    "(device collectives are meaningless on the "
                    f"in-process {node.kind!r} fabric)")
            from .collective import CollectiveSync
            self.coll = CollectiveSync(self, server.opts.collective_bucket)
        node.barrier("pm-up")

    @contextmanager
    def delta_window(self, channels=None):
        """Context manager holding the delta-in-flight locks for the given
        channel ids (None = all), acquired in channel order."""
        cs = self._all_channels if channels is None \
            else sorted(set(int(c) for c in channels))
        held = []
        try:
            for c in cs:
                lk = self._delta_locks[c]
                lk.acquire()
                held.append(lk)
            yield
        finally:
            for lk in reversed(held):
                lk.release()

    def delta_window_for(self, keys: np.ndarray):
        """delta_window over exactly the channels the keys hash to
        (core.sync.key_channel — the partition the sync rounds use)."""
        from ..core.sync import key_channel
        if len(keys) == 0:
            return self.delta_window(())
        return self.delta_window(
            np.unique(key_channel(np.asarray(keys, dtype=np.int64),
                                  len(self._delta_locks))))

    # -- partition helpers ---------------------------------------------------

    def home_proc(self, keys: np.ndarray) -> np.ndarray:
        """Manager process of each key: global home shard // S_local
        (reference manager = key % num_servers, addressbook.h:110-112)."""
        return (keys % self._gs) // self.server.num_shards

    def _route_dest(self, keys: np.ndarray) -> np.ndarray:
        """Best-known destination process per key: location hint if cached,
        else the manager (which redirects to the owner it has on record).
        dest == self is legitimate: a key may have been adopted locally
        after the caller classified it as remote — _drive serves those
        through the local handler, which owns the truth."""
        hint = self.owner_hint[keys]
        home = self.home_proc(keys)
        return np.where(hint >= 0, hint, home).astype(np.int64)

    def _learn(self, keys: np.ndarray, owners: np.ndarray) -> None:
        """Update location caches from reply traffic (reference
        addressbook.h:114-133, coloc_kv_worker.h:880-884). Manager entries
        are authoritative and only move via counter-checked owner updates."""
        if not self.server.opts.location_caches or len(keys) == 0:
            return
        mask = self.home_proc(keys) != self.pid
        self.owner_hint[keys[mask]] = owners[mask]

    def _hint_for(self, keys: np.ndarray) -> np.ndarray:
        """Redirect hints for keys we do not own: our best owner knowledge
        (authoritative for keys managed here), NOT_CACHED when unknown."""
        h = self.owner_hint[keys].copy()
        return np.where(h == self.pid, NOT_CACHED, h).astype(np.int32)

    # -- the redirect-retry driver ------------------------------------------

    def _drive(self, keys: np.ndarray,
               make_msg: Callable[[np.ndarray, np.ndarray], tuple],
               serve_local: Callable[[tuple], tuple],
               merge: Callable[[tuple, np.ndarray], np.ndarray],
               what: str) -> None:
        """Send per-destination requests for `keys`, retrying unserved keys
        at the redirect hint (or their manager). `make_msg(ks, pos)` builds
        the request for a destination (pos = positions into `keys`);
        `serve_local(msg)` handles the dest==self case; `merge(reply, pos)`
        consumes a reply and returns the per-key owner/hint array (>= 0 and
        served, or a hint/NOT_CACHED for unserved keys — unserved is
        signaled by reply[0], the served mask)."""
        pending = np.arange(len(keys), dtype=np.int64)
        dest = self._route_dest(keys)
        tries = 0
        while len(pending):
            tries += 1
            if tries > MAX_TRIES:
                raise RuntimeError(
                    f"{what}: ownership metadata did not converge for keys "
                    f"{keys[pending][:5].tolist()}...")
            if tries > 2:
                with self._stats_lock:
                    self.stats["redirects"] += len(pending)
                time.sleep(min(0.002 * tries, 0.1))
            still: List[np.ndarray] = []
            # freeze this round's grouping: redirect handling below mutates
            # `dest`, and re-evaluating dest[pending] mid-loop would let a
            # key redirected out of an earlier group be served by a later
            # group in the SAME round and then retried next round — a
            # double apply (caught by tests/mp_bisect.py reloc_only)
            dcur = dest[pending].copy()
            groups = [(int(d), pending[dcur == d]) for d in np.unique(dcur)]
            # fan out: all remote destinations' round-trips overlap (the
            # channel demuxes by request id; pre-r4 each destination's RTT
            # was paid serially — reference SyncManager channels run in C
            # parallel threads, coloc_kv_server.h:100-105). Merging stays
            # on this thread: merge() writes shared buffers.
            futs = {}
            n_remote_groups = sum(1 for d, _ in groups if d != self.pid)
            if n_remote_groups > 1:  # single dest: no pool hop needed
                for d, pos in groups:
                    if d != self.pid:
                        futs[d] = self._exec_fan.submit(
                            self.chan.request, d, make_msg(keys[pos], pos))
            try:
                for d, pos in groups:
                    if d in futs:
                        reply = futs.pop(d).result()
                    else:
                        msg = make_msg(keys[pos], pos)
                        reply = serve_local(msg) if d == self.pid \
                            else self.chan.request(d, msg)
                    served = reply[0].astype(bool)
                    owners = merge(reply, pos)
                    with self._stats_lock:
                        self.hops[min(tries, 3) - 1] += int(served.sum())
                    self._learn(keys[pos][served], owners[served])
                    uns = pos[~served]
                    if len(uns):
                        hint = owners[~served]
                        home = self.home_proc(keys[uns])
                        # hint == self means an adoption by our own
                        # planner is in flight; keep routing to the local
                        # handler until it lands (the retry backoff gives
                        # it time)
                        dest[uns] = np.where(hint >= 0, hint, home)
                        still.append(uns)
            except BaseException:
                # A failed destination must not leave sibling in-flight
                # requests half-done: they were already SERVED remotely
                # (deltas merged at owners, intents registered), so drain
                # their replies before propagating — the caller sees one
                # failure, not silent remote/local divergence. Replies
                # drained here are discarded; _drive failures are fatal
                # to the op, and the retry path re-resolves ownership.
                for d, f in futs.items():
                    try:
                        f.result(timeout=30.0)
                        alog(f"pm{self.pid}: {what}: drained reply from "
                             f"{d} after sibling failure (discarded)")
                    except Exception as e2:
                        alog(f"pm{self.pid}: {what}: drain of {d} also "
                             f"failed: {e2!r}")
                raise
            pending = np.concatenate(still) if still \
                else np.empty(0, dtype=np.int64)

    # -- inbound dispatch ----------------------------------------------------

    def _handle(self, msg):
        op = msg[0]
        if op == "pull":
            return self._serve_pull(msg)
        if op in ("push", "set"):
            return self._serve_write(msg)
        if op == "intent":
            return self._serve_intent(msg)
        if op == "sync":
            return self._serve_sync(msg)
        if op == "unsub":
            return self._serve_unsub(msg)
        if op == "owner_update":
            return self._serve_owner_update(msg)
        raise ValueError(f"unknown DCN op {op!r}")

    # -- pull ---------------------------------------------------------------

    def _serve_pull(self, msg):
        """Serve the keys we own; hint the rest. Reply:
        (served u8[n], vals f32 flat[n], owners i32[n])."""
        _, keys = msg
        srv = self.server
        keys = np.asarray(keys, dtype=np.int64)
        lens = srv.value_lengths[keys]
        offs = _offsets(lens)
        out = np.zeros(offs[-1], dtype=np.float32)
        owners = np.empty(len(keys), dtype=np.int32)
        with self._stats_lock:
            self.stats["pulls_in"] += len(keys)
        with srv._lock:
            owned = srv.ab.owner[keys] >= 0
            pos = np.nonzero(owned)[0]
            if len(pos):
                _fill_flat(out, offs, lens, pos,
                           srv._read_owned_flat(keys[pos]))
                owners[pos] = self.pid
        rem = np.nonzero(~owned)[0]
        if len(rem):
            owners[rem] = self._hint_for(keys[rem])
        return owned.astype(np.uint8), out, owners

    def request_pull(self, keys: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        """Fetch current values of remotely-owned keys (synchronous).
        Returns (flat values, owners)."""
        lens = self.server.value_lengths[keys]
        offs = _offsets(lens)
        out = np.empty(offs[-1], dtype=np.float32)
        owners = np.empty(len(keys), dtype=np.int32)

        def merge(reply, pos):
            served, vals, own = reply[0].astype(bool), reply[1], reply[2]
            sub_lens = lens[pos]
            sub_offs = _offsets(sub_lens)
            spos = np.nonzero(served)[0]
            _fill_flat(out, offs, lens, pos[spos],
                       _select_flat(vals, sub_offs, sub_lens, spos))
            owners[pos[spos]] = own[spos]
            return own

        self._drive(keys, lambda ks, pos: ("pull", ks),
                    self._serve_pull, merge, "pull")
        return out, owners

    def pull_async(self, keys: np.ndarray,
                   after: Sequence[Future] = ()) -> Future:
        """Async pull of remote keys; `after` futures (this worker's
        outstanding remote writes) complete first, preserving
        read-your-writes across the channel."""
        after = list(after)

        def task():
            for f in after:
                f.result()
            flat, _ = self.request_pull(keys)
            return flat

        return self._exec_r.submit(task)

    # -- push / set ---------------------------------------------------------

    def _serve_write(self, msg):
        """Apply push/set to keys we own; hint the rest. Reply:
        (served u8[n], owners i32[n])."""
        op, keys, flat = msg
        is_set = op == "set"
        srv = self.server
        keys = np.asarray(keys, dtype=np.int64)
        lens = srv.value_lengths[keys]
        offs = _offsets(lens)
        owners = np.empty(len(keys), dtype=np.int32)
        with self._stats_lock:
            self.stats["pushes_in"] += len(keys)
        with srv._lock:
            owned = srv.ab.owner[keys] >= 0
            pos = np.nonzero(owned)[0]
            if len(pos):
                part = _select_flat(flat, offs, lens, pos)
                srv._apply_remote_write(keys[pos], part, is_set)
                owners[pos] = self.pid
                if self._dbg is not None and not is_set:
                    np.add.at(self._dbg["served"], keys[pos],
                              part[_offsets(lens[pos])[:-1]])
        rem = np.nonzero(~owned)[0]
        if len(rem):
            owners[rem] = self._hint_for(keys[rem])
        return owned.astype(np.uint8), owners

    def request_write(self, keys: np.ndarray, flat: np.ndarray,
                      is_set: bool) -> None:
        lens = self.server.value_lengths[keys]
        offs = _offsets(lens)
        op = "set" if is_set else "push"
        if self._dbg is not None and not is_set:
            np.add.at(self._dbg["sent"], keys, flat[offs[:-1]])

        def make(ks, pos):
            return (op, ks, _select_flat(flat, offs, lens, pos))

        self._drive(keys, make, self._serve_write,
                    lambda reply, pos: reply[1], op)

    def write_async(self, keys: np.ndarray, flat: np.ndarray,
                    is_set: bool, after: Sequence[Future] = ()) -> Future:
        """Async remote write. `after` = the issuing worker's earlier write
        futures: chaining preserves per-worker write order (push-then-set
        must land in that order at the owner). Waiting inside the pool is
        safe: FIFO scheduling means a task only ever waits on
        earlier-submitted tasks, which are running or done."""
        keys = keys.copy()
        flat = np.ascontiguousarray(flat)
        after = list(after)

        def task():
            for f in after:
                f.result()
            self.request_write(keys, flat, is_set)

        return self._exec_w.submit(task)

    # -- intent: the relocate-vs-replicate decision --------------------------

    def _serve_intent(self, msg):
        """Owner side (reference ProcessSyncMessage request branch,
        sync_manager.h:553-739): per key decide relocation vs replication,
        transfer or register, and return current values. Reply:
        (served u8, actions u8, vals f32 flat, counters i32, owners i32)."""
        _, keys, end, req = msg
        srv = self.server
        keys = np.asarray(keys, dtype=np.int64)
        lens = srv.value_lengths[keys]
        offs = _offsets(lens)
        n = len(keys)
        actions = np.zeros(n, dtype=np.uint8)   # 0=replicated, 1=relocated
        out = np.zeros(offs[-1], dtype=np.float32)
        counters = np.zeros(n, dtype=np.int32)
        owners = np.empty(n, dtype=np.int32)
        with self._stats_lock:
            self.stats["intents_in"] += n
        bit = np.uint64(1) << np.uint64(req)
        rel_keys = np.empty(0, dtype=np.int64)
        with srv._lock:
            ab = srv.ab
            owned = ab.owner[keys] >= 0
            pos = np.nonzero(owned)[0]
            if len(pos):
                ko = keys[pos]
                tech = srv.opts.techniques
                if tech == MgmtTechniques.REPLICATION_ONLY:
                    rel_mask = np.zeros(len(ko), dtype=bool)
                elif tech == MgmtTechniques.RELOCATION_ONLY:
                    rel_mask = np.ones(len(ko), dtype=bool)
                else:
                    # relocate iff no OTHER process subscribed and no
                    # owner-local worker interest (active intent or local
                    # replica) — sync_manager.h:624-644
                    other = (self.interest[ko] & ~bit) != 0
                    clocks = srv.shard_min_clocks()
                    ie = srv.sync.intent_end
                    local_act = (ie[:, ko] >= clocks[:, None]).any(axis=0)
                    has_rep = ab.replica_count[ko] > 0
                    rel_mask = ~other & ~local_act & ~has_rep
                rel_keys = ko[rel_mask]
                # forced relocation may move keys that still have local
                # replicas: flush + drop them first so no delta is lost
                if len(rel_keys) and (ab.replica_count[rel_keys] > 0).any():
                    srv._flush_drop_local_replicas(rel_keys)
                _fill_flat(out, offs, lens, pos, srv._read_owned_flat(ko))
                ctr = self.reloc[ko].copy()
                ctr[rel_mask] += 1
                counters[pos] = ctr
                actions[pos] = rel_mask.astype(np.uint8)
                owners[pos] = np.where(rel_mask, req, self.pid)
                if len(rel_keys):
                    with srv._topology_mutation():
                        self.reloc[rel_keys] = ctr[rel_mask]
                        for cid, cpos in srv._group_by_class(rel_keys):
                            if srv.tier is not None:
                                # release the abandoned slots' residency
                                # (hot rows freed without copy-back: the
                                # authoritative values were read into
                                # `out` above) BEFORE the slots return
                                # to the allocator
                                from ..tier.promote import release_rows
                                ks = rel_keys[cpos]
                                release_rows(srv.stores[cid],
                                             ab.owner[ks], ab.slot[ks])
                            ab.abandon_batch(rel_keys[cpos])
                        self.owner_hint[rel_keys] = req
                        self.interest[rel_keys] = 0
                        self.stats["relocations_out"] += len(rel_keys)
                rep_keys = ko[~rel_mask]
                if len(rep_keys):
                    self.interest[rep_keys] |= bit
                    self.stats["replicas_granted"] += len(rep_keys)
        # notify managers of the transfers — from the executor, not this
        # handler thread (handlers must never block on requests); the
        # counter check makes late arrival harmless
        if len(rel_keys):
            mgr = self.home_proc(rel_keys)
            ctr_rel = self.reloc[rel_keys]
            for d in np.unique(mgr):
                if d in (self.pid, req):
                    continue  # both already hold the new owner
                m = mgr == d
                self._exec_w.submit(self._notify_manager, int(d),
                                    rel_keys[m], req, ctr_rel[m])
        rem = np.nonzero(~owned)[0]
        if len(rem):
            owners[rem] = self._hint_for(keys[rem])
        return owned.astype(np.uint8), actions, out, counters, owners

    def _notify_manager(self, dest: int, keys, new_owner, counters):
        try:
            self.chan.request(dest, ("owner_update", keys, new_owner,
                                     counters))
        except Exception:  # noqa: BLE001 — counters make retries optional
            from ..utils import alog
            alog(f"[pm] owner_update to {dest} failed "
                 f"({len(keys)} keys); manager hint remains stale")

    def intent_remote(self, keys: np.ndarray, shard: int, end: int) -> None:
        """Requester side: act on an intent for remotely-owned keys — ask
        each owner to relocate or replicate, then install the outcome
        locally. Called from the planner (SyncManager._register) and the
        miss path (Server.ensure_local)."""
        with self.delta_window_for(keys):  # adoption consumes deltas
            self._intent_remote_locked(keys, shard, end)

    def _intent_remote_locked(self, keys, shard, end) -> None:
        srv = self.server
        # writes completed before this point are applied at their owners,
        # so the owner's base snapshot during this RPC will include them;
        # anything still pending (or submitted during the RPC) stays in
        # _rw_pending and blocks installation of that key's replica below
        with srv._lock:
            srv._prune_rw_pending()
        lens = srv.value_lengths[keys]
        offs = _offsets(lens)
        n = len(keys)
        actions = np.zeros(n, dtype=np.uint8)
        flat = np.empty(offs[-1], dtype=np.float32)
        counters = np.zeros(n, dtype=np.int32)

        def merge(reply, pos):
            served = reply[0].astype(bool)
            act, vals, ctr, own = reply[1], reply[2], reply[3], reply[4]
            sub_lens = lens[pos]
            sub_offs = _offsets(sub_lens)
            spos = np.nonzero(served)[0]
            actions[pos[spos]] = act[spos]
            _fill_flat(flat, offs, lens, pos[spos],
                       _select_flat(vals, sub_offs, sub_lens, spos))
            counters[pos[spos]] = ctr[spos]
            return own

        self._drive(keys, lambda ks, pos: ("intent", ks, end, self.pid),
                    self._serve_intent, merge, "intent")
        rel = np.nonzero(actions == 1)[0]
        rep = np.nonzero(actions == 0)[0]
        if len(rel):
            self._adopt(keys[rel], _select_flat(flat, offs, lens, rel),
                        counters[rel], shard)
        if len(rep):
            self._install_replicas(
                keys[rep], _select_flat(flat, offs, lens, rep), shard)

    def _adopt(self, keys: np.ndarray, flat: np.ndarray,
               counters: np.ndarray, shard: int) -> None:
        """Take ownership of relocated keys: merge any pending local replica
        deltas (replica -> owner upgrade, reference
        refreshUpgradeReplicaUnsafe handle.h:776-840), then install the rows
        as main copies on `shard`."""
        srv = self.server
        from ..core.store import OOB
        lens = srv.value_lengths[keys]
        offs = _offsets(lens)
        with srv._lock, srv._topology_mutation():
            self.reloc[keys] = counters
            self.owner_hint[keys] = self.pid
            ab = srv.ab
            for cid, pos in srv._group_by_class(keys):
                ks = keys[pos]
                L = srv.class_lengths[cid]
                rows = np.array(
                    _select_flat(flat, offs, lens, pos).reshape(-1, L))
                for s in range(srv.num_shards):
                    cs = ab.cache_slot[s, ks]
                    has = cs >= 0
                    if not has.any():
                        continue
                    d = srv.stores[cid].read_rows(
                        "delta", np.full(int(has.sum()), s, np.int32),
                        cs[has].astype(np.int32))
                    rows[has] += d
                    dropped = ks[has]
                    srv.sync.replica_discard(dropped, s)
                    ab.drop_replicas(dropped, s)
                shards, slots = ab.adopt_batch(ks, shard)
                nk = len(ks)
                srv.stores[cid].set_rows(
                    shards.astype(np.int32), slots.astype(np.int32),
                    rows, np.zeros(nk, np.int32), np.full(nk, OOB, np.int32))
            self.stats["relocations_in"] += len(keys)
            srv.sync.stats.add(relocations=len(keys))
            if srv.tracer is not None:
                from ..utils.stats import RELOCATE
                srv.tracer.record(keys, RELOCATE, shard)

    def _install_replicas(self, keys: np.ndarray, flat: np.ndarray,
                          shard: int) -> None:
        """Install replicas of remote-owned keys on local `shard` with the
        owner-provided base values."""
        srv = self.server
        lens = srv.value_lengths[keys]
        offs = _offsets(lens)
        surplus: List[np.ndarray] = []
        with srv._lock:
            ab = srv.ab
            # keys with an in-flight remote write: the owner's base
            # snapshot may predate the write landing, so installing it
            # would let a local read miss the worker's own push. Defer —
            # the key stays remote and a later intent drain retries.
            blocked = srv._rw_blocked_keys()
            with srv._topology_mutation() as tm:
                installed = 0
                for cid, pos in srv._group_by_class(keys):
                    ks = keys[pos]
                    # an earlier entry in the same drain may have
                    # replicated (or adopted) some of these already
                    fresh = (ab.cache_slot[shard, ks] < 0) & \
                        (ab.owner[ks] < 0)
                    if blocked is not None:
                        bl = np.isin(ks, blocked)
                        # only keys that WOULD have been installed are
                        # deferred + unsubscribed; keys already
                        # replicated/adopted keep their registration
                        # (unsub would orphan them)
                        skipped = ks[fresh & bl]
                        if len(skipped):
                            surplus.append(skipped)
                        fresh &= ~bl
                    ks, pos = ks[fresh], pos[fresh]
                    if len(ks) == 0:
                        continue
                    L = srv.class_lengths[cid]
                    cs = ab.add_replicas(ks, shard)
                    took = ks[: len(cs)]
                    if len(took):
                        installed += len(took)
                        rows = _select_flat(flat, offs, lens,
                                            pos[: len(cs)]).reshape(-1, L)
                        srv.stores[cid].install_replica_rows(
                            np.full(len(took), shard, np.int32),
                            cs.astype(np.int32), rows)
                        srv.sync.replica_add(took, shard)
                        srv.sync.stats.add(replicas_created=len(took))
                        if srv.tracer is not None:
                            from ..utils.stats import REPLICA_SETUP
                            srv.tracer.record(took, REPLICA_SETUP, shard)
                    if len(cs) < len(ks):  # cache pool full
                        surplus.append(ks[len(cs):])
                if installed == 0:
                    tm.cancel()  # everything deferred or pool-full
        if surplus:
            # the owner registered our interest for keys we could not host:
            # unsubscribe so they stay relocatable
            self.unsub(np.concatenate(surplus))

    def failover_dead_peer(self, dead: int):
        """Dead-peer failover (net/membership.py drives this exactly
        once per death): promote every LOCAL replica of a key the dead
        rank owned to a main copy via the same replica->owner upgrade
        relocation uses (_adopt — pending sync deltas merge, counters
        bump, addressbook adopts under _topology_mutation). Keys the
        corpse owned with no replica here are LOST: their owner hint
        keeps pointing at the corpse, so reads fail fast with
        NetPeerDeadError instead of hanging. Returns (promoted, lost).

        Lock order: delta locks (channel order) -> server._lock, same
        as every other delta consumer — the beat thread that calls this
        holds nothing else, so the sentinel stays green."""
        srv = self.server
        keys_all = np.arange(srv.num_keys, dtype=np.int64)
        home = self.home_proc(keys_all)
        # believed owned by the corpse: an explicit hint, or unlearned
        # keys whose manager is the corpse (hint still at NOT_CACHED)
        dead_owned = (self.owner_hint == dead) | \
            ((self.owner_hint == NOT_CACHED) & (home == dead))
        promoted = 0
        with srv._lock:
            # stop sync rounds from shipping deltas at the corpse
            self.interest &= ~np.uint64(1 << dead)
            ab = srv.ab
            cand = keys_all[dead_owned & (ab.owner[keys_all] < 0)]
            # shard hosting each candidate's replica (-1 = none = lost)
            rep_shard = np.full(len(cand), -1, np.int32)
            for s in range(srv.num_shards):
                has = (rep_shard < 0) & (ab.cache_slot[s, cand] >= 0)
                rep_shard[has] = s
        for s in range(srv.num_shards):
            keys = cand[rep_shard == s]
            if len(keys) == 0:
                continue
            lens = srv.value_lengths[keys]
            offs = _offsets(lens)
            flat = np.zeros(offs[-1], dtype=np.float32)
            with self.delta_window_for(keys):
                # replica BASE rows under the delta window: an in-flight
                # refresh (which holds these locks across its round
                # trip) can never land between this read and the adopt
                with srv._lock:
                    for cid, pos in srv._group_by_class(keys):
                        ks = keys[pos]
                        cs = ab.cache_slot[s, ks]
                        live = cs >= 0
                        if not live.any():
                            continue
                        rows = srv.stores[cid].read_rows(
                            "cache", np.full(int(live.sum()), s,
                                             np.int32),
                            cs[live].astype(np.int32))
                        _fill_flat(flat, offs, lens, pos[live],
                                   rows.ravel())
                self._adopt(keys, flat, self.reloc[keys] + 1, int(s))
            promoted += len(keys)
        lost = int((rep_shard < 0).sum())
        return promoted, lost

    # -- cross-process sync rounds ------------------------------------------

    def _serve_sync(self, msg):
        """Owner side of a replica refresh: merge shipped deltas into the
        main copies, return fresh values (reference owner branch of
        ProcessSyncMessage, sync_manager.h:553-739). Reply:
        (served u8, vals f32 flat, owners i32)."""
        _, keys, flat, req = msg
        srv = self.server
        keys = np.asarray(keys, dtype=np.int64)
        lens = srv.value_lengths[keys]
        offs = _offsets(lens)
        out = np.zeros(offs[-1], dtype=np.float32)
        owners = np.empty(len(keys), dtype=np.int32)
        with self._stats_lock:
            self.stats["syncs_in"] += len(keys)
        bit = np.uint64(1) << np.uint64(req)
        with srv._lock:
            owned = srv.ab.owner[keys] >= 0
            pos = np.nonzero(owned)[0]
            if len(pos):
                ko = keys[pos]
                srv._apply_remote_write(
                    ko, _select_flat(flat, offs, lens, pos), is_set=False)
                _fill_flat(out, offs, lens, pos, srv._read_owned_flat(ko))
                owners[pos] = self.pid
                self.interest[ko] |= bit  # defensive (e.g. after restore)
        rem = np.nonzero(~owned)[0]
        if len(rem):
            owners[rem] = self._hint_for(keys[rem])
        return owned.astype(np.uint8), out, owners

    def _request_sync(self, keys: np.ndarray,
                      flat: np.ndarray) -> np.ndarray:
        """Ship deltas to owners, return fresh values (synchronous)."""
        lens = self.server.value_lengths[keys]
        offs = _offsets(lens)
        fresh = np.empty(offs[-1], dtype=np.float32)

        def make(ks, pos):
            return ("sync", ks, _select_flat(flat, offs, lens, pos),
                    self.pid)

        def merge(reply, pos):
            served, vals, own = reply[0].astype(bool), reply[1], reply[2]
            sub_lens = lens[pos]
            sub_offs = _offsets(sub_lens)
            spos = np.nonzero(served)[0]
            _fill_flat(fresh, offs, lens, pos[spos],
                       _select_flat(vals, sub_offs, sub_lens, spos))
            return own

        self._drive(keys, make, self._serve_sync, merge, "sync")
        return fresh

    def sync_replicas(self, keys: np.ndarray, shards: np.ndarray) -> None:
        """One cross-process sync round over local replicas of remote keys
        (parallel key / holder-shard arrays): extract pending deltas,
        ship to owners, install fresh bases. Requester side of the
        reference's startSync/response branch (sync_manager.h:291-382,
        740-799)."""
        with self.delta_window_for(np.asarray(keys, np.int64)):
            self._sync_replicas_locked(keys, shards)

    def _extract_deltas(self, keys: np.ndarray, shards: np.ndarray):
        """Snapshot live replica pairs + their pending delta rows; returns
        None when nothing is live, else the state _install_fresh needs."""
        srv = self.server
        karr = np.ascontiguousarray(keys, dtype=np.int64)
        sarr = np.ascontiguousarray(shards, dtype=np.int32)
        class_rows: Dict[int, tuple] = {}
        with srv._lock:
            # skip replicas dropped/upgraded since the caller's snapshot
            # (a -1 slot would wrap in the device gather)
            ok = srv.ab.cache_slot[sarr, karr] >= 0
            karr, sarr = karr[ok], sarr[ok]
            if len(karr) == 0:
                return None
            lens = srv.value_lengths[karr]
            offs = _offsets(lens)
            shipped = np.empty(offs[-1], dtype=np.float32)
            cs_all = srv.ab.cache_slot[sarr, karr].astype(np.int32)
            for cid, pos in srv._group_by_class(karr):
                rows = srv.stores[cid].read_rows("delta", sarr[pos],
                                                 cs_all[pos])
                class_rows[cid] = (pos, rows)
                _fill_flat(shipped, offs, lens, pos, rows.ravel())
        return karr, sarr, cs_all, class_rows, lens, offs, shipped

    def _install_fresh(self, karr, sarr, cs_all, class_rows, lens, offs,
                       fresh) -> None:
        """Install owner-fresh values as the new replica bases, subtracting
        exactly the shipped deltas (refresh_after_sync)."""
        srv = self.server
        with srv._lock:
            ab = srv.ab
            # the refresh replaces replica bases with owner-fresh values:
            # staged prefetch buffers of these keys go stale
            srv._prefetch_note(karr)
            for cid, (pos, rows) in class_rows.items():
                # replicas may have been dropped/upgraded while the round
                # was in flight; refresh only still-live ones
                cs_now = ab.cache_slot[sarr[pos], karr[pos]].astype(np.int32)
                live = cs_now == cs_all[pos]
                if not live.any():
                    continue
                L = srv.class_lengths[cid]
                srv.stores[cid].refresh_after_sync(
                    sarr[pos][live], cs_now[live],
                    _select_flat(fresh, offs, lens,
                                 pos[live]).reshape(-1, L),
                    rows[live])

    def _sync_replicas_locked(self, keys: np.ndarray,
                              shards: np.ndarray) -> None:
        ext = self._extract_deltas(keys, shards)
        if ext is None:
            return
        karr, sarr, cs_all, class_rows, lens, offs, shipped = ext
        fresh = self._request_sync(karr, shipped)
        self._install_fresh(karr, sarr, cs_all, class_rows, lens, offs,
                            fresh)
        with self._stats_lock:
            self.stats["keys_synced_out"] += len(keys)

    def collective_sync(self, keys: np.ndarray, shards: np.ndarray,
                        quiescing: bool = True) -> bool:
        """BSP replica refresh over device collectives
        (parallel/collective.py): same contract as sync_replicas, but
        EVERY process must call this together (the WaitSync/quiesce
        protocol, or a --sys.collective_cadence clock boundary) — `keys`
        may be empty and the process still joins each exchange. Enabled
        by --sys.collective_sync. Returns True iff every process entered
        this exchange with quiescing=True (the cadence flag loop's
        termination test, core/sync.py)."""
        assert self.coll is not None, "--sys.collective_sync is off"
        with self.delta_window():
            ext = self._extract_deltas(keys, shards)
            if ext is None:
                empty = np.empty(0, dtype=np.int64)
                _, all_q = self.coll.request_sync(
                    empty, np.empty(0, np.float32), empty,
                    quiescing=quiescing)
                return all_q
            karr, sarr, cs_all, class_rows, lens, offs, shipped = ext
            fresh, all_q = self.coll.request_sync(karr, shipped, lens,
                                                  quiescing=quiescing)
            self._install_fresh(karr, sarr, cs_all, class_rows, lens,
                                offs, fresh)
            with self._stats_lock:
                self.stats["keys_synced_out"] += len(karr)
            return all_q

    def collective_pull(self, keys) -> np.ndarray:
        """BSP pull over device collectives: the remaining half of
        SURVEY.md's ICI mapping ("pull misses ride a ragged all-to-all"),
        prototyped on the same exchange engine as collective_sync
        (VERDICT r4 item 4). EVERY process must call this together (keys
        MAY be empty); request keys travel to their owners with a ZERO
        delta (the owner-side merge is a no-op) and the owners' current
        values ride the return exchange. Returns the flat value buffer
        for `keys`.

        Contract differences from Worker.pull, by design of the BSP
        prototype: values are the OWNER's (a local replica's pending
        delta is not folded in), and the owner records requester
        interest for the pulled keys as it does for replica syncs.
        Requires --sys.collective_sync.

        DEADLOCK RULE (applies to every collective_* entry point):
        synchronous RPC data ops (read_main, remote Pull/Push/Set) must
        be separated from the NEXT exchange by a Server.barrier(). A
        rank waiting inside an exchange parks its devices in the
        pending collective; serving a peer's RPC needs a device
        gather, which queues behind it — if that peer is the one being
        waited for, neither side can progress. The barrier is
        device-free, so pending serves drain during it."""
        assert self.coll is not None, "--sys.collective_sync is off"
        keys = np.asarray(keys, dtype=np.int64)
        lens = self.server.value_lengths[keys] if len(keys) \
            else np.empty(0, dtype=np.int64)
        zeros = np.zeros(int(lens.sum()), dtype=np.float32)
        # the sync manager's _coll_lock serializes ALL of this process's
        # exchange joins (cadence boundaries, quiesce flag loops, these
        # entry points): two local threads in request_sync concurrently
        # would interleave their collectives against the peers' single
        # exchange stream
        with self.server.sync._coll_lock:
            fresh, _ = self.coll.request_sync(keys, zeros, lens,
                                              quiescing=False)
        return fresh

    def collective_push(self, keys, vals) -> None:
        """BSP additive push over device collectives (SURVEY.md mapping:
        "push = additive scatter over ICI/DCN"; VERDICT r4 item 4): the
        delta rows travel to their owners through the all-to-all and
        merge there — the exact owner-side apply of a remote Push, with
        the transport swapped. Same collective contract as
        collective_pull (every process joins; keys MAY be empty)."""
        assert self.coll is not None, "--sys.collective_sync is off"
        keys = np.asarray(keys, dtype=np.int64)
        lens = self.server.value_lengths[keys] if len(keys) \
            else np.empty(0, dtype=np.int64)
        flat = np.ascontiguousarray(vals, dtype=np.float32).ravel()
        assert flat.size == int(lens.sum()), \
            f"vals size {flat.size} != keys' total length {lens.sum()}"
        with self.server.sync._coll_lock:  # see collective_pull
            self.coll.request_sync(keys, flat, lens, quiescing=False)

    def drop_replicas(self, keys: np.ndarray, shards: np.ndarray) -> None:
        """Drop local replicas of remote-owned keys (parallel key /
        holder-shard arrays): ship the final delta with the
        unsubscription, then free the slots. Any pushes that land
        between extraction and the free are re-shipped as plain remote
        pushes, so no update is ever lost."""
        with self.delta_window_for(np.asarray(keys, np.int64)):
            self._drop_replicas_locked(keys, shards)

    def _drop_replicas_locked(self, keys: np.ndarray,
                              shards: np.ndarray) -> None:
        srv = self.server
        karr = np.ascontiguousarray(keys, dtype=np.int64)
        sarr = np.ascontiguousarray(shards, dtype=np.int32)
        req_k, req_s = karr, sarr  # the full request (channel discard)
        class_rows: Dict[int, tuple] = {}
        with srv._lock:
            ok = srv.ab.cache_slot[sarr, karr] >= 0
            karr, sarr = karr[ok], sarr[ok]
            if len(karr) == 0:
                return
            lens = srv.value_lengths[karr]
            offs = _offsets(lens)
            shipped = np.empty(offs[-1], dtype=np.float32)
            cs_all = srv.ab.cache_slot[sarr, karr].astype(np.int32)
            for cid, pos in srv._group_by_class(karr):
                rows = srv.stores[cid].read_rows("delta", sarr[pos],
                                                 cs_all[pos])
                class_rows[cid] = (pos, rows)
                _fill_flat(shipped, offs, lens, pos, rows.ravel())
        self.unsub(karr, shipped)
        residue_keys: List[np.ndarray] = []
        residue_flat: List[np.ndarray] = []
        with srv._lock, srv._topology_mutation() as tm:
            dropped_any = False
            ab = srv.ab
            for cid, (pos, rows) in class_rows.items():
                # only replicas whose slot is unchanged since extraction:
                # a concurrent drop/upgrade (e.g. a Set invalidation)
                # already accounted for its own delta
                cs_now = ab.cache_slot[sarr[pos], karr[pos]].astype(np.int32)
                live = cs_now == cs_all[pos]
                pos, rows = pos[live], rows[live]
                if len(pos) == 0:
                    continue
                now = srv.stores[cid].read_rows("delta", sarr[pos],
                                                cs_all[pos])
                rem = now - rows
                nz = np.abs(rem).max(axis=1) > 0
                if nz.any():
                    residue_keys.append(karr[pos][nz])
                    residue_flat.append(rem[nz].ravel())
                for s in np.unique(sarr[pos]):
                    m = sarr[pos] == s
                    ab.drop_replicas(karr[pos][m], int(s))
                    dropped_any = True
                    if srv.tracer is not None:
                        from ..utils.stats import REPLICA_DROP
                        srv.tracer.record(karr[pos][m], REPLICA_DROP,
                                          int(s))
            srv.sync.replica_discard(req_k, req_s)
            if not dropped_any:
                tm.cancel()  # every replica was already dropped/upgraded
        if residue_keys:
            self.request_write(np.concatenate(residue_keys),
                               np.concatenate(residue_flat), is_set=False)

    def unsub(self, keys: np.ndarray,
              flat: Optional[np.ndarray] = None) -> None:
        """Tell owners this process no longer holds replicas of `keys`
        (optionally shipping final deltas)."""
        lens = self.server.value_lengths[keys]
        offs = _offsets(lens)
        if flat is None:
            flat = np.zeros(offs[-1], dtype=np.float32)

        def make(ks, pos):
            return ("unsub", ks, _select_flat(flat, offs, lens, pos),
                    self.pid)

        self._drive(keys, make, self._serve_unsub,
                    lambda reply, pos: reply[1], "unsub")

    def unsub_async(self, keys: np.ndarray,
                    after: Sequence[Future] = ()) -> Future:
        keys = keys.copy()
        after = list(after)

        def task():
            for f in after:
                f.result()
            self.unsub(keys)

        return self._exec_w.submit(task)

    def _serve_unsub(self, msg):
        """Reply: (served u8, owners i32)."""
        _, keys, flat, req = msg
        srv = self.server
        keys = np.asarray(keys, dtype=np.int64)
        lens = srv.value_lengths[keys]
        offs = _offsets(lens)
        owners = np.empty(len(keys), dtype=np.int32)
        bit = np.uint64(1) << np.uint64(req)
        with srv._lock:
            owned = srv.ab.owner[keys] >= 0
            pos = np.nonzero(owned)[0]
            if len(pos):
                ko = keys[pos]
                part = _select_flat(flat, offs, lens, pos)
                if len(part) and np.abs(part).max() > 0:
                    srv._apply_remote_write(ko, part, is_set=False)
                self.interest[ko] &= ~bit
                owners[pos] = self.pid
        rem = np.nonzero(~owned)[0]
        if len(rem):
            owners[rem] = self._hint_for(keys[rem])
        return owned.astype(np.uint8), owners

    # -- manager metadata ----------------------------------------------------

    def _serve_owner_update(self, msg):
        """Manager side: record an ownership transfer, rejecting stale
        updates by relocation counter (reference addressbook.h:92-102)."""
        _, keys, new_owner, counters = msg
        keys = np.asarray(keys, dtype=np.int64)
        assert (self.home_proc(keys) == self.pid).all(), \
            "owner_update sent to a non-manager"
        with self.server._lock:
            newer = counters > self.reloc[keys]
            ks = keys[newer]
            self.owner_hint[ks] = new_owner
            self.reloc[ks] = counters[newer]
        self._c_ou_acc.inc(int(newer.sum()))
        self._c_ou_stale.inc(int(len(keys) - newer.sum()))
        return ("ok",)

    # -- lifecycle -----------------------------------------------------------

    def report(self) -> str:
        s = self.stats
        h = self.hops
        out = (f"pm: pulls_in={s['pulls_in']} pushes_in={s['pushes_in']} "
               f"redirects={s['redirects']} intents_in={s['intents_in']} "
               f"reloc_out={s['relocations_out']} "
               f"reloc_in={s['relocations_in']} "
               f"rep_granted={s['replicas_granted']} "
               f"synced_out={s['keys_synced_out']} "
               f"hops(1/2/3+)={h[0]}/{h[1]}/{h[2]}")
        if self.coll is not None:
            c = self.coll.stats
            out += (f" | coll: rounds={c['rounds']} "
                    f"iters={c['iterations']} rows_out={c['rows_out']} "
                    f"rows_in={c['rows_in']}")
        return out

    def shutdown(self) -> None:
        # Three-step leave-together protocol:
        # 1. pre-down barrier: every rank's planner (sync thread) is
        #    stopped before Server.shutdown reaches here, and a peer's
        #    in-flight request completes before that peer can enter the
        #    barrier — so afterwards no NEW inbound work (and no handler
        #    submits to our executors) can appear.
        # 2. drain our own outbound executors: peers still serve, their
        #    channels stay open until step 3.
        # 3. down barrier, then close the channel.
        # Step 0 (loopback): announce a graceful leave FIRST, so peers'
        # membership planes mark this rank `left` — its beats stopping
        # during teardown must never read as a death (no-op on DCN).
        self.node.pre_down()
        self.node.barrier("pm-pre-down")
        self._exec_r.shutdown(wait=True)
        self._exec_w.shutdown(wait=True)
        self._exec_fan.shutdown(wait=True)
        self.node.barrier("pm-down")
        self.chan.shutdown()
