"""Device mesh construction and sharding helpers.

Replaces the reference's process/topology bootstrap (Postoffice + Van ADD_NODE
rendezvous, src/van.cc:267-357): on TPU the "nodes" are mesh devices, rank
assignment is the mesh order, and the scheduler is `jax.distributed`'s
coordinator (multi-host) or nothing (single host).

The canonical mesh has one axis:
  - "kv": parameter shards (the reference's server dimension). Data-parallel
    workers are co-located with kv shards, mirroring the reference's co-located
    worker+server process model (README.md:161-165).

Model code may build richer meshes (e.g. ("data", "model")) on top; the KV
store only needs "kv".
"""
from __future__ import annotations

import dataclasses
import os
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

KV_AXIS = "kv"


@dataclasses.dataclass
class MeshContext:
    mesh: Mesh

    @property
    def num_shards(self) -> int:
        return self.mesh.shape[KV_AXIS]

    @property
    def devices(self) -> Sequence[jax.Device]:
        return list(self.mesh.devices.flat)

    def shard0(self) -> NamedSharding:
        """Sharding for pool arrays [S, slots, L]: dim 0 over the kv axis."""
        return NamedSharding(self.mesh, P(KV_AXIS))

    def replicated(self) -> NamedSharding:
        if not hasattr(self, "_replicated"):
            self._replicated = NamedSharding(self.mesh, P())
        return self._replicated

    def put_replicated(self, arr):
        """Stage a host array for jitted programs: committed + replicated.
        This is THE staging rule (docs/PERF.md "Host-array staging"): a
        device-0 `jnp.asarray` gets host-resharded by every mesh-compiled
        executable per call, and a bare numpy arg uploads synchronously
        inside dispatch on remote-attached backends; a replicated
        device_put is asynchronous and already in the sharding
        executables expect. Routed through the DevicePort (ISSUE 14) —
        late import: the device plane sits above the mesh layer."""
        from ..device import default_port
        return default_port().put_replicated(arr, self.replicated())


def make_mesh(num_shards: Optional[int] = None,
              devices: Optional[Sequence[jax.Device]] = None) -> MeshContext:
    if devices is None:
        # ADAPM_PLATFORM forces a backend (tests use cpu + virtual devices
        # even when a TPU plugin claimed the default platform). Also make it
        # the *default* backend when possible: remote-attached default
        # backends add per-dispatch round trips even for arrays living on
        # the forced platform's devices.
        platform = os.environ.get("ADAPM_PLATFORM")
        if platform:
            try:
                jax.config.update("jax_platforms", platform)
            except Exception:
                pass  # backends already initialized differently: still
                # usable via the explicit device list below
        if jax.process_count() > 1:
            # multi-host: each process's Server owns pools on ITS devices
            # only (the cross-process plane is the DCN channel + global
            # sync rounds, core/kv.py); jax.devices() would include
            # non-addressable peers
            devices = jax.local_devices()
        else:
            devices = jax.devices(platform) if platform else jax.devices()
    if num_shards is None:
        num_shards = len(devices)
    if num_shards > len(devices):
        raise ValueError(
            f"requested {num_shards} shards but only {len(devices)} devices")
    mesh = Mesh(np.asarray(devices[:num_shards]), (KV_AXIS,))
    return MeshContext(mesh=mesh)


_default_ctx: Optional[MeshContext] = None


def get_mesh_context() -> MeshContext:
    global _default_ctx
    if _default_ctx is None:
        _default_ctx = make_mesh()
    return _default_ctx


def set_mesh_context(ctx: MeshContext) -> None:
    global _default_ctx
    _default_ctx = ctx
