"""Host-side DCN data channel for cross-process parameter traffic.

The reference moves every cross-node byte through its ZeroMQ van
(include/zmq_van.h, src/van.cc). In the TPU design the *hot* data plane is
on-device (intent makes keys local before use; SURVEY.md §2.5), so what
remains for the network is the thin tail the reference also has: misses
(pull/push of keys owned by another process), row fetches for replica
creation/relocation, and delta shipping during sync rounds. Those ride this
channel: one TCP listener per process, peer addresses rendezvoused through
the jax.distributed coordinator's key-value store (the scheduler's
replacement — src/van.cc:40-111 ADD_NODE ↔ key_value_set/get), length-framed
pickle messages (protocol 5: numpy buffers serialize zero-copy).

Concurrency model (the reference multiplexes via ZMQ identity frames + N IO
threads, zmq_van.h:109-112; the analog here is request-id demultiplexing):
every frame carries a request id, a per-peer reader thread resolves replies
to their waiting futures, and the serving side dispatches handler calls to
a small pool and tags each reply with the request's id — so concurrent
requests to the SAME peer overlap instead of queueing head-of-line behind
one another (pre-r4 a per-peer lock held across the full round trip
serialized them). Ordering note: requests from one process to one peer are
NOT serialized; this matches the existing contract — the write executor in
parallel/pm.py is multi-threaded, so cross-process writes were already
unordered, and read-your-writes is enforced above the channel by write
futures (core/kv.py _WaitEntry), never by socket FIFO.

Request handling takes the server lock only around local table/pool
operations — never across a nested channel call — so two processes pulling
from each other cannot deadlock.
"""
from __future__ import annotations

import itertools
import os
import pickle
import socket
import struct
import threading
import time
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Callable, Dict, Optional

_LEN = struct.Struct("!Q")
# benchmark-only latency injection (see _serve.run): emulates a real
# cross-host RTT on loopback so latency-hiding levers are measurable
_EMULATED_RTT_S = float(os.environ.get("ADAPM_DCN_EMULATE_RTT_MS", "0")) \
    / 1e3


def _send_msg(sock: socket.socket, obj) -> None:
    data = pickle.dumps(obj, protocol=5)
    sock.sendall(_LEN.pack(len(data)) + data)


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf.extend(chunk)
    return bytes(buf)


def _recv_msg(sock: socket.socket):
    head = _recv_exact(sock, _LEN.size)
    if head is None:
        return None
    (n,) = _LEN.unpack(head)
    body = _recv_exact(sock, n)
    if body is None:
        return None
    return pickle.loads(body)


class DcnChannel:
    """Request/reply channel between the launcher's processes.

    `handler(msg) -> reply` is called for every incoming request on the
    serve pool. Outgoing `request(peer, msg)` is synchronous for the
    caller (send + await its reply future) but overlaps freely with other
    in-flight requests to the same or other peers.
    """

    def __init__(self, process_id: int, num_processes: int,
                 handler: Callable, serve_threads: int = 4):
        self.pid = process_id
        self.num = num_processes
        self.handler = handler
        self._listener: Optional[socket.socket] = None
        self._peers: Dict[int, socket.socket] = {}
        # held only across sendall (frame atomicity), never across a recv
        self._send_locks: Dict[int, threading.Lock] = {}
        # guards _peers/_send_locks mutation: two threads making first
        # requests to the same peer must agree on one (socket, lock) pair
        self._resolve_lock = threading.Lock()
        self._rid = itertools.count(1)
        self._pending: Dict[int, Future] = {}
        self._pending_lock = threading.Lock()
        # peer -> rids awaiting its reply (failed fast on disconnect)
        self._pending_by_peer: Dict[int, set] = {}
        self._serve_pool = ThreadPoolExecutor(
            max_workers=max(1, serve_threads),
            thread_name_prefix="adapm-dcn-h")
        self._threads = []
        self._stop = threading.Event()

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        from jax._src import distributed
        client = distributed.global_state.client
        assert client is not None, "jax.distributed not initialized"
        self._listener = socket.socket()
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind(("0.0.0.0", 0))
        self._listener.listen(self.num)
        port = self._listener.getsockname()[1]
        host = socket.gethostname()
        client.key_value_set(f"adapm/dcn/{self.pid}", f"{host}:{port}")
        t = threading.Thread(target=self._accept_loop, daemon=True,
                             name="adapm-dcn-accept")
        t.start()
        self._threads.append(t)

    def _resolve(self, peer: int) -> socket.socket:
        with self._resolve_lock:
            sock = self._peers.get(peer)
            if sock is not None:
                return sock
            from jax._src import distributed
            client = distributed.global_state.client
            addr = client.blocking_key_value_get(f"adapm/dcn/{peer}", 60_000)
            host, port = addr.rsplit(":", 1)
            sock = socket.create_connection((host, int(port)), timeout=60)
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._peers[peer] = sock
            self._send_locks[peer] = threading.Lock()
            self._pending_by_peer.setdefault(peer, set())
            t = threading.Thread(target=self._read_replies,
                                 args=(peer, sock), daemon=True,
                                 name=f"adapm-dcn-r{peer}")
            t.start()
            self._threads.append(t)
            return sock

    def _read_replies(self, peer: int, sock: socket.socket) -> None:
        """Demux loop: deliver each tagged reply to its waiting future."""
        try:
            while not self._stop.is_set():
                try:
                    frame = _recv_msg(sock)
                except Exception:  # noqa: BLE001 — a corrupt frame must
                    # still run the death-cleanup below, or every waiter
                    # hangs forever on an unresolved future
                    frame = None
                if frame is None:
                    return  # disconnect: cleanup in finally
                rid, reply = frame
                with self._pending_lock:
                    fut = self._pending.pop(rid, None)
                    self._pending_by_peer.get(peer, set()).discard(rid)
                if fut is not None:
                    fut.set_result(reply)
        finally:
            # disconnect. Remove the dead socket FIRST so new requests
            # re-resolve (a keepalive-restarted peer reconnects; a dead
            # one fails at connect), THEN fail everything still waiting —
            # any rid registered against the old socket after this drain
            # is caught by request()'s post-send liveness check (it
            # observes the socket gone from _peers).
            with self._resolve_lock:
                if self._peers.get(peer) is sock:
                    del self._peers[peer]
            with self._pending_lock:
                rids = self._pending_by_peer.pop(peer, set())
                futs = [self._pending.pop(r) for r in rids
                        if r in self._pending]
            for f in futs:
                if not f.done():
                    f.set_exception(
                        ConnectionError(f"peer {peer} closed the channel"))

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            t = threading.Thread(target=self._serve, args=(conn,),
                                 daemon=True, name="adapm-dcn-serve")
            t.start()
            self._threads.append(t)

    def _serve(self, conn: socket.socket) -> None:
        """Per-connection reader: requests fan out to the serve pool and
        replies return tagged + out-of-order as handlers finish."""
        send_lock = threading.Lock()

        def run(rid, msg):
            if _EMULATED_RTT_S > 0.0:
                # ADAPM_DCN_EMULATE_RTT_MS: benchmark-only latency
                # injection — loopback RTT is pure CPU, so latency-hiding
                # levers (channel overlap, request fan-out) cannot show
                # their effect without it. Never set in production.
                time.sleep(_EMULATED_RTT_S)
            try:
                reply = self.handler(msg)
            except Exception as e:  # noqa: BLE001 - ship errors to requester
                reply = ("error", f"{type(e).__name__}: {e}")
            try:
                with send_lock:
                    _send_msg(conn, (rid, reply))
            except OSError:
                pass  # requester is gone; its future fails on disconnect

        while not self._stop.is_set():
            try:
                frame = _recv_msg(conn)
            except OSError:
                frame = None
            if frame is None:
                try:
                    conn.close()
                except OSError:
                    pass
                return
            rid, msg = frame
            self._serve_pool.submit(run, rid, msg)

    # -- requests ------------------------------------------------------------

    def request(self, peer: int, msg):
        """Synchronous round-trip to `peer`. Raises on remote error.
        Concurrent callers' requests to the same peer are in flight
        simultaneously (demuxed by request id)."""
        assert peer != self.pid, "use local ops, not a self-request"
        sock = self._resolve(peer)
        rid = next(self._rid)
        fut: Future = Future()
        with self._pending_lock:
            self._pending[rid] = fut
            self._pending_by_peer.setdefault(peer, set()).add(rid)
        try:
            with self._send_locks[peer]:
                _send_msg(sock, (rid, msg))
        except OSError as e:
            with self._pending_lock:
                self._pending.pop(rid, None)
                self._pending_by_peer.get(peer, set()).discard(rid)
            raise ConnectionError(f"peer {peer} send failed: {e}") from e
        # liveness check closing the race with the reader's death: if the
        # reader drained pendings BEFORE this rid registered, nothing will
        # ever resolve the future — the reader removes the socket from
        # _peers before draining, so observing it gone (or replaced) here
        # means this rid may have been orphaned.
        if self._peers.get(peer) is not sock:
            with self._pending_lock:
                orphaned = self._pending.pop(rid, None)
                self._pending_by_peer.get(peer, set()).discard(rid)
            if orphaned is not None and not orphaned.done():
                raise ConnectionError(f"peer {peer} closed the channel")
        reply = fut.result()
        if isinstance(reply, tuple) and reply \
                and isinstance(reply[0], str) and reply[0] == "error":
            raise RuntimeError(f"peer {peer}: {reply[1]}")
        return reply

    def shutdown(self) -> None:
        self._stop.set()
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
        # snapshot under the lock: closing a socket wakes its reader
        # thread, whose death-cleanup removes the peer from _peers —
        # iterating the live dict here would race that removal
        with self._resolve_lock:
            socks = list(self._peers.values())
            self._peers.clear()
        for sock in socks:
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                sock.close()
            except OSError:
                pass
        self._serve_pool.shutdown(wait=False)
