"""Host-side DCN data channel for cross-process parameter traffic.

The reference moves every cross-node byte through its ZeroMQ van
(include/zmq_van.h, src/van.cc). In the TPU design the *hot* data plane is
on-device (intent makes keys local before use; SURVEY.md §2.5), so what
remains for the network is the thin tail the reference also has: misses
(pull/push of keys owned by another process), row fetches for replica
creation/relocation, and delta shipping during sync rounds. Those ride this
channel: one TCP listener per process, peer addresses rendezvoused through
the jax.distributed coordinator's key-value store (the scheduler's
replacement — src/van.cc:40-111 ADD_NODE ↔ key_value_set/get), length-framed
pickle messages (protocol 5: numpy buffers serialize zero-copy).

Request handling runs on a per-connection receiver thread and takes the
server lock only around local table/pool operations — never across a nested
channel call — so two processes pulling from each other cannot deadlock.
"""
from __future__ import annotations

import pickle
import socket
import struct
import threading
from typing import Callable, Dict, Optional

_LEN = struct.Struct("!Q")


def _send_msg(sock: socket.socket, obj) -> None:
    data = pickle.dumps(obj, protocol=5)
    sock.sendall(_LEN.pack(len(data)) + data)


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf.extend(chunk)
    return bytes(buf)


def _recv_msg(sock: socket.socket):
    head = _recv_exact(sock, _LEN.size)
    if head is None:
        return None
    (n,) = _LEN.unpack(head)
    body = _recv_exact(sock, n)
    if body is None:
        return None
    return pickle.loads(body)


class DcnChannel:
    """Request/reply channel between the launcher's processes.

    `handler(msg) -> reply` is called for every incoming request on a
    receiver thread. Outgoing `request(peer, msg)` is synchronous (send +
    await reply) under a per-peer lock; concurrency across peers is free.
    """

    def __init__(self, process_id: int, num_processes: int,
                 handler: Callable):
        self.pid = process_id
        self.num = num_processes
        self.handler = handler
        self._listener: Optional[socket.socket] = None
        self._peers: Dict[int, socket.socket] = {}
        self._peer_locks: Dict[int, threading.Lock] = {}
        # guards _peers/_peer_locks mutation: two threads making first
        # requests to the same peer must agree on one (socket, lock) pair
        self._resolve_lock = threading.Lock()
        self._threads = []
        self._stop = threading.Event()

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        from jax._src import distributed
        client = distributed.global_state.client
        assert client is not None, "jax.distributed not initialized"
        self._listener = socket.socket()
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind(("0.0.0.0", 0))
        self._listener.listen(self.num)
        port = self._listener.getsockname()[1]
        host = socket.gethostname()
        client.key_value_set(f"adapm/dcn/{self.pid}", f"{host}:{port}")
        t = threading.Thread(target=self._accept_loop, daemon=True,
                             name="adapm-dcn-accept")
        t.start()
        self._threads.append(t)

    def _resolve(self, peer: int) -> socket.socket:
        with self._resolve_lock:
            sock = self._peers.get(peer)
            if sock is not None:
                return sock
            from jax._src import distributed
            client = distributed.global_state.client
            addr = client.blocking_key_value_get(f"adapm/dcn/{peer}", 60_000)
            host, port = addr.rsplit(":", 1)
            sock = socket.create_connection((host, int(port)), timeout=60)
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._peers[peer] = sock
            self._peer_locks[peer] = threading.Lock()
            return sock

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            t = threading.Thread(target=self._serve, args=(conn,),
                                 daemon=True, name="adapm-dcn-serve")
            t.start()
            self._threads.append(t)

    def _serve(self, conn: socket.socket) -> None:
        while not self._stop.is_set():
            msg = _recv_msg(conn)
            if msg is None:
                conn.close()
                return
            try:
                reply = self.handler(msg)
            except Exception as e:  # noqa: BLE001 - ship errors to requester
                reply = ("error", f"{type(e).__name__}: {e}")
            _send_msg(conn, reply)

    # -- requests ------------------------------------------------------------

    def request(self, peer: int, msg):
        """Synchronous round-trip to `peer`. Raises on remote error."""
        assert peer != self.pid, "use local ops, not a self-request"
        sock = self._resolve(peer)
        with self._peer_locks[peer]:
            _send_msg(sock, msg)
            reply = _recv_msg(sock)
        if reply is None:
            raise ConnectionError(f"peer {peer} closed the channel")
        if isinstance(reply, tuple) and reply \
                and isinstance(reply[0], str) and reply[0] == "error":
            raise RuntimeError(f"peer {peer}: {reply[1]}")
        return reply

    def shutdown(self) -> None:
        self._stop.set()
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
        for sock in self._peers.values():
            try:
                sock.close()
            except OSError:
                pass
        self._peers.clear()
