"""Skip-gram negative-sampling word2vec (SGNS).

Reference apps/word2vec.cc (Google-C w2v ported to the PM): two keys per
word — syn0 (input embedding) = 2w, syn1 (output embedding) = 2w+1
(word2vec.cc:83-105); unigram^0.75 negative table (:125-144); AdaGrad
update (:718-743). Here one fused step trains a whole batch of (center,
context) pairs with N shared-per-pair negatives.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


def syn0_key(word: np.ndarray):
    """Input-embedding key for word id(s) (word2vec.cc:83-105)."""
    return 2 * np.asarray(word, dtype=np.int64)


def syn1_key(word: np.ndarray):
    """Output-embedding key for word id(s)."""
    return 2 * np.asarray(word, dtype=np.int64) + 1


def sgns_loss(embs, aux):
    """Roles: center [B, d] (syn0), ctx [B, d] (syn1), neg [B, N, d] (syn1).
    loss = -log sig(u.v) - sum log sig(-u.v_neg)."""
    center, ctx, neg = embs["center"], embs["ctx"], embs["neg"]
    pos = (center * ctx).sum(-1)
    negs = (center[:, None, :] * neg).sum(-1)
    return (jax.nn.softplus(-pos) + jax.nn.softplus(negs).sum(-1)).mean()


def build_unigram_table(counts: np.ndarray, power: float = 0.75):
    """Noise distribution over words: count^0.75 / Z (word2vec.cc:125-144).
    Returns a sampler closure `fn(n, rng) -> word ids` suitable for
    Server.enable_sampling_support (drawing *syn1 keys* is the caller's
    concern via syn1_key)."""
    p = counts.astype(np.float64) ** power
    p /= p.sum()

    def sample(n: int, rng: np.random.Generator) -> np.ndarray:
        return rng.choice(len(p), size=n, p=p).astype(np.int64)

    return sample


def build_alias_table(counts: np.ndarray, power: float = 0.75):
    """Vose alias table for the unigram^power noise distribution — the
    device-sampler form of the reference's pre-materialized 1e8-entry
    unigram table (word2vec.cc:125-144): two O(V) arrays in HBM instead of
    a 400MB table, sampled in-program with two uniform draws.
    Returns (prob float32[V], alias int32[V])."""
    p = counts.astype(np.float64) ** power
    p /= p.sum()
    V = len(p)
    prob = np.zeros(V, dtype=np.float32)
    alias = np.zeros(V, dtype=np.int32)
    scaled = p * V
    small = [i for i in range(V) if scaled[i] < 1.0]
    large = [i for i in range(V) if scaled[i] >= 1.0]
    while small and large:
        s, l = small.pop(), large.pop()
        prob[s] = scaled[s]
        alias[s] = l
        scaled[l] = (scaled[l] + scaled[s]) - 1.0
        (small if scaled[l] < 1.0 else large).append(l)
    for i in small + large:
        prob[i] = 1.0
    return prob, alias


def subsample_mask(word_counts: np.ndarray, words: np.ndarray,
                   total: int, t: float, rng) -> np.ndarray:
    """Frequent-word subsampling keep-mask, word2vec.c's keep probability
    sqrt(t/f) + t/f for a word with corpus frequency f (word2vec.cc applies
    this while filling its sentence buffer)."""
    f = word_counts[words] / max(total, 1)
    keep_p = np.minimum(1.0, np.sqrt(t / np.maximum(f, 1e-12))
                        + t / np.maximum(f, 1e-12))
    return rng.random(len(words)) < keep_p
