"""Model families ported from the reference apps (SURVEY.md §2.3), as pure
JAX scoring/loss functions pluggable into ops.fused."""
from .kge import (complex_eval_scores, complex_score, make_kge_loss,  # noqa
                  rescal_score)
from .mf import col_key, full_loss, make_mf_loss, row_key  # noqa
from .sgns import build_unigram_table, sgns_loss, syn0_key, syn1_key  # noqa
