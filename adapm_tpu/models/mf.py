"""Matrix factorization with AdaGrad + L2 (reference
apps/matrix_factorization.cc
+ apps/mf/update.h:23-79 `UpdateNsqlL2Adagrad`).

Key layout (matrix_factorization.cc:692-697): row keys [0, first_col_key),
column keys from first_col_key; value row = [factor (rank) | AdaGrad (rank)].
Loss = nonzero squared loss + L2 on both factors.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np


def make_mf_loss(l2: float = 0.0):
    """Roles: w [B, rank] (row factors), h [B, rank] (col factors);
    aux = observed ratings x [B]. Mean squared residual + L2."""

    def loss_fn(embs, aux):
        w, h = embs["w"], embs["h"]
        x = aux
        pred = (w * h).sum(-1)
        err = (pred - x) ** 2
        reg = l2 * ((w * w).sum(-1) + (h * h).sum(-1))
        return (err + reg).mean()

    return loss_fn


def row_key(i: np.ndarray):
    return np.asarray(i, dtype=np.int64)


def col_key(j: np.ndarray, first_col_key: int):
    return np.asarray(j, dtype=np.int64) + first_col_key


def full_loss(W: np.ndarray, H: np.ndarray, coo, l2: float = 0.0) -> float:
    """Test/train loss over all observed entries (reference apps/mf/loss.h):
    coo = (rows, cols, vals) numpy arrays."""
    i, j, x = coo
    pred = (W[i] * H[j]).sum(-1)
    err = float(((pred - x) ** 2).sum())
    if l2:
        err += l2 * float((W * W).sum() + (H * H).sum())
    return err
