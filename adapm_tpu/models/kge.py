"""Knowledge-graph embedding models: ComplEx and RESCAL.

Reference apps/knowledge_graph_embeddings.cc (ComplEx score/grad :832-858,
RESCAL :860-907, AdaGrad :415-435, negative sampling via PullSample
:452-465). Here the scoring functions are pure JAX on *batches* of triples,
so score + grad + update fuse into one XLA program (ops/fused.py) instead of
the reference's per-triple loop.

Embedding layout: an entity row holds a complex vector of dimension `dim` as
[re | im] (2*dim floats); ComplEx relations are the same; RESCAL relations
are a real dim x dim matrix (dim^2 floats). The stored value row additionally
carries the AdaGrad accumulator (ops/fused.py layout [emb | acc]).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp


def complex_score(s: jnp.ndarray, r: jnp.ndarray,
                  o: jnp.ndarray) -> jnp.ndarray:
    """Re(<s, r, conj(o)>) for [..., 2d] embeddings (kge.cc ComplEx)."""
    d = s.shape[-1] // 2
    sr, si = s[..., :d], s[..., d:]
    rr, ri = r[..., :d], r[..., d:]
    orr, oi = o[..., :d], o[..., d:]
    return (sr * rr * orr + si * rr * oi
            + sr * ri * oi - si * ri * orr).sum(-1)


def rescal_score(s: jnp.ndarray, r: jnp.ndarray,
                 o: jnp.ndarray) -> jnp.ndarray:
    """s^T R o with R = r reshaped to [d, d] (kge.cc RESCAL)."""
    d = s.shape[-1]
    R = r.reshape(r.shape[:-1] + (d, d))
    return jnp.einsum("...i,...ij,...j->...", s, R, o)


def _nll_loss(pos: jnp.ndarray, neg_s: jnp.ndarray, neg_o: jnp.ndarray,
              self_adv_temp: float = 0.0) -> jnp.ndarray:
    """Negative-sampling logistic loss: -log sig(pos) - sum log sig(-neg)
    (the reference trains with sigmoid loss over neg_ratio negatives per
    side, kge.cc train loop :437-531).

    self_adv_temp > 0 switches the negative term to SELF-ADVERSARIAL
    weighting (Sun et al. 2019, RotatE eq. 5): each negative is weighted
    by softmax(temp * score) with a stopped gradient, so the hardest
    negatives in the batch dominate the update. This addresses the
    measured mid-scale failure of uniform negatives (docs/PERF.md
    "Quality": at 14k entities uniform draws almost never hit the
    runner-up entities that carry the signal)."""
    pos_l = jax.nn.softplus(-pos)
    if self_adv_temp > 0.0:
        ws = jax.nn.softmax(
            self_adv_temp * jax.lax.stop_gradient(neg_s), axis=-1)
        wo = jax.nn.softmax(
            self_adv_temp * jax.lax.stop_gradient(neg_o), axis=-1)
        neg_l = (ws * jax.nn.softplus(neg_s)).sum(-1) \
            + (wo * jax.nn.softplus(neg_o)).sum(-1)
    else:
        neg_l = jax.nn.softplus(neg_s).sum(-1) \
            + jax.nn.softplus(neg_o).sum(-1)
    return (pos_l + neg_l).mean()


def make_kge_loss(model: str = "complex", self_adv_temp: float = 0.0):
    """loss_fn for ops/fused.py. Roles: s, r, o [B, *]; neg [B, N] entity
    embeddings used to corrupt both the subject and the object side.
    `self_adv_temp` enables self-adversarial negative weighting (see
    _nll_loss)."""
    score = {"complex": complex_score, "rescal": rescal_score}[model]

    def loss_fn(embs, aux):
        s, r, o, neg = embs["s"], embs["r"], embs["o"], embs["neg"]
        pos = score(s, r, o)
        # corrupt subject and object with the same negative pool
        neg_s = score(neg, r[:, None, :], o[:, None, :])
        neg_o = score(s[:, None, :], r[:, None, :], neg)
        return _nll_loss(pos, neg_s, neg_o, self_adv_temp)

    return loss_fn


def complex_eval_scores(ent: jnp.ndarray, rel: jnp.ndarray,
                        s: jnp.ndarray, r: jnp.ndarray,
                        o: jnp.ndarray) -> jnp.ndarray:
    """All-entity scores for filtered-MRR eval (kge.cc Evaluator :544-775):
    given full entity matrix [E, 2d] and a triple batch, return
    (scores_o [B, E] for object prediction, scores_s [B, E] for subject).
    One matmul per side -> MXU-friendly."""
    d = ent.shape[-1] // 2
    er, ei = ent[..., :d], ent[..., d:]
    sr, si = s[..., :d], s[..., d:]
    rr, ri = r[..., :d], r[..., d:]
    # object prediction: Re(<s, r, conj(e)>) for all e
    a = sr * rr - si * ri   # coefficient of e_re
    b = sr * ri + si * rr   # coefficient of e_im
    scores_o = a @ er.T + b @ ei.T
    # subject prediction: Re(<e, r, conj(o)>) for all e
    orr, oi = o[..., :d], o[..., d:]
    c = rr * orr + ri * oi
    dcoef = rr * oi - ri * orr
    scores_s = c @ er.T + dcoef @ ei.T
    return scores_o, scores_s


def rescal_eval_scores(ent: jnp.ndarray, rel: jnp.ndarray,
                       s: jnp.ndarray, r: jnp.ndarray,
                       o: jnp.ndarray) -> jnp.ndarray:
    """All-entity RESCAL scores s^T R e (object side) and e^T R o (subject
    side) as two matmuls against the full entity matrix [E, d]."""
    d = ent.shape[-1]
    R = r.reshape(r.shape[:-1] + (d, d))
    sR = jnp.einsum("bi,bij->bj", s, R)      # [B, d]
    Ro = jnp.einsum("bij,bj->bi", R, o)      # [B, d]
    return sR @ ent.T, Ro @ ent.T


def make_eval_scores(model: str):
    return {"complex": complex_eval_scores,
            "rescal": rescal_eval_scores}[model]
