"""Knowledge-graph embedding models: ComplEx and RESCAL.

Reference apps/knowledge_graph_embeddings.cc (ComplEx score/grad :832-858,
RESCAL :860-907, AdaGrad :415-435, negative sampling via PullSample
:452-465). Here the scoring functions are pure JAX on *batches* of triples,
so score + grad + update fuse into one XLA program (ops/fused.py) instead of
the reference's per-triple loop.

Embedding layout: an entity row holds a complex vector of dimension `dim` as
[re | im] (2*dim floats); ComplEx relations are the same; RESCAL relations
are a real dim x dim matrix (dim^2 floats). The stored value row additionally
carries the AdaGrad accumulator (ops/fused.py layout [emb | acc]).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp


def complex_score(s: jnp.ndarray, r: jnp.ndarray,
                  o: jnp.ndarray) -> jnp.ndarray:
    """Re(<s, r, conj(o)>) for [..., 2d] embeddings (kge.cc ComplEx)."""
    d = s.shape[-1] // 2
    sr, si = s[..., :d], s[..., d:]
    rr, ri = r[..., :d], r[..., d:]
    orr, oi = o[..., :d], o[..., d:]
    return (sr * rr * orr + si * rr * oi
            + sr * ri * oi - si * ri * orr).sum(-1)


def rescal_score(s: jnp.ndarray, r: jnp.ndarray,
                 o: jnp.ndarray) -> jnp.ndarray:
    """s^T R o with R = r reshaped to [d, d] (kge.cc RESCAL)."""
    d = s.shape[-1]
    R = r.reshape(r.shape[:-1] + (d, d))
    return jnp.einsum("...i,...ij,...j->...", s, R, o)


def _nll_loss(pos: jnp.ndarray, neg_s: jnp.ndarray, neg_o: jnp.ndarray,
              self_adv_temp: float = 0.0) -> jnp.ndarray:
    """Negative-sampling logistic loss: -log sig(pos) - sum log sig(-neg)
    (the reference trains with sigmoid loss over neg_ratio negatives per
    side, kge.cc train loop :437-531).

    self_adv_temp > 0 switches the negative term to SELF-ADVERSARIAL
    weighting (Sun et al. 2019, RotatE eq. 5): each negative is weighted
    by softmax(temp * score) with a stopped gradient, so the hardest
    negatives in the batch dominate the update. This addresses the
    measured mid-scale failure of uniform negatives (docs/PERF.md
    "Quality": at 14k entities uniform draws almost never hit the
    runner-up entities that carry the signal)."""
    pos_l = jax.nn.softplus(-pos)
    if self_adv_temp > 0.0:
        ws = jax.nn.softmax(
            self_adv_temp * jax.lax.stop_gradient(neg_s), axis=-1)
        wo = jax.nn.softmax(
            self_adv_temp * jax.lax.stop_gradient(neg_o), axis=-1)
        neg_l = (ws * jax.nn.softplus(neg_s)).sum(-1) \
            + (wo * jax.nn.softplus(neg_o)).sum(-1)
    else:
        neg_l = jax.nn.softplus(neg_s).sum(-1) \
            + jax.nn.softplus(neg_o).sum(-1)
    return (pos_l + neg_l).mean()


def make_kge_loss(model: str = "complex", self_adv_temp: float = 0.0,
                  l2: float = 0.0):
    """loss_fn for ops/fused.py. Roles: s, r, o [B, *]; neg [B, N] entity
    embeddings used to corrupt both the subject and the object side.
    `self_adv_temp` enables self-adversarial negative weighting (see
    _nll_loss).

    `l2` > 0 adds per-batch (lazy) L2 on the POSITIVE triple's embedding
    rows — the ComplEx paper's regularizer, absent in the reference's
    sigmoid-loss trainer (kge.cc :437-531) but load-bearing once train
    coverage of the (s, r) pair space is sparse: unregularized NS-SGD
    then memorizes train triples (loss falls) while test ranking stays
    random (measured, docs/PERF.md 'Quality at 14.5k'). Lazy = only rows
    touched by the step decay, which is exactly AdaGrad-compatible."""
    score = {"complex": complex_score, "rescal": rescal_score}[model]

    def loss_fn(embs, aux):
        s, r, o, neg = embs["s"], embs["r"], embs["o"], embs["neg"]
        pos = score(s, r, o)
        # corrupt subject and object with the same negative pool
        neg_s = score(neg, r[:, None, :], o[:, None, :])
        neg_o = score(s[:, None, :], r[:, None, :], neg)
        loss = _nll_loss(pos, neg_s, neg_o, self_adv_temp)
        if l2 > 0.0:
            loss = loss + l2 * ((s * s).sum(-1) + (r * r).sum(-1)
                                + (o * o).sum(-1)).mean()
        return loss

    return loss_fn


def complex_eval_scores(ent: jnp.ndarray, rel: jnp.ndarray,
                        s: jnp.ndarray, r: jnp.ndarray,
                        o: jnp.ndarray) -> jnp.ndarray:
    """All-entity scores for filtered-MRR eval (kge.cc Evaluator :544-775):
    given full entity matrix [E, 2d] and a triple batch, return
    (scores_o [B, E] for object prediction, scores_s [B, E] for subject).
    One matmul per side -> MXU-friendly."""
    d = ent.shape[-1] // 2
    er, ei = ent[..., :d], ent[..., d:]
    sr, si = s[..., :d], s[..., d:]
    rr, ri = r[..., :d], r[..., d:]
    # object prediction: Re(<s, r, conj(e)>) for all e
    a = sr * rr - si * ri   # coefficient of e_re
    b = sr * ri + si * rr   # coefficient of e_im
    scores_o = a @ er.T + b @ ei.T
    # subject prediction: Re(<e, r, conj(o)>) for all e
    orr, oi = o[..., :d], o[..., d:]
    c = rr * orr + ri * oi
    dcoef = rr * oi - ri * orr
    scores_s = c @ er.T + dcoef @ ei.T
    return scores_o, scores_s


def rescal_eval_scores(ent: jnp.ndarray, rel: jnp.ndarray,
                       s: jnp.ndarray, r: jnp.ndarray,
                       o: jnp.ndarray) -> jnp.ndarray:
    """All-entity RESCAL scores s^T R e (object side) and e^T R o (subject
    side) as two matmuls against the full entity matrix [E, d]."""
    d = ent.shape[-1]
    R = r.reshape(r.shape[:-1] + (d, d))
    sR = jnp.einsum("bi,bij->bj", s, R)      # [B, d]
    Ro = jnp.einsum("bij,bj->bi", R, o)      # [B, d]
    return sR @ ent.T, Ro @ ent.T


def make_eval_scores(model: str):
    return {"complex": complex_eval_scores,
            "rescal": rescal_eval_scores}[model]


def score_numpy(model: str, s, r, o):
    """Host-side scoring of a handful of (s, r, o) rows — used for the
    filtered-rank correction, whose per-batch filter sets are tiny."""
    import numpy as np
    s, r, o = (np.asarray(x, dtype=np.float64) for x in (s, r, o))
    if model == "complex":
        d = s.shape[-1] // 2
        sr, si = s[..., :d], s[..., d:]
        rr, ri = r[..., :d], r[..., d:]
        orr, oi = o[..., :d], o[..., d:]
        return (sr * rr * orr + si * rr * oi
                + sr * ri * oi - si * ri * orr).sum(-1)
    d = s.shape[-1]
    R = r.reshape(r.shape[:-1] + (d, d))
    return np.einsum("...i,...ij,...j->...", s, R, o)


def make_true_score(model: str):
    """True-triple scores from query ROWS, as its own tiny executable.

    Kept separate from the candidate-count scan on purpose: in the
    candidate-partitioned multi-process eval every rank compiles a counts
    program with a DIFFERENT tile count (its owned-entity share), and the
    comparisons `candidate > true` must use byte-identical true scores on
    every rank — a shared, shape-identical executable guarantees that;
    a subgraph inside differently-shaped programs does not."""
    score = {"complex": complex_score, "rescal": rescal_score}[model]

    # apm-lint: disable=APM008 model-math eval program over already-
    # gathered rows: backend-generic jax compute, no pool donation and no
    # sharded dispatch — the PM data plane proper rides the DevicePort
    @jax.jit
    def fn(se, re_, oe):
        return score(se, re_, oe)

    return fn


def make_pool_eval_counts_mp(model: str, ent_dim: int, rel_dim: int,
                             chunk: int):
    """Candidate-partitioned twin of make_pool_eval_counts (VERDICT r4
    item 5 — multi-process chunked eval). Differences:

      - query embeddings arrive as ROWS (se/re_/oe, fetched via
        Server.read_main, which resolves remote owners over the DCN
        channel) instead of keys, so the program only gathers CANDIDATE
        rows — which are exactly this rank's owned entities, always in
        the local pool;
      - `ent_keys` tiles cover the rank's OWNED entities only, padded at
        the tail (`nvalid` masks the padding); each entity has exactly
        one owner, so N ranks partition the candidate set exactly and
        the per-rank greater-counts allreduce-SUM to the global counts
        (reference distributed Evaluator, kge.cc:544-775);
      - the true score is an INPUT (make_true_score), identical bytes on
        every rank.

    fn(ent_main, tables, ent_keys [nch, chunk], nvalid, se, re_, oe,
       skeys [B], okeys [B], true_sc [B]) -> (greater_o [B],
       greater_s [B])."""
    scores_fn = make_eval_scores(model)

    # apm-lint: disable=APM008 chunked eval-count program (model math
    # over the shared pool mirror): backend-generic jax, not a PM
    # data-plane dispatch site
    @jax.jit
    def counts(ent_main, tables, ent_keys, nvalid, se, re_, oe, skeys,
               okeys, true_sc):
        owner, slot, _ = tables

        def ent_rows(keys):
            return ent_main[owner[keys], slot[keys], :ent_dim]

        C = ent_keys.shape[1]

        def body(carry, xs):
            g_o, g_s = carry
            keys, start = xs
            # barrier: see make_pool_eval_counts (blocks the whole-pool
            # bf16 convert hoist at north-star scale)
            rows = jax.lax.optimization_barrier(
                ent_rows(keys))                          # [C, d]
            so, ss = scores_fn(rows, None, se, re_, oe)  # [B, C] each
            mask = (start + jnp.arange(C)) < nvalid
            # exclude the true entity BY KEY (see make_pool_eval_counts)
            m_o = mask[None, :] & (keys[None, :] != okeys[:, None])
            m_s = mask[None, :] & (keys[None, :] != skeys[:, None])
            g_o = g_o + ((so > true_sc[:, None]) & m_o).sum(
                axis=1, dtype=jnp.int32)
            g_s = g_s + ((ss > true_sc[:, None]) & m_s).sum(
                axis=1, dtype=jnp.int32)
            return (g_o, g_s), None

        B = skeys.shape[0]
        z = jnp.zeros(B, jnp.int32)
        starts = jnp.arange(ent_keys.shape[0]) * C
        (g_o, g_s), _ = jax.lax.scan(body, (z, z), (ent_keys, starts))
        return g_o, g_s

    return counts


def make_pool_eval_counts(model: str, ent_dim: int, rel_dim: int,
                          chunk: int, shared_pool: bool = False):
    """Full-entity eval WITHOUT materializing the entity matrix: candidate
    rows are gathered straight from the sharded main POOL in [B, chunk]
    tiles under a lax.scan (VERDICT r3 item 4 — at Wikidata5M scale the
    old evaluate() shipped ~1.2 GiB of scores to the host per batch of 64
    and needed a 4.7 GB host entity matrix; reference Evaluator
    kge.cc:544-775 loops candidates per triple).

    Returns fn(ent_main, rel_main, tables, ent_keys [nch, chunk] (key
    OOB-padded), nE, skeys [B], rkeys [B], okeys [B]) ->
    (greater_o [B], greater_s [B], true_sc [B]): for each side, the
    number of real candidates scoring strictly above the true triple.
    Filtered-rank correction happens on the host over the (tiny)
    per-triple filter sets (apps/.. evaluate).

    shared_pool=True drops the rel_main parameter and reads relation rows
    from ent_main — REQUIRED at north-star scale when entities and
    relations share one length class: the AOT compiler accounts each
    program parameter's HBM separately even when the caller passes the
    same buffer twice, so an 8.8 GiB pool passed as both ent_main and
    rel_main is budgeted at 17.6 GiB and the compile is rejected before
    any real allocation happens (observed on v5e at 4.6M entities)."""
    score = {"complex": complex_score, "rescal": rescal_score}[model]
    scores_fn = make_eval_scores(model)

    # apm-lint: disable=APM008 pool-eval count program (model math):
    # backend-generic jax, not a PM data-plane dispatch site
    @jax.jit
    def counts(ent_main, rel_main, tables, ent_keys, nE, skeys, rkeys,
               okeys):
        owner, slot, _ = tables

        def ent_rows(keys):
            return ent_main[owner[keys], slot[keys], :ent_dim]

        se = ent_rows(skeys)
        oe = ent_rows(okeys)
        rpool = ent_main if shared_pool else rel_main
        re_ = rpool[owner[rkeys], slot[rkeys], :rel_dim]
        true_sc = score(se, re_, oe)  # same triple -> same score each side

        C = ent_keys.shape[1]

        def body(carry, xs):
            g_o, g_s = carry
            keys, start = xs
            # the barrier pins the gathered tile: without it XLA commutes
            # the matmul's bf16 convert across the gather and hoists it
            # out of the scan as convert(whole pool) — a pool-sized HLO
            # temp (4.47 GiB at Wikidata5M scale, compile-time OOM)
            rows = jax.lax.optimization_barrier(
                ent_rows(keys))                        # [C, d]
            so, ss = scores_fn(rows, None, se, re_, oe)  # [B, C] each
            mask = (start + jnp.arange(C)) < nE
            # exclude the true entity BY KEY, not by score comparison:
            # the candidate matmul form rounds differently from the
            # direct true-score form, so the true entity could otherwise
            # count itself as "greater" by an ulp
            m_o = mask[None, :] & (keys[None, :] != okeys[:, None])
            m_s = mask[None, :] & (keys[None, :] != skeys[:, None])
            g_o = g_o + ((so > true_sc[:, None]) & m_o).sum(
                axis=1, dtype=jnp.int32)
            g_s = g_s + ((ss > true_sc[:, None]) & m_s).sum(
                axis=1, dtype=jnp.int32)
            return (g_o, g_s), None

        B = skeys.shape[0]
        z = jnp.zeros(B, jnp.int32)
        starts = jnp.arange(ent_keys.shape[0]) * C
        (g_o, g_s), _ = jax.lax.scan(body, (z, z), (ent_keys, starts))
        return g_o, g_s, true_sc

    if shared_pool:
        def counts_shared(ent_main, tables, ent_keys, nE, skeys, rkeys,
                          okeys):
            return counts(ent_main, None, tables, ent_keys, nE, skeys,
                          rkeys, okeys)
        return counts_shared
    return counts
