"""PyTorch/NumPy bindings: the reference's `adapm` Python module surface
(bindings/bindings.cc) so external apps (e.g. the GCN/CTR PyTorch apps,
README.md:23) can switch backends without code changes.

Surface parity (bindings.cc):
  setup(num_keys, num_threads, use_techniques="", num_channels=-1)
  scheduler(num_keys, num_threads)            -- no-op here (no scheduler
                                                 process; jax.distributed's
                                                 coordinator plays that role)
  Server(num_keys_or_value_lengths)
    .enable_sampling_support(scheme, with_replacement, distribution, min, max)
    .barrier() / .shutdown() / .my_rank()
  Worker(customer_id, server)
    .pull/.push/.set(keys, vals, async=False) -> ts   (in-place into vals)
    .intent(keys, start, end=0)
    .prepare_sample(K, start, end=0) / .pull_sample(id, keys, vals, async)
    .wait(ts) / .waitall() / .wait_sync() / .advance_clock()
    .current_clock / .begin_setup / .end_setup / .barrier / .finalize
    .get_key_size(key) / .num_keys

Both torch.Tensor (CPU) and numpy arrays are accepted; results are written
in place through a zero-copy numpy view of the tensor's memory (the
reference writes through data_ptr). Value-length and key-range validation
mirror assert_correct_value_length / assert_keys_in_range (bindings.cc:38-61)
including the error messages' intent. Built-in sampling distributions:
uniform and log-uniform over [min, max) (bindings.cc:64-78).
"""
from __future__ import annotations

from typing import Optional, Union

import numpy as np

from .base import CLOCK_MAX, LOCAL
from .config import SystemOptions
from .core import kv as _kv

_global_opts: Optional[SystemOptions] = None


def _as_numpy(x) -> np.ndarray:
    """Zero-copy view of a torch CPU tensor or numpy array."""
    if hasattr(x, "detach") and hasattr(x, "numpy"):  # torch.Tensor
        return x.detach().numpy()
    return np.asarray(x)


def setup(num_keys: int, num_threads: int, use_techniques: str = "",
          num_channels: int = -1) -> None:
    """Record global PM options (reference bindings.cc setup: techniques and
    channel count are process-wide, applied to Servers constructed later).
    Under the launcher this also joins the multi-process runtime — the
    reference's ps::Setup -> Postoffice::Start."""
    from .parallel import control
    control.init_from_env()
    global _global_opts
    from .base import MgmtTechniques
    opts = SystemOptions()
    if use_techniques:
        opts.techniques = MgmtTechniques(use_techniques)
    if num_channels != -1:
        opts.channels = num_channels
    opts.sync_max_per_sec = 0.0  # bindings drive sync via wait_sync/barrier
    opts.bindings_num_workers = num_threads  # type: ignore[attr-defined]
    _global_opts = opts


def scheduler(num_keys: int, num_threads: int) -> None:
    """Reference: runs the scheduler role. The TPU runtime has no scheduler
    process (jax.distributed's coordinator is the rendezvous), so this
    returns immediately — kept so launch scripts port unchanged."""


class Server:
    """Reference ServerT binding (bindings.cc Server class)."""

    def __init__(self, value_lengths: Union[int, np.ndarray, "object"],
                 num_keys: Optional[int] = None):
        opts = _global_opts or SystemOptions(sync_max_per_sec=0.0)
        nw = getattr(opts, "bindings_num_workers", None)
        if np.ndim(value_lengths) == 0 and num_keys is None:
            # ServerT(int): uniform length for the setup()-declared key count
            raise TypeError(
                "Server(uniform_len) needs num_keys: use "
                "Server(value_length, num_keys) or pass a per-key array")
        if np.ndim(value_lengths) == 0:
            lens: Union[int, np.ndarray] = int(value_lengths)
            nk = int(num_keys)
        else:
            lens = _as_numpy(value_lengths).astype(np.int64)
            nk = len(lens)
        self._srv = _kv.Server(nk, lens, opts=opts, num_workers=nw)

    def enable_sampling_support(self, scheme: str, with_replacement: bool,
                                distribution: str, min: int, max: int
                                ) -> None:  # noqa: A002 (reference names)
        opts = self._srv.opts
        opts.sampling_scheme = scheme
        opts.sampling_with_replacement = bool(with_replacement)
        lo, hi = int(min), int(max)
        if distribution == "uniform":
            def fn(n, rng):
                return rng.integers(lo, hi, n).astype(np.int64)
        elif distribution == "log-uniform":
            def fn(n, rng):
                u = rng.random(n)
                return (np.exp(u * np.log(hi - lo + 1)) + lo - 1
                        ).astype(np.int64)
        else:
            raise ValueError(
                f"Unknown sampling distribution '{distribution}'")
        self._srv.enable_sampling_support(fn, lo, hi)

    def barrier(self) -> None:
        self._srv.barrier()

    def shutdown(self) -> None:
        self._srv.shutdown()

    def my_rank(self) -> int:
        from .parallel import control
        return control.process_id()


class Worker:
    """Reference WorkerT binding: ops write results into the caller's
    buffer, async ops return a timestamp for wait()."""

    def __init__(self, customer_id: int, server: Server):
        self._server = server
        self._w = server._srv.make_worker(customer_id)

    # -- validation (bindings.cc:38-61) --------------------------------------

    def _check(self, keys: np.ndarray, vals: Optional[np.ndarray]) -> None:
        srv = self._server._srv
        if len(keys) and (keys.min() < 0 or keys.max() >= srv.num_keys):
            bad = keys[(keys < 0) | (keys >= srv.num_keys)][0]
            raise IndexError(
                f"At least one of the provided keys ({bad}) is outside the "
                f"key range [0, {srv.num_keys})")
        if vals is not None:
            needed = int(srv.value_lengths[keys].sum())
            if vals.size != needed:
                raise ValueError(
                    "The provided value array does not match the size "
                    f"specified in the parameter server: {vals.size} != "
                    f"{needed}")

    def _kv(self, keys, vals):
        k = _as_numpy(keys).astype(np.int64, copy=False).ravel()
        v = _as_numpy(vals)
        if not v.flags["C_CONTIGUOUS"]:
            # reshape(-1) on a non-contiguous view would copy, silently
            # breaking the in-place fill contract
            raise ValueError(
                "value buffer must be contiguous (got a strided view; "
                "call .contiguous() / np.ascontiguousarray first)")
        self._check(k, v)
        return k, v

    # -- data plane ----------------------------------------------------------

    def pull(self, keys, vals, asynchronous: bool = False) -> int:
        k, v = self._kv(keys, vals)
        flat = v.reshape(-1)
        ts = self._w.pull(k, out=flat)
        if not asynchronous and ts != LOCAL:
            self._w.wait(ts)
        return ts

    def push(self, keys, vals, asynchronous: bool = False) -> int:
        k, v = self._kv(keys, vals)
        ts = self._w.push(k, v.reshape(-1))
        if not asynchronous and ts != LOCAL:
            self._w.wait(ts)
        return ts

    def set(self, keys, vals, asynchronous: bool = False) -> int:
        k, v = self._kv(keys, vals)
        ts = self._w.set(k, v.reshape(-1))
        if not asynchronous and ts != LOCAL:
            self._w.wait(ts)
        return ts

    # -- intent / clock ------------------------------------------------------

    def intent(self, keys, start: int, end: int = 0) -> None:
        k = _as_numpy(keys).astype(np.int64, copy=False).ravel()
        self._check(k, None)
        self._w.intent(k, start, end if end else None)

    def advance_clock(self) -> int:
        return self._w.advance_clock()

    @property
    def current_clock(self) -> int:
        return self._w.current_clock

    # -- sampling ------------------------------------------------------------

    def prepare_sample(self, K: int, start: int, end: int = 0) -> int:
        return self._w.prepare_sample(K, start, end if end else None)

    def pull_sample(self, sample_id: int, keys, vals,
                    asynchronous: bool = False) -> int:
        """Draw samples into `keys` and their values into `vals`. Mirrors
        bindings.cc:330-337: returns the underlying pull's timestamp (-1
        when every sampled key was local, e.g. the Local scheme by
        construction); asynchronous=True skips the wait — `vals` is filled
        when the caller waits on the returned timestamp."""
        k = _as_numpy(keys)
        if not k.flags["C_CONTIGUOUS"]:
            raise ValueError("pull_sample key buffer must be contiguous")
        drawn = self._w.pull_sample_keys(sample_id, len(k))
        k.ravel()[:] = drawn
        # the value fetch is an ordinary pull of the drawn keys: shared
        # validation + out= fill + async contract
        return self.pull(drawn, vals, asynchronous)

    # -- waiting / lifecycle -------------------------------------------------

    def wait(self, ts: int) -> None:
        self._w.wait(ts)

    def waitall(self) -> None:
        self._w.wait_all()

    def wait_sync(self) -> None:
        self._w.wait_sync()

    def barrier(self) -> None:
        self._w.barrier()

    def begin_setup(self) -> None:
        self._w.begin_setup()

    def end_setup(self) -> None:
        self._w.end_setup()

    def finalize(self) -> None:
        self._w.finalize()

    def get_key_size(self, key_id: int = 0) -> int:
        return int(self._server._srv.value_lengths[key_id])

    @property
    def num_keys(self) -> int:
        return self._server._srv.num_keys
