"""Shared application harness: the idioms every reference app uses
(SURVEY.md §2.3 "Common app idioms").

- `enforce_random_keys`: random key shuffling for load balance — apps address
  logical keys, a fixed permutation maps them to physical PM keys
  (reference apps shuffle key assignment, e.g. kge.cc / word2vec.cc flag).
- `enforce_full_replication`: Intent all keys to CLOCK_MAX as an ablation
  (replication-everywhere baseline).
- worker-0-initializes + BeginSetup/EndSetup bracket.
- `max_runtime` epoch cutoff.
- wrap-around batching: fused steps are fixed-shape XLA programs, so the tail
  of a data partition wraps to its start (a few duplicate points per epoch
  instead of a recompile per tail size).
"""
from __future__ import annotations

import argparse
import time
from typing import Optional

import numpy as np

from ..base import CLOCK_MAX
from ..config import SystemOptions
from ..utils import Stopwatch, alog


def add_common_arguments(parser: argparse.ArgumentParser,
                         default_epochs: int = 4) -> None:
    g = parser.add_argument_group("run")
    g.add_argument("--num_workers", type=int, default=0,
                   help="logical workers (0 = one per mesh shard)")
    g.add_argument("--num_shards", type=int, default=0,
                   help="kv shards (0 = all visible devices)")
    g.add_argument("--epochs", type=int, default=default_epochs)
    g.add_argument("--batch_size", type=int, default=256)
    g.add_argument("--lr", type=float, default=0.1)
    g.add_argument("--seed", type=int, default=42)
    g.add_argument("--max_runtime", type=float, default=0.0,
                   help="stop after this many seconds (0 = unlimited)")
    g.add_argument("--enforce_random_keys", action="store_true",
                   help="randomly permute key assignment for load balance")
    g.add_argument("--enforce_full_replication", action="store_true",
                   help="ablation: Intent all keys everywhere, forever")
    g.add_argument("--sync_rounds_per_step", type=int, default=1,
                   help="planner sync rounds driven per training step")
    SystemOptions.add_arguments(parser)


def make_server(args, num_keys: int, value_lengths, num_workers: int):
    import adapm_tpu
    opts = SystemOptions.from_args(args)
    srv = adapm_tpu.setup(num_keys, value_lengths, opts=opts,
                          num_shards=args.num_shards or None,
                          num_workers=num_workers)
    return srv


class KeyMapper:
    """Logical key -> physical PM key. Identity unless enforce_random_keys;
    then a seeded permutation (reference `enforce_random_keys`: shuffled
    assignment balances hot keys over servers)."""

    def __init__(self, num_keys: int, shuffle: bool, seed: int = 1234):
        if shuffle:
            rng = np.random.default_rng(seed)
            self.perm = rng.permutation(num_keys).astype(np.int64)
        else:
            self.perm = None

    def __call__(self, keys):
        keys = np.asarray(keys, dtype=np.int64)
        return self.perm[keys] if self.perm is not None else keys


def enforce_full_replication(workers, num_keys: int) -> None:
    """Every worker declares eternal intent on every key, then one forced
    sync round materializes the replicas (ablation mode)."""
    all_keys = np.arange(num_keys, dtype=np.int64)
    for w in workers:
        w.intent(all_keys, 0, CLOCK_MAX)
    workers[0].server.wait_sync()


def worker0_init(workers, keys: np.ndarray, values: np.ndarray,
                 slab: int = 100_000) -> None:
    """Worker 0 of PROCESS 0 initializes the model inside
    BeginSetup/EndSetup (the reference's worker-0-initializes pattern;
    under the launcher, cross-process Sets route to each key's owner)."""
    from ..parallel import control
    w0 = workers[0]
    w0.begin_setup()
    if control.process_id() == 0:
        for lo in range(0, len(keys), slab):
            hi = min(lo + slab, len(keys))
            w0.set(keys[lo:hi], values[lo:hi])
        w0.wait_all()
    w0.end_setup()  # barriers: every rank sees the initialized model


def global_worker_slices(n_items: int, num_local_workers: int):
    """Per-local-worker contiguous slices of [0, n_items) partitioned over
    ALL workers of ALL processes (reference apps partition data by global
    worker id, word2vec.cc:524-531, kge.cc:968-970). Returns a list of
    index arrays, one per local worker."""
    from ..parallel import control
    P, pid = control.num_processes(), control.process_id()
    parts = np.array_split(np.arange(n_items), P * num_local_workers)
    return [parts[pid * num_local_workers + wi]
            for wi in range(num_local_workers)]


def wrap_batches(n: int, batch_size: int, rng: Optional[np.random.Generator]
                 = None):
    """Yield index arrays of exactly batch_size covering [0, n), shuffled if
    rng given; the final batch wraps around to the start."""
    if n == 0:
        return
    order = rng.permutation(n) if rng is not None else np.arange(n)
    for lo in range(0, n, batch_size):
        idx = order[lo:lo + batch_size]
        if len(idx) < batch_size:
            reps = -(-batch_size // n)  # n may be smaller than the shortfall
            idx = np.concatenate([idx, np.tile(order, reps)])[:batch_size]
        yield idx


class ScanWindow:
    """The apps' shared --scan_steps dispatch contract (KGE/w2v/MF): a
    full K-batch window trains in ONE lax.scan dispatch
    (DeviceRoutedRunner.run_scan) followed by K * sync_rounds_per_step
    planner rounds; a partial tail window falls back to per-step dispatch
    (one compiled scan variant per K, and tails are rare). Batches in one
    window must come from ONE worker shard — flush at worker/block
    boundaries."""

    def __init__(self, server, K: int, sync_rounds_per_step: int,
                 on_loss=None):
        self.server = server
        self.K = K
        self.rounds = sync_rounds_per_step
        self.on_loss = on_loss or (lambda loss: None)
        self.buf: list = []  # (runner, roles, aux)

    def add(self, runner, roles, aux, lr) -> None:
        self.buf.append((runner, roles, aux))
        if len(self.buf) == self.K:
            self.flush(lr)

    def flush(self, lr) -> None:
        if not self.buf:
            return
        runner = self.buf[0][0]
        if len(self.buf) == self.K and self.K > 1:
            has_aux = self.buf[0][2] is not None
            self.on_loss(runner.run_scan(
                [r for _, r, _ in self.buf],
                [a for _, _, a in self.buf] if has_aux else None, lr))
            # drive_rounds: inline planner rounds, or delegated to the
            # prefetch pipeline's background thread (SystemOptions
            # .prefetch) so they overlap the in-flight scan window
            self.server.drive_rounds(len(self.buf) * self.rounds)
        else:
            for rn, roles, aux in self.buf:
                self.on_loss(rn(roles, aux, lr))
                self.server.drive_rounds(self.rounds)
        self.buf.clear()


class RuntimeGuard:
    """max_runtime cutoff (reference apps' --max_runtime). The decision is
    COLLECTIVE in a multi-process run: every rank must leave the epoch
    loop together or the per-epoch barriers deadlock."""

    def __init__(self, max_runtime_s: float):
        self.max = max_runtime_s
        self.watch = Stopwatch(start=True)

    def expired(self) -> bool:
        mine = self.max > 0 and self.watch.elapsed_s > self.max
        from ..parallel import control
        if control.num_processes() == 1:
            return mine
        return bool(control.allreduce(float(mine), "max")[0] > 0)


def is_rank0() -> bool:
    from ..parallel import control
    return control.process_id() == 0


def epoch_report(name: str, epoch: int, loss: float, watch: Stopwatch,
                 extra: str = "") -> None:
    alog(f"[{name}] epoch {epoch}: loss={loss:.6f} "
         f"time={watch.elapsed_s:.2f}s {extra}")
