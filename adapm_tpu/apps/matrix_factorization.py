"""Matrix factorization app (reference apps/matrix_factorization.cc).

SGD MF with AdaGrad, L2, and bold-driver step size, in the reference's three
access orders (matrix_factorization.cc:409-579):

  dsgd        worker x subepoch disjoint column-block schedule, barrier per
              subepoch, intent one subepoch ahead
  columnwise  each worker walks its points sorted by column, intent
              `--lookahead` batches ahead
  plain       shuffled SGD over the worker's row-block partition

Key layout (reference :692-693): row keys [0, m), column keys [m, m+n);
value row = [factor (rank) | AdaGrad (rank)] (:695-697). Batches run as one
fused gather -> grad -> AdaGrad -> scatter-add program (ops/fused.py).

Run: python -m adapm_tpu.apps.matrix_factorization --synthetic ...
"""
from __future__ import annotations

import argparse
import sys

import numpy as np

from ..io import mf as mfio
from ..models.mf import make_mf_loss
from ..ops import DeviceRoutedRunner, FusedStepRunner
from ..utils import Stopwatch, alog
from .common import (KeyMapper, RuntimeGuard, ScanWindow,
                     add_common_arguments, enforce_full_replication,
                     epoch_report, make_server, wrap_batches,
                     worker0_init)


def _load_data(args):
    if args.data:
        rows, cols, vals, m, n = mfio.read_coo(args.data)
    else:
        rows, cols, vals, _, _ = mfio.generate_synthetic(
            args.rows, args.cols, args.rank, args.nnz, seed=args.seed)
        m, n = args.rows, args.cols
    return rows, cols, vals, m, n


def _init_factors(args, m, n, rank, rng):
    if args.init_w and args.init_h:
        W = mfio.read_dense(args.init_w)[:, :rank]
        H = mfio.read_dense(args.init_h)[:, :rank]
    else:
        W = (rng.random((m, rank)).astype(np.float32) - 0.5) / np.sqrt(rank)
        H = (rng.random((n, rank)).astype(np.float32) - 0.5) / np.sqrt(rank)
    return W, H


def run(args) -> float:
    rows, cols, vals, m, n = _load_data(args)
    rank = args.rank
    num_keys = m + n
    rng = np.random.default_rng(args.seed)

    kmap = KeyMapper(num_keys, args.enforce_random_keys, seed=args.seed)
    srv = make_server(args, num_keys, value_lengths=2 * rank,
                      num_workers=args.num_workers or None)
    num_workers = args.num_workers or srv.num_shards
    workers = [srv.make_worker(i) for i in range(num_workers)]

    W, H = _init_factors(args, m, n, rank, rng)
    init = np.concatenate(
        [np.concatenate([W, np.full_like(W, args.adagrad_init)], axis=1),
         np.concatenate([H, np.full_like(H, args.adagrad_init)], axis=1)])
    worker0_init(workers, kmap(np.arange(num_keys)), init)
    if args.enforce_full_replication:
        enforce_full_replication(workers, num_keys)

    runner = FusedStepRunner(
        srv, make_mf_loss(args.l2), role_class={"w": 0, "h": 0},
        role_dim={"w": rank, "h": rank})

    # --device_routes: routing tables mirrored into HBM, host ships only
    # the raw key batch per step (TPU hot path; ops/fused.py)
    dev_runners = {}

    def device_runner(shard: int) -> DeviceRoutedRunner:
        if shard not in dev_runners:
            dev_runners[shard] = DeviceRoutedRunner(
                srv, make_mf_loss(args.l2), role_class={"w": 0, "h": 0},
                role_dim={"w": rank, "h": rank}, shard=shard,
                seed=args.seed + shard)
        return dev_runners[shard]

    # row-block data partition over ALL workers of ALL processes
    # (reference mf/io.h:125+; DSGD's block schedule spans them too)
    from ..parallel import control
    P, pid = control.num_processes(), control.process_id()
    total_workers = P * num_workers
    part = mfio.partition_points(rows, total_workers, m)
    by_worker = [np.nonzero(part == pid * num_workers + wi)[0]
                 for wi in range(num_workers)]
    B = args.batch_size
    lr = args.lr
    prev_loss = np.inf
    best_loss = np.inf
    guard = RuntimeGuard(args.max_runtime)
    watch = Stopwatch(start=True)

    # --scan_steps K (device-routed only): buffer K batches and train
    # them in ONE lax.scan dispatch (ScanWindow — the shared app
    # contract; placement frozen per window). The clock still advances
    # per batch at buffering time; intent windows are extended by K-1
    # clocks to cover the dispatch delay. The window is flushed at every
    # worker/block boundary (shards must not mix in one window) and
    # before each barrier/quiesce. lr changes per epoch (bold driver), so
    # the CURRENT lr is passed at every add/flush.
    K = max(1, args.scan_steps) if args.device_routes else 1
    scan_win = ScanWindow(srv, K, args.sync_rounds_per_step)

    def flush_scan():
        scan_win.flush(lr)

    def train_batch(w, idx):
        roles = {"w": kmap(rows[idx]), "h": kmap(cols[idx] + m)}
        if args.device_routes and K > 1:
            scan_win.add(device_runner(w.shard), roles,
                         np.asarray(vals[idx]), lr)
            w.advance_clock()
            return None
        if args.device_routes:
            loss = device_runner(w.shard)(roles, np.asarray(vals[idx]), lr)
        else:
            loss = runner(roles, np.asarray(vals[idx]), lr, shard=w.shard)
        # inline rounds, or delegated to the prefetch pipeline so
        # planner work overlaps the in-flight step
        srv.drive_rounds(args.sync_rounds_per_step)
        w.advance_clock()
        return loss

    def signal_intent(w, idx, start, end):
        ks = np.concatenate([kmap(rows[idx]), kmap(cols[idx] + m)])
        w.intent(np.unique(ks), start, end + (K - 1))

    for epoch in range(args.epochs):
        if args.algorithm == "dsgd":
            sched = mfio.dsgd_schedule(total_workers, epoch, seed=args.seed)
            cblock = mfio.column_block(cols, total_workers, n)
            for s in range(total_workers):
                for wi, w in enumerate(workers):
                    gwi = pid * num_workers + wi  # global worker id
                    mine = by_worker[wi]
                    blk = mine[cblock[mine] == sched[s, gwi]]
                    # intent for the *next* subepoch's block; the clock
                    # advances once per batch, so the window starts after
                    # this block's batches and spans the next block's
                    nb_cur = max(-(-len(blk) // B), 1)
                    if s + 1 < total_workers:
                        nxt = mine[cblock[mine] == sched[s + 1, gwi]]
                        if len(nxt):
                            nb_nxt = max(-(-len(nxt) // B), 1)
                            signal_intent(w, nxt, w.current_clock + nb_cur,
                                          w.current_clock + nb_cur + nb_nxt)
                    # fixed batch size B: wrap_batches tiles small blocks so
                    # every fused step has one static shape (one XLA compile)
                    for idx in wrap_batches(len(blk), B, rng):
                        train_batch(w, blk[idx])
                    flush_scan()
                srv.barrier()  # per-subepoch barrier (reference :409-458)
        elif args.algorithm == "columnwise":
            for wi, w in enumerate(workers):
                mine = by_worker[wi][np.argsort(cols[by_worker[wi]],
                                                kind="stable")]
                batches = list(wrap_batches(len(mine), B))
                for bi, idx in enumerate(batches):
                    la = bi + args.lookahead
                    if la < len(batches):
                        signal_intent(w, mine[batches[la]],
                                      w.current_clock + args.lookahead,
                                      w.current_clock + args.lookahead + 1)
                    train_batch(w, mine[idx])
                flush_scan()
        else:  # plain SGD
            for wi, w in enumerate(workers):
                mine = by_worker[wi]
                batches = list(wrap_batches(len(mine), B, rng))
                for bi, idx in enumerate(batches):
                    la = bi + args.lookahead
                    if la < len(batches):
                        signal_intent(w, mine[batches[la]],
                                      w.current_clock + args.lookahead,
                                      w.current_clock + args.lookahead + 1)
                    train_batch(w, mine[idx])
                flush_scan()

        srv.quiesce()
        Wc, Hc = _current_factors(srv, kmap, m, n, rank)
        loss = _full_loss(Wc, Hc, rows, cols, vals, args.l2)
        epoch_report("mf", epoch, loss, watch, extra=f"lr={lr:.4f}")
        # bold driver (reference matrix_factorization.cc): grow on success,
        # shrink on divergence — compared to the *previous* epoch, so a
        # recovery after one bad epoch counts as success again
        lr = lr * args.bold_inc if loss <= prev_loss else lr * args.bold_dec
        prev_loss = loss
        best_loss = min(best_loss, loss)
        if guard.expired():
            alog("[mf] max_runtime reached")
            break

    if args.export_prefix and pid == 0:
        Wc, Hc = _current_factors(srv, kmap, m, n, rank)
        mfio.write_dense(args.export_prefix + "W.mma", Wc)
        mfio.write_dense(args.export_prefix + "H.mma", Hc)
    alog("[mf]", srv.sync.report())
    srv.shutdown()
    return float(best_loss)


def _current_factors(srv, kmap, m, n, rank):
    flat = srv.read_main(kmap(np.arange(m + n)))
    rowsz = 2 * rank
    M = flat.reshape(m + n, rowsz)[:, :rank]
    return M[:m], M[m:]


def _full_loss(W, H, rows, cols, vals, l2):
    pred = (W[rows] * H[cols]).sum(-1)
    loss = float(((pred - vals) ** 2).sum())
    if l2:
        loss += l2 * float((W * W).sum() + (H * H).sum())
    return loss


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--data", default=None,
                        help="MatrixMarket coordinate file (else synthetic)")
    parser.add_argument("--rows", type=int, default=200)
    parser.add_argument("--cols", type=int, default=100)
    parser.add_argument("--nnz", type=int, default=4000)
    parser.add_argument("--rank", type=int, default=16)
    parser.add_argument("--l2", type=float, default=0.01)
    parser.add_argument("--algorithm", default="dsgd",
                        choices=["dsgd", "columnwise", "plain"])
    parser.add_argument("--scan_steps", type=int, default=1,
                        help="batches trained per device dispatch "
                             "(lax.scan window, runner.run_scan; device "
                             "routing only — same contract as the KGE "
                             "app's --scan_steps)")
    parser.add_argument("--lookahead", type=int, default=2,
                        help="intent batches ahead (columnwise/plain)")
    parser.add_argument("--adagrad_init", type=float, default=1e-6)
    parser.add_argument("--bold_inc", type=float, default=1.05)
    parser.add_argument("--bold_dec", type=float, default=0.5)
    parser.add_argument("--device_routes",
                        action=argparse.BooleanOptionalAction, default=True,
                        help="device-routed fused step (TPU hot path; "
                             "default on, --no-device_routes for host "
                             "routing)")
    parser.add_argument("--init_w", default=None)
    parser.add_argument("--init_h", default=None)
    parser.add_argument("--export_prefix", default=None)
    add_common_arguments(parser)
    return parser


def main(argv=None) -> int:
    run(build_parser().parse_args(argv))
    return 0


if __name__ == "__main__":
    sys.exit(main())
