"""Knowledge-graph embedding app: ComplEx & RESCAL with AdaGrad, filtered
MRR/Hits@k eval, checkpoints (reference apps/knowledge_graph_embeddings.cc).

Pipeline parity (kge.cc:1059-1122): for each future triple batch the worker
signals `Intent({s, r, o})` and `PrepareSample(2*neg_ratio*B)` at the future
clock; negatives arrive via PullSample (managed sampling). Clock advances per
batch. Loss and eval statistics aggregate through PS keys — the reference's
`ps_allreduce` / eval_key idiom (utils.h:163-197, kge.cc:544-775) — a loss
key (length 1) and an eval key (length 8) live at the end of the key space.

Key layout (kge.cc:1296-1306): entities [0, E) with embedding length 2*dim
(ComplEx re|im) or dim (RESCAL); relations [E, E+R) length 2*dim (ComplEx) or
dim^2 (RESCAL); stored rows carry AdaGrad inline: [emb | acc].

Eval (kge.cc Evaluator :544-775): filtered MRR and Hits@{1,10}, ranking all
entities for both subject and object replacement via full-entity matmuls
(models/kge.py eval scores — MXU-shaped, unlike the reference's per-candidate
loop).

Run: python -m adapm_tpu.apps.knowledge_graph_embeddings --synthetic ...
"""
from __future__ import annotations

import argparse
import os
import sys
from typing import Optional

import numpy as np

from ..io import kge as kgeio
from ..models.kge import make_eval_scores, make_kge_loss
from ..ops import DeviceRoutedRunner, FusedStepRunner
from ..utils import Stopwatch, alog
from .common import (KeyMapper, RuntimeGuard, add_common_arguments,
                     enforce_full_replication, epoch_report,
                     global_worker_slices, make_server, wrap_batches,
                     worker0_init)

# eval stats layout: [0:4] object side (mrr_sum, h1, h10, count),
# [4:8] subject side — separated because the generators/datasets can have
# asymmetric sides (the lowrank synthetic's subject is information-free,
# docs/PERF.md); reported combined plus per-side (reference eval_key len 20)
EVAL_LEN = 8


class KgeRun:
    """Holds the server, key layout, and fused runner for one training run."""

    def __init__(self, args, ds: kgeio.TripleDataset):
        self.args = args
        self.ds = ds
        d = args.dim
        E, R = ds.num_entities, ds.num_relations
        self.ent_dim = 2 * d if args.model == "complex" else d
        self.rel_dim = 2 * d if args.model == "complex" else d * d
        self.E, self.R = E, R
        self.loss_key_l = E + R          # logical loss key (kge.cc idiom)
        self.eval_key_l = E + R + 1
        num_keys = E + R + 2

        value_lengths = np.empty(num_keys, dtype=np.int64)
        value_lengths[:E] = 2 * self.ent_dim          # [emb | acc]
        value_lengths[E:E + R] = 2 * self.rel_dim
        value_lengths[self.loss_key_l] = 1
        value_lengths[self.eval_key_l] = EVAL_LEN

        # enforce_random_keys shuffles *within* each population: entities
        # among [0, E), relations among [E, E+R). A joint shuffle would map
        # entity keys onto relation-width rows (different value lengths);
        # aux keys keep their identity.
        self.ent_map = KeyMapper(E, args.enforce_random_keys, seed=args.seed)
        self.rel_map = KeyMapper(R, args.enforce_random_keys,
                                 seed=args.seed + 1)
        self.srv = make_server(args, num_keys, value_lengths,
                               num_workers=args.num_workers or None)
        self.num_workers = args.num_workers or self.srv.num_shards
        self.workers = [self.srv.make_worker(i)
                        for i in range(self.num_workers)]

        ab = self.srv.ab
        self.ent_class = int(ab.key_class[0])
        self.rel_class = int(ab.key_class[E])
        self._pool_eval = None       # chunked pool-gather eval program
        self._pool_eval_chunk = 0
        self._pool_eval_keys = None  # staged padded entity-key tiles
        self._pool_eval_router = None
        self._pool_eval_mp = None    # candidate-partitioned mp variant
        self._pool_eval_topo = -1    # owned-tile cache topology version
        self._pool_eval_n = 0        # this rank's owned-entity count
        self._true_score = None
        self.runner = FusedStepRunner(
            self.srv, make_kge_loss(args.model, args.self_adv_temp, args.l2),
            role_class={"s": self.ent_class, "r": self.rel_class,
                        "o": self.ent_class, "neg": self.ent_class},
            role_dim={"s": self.ent_dim, "r": self.rel_dim,
                      "o": self.ent_dim, "neg": self.ent_dim})

    # -- key helpers ---------------------------------------------------------

    def ekey(self, e):   # entity logical -> physical
        return self.ent_map(np.asarray(e, dtype=np.int64))

    def rkey(self, r):   # relation logical -> physical
        return self.rel_map(np.asarray(r, dtype=np.int64)) + self.E

    # -- init / checkpoint ---------------------------------------------------

    def init_model(self) -> None:
        a = self.args
        rng = np.random.default_rng(a.seed)
        if a.init_from:
            ck = np.load(a.init_from)
            ent_rows = np.concatenate([ck["ent"], ck["ent_acc"]], axis=1)
            rel_rows = np.concatenate([ck["rel"], ck["rel_acc"]], axis=1)
            alog(f"[kge] initialized from checkpoint {a.init_from}")
        else:
            scale = a.init_scale
            if a.init_scheme == "uniform":
                ent = (rng.random((self.E, self.ent_dim)) - 0.5) * 2 * scale
                rel = (rng.random((self.R, self.rel_dim)) - 0.5) * 2 * scale
            else:  # normal (kge.cc init none/uniform/normal :988-1018)
                ent = rng.normal(0, scale, (self.E, self.ent_dim))
                rel = rng.normal(0, scale, (self.R, self.rel_dim))
            ent_rows = np.concatenate(
                [ent, np.full_like(ent, a.adagrad_init)], axis=1)
            rel_rows = np.concatenate(
                [rel, np.full_like(rel, a.adagrad_init)], axis=1)
        worker0_init(self.workers, self.ekey(np.arange(self.E)),
                     ent_rows.astype(np.float32))
        from ..parallel import control
        w0 = self.workers[0]
        w0.begin_setup()
        if control.process_id() == 0:  # worker-0-of-process-0 initializes
            w0.set(self.rkey(np.arange(self.R)),
                   rel_rows.astype(np.float32))
            w0.set(np.array([self.loss_key_l]), np.zeros(1, np.float32))
            w0.set(np.array([self.eval_key_l]),
                   np.zeros(EVAL_LEN, np.float32))
            w0.wait_all()  # cross-process Sets land before the barrier
        w0.end_setup()

    def current_model(self):
        ent = self.srv.read_main(self.ekey(np.arange(self.E))).reshape(
            self.E, 2 * self.ent_dim)
        rel = self.srv.read_main(self.rkey(np.arange(self.R))).reshape(
            self.R, 2 * self.rel_dim)
        return (ent[:, :self.ent_dim], ent[:, self.ent_dim:],
                rel[:, :self.rel_dim], rel[:, self.rel_dim:])

    def checkpoint(self, path: str) -> None:
        ent, ent_acc, rel, rel_acc = self.current_model()
        np.savez(path, ent=ent, ent_acc=ent_acc, rel=rel, rel_acc=rel_acc)
        alog(f"[kge] wrote checkpoint {path}")

    # -- PS-key aggregation (reference ps_allreduce, utils.h:163-197) --------

    def allreduce(self, key_l: int, contribution: np.ndarray) -> np.ndarray:
        """Each process's worker 0 pushes its contribution; after the
        flush + barrier the key's main copy holds the global sum
        (reference ps_allreduce: push -> barrier -> pull,
        utils.h:163-197)."""
        w0 = self.workers[0]
        w0.wait(w0.push(np.array([key_l]),
                        contribution.astype(np.float32)))
        self.srv.quiesce()
        self.srv.barrier()
        out = self.srv.read_main(np.array([key_l]))
        self.srv.barrier()  # all reads done before anyone resets
        return out

    def reset_key(self, key_l: int, length: int) -> None:
        from ..parallel import control
        if control.process_id() == 0:
            w0 = self.workers[0]
            w0.wait(w0.set(np.array([key_l]),
                           np.zeros(length, np.float32)))
        self.srv.barrier()


def _flt_pairs(ab_pairs, flt: dict):
    """Flatten per-triple filter sets into (triple_idx, entity) arrays."""
    fi: list = []
    fe: list = []
    for i, key in enumerate(ab_pairs):
        f = flt.get(key)
        if f:
            fi.extend([i] * len(f))
            fe.extend(f)
    return (np.asarray(fi, dtype=np.int64),
            np.asarray(fe, dtype=np.int64))


def _side_stats(sc: np.ndarray, true_e: np.ndarray, fi: np.ndarray,
                fe: np.ndarray) -> np.ndarray:
    """Filtered ranks for one side, fully batched: rank = 1 + #{better
    candidates} - #{better FILTERED candidates} (the filtered set never
    contains the true entity's own contribution). Replaces the reference's
    (and round 2's) per-triple/per-candidate loop — at FB15k-237's 20k eval
    triples the per-key Python was the bottleneck (VERDICT r2)."""
    B = len(true_e)
    true_sc = sc[np.arange(B), true_e]
    greater = (sc > true_sc[:, None]).sum(axis=1).astype(np.int64)
    if len(fi):
        contrib = (sc[fi, fe] > true_sc[fi]) & (fe != true_e[fi])
        np.subtract.at(greater, fi, contrib.astype(np.int64))
    rank = 1 + greater
    return np.array([(1.0 / rank).sum(), (rank <= 1).sum(),
                     (rank <= 10).sum(), B], dtype=np.float64)


def evaluate(run: KgeRun, triples: np.ndarray, batch: int = 64):
    """Filtered MRR / Hits@{1,10} over `triples`, both-side ranking.

    Production path (--eval_chunk > 0): candidate rows are gathered from
    the POOL in [B, chunk] device tiles and only [B] rank counts return
    to the host — no dense entity matrix anywhere, which is what makes
    4.6M-entity eval feasible (VERDICT r3 item 4). Single process:
    make_pool_eval_counts over all entities. Multi-process: the
    candidate-partitioned variant — every rank must call evaluate() with
    the SAME triples; counts merge inside (_evaluate_pool_mp, VERDICT r4
    item 5). --eval_chunk 0 falls back to the dense-matrix path."""
    if run.args.eval_chunk > 0:
        if run.srv.glob is None:
            return _evaluate_pool(run, triples, batch)
        return _evaluate_pool_mp(run, triples, batch)
    import jax.numpy as jnp
    ent, _, rel, _ = run.current_model()
    ent_j, rel_j = jnp.asarray(ent), jnp.asarray(rel)
    scores_fn = make_eval_scores(run.args.model)
    sr_o, ro_s = run.ds.filters()

    stats = np.zeros(EVAL_LEN, dtype=np.float64)  # mrr, h1, h10, count
    for lo in range(0, len(triples), batch):
        t = triples[lo:lo + batch]
        s, r, o = t[:, 0], t[:, 1], t[:, 2]
        so, ss = scores_fn(ent_j, rel_j, ent_j[s], rel_j[r], ent_j[o])
        so, ss = np.asarray(so), np.asarray(ss)
        fi_o, fe_o = _flt_pairs(list(zip(s.tolist(), r.tolist())), sr_o)
        fi_s, fe_s = _flt_pairs(list(zip(r.tolist(), o.tolist())), ro_s)
        stats[:4] += _side_stats(so, o, fi_o, fe_o)
        stats[4:] += _side_stats(ss, s, fi_s, fe_s)
    return stats


def _rank_side_stats(greater: np.ndarray) -> np.ndarray:
    rank = 1 + greater
    return np.array([(1.0 / rank).sum(), (rank <= 1).sum(),
                     (rank <= 10).sum(), len(rank)], dtype=np.float64)


def _evaluate_pool(run: KgeRun, triples: np.ndarray, batch: int):
    """Pool-gather eval: device counts + host filter correction."""
    from ..models.kge import make_pool_eval_counts, score_numpy
    from ..ops import DeviceRouter
    srv = run.srv
    C = min(run.args.eval_chunk, max(run.E, 8))
    put = srv.ctx.put_replicated
    shared = run.ent_class == run.rel_class
    if run._pool_eval is None or run._pool_eval_chunk != C:
        run._pool_eval = make_pool_eval_counts(
            run.args.model, run.ent_dim, run.rel_dim, C,
            shared_pool=shared)
        run._pool_eval_chunk = C
        # the padded full-entity key tiles and the router are per-(E, C)
        # constants — re-uploading them every evaluate() call is a ~37 MiB
        # host->device staging transfer at the 4.6M-entity scale
        ekeys = run.ekey(np.arange(run.E)).astype(np.int64)
        nch = -(-run.E // C)
        pad = np.full(nch * C, ekeys[0], dtype=np.int64)
        pad[: run.E] = ekeys
        run._pool_eval_keys = put(pad.reshape(nch, C))
        run._pool_eval_router = DeviceRouter(srv, 0)
    counts_fn = run._pool_eval
    ent_keys_dev = run._pool_eval_keys
    router = run._pool_eval_router
    sr_o, ro_s = run.ds.filters()

    def emb_rows(keys, dim):
        rows = np.asarray(srv.read_main(keys)).reshape(len(keys), -1)
        return rows[:, :dim]

    stats = np.zeros(EVAL_LEN, dtype=np.float64)
    for lo in range(0, len(triples), batch):
        t = triples[lo:lo + batch]
        s, r, o = t[:, 0], t[:, 1], t[:, 2]
        with srv._lock:
            tables = router.tables()
            pools = (srv.stores[run.ent_class].main,) if shared else \
                (srv.stores[run.ent_class].main,
                 srv.stores[run.rel_class].main)
            g_o, g_s, true_sc = counts_fn(
                *pools, tables, ent_keys_dev,
                np.int32(run.E), put(run.ekey(s)), put(run.rkey(r)),
                put(run.ekey(o)))
        g_o = np.asarray(g_o).astype(np.int64)
        g_s = np.asarray(g_s).astype(np.int64)
        true_sc = np.asarray(true_sc)
        _filter_correct(run, emb_rows, s, r, o, g_o, g_s, true_sc,
                        sr_o, ro_s)
        stats[:4] += _rank_side_stats(g_o)
        stats[4:] += _rank_side_stats(g_s)
    return stats


def _filter_correct(run, emb_rows, s, r, o, g_o, g_s, true_sc,
                    sr_o, ro_s) -> None:
    """Filtered-rank correction (in place on g_o/g_s): subtract the
    (tiny) per-triple filter sets' contributions, scored on host from a
    handful of pool rows."""
    from ..models.kge import score_numpy
    for g, fi, fe, true_e, q in (
            (g_o, *_flt_pairs(list(zip(s.tolist(), r.tolist())), sr_o),
             o, "o"),
            (g_s, *_flt_pairs(list(zip(r.tolist(), o.tolist())), ro_s),
             s, "s")):
        if not len(fi):
            continue
        fe_rows = emb_rows(run.ekey(fe), run.ent_dim)
        r_rows = emb_rows(run.rkey(r[fi]), run.rel_dim)
        if q == "o":
            sc_f = score_numpy(run.args.model,
                               emb_rows(run.ekey(s[fi]), run.ent_dim),
                               r_rows, fe_rows)
        else:
            sc_f = score_numpy(run.args.model, fe_rows, r_rows,
                               emb_rows(run.ekey(o[fi]), run.ent_dim))
        contrib = (sc_f > true_sc[fi]) & (fe != true_e[fi])
        np.subtract.at(g, fi, contrib.astype(np.int64))
        # host f64 vs device f32 can disagree by an ulp at a tie: a
        # filter entity the device never counted must not push the
        # count negative (rank 0 -> infinite MRR)
        np.maximum(g, 0, out=g)


def _evaluate_pool_mp(run: KgeRun, triples: np.ndarray, batch: int):
    """Candidate-partitioned pool eval across processes (VERDICT r4 item
    5). Every rank walks the SAME full triple set; each scores only the
    entities it OWNS, gathered from its local pool (each entity has
    exactly one owner, so the per-rank greater-counts allreduce-SUM to
    exactly the global counts — reference distributed Evaluator,
    kge.cc:544-775). Query rows come via Server.read_main (remote owners
    resolve over the DCN channel), the true score is a shared
    shape-identical executable so its bytes match on every rank
    (models/kge.make_true_score), and ONE collective per evaluate() call
    merges the counts. No dense entity matrix, no remote candidate-row
    fetches. Contract: all ranks call evaluate() together with identical
    `triples` (the quiesced, no-training-in-flight state the dense mp
    path already assumed)."""
    from ..models.kge import make_pool_eval_counts_mp, make_true_score
    from ..ops import DeviceRouter
    from ..parallel import control
    srv = run.srv
    C = min(run.args.eval_chunk, max(run.E, 8))
    put = srv.ctx.put_replicated
    if run._pool_eval_mp is None or run._pool_eval_chunk != C:
        run._pool_eval_mp = make_pool_eval_counts_mp(
            run.args.model, run.ent_dim, run.rel_dim, C)
        run._true_score = make_true_score(run.args.model)
        run._pool_eval_chunk = C
        run._pool_eval_topo = -1
        run._pool_eval_router = DeviceRouter(srv, 0)
    topo = srv.topology_version
    if run._pool_eval_topo != topo:
        # the owned set follows relocations: rebuild the candidate tiles
        # whenever placement changed since the last eval
        ekeys = run.ekey(np.arange(run.E)).astype(np.int64)
        with srv._lock:
            owned = ekeys[srv.ab.owner[ekeys] >= 0]
        nown = len(owned)
        if nown:
            nch = -(-nown // C)
            pad = np.full(nch * C, owned[0], dtype=np.int64)
            pad[:nown] = owned
            run._pool_eval_keys = put(pad.reshape(nch, C))
        else:  # a rank may own no entities; it still joins the merge
            run._pool_eval_keys = None
        run._pool_eval_n = nown
        run._pool_eval_topo = topo
    counts_fn = run._pool_eval_mp
    router = run._pool_eval_router
    sr_o, ro_s = run.ds.filters()

    def emb_rows(keys, dim):
        rows = np.asarray(srv.read_main(keys)).reshape(len(keys), -1)
        return rows[:, :dim]

    T = len(triples)
    G_o = np.zeros(T, dtype=np.int64)
    G_s = np.zeros(T, dtype=np.int64)
    true_all = np.zeros(T, dtype=np.float32)
    for lo in range(0, T, batch):
        t = triples[lo:lo + batch]
        s, r, o = t[:, 0], t[:, 1], t[:, 2]
        se = put(emb_rows(run.ekey(s), run.ent_dim))
        re_ = put(emb_rows(run.rkey(r), run.rel_dim))
        oe = put(emb_rows(run.ekey(o), run.ent_dim))
        t_sc = run._true_score(se, re_, oe)
        true_all[lo:lo + len(t)] = np.asarray(t_sc)
        if run._pool_eval_n:
            with srv._lock:
                tables = router.tables()
                g_o, g_s = counts_fn(
                    srv.stores[run.ent_class].main, tables,
                    run._pool_eval_keys, np.int32(run._pool_eval_n),
                    se, re_, oe, put(run.ekey(s)), put(run.ekey(o)),
                    t_sc)
            G_o[lo:lo + len(t)] = np.asarray(g_o)
            G_s[lo:lo + len(t)] = np.asarray(g_s)
    # merge the candidate partitions: ONE collective per evaluate() call.
    # The preceding coordination-service barrier absorbs per-rank count/
    # compile skew vs the backend's ~30 s collective-context deadline
    # (same pattern as parallel/collective.py's first-exchange barrier).
    control.barrier("adapm-eval-merge")
    gg = control.allreduce(
        np.concatenate([G_o, G_s]).astype(np.float64), "sum",
        site="eval-merge")
    G_o = gg[:T].astype(np.int64)
    G_s = gg[T:].astype(np.int64)

    # correction + stats over GLOBAL counts, identical on every rank
    stats = np.zeros(EVAL_LEN, dtype=np.float64)
    for lo in range(0, T, batch):
        t = triples[lo:lo + batch]
        s, r, o = t[:, 0], t[:, 1], t[:, 2]
        g_o = G_o[lo:lo + len(t)]
        g_s = G_s[lo:lo + len(t)]
        _filter_correct(run, emb_rows, s, r, o, g_o, g_s,
                        true_all[lo:lo + len(t)], sr_o, ro_s)
        stats[:4] += _rank_side_stats(g_o)
        stats[4:] += _rank_side_stats(g_s)
    return stats


def _eval_global(run: KgeRun, triples: np.ndarray) -> np.ndarray:
    """Global filtered-eval stats across processes. Pool path
    (--eval_chunk > 0) multi-process: candidate-partitioned — every rank
    walks the full triple set and the counts merge INSIDE evaluate(), so
    its return is already global (identical on all ranks). Dense path /
    single process: triples split over ranks, partial stats merged by
    the PS-key allreduce (reference distributed Evaluator idiom)."""
    from ..parallel import control
    P = control.num_processes()
    if P > 1 and run.args.eval_chunk > 0:
        return evaluate(run, triples)
    part = np.array_split(triples, P)[control.process_id()]
    stats = evaluate(run, part)
    if P == 1:
        return np.asarray(stats, dtype=np.float64)
    agg = np.asarray(run.allreduce(run.eval_key_l, stats),
                     dtype=np.float64)
    run.reset_key(run.eval_key_l, EVAL_LEN)
    return agg


def run_app(args) -> dict:
    truth_mrr = None
    if args.train:
        ds = kgeio.load_dataset(args.train, args.valid, args.test,
                                args.num_entities or None,
                                args.num_relations or None)
    elif args.synthetic_mode == "lowrank":
        ds, truth_mrr = kgeio.generate_lowrank(
            num_entities=args.synthetic_entities,
            num_relations=args.synthetic_relations,
            n_train=args.synthetic_triples, seed=args.seed,
            dim_truth=args.gen_dim_truth, temperature=args.gen_temperature)
        alog(f"[kge] lowrank synthetic: generating-model filtered "
             f"MRR ceiling = {truth_mrr:.4f} (o={ds.truth_mrr_o:.4f} "
             f"s={ds.truth_mrr_s:.4f})")
    else:
        ds = kgeio.generate_synthetic(
            num_entities=args.synthetic_entities,
            num_relations=args.synthetic_relations,
            n_train=args.synthetic_triples, seed=args.seed)
    run = KgeRun(args, ds)
    run.init_model()
    if args.enforce_full_replication:
        enforce_full_replication(run.workers, run.E + run.R)

    B, N = args.batch_size, args.neg_ratio
    srv, workers = run.srv, run.workers
    # negative sampling over entities. uniform = the reference's scheme
    # (kge.cc draws uniform entities); freq = unigram^pow over the
    # training-triple entity frequencies (word2vec's noise distribution
    # applied to KGE — hits the populated region of the entity space,
    # part of the mid-scale fix alongside --self_adv_temp). The Local
    # scheme may only snap within the entity key population.
    neg_alias = None
    if args.neg_sampling == "freq":
        from ..models.sgns import build_alias_table
        counts = (np.bincount(ds.train[:, 0], minlength=run.E)
                  + np.bincount(ds.train[:, 2], minlength=run.E)
                  + 1.0)
        neg_alias = build_alias_table(counts, power=args.neg_freq_pow)

        def host_neg(n, r):
            prob, alias = neg_alias
            u = r.integers(0, run.E, n)
            keep = r.random(n) < prob[u]
            return run.ekey(np.where(keep, u, alias[u]))

        srv.enable_sampling_support(
            host_neg, allowed_keys=run.ekey(np.arange(run.E)))
    else:
        srv.enable_sampling_support(
            lambda n, r: run.ekey(r.integers(0, run.E, n)),
            allowed_keys=run.ekey(np.arange(run.E)))

    # --device_routes: the production TPU hot path — routing tables and
    # negative sampling (Local scheme, uniform or alias-table freq) live
    # on device; one runner per worker shard (docs/PERF.md: ~2.4x over
    # host routing)
    dev_runners = {}

    def device_runner(shard: int) -> DeviceRoutedRunner:
        if shard not in dev_runners:
            dev_runners[shard] = DeviceRoutedRunner(
                srv, make_kge_loss(args.model, args.self_adv_temp, args.l2),
                role_class={"s": run.ent_class, "r": run.rel_class,
                            "o": run.ent_class, "neg": run.ent_class},
                role_dim={"s": run.ent_dim, "r": run.rel_dim,
                          "o": run.ent_dim, "neg": run.ent_dim},
                shard=shard, neg_role="neg", neg_shape=(B, N),
                neg_population=run.ekey(np.arange(run.E)),
                neg_alias=neg_alias, seed=args.seed + shard)
        return dev_runners[shard]

    train = ds.train
    # data parallelism over ALL workers of ALL processes (kge.cc:968-970)
    parts = global_worker_slices(len(train), run.num_workers)
    rng = np.random.default_rng(args.seed)
    guard = RuntimeGuard(args.max_runtime)
    watch = Stopwatch(start=True)
    result = {}
    if truth_mrr is not None:
        result["truth_mrr"] = truth_mrr
        result["truth_mrr_o"] = ds.truth_mrr_o
        result["truth_mrr_s"] = ds.truth_mrr_s

    for epoch in range(args.epochs):
        # per-epoch step size: AdaGrad already decays effective rates, but
        # an explicit multiplicative schedule helps late-stage ranking
        # quality on the lowrank harness (docs/PERF.md "Quality");
        # --lr_decay 1.0 = the reference's constant-lr behavior
        lr_epoch = args.lr * (args.lr_decay ** epoch)
        # losses stay device scalars until epoch end: a float() per step
        # would serialize host and device (docs/PERF.md gap analysis)
        epoch_losses = []
        for wi, w in enumerate(workers):
            mine = parts[wi]
            batches = [mine[idx] for idx in
                       wrap_batches(len(mine), B, rng)]
            handles = {}
            staged = {}  # bi -> (roles, StagedKeys) pre-uploaded batches
            prepared_hi = -1  # highest batch index already prepared

            def triple_roles(t):
                # the ONE logical->physical role mapping for a triple
                # batch (prepare, staged-miss fallback, and host path
                # must all agree)
                return {"s": run.ekey(t[:, 0]), "r": run.rkey(t[:, 1]),
                        "o": run.ekey(t[:, 2])}

            def prepare(bi: int, ahead: int) -> None:
                # the scan-window loop prepares up to lo+look+K ahead; the
                # tail loop would otherwise re-prepare those indices at the
                # same fut clock (duplicate intent RPC per epoch tail)
                nonlocal prepared_hi
                if bi <= prepared_hi:
                    return
                prepared_hi = bi
                t = train[batches[bi]]
                roles = triple_roles(t)
                ks = np.unique(np.concatenate(
                    [roles["s"], roles["r"], roles["o"]]))
                fut = w.current_clock + ahead
                w.intent(ks, fut, fut + 1)
                if not args.device_routes:
                    handles[bi] = w.prepare_sample(B * N, fut, fut + 1)
                elif srv.prefetch is not None and K == 1:
                    # prefetch pipeline on: the batch's key upload rides
                    # the prepare path (DeviceRoutedRunner.prefetch_keys)
                    # instead of the dispatch critical section
                    staged[bi] = (roles, device_runner(w.shard)
                                  .prefetch_keys(roles))

            K = max(1, args.scan_steps) if args.device_routes else 1
            for bi in range(min(max(args.lookahead, K), len(batches))):
                prepare(bi, ahead=bi)
            if K > 1:
                # K-step scan windows (runner.run_scan): ONE dispatch
                # trains K batches; intents run a window ahead and the K
                # planner rounds + clock ticks execute while the device
                # works through the window (VERDICT r3 item 2). The tail
                # window short of K batches falls back to per-step.
                look = max(args.lookahead, K)
                for lo in range(0, len(batches) - len(batches) % K, K):
                    for bi in range(lo + look,
                                    min(lo + look + K, len(batches))):
                        prepare(bi, ahead=bi - lo)
                    window = [train[batches[lo + j]] for j in range(K)]
                    roles = [triple_roles(t) for t in window]
                    epoch_losses.append(
                        device_runner(w.shard).run_scan(
                            roles, None, lr_epoch))
                    srv.drive_rounds(K * args.sync_rounds_per_step)
                    for _ in range(K):
                        w.advance_clock()
                tail_start = len(batches) - len(batches) % K
            else:
                tail_start = 0
            for bi in range(tail_start, len(batches)):
                idx = batches[bi]
                if bi + args.lookahead < len(batches):
                    prepare(bi + args.lookahead, ahead=args.lookahead)
                if args.device_routes:
                    pre = staged.pop(bi, None)
                    if pre is not None:  # keys already on device
                        roles, stg = pre
                        loss = device_runner(w.shard)(roles, None,
                                                      lr_epoch, staged=stg)
                    else:
                        loss = device_runner(w.shard)(
                            triple_roles(train[idx]), None, lr_epoch)
                else:
                    roles = triple_roles(train[idx])
                    neg = np.asarray(
                        w.pull_sample_keys(handles[bi], B * N)).reshape(B, N)
                    w.finish_sample(handles.pop(bi))
                    roles["neg"] = neg
                    loss = run.runner(roles, None, lr_epoch,
                                      shard=w.shard)
                epoch_losses.append(loss)
                srv.drive_rounds(args.sync_rounds_per_step)
                w.advance_clock()
        srv.quiesce()

        # scan windows contribute [K] loss vectors, per-step path scalars
        epoch_loss = float(np.sum([np.asarray(l).sum()
                                   for l in epoch_losses]))
        nbatches = int(np.sum([np.asarray(l).size for l in epoch_losses]))
        # loss aggregation through the PS loss key (ps_allreduce idiom)
        total = run.allreduce(run.loss_key_l,
                              np.array([epoch_loss / max(nbatches, 1)]))
        run.reset_key(run.loss_key_l, 1)
        epoch_report("kge", epoch, float(total[0]), watch)
        result["loss"] = float(total[0])

        if args.eval_every and (epoch + 1) % args.eval_every == 0 and \
                ds.valid is not None and len(ds.valid):
            agg = _eval_global(run, ds.valid[:args.eval_triples])
            cnt = max(float(agg[3]) + float(agg[7]), 1.0)
            result.update(
                mrr=(float(agg[0]) + float(agg[4])) / cnt,
                hits1=(float(agg[1]) + float(agg[5])) / cnt,
                hits10=(float(agg[2]) + float(agg[6])) / cnt,
                mrr_o=float(agg[0]) / max(float(agg[3]), 1.0),
                mrr_s=float(agg[4]) / max(float(agg[7]), 1.0))
            alog(f"[kge] epoch {epoch}: filtered MRR={result['mrr']:.4f} "
                 f"(o={result['mrr_o']:.4f} s={result['mrr_s']:.4f}) "
                 f"Hits@1={result['hits1']:.4f} "
                 f"Hits@10={result['hits10']:.4f}")
        if args.checkpoint_every and \
                (epoch + 1) % args.checkpoint_every == 0:
            from .common import is_rank0
            if is_rank0():
                os.makedirs(args.checkpoint_dir, exist_ok=True)
                run.checkpoint(os.path.join(
                    args.checkpoint_dir, f"kge_epoch{epoch}.npz"))
        if guard.expired():
            alog("[kge] max_runtime reached")
            break

    if ds.test is not None and len(ds.test) and args.eval_every:
        agg = _eval_global(run, ds.test[:args.eval_triples])
        cnt = max(float(agg[3]) + float(agg[7]), 1.0)
        result.update(
            test_mrr=(float(agg[0]) + float(agg[4])) / cnt,
            test_hits10=(float(agg[2]) + float(agg[6])) / cnt,
            test_mrr_o=float(agg[0]) / max(float(agg[3]), 1.0),
            test_mrr_s=float(agg[4]) / max(float(agg[7]), 1.0))
        alog(f"[kge] TEST filtered MRR={result['test_mrr']:.4f} "
             f"(o={result['test_mrr_o']:.4f} s={result['test_mrr_s']:.4f}) "
             f"Hits@10={result['test_hits10']:.4f}")
    # mean entity-row L2 norm: regularization evidence (--l2 must shrink
    # it; tests/test_apps.py test_kge_l2_regularizer_shrinks_norms)
    ent = srv.read_main(run.ekey(np.arange(min(run.E, 2048)))).reshape(
        -1, 2 * run.ent_dim)[:, : run.ent_dim]
    result["ent_norm"] = float(np.sqrt((ent * ent).sum(axis=1)).mean())
    alog("[kge]", srv.sync.report())
    srv.shutdown()
    return result


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--model", default="complex",
                        choices=["complex", "rescal"])
    parser.add_argument("--dim", type=int, default=16)
    parser.add_argument("--neg_ratio", type=int, default=4)
    parser.add_argument("--train", default=None, help="triples file (s r o)")
    parser.add_argument("--valid", default=None)
    parser.add_argument("--test", default=None)
    parser.add_argument("--num_entities", type=int, default=0)
    parser.add_argument("--num_relations", type=int, default=0)
    parser.add_argument("--synthetic_entities", type=int, default=120)
    parser.add_argument("--synthetic_relations", type=int, default=8)
    parser.add_argument("--synthetic_triples", type=int, default=1500)
    parser.add_argument("--synthetic_mode", default="permutation",
                        choices=["permutation", "lowrank"],
                        help="lowrank = drawn from a ground-truth ComplEx "
                             "model (learnable by construction)")
    parser.add_argument("--gen_dim_truth", type=int, default=16,
                        help="lowrank generator: rank of the ground-truth "
                             "ComplEx model")
    parser.add_argument("--gen_temperature", type=float, default=0.25,
                        help="lowrank generator: softmax temperature for "
                             "object sampling (higher = flatter object "
                             "marginal, lower truth ceiling)")
    parser.add_argument("--lookahead", type=int, default=4,
                        help="intent/sample batches ahead (kge.cc :1059)")
    parser.add_argument("--lr_decay", type=float, default=1.0,
                        help="multiplicative per-epoch lr decay "
                             "(1.0 = constant, the reference behavior)")
    parser.add_argument("--scan_steps", type=int, default=1,
                        help="K>1: train K batches per device dispatch "
                             "(lax.scan window, runner.run_scan; device "
                             "routing only — amortizes dispatch overhead)")
    parser.add_argument("--device_routes",
                        action=argparse.BooleanOptionalAction, default=True,
                        help="device-routed fused step + on-device "
                             "negative sampling (TPU hot path; default on,"
                             " --no-device_routes for host routing)")
    parser.add_argument("--neg_sampling", default="uniform",
                        choices=["uniform", "freq"],
                        help="negative entity distribution: uniform "
                             "(kge.cc) or unigram^pow over train-triple "
                             "frequencies (mid-scale fix, docs/PERF.md)")
    parser.add_argument("--neg_freq_pow", type=float, default=0.75,
                        help="power for --neg_sampling freq")
    parser.add_argument("--self_adv_temp", type=float, default=0.0,
                        help="self-adversarial negative weighting "
                             "temperature (RotatE eq. 5; 0 = off)")
    parser.add_argument("--l2", type=float, default=0.0,
                        help="lazy L2 on the positive triple's embedding "
                             "rows (ComplEx-paper regularizer; 0 = the "
                             "reference's unregularized loss)")
    parser.add_argument("--init_scheme", default="normal",
                        choices=["normal", "uniform"])
    parser.add_argument("--init_scale", type=float, default=0.1)
    parser.add_argument("--init_from", default=None,
                        help="checkpoint .npz to resume from")
    parser.add_argument("--adagrad_init", type=float, default=1e-6)
    parser.add_argument("--eval_every", type=int, default=2)
    parser.add_argument("--eval_triples", type=int, default=500)
    parser.add_argument("--eval_chunk", type=int, default=65536,
                        help="candidate-chunk size for pool-gather eval "
                             "(device [B, C] tiles; 0 = dense-matrix "
                             "fallback)")
    parser.add_argument("--checkpoint_every", type=int, default=0)
    parser.add_argument("--checkpoint_dir", default="/tmp/adapm_kge_ckpt")
    add_common_arguments(parser)
    return parser


def main(argv=None) -> int:
    run_app(build_parser().parse_args(argv))
    return 0


if __name__ == "__main__":
    sys.exit(main())
