"""SGNS word2vec app (reference apps/word2vec.cc).

Two PM keys per word — syn0 (input) = 2w, syn1 (output) = 2w+1
(word2vec.cc:83-105); unigram^0.75 negative table (:125-144); AdaGrad; the
logical clock advances per sentence and a read-ahead pipeline (default 1000
sentences, :561-626) signals `Intent` + `PrepareSample` for future sentences.
Pair generation for a future sentence is precomputed with a per-sentence
seeded RNG — the moral equivalent of the reference's PeekableRandom
(:445-491), which pre-draws future window sizes.

Training pairs accumulate into fixed-size batches for the fused
gather -> SGNS loss -> AdaGrad -> scatter-add program (ops/fused.py).

Run: python -m adapm_tpu.apps.word2vec --synthetic ...
"""
from __future__ import annotations

import argparse
import sys
from collections import deque
from typing import List

import numpy as np

from ..io import text as textio
from ..models.sgns import (build_alias_table, build_unigram_table,
                           sgns_loss, subsample_mask, syn0_key, syn1_key)
from ..ops import DeviceRoutedRunner, FusedStepRunner
from ..utils import Stopwatch, alog
from .common import (KeyMapper, RuntimeGuard, ScanWindow,
                     add_common_arguments, enforce_full_replication,
                     epoch_report, global_worker_slices, make_server,
                     worker0_init)


def _pairs_for(sent: np.ndarray, sent_idx: int, window: int, seed: int,
               counts=None, total: int = 0, sample_t: float = 0.0):
    """Deterministic pairs for a sentence — identical at intent time and at
    train time (PeekableRandom pattern). Frequent-word subsampling
    (word2vec.cc --sample) is applied before pair generation, also
    deterministically per sentence."""
    rng = np.random.default_rng(seed * 1_000_003 + sent_idx)
    if sample_t > 0 and counts is not None:
        sent = sent[subsample_mask(counts, sent, total, sample_t, rng)]
    return textio.skipgram_pairs(sent, window, rng)


def run(args) -> float:
    if args.data:
        corpus = args.data
    else:
        corpus = args.synthetic_path or "/tmp/adapm_w2v_corpus.txt"
        textio.generate_synthetic_corpus(
            corpus, vocab_size=args.synthetic_vocab,
            num_sentences=args.synthetic_sentences, seed=args.seed)
    words, counts, vocab = textio.build_vocab(corpus, args.min_count)
    total_words = int(counts.sum())
    V, d = len(words), args.dim
    if V == 0:
        raise SystemExit("empty vocabulary")
    sents: List[np.ndarray] = list(textio.sentences(corpus, vocab))
    num_keys = 2 * V

    kmap = KeyMapper(num_keys, args.enforce_random_keys, seed=args.seed)
    srv = make_server(args, num_keys, value_lengths=2 * d,
                      num_workers=args.num_workers or None)
    num_workers = args.num_workers or srv.num_shards
    workers = [srv.make_worker(i) for i in range(num_workers)]

    # init: syn0 ~ U[-.5/d, .5/d], syn1 = 0 (classic w2v); [emb | adagrad]
    rng = np.random.default_rng(args.seed)
    init = np.zeros((num_keys, 2 * d), dtype=np.float32)
    init[syn0_key(np.arange(V)), :d] = \
        (rng.random((V, d)).astype(np.float32) - 0.5) / d
    init[:, d:] = args.adagrad_init
    worker0_init(workers, kmap(np.arange(num_keys)), init)
    if args.enforce_full_replication:
        enforce_full_replication(workers, num_keys)

    # negative sampling: unigram^0.75 over words -> syn1 physical keys; the
    # Local scheme may only snap to other syn1 keys (never syn0)
    word_sampler = build_unigram_table(counts)
    srv.enable_sampling_support(
        lambda n, r: kmap(syn1_key(word_sampler(n, r))),
        allowed_keys=kmap(syn1_key(np.arange(V))))

    runner = FusedStepRunner(
        srv, sgns_loss, role_class={"center": 0, "ctx": 0, "neg": 0},
        role_dim={k: d for k in ("center", "ctx", "neg")})

    B, N = args.batch_size, args.negative

    # --device_routes: negatives drawn IN-PROGRAM from the unigram^0.75
    # alias table with a Local-scheme snap (the reference's negative table,
    # word2vec.cc:125-144, as two O(V) HBM arrays); per step the host ships
    # only the center/context key batch
    dev_runners = {}

    def device_runner(shard: int) -> DeviceRoutedRunner:
        if shard not in dev_runners:
            dev_runners[shard] = DeviceRoutedRunner(
                srv, sgns_loss,
                role_class={"center": 0, "ctx": 0, "neg": 0},
                role_dim={k: d for k in ("center", "ctx", "neg")},
                shard=shard, neg_role="neg", neg_shape=(B, N),
                neg_population=kmap(syn1_key(np.arange(V))),
                neg_alias=build_alias_table(counts),
                seed=args.seed + shard)
        return dev_runners[shard]
    guard = RuntimeGuard(args.max_runtime)
    watch = Stopwatch(start=True)
    mean_loss = 0.0

    # per-worker contiguous sentence partition over all processes'
    # workers (reference :524-531)
    slices = global_worker_slices(len(sents), num_workers)

    # --scan_steps K (device-routed only): buffer K materialized batches
    # and train them in ONE lax.scan dispatch (runner.run_scan — same
    # contract as the KGE app: placement frozen per window, negative RNG
    # identical to K sequential steps). Clocks still advance per
    # SENTENCE; a buffered batch waits up to ~K*B/pairs-per-sentence
    # clocks before dispatch, so intent windows are extended by a slack
    # estimated from the corpus (otherwise replicas could expire while a
    # batch sits in the window).
    K = max(1, args.scan_steps) if args.device_routes else 1
    scan_slack = 0
    if K > 1:
        probe = [len(_pairs_for(sents[si], si, args.window, args.seed,
                                counts, total_words, args.sample)[0])
                 for si in range(min(50, len(sents)))]
        est_pairs = max(1.0, float(np.mean(probe)) if probe else 1.0)
        scan_slack = int(np.ceil(K * B / est_pairs)) * 2 + K

    for epoch in range(args.epochs):
        losses = []
        for wi, w in enumerate(workers):
            my = slices[wi].tolist()
            # (sent position, sample handle) for prepared future sentences
            prepared: deque = deque()
            buf_c: List[np.ndarray] = []
            buf_x: List[np.ndarray] = []
            buf_n: List[np.ndarray] = []

            def prepare(pos: int, ahead: int) -> None:
                """Signal intent + prepare negatives for the sentence that
                will be trained `ahead` clocks from now."""
                si = my[pos]
                c, x = _pairs_for(sents[si], si, args.window, args.seed,
                                  counts, total_words, args.sample)
                if len(c) == 0:
                    prepared.append((pos, None, c, x))
                    return
                fut = w.current_clock + ahead
                ks = np.unique(np.concatenate(
                    [kmap(syn0_key(c)), kmap(syn1_key(x))]))
                w.intent(ks, fut, fut + 1 + scan_slack)
                h = None if args.device_routes else \
                    w.prepare_sample(len(c) * N, fut, fut + 1)
                prepared.append((pos, h, c, x))

            # prime the pipeline
            for pos in range(min(args.readahead, len(my))):
                prepare(pos, ahead=pos)

            scan_win = ScanWindow(srv, K, args.sync_rounds_per_step,
                                  on_loss=losses.append)

            n_buf = 0
            for pos in range(len(my)):
                if pos + args.readahead < len(my):
                    prepare(pos + args.readahead, ahead=args.readahead)
                _, h, c, x = prepared.popleft()
                if len(c):
                    if h is not None:
                        negk = w.pull_sample_keys(h, len(c) * N)
                        w.finish_sample(h)
                        buf_n.append(np.asarray(negk).reshape(len(c), N))
                    buf_c.append(kmap(syn0_key(c)))
                    buf_x.append(kmap(syn1_key(x)))
                    n_buf += len(c)

                def step(cc, xx, nn):
                    if args.device_routes:
                        return device_runner(w.shard)(
                            {"center": cc, "ctx": xx}, None, args.lr)
                    return runner({"center": cc, "ctx": xx, "neg": nn},
                                  None, args.lr, shard=w.shard)

                while n_buf >= B:
                    cc = np.concatenate(buf_c)
                    xx = np.concatenate(buf_x)
                    nn = np.concatenate(buf_n) if buf_n else None
                    if K > 1:
                        scan_win.add(device_runner(w.shard),
                                     {"center": cc[:B], "ctx": xx[:B]},
                                     None, args.lr)
                    else:
                        losses.append(step(cc[:B], xx[:B],
                                           None if nn is None else nn[:B]))
                        # inline rounds, or delegated to the prefetch
                        # pipeline so planner work overlaps the step
                        srv.drive_rounds(args.sync_rounds_per_step)
                    buf_c, buf_x = [cc[B:]], [xx[B:]]
                    buf_n = [] if nn is None else [nn[B:]]
                    n_buf -= B
                w.advance_clock()
            scan_win.flush(args.lr)  # partial window at worker end
            # tail: wrap-pad the remaining pairs into one final batch
            if n_buf > 0:
                cc = np.concatenate(buf_c)
                xx = np.concatenate(buf_x)
                nn = np.concatenate(buf_n) if buf_n else None
                reps = -(-B // len(cc))
                losses.append(step(
                    np.tile(cc, reps)[:B], np.tile(xx, reps)[:B],
                    None if nn is None else np.tile(nn, (reps, 1))[:B]))
        srv.quiesce()
        # scan windows contribute [K] loss vectors, per-step path scalars
        mean_loss = float(np.mean(np.concatenate(
            [np.ravel(np.asarray(l)) for l in losses]))) if losses else 0.0
        from ..parallel import control
        mean_loss = float(control.allreduce(mean_loss, "mean")[0])
        epoch_report("w2v", epoch, mean_loss, watch)
        if args.export_prefix and control.process_id() == 0:
            _export(srv, kmap, words, d,
                    f"{args.export_prefix}epoch{epoch}.txt")
        if guard.expired():
            alog("[w2v] max_runtime reached")
            break

    alog("[w2v]", srv.sync.report())
    srv.shutdown()
    return mean_loss


def _export(srv, kmap, words, d, path: str) -> None:
    """Write syn0 embeddings in the classic word2vec text format (the
    reference writes epoch embeddings, word2vec.cc:367-416)."""
    V = len(words)
    flat = srv.read_main(kmap(syn0_key(np.arange(V))))
    emb = flat.reshape(V, 2 * d)[:, :d]
    with open(path, "w") as f:
        f.write(f"{V} {d}\n")
        for w, row in zip(words, emb):
            f.write(w + " " + " ".join(f"{v:.6f}" for v in row) + "\n")


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--data", default=None, help="corpus text file")
    parser.add_argument("--synthetic_path", default=None)
    parser.add_argument("--synthetic_vocab", type=int, default=200)
    parser.add_argument("--synthetic_sentences", type=int, default=300)
    parser.add_argument("--dim", type=int, default=32)
    parser.add_argument("--window", type=int, default=5)
    parser.add_argument("--negative", type=int, default=5)
    parser.add_argument("--min_count", type=int, default=1)
    parser.add_argument("--sample", type=float, default=1e-3,
                        help="frequent-word subsampling threshold "
                             "(word2vec.cc --sample; 0 disables)")
    parser.add_argument("--readahead", type=int, default=1000,
                        help="sentences of intent/sample lookahead")
    parser.add_argument("--scan_steps", type=int, default=1,
                        help="batches trained per device dispatch "
                             "(lax.scan window, runner.run_scan; device "
                             "routing only — same contract as the KGE "
                             "app's --scan_steps)")
    parser.add_argument("--device_routes",
                        action=argparse.BooleanOptionalAction, default=True,
                        help="device-routed fused step + in-program "
                             "unigram^0.75 negatives (TPU hot path; default "
                             "on, --no-device_routes for host routing)")
    parser.add_argument("--adagrad_init", type=float, default=1e-6)
    parser.add_argument("--export_prefix", default=None)
    add_common_arguments(parser)
    return parser


def main(argv=None) -> int:
    run(build_parser().parse_args(argv))
    return 0


if __name__ == "__main__":
    sys.exit(main())
