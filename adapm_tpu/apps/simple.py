"""Smoke-test app (reference apps/simple.cc:36-67): every worker repeatedly
declares intent on a key, pushes {1}, advances its clock, and pulls —
asserting at the end that the aggregate value equals the total pushed.

Run: python -m adapm_tpu.apps.simple [--iterations 10]
"""
from __future__ import annotations

import argparse
import sys

import numpy as np

from ..utils import alog
from .common import add_common_arguments, make_server


def run(args) -> bool:
    num_keys = 32
    srv = make_server(args, num_keys, value_lengths=2,
                      num_workers=args.num_workers or None)
    workers = [srv.make_worker(i)
               for i in range(args.num_workers or srv.num_shards)]

    key = np.array([7], dtype=np.int64)
    per_iter = np.array([1.0, 2.0], dtype=np.float32)
    for it in range(args.iterations):
        for w in workers:
            w.intent(key, w.current_clock, w.current_clock + 2)
            w.push(key, per_iter)
            w.advance_clock()
        srv.sync.run_round(force_intents=True, all_channels=True)
    for w in workers:
        w.wait_all()
    srv.quiesce()

    expect = per_iter * args.iterations * len(workers)
    vals = [w.pull_sync(key)[0] for w in workers]
    main = srv.read_main(key)
    ok = all(np.allclose(v, expect) for v in vals) and \
        np.allclose(main, expect)
    alog(f"[simple] expect={expect.tolist()} main={main.tolist()} "
         f"{'PASSED' if ok else 'FAILED'}")
    srv.shutdown()
    return ok


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--iterations", type=int, default=10)
    add_common_arguments(parser)
    args = parser.parse_args(argv)
    return 0 if run(args) else 1


if __name__ == "__main__":
    sys.exit(main())
