"""Cluster launcher: the tracker reborn (reference tracker/{tracker.py,
dmlc_local.py,dmlc_ssh.py,dmlc_mpi.py}).

Spawns N copies of a program with the env contract consumed by
`adapm_tpu.parallel.control.init_from_env` (ADAPM_COORDINATOR /
ADAPM_NUM_PROCESSES / ADAPM_PROCESS_ID — the analog of the reference's
DMLC_PS_ROOT_URI/PORT + DMLC_ROLE env rendezvous, docs/env.md). There is no
separate scheduler process: process 0's coordinator service (gRPC inside
jax.distributed) plays that role.

Modes:
  local  N subprocesses on this machine (reference dmlc_local.py), with the
         keepalive contract: a process exiting with code 254 is restarted
         (dmlc_local.py:15-25).
  ssh    fan out over ssh using a hostfile, one process per line
         (reference dmlc_ssh.py).
  mpi    delegate process placement to mpirun (reference dmlc_mpi.py).

Usage: python -m adapm_tpu.launcher -n 2 -- python my_app.py --epochs 4
"""
from __future__ import annotations

import argparse
import os
import shlex
import socket
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional

KEEPALIVE_EXIT_CODE = 254  # reference dmlc_local.py restart contract


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


def make_env(rank: int, num: int, coordinator: str,
             base: Optional[Dict[str, str]] = None) -> Dict[str, str]:
    env = dict(base if base is not None else os.environ)
    env["ADAPM_COORDINATOR"] = coordinator
    env["ADAPM_NUM_PROCESSES"] = str(num)
    env["ADAPM_PROCESS_ID"] = str(rank)
    return env


def launch_local(n: int, cmd: List[str], keepalive: bool = True,
                 coordinator: Optional[str] = None,
                 max_restarts: int = 8,
                 backoff_base_s: float = 0.5,
                 backoff_max_s: float = 30.0) -> int:
    """Run n copies locally; returns the first nonzero exit code (0 if all
    succeed). Keepalive restarts rank processes that exit with 254 —
    with CAPPED EXPONENTIAL BACKOFF and a max-restart budget (ISSUE 10
    satellite: the reference dmlc_local.py contract restarts forever at
    a fixed 0.5 s cadence, so a rank that crashes at startup hot-loops
    indefinitely; here restart k waits min(backoff_base * 2^k,
    backoff_max) and after `max_restarts` restarts the rank's 254 is
    propagated as the job's failure code instead of looping)."""
    coordinator = coordinator or f"localhost:{free_port()}"
    codes = [0] * n
    threads = []

    def run(rank: int) -> None:
        restarts = 0
        while True:
            p = subprocess.Popen(cmd, env=make_env(rank, n, coordinator))
            p.wait()
            if keepalive and p.returncode == KEEPALIVE_EXIT_CODE:
                if restarts >= max_restarts:
                    print(f"[launcher] rank {rank} exhausted its "
                          f"restart budget ({max_restarts}): crash "
                          f"loop — giving up with exit code "
                          f"{p.returncode}", file=sys.stderr)
                    codes[rank] = p.returncode
                    return
                delay = min(backoff_max_s,
                            backoff_base_s * (2.0 ** restarts))
                restarts += 1
                time.sleep(delay)
                continue
            codes[rank] = p.returncode
            return

    for r in range(n):
        t = threading.Thread(target=run, args=(r,), daemon=True)
        t.start()
        threads.append(t)
    for t in threads:
        t.join()
    return next((c for c in codes if c != 0), 0)


def remote_port(seed: Optional[int] = None) -> int:
    """A port for a coordinator that binds on a REMOTE machine: probing a
    local free port (free_port) says nothing about the remote host, so pick
    from a high range instead; pass --coordinator-port to pin one."""
    import random
    return random.Random(seed).randint(20000, 39999)


def launch_ssh(hosts: List[str], cmd: List[str], coordinator_port: int = 0,
               ssh_opts: str = "-o StrictHostKeyChecking=no") -> int:
    """One process per host line (reference dmlc_ssh.py). The first host
    runs process 0 and the coordinator."""
    n = len(hosts)
    port = coordinator_port or remote_port()
    coordinator = f"{hosts[0]}:{port}"
    procs = []
    for rank, host in enumerate(hosts):
        envs = " ".join(
            f"{k}={shlex.quote(v)}"
            for k, v in [("ADAPM_COORDINATOR", coordinator),
                         ("ADAPM_NUM_PROCESSES", str(n)),
                         ("ADAPM_PROCESS_ID", str(rank))])
        remote = f"cd {shlex.quote(os.getcwd())} && {envs} " + \
            " ".join(shlex.quote(c) for c in cmd)
        procs.append(subprocess.Popen(
            ["ssh"] + ssh_opts.split() + [host, remote]))
    code = 0
    for p in procs:
        p.wait()
        code = code or p.returncode
    return code


def launch_mpi(n: int, cmd: List[str], mpirun: str = "mpirun",
               coordinator_port: int = 0) -> int:
    """Delegate to mpirun (reference dmlc_mpi.py): ranks come from
    OMPI_COMM_WORLD_RANK et al; we translate via a tiny bootstrap that maps
    MPI env to the ADAPM contract. Rank 0 may land on another host, so the
    coordinator port comes from remote_port()."""
    coordinator = f"{socket.gethostname()}:{coordinator_port or remote_port()}"
    boot = (
        "import os,subprocess,sys;"
        "r=os.environ.get('OMPI_COMM_WORLD_RANK') or "
        "os.environ.get('PMI_RANK') or '0';"
        f"os.environ['ADAPM_COORDINATOR']='{coordinator}';"
        f"os.environ['ADAPM_NUM_PROCESSES']='{n}';"
        "os.environ['ADAPM_PROCESS_ID']=r;"
        f"sys.exit(subprocess.call({cmd!r}))")
    return subprocess.call([mpirun, "-n", str(n), sys.executable, "-c", boot])


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("-n", "--num-processes", type=int, default=1)
    parser.add_argument("--mode", choices=["local", "ssh", "mpi"],
                        default="local")
    parser.add_argument("--hostfile", default=None,
                        help="ssh mode: one host per line")
    parser.add_argument("--coordinator-port", type=int, default=0,
                        help="pin the coordinator port (ssh/mpi modes)")
    parser.add_argument("--no-keepalive", action="store_true")
    parser.add_argument("--max-restarts", type=int, default=8,
                        help="local mode: keepalive restart budget per "
                        "rank before a crash-looping 254 propagates")
    parser.add_argument("--restart-backoff", type=float, default=0.5,
                        help="local mode: base seconds of the capped "
                        "exponential keepalive restart backoff")
    parser.add_argument("cmd", nargs=argparse.REMAINDER,
                        help="program to launch (prefix with --)")
    args = parser.parse_args(argv)
    cmd = args.cmd[1:] if args.cmd and args.cmd[0] == "--" else args.cmd
    if not cmd:
        parser.error("no command given")
    if args.mode == "local":
        return launch_local(args.num_processes, cmd,
                            keepalive=not args.no_keepalive,
                            max_restarts=args.max_restarts,
                            backoff_base_s=args.restart_backoff)
    if args.mode == "ssh":
        with open(args.hostfile) as f:
            hosts = [h.strip() for h in f if h.strip()]
        return launch_ssh(hosts, cmd, coordinator_port=args.coordinator_port)
    return launch_mpi(args.num_processes, cmd,
                      coordinator_port=args.coordinator_port)


if __name__ == "__main__":
    sys.exit(main())
