"""North-star scenario (ISSUE 20 tentpole c; bench `northstar` phase):
the whole system story on one artifact — a PM that trains
CONTINUOUSLY from a click-event stream while serving multi-tenant
embedding-bag reads, checkpoints incrementally, survives a mid-stream
kill/restore, and captures a `.wtrace` of the run.

One `run_northstar()` call drives, in order:

  1. **segment A** — executor-pumped ingest (`StreamTrainer.start`)
     + inline multi-tenant `lookup_bags` load (gold: hot bags at
     priority 1; bronze: uniform bags on a short deadline) + periodic
     incremental checkpoints (`IncrementalCheckpointer.start_periodic`
     on the `ckpt` stream) + workload-trace capture;
  2. **kill** — the server is shut down mid-stream (the last
     checkpoint link deliberately LAGS the live acked cursor);
  3. **restore** — a fresh server restores the chain
     (`restore_chain`; wall time = the artifact's `recovery_s`), a
     resumed trainer `replay_tail`s the gap between the restored
     cursor and the pre-kill ack watermark (counted loudly into
     `stream.replayed_events_total` — the at-least-once half of the
     drill; tests/test_stream.py pins the exactly-once half bitwise);
  4. **segment B** — ingest + serve resume on the restored state; the
     FreshnessSLO controller walks its levers the whole time and the
     TRAILING window of `flight.freshness_s` scores the closed loop
     (`freshness.p99_ms` — the number ISSUE 20's acceptance compares
     against r18's uncontrolled 3.19 s).

Threading discipline: ingest, checkpoints, and the freshness
controller all run as executor programs (`stream` / `ckpt` /
`stream.slo` streams); the serve load is driven INLINE from the
caller's thread — package code spawns no raw threads (APM004), and
parking a load loop on the shared executor pool would starve the very
programs it measures.
"""
from __future__ import annotations

import os
import time
from typing import Dict, List, Optional

import numpy as np

from ..obs.metrics import hist_percentile
from .ingest import EventLog, StreamTrainer

# the deliberately-lazy static knobs segment A/B start from: the
# controller (not the operator) is what tightens the loop
_STATIC_SYNC_RATE = 2.0
_STATIC_REFRESH_MS = 250.0


def _opts(batch: int, rate: float, slo_ms: float,
          wtrace_path: Optional[str]):
    from ..config import SystemOptions
    return SystemOptions(
        sync_max_per_sec=_STATIC_SYNC_RATE,
        prefetch=False,
        metrics=True,
        trace_flight=True,
        serve_replica_rows=1024,
        serve_replica_refresh_ms=_STATIC_REFRESH_MS,
        serve_max_wait_us=200,
        stream_batch=batch,
        stream_rate=rate,
        stream_freshness_slo_ms=slo_ms,
        trace_workload=wtrace_path,
        trace_workload_keys=256)


def _build(num_keys: int, vlen: int, opts, hot: np.ndarray):
    """Server + warmed serve plane + tenant sessions. Returns
    (server, plane, {tenant: session})."""
    import adapm_tpu
    from ..serve import ServePlane

    srv = adapm_tpu.setup(num_keys, vlen, opts=opts, num_workers=4)
    w = srv.make_worker(0)
    rng = np.random.default_rng(3)
    slab = 4096
    for lo in range(0, num_keys, slab):
        hi = min(lo + slab, num_keys)
        w.set(np.arange(lo, hi),
              rng.normal(size=(hi - lo, vlen)).astype(np.float32))
    srv.block()
    plane = ServePlane(srv)
    plane.configure_tenant("gold", priority=1)
    plane.configure_tenant("bronze", priority=0)
    sessions = {"gold": plane.session(tenant="gold"),
                "bronze": plane.session(tenant="bronze")}
    # score the hot working set into the replica and snapshot it once,
    # so segment reads start on the lock-free path (the refresh lever
    # then governs how stale that path is allowed to run)
    sessions["gold"].lookup(hot)
    if plane.replica is not None:
        plane.replica.refresh_now()
    return srv, plane, sessions


def _serve_segment(srv, sessions, num_keys: int, hot: np.ndarray,
                   seconds: float, seed: int,
                   trailing_s: float = 0.0):
    """Inline multi-tenant bag load for `seconds`. Returns
    (gold_latencies_s, sheds, freshness_snap_at_trailing_mark) — the
    mark is the cumulative `flight.freshness_s` snapshot taken
    `trailing_s` before the segment end (None when trailing_s == 0),
    so the caller can window the tail of the segment."""
    from ..serve import DeadlineExceededError, ServeOverloadError

    rng = np.random.default_rng(seed)
    h_fresh = srv.flight.freshness.h_freshness
    lat: List[float] = []
    sheds = 0
    mark = None
    i = 0
    t_end = time.monotonic() + seconds
    while time.monotonic() < t_end:
        # gold: 16 bags x 4 members from the hot head (replica-covered)
        members = rng.choice(hot, 64).astype(np.int64)
        offs = np.arange(0, 65, 4, dtype=np.int64)
        t0 = time.perf_counter()
        sessions["gold"].lookup_bags([members], [offs])
        lat.append(time.perf_counter() - t0)
        if i % 3 == 0:
            # bronze: uniform members, short deadline — sheds loudly
            # under pressure instead of dragging gold's lane
            mem_b = rng.integers(0, num_keys, 32).astype(np.int64)
            offs_b = np.arange(0, 33, 8, dtype=np.int64)
            try:
                sessions["bronze"].lookup_bags([mem_b], [offs_b],
                                               deadline_ms=25.0)
            except (DeadlineExceededError, ServeOverloadError):
                sheds += 1
        if mark is None and trailing_s > 0 and \
                time.monotonic() >= t_end - trailing_s:
            mark = h_fresh.snap()
        i += 1
    return lat, sheds, mark


def _pctl(sorted_lat: List[float], q: float) -> Optional[float]:
    if not sorted_lat:
        return None
    return sorted_lat[min(len(sorted_lat) - 1,
                          int(q * len(sorted_lat)))]


def run_northstar(num_keys: int = 8192, vlen: int = 16,
                  batch: int = 32, rate: float = 2000.0,
                  freshness_slo_ms: float = 400.0,
                  segment_s: float = 3.0, ckpt_every_s: float = 0.75,
                  trailing_s: float = 1.5, seed: int = 7,
                  workdir: Optional[str] = None) -> Dict:
    """Run the full scenario (module docstring). `workdir` (a fresh
    directory; a tempdir when None) receives the checkpoint chain and
    the captured `northstar.wtrace`; the returned artifact carries
    `wtrace_path` so the caller can replay it
    (`bench.py --phase northstar` asserts the reads digest is stable
    across two replays)."""
    import tempfile

    from ..fault.ckpt import IncrementalCheckpointer, restore_chain

    own_tmp = None
    if workdir is None:
        own_tmp = tempfile.TemporaryDirectory(prefix="adapm_northstar_")
        workdir = own_tmp.name
    chain_dir = os.path.join(workdir, "chain")
    wtrace_path = os.path.join(workdir, "northstar.wtrace")
    hot = np.arange(512, dtype=np.int64)
    log = EventLog(num_keys, seed=seed, keys_per_event=8)
    try:
        # -- segment A: ingest + serve + periodic checkpoints ---------
        opts = _opts(batch, rate, freshness_slo_ms, wtrace_path)
        srv, plane, sessions = _build(num_keys, vlen, opts, hot)
        trainer = StreamTrainer(srv, log)
        ck = IncrementalCheckpointer(srv, chain_dir)
        ck.save()                       # base link before the stream
        ck.start_periodic(ckpt_every_s)
        trainer.start()
        t0 = time.perf_counter()
        lat_a, sheds_a, _ = _serve_segment(
            srv, sessions, num_keys, hot, segment_s, seed + 1)
        wall_a = time.perf_counter() - t0
        events_a = int(srv.stream.c_events.value)
        # -- kill (mid-stream: the chain's cursor lags the live one) --
        # stop the periodic SAVER only (no final flush — the restore
        # below must land BEHIND the live acked cursor, that is the
        # drill); the trainer keeps pumping until shutdown drains it
        ck.close()
        srv.shutdown()
        acked = int(srv.stream.cursor[0])
        # -- restore + replay the acked tail --------------------------
        opts_b = _opts(batch, rate, freshness_slo_ms, None)
        srv2, plane2, sessions2 = _build(num_keys, vlen, opts_b, hot)
        recovery_s = restore_chain(srv2, chain_dir)
        restored = int(srv2.stream.cursor[0])
        trainer2 = StreamTrainer(srv2, log)
        replayed = trainer2.replay_tail(acked)
        if int(srv2.stream.cursor[0]) != acked:
            raise RuntimeError(
                f"replay_tail stopped at cursor "
                f"{int(srv2.stream.cursor[0])} != acked watermark "
                f"{acked} — the at-least-once contract is broken")
        # -- segment B: resume on the restored state ------------------
        ck2 = IncrementalCheckpointer(srv2, chain_dir)
        ck2.start_periodic(ckpt_every_s)
        trainer2.start()
        t0 = time.perf_counter()
        lat_b, sheds_b, mark = _serve_segment(
            srv2, sessions2, num_keys, hot, segment_s, seed + 2,
            trailing_s=min(trailing_s, segment_s))
        wall_b = time.perf_counter() - t0
        fl = srv2.flight   # _opts sets trace_flight — the sensor is on
        fresh_end = (fl.freshness.h_freshness.snap()
                     if fl is not None else {"count": 0})
        events_b = int(srv2.stream.c_events.value) - restored
        slo_rep = (srv2.stream.freshness.report()
                   if srv2.stream.freshness is not None else None)
        snap = srv2.metrics_snapshot()
        ck2.close()
        srv2.shutdown()
        # trailing freshness window: cumulative histogram diffed
        # against the mark taken `trailing_s` before segment B's end —
        # the controller has had the whole run to walk its levers
        win = None
        if mark is not None:
            cnt = fresh_end["count"] - mark["count"]
            if cnt > 0:
                win = {"count": cnt, "bounds": fresh_end["bounds"],
                       "buckets": [a - b for a, b in
                                   zip(fresh_end["buckets"],
                                       mark["buckets"])]}
        lat = sorted(lat_a + lat_b)
        p50 = _pctl(lat, 0.50)
        p99 = _pctl(lat, 0.99)
        return {
            "num_keys": num_keys, "vlen": vlen,
            "stream_batch": batch, "stream_rate": rate,
            "freshness_slo_ms": freshness_slo_ms,
            "events_per_sec": round(
                (events_a + events_b) / (wall_a + wall_b), 1),
            "events_applied": events_a + events_b,
            "served_lookups": len(lat),
            "served_p50_ms": round(1e3 * p50, 3) if p50 else None,
            "served_p99_ms": round(1e3 * p99, 3) if p99 else None,
            "bronze_sheds": sheds_a + sheds_b,
            "freshness": {
                "target_ms": freshness_slo_ms,
                "trailing_window_s": min(trailing_s, segment_s),
                "samples": int(win["count"]) if win else 0,
                "p50_ms": round(1e3 * hist_percentile(win, 0.50), 3)
                if win else None,
                "p99_ms": round(1e3 * hist_percentile(win, 0.99), 3)
                if win else None,
                "cumulative_samples": int(fresh_end["count"]),
                "cumulative_p99_ms": round(
                    1e3 * hist_percentile(fresh_end, 0.99), 3)
                if fresh_end["count"] else None},
            "freshness_slo": slo_rep,
            "drill": {
                "acked_at_kill": acked,
                "restored_cursor": restored,
                "replayed_events": replayed,
                "recovery_s": round(recovery_s, 3)},
            "stream_section": snap["stream"],
            "wtrace_path": (wtrace_path
                            if os.path.exists(wtrace_path) and
                            own_tmp is None else None),
        }
    finally:
        if own_tmp is not None:
            own_tmp.cleanup()
