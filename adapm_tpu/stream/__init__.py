"""Streaming plane (ISSUE 20 tentpole; docs/STREAMING.md): the PM as a
continuously-trained online service — train-while-serve as a
first-class subsystem instead of an example script.

Three pieces:

  - `ingest`    — `EventLog` (seeded, bounded, regenerable-by-index
    click events) + `StreamTrainer` (micro-batched fused Push steps on
    the executor's `stream` stream, with the acked-event cursor
    committed under the same lock hold as each push's enqueue — the
    exactly-once seam the kill/restore drill proves);
  - `freshness` — `FreshnessSLO`, the closed loop over
    event-to-servable staleness: the obs/slo.py control law
    re-targeted at `flight.freshness_s`, walking the effective sync
    rate and the serve-replica refresh window against
    `--sys.stream.freshness_slo_ms`;
  - `scenario`  — the north-star harness (bench `northstar` phase):
    continuous ingest + multi-tenant `lookup_bags` serving + periodic
    incremental checkpoints + a mid-stream kill/restore drill + a
    captured `.wtrace`, emitting events/s, served P99, freshness P99,
    and recovery_s on one artifact.

Default-off discipline (r7): with no `--sys.stream.*` knob set the
Server holds `stream = None`, every integration site pays one
`is None` check, and the registry holds zero `stream.*` names
(scripts/metrics_overhead_check.py pins it).

Quickstart::

    opts = SystemOptions(stream_batch=32, stream_rate=2000,
                         stream_freshness_slo_ms=400,
                         trace_flight=True)
    server = Server(num_keys, value_lengths, opts=opts)
    log = EventLog(num_keys, seed=7)
    trainer = StreamTrainer(server, log)
    trainer.start()                     # executor pump
    ...serve reads, checkpoints...
    server.shutdown()                   # closes the plane
"""
from __future__ import annotations

from typing import Dict

import numpy as np

from .freshness import FreshnessSLO  # noqa: F401
from .ingest import EventLog, StreamTrainer  # noqa: F401


class StreamPlane:
    """Owned by the Server when any `--sys.stream.*` knob is set:
    holds the acked-event cursor (the array the checkpoint chain
    captures as `aux_stream_cursor`), the ingest accounting counters,
    and — with `--sys.stream.freshness_slo_ms` — the FreshnessSLO
    controller. Built after the sync manager (the controller's first
    lever) and closed by `Server.shutdown()` BEFORE the executor."""

    def __init__(self, server):
        opts = server.opts
        self.server = server
        # acked-event horizon: events [0, cursor) are applied exactly
        # once. An int64 ARRAY cell (not a plain int) so checkpoint
        # capture snapshots it with np.array_equal/copy like every
        # other aux table, and restore writes it back in place.
        self.cursor = np.zeros(1, dtype=np.int64)
        self.trainer = None  # attached by StreamTrainer.__init__
        reg = server.obs
        self.c_events = reg.counter("stream.events_total", shared=True)
        self.c_batches = reg.counter("stream.batches_total",
                                     shared=True)
        self.c_acked = reg.counter("stream.acked_events_total",
                                   shared=True)
        self.c_replayed = reg.counter("stream.replayed_events_total",
                                      shared=True)
        if reg.enabled:
            reg.gauge("stream.cursor", shared=True,
                      fn=lambda: int(self.cursor[0]))
        self.freshness = None
        base = float(opts.stream_freshness_slo_ms)
        if base > 0:
            from ..config import parse_class_targets
            cls = parse_class_targets(
                base, opts.stream_freshness_slo_class,
                flag="--sys.stream.freshness_slo_ms")
            self.freshness = FreshnessSLO(server, base,
                                          class_targets=cls)

    def start(self) -> None:
        if self.freshness is not None:
            self.freshness.start()

    def close(self) -> None:
        """Idempotent; called by Server.shutdown() before the executor
        closes (the trainer pump pushes through the live pools)."""
        t = self.trainer
        if t is not None:
            t.close()
        if self.freshness is not None:
            self.freshness.close()

    def stats(self) -> Dict:
        """The always-present-when-on `stream` snapshot section
        (schema v16; docs/OBSERVABILITY.md)."""
        out: Dict = {"cursor": int(self.cursor[0]),
                     "events_total": int(self.c_events.value),
                     "batches_total": int(self.c_batches.value),
                     "acked_events_total": int(self.c_acked.value),
                     "replayed_events_total":
                         int(self.c_replayed.value)}
        t = self.trainer
        if t is not None:
            out["trainer"] = t.stats()
        if self.freshness is not None:
            out["freshness"] = self.freshness.report()
        return out
