"""FreshnessSLO: the closed loop over event-to-servable staleness
(ISSUE 20 tentpole b; docs/STREAMING.md "Controller law & levers").

The r12 FreshnessProbe measures the wall time from a push EVENT to the
first serve read that observes it (`flight.freshness_s`) — bench r18
surfaced it at P50 231 ms / P99 3.19 s, measured but uncontrolled.
This controller re-targets the obs/slo.py control law at that
histogram and walks the TWO levers that bound staleness:

  - **sync cadence** — `SyncManager.effective_max_per_sec`, the
    effective rate bound `_throttle` honors. Tightening multiplies it
    ABOVE the static `--sys.sync.max_per_sec` (more rounds/s -> newer
    replicas), bounded at 64x static; relaxing walks it back down,
    never below the static knob. An unthrottled static knob (<= 0)
    leaves this lever inert.
  - **serve-replica refresh** — `ServeReplica.refresh_s`, the snapshot
    refresh throttle. Tightening divides it toward a 1 ms floor
    (fresher snapshots on the lock-free fast path); relaxing grows it
    back, never above the static `--sys.serve.replica_refresh_ms`.
    With no replica attached the lever is skipped (the exact locked
    path reads live values — sync cadence is then the whole story).

Law (identical shape to the serve SLO controller): windowed P99 —
each tick diffs the cumulative histogram against the previous window
mark and extracts the quantile of just that window; a window short of
`min_samples` EXTENDS across ticks (the probe samples every Nth push,
so low ingest rates would otherwise starve the controller) — compared
to the target with a +/- tol deadband; outside it, every available
lever moves one multiplicative step in the correcting direction. Bounded,
hysteretic, and logged: every applied move lands in a bounded
adjustment log and increments `stream.slo_adjustments_total`
(`scripts/freshness_slo_check.py` asserts the first move's direction
and trailing-window convergence).

Per-class targets (`--sys.stream.freshness_slo_ms 400,1=200`): the
controller steers to the TIGHTEST class target. Freshness is a
write-path property — sync rounds and snapshot refreshes serve every
class's reads at once, so per-class freshness cannot be steered
independently the way per-class LANE WINDOWS can (obs/slo.py grows
that half); meeting gold's bound meets bronze's automatically
(docs/STREAMING.md states this honestly).

Runs as a self-rescheduling delayed program on the executor's
`stream.slo` stream; requires `--sys.trace.flight` (the sensor) and
`--sys.metrics` (validate_serve rejects the combinations loudly).
"""
from __future__ import annotations

import collections
import time
from typing import Dict, List, Optional, Tuple

from ..obs.metrics import hist_percentile

# sync-rate ceiling, as a multiple of the static knob: the controller
# may run sync up to this much hotter than the operator's throttle
_RATE_CAP_X = 64.0
# replica-refresh floor: below ~1 ms the refresh program itself is the
# staleness (and the executor would spin on coalesced refresh kicks)
_REFRESH_FLOOR_S = 1e-3


class FreshnessSLO:
    """One per StreamPlane when `--sys.stream.freshness_slo_ms > 0`;
    owned and closed by the plane."""

    def __init__(self, server, target_ms: float,
                 class_targets: Optional[Dict[int, float]] = None,
                 interval_s: float = 0.1, tol: float = 0.25,
                 step: float = 1.5, min_samples: int = 4,
                 quantile: float = 0.99):
        assert target_ms > 0, "freshness SLO target must be positive"
        self.server = server
        self.class_targets = dict(class_targets or {})
        # steer to the tightest class (module docstring): the base
        # target covers classes without an override
        eff_ms = min([float(target_ms)] +
                     [float(v) for v in self.class_targets.values()])
        self.target_ms = float(target_ms)
        self.target_s = eff_ms * 1e-3
        self.interval_s = float(interval_s)
        self.tol = float(tol)
        self.step = float(step)
        self.min_samples = int(min_samples)
        self.quantile = float(quantile)
        # lever bounds, anchored at the operator's static knobs
        self.static_rate = float(server.opts.sync_max_per_sec)
        self.hi_rate = self.static_rate * _RATE_CAP_X
        self.static_refresh_s = \
            float(server.opts.serve_replica_refresh_ms) * 1e-3
        # sensor: the freshness histogram itself (probe-owned — present
        # whenever flight tracing is on, which validate_serve requires)
        self._h = server.flight.freshness.h_freshness
        self._prev_snap: Optional[Dict] = None
        self._closed = False
        # bounded move log: (wall, mono, [(lever, old, new), ...],
        # p99_ms); the first move is kept past the deque bound for the
        # convergence guard's direction check
        self.adjustments: "collections.deque" = collections.deque(
            maxlen=256)
        self.first_adjustment: Optional[Tuple] = None
        reg = server.obs
        self.c_adjust = reg.counter("stream.slo_adjustments_total",
                                    shared=True)
        self.c_ticks = reg.counter("stream.slo_ticks_total", shared=True)
        self.g_p99 = reg.gauge("stream.freshness_p99_ms", shared=True)
        self.g_target = reg.gauge("stream.freshness_target_ms",
                                  shared=True)
        self.g_rate = reg.gauge("stream.sync_rate", shared=True)
        self.g_refresh = reg.gauge("stream.refresh_ms", shared=True)
        self.g_target.set(eff_ms)
        self.g_rate.set(self.static_rate)
        self.g_refresh.set(self.static_refresh_s * 1e3)

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        self._resubmit()

    def close(self) -> None:
        """Stop rescheduling. Idempotent; a queued tick sees _closed
        and exits without resubmitting."""
        self._closed = True

    def _resubmit(self) -> None:
        if self._closed:
            return
        # per-INSTANCE coalesce key (obs/slo.py discipline): a plane
        # rebuilt within one interval must not have its first tick
        # absorbed into the closed predecessor's queued tick
        self.server.exec.submit(
            "stream.slo", self._tick, label="stream.slo.tick",
            coalesce_key=f"stream.slo.tick.{id(self)}",
            delay=self.interval_s)

    def _tick(self) -> None:
        if self._closed or self.server.exec.closed:
            return
        try:
            self._control()
        finally:
            self._resubmit()

    # -- control law ---------------------------------------------------------

    def _window_p99(self) -> Optional[float]:
        """Quantile of the freshness observations accumulated since the
        last ACTED-ON tick (cumulative histogram diffed against the
        previous window mark). The probe samples every Nth push, so at
        modest ingest rates one tick interval holds fewer than
        `min_samples` observations — the window mark then stays put and
        the window EXTENDS across ticks until it qualifies (a
        fixed-width window would starve the controller into never
        acting); None until then."""
        snap = self._h.snap()
        prev = self._prev_snap
        if prev is None:
            self._prev_snap = snap
            return None
        count = snap["count"] - prev["count"]
        if count < self.min_samples:
            return None         # extend: keep the window mark
        self._prev_snap = snap
        buckets = [a - b for a, b in zip(snap["buckets"],
                                         prev["buckets"])]
        return hist_percentile({"count": count,
                                "bounds": snap["bounds"],
                                "buckets": buckets}, self.quantile)

    def _control(self) -> None:
        self.c_ticks.inc()
        p99 = self._window_p99()
        if p99 is None:
            return
        self.g_p99.set(p99 * 1e3)
        if p99 > self.target_s * (1.0 + self.tol):
            tighten = True
        elif p99 < self.target_s * (1.0 - self.tol):
            tighten = False
        else:
            return  # deadband: hysteresis against lever chatter
        moves: List[Tuple[str, float, float]] = []
        # lever 1: effective sync rate (inert when unthrottled)
        sm = self.server.sync
        cur = float(sm.effective_max_per_sec)
        if self.static_rate > 0:
            if tighten:
                new = min(self.hi_rate, max(cur * self.step, cur + 1.0))
            else:
                new = max(self.static_rate, cur / self.step) \
                    if cur > self.static_rate else cur
            if new != cur:
                sm.effective_max_per_sec = new
                self.g_rate.set(new)
                moves.append(("sync_rate", cur, new))
        # lever 2: serve-replica refresh window (skipped without a
        # replica — the locked path reads live values already)
        plane = getattr(self.server, "_serve_plane", None)
        rep = plane.replica if plane is not None else None
        if rep is not None:
            cur_s = float(rep.refresh_s)
            if tighten:
                new_s = max(_REFRESH_FLOOR_S, cur_s / self.step) \
                    if cur_s > _REFRESH_FLOOR_S else cur_s
            else:
                new_s = min(self.static_refresh_s, cur_s * self.step) \
                    if cur_s < self.static_refresh_s else cur_s
            if new_s != cur_s:
                rep.refresh_s = new_s
                self.g_refresh.set(new_s * 1e3)
                moves.append(("refresh_ms", cur_s * 1e3, new_s * 1e3))
        if not moves:
            return  # both levers pinned at their bounds
        self.c_adjust.inc(len(moves))
        # BOTH clock domains (ISSUE 15 discipline): the flight slices
        # this log is read against are monotonic; wall time is for
        # humans and cross-run joins
        move = (time.time(), time.monotonic(), moves, p99 * 1e3)
        if self.first_adjustment is None:
            self.first_adjustment = move
        self.adjustments.append(move)

    # -- reporting -----------------------------------------------------------

    @staticmethod
    def _fmt(move: Tuple) -> Dict:
        t, tm, levers, p99 = move
        return {"t": round(t, 3), "t_mono": round(tm, 6),
                "levers": [{"lever": lv, "old": round(o, 4),
                            "new": round(n, 4)}
                           for (lv, o, n) in levers],
                "p99_ms": round(p99, 3)}

    def report(self) -> Dict:
        """JSON-safe summary for `metrics_snapshot()["stream"]` and
        the bench artifact."""
        sm = self.server.sync
        plane = getattr(self.server, "_serve_plane", None)
        rep = plane.replica if plane is not None else None
        return {"active": True,
                "target_ms": round(self.target_s * 1e3, 3),
                "base_target_ms": round(self.target_ms, 3),
                "class_targets": {str(k): v for k, v in
                                  sorted(self.class_targets.items())},
                "sync_rate": float(sm.effective_max_per_sec),
                "static_sync_rate": self.static_rate,
                "refresh_ms": (float(rep.refresh_s) * 1e3
                               if rep is not None else None),
                "static_refresh_ms": self.static_refresh_s * 1e3,
                "adjustments": int(self.c_adjust.value),
                "first_adjustment": (
                    self._fmt(self.first_adjustment)
                    if self.first_adjustment is not None else None),
                "recent_adjustments": [
                    self._fmt(m) for m in list(self.adjustments)[-8:]]}
