"""Event ingest: a seeded click-event source + the micro-batching
StreamTrainer that turns events into fused additive Push steps while
the serve plane reads (ISSUE 20 tentpole a; docs/STREAMING.md).

Event lifecycle and the exactly-once contract:

  1. **Generated** — event `i` is a pure function of `(seed, i)`
     (`EventLog.event`): a skewed key set plus a per-key gradient row.
     Nothing is ever buffered durably; any index can be regenerated,
     so replay after a crash needs no retained queue — the log is
     bounded by construction.
  2. **Applied** — the trainer fuses `--sys.stream.batch` events into
     ONE additive `Worker.push`. Inside a single (reentrant) server-
     lock bracket the push's scatter is enqueued — which is also where
     the r12 FreshnessProbe's `push_visible` stamp lands — and the
     plane's acked-event cursor advances to the batch end. Enqueue
     order is this codebase's read-visibility order, so at that point
     the events are servable-ordered: that is the ACK.
  3. **Checkpointed** — the cursor rides every checkpoint link as the
     `stream_cursor` aux array (fault/ckpt.py), captured under the
     SAME lock hold as the row bits. A restored chain therefore lands
     on a state where events `[0, cursor)` are applied exactly once
     and nothing after the cursor is applied at all.
  4. **Replayed** — after a mid-stream kill + restore, a new trainer
     resumes from the restored cursor and `replay_tail(acked)`
     re-applies the tail up to the pre-kill ack watermark, counting
     each into `stream.replayed_events_total` (loud, not silent).
     Because the cursor only moves at batch boundaries, the replayed
     batches are the SAME batches an unkilled shadow applied — same
     grouping, same order, so the additive scatter sums are bitwise
     identical (pinned by tests/test_stream.py).

The pump runs as a self-rescheduling program on the executor's
`stream` stream (the r6 timer discipline: pacing via `delay=`, never a
sleeping worker); `--sys.stream.rate` bounds events/s.
"""
from __future__ import annotations

import collections
import time
from typing import Dict, Optional, Tuple

import numpy as np


class EventLog:
    """Seeded, bounded click-event source. Event `i` is a pure
    function of `(seed, i)`: `keys_per_event` keys drawn with a hot
    head (power-law-ish: serve traffic and pushes contest the same hot
    rows, the access shape the DLRM bag papers model) and one gradient
    row per key. A bounded memo ring caches recently materialized
    events; anything evicted is regenerated bit-identically on demand
    — the property the kill/restore replay leans on."""

    def __init__(self, num_keys: int, seed: int = 0,
                 keys_per_event: int = 8, skew: float = 3.0,
                 scale: float = 0.01, bound: int = 4096):
        assert num_keys > 0 and keys_per_event > 0
        self.num_keys = int(num_keys)
        self.seed = int(seed)
        self.keys_per_event = int(keys_per_event)
        self.skew = float(skew)
        self.scale = float(scale)
        self._bound = max(1, int(bound))
        self._memo: "collections.OrderedDict" = collections.OrderedDict()

    def keys(self, i: int) -> np.ndarray:
        """The event's key set (sorted, may repeat across events but
        unique within one — duplicates inside one additive scatter
        would make the fused batch order-sensitive)."""
        rng = np.random.default_rng((self.seed, int(i)))
        # u**skew concentrates mass near 0: a hot head without the
        # unbounded tail of a true zipf draw
        u = rng.random(4 * self.keys_per_event)
        k = np.unique((u ** self.skew * self.num_keys).astype(np.int64))
        k = np.minimum(k, self.num_keys - 1)
        return k[:self.keys_per_event]

    def event(self, i: int, value_lengths: np.ndarray) \
            -> Tuple[np.ndarray, np.ndarray]:
        """(keys, flat gradient buffer) for event `i`. The gradient is
        drawn from the event's own generator AFTER the key draw, so it
        is deterministic given (seed, i) alone."""
        i = int(i)
        hit = self._memo.get(i)
        if hit is not None:
            self._memo.move_to_end(i)
            return hit
        rng = np.random.default_rng((self.seed, i))
        u = rng.random(4 * self.keys_per_event)
        k = np.unique((u ** self.skew * self.num_keys).astype(np.int64))
        k = np.minimum(k, self.num_keys - 1)[:self.keys_per_event]
        total = int(np.sum(value_lengths[k]))
        vals = (rng.standard_normal(total) * self.scale).astype(
            np.float32)
        out = (k, vals)
        self._memo[i] = out
        if len(self._memo) > self._bound:
            self._memo.popitem(last=False)
        return out


class StreamTrainer:
    """Micro-batching ingest: fuses `batch` events into one additive
    Push per step, advancing the stream plane's acked-event cursor
    under the same server-lock hold as the push enqueue (module
    docstring). Requires the stream plane (`--sys.stream.batch` or
    another --sys.stream.* knob) — no plane, no trainer, no stream.*
    names (the r7 skip-wrapper discipline).

    Two drive modes, freely mixable:
      - `step()` / `run_until(n)` — inline on the caller's thread
        (deterministic; what the drill tests and the shadow use);
      - `start()` — the executor pump on the `stream` stream, paced by
        `--sys.stream.rate` via `delay=` rescheduling.
    """

    def __init__(self, server, log: EventLog, worker=None,
                 batch: Optional[int] = None,
                 rate: Optional[float] = None):
        plane = getattr(server, "stream", None)
        if plane is None:
            raise RuntimeError(
                "StreamTrainer needs the stream plane: set "
                "--sys.stream.batch (or another --sys.stream.* knob) "
                "so the Server builds one — the acked-event cursor "
                "lives there and rides the checkpoint chain")
        self.server = server
        self.plane = plane
        self.log = log
        self.batch = int(batch if batch is not None
                         else server.opts.stream_batch)
        if self.batch < 1:
            raise ValueError(
                f"stream micro-batch must be >= 1 (got {self.batch}; "
                f"set --sys.stream.batch or pass batch=)")
        self.rate = float(rate if rate is not None
                          else server.opts.stream_rate)
        self.worker = worker if worker is not None \
            else server.make_worker()
        self.resumed_from = int(plane.cursor[0])
        self._closed = False
        self._target: Optional[int] = None  # pump stop horizon
        self._due = 0.0  # monotonic schedule base for rate pacing
        plane.trainer = self

    # -- accounting ----------------------------------------------------------

    @property
    def cursor(self) -> int:
        """Acked-event horizon: events [0, cursor) are applied exactly
        once in the live state (and in any checkpoint whose link
        captured this cursor value)."""
        return int(self.plane.cursor[0])

    def stats(self) -> Dict:
        return {"cursor": self.cursor,
                "resumed_from": self.resumed_from,
                "batch": self.batch, "rate": self.rate,
                "closed": self._closed}

    # -- inline drive (deterministic; drills and shadows) --------------------

    def step(self, replayed: bool = False) -> int:
        """Apply ONE micro-batch inline. Returns the new cursor."""
        plane = self.plane
        srv = self.server
        start = int(plane.cursor[0])
        end = start + self.batch
        vlen = srv.value_lengths
        parts_k, parts_v = [], []
        for i in range(start, end):
            k, v = self.log.event(i, vlen)
            parts_k.append(k)
            parts_v.append(v)
        keys = np.concatenate(parts_k)
        vals = np.concatenate(parts_v)
        # one reentrant bracket (the server lock is an RLock): the
        # push's own under-lock scatter enqueue — where push_visible
        # stamps the freshness probe, the ACK point — and the cursor
        # bump commit atomically against checkpoint capture, which
        # snapshots rows AND the cursor under the same lock. A capture
        # therefore never sees the push without the cursor bump or
        # vice versa — the exactly-once seam of the kill/restore drill.
        with srv._lock:
            self.worker.push(keys, vals)
            plane.cursor[0] = end
        plane.c_events.inc(self.batch)
        plane.c_batches.inc()
        plane.c_acked.inc(self.batch)
        if replayed:
            plane.c_replayed.inc(self.batch)
        return end

    def run_until(self, n_events: int) -> int:
        """Step inline until the cursor reaches (at least) `n_events`.
        Returns the cursor."""
        while int(self.plane.cursor[0]) < int(n_events):
            self.step()
        return self.cursor

    def replay_tail(self, acked_watermark: int) -> int:
        """Post-restore: re-apply the tail between the RESTORED cursor
        and the pre-kill ack watermark (module docstring step 4). The
        re-applied events are counted loudly into
        stream.replayed_events_total. Returns how many were replayed."""
        replayed = 0
        while int(self.plane.cursor[0]) < int(acked_watermark):
            before = int(self.plane.cursor[0])
            self.step(replayed=True)
            replayed += int(self.plane.cursor[0]) - before
        return replayed

    # -- executor pump -------------------------------------------------------

    def start(self, target_events: Optional[int] = None) -> None:
        """Run the pump on the executor's `stream` stream until
        `target_events` (None = until close())."""
        self._target = None if target_events is None \
            else int(target_events)
        self._due = time.monotonic()
        self._resubmit(0.0)

    def _resubmit(self, delay: float) -> None:
        if self._closed:
            return
        self.server.exec.submit(
            "stream", self._pump, label="stream.ingest",
            coalesce_key=f"stream.ingest.{id(self)}", delay=delay)

    def _pump(self) -> None:
        if self._closed or self.server.exec.closed:
            return
        tgt = self._target
        if tgt is not None and int(self.plane.cursor[0]) >= tgt:
            return  # target reached: park (start() re-arms)
        try:
            self.step()
        finally:
            if self.rate > 0:
                # fixed-cadence schedule: each batch is due batch/rate
                # after the previous DUE time (not after it finished),
                # so transient slow batches don't permanently lower
                # the achieved rate
                self._due = max(self._due + self.batch / self.rate,
                                time.monotonic() - 1.0)
                delay = max(0.0, self._due - time.monotonic())
            else:
                delay = 0.0
            self._resubmit(delay)

    def close(self, timeout: float = 30.0) -> None:
        """Stop the pump and drain any queued ingest program. Called
        by StreamPlane.close() during Server.shutdown BEFORE the
        executor closes (the pump pushes through the live pools)."""
        if self._closed:
            return
        self._closed = True
        ex = self.server.exec
        if not ex.closed and not ex.drain("stream", timeout=timeout):
            from ..utils import alog
            alog("[stream] ingest pump failed to drain within "
                 f"{timeout:.0f}s — wedged mid-push?")
            raise RuntimeError(
                "stream ingest pump wedged: did not drain within "
                f"{timeout:.0f}s of close; refusing to proceed into "
                "pool teardown under a live pusher")
